"""Sharded ops: sequence-parallel convolution, TP GEMM, DP batching.

The distributed re-expression of the reference's hot paths (SURVEY.md §5
"long-context" analog): overlap-save block filtering
(``/root/reference/src/convolve.c:103-229``) becomes ``shard_map`` over a
sequence axis with a ``ppermute`` halo exchange; the GEMM column loop
(``src/matrix.c:200-226``) becomes a contracting-dim-sharded
``dot_general`` + ``psum``.  Everything here is pure SPMD: one jitted
program, XLA inserts the collectives, ICI carries them.

**Mesh-loss degradation**: every instrumented sharded dispatch runs
through :func:`_sharded_guard` — the transient-fault policy
(:func:`veles.simd_tpu.runtime.faults.guarded`) with a degrade path to
the op's single-chip ``ops/`` twin on device loss (recorded as a
``mesh_degrade`` decision event with the mesh geometry), gated by a
per-``(op, mesh-class)`` circuit breaker
(:mod:`veles.simd_tpu.runtime.breaker`) so a dead mesh answers via the
twin immediately instead of paying the retry ladder per call, with
call-counted half-open probes that re-enable sharded dispatch when the
mesh comes back.  ``tools/lint.py`` enforces the discipline: an
instrumented sharded dispatch outside a ``faults.guarded`` thunk is a
lint failure.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.5 keeps shard_map in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.runtime import precision as prx


def _axis_size(axis_name) -> int:
    """Static size of a named mesh axis, from inside ``shard_map``.

    ``jax.lax.axis_size`` only exists on newer jax; older releases get
    the same Python int from the constant-folded ``psum(1, axis)``."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


__all__ = ["sharded_convolve", "sharded_convolve_ring",
           "sharded_convolve_batch",
           "sharded_convolve2d", "sharded_convolve2d_ring",
           "sharded_matmul",
           "sharded_swt", "sharded_swt_reconstruct",
           "sharded_wavelet_apply", "sharded_wavelet_transform",
           "sharded_wavelet_inverse_transform",
           "sharded_wavelet_reconstruct",
           "sharded_wavelet_apply2d",
           "sharded_wavelet_reconstruct2d",
           "sharded_swt_apply2d",
           "sharded_wavelet_packet_transform2d",
           "sharded_order_filter", "sharded_medfilt",
           "sharded_savgol_filter", "sharded_lombscargle",
           "sharded_stft", "sharded_istft", "sharded_sosfilt",
           "sharded_welch", "sharded_resample_poly", "data_parallel",
           "halo_exchange_left", "halo_exchange_right"]



def _instrumented(op: str, run_fn):
    """Route one shard_map program through the instrumented compile
    helper (:func:`veles.simd_tpu.obs.instrumented_jit`) so sharded
    executables land in the resource axis — per-(op, route) FLOPs,
    bytes moved, and memory breakdown — like every single-chip compile
    site.  The wrapper is transparent (jit of a shard_map program is
    the standard SPMD form); with telemetry off it costs one flag
    check per call.

    KNOWN COST, inherited not introduced: every sharded_* entry point
    builds its ``_run`` closure per call, so jax's identity-keyed
    caches retrace per call — measured on the 8-device CPU mesh the
    eager shard_map form paid ~640 ms/call and this jitted form
    ~140 ms/call (the jit path dispatches cheaper after tracing).
    The real fix is a geometry-keyed compiled-handle LRU like
    ``ops/batched.py`` — a structural refactor of every closure's
    captures, deliberately left for its own PR."""
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def _sharded_guard(op: str, thunk, fallback, mesh: Mesh, axis: str):
    """One sharded dispatch under the mesh-loss policy.

    ``thunk`` runs the instrumented shard_map program (including any
    output slicing); ``fallback`` computes the SAME answer on the
    single-chip ``ops/`` twin.  The dispatch is breaker-gated per
    ``(op, mesh-class)``: transient mesh faults ride the bounded
    retry of :func:`veles.simd_tpu.runtime.faults.guarded` and
    degrade to the twin (a ``mesh_degrade`` decision event carrying
    the mesh geometry); once the class's breaker opens, calls go
    straight to the twin — a dead mesh costs zero retry latency —
    and every ``probe_every``-th call probes the mesh with a
    zero-retry budget, re-enabling sharded dispatch on the first
    success."""
    site = f"parallel.{op}"
    geom = routing.mesh_class(mesh, axis)

    def degrade():
        obs.count("mesh_degrade", op=op)
        obs.record_decision("mesh_degrade", op, site=site, mesh=geom,
                            fallback="single_chip")
        return fallback()

    return faults.breaker_guarded(
        site, (op, geom), thunk, fallback=degrade,
        fallback_name="single_chip", breaker_site="parallel.dispatch")


def halo_exchange_left(x_local, halo_len: int, axis_name: str,
                       periodic: bool = False):
    """Bring the last ``halo_len`` samples of the left neighbour's shard.

    By default the first shard receives zeros (``ppermute`` drops absent
    sources) — exactly the zero history the overlap-save formulation
    wants (``src/convolve.c:194-196`` zero-pads the first block).  With
    ``periodic=True`` the first shard receives the LAST shard's tail (a
    ring over ICI) — the synthesis-side mirror of
    ``halo_exchange_right(..., periodic=True)``.
    """
    n_shards = _axis_size(axis_name)
    block = x_local.shape[-1]
    tail = x_local[..., block - halo_len:]  # empty when halo_len == 0
    perm = [(i, i + 1) for i in range(n_shards - 1)]
    if periodic:
        perm.append((n_shards - 1, 0))
    return jax.lax.ppermute(tail, axis_name, perm)


def halo_exchange_right(x_local, halo_len: int, axis_name: str,
                        periodic: bool = False):
    """Bring the first ``halo_len`` samples of the right neighbour's shard.

    With ``periodic=True`` the last shard receives the first shard's head
    (a ring over ICI) — the sharded form of the reference's PERIODIC
    boundary extension (``src/wavelet.c:248-269``); otherwise the last
    shard receives zeros.
    """
    n_shards = _axis_size(axis_name)
    head = x_local[..., :halo_len]
    perm = [(i, i - 1) for i in range(1, n_shards)]
    if periodic:
        perm.append((0, n_shards - 1))
    return jax.lax.ppermute(head, axis_name, perm)


def _local_block_conv(x_ext, h):
    """The local overlap-save block step: FULL convolution of the
    halo-extended block, sliced to the block's span of the global result.

    Reuses the single-chip overlap-save kernels — the MXU block-matmul
    form for short/medium filters, batched-frames FFT for long ones
    (:mod:`veles.simd_tpu.ops.convolve` auto-select) — so each shard runs
    the same code the single-chip path does on its block.
    """
    from veles.simd_tpu.ops import convolve as cv

    k = h.shape[-1]
    n_local = x_ext.shape[-1] - (k - 1)
    if k <= cv.AUTO_OS_MATMUL_MAX_H:
        full = cv._conv_os_matmul(x_ext, h, cv.overlap_save_step(k),
                                  precision=cv.os_precision())
    else:
        full = cv._conv_overlap_save(
            x_ext, h, cv.tpu_block_length(k, x_ext.shape[-1]))
    # y_local[j] = full[j + k - 1]: the VALID span of this block
    return jax.lax.slice_in_dim(full, k - 1, k - 1 + n_local, axis=-1)


def sharded_convolve(x, h, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel full linear convolution over ``mesh[axis]``.

    The signal is sharded along its length; each device convolves its
    block after a one-hop left-halo exchange of ``h−1`` samples.  Returns
    the full ``n + h - 1`` result (same semantics as
    :func:`veles.simd_tpu.ops.convolve.convolve`).

    This is the distributed overlap-save: reference blocks-with-overlap
    (``src/convolve.c:181-228``) → shards-with-halo; the intra-block FFT
    pipeline stays whatever XLA picks locally.
    """
    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim < 1:
        raise ValueError("sharded_convolve needs [..., n]")
    n, k = x.shape[-1], h.shape[-1]
    n_shards = mesh.shape[axis]
    out_len = n + k - 1
    pad_to = -(-out_len // n_shards) * n_shards
    if k - 1 > pad_to // n_shards:
        # filter halo exceeds one block: auto-select the multi-hop ring
        # pipeline, the same spirit as convolve_initialize's algorithm
        # auto-select (src/convolve.c:328-366)
        return sharded_convolve_ring(x, h, mesh, axis=axis)
    obs.record_decision(
        "sharded_convolve", "one_hop_halo", n_shards=int(n_shards),
        axis=axis, x_length=int(n), h_length=int(k),
        block=int(pad_to // n_shards), halo=int(k - 1))
    with obs.span("sharded_convolve.dispatch", route="one_hop_halo",
                  n_shards=int(n_shards)):
        x_pad = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad_to - n)])
        # leading batch dims (if any) stay replicated; shard the length
        spec = P(*([None] * (x.ndim - 1) + [axis]))

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(spec, P()), out_specs=spec)
        def _run(x_local, h_full):
            halo = halo_exchange_left(x_local, k - 1, axis)
            x_ext = jnp.concatenate([halo, x_local], axis=-1)
            return _local_block_conv(x_ext, h_full)

        from veles.simd_tpu.ops import convolve as cv

        jfn = _instrumented("sharded_convolve", _run)
        return _sharded_guard(
            "sharded_convolve",
            lambda: jfn(x_pad, h)[..., :out_len],
            lambda: cv.convolve_simd(x, h),
            mesh, axis)


def sharded_convolve_ring(x, h, mesh: Mesh, axis: str = "sp",
                          batch_axis: str | None = None):
    """Sequence-parallel convolution for filters LONGER than a shard
    block — the ring-attention communication pattern applied to
    convolution.

    :func:`sharded_convolve`'s one-hop halo needs ``h_length-1`` to fit
    in one block.  Here instead, x blocks stream around the ring
    (``ppermute``, one block per hop) while every shard accumulates each
    arriving block against the static segment of the (replicated) filter
    that lands in its output window:

        y_s[j] = Σ_m Σ_i B_{s-m}[i] · h[m·blk + j - i]

    — ``M = min(ceil((k-1)/blk), S-1)`` hops, total compute ≈ 2× the
    one-shot conv, per-shard memory O(blk + k).  Convolution is causal,
    so blocks from shards right of ``s`` never contribute to ``s``'s
    window; ring-wrapped arrivals are masked by ``axis_index``.  Works
    for ANY filter length — even ``h`` longer than ``x`` (the hop count
    clamps at S−1, which covers every causal block pair); for short
    filters prefer :func:`sharded_convolve` (single hop, half the
    compute).  With
    ``batch_axis`` set, a leading ``[batch, n]`` dimension is sharded
    over that mesh axis too (the dp×sp form).
    """
    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim < 1:
        raise ValueError("sharded_convolve_ring needs [..., n]")
    n, k = x.shape[-1], h.shape[-1]
    if batch_axis is not None and x.ndim != 2:
        raise ValueError("batch_axis needs x of shape [batch, n]")
    n_shards = mesh.shape[axis]
    out_len = n + k - 1
    blk = -(-out_len // n_shards)
    pad_to = blk * n_shards
    pads = [(0, 0)] * x.ndim
    pads[-1] = (0, pad_to - n)
    batch_pad = 0
    if batch_axis is not None:
        batch_pad = (-x.shape[0]) % mesh.shape[batch_axis]
        pads[0] = (0, batch_pad)
    x_pad = jnp.pad(x, pads)
    hops = min(-(-(k - 1) // blk), n_shards - 1)
    obs.record_decision(
        "sharded_convolve", "ring", n_shards=int(n_shards), axis=axis,
        x_length=int(n), h_length=int(k), block=int(blk),
        hops=int(hops))
    # h segments: seg_m = h_pp[m·blk : m·blk + 2·blk - 1] with h_pp
    # left-padded blk-1 and right-padded so the last slice is in range
    h_pp = jnp.pad(h, (blk - 1, (hops + 2) * blk))
    lead = ([batch_axis] + [None] * (x.ndim - 2) if batch_axis is not None
            else [None] * (x.ndim - 1))
    spec = P(*(lead + [axis]))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, P()), out_specs=spec)
    def _run(x_local, h_padded):
        s = jax.lax.axis_index(axis)
        y = jnp.zeros_like(x_local)
        block = x_local
        for m in range(hops + 1):
            seg = jax.lax.slice_in_dim(h_padded, m * blk,
                                       m * blk + 2 * blk - 1, axis=-1)
            contrib = _ring_block_conv(block, seg)
            # blocks that ring-wrapped (from shards right of s) are
            # acausal for this window — mask them out
            keep = (s - m >= 0).astype(contrib.dtype)
            y = y + keep * contrib
            if m < hops:
                perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
                block = jax.lax.ppermute(block, axis, perm)
        return y

    from veles.simd_tpu.ops import convolve as cv

    def _ring_thunk():
        out = _instrumented("sharded_convolve_ring",
                            _run)(x_pad, h_pp)[..., :out_len]
        if batch_pad:
            out = out[:x.shape[0]]
        return out

    return _sharded_guard("sharded_convolve_ring", _ring_thunk,
                          lambda: cv.convolve_simd(x, h), mesh, axis)


def _ring_block_conv(block, seg):
    """Samples ``[blk-1, 2·blk-1)`` of the full linear convolution of a
    [..., blk] block with a [2·blk-1] filter segment — exactly the
    shard's output window for one ring hop.  Direct MXU form for small
    products (padding sized so only the needed blk outputs are
    computed), spectral beyond the measured 1D crossover
    (ops/convolve.py AUTO_FFT_MIN_PRODUCT — direct cost per hop is
    O(blk²), which would regress long shards by orders of magnitude)."""
    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.utils.memory import next_highest_power_of_2

    blk = block.shape[-1]
    ks = seg.shape[-1]
    if blk * ks < cv.AUTO_FFT_MIN_PRODUCT:
        lhs = block.reshape((-1, 1, blk))
        rhs = jnp.flip(seg, -1).reshape((1, 1, ks))
        # pad (blk-1, blk-1): output index o == full-conv index o + blk-1,
        # so the blk outputs are precisely the shard's window
        out = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(1,),
            padding=[(blk - 1, blk - 1)],
            precision=prx.HIGHEST)
        return out.reshape(block.shape[:-1] + (blk,))
    m = next_highest_power_of_2(blk + ks - 1)
    spec = jnp.fft.rfft(block, m) * jnp.fft.rfft(seg, m)
    return jnp.fft.irfft(spec, m)[..., blk - 1:2 * blk - 1].astype(
        block.dtype)


def sharded_convolve_batch(x, h, mesh: Mesh, batch_axis: str = "dp",
                           seq_axis: str = "sp"):
    """dp×sp convolution: a batch of signals sharded over ``batch_axis``
    *and* each signal's length over ``seq_axis``.

    The 2D-mesh form of the reference's block pipeline: every (dp, sp)
    device holds a [batch/dp, n/sp] tile, halo-exchanges ``h−1`` samples
    along sp, and convolves its tile with the shared filter.  Returns the
    full ``[batch, n + h - 1]`` result.
    """
    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim != 2:
        raise ValueError("sharded_convolve_batch expects [batch, n]")
    batch, n = x.shape
    k = h.shape[-1]
    dp = mesh.shape[batch_axis]
    sp = mesh.shape[seq_axis]
    out_len = n + k - 1
    # pad-and-slice an indivisible batch (zero rows convolve to zeros),
    # like the TP GEMM pads its contracting dim
    batch_pad = (-batch) % dp
    pad_to = -(-out_len // sp) * sp
    if k - 1 > pad_to // sp:
        # same auto-select as sharded_convolve: the multi-hop ring
        # handles filters longer than a shard block, dp×sp intact
        return sharded_convolve_ring(x, h, mesh, axis=seq_axis,
                                     batch_axis=batch_axis)
    x_pad = jnp.pad(x, ((0, batch_pad), (0, pad_to - n)))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(batch_axis, seq_axis), P()),
        out_specs=P(batch_axis, seq_axis))
    def _run(x_local, h_full):
        halo = halo_exchange_left(x_local, k - 1, seq_axis)
        x_ext = jnp.concatenate([halo, x_local], axis=-1)
        return _local_block_conv(x_ext, h_full)

    from veles.simd_tpu.ops import convolve as cv

    jfn = _instrumented("sharded_convolve_batch", _run)
    return _sharded_guard(
        "sharded_convolve_batch",
        lambda: jfn(x_pad, h)[:batch, :out_len],
        lambda: cv.convolve_simd(x, h),
        mesh, seq_axis)


def sharded_convolve2d(x, h, mesh: Mesh, axes=("dp", "sp")):
    """Full 2D convolution of one image sharded over a 2D device grid.

    The image is tiled over ``mesh[axes[0]] x mesh[axes[1]]``; each tile
    needs a top halo of ``k0-1`` rows and a left halo of ``k1-1``
    columns.  The corner (top-left diagonal neighbour's data) rides the
    classic two-phase exchange: rows are exchanged first, then columns of
    the *row-extended* tile — the second hop carries the corner without
    any diagonal communication.  Returns the full
    ``[n0 + k0 - 1, n1 + k1 - 1]`` result.

    The 2D form of the 1D halo pipeline (``src/convolve.c:181-228``
    blocks → shards); local tiles run the single-chip direct conv.
    """
    from veles.simd_tpu.ops import convolve2d as cv2

    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim != 2 or h.ndim != 2:
        raise ValueError("sharded_convolve2d shards one [n0, n1] image "
                         "with an [k0, k1] kernel")
    a0, a1 = axes
    s0, s1 = mesh.shape[a0], mesh.shape[a1]
    n0, n1 = x.shape
    k0, k1 = h.shape
    out0, out1 = n0 + k0 - 1, n1 + k1 - 1
    pad0 = -(-out0 // s0) * s0
    pad1 = -(-out1 // s1) * s1
    if k0 - 1 > pad0 // s0 or k1 - 1 > pad1 // s1:
        # kernel halo exceeds one tile: auto-select the 2D ring pipeline
        # (multi-hop streaming along both mesh axes)
        return sharded_convolve2d_ring(x, h, mesh, axes=axes)
    x_pad = jnp.pad(x, ((0, pad0 - n0), (0, pad1 - n1)))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(a0, a1), P()), out_specs=P(a0, a1))
    def _run(x_local, h_full):
        # phase 1: top halo rows over axes[0]
        top = halo_exchange_left(
            jnp.swapaxes(x_local, -1, -2), k0 - 1, a0)
        ext0 = jnp.concatenate(
            [jnp.swapaxes(top, -1, -2), x_local], axis=-2)
        # phase 2: left halo columns of the row-extended tile over
        # axes[1] — carries the diagonal corner for free
        left = halo_exchange_left(ext0, k1 - 1, a1)
        ext = jnp.concatenate([left, ext0], axis=-1)
        # local tile step is ALWAYS the batched-FFT form: the Pallas
        # route is skipped inside shard_map deliberately (the XLA paths
        # are the ones validated under SPMD), and without Pallas the
        # round-5 hardware sweep found XLA's im2col direct conv losing
        # every cell to the FFT — and crashing the TPU worker at large
        # kernels (crossover table at cv2.select_algorithm2d).  Note
        # select_algorithm2d's 'direct' now means "Pallas will take
        # it", so it must not be consulted for an XLA-only tile step.
        from veles.simd_tpu.utils.memory import (
            next_highest_power_of_2 as _np2)
        full = cv2._conv2d_fft(
            ext, h_full, _np2(ext.shape[-2] + k0 - 1),
            _np2(ext.shape[-1] + k1 - 1))
        # VALID span of this tile in the global result: the halo shifts
        # the tile origin by (k0-1, k1-1), exactly as the 1D form
        # (full[j + k - 1] in _local_block_conv)
        return jax.lax.slice(
            full, (k0 - 1, k1 - 1),
            (k0 - 1 + x_local.shape[-2], k1 - 1 + x_local.shape[-1]))

    jfn = _instrumented("sharded_convolve2d", _run)
    return _sharded_guard(
        "sharded_convolve2d",
        lambda: jfn(x_pad, h)[:out0, :out1],
        lambda: cv2.convolve2d(x, h),
        mesh, a1)


def sharded_convolve2d_ring(x, h, mesh: Mesh, axes=("dp", "sp")):
    """2D convolution for kernels LARGER than a shard tile: the 2D ring
    pipeline.

    The 1D ring's index algebra separates per axis, so the tile for
    device (s0, s1) accumulates

        y[j0, j1] = Σ_{m0, m1} Σ_{i0, i1}
            B_{s0-m0, s1-m1}[i0, i1] · h[m0·blk0 + j0 - i0,
                                         m1·blk1 + j1 - i1]

    with tiles streaming along ``axes[1]`` (inner ring) inside a stream
    along ``axes[0]`` (outer ring) — ``(M0+1)·(M1+1)`` local convs and
    ``M0 + (M0+1)·M1`` ``ppermute`` hops, causality-masked per axis.
    Works for ANY kernel size, even larger than the image on either
    axis (hop counts clamp at the mesh axis size − 1, covering every
    causal tile pair); for kernels whose halo fits one tile prefer
    :func:`sharded_convolve2d` (two hops).
    """
    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim != 2 or h.ndim != 2:
        raise ValueError("sharded_convolve2d_ring shards one [n0, n1] "
                         "image with an [k0, k1] kernel")
    n0, n1 = x.shape
    k0, k1 = h.shape
    a0, a1 = axes
    s0, s1 = mesh.shape[a0], mesh.shape[a1]
    out0, out1 = n0 + k0 - 1, n1 + k1 - 1
    blk0, blk1 = -(-out0 // s0), -(-out1 // s1)
    x_pad = jnp.pad(x, ((0, blk0 * s0 - n0), (0, blk1 * s1 - n1)))
    hops0 = min(-(-(k0 - 1) // blk0), s0 - 1)
    hops1 = min(-(-(k1 - 1) // blk1), s1 - 1)
    h_pp = jnp.pad(h, ((blk0 - 1, (hops0 + 2) * blk0),
                       (blk1 - 1, (hops1 + 2) * blk1)))

    perm0 = [(i, (i + 1) % s0) for i in range(s0)]
    perm1 = [(i, (i + 1) % s1) for i in range(s1)]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(a0, a1), P()), out_specs=P(a0, a1))
    def _run(x_local, h_padded):
        i0 = jax.lax.axis_index(a0)
        i1 = jax.lax.axis_index(a1)
        y = jnp.zeros_like(x_local)
        row = x_local
        for m0 in range(hops0 + 1):
            tile = row
            for m1 in range(hops1 + 1):
                seg = jax.lax.slice(
                    h_padded, (m0 * blk0, m1 * blk1),
                    (m0 * blk0 + 2 * blk0 - 1,
                     m1 * blk1 + 2 * blk1 - 1))
                contrib = _ring_tile_conv2d(tile, seg)
                keep = jnp.logical_and(i0 - m0 >= 0,
                                       i1 - m1 >= 0).astype(contrib.dtype)
                y = y + keep * contrib
                if m1 < hops1:
                    tile = jax.lax.ppermute(tile, a1, perm1)
            if m0 < hops0:
                row = jax.lax.ppermute(row, a0, perm0)
        return y

    from veles.simd_tpu.ops import convolve2d as cv2

    jfn = _instrumented("sharded_convolve2d_ring", _run)
    return _sharded_guard(
        "sharded_convolve2d_ring",
        lambda: jfn(x_pad, h_pp)[:out0, :out1],
        lambda: cv2.convolve2d(x, h),
        mesh, a1)


def _ring_tile_conv2d(tile, seg):
    """The [blk0-1, 2·blk0-1) × [blk1-1, 2·blk1-1) window of the full 2D
    convolution of a [blk0, blk1] tile with a [2·blk0-1, 2·blk1-1]
    kernel segment — one 2D ring hop's contribution.  Direct MXU form
    below the (1D-measured) spectral crossover, rFFT2 above."""
    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.utils.memory import next_highest_power_of_2

    b0, b1 = tile.shape[-2:]
    g0, g1 = seg.shape[-2:]
    if b0 * b1 * g0 * g1 < cv.AUTO_FFT_MIN_PRODUCT ** 2:
        lhs = tile.reshape((1, 1, b0, b1))
        rhs = jnp.flip(seg, axis=(-2, -1)).reshape((1, 1, g0, g1))
        out = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(1, 1),
            padding=[(b0 - 1, b0 - 1), (b1 - 1, b1 - 1)],
            precision=prx.HIGHEST)
        return out.reshape(tile.shape[:-2] + (b0, b1))
    m0 = next_highest_power_of_2(b0 + g0 - 1)
    m1 = next_highest_power_of_2(b1 + g1 - 1)
    spec = (jnp.fft.rfft2(tile, (m0, m1)) * jnp.fft.rfft2(seg, (m0, m1)))
    full = jnp.fft.irfft2(spec, (m0, m1))
    return full[..., b0 - 1:2 * b0 - 1, b1 - 1:2 * b1 - 1].astype(
        tile.dtype)


def _a2a_quad2d(row_fn, imgs, mesh: Mesh, axis: str):
    """Shared all-to-all separable-2D choreography for a BATCH of
    row-sharded images ``[m, n0, n1]``: row pass on complete local
    rows, tiled ``all_to_all`` to column-split, column pass, transpose
    back.  ``row_fn(x) -> (hi, lo)`` is the 1D analysis along the last
    axis (decimating or not).  Returns ``(ll, lh, hl, hh)``, each
    ``[m, n0', n1']`` row-sharded.  One shard_map / two collective
    rounds regardless of ``m`` — callers batch bands instead of
    looping."""
    from veles.simd_tpu.ops import wavelet as wv

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(None, axis, None),
        out_specs=(P(None, axis, None),) * 4)
    def _run(x_local):
        hi_r, lo_r = row_fn(x_local)                 # [m, n0/S, n1']
        both = jnp.stack([hi_r, lo_r])               # [2, m, n0/S, n1']
        cols = jax.lax.all_to_all(both, axis, split_axis=3,
                                  concat_axis=2, tiled=True)
        bands, lows = wv._apply_last(row_fn, cols)   # [2, m, n0', n1'/S]
        quad = jnp.stack([bands, lows])              # [2, 2, m, ...]
        quad = jax.lax.all_to_all(quad, axis, split_axis=3,
                                  concat_axis=4, tiled=True)
        (hh, lh), (hl, ll) = quad[0], quad[1]
        return ll, lh, hl, hh

    return _run(imgs)


def sharded_wavelet_apply2d(type, order, ext, img, mesh: Mesh,
                            axis: str = "sp"):
    """Separable 2D DWT of one image with rows sharded over
    ``mesh[axis]`` — the **all-to-all** (Ulysses-style) layout pattern,
    complementing the halo/ring family.

    Each device transforms its complete rows locally, an
    ``all_to_all`` re-shards from row-split to column-split (the
    distributed-transpose step of 2D FFTs), the column pass runs
    locally on complete columns, and a second ``all_to_all`` restores
    the row sharding.  Because every 1D pass sees whole rows/columns,
    **all four boundary extensions are exact** — no halo approximation
    anywhere.  Returns ``(ll, lh, hl, hh)``, each ``[n0/2, n1/2]``
    sharded on the first dim, matching
    :func:`veles.simd_tpu.ops.wavelet.wavelet_apply2d`.

    Requires ``n0 % (2·S) == 0`` and ``n1 % (2·S) == 0`` (both passes
    halve a dimension that must then re-split S ways).
    """
    from veles.simd_tpu.ops import wavelet as wv

    img = jnp.asarray(img, jnp.float32)
    if img.ndim != 2:
        raise ValueError("sharded_wavelet_apply2d shards one [n0, n1] "
                         "image")
    n0, n1 = img.shape
    s = mesh.shape[axis]
    if n0 % (2 * s) or n1 % (2 * s):
        raise ValueError(
            f"image {img.shape} must have both dims divisible by "
            f"2*{axis}={2 * s} (each pass halves a dim that re-splits "
            f"{s} ways)")

    quad = _a2a_quad2d(
        lambda v: wv.wavelet_apply(type, order, ext, v, simd=True),
        img[None], mesh, axis)
    return tuple(b[0] for b in quad)


def sharded_wavelet_reconstruct2d(type, order, ll, lh, hl, hh, mesh: Mesh,
                                  axis: str = "sp"):
    """Exact inverse of :func:`sharded_wavelet_apply2d` for the PERIODIC
    extension: the same all-to-all choreography in reverse (column
    synthesis on complete columns, transpose, row synthesis).

    Non-PERIODIC synthesis needs the host-float64 boundary solve
    (:mod:`veles.simd_tpu.ops.wavelet`), which cannot run inside
    ``shard_map`` — gather the bands and use the single-chip
    :func:`wavelet_reconstruct2d` for those.
    """
    from veles.simd_tpu.ops import wavelet as wv

    bands = [jnp.asarray(b, jnp.float32) for b in (ll, lh, hl, hh)]
    if any(b.shape != bands[0].shape or b.ndim != 2 for b in bands):
        raise ValueError("need four equal [m0, m1] bands")
    m0, m1 = bands[0].shape
    s = mesh.shape[axis]
    if m0 % s or m1 % s:
        raise ValueError(
            f"band dims {bands[0].shape} must be divisible by {axis}={s}")

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None),) * 4, out_specs=P(axis, None))
    def _run(ll_b, lh_b, hl_b, hh_b):
        quad = jnp.stack([jnp.stack([hh_b, lh_b]),
                          jnp.stack([hl_b, ll_b])])  # [2, 2, m0/S, m1]
        # row-split -> column-split
        quad = jax.lax.all_to_all(quad, axis, split_axis=3, concat_axis=2,
                                  tiled=True)        # [2, 2, m0, m1/S]
        # column synthesis on complete columns
        rec = wv.wavelet_reconstruct(
            type, order, quad[0].swapaxes(-1, -2),
            quad[1].swapaxes(-1, -2), simd=True)     # [2, m1/S, 2*m0]
        rec = rec.swapaxes(-1, -2)                   # [2, 2*m0, m1/S]
        # column-split -> row-split
        rec = jax.lax.all_to_all(rec, axis, split_axis=1, concat_axis=2,
                                 tiled=True)         # [2, 2*m0/S, m1]
        # row synthesis on complete rows
        return wv.wavelet_reconstruct(type, order, rec[0], rec[1],
                                      simd=True)     # [2*m0/S, 2*m1]

    return _run(*bands)


def sharded_swt(type, order, levels, x, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel stationary-wavelet cascade (periodic extension).

    The à-trous cascade (``src/wavelet.c:211-246``) sharded along the
    signal: level ℓ needs a right halo of ``(order-1)·2^(ℓ-1)`` samples,
    fetched with a ring ``ppermute`` (periodic extension wraps the global
    signal, which on a ring mesh is exactly the last→first hop).  All
    ``levels`` levels run inside ONE shard_map, so XLA overlaps each
    level's halo transfer with compute.  Returns
    ``[hi_1, ..., hi_levels, lo_levels]``, every band of the input length
    — matching :func:`stationary_wavelet_transform` with PERIODIC.
    """
    from veles.simd_tpu.ops import wavelet as wv

    x = jnp.asarray(x, jnp.float32)
    if x.ndim < 1:
        raise ValueError("sharded_swt needs [..., n]")
    n = x.shape[-1]
    order = int(order)
    levels = int(levels)
    if levels < 1:
        raise ValueError("levels must be >= 1")
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible by {axis}="
                         f"{n_shards} (SWT keeps length; pad first)")
    max_halo = order * (1 << (levels - 1))
    if max_halo > n // n_shards:
        raise ValueError(
            f"level-{levels} halo {max_halo} exceeds the per-shard block "
            f"({n // n_shards}); fewer shards or fewer levels")
    hi_f, lo_f = wv._filters(type, order)
    hi_f, lo_f = jnp.asarray(hi_f), jnp.asarray(lo_f)
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    def _level(cur, dilation):
        # reference right-extension is order*dilation; VALID windows only
        # reach (order-1)*dilation past the last start, but keep the full
        # ext for bit-parity with the single-chip kernel's slice
        halo_len = order * dilation
        halo = halo_exchange_right(cur, halo_len, axis, periodic=True)
        cur_ext = jnp.concatenate([cur, halo], axis=-1)
        batch_shape = cur.shape[:-1]
        lhs = cur_ext.reshape((-1, 1, cur_ext.shape[-1]))
        rhs = jnp.stack([hi_f, lo_f]).reshape((2, 1, order))
        out = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(1,), padding="VALID",
            rhs_dilation=(dilation,),
            precision=prx.HIGHEST)
        out = out[..., :cur.shape[-1]].reshape(
            batch_shape + (2, cur.shape[-1]))
        return out[..., 0, :], out[..., 1, :]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=spec, out_specs=spec)
    def _run(x_local):
        outs = []
        cur = x_local
        for lvl in range(1, levels + 1):
            hi, cur = _level(cur, 1 << (lvl - 1))
            outs.append(hi)
        return tuple(outs) + (cur,)

    return list(_run(x))


def sharded_swt_reconstruct(type, order, levels, coeffs, mesh: Mesh,
                            axis: str = "sp"):
    """Sequence-parallel inverse of :func:`sharded_swt` (PERIODIC).

    Synthesis is the frame adjoint over ``2c²``: a dilated *convolution*
    with the unflipped filters, whose windows reach ``(order−1)·2^(ℓ−1)``
    samples to the **left** — so each level does one left-halo ring
    ``ppermute`` (the mirror image of the analysis' right halo) and a
    local ``conv_general_dilated``.  All levels run inside one
    ``shard_map``.  Accepts the ``[hi_1, ..., hi_L, lo_L]`` list that
    :func:`sharded_swt` (or the single-chip transform) returns, every
    band ``[..., n]``; returns the reconstructed ``[..., n]`` signal
    matching :func:`stationary_wavelet_inverse_transform`.
    """
    from veles.simd_tpu.ops import wavelet as wv

    coeffs = [jnp.asarray(c, jnp.float32) for c in coeffs]
    levels = int(levels)
    order = int(order)
    if levels < 1 or len(coeffs) != levels + 1:
        raise ValueError("need [hi_1, ..., hi_L, lo_L] matching levels")
    n = coeffs[0].shape[-1]
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"band length {n} not divisible by {axis}="
                         f"{n_shards}")
    max_halo = (order - 1) * (1 << (levels - 1))
    if max_halo > n // n_shards:
        raise ValueError(
            f"level-{levels} synthesis halo {max_halo} exceeds the "
            f"per-shard block ({n // n_shards}); fewer shards or levels")
    hi_f, lo_f = wv._filters(type, order)
    c2 = float(wv._c2(lo_f))
    # convolution = correlation with flipped taps
    rhs = jnp.stack([jnp.asarray(hi_f[::-1].copy()),
                     jnp.asarray(lo_f[::-1].copy())]).reshape(1, 2, order)
    nd = coeffs[0].ndim
    spec = P(*([None] * (nd - 1) + [axis]))

    def _inv_level(hi_b, lo_b, dilation):
        halo_len = (order - 1) * dilation
        # left halo: x[t] sums y[(t − j·dil) mod n] — periodic ring
        h_hi = halo_exchange_left(hi_b, halo_len, axis, periodic=True)
        h_lo = halo_exchange_left(lo_b, halo_len, axis, periodic=True)
        ext = jnp.stack(
            [jnp.concatenate([h_hi, hi_b], axis=-1),
             jnp.concatenate([h_lo, lo_b], axis=-1)], axis=-2)
        batch_shape = hi_b.shape[:-1]
        lhs = ext.reshape((-1, 2, ext.shape[-1]))
        out = jax.lax.conv_general_dilated(
            lhs, rhs.astype(jnp.float32),
            window_strides=(1,), padding="VALID",
            rhs_dilation=(dilation,),
            precision=prx.HIGHEST)[:, 0]
        return (out / (2.0 * c2)).reshape(batch_shape + (hi_b.shape[-1],))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple([spec] * (levels + 1)), out_specs=spec)
    def _run(*bands):
        cur = bands[-1]
        for lvl in range(levels, 0, -1):
            cur = _inv_level(bands[lvl - 1], cur, 1 << (lvl - 1))
        return cur

    return _run(*coeffs)


def sharded_wavelet_apply(type, order, x, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel single-level DWT analysis (PERIODIC): signal
    ``[..., n]`` sharded along length → ``(hi, lo)`` bands ``[..., n/2]``
    sharded the same way.

    Each shard's stride-2 windows reach ``order − 2`` samples past its
    block, so one right-halo ring ``ppermute`` (periodic wrap) feeds a
    local strided conv — the analysis-side mirror of
    :func:`sharded_wavelet_reconstruct`, closing the sharded DWT round
    trip.
    """
    from veles.simd_tpu.ops import wavelet as wv

    x = jnp.asarray(x, jnp.float32)
    order = int(order)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    if n % (2 * n_shards):
        raise ValueError(f"signal length {n} must be divisible by "
                         f"2*{axis}={2 * n_shards}")
    # stride-2 windows reach (order-2) samples past the block: the last
    # window starts at block-2 and spans order taps
    halo = order - 2
    if halo > n // n_shards:
        raise ValueError(
            f"analysis halo {halo} exceeds the per-shard block "
            f"({n // n_shards}); fewer shards")
    hi_f, lo_f = wv._filters(type, order)
    rhs = jnp.stack([jnp.asarray(hi_f),
                     jnp.asarray(lo_f)]).reshape(2, 1, order)
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=spec, out_specs=(spec, spec))
    def _run(x_local):
        h = halo_exchange_right(x_local, halo, axis, periodic=True)
        ext = jnp.concatenate([x_local, h], axis=-1)
        batch_shape = x_local.shape[:-1]
        m_loc = x_local.shape[-1] // 2
        lhs = ext.reshape((-1, 1, ext.shape[-1]))
        out = jax.lax.conv_general_dilated(
            lhs, rhs.astype(jnp.float32), window_strides=(2,),
            padding="VALID", precision=prx.HIGHEST)
        out = out[..., :m_loc].reshape(batch_shape + (2, m_loc))
        return out[..., 0, :], out[..., 1, :]

    return _run(x)


def sharded_wavelet_transform(type, order, x, levels, mesh: Mesh,
                              axis: str = "sp"):
    """Multi-level sequence-parallel DWT cascade (PERIODIC): repeatedly
    split the length-sharded lowpass band.  Returns
    ``[hi_1, ..., hi_L, lo_L]`` like the single-chip
    :func:`veles.simd_tpu.ops.wavelet.wavelet_transform`, every band
    sharded over ``mesh[axis]``.  The per-shard block halves each level,
    so depth is bounded by ``n / (S · 2^(L-1)) >= order - 2``."""
    coeffs = []
    cur = x
    for _ in range(int(levels)):
        hi, cur = sharded_wavelet_apply(type, order, cur, mesh, axis=axis)
        coeffs.append(hi)
    coeffs.append(cur)
    return coeffs


def sharded_wavelet_inverse_transform(type, order, coeffs, mesh: Mesh,
                                      axis: str = "sp"):
    """Invert :func:`sharded_wavelet_transform` on the mesh (PERIODIC)."""
    coeffs = list(coeffs)
    if len(coeffs) < 2:
        raise ValueError("need [hi_1, ..., hi_L, lo_L] with L >= 1")
    cur = coeffs[-1]
    for hi in reversed(coeffs[:-1]):
        cur = sharded_wavelet_reconstruct(type, order, hi, cur, mesh,
                                          axis=axis)
    return cur


def sharded_wavelet_reconstruct(type, order, desthi, destlo, mesh: Mesh,
                                axis: str = "sp"):
    """Sequence-parallel exact inverse of the PERIODIC DWT analysis:
    bands ``[..., m]`` sharded along length → signal ``[..., 2m]``.

    The adjoint upsamples by 2 and convolves: output sample ``t`` sums
    band samples down to ``⌈(t−order+1)/2⌉``, i.e. a left halo of
    ``order/2`` band samples per shard (ring ``ppermute``), then a local
    ``lhs_dilation=2`` convolution sliced to the shard's span — the
    distributed form of :func:`veles.simd_tpu.ops.wavelet._synth_conv`.
    """
    from veles.simd_tpu.ops import wavelet as wv

    desthi = jnp.asarray(desthi, jnp.float32)
    destlo = jnp.asarray(destlo, jnp.float32)
    if desthi.shape != destlo.shape:
        raise ValueError(
            f"band shapes differ: {desthi.shape} vs {destlo.shape}")
    order = int(order)
    m = desthi.shape[-1]
    n_shards = mesh.shape[axis]
    if m % n_shards:
        raise ValueError(f"band length {m} not divisible by {axis}="
                         f"{n_shards}")
    halo = order // 2
    if halo > m // n_shards:
        raise ValueError(
            f"synthesis halo {halo} exceeds the per-shard band block "
            f"({m // n_shards}); fewer shards")
    hi_f, lo_f = wv._filters(type, order)
    c2 = float(wv._c2(lo_f))
    rhs = jnp.stack([jnp.asarray(hi_f[::-1].copy()),
                     jnp.asarray(lo_f[::-1].copy())]).reshape(1, 2, order)
    nd = desthi.ndim
    spec = P(*([None] * (nd - 1) + [axis]))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec), out_specs=spec)
    def _run(hi_b, lo_b):
        h_hi = halo_exchange_left(hi_b, halo, axis, periodic=True)
        h_lo = halo_exchange_left(lo_b, halo, axis, periodic=True)
        ext = jnp.stack(
            [jnp.concatenate([h_hi, hi_b], axis=-1),
             jnp.concatenate([h_lo, lo_b], axis=-1)], axis=-2)
        batch_shape = hi_b.shape[:-1]
        m_loc = hi_b.shape[-1]
        lhs = ext.reshape((-1, 2, ext.shape[-1]))
        # full conv of the 2-upsampled ext; pad so every needed index
        # exists, then take the shard's span: out_local[τ] = full[τ + 2H]
        pad = order - 1
        full = jax.lax.conv_general_dilated(
            lhs, rhs.astype(jnp.float32), window_strides=(1,),
            padding=[(pad, pad)], lhs_dilation=(2,),
            precision=prx.HIGHEST)[:, 0]
        out = jax.lax.slice_in_dim(full, 2 * halo, 2 * halo + 2 * m_loc,
                                   axis=-1)
        return (out / c2).reshape(batch_shape + (2 * m_loc,))

    return _run(desthi, destlo)


def sharded_matmul(a, b, mesh: Mesh, axis: str = "tp"):
    """Tensor-parallel GEMM: contracting dim sharded, ``psum`` over ICI.

    ``a [m, K] @ b [K, n]`` with K split across ``mesh[axis]``; each chip
    computes a partial ``[m, n]`` on its MXU and the partials are
    all-reduced.  K is zero-padded up to a multiple of the axis size
    (zeros contribute nothing to the contraction).
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.shape[-1] != b.shape[-2]:
        raise ValueError(f"contracting dims differ: {a.shape} @ {b.shape}")
    shards = mesh.shape[axis]
    obs.record_decision(
        "sharded_matmul", "contracting_dim", n_shards=int(shards),
        axis=axis, m=int(a.shape[-2]), k=int(a.shape[-1]),
        n=int(b.shape[-1]))
    with obs.span("sharded_matmul.dispatch", n_shards=int(shards)):
        a0, b0 = a, b
        rem = a.shape[-1] % shards
        if rem:
            pad = shards - rem
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, pad)])
            b = jnp.pad(b, [(0, 0)] * (b.ndim - 2)
                        + [(0, pad), (0, 0)])

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, axis), P(axis, None)),
            out_specs=P(None, None))
        def _run(a_local, b_local):
            partial = jnp.dot(a_local, b_local,
                              precision=prx.HIGHEST)
            return jax.lax.psum(partial, axis)

        from veles.simd_tpu.ops import matrix as mx

        jfn = _instrumented("sharded_matmul", _run)
        return _sharded_guard(
            "sharded_matmul",
            lambda: jfn(a, b),
            lambda: mx.matrix_multiply(a0, b0),
            mesh, axis)


def _check_stft_sharding(n, frame_length, hop, n_shards):
    from veles.simd_tpu.ops import spectral as sp

    sp._check_stft_args(n, frame_length, hop)
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible into "
                         f"{n_shards} shards (pad first)")
    block = n // n_shards
    if block % hop:
        raise ValueError(
            f"per-shard block {block} not a multiple of hop {hop} — "
            "frame starts would straddle shard ownership; choose a hop "
            "that divides the block (or fewer shards)")
    halo = frame_length - hop
    if halo > block:
        raise ValueError(
            f"frame overlap {halo} (frame_length - hop) exceeds the "
            f"per-shard block {block}; fewer shards or a larger hop")
    return block, halo


def sharded_stft(x, frame_length: int, hop: int, mesh: Mesh,
                 axis: str = "sp", window=None):
    """Sequence-parallel STFT: the signal sharded along time, one
    ``ppermute`` right-halo of ``frame_length - hop`` samples per shard.

    Frame ownership follows sample ownership: a frame belongs to the
    shard its start sample lives on (``block % hop == 0`` keeps that
    uniform at ``block // hop`` frames per shard), so the output's frame
    axis comes back sharded over the SAME mesh axis — a long-signal
    spectrogram pipeline never gathers the signal.  Matches the
    single-chip :func:`veles.simd_tpu.ops.spectral.stft` exactly: the
    per-shard frame count includes up to ``(frame_length - hop) / hop``
    trailing frames that overhang the global signal end (computed
    against the zero halo ``ppermute`` feeds the last shard), and those
    are sliced off the sharded result before returning.
    """
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import fourier as _fr

    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    block, halo = _check_stft_sharding(n, frame_length, hop, n_shards)
    obs.record_decision(
        "sharded_stft", "right_halo", n_shards=int(n_shards), axis=axis,
        n=int(n), frame_length=int(frame_length), hop=int(hop),
        block=int(block), halo=int(halo))
    window_np = sp._resolve_window(window, frame_length)
    # the LOCAL per-frame transform goes through the engine's
    # parallel.frame_dft table (never raw jnp.fft): the rdft-basis
    # matmul within the single-chip cutoff, the Cooley-Tukey
    # factorized matmul above it, xla_fft terminal — recorded either
    # way so the executed formulation is artifact-attributable
    local_route = _fr.select_frame_route(frame_length)
    obs.record_decision(
        "sharded_stft_local", local_route, n_shards=int(n_shards),
        frame_length=int(frame_length), hop=int(hop))
    frame_fn = _fr.frame_rfft_fn(local_route, frame_length, window_np)
    # per-shard framing layout == the single-chip layout on block + halo
    # samples (frame_count(block + halo, fl, hop) == block // hop)
    frames_local = sp.frame_count(block + halo, frame_length, hop)
    in_spec = P(*([None] * (x.ndim - 1) + [axis]))
    out_spec = P(*([None] * (x.ndim - 1) + [axis, None]))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_spec, out_specs=out_spec)
    def _run(x_local):
        halo_part = halo_exchange_right(x_local, halo, axis)
        x_ext = jnp.concatenate([x_local, halo_part], axis=-1)
        # the reshape-interleave framing (99x over the row gather on
        # dividing hops, sp._take_frames); slice to the uniform
        # per-shard frame count the layout math above established
        frames = sp._take_frames(x_ext, frame_length, hop)
        return frame_fn(frames[..., :frames_local, :])

    with obs.span("sharded_stft.dispatch", n_shards=int(n_shards)):
        fc = sp.frame_count(n, frame_length, hop)
        jfn = _instrumented("sharded_stft", _run)
        return _sharded_guard(
            "sharded_stft",
            lambda: jfn(x)[..., :fc, :],
            lambda: sp.stft(x, frame_length, hop, window=window),
            mesh, axis)


def sharded_istft(spec, n: int, frame_length: int, hop: int, mesh: Mesh,
                  axis: str = "sp", window=None):
    """Sequence-parallel inverse STFT (windowed overlap-add).

    The adjoint of :func:`sharded_stft`'s halo pattern: each shard
    overlap-adds its own frames into a ``block + (frame_length - hop)``
    local buffer, and the overhang — the samples its last frames wrote
    into the RIGHT neighbour's territory — rides one ``ppermute`` to be
    added onto that neighbour's head.  COLA normalization (division by
    the global window-overlap envelope) happens outside the shard_map as
    a plain sharded elementwise multiply.  Matches the single-chip
    :func:`veles.simd_tpu.ops.spectral.istft`.
    """
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import fourier as _fr

    n_shards = mesh.shape[axis]
    block, halo = _check_stft_sharding(n, frame_length, hop, n_shards)
    window_np = sp._resolve_window(window, frame_length)
    # engine-selected local synthesis transform (inverse rdft basis
    # within the cutoff / Cooley-Tukey above it / xla irfft terminal)
    local_route = _fr.select_frame_route(frame_length)
    obs.record_decision(
        "sharded_istft_local", local_route, n_shards=int(n_shards),
        frame_length=int(frame_length), hop=int(hop))
    frame_fn = _fr.frame_irfft_fn(local_route, frame_length,
                                  window_np)
    spec = jnp.asarray(spec, jnp.complex64)
    spec0 = spec
    frames_total = sp.frame_count(n, frame_length, hop)
    if spec.shape[-2:] != (frames_total, frame_length // 2 + 1):
        raise ValueError(
            f"spec shape {spec.shape[-2:]} inconsistent with n={n}, "
            f"frame_length={frame_length}, hop={hop} (expect "
            f"{(frames_total, frame_length // 2 + 1)})")
    # pad the frame axis back out to the uniform n // hop per-shard count
    # (the overhang frames sharded_stft sliced off) with zero frames —
    # zeros contribute nothing to the overlap-add
    pad_frames = n // hop - frames_total
    if pad_frames:
        spec = jnp.pad(spec, [(0, 0)] * (spec.ndim - 2)
                       + [(0, pad_frames), (0, 0)])
    in_spec = P(*([None] * (spec.ndim - 2) + [axis, None]))
    out_spec = P(*([None] * (spec.ndim - 2) + [axis]))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_spec, out_specs=out_spec)
    def _run(spec_local):
        frames = frame_fn(spec_local)
        # the decomposed overlap-add (sp._overlap_add, 52x over the
        # .at[].add scatter on dividing hops) on the local block+halo
        buf = sp._overlap_add(frames, block + halo, frame_length, hop)
        overflow = buf[..., block:]  # [..., halo] — right neighbour's head
        n_sh = _axis_size(axis)
        recv = jax.lax.ppermute(overflow, axis,
                                [(i, i + 1) for i in range(n_sh - 1)])
        head = buf[..., :halo] + recv
        return jnp.concatenate([head, buf[..., halo:block]], axis=-1)

    env_inv = jnp.asarray(
        sp._env_inv(n, frame_length, hop, window_np).astype(np.float32))
    jfn = _instrumented("sharded_istft", _run)
    return _sharded_guard(
        "sharded_istft",
        lambda: jfn(spec) * env_inv,
        lambda: sp.istft(spec0, n, frame_length, hop, window=window),
        mesh, axis)


def sharded_sosfilt(sos, x, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel IIR cascade — the recurrence crosses shard
    boundaries, and still never runs sequentially.

    Two-level formulation of the associative-scan recurrence
    (:func:`veles.simd_tpu.ops.iir.sosfilt`): each shard scans its own
    block assuming a zero incoming state (level 1, O(log B) depth); the
    per-shard exit states ride ONE ``all_gather`` of a ``[2]`` vector
    per shard, and every shard combines its predecessors' summaries
    through host-precomputed powers of the block transition matrix
    ``A^B`` to get its true incoming state (level 2 — a tiny [S, S]
    constant contraction, no sequential hop chain); the exact global
    result is then ``s_local[t] + A^(t+1) @ s_in``, with the cumulative
    powers ``A^(t+1)`` taken from the same scan's product track — one
    scan total per section.  Collective traffic per section and shard:
    a 2-float exit state (all_gather) plus a 2-sample x halo (ppermute).

    ``x`` is ``[..., n]`` with the last axis sharded; sections run in
    cascade order as on a single chip.  Matches
    ``iir.sosfilt(sos, gathered_x)`` exactly.
    """
    from veles.simd_tpu.ops import iir as _iir

    sos = _iir._check_sos(sos)
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible into "
                         f"{n_shards} shards (pad first)")
    block = n // n_shards
    if block < 2:
        raise ValueError("per-shard block must be >= 2")
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    # host-side constants per section: A, A^B, and the prefix-combine
    # weights W[i, j] = (A^B)^(i-1-j) for j < i (zero otherwise), so
    # s_in[i] = sum_j W[i, j] @ s_exit[j]
    sections = []
    for b0, b1, b2, _, a1, a2 in sos:
        a_np = np.array([[-a1, -a2], [1.0, 0.0]])
        a_blk = np.linalg.matrix_power(a_np, block)
        pows = [np.eye(2)]
        for _ in range(n_shards - 1):
            pows.append(a_blk @ pows[-1])
        w = np.zeros((n_shards, n_shards, 2, 2))
        for i in range(n_shards):
            for j in range(i):
                w[i, j] = pows[i - 1 - j]
        sections.append((np.float32(b0), np.float32(b1), np.float32(b2),
                         np.float32(a1), np.float32(a2),
                         jnp.asarray(w, jnp.float32)))

    def _section(x_local, sec):
        b0, b1, b2, a1, a2, w = sec
        # FIR drive with the 2-sample x halo from the left neighbour
        halo = halo_exchange_left(x_local, 2, axis)
        x_ext = jnp.concatenate([halo, x_local], axis=-1)
        u = (b0 * x_ext[..., 2:] + b1 * x_ext[..., 1:-1]
             + b2 * x_ext[..., :-2])
        # level 1: ONE local scan from a zero incoming state; the same
        # scan's cumulative products cum_a[t] = A^(t+1) come out free
        drive = jnp.stack([u, jnp.zeros_like(u)], axis=-1)
        cum_a, states0 = _iir._biquad_affine_scan(a1, a2, drive)
        s_exit = states0[..., -1, :]                     # [..., 2]
        # level 2: gather every shard's exit state, combine prefixes
        gathered = jax.lax.all_gather(s_exit, axis)      # [S, ..., 2]
        # Precision.HIGHEST on both contractions: TPU einsum defaults
        # to bf16 and the state corrections are exactly where rounding
        # becomes audible (see iir._affine_combine)
        hi = prx.HIGHEST
        s_in_all = jnp.einsum("ijkl,j...l->i...k", w, gathered,
                              precision=hi)
        idx = jax.lax.axis_index(axis)
        s_in = jnp.take(s_in_all, idx, axis=0)           # [..., 2]
        # exact correction, no second scan:
        # s_true[t] = s_local[t] + A^(t+1) @ s_in
        return (states0 + jnp.einsum("...tij,...j->...ti", cum_a,
                                     s_in, precision=hi))[..., 0]

    @functools.partial(shard_map, mesh=mesh, in_specs=spec,
                       out_specs=spec)
    def _run(x_local):
        cur = x_local
        for sec in sections:
            cur = _section(cur, sec)
        return cur

    jfn = _instrumented("sharded_sosfilt", _run)
    return _sharded_guard(
        "sharded_sosfilt",
        lambda: jfn(x),
        lambda: _iir.sosfilt(sos, x),
        mesh, axis)


def sharded_welch(x, mesh: Mesh, axis: str = "sp", fs: float = 1.0,
                  nperseg: int = 256, noverlap=None, window=None):
    """Sequence-parallel Welch PSD: segments are framed per shard with
    the :func:`sharded_stft` halo pattern, each shard accumulates its
    own masked ``|fft|^2`` sum, and ONE ``psum`` of a ``[bins]`` vector
    per shard produces the global average — the signal is never
    gathered, and the collective payload is independent of its length.

    Matches the single-chip :func:`veles.simd_tpu.ops.spectral.welch`
    (Hann window, constant per-segment detrend, density scaling).
    Returns ``(freqs, Pxx)`` with ``Pxx`` replicated over the mesh.
    """
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import fourier as _fr

    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    nperseg_c, hop, window_np = sp._welch_args(n, nperseg, noverlap,
                                               window)
    block, halo = _check_stft_sharding(n, nperseg_c, hop, n_shards)
    frames_total = sp.frame_count(n, nperseg_c, hop)
    frames_per_shard = block // hop
    # engine-selected local segment transform (parallel.frame_dft,
    # window folded into the basis on the rdft route)
    local_route = _fr.select_frame_route(nperseg_c)
    obs.record_decision(
        "sharded_welch_local", local_route, n_shards=int(n_shards),
        nperseg=int(nperseg_c), hop=int(hop))
    frame_fn = _fr.frame_rfft_fn(
        local_route, nperseg_c, np.asarray(window_np, np.float32))
    scale_mult = jnp.asarray(
        sp._onesided_scale(nperseg_c, fs, window_np, "density"),
        jnp.float32)
    freqs = np.fft.rfftfreq(nperseg_c, 1.0 / fs)
    in_spec = P(*([None] * (x.ndim - 1) + [axis]))
    out_spec = P(*([None] * (x.ndim - 1) + [None]))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=in_spec, out_specs=out_spec)
    def _run(x_local):
        halo_part = halo_exchange_right(x_local, halo, axis)
        x_ext = jnp.concatenate([x_local, halo_part], axis=-1)
        segs = sp._take_frames(x_ext, nperseg_c,
                               hop)[..., :frames_per_shard, :]
        segs = segs - jnp.mean(segs, axis=-1, keepdims=True)
        fx = frame_fn(segs)
        # mask the trailing frames that overhang the global signal end
        # (they exist only so every shard has a uniform frame count)
        gidx = (jax.lax.axis_index(axis) * frames_per_shard
                + jnp.arange(frames_per_shard))
        mask = (gidx < frames_total).astype(jnp.float32)
        local = jnp.sum((jnp.abs(fx) ** 2) * mask[..., :, None],
                        axis=-2)
        return jax.lax.psum(local, axis) / frames_total

    jfn = _instrumented("sharded_welch", _run)
    pxx = _sharded_guard(
        "sharded_welch",
        lambda: jfn(x) * scale_mult,
        lambda: sp.welch(x, fs=fs, nperseg=nperseg,
                         noverlap=noverlap, window=window)[1],
        mesh, axis)
    return freqs, pxx


def sharded_resample_poly(x, up: int, down: int, mesh: Mesh,
                          axis: str = "sp", taps=None):
    """Sequence-parallel rational-rate resampling: each shard runs the
    SAME dilated/strided polyphase conv the single chip runs
    (``ops.resample._resample_conv``) on its halo-extended block.

    Output ownership follows input ownership: with ``block * up``
    divisible by ``down``, every shard produces exactly
    ``block * up / down`` output samples, so the result comes back
    sharded over the same axis.  Halos are the filter's group-delay
    reach divided by the upsampling factor (left ``ceil(pad_l / up)``,
    right ``ceil((k - 1 - pad_l) / up)``); a negative conv padding
    crops the local window start into alignment, so edge shards
    reproduce the single-chip zero-padding exactly.  Matches
    :func:`veles.simd_tpu.ops.resample.resample_poly`.
    """
    from veles.simd_tpu.ops import resample as _rs

    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    up, down, taps = _rs._normalize_resample_args(n, up, down, taps)
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible into "
                         f"{n_shards} shards (pad first)")
    block = n // n_shards
    if (block * up) % down:
        raise ValueError(
            f"block {block} * up {up} not divisible by down {down} — "
            "output ownership would straddle shards; choose a length "
            "whose per-shard block * up is a multiple of down")
    if up == 1 and down == 1:
        return x
    k = len(taps)
    pad_l = (k - 1) // 2
    hl = -(-pad_l // up)
    hr = -(-max(k - 1 - pad_l, 0) // up)
    if max(hl, hr) > block:
        raise ValueError(
            f"filter halo ({hl} left / {hr} right input samples) "
            f"exceeds the per-shard block ({block}); fewer shards or "
            "shorter taps")
    out_block = block * up // down
    taps_j = jnp.asarray(taps, jnp.float32)
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    # negative left padding crops hl*up - pad_l dilated positions,
    # aligning local output 0 with global output s * out_block
    p_lo = pad_l - hl * up
    ext_len = block + hl + hr
    dil = (ext_len - 1) * up + 1
    p_hi = max(0, (out_block - 1) * down + k - (dil + p_lo))

    @functools.partial(shard_map, mesh=mesh, in_specs=spec,
                       out_specs=spec)
    def _run(x_local):
        left = halo_exchange_left(x_local, hl, axis)
        right = halo_exchange_right(x_local, hr, axis)
        x_ext = jnp.concatenate([left, x_local, right], axis=-1)
        # the single-chip polyphase kernel, padding overridden to the
        # halo-cropping alignment
        return _rs._resample_conv(x_ext, taps_j, up, down, out_block,
                                  pad=(p_lo, p_hi))

    jfn = _instrumented("sharded_resample_poly", _run)
    return _sharded_guard(
        "sharded_resample_poly",
        lambda: jfn(x),
        lambda: _rs.resample_poly(x, up, down, taps=taps),
        mesh, axis)


def sharded_swt_apply2d(type, order, level, ext, img, mesh: Mesh,
                        axis: str = "sp"):
    """Undecimated 2D SWT of one row-sharded image — the same
    all-to-all (distributed-transpose) choreography as
    :func:`sharded_wavelet_apply2d`, without the decimation: the row
    pass runs on complete local rows, an ``all_to_all`` re-shards to
    column-split, the column pass runs on complete columns, and a
    second ``all_to_all`` restores row sharding.  All four extensions
    are exact (every 1D pass sees whole rows/columns).  Returns
    ``(ll, lh, hl, hh)``, each full ``[n0, n1]``, row-sharded —
    matching :func:`veles.simd_tpu.ops.wavelet.
    stationary_wavelet_apply2d`.

    Requires both dims divisible by ``S`` (no halving here, so no
    factor 2).
    """
    from veles.simd_tpu.ops import wavelet as wv

    img = jnp.asarray(img, jnp.float32)
    if img.ndim != 2:
        raise ValueError("sharded_swt_apply2d shards one [n0, n1] image")
    n0, n1 = img.shape
    s = mesh.shape[axis]
    if n0 % s or n1 % s:
        raise ValueError(f"image {img.shape} must have both dims "
                         f"divisible by {axis}={s}")

    quad = _a2a_quad2d(
        lambda v: wv.stationary_wavelet_apply(type, order, level, ext,
                                              v, simd=True),
        img[None], mesh, axis)
    return tuple(b[0] for b in quad)


def sharded_wavelet_packet_transform2d(type, order, ext, img, levels,
                                       mesh: Mesh, axis: str = "sp"):
    """2D quad-tree wavelet packets of a row-sharded image: every band
    re-split at every level, all bands batched through ONE shard_map
    (two all-to-all rounds) per LEVEL — the tree stays device-resident
    end to end.  Returns the ``4^levels`` leaves in
    the same natural ``(ll, lh, hl, hh)`` order as
    :func:`veles.simd_tpu.ops.wavelet.wavelet_packet_transform2d`,
    each ``[n0/2^levels, n1/2^levels]`` row-sharded.

    Requires both dims divisible by ``2^levels * S`` (every level
    halves dims that must still split S ways).
    """
    from veles.simd_tpu.ops import wavelet as wv

    levels = int(levels)
    if levels < 1:
        raise ValueError("levels must be >= 1")
    img = jnp.asarray(img, jnp.float32)
    if img.ndim != 2:
        raise ValueError("sharded_wavelet_packet_transform2d shards one "
                         "[n0, n1] image")
    s = mesh.shape[axis]
    n0, n1 = img.shape
    need = (1 << levels) * s
    if n0 % need or n1 % need:
        raise ValueError(
            f"image {img.shape} must have both dims divisible by "
            f"2^levels * S = {need}")
    row_fn = lambda v: wv.wavelet_apply(type, order, ext, v, simd=True)
    stack = img[None]                                # [m, n0, n1]
    for _ in range(levels):
        ll, lh, hl, hh = _a2a_quad2d(row_fn, stack, mesh, axis)
        # leaf index grows a base-4 digit per level, natural
        # (ll, lh, hl, hh) order — ONE shard_map per level regardless
        # of the band count
        stack = jnp.stack([ll, lh, hl, hh], axis=1).reshape(
            (4 * stack.shape[0],) + ll.shape[1:])
    return [stack[i] for i in range(stack.shape[0])]


def sharded_order_filter(x, rank: int, kernel_size: int, mesh: Mesh,
                         axis: str = "sp"):
    """Sequence-parallel rank-order filter: pure halo exchange — each
    shard fetches ``k // 2`` neighbour samples per side and runs the
    single-chip rank kernel on its extended block (the Batcher
    compare-exchange network for ``k`` <= 32, gather+sort beyond).
    Global edge shards receive zeros from the open ``ppermute``, which
    is exactly the single-chip zero-padding, so the result is bitwise
    the single-chip :func:`veles.simd_tpu.ops.filters.order_filter`
    (both sides run the identical kernel).
    """
    from veles.simd_tpu.ops import filters as fl

    k = fl._check_kernel(kernel_size)
    rank = int(rank)
    if not 0 <= rank < k:
        raise ValueError(f"rank {rank} outside [0, {k})")
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible into "
                         f"{n_shards} shards (pad first)")
    block = n // n_shards
    half = k // 2
    if half > block:
        raise ValueError(f"kernel halo {half} exceeds the per-shard "
                         f"block {block}")
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    @functools.partial(shard_map, mesh=mesh, in_specs=spec,
                       out_specs=spec)
    def _run(x_local):
        left = halo_exchange_left(x_local, half, axis)
        right = halo_exchange_right(x_local, half, axis)
        x_ext = jnp.concatenate([left, x_local, right], axis=-1)
        y = fl._rank_filter_xla(x_ext, k, rank)
        return jax.lax.slice_in_dim(y, half, half + block, axis=-1)

    return _run(x)


def sharded_medfilt(x, kernel_size: int, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel median filter (scipy ``medfilt`` semantics) —
    :func:`sharded_order_filter` at the median rank."""
    from veles.simd_tpu.ops import filters as fl

    k = fl._check_kernel(kernel_size)
    return sharded_order_filter(x, k // 2, k, mesh, axis)


def sharded_savgol_filter(x, window_length: int, polyorder: int,
                          mesh: Mesh, deriv: int = 0, delta: float = 1.0,
                          mode: str = "interp", axis: str = "sp"):
    """Sequence-parallel Savitzky-Golay: the smoothing itself is one
    halo exchange + the local FIR correlation; the edge semantics run
    on the shards that own the edges — ``'constant'`` needs nothing
    (the open halo IS zero padding), ``'nearest'`` substitutes the
    edge shards' halos with replicated end samples, ``'interp'``
    replaces each edge half-window with the polynomial edge fit as a
    precomputed ``[half, window]`` matrix applied to the local end
    window (masked by ``axis_index``, so the fix-up costs one tiny
    matmul on every shard).  Matches the single-chip
    :func:`veles.simd_tpu.ops.filters.savgol_filter`.
    """
    from veles.simd_tpu.ops import filters as fl

    w = fl._check_kernel(window_length, "window_length")
    if mode not in ("interp", "constant", "nearest"):
        raise ValueError(f"unknown mode {mode!r}")
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(f"signal length {n} not divisible into "
                         f"{n_shards} shards (pad first)")
    block = n // n_shards
    half = w // 2
    need = w if mode == "interp" else half
    if need > block:
        raise ValueError(f"window reach {need} exceeds the per-shard "
                         f"block {block}; fewer shards or a shorter "
                         "window")
    taps = jnp.asarray(
        fl._savgol_corr_taps(w, polyorder, deriv, delta), jnp.float32)
    if mode == "interp":
        head_mat, tail_mat = (jnp.asarray(m, jnp.float32) for m in
                              fl._savgol_edge_mats(w, polyorder,
                                                   int(deriv),
                                                   float(delta)))
    spec = P(*([None] * (x.ndim - 1) + [axis]))

    @functools.partial(shard_map, mesh=mesh, in_specs=spec,
                       out_specs=spec)
    def _run(x_local):
        idx = jax.lax.axis_index(axis)
        left = halo_exchange_left(x_local, half, axis)
        right = halo_exchange_right(x_local, half, axis)
        if mode == "nearest":
            rep_l = jnp.repeat(x_local[..., :1], half, axis=-1)
            rep_r = jnp.repeat(x_local[..., -1:], half, axis=-1)
            left = jnp.where(idx == 0, rep_l, left)
            right = jnp.where(idx == n_shards - 1, rep_r, right)
        x_ext = jnp.concatenate([left, x_local, right], axis=-1)
        lhs = x_ext.reshape((-1, 1, x_ext.shape[-1]))
        rhs = taps[None, None, :]
        y = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(1,), padding="VALID",
            precision=prx.HIGHEST)
        y = y.reshape(x_local.shape[:-1] + (block,))
        if mode == "interp":
            hi = prx.HIGHEST
            head = jnp.einsum("hw,...w->...h", head_mat,
                              x_local[..., :w], precision=hi)
            tail = jnp.einsum("hw,...w->...h", tail_mat,
                              x_local[..., -w:], precision=hi)
            is_first = (idx == 0)
            is_last = (idx == n_shards - 1)
            y = jnp.concatenate(
                [jnp.where(is_first, head, y[..., :half]),
                 y[..., half:block - half],
                 jnp.where(is_last, tail, y[..., block - half:])],
                axis=-1)
        return y

    return _run(x)


def sharded_lombscargle(t, x, freqs, mesh: Mesh, axis: str = "sp",
                        weights=None):
    """Sequence-parallel Lomb-Scargle periodogram: the sample axis (the
    long one — irregular timestamps can be millions of points) is
    sharded; each device evaluates its trig grid slab and TWO ``psum``
    rounds of ``[m]``-vectors produce the global sums (first the tau
    phase sums, then the four projection sums), so the samples are
    never gathered and the collective payload is independent of the
    signal length.  Power comes back replicated, matching the
    single-chip :func:`veles.simd_tpu.ops.spectral.lombscargle`.

    Any sample count is accepted: indivisible lengths are padded to the
    next shard multiple with ZERO-weighted samples, which drop out of
    every weighted Scargle sum exactly (the weights channel VERDICT r4
    item 7 asked for).  ``weights`` is also a public argument for
    per-sample confidence, mirroring the single-chip op.
    """
    from veles.simd_tpu.ops.spectral import _check_lombscargle_args

    t, x_np, freqs_np, w_np = _check_lombscargle_args(t, x, freqs,
                                                      weights)
    n_shards = mesh.shape[axis]
    # center in float64 before the f32 cast (same reasoning as the
    # single-chip path: tau makes the estimate shift-invariant); the
    # weighted mean ignores padding by construction
    t = t - (w_np @ t) / w_np.sum()
    pad = (-len(t)) % n_shards
    if pad:
        t = np.concatenate([t, np.zeros(pad)])
        x_np = np.concatenate([x_np, np.zeros(pad)])
        w_np = np.concatenate([w_np, np.zeros(pad)])
    tj = jnp.asarray(t, jnp.float32)
    xj = jnp.asarray(x_np, jnp.float32)
    fj = jnp.asarray(freqs_np, jnp.float32)
    wj = jnp.asarray(w_np, jnp.float32)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(), P(axis)),
                       out_specs=P())
    def _run(t_local, x_local, w, wt_local):
        wt = w[:, None] * t_local[None, :]
        sin2 = jax.lax.psum(
            jnp.sum(wt_local * jnp.sin(2 * wt), axis=-1), axis)
        cos2 = jax.lax.psum(
            jnp.sum(wt_local * jnp.cos(2 * wt), axis=-1), axis)
        tau = jnp.arctan2(sin2, cos2) / 2.0
        arg = wt - tau[:, None]
        c, s = jnp.cos(arg), jnp.sin(arg)
        xw = wt_local * x_local
        sums = jnp.stack([
            jnp.sum(xw[None, :] * c, axis=-1),
            jnp.sum(xw[None, :] * s, axis=-1),
            jnp.sum(wt_local * c * c, axis=-1),
            jnp.sum(wt_local * s * s, axis=-1)])
        xc, xs, cc, ss = jax.lax.psum(sums, axis)
        return 0.5 * (xc * xc / cc + xs * xs / ss)

    return _run(tj, xj, fj, wj)


def sharded_normalize2d(src, mesh: Mesh, axis: str = "sp"):
    """Row-sharded u8 plane → f32 [-1, 1] normalization — the
    distributed form of the reference's ``normalize2D``
    (``/root/reference/src/normalize.c:445-451``), closing the last
    reference L4 component without a sharded twin.

    Each shard reduces its row block, ONE ``pmin``/``pmax`` pair of
    scalars rides the collective, and the normalize stays local —
    collective payload is 2 floats regardless of the image size.
    Preserves the reference's max==min → all-zeros rule, and (like the
    single-chip op) accepts any numeric dtype, not just u8.  Rows are
    padded to a shard multiple internally with wrapped copies of real
    rows, which cannot perturb the global min/max — wrap also covers
    fewer rows than shards.
    """
    src = np.asarray(src) if not hasattr(src, "dtype") else src
    if src.ndim != 2:
        raise ValueError("sharded_normalize2d shards one [h, w] plane")
    h, w = src.shape
    n_shards = mesh.shape[axis]
    pad = (-h) % n_shards
    srcj = jnp.asarray(src)
    if pad:
        srcj = jnp.pad(srcj, ((0, pad), (0, 0)), mode="wrap")

    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis, None),
                       out_specs=P(axis, None))
    def _run(block):
        v = block.astype(jnp.float32)
        mn = jax.lax.pmin(jnp.min(v), axis)
        mx = jax.lax.pmax(jnp.max(v), axis)
        # guard the denominator BEFORE dividing: a flat plane would
        # otherwise manufacture inf/nan that the final where() hides
        # from the result but not from jax_debug_nans (matches the
        # single-chip ops/normalize.py guard)
        diff = jnp.where(mx == mn, 1.0, (mx - mn) / 2.0)
        out = (v - mn) / diff - 1.0
        return jnp.where(mx == mn, jnp.zeros_like(out), out)

    from veles.simd_tpu.ops import normalize as _nm

    jfn = _instrumented("sharded_normalize2d", _run)
    return _sharded_guard(
        "sharded_normalize2d",
        lambda: jfn(srcj)[:h],
        lambda: _nm.normalize2D(src),
        mesh, axis)


def data_parallel(fn, mesh: Mesh, axis: str = "dp"):
    """Wrap a batched op so its leading batch axis is sharded over
    ``mesh[axis]`` — jit + sharding constraint, XLA partitions the rest.

    >>> dwt = data_parallel(lambda x: wavelet_apply(DAUB, 8, PERIODIC, x),
    ...                     mesh)
    >>> hi, lo = dwt(batch_of_signals)   # batch split across chips

    The wrapper holds a persistent ``jax.jit``: config read at trace time
    (e.g. ``Config.conv_precision``) is baked into the cached executable —
    later ``set_config`` changes do not retrace existing wrappers.
    """
    jfn = obs.instrumented_jit(fn, op="data_parallel",
                               route="jit")

    def wrapper(batch, *args, **kwargs):
        batch = jnp.asarray(batch)
        spec = P(axis, *([None] * (batch.ndim - 1)))
        batch = jax.device_put(batch, NamedSharding(mesh, spec))
        with mesh:
            # guarded (bounded retry on transient mesh faults); no
            # single-chip fallback exists for a user-supplied fn, so
            # exhaustion re-raises typed
            return faults.guarded("parallel.data_parallel",
                                  lambda: jfn(batch, *args, **kwargs))

    return wrapper
