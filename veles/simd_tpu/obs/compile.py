"""Compile & compilation-cache tracking via ``jax.monitoring``.

jax already announces every backend compile and every persistent-cache
hit/miss through its monitoring hooks (``jax._src.compiler`` records
``/jax/compilation_cache/cache_hits``/``cache_misses``; ``pxla`` wraps
each backend compile in ``/jax/core/compile/backend_compile_duration``).
Nothing in the stock runtime *listens* — so
:func:`veles.simd_tpu.utils.profiler.enable_compilation_cache` could
never report how often the cache actually paid off.  This module bridges
those hooks into the telemetry registry: compiles become counters plus a
timing histogram, cache traffic becomes hit/miss counters.

Listeners are installed once per process (jax offers no public
unregister) and stay registered; each callback first checks
``obs.enabled()``, so ``obs.disable()`` silences them with the same
one-branch cost as every other telemetry helper.  jax is imported only
inside :func:`install` — the obs package itself stays importable without
an accelerator runtime.
"""

from __future__ import annotations

__all__ = ["install", "installed", "EVENT_COUNTERS", "DURATION_METRICS"]

# jax.monitoring event name -> telemetry counter name
EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile.cache_hits",
    "/jax/compilation_cache/cache_misses": "compile.cache_misses",
    "/jax/compilation_cache/tasks_using_cache":
        "compile.tasks_using_cache",
    "/jax/compilation_cache/task_disabled_cache":
        "compile.task_disabled_cache",
    "/jax/compilation_cache/compile_requests_use_cache":
        "compile.requests_use_cache",
}

# jax.monitoring duration event -> (counter name or None, histogram name)
DURATION_METRICS = {
    "/jax/core/compile/backend_compile_duration":
        ("compile.backend_compile", "compile.backend_compile_secs"),
    "/jax/core/compile/jaxpr_trace_duration":
        (None, "compile.jaxpr_trace_secs"),
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        (None, "compile.lowering_secs"),
    "/jax/compilation_cache/cache_retrieval_time_sec":
        (None, "compile.cache_retrieval_secs"),
    "/jax/compilation_cache/compile_time_saved_sec":
        (None, "compile.cache_time_saved_secs"),
}

_installed = False


def installed() -> bool:
    return _installed


def install() -> bool:
    """Register the monitoring listeners (idempotent).

    Returns True when this call did the registration, False when they
    were already installed.  Callbacks route through the gated
    :func:`veles.simd_tpu.obs.count`/``observe`` helpers, so they are
    inert whenever telemetry is disabled.
    """
    global _installed
    if _installed:
        return False
    import jax.monitoring

    from veles.simd_tpu import obs

    def _on_event(event, **kwargs):
        name = EVENT_COUNTERS.get(event)
        if name is not None:
            obs.count(name)

    def _on_duration(event, duration_secs, **kwargs):
        names = DURATION_METRICS.get(event)
        if names is None:
            return
        counter, hist = names
        if counter is not None:
            obs.count(counter)
        obs.observe(hist, duration_secs)

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _installed = True
    return True
