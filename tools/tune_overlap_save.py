#!/usr/bin/env python
"""Measure the overlap-save block-matmul step-size sweep on the device.

The reference's algorithm thresholds are hardcoded from offline
measurement (``/root/reference/src/convolve.c:328-364``); this is the
measurement tool for ours.  For each filter length it times the MXU
block-matmul overlap-save (``_conv_os_matmul``) across output-block
sizes and both precisions with chained on-device loops, checks accuracy
against a float64 oracle, and prints the winning step per (k, precision)
— the data behind ``ops/convolve.py``'s ``overlap_save_step`` and
``AUTO_*`` constants.  Rerun on new hardware generations.

Run:  python tools/tune_overlap_save.py [--quick] [--n 1048576]
      VELES_SIMD_PLATFORM=cpu ... works but only validates plumbing —
      step size is an MXU tiling decision, so tune on the real chip.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform  # noqa: E402

# steps whose rel. error exceeds this never become winners — matches the
# TPU smoke gate for convolve (tools/tpu_smoke.py)
ERR_GATE = 1e-4


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--n", type=int, default=1 << 20)
    args = parser.parse_args()
    maybe_override_platform()
    quick = args.quick
    n = args.n

    import jax
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.utils.benchmark import device_time_chained

    rng = np.random.RandomState(0)
    x_np = rng.randn(n).astype(np.float32)
    x = jnp.asarray(x_np)
    print(f"device: {jax.devices()[0]}  signal: {n}", flush=True)

    ks = (127, 2047) if quick else (127, 511, 2047, 8191)
    steps = (256, 512, 1024, 2048)
    precisions = ("highest", "high")
    winners = {}
    for k in ks:
        h_np = rng.randn(k).astype(np.float32)
        h = jnp.asarray(h_np)
        want = np.convolve(x_np.astype(np.float64), h_np.astype(np.float64))
        scale = np.max(np.abs(want))
        for prec in precisions:
            best = (float("inf"), None)
            for step in steps:
                got = np.asarray(
                    cv._conv_os_matmul(x, h, step, precision=prec),
                    np.float64)
                err = float(np.max(np.abs(got - want)) / scale)

                def stp(v, step=step, prec=prec, h=h):
                    y = cv._conv_os_matmul(v, h, step, precision=prec)
                    return v + 1e-30 * y[..., :n]

                t = device_time_chained(stp, x, iters=64, repeats=2)
                gated = " (fails accuracy gate)" if err > ERR_GATE else ""
                print(f"k={k:5d} prec={prec:8s} step={step:5d}: "
                      f"{t * 1e3:7.3f} ms  {n / t / 1e6:7.0f} Ms/s  "
                      f"rel_err={err:.1e}{gated}", flush=True)
                if err <= ERR_GATE and t < best[0]:
                    best = (t, step)
            winners[(k, prec)] = best[1]
            cur = cv.overlap_save_step(k)
            print(f"  -> k={k} {prec}: best step {best[1]} "
                  f"(overlap_save_step gives {cur})", flush=True)
    print("winners:", winners)


if __name__ == "__main__":
    main()
