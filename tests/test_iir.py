"""IIR family: design parity vs scipy, scan-vs-sequential cross-checks.

The reference has no IIR stack (its filtering is FIR-only,
``/root/reference/src/convolve.c``) — this family is a new capability.
scipy.signal is the external ground truth for the design math and the
filtering semantics; the in-module ``*_na`` oracles implement the
sequential textbook recurrence, cross-validating the associative-scan
device path (the reference's two-implementations discipline,
``/root/reference/tests/matrix.cc:94-98``).
"""

import numpy as np
import pytest

from scipy import signal as ss

from veles.simd_tpu.ops import iir

RNG = np.random.RandomState(71)

DESIGNS = [
    (1, 0.15, "lowpass"), (2, 0.2, "lowpass"), (4, 0.3, "lowpass"),
    (8, 0.4, "lowpass"), (2, 0.35, "highpass"), (5, 0.6, "highpass"),
    (2, (0.2, 0.5), "bandpass"), (5, (0.15, 0.55), "bandpass"),
    (3, (0.25, 0.6), "bandstop"), (4, (0.3, 0.7), "bandstop"),
]


class TestButterworthDesign:
    @pytest.mark.parametrize("order,wn,btype", DESIGNS)
    def test_matches_scipy_transfer_function(self, order, wn, btype):
        """Same H(e^jw) as scipy.butter up to section pairing."""
        mine = iir.butterworth(order, wn, btype)
        sp = ss.butter(order, wn, btype, output="sos")
        _, h1 = iir.sos_frequency_response(mine, 256)
        _, h2 = ss.sosfreqz(sp, worN=256, whole=False)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    def test_lowpass_dc_gain_unity(self):
        for order in (1, 3, 6):
            sos = iir.butterworth(order, 0.3, "lowpass")
            _, h = iir.sos_frequency_response(sos, 16)
            assert abs(abs(h[0]) - 1.0) < 1e-12

    def test_sections_shape_and_normalization(self):
        sos = iir.butterworth(5, (0.2, 0.5), "bandpass")
        assert sos.shape == (5, 6)  # bandpass doubles the order
        np.testing.assert_allclose(sos[:, 3], 1.0)

    def test_contracts(self):
        with pytest.raises(ValueError, match="order"):
            iir.butterworth(0, 0.3)
        with pytest.raises(ValueError, match="cutoff"):
            iir.butterworth(2, 1.5)
        with pytest.raises(ValueError, match="band edges"):
            iir.butterworth(2, (0.5, 0.2), "bandpass")
        with pytest.raises(ValueError, match="btype"):
            iir.butterworth(2, 0.3, "notch")

    def test_frequency_response_ba(self):
        b, a = ss.butter(4, 0.25)
        _, h1 = iir.frequency_response(b, a, 128)
        _, h2 = ss.freqz(b, a, worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-12)


class TestSosfilt:
    @pytest.mark.parametrize("order,wn,btype", DESIGNS)
    def test_scan_matches_scipy(self, order, wn, btype):
        sos = iir.butterworth(order, wn, btype)
        x = RNG.randn(3, 300).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = ss.sosfilt(sos, x.astype(np.float64), axis=-1)
        scale = max(1.0, np.max(np.abs(want)))
        np.testing.assert_allclose(got, want, atol=2e-5 * scale)

    def test_oracle_matches_scipy_exactly(self):
        sos = iir.butterworth(4, 0.3, "lowpass")
        x = RNG.randn(200)
        np.testing.assert_allclose(iir.sosfilt_na(sos, x),
                                   ss.sosfilt(sos, x), atol=1e-12)

    def test_scan_vs_oracle(self):
        sos = iir.butterworth(3, 0.25, "highpass")
        x = RNG.randn(5, 257).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = iir.sosfilt_na(sos, x)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_zi_settled_step_response(self):
        """With zi from sosfilt_zi, a constant input yields a constant
        output from sample 0 — the filter starts settled."""
        sos = iir.butterworth(4, 0.2, "lowpass")
        zi = iir.sosfilt_zi(sos)
        x = np.full((64,), 2.5, np.float32)
        y = np.asarray(iir.sosfilt(sos, x, zi=zi * 2.5, simd=True))
        np.testing.assert_allclose(y, y[0], atol=1e-4)

    def test_zi_matches_scipy_semantics(self):
        """Same (sos, zi) pair fed to both implementations agrees."""
        sos = iir.butterworth(3, 0.3, "lowpass")
        zi = RNG.randn(len(sos), 2)
        x = RNG.randn(100)
        want, _ = ss.sosfilt(sos, x, zi=zi)
        got = np.asarray(iir.sosfilt(sos, x.astype(np.float32),
                                     zi=zi.astype(np.float32), simd=True))
        np.testing.assert_allclose(got, want, atol=2e-5)
        np.testing.assert_allclose(iir.sosfilt_na(sos, x, zi=zi), want,
                                   atol=1e-12)

    def test_unbatched_zi_with_batched_signal(self):
        """The documented [n_sections, 2] zi shape broadcasts over a
        batched x on both paths."""
        sos = iir.butterworth(2, 0.3, "lowpass")
        zi = iir.sosfilt_zi(sos)
        x = RNG.randn(3, 50).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, zi=zi, simd=True))
        want = iir.sosfilt_na(sos, x, zi=zi)
        assert got.shape == (3, 50)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_impulse_response_stable_and_decaying(self):
        sos = iir.butterworth(6, 0.1, "lowpass")
        x = np.zeros(2048, np.float32)
        x[0] = 1.0
        h = np.asarray(iir.sosfilt(sos, x, simd=True))
        assert np.all(np.isfinite(h))
        assert np.max(np.abs(h[-100:])) < 1e-6

    def test_contracts(self):
        with pytest.raises(ValueError, match="sos"):
            iir.sosfilt(np.zeros((2, 5)), np.zeros(8, np.float32))
        bad = iir.butterworth(2, 0.3).copy()
        bad[0, 3] = 2.0
        with pytest.raises(ValueError, match="normalized"):
            iir.sosfilt(bad, np.zeros(8, np.float32))


class TestSosfiltfilt:
    @pytest.mark.parametrize("order,wn,btype", DESIGNS[:6])
    def test_matches_scipy(self, order, wn, btype):
        sos = iir.butterworth(order, wn, btype)
        x = RNG.randn(2, 400).astype(np.float32)
        got = np.asarray(iir.sosfiltfilt(sos, x, simd=True))
        want = ss.sosfiltfilt(sos, x.astype(np.float64), axis=-1)
        scale = max(1.0, np.max(np.abs(want)))
        np.testing.assert_allclose(got, want, atol=2e-5 * scale)

    def test_oracle_matches_scipy_exactly(self):
        sos = iir.butterworth(3, (0.2, 0.5), "bandpass")
        x = RNG.randn(300)
        np.testing.assert_allclose(iir.sosfiltfilt_na(sos, x),
                                   ss.sosfiltfilt(sos, x), atol=1e-10)

    def test_zero_phase(self):
        """A band-interior sinusoid passes with no phase shift (the
        point of forward-backward filtering)."""
        sos = iir.butterworth(4, 0.5, "lowpass")
        n = 1024
        t = np.arange(n)
        x = np.sin(0.2 * np.pi * t).astype(np.float32)
        y = np.asarray(iir.sosfiltfilt(sos, x, simd=True))
        # compare against the input in the interior: same phase, gain ~1
        np.testing.assert_allclose(y[100:-100], x[100:-100], atol=5e-3)

    def test_explicit_padlen(self):
        sos = iir.butterworth(2, 0.3, "lowpass")
        x = RNG.randn(100).astype(np.float32)
        got = np.asarray(iir.sosfiltfilt(sos, x, padlen=40, simd=True))
        want = ss.sosfiltfilt(sos, x.astype(np.float64), padlen=40)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_contracts(self):
        sos = iir.butterworth(2, 0.3, "lowpass")
        with pytest.raises(ValueError, match="padlen"):
            iir.sosfiltfilt(sos, np.zeros(10, np.float32), padlen=10)


class TestLfilter:
    def test_matches_scipy(self):
        b, a = ss.butter(4, 0.25)
        x = RNG.randn(3, 256).astype(np.float32)
        got = np.asarray(iir.lfilter(b, a, x, simd=True))
        want = ss.lfilter(b, a, x.astype(np.float64), axis=-1)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_oracle_matches_scipy_exactly(self):
        b, a = ss.butter(3, 0.4, "highpass")
        x = RNG.randn(128)
        np.testing.assert_allclose(iir.lfilter_na(b, a, x),
                                   ss.lfilter(b, a, x), atol=1e-12)

    def test_pure_fir(self):
        """a == [1]: degenerates to convolution (no recurrence)."""
        b = ss.firwin(33, 0.4)
        x = RNG.randn(200).astype(np.float32)
        got = np.asarray(iir.lfilter(b, [1.0], x, simd=True))
        want = ss.lfilter(b, [1.0], x.astype(np.float64))
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_unnormalized_a0(self):
        b, a = np.array([2.0, 1.0]), np.array([2.0, -0.8])
        x = RNG.randn(64).astype(np.float32)
        got = np.asarray(iir.lfilter(b, a, x, simd=True))
        want = ss.lfilter(b, a, x.astype(np.float64))
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_contracts(self):
        with pytest.raises(ValueError, match="nonzero"):
            iir.lfilter([1.0], [0.0, 1.0], np.zeros(8, np.float32))
        with pytest.raises(ValueError, match="order"):
            iir.lfilter([1.0], np.ones(40), np.zeros(8, np.float32))


class TestLongSignalEquivalence:
    @pytest.mark.slow
    def test_long_signal_scan_accuracy(self):
        """The O(log n) scan stays accurate over 2^17 samples (error
        does not accumulate the way naive recomputation would)."""
        sos = iir.butterworth(4, 0.3, "lowpass")
        x = RNG.randn(1 << 17).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = ss.sosfilt(sos, x.astype(np.float64))
        scale = np.max(np.abs(want))
        np.testing.assert_allclose(got, want, atol=5e-5 * scale)


class TestChebyshev:
    C1 = [(2, 1.0, 0.3, "lowpass"), (4, 0.5, 0.25, "lowpass"),
          (5, 3.0, 0.4, "highpass"), (3, 1.0, (0.2, 0.5), "bandpass"),
          (4, 2.0, (0.3, 0.6), "bandstop"), (1, 1.0, 0.3, "lowpass")]
    C2 = [(2, 30.0, 0.3, "lowpass"), (4, 40.0, 0.25, "lowpass"),
          (5, 35.0, 0.4, "highpass"), (3, 30.0, (0.2, 0.5), "bandpass"),
          (4, 45.0, (0.3, 0.6), "bandstop"), (1, 20.0, 0.3, "lowpass")]

    @pytest.mark.parametrize("order,rp,wn,bt", C1)
    def test_cheby1_matches_scipy(self, order, rp, wn, bt):
        _, h1 = iir.sos_frequency_response(iir.cheby1(order, rp, wn, bt),
                                           128)
        _, h2 = ss.sosfreqz(ss.cheby1(order, rp, wn, bt, output="sos"),
                            worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    @pytest.mark.parametrize("order,rs,wn,bt", C2)
    def test_cheby2_matches_scipy(self, order, rs, wn, bt):
        _, h1 = iir.sos_frequency_response(iir.cheby2(order, rs, wn, bt),
                                           128)
        _, h2 = ss.sosfreqz(ss.cheby2(order, rs, wn, bt, output="sos"),
                            worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    def test_ripple_properties(self):
        """cheby1 passband ripple stays within rp dB; cheby2 stopband
        stays rs dB down."""
        sos = iir.cheby1(5, 1.0, 0.5)
        w, h = iir.sos_frequency_response(sos, 4096)
        pb = np.abs(h[w < 0.49])
        assert pb.max() < 1.0 + 1e-6
        assert pb.min() > 10 ** (-1.0 / 20) - 1e-6
        sos2 = iir.cheby2(5, 40.0, 0.5)
        _, h2 = iir.sos_frequency_response(sos2, 4096)
        sb = np.abs(h2[w > 0.51])
        assert sb.max() < 10 ** (-40.0 / 20) + 1e-4

    def test_runs_through_sosfilt(self):
        x = RNG.randn(2, 300).astype(np.float32)
        for sos in (iir.cheby1(4, 1.0, 0.3),
                    iir.cheby2(4, 35.0, 0.3)):
            got = np.asarray(iir.sosfilt(sos, x, simd=True))
            want = ss.sosfilt(sos, x.astype(np.float64), axis=-1)
            np.testing.assert_allclose(got, want, atol=2e-5)

    def test_contracts(self):
        with pytest.raises(ValueError, match="rp"):
            iir.cheby1(3, 0.0, 0.3)
        with pytest.raises(ValueError, match="rs"):
            iir.cheby2(3, -5.0, 0.3)


class TestStreaming:
    @pytest.mark.slow
    def test_concatenated_chunks_equal_one_shot(self):
        sos = iir.butterworth(4, 0.2, "lowpass")
        x = RNG.randn(1024).astype(np.float32)
        st = iir.StreamingSosfilt(sos)
        ys = [np.asarray(st.process(c)) for c in x.reshape(8, 128)]
        got = np.concatenate(ys)
        want = np.asarray(iir.sosfilt(sos, x, simd=True))
        np.testing.assert_allclose(got, want, atol=2e-5)

    @pytest.mark.slow
    def test_ragged_chunks_and_reset(self):
        sos = iir.cheby1(3, 1.0, 0.35)
        x = RNG.randn(500).astype(np.float32)
        st = iir.StreamingSosfilt(sos)
        cuts = [0, 100, 150, 400, 500]
        got = np.concatenate([
            np.asarray(st.process(x[a:b]))
            for a, b in zip(cuts[:-1], cuts[1:])])
        want = ss.sosfilt(sos, x.astype(np.float64))
        np.testing.assert_allclose(got, want, atol=2e-5)
        st.reset()
        again = np.asarray(st.process(x[:100]))
        np.testing.assert_allclose(again, want[:100], atol=2e-5)

    def test_zf_matches_scipy(self):
        """return_zf's exit state equals scipy's sosfilt zf."""
        sos = iir.butterworth(3, 0.3, "lowpass")
        x = RNG.randn(64)
        zi = RNG.randn(len(sos), 2)
        want_y, want_zf = ss.sosfilt(sos, x, zi=zi)
        got_y, got_zf = iir.sosfilt(sos, x.astype(np.float32),
                                    zi=zi.astype(np.float32),
                                    simd=True, return_zf=True)
        np.testing.assert_allclose(np.asarray(got_y), want_y, atol=2e-5)
        np.testing.assert_allclose(np.asarray(got_zf), want_zf,
                                   atol=2e-5)
        ony, onzf = iir.sosfilt_na(sos, x, zi=zi, return_zf=True)
        np.testing.assert_allclose(onzf, want_zf, atol=1e-12)

    def test_short_block_contract(self):
        sos = iir.butterworth(2, 0.3)
        with pytest.raises(ValueError, match="2 samples"):
            iir.sosfilt(sos, np.zeros(1, np.float32), return_zf=True)


class TestElliptic:
    CASES = [(2, 1.0, 20.0, 0.3, "lowpass"),
             (4, 1.0, 40.0, 0.25, "lowpass"),
             (5, 0.5, 60.0, 0.4, "highpass"),
             (3, 1.0, 45.0, (0.2, 0.5), "bandpass"),
             (4, 2.0, 50.0, (0.3, 0.6), "bandstop"),
             (1, 1.0, 40.0, 0.3, "lowpass"),
             (8, 0.1, 100.0, 0.45, "lowpass"),
             (7, 3.0, 80.0, 0.2, "lowpass")]

    @pytest.mark.parametrize("order,rp,rs,wn,bt", CASES)
    def test_matches_scipy(self, order, rp, rs, wn, bt):
        _, h1 = iir.sos_frequency_response(
            iir.ellip(order, rp, rs, wn, bt), 128)
        _, h2 = ss.sosfreqz(ss.ellip(order, rp, rs, wn, bt,
                                     output="sos"), worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    def test_equiripple_both_bands(self):
        """The defining property: passband within rp dB, stopband at
        least rs dB down, transition steeper than cheby1 at the same
        order."""
        sos = iir.ellip(5, 1.0, 50.0, 0.4)
        w, h = iir.sos_frequency_response(sos, 8192)
        pb = 20 * np.log10(np.abs(h[w < 0.399]) + 1e-300)
        assert pb.max() < 1e-6 and pb.min() > -1.0 - 1e-3
        # stopband starts where attenuation first reaches rs (measured
        # 0.507 for this design); beyond it the equiripple response
        # never comes back up
        sb = 20 * np.log10(np.abs(h[w > 0.51]) + 1e-300)
        assert sb.max() < -50.0 + 1e-3
        ch = iir.cheby1(5, 1.0, 0.4)
        _, hc = iir.sos_frequency_response(ch, 8192)
        sbc = 20 * np.log10(np.abs(hc[w > 0.51]) + 1e-300)
        assert sb.max() < sbc.max()  # steeper than cheby1

    def test_oracle_filter_agrees(self):
        """An ellip bandpass run through sosfilt: scan vs oracle."""
        sos = iir.ellip(4, 1.0, 40.0, (0.2, 0.6), "bandpass")
        x = np.random.RandomState(3).randn(4096).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = iir.sosfilt_na(sos, x)
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_contracts(self):
        with pytest.raises(ValueError, match="rp"):
            iir.ellip(4, 0.0, 40.0, 0.3)
        with pytest.raises(ValueError, match="rs"):
            iir.ellip(4, 1.0, 0.5, 0.3)
        with pytest.raises(ValueError, match="order"):
            iir.ellip(0, 1.0, 40.0, 0.3)


class TestNotchPeak:
    @pytest.mark.parametrize("w0,Q", [(0.3, 30.0), (0.1, 5.0),
                                      (0.7, 50.0), (0.5, 10.0)])
    def test_notch_matches_scipy(self, w0, Q):
        _, h1 = iir.sos_frequency_response(iir.iirnotch(w0, Q), 256)
        b, a = ss.iirnotch(w0, Q, fs=2.0)
        _, h2 = ss.freqz(b, a, worN=256)
        np.testing.assert_allclose(h1, h2, atol=1e-12)

    @pytest.mark.parametrize("w0,Q", [(0.3, 30.0), (0.1, 5.0),
                                      (0.7, 50.0)])
    def test_peak_matches_scipy(self, w0, Q):
        _, h1 = iir.sos_frequency_response(iir.iirpeak(w0, Q), 256)
        b, a = ss.iirpeak(w0, Q, fs=2.0)
        _, h2 = ss.freqz(b, a, worN=256)
        np.testing.assert_allclose(h1, h2, atol=1e-12)

    def test_notch_kills_hum(self):
        """The use case: a 50 Hz hum (w0 = 50/500 at fs=1000) vanishes
        while the rest of the signal survives."""
        fs = 1000.0
        t = np.arange(4096) / fs
        clean = np.sin(2 * np.pi * 123.0 * t).astype(np.float32)
        hum = 0.5 * np.sin(2 * np.pi * 50.0 * t)
        sos = iir.iirnotch(50.0 / (fs / 2), 30.0)
        out = np.asarray(iir.sosfilt(sos, (clean + hum).astype(np.float32),
                                     simd=True))
        # steady state: hum suppressed > 20x, signal intact
        tail = slice(1024, None)
        resid = out[tail] - clean[tail]
        assert np.sqrt(np.mean(resid ** 2)) < 0.05
        assert np.corrcoef(out[tail], clean[tail])[0, 1] > 0.99

    def test_contracts(self):
        with pytest.raises(ValueError, match="w0"):
            iir.iirnotch(1.2, 30.0)
        with pytest.raises(ValueError, match="Q"):
            iir.iirpeak(0.3, 0.0)


class TestBessel:
    CASES = [(2, 0.3, "lowpass"), (4, 0.25, "lowpass"),
             (5, 0.4, "highpass"), (3, (0.2, 0.5), "bandpass"),
             (4, (0.3, 0.6), "bandstop"), (1, 0.3, "lowpass"),
             (8, 0.2, "lowpass")]

    @pytest.mark.parametrize("order,wn,bt", CASES)
    def test_matches_scipy(self, order, wn, bt):
        _, h1 = iir.sos_frequency_response(iir.bessel(order, wn, bt),
                                           128)
        _, h2 = ss.sosfreqz(ss.bessel(order, wn, bt, norm="phase",
                                      output="sos"), worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    def test_group_delay_flatness(self):
        """The defining property: in-band group delay is flat — far
        flatter than a Butterworth of the same order.  Low cutoff: the
        bilinear transform's phase warp erodes the analog property as
        the cutoff approaches Nyquist (0.15 gives ~14x here; at 0.4 the
        advantage shrinks to ~2x)."""
        w, hb = iir.sos_frequency_response(iir.bessel(5, 0.15), 2048)
        _, hw = iir.sos_frequency_response(iir.butterworth(5, 0.15),
                                           2048)

        def gd(h):
            ph = np.unwrap(np.angle(h))
            return -np.diff(ph) / (np.pi * np.diff(w))

        band = (w[:-1] > 0.01) & (w[:-1] < 0.1)
        spread_b = np.ptp(gd(hb)[band])
        spread_w = np.ptp(gd(hw)[band])
        assert spread_b < 0.15 * spread_w

    def test_pulse_shape_preserved(self):
        """A Gaussian pulse through a Bessel lowpass keeps its shape
        (no ringing) — the reason this design exists."""
        t = (np.arange(2048) - 1024) / 8000.0
        x = np.exp(-(t * 400) ** 2).astype(np.float32)
        y = np.asarray(iir.sosfilt(iir.bessel(4, 0.5), x, simd=True))
        # no overshoot below the baseline beyond 1% of the peak
        assert y.min() > -0.01 * y.max()

    def test_runs_through_sosfilt(self):
        x = RNG.randn(2, 300).astype(np.float32)
        sos = iir.bessel(4, 0.3)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = ss.sosfilt(sos, x.astype(np.float64), axis=-1)
        np.testing.assert_allclose(got, want, atol=2e-5)


class TestOrderEstimation:
    """buttord/cheb1ord/cheb2ord/ellipord vs scipy.  Orders match
    exactly on these cases; bandstop wn to 5e-5 (scipy's own fminbound
    runs at xatol=1e-5, so tighter agreement is not even defined — and
    on rare ceil-boundary bandstop specs the sharper edge optimization
    here can legitimately return an order one LOWER than scipy's, see
    the _nat_freq docstring)."""

    CASES = [
        (0.2, 0.3, 1.0, 40.0), (0.3, 0.2, 1.0, 40.0),
        (0.1, 0.25, 0.5, 60.0), (0.45, 0.4, 3.0, 30.0),
        ((0.2, 0.5), (0.1, 0.6), 1.0, 40.0),
        ((0.2, 0.5), (0.14, 0.6), 2.0, 60.0),
        ((0.1, 0.6), (0.2, 0.5), 1.0, 40.0),
        ((0.07, 0.66), (0.2, 0.5), 0.5, 55.0),
    ]

    @pytest.mark.parametrize("wp,ws,gp,gs", CASES)
    @pytest.mark.parametrize("name", ["buttord", "cheb1ord", "cheb2ord",
                                      "ellipord"])
    def test_matches_scipy(self, name, wp, ws, gp, gs):
        o1, w1 = getattr(iir, name)(wp, ws, gp, gs)
        o2, w2 = getattr(ss, name)(wp, ws, gp, gs)
        assert o1 == o2
        np.testing.assert_allclose(np.atleast_1d(w1),
                                   np.atleast_1d(w2), atol=5e-5)

    def test_design_at_estimated_order_meets_spec(self):
        """The whole point: design at (ord, wn) and check the spec."""
        wp, ws, gp, gs = 0.25, 0.35, 1.0, 45.0
        for est, design, extra in (
                (iir.buttord, iir.butterworth, ()),
                (iir.cheb1ord, iir.cheby1, (gp,)),
                (iir.ellipord, iir.ellip, (gp, gs))):
            order, wn = est(wp, ws, gp, gs)
            sos = design(order, *extra, wn)
            w, h = iir.sos_frequency_response(sos, 4096)
            db = 20 * np.log10(np.abs(h) + 1e-300)
            assert db[w <= wp].min() >= -gp - 1e-2
            assert db[w >= ws].max() <= -gs + 1e-2

    def test_contracts(self):
        with pytest.raises(ValueError, match="gpass"):
            iir.buttord(0.2, 0.3, 40.0, 1.0)
        with pytest.raises(ValueError, match="pairs"):
            iir.buttord(0.2, (0.1, 0.3), 1.0, 40.0)
        with pytest.raises(ValueError, match="Nyquist"):
            iir.cheb1ord(1.2, 0.3, 1.0, 40.0)
        # non-nesting band pairs must raise, not return garbage orders
        with pytest.raises(ValueError, match="bandstop"):
            iir.buttord((0.1, 0.4), (0.2, 0.5), 1.0, 40.0)
        with pytest.raises(ValueError, match="bandpass"):
            iir.ellipord((0.2, 0.7), (0.1, 0.6), 1.0, 40.0)


class TestConversions:
    """ba <-> zpk <-> sos plumbing + group_delay vs scipy."""

    def test_tf_zpk_round_trip(self):
        b, a = ss.butter(5, 0.3)
        z, p, k = iir.tf2zpk(b, a)
        zs, ps, ks = ss.tf2zpk(b, a)
        np.testing.assert_allclose(np.sort_complex(z),
                                   np.sort_complex(zs), atol=1e-10)
        np.testing.assert_allclose(np.sort_complex(p),
                                   np.sort_complex(ps), atol=1e-10)
        assert abs(k - ks) < 1e-12
        b2, a2 = iir.zpk2tf(z, p, k)
        np.testing.assert_allclose(b2, b, atol=1e-10)
        np.testing.assert_allclose(a2, a, atol=1e-10)

    @pytest.mark.parametrize("order,wn", [(3, 0.2), (6, 0.45), (1, 0.3)])
    def test_tf2sos_same_response(self, order, wn):
        b, a = ss.butter(order, wn)
        sos = iir.tf2sos(b, a)
        _, h1 = iir.sos_frequency_response(sos, 128)
        _, h2 = ss.freqz(b, a, worN=128)
        np.testing.assert_allclose(h1, h2, atol=1e-9)

    def test_sos2tf_matches_scipy(self):
        sos = iir.cheby1(4, 1.0, 0.3)
        b1, a1 = iir.sos2tf(sos)
        b2, a2 = ss.sos2tf(sos)
        np.testing.assert_allclose(b1, b2, atol=1e-12)
        np.testing.assert_allclose(a1, a2, atol=1e-12)

    def test_zpk2sos_runs_through_sosfilt(self):
        z, p, k = ss.ellip(4, 1.0, 40.0, 0.3, output="zpk")
        sos = iir.zpk2sos(z, p, k)
        x = RNG.randn(300).astype(np.float32)
        got = np.asarray(iir.sosfilt(sos, x, simd=True))
        want = ss.sosfilt(ss.zpk2sos(z, p, k), x.astype(np.float64))
        np.testing.assert_allclose(got, want, atol=5e-5)

    def test_pure_delay_numerator(self):
        z, p, k = iir.tf2zpk([0.0, 0.0, 1.0], [1.0, -0.5])
        zs, ps, ks = ss.tf2zpk([0.0, 0.0, 1.0], [1.0, -0.5])
        assert len(z) == len(zs) and abs(k - ks) < 1e-12
        np.testing.assert_allclose(p, ps, atol=1e-12)

    def test_fir_and_unequal_degrees(self):
        """FIR (no poles) and fewer-zeros-than-poles inputs must match
        scipy exactly — no spurious delay (round-4 review finding)."""
        sos = iir.tf2sos([1.0, 2.0, 1.0], [1.0])
        np.testing.assert_allclose(sos, ss.tf2sos([1, 2, 1], [1]),
                                   atol=1e-12)
        sos2 = iir.zpk2sos([], [0.5], 1.0)
        np.testing.assert_allclose(sos2, ss.zpk2sos([], [0.5], 1.0),
                                   atol=1e-12)
        # impulse responses line up sample-for-sample
        b, a = ss.butter(3, 0.4)
        imp = np.zeros(32, np.float32)
        imp[0] = 1.0
        got = np.asarray(iir.sosfilt(iir.tf2sos(b[:2], a), imp,
                                     simd=False))
        want = ss.lfilter(b[:2], a, imp.astype(np.float64))
        np.testing.assert_allclose(got, want, atol=1e-10)

    def test_group_delay_singular_warns(self):
        """A numerator zero ON the unit circle at a grid frequency is
        flagged, not silently garbage."""
        # zero exactly at w = 0.5 (z = exp(j pi/2)): b = [1, 0, 1]
        with pytest.warns(RuntimeWarning, match="singular"):
            _, gd = iir.group_delay(([1.0, 0.0, 1.0], [1.0]), 4)
        assert np.all(np.isfinite(gd))

    def test_group_delay_matches_scipy(self):
        b, a = ss.cheby1(5, 1.0, 0.35)
        w, gd = iir.group_delay((b, a), 256)
        ws, gds = ss.group_delay((b, a), w=w * np.pi)
        np.testing.assert_allclose(gd, gds, atol=1e-8)

    def test_group_delay_linear_phase_fir(self):
        """A symmetric FIR's group delay is exactly (n-1)/2 samples."""
        from veles.simd_tpu.ops import filters as fl

        taps = fl.firwin(31, 0.4)
        _, gd = iir.group_delay((taps, [1.0]), 64)
        np.testing.assert_allclose(gd, 15.0, atol=1e-8)


class TestFiltfiltBa:
    def test_matches_scipy(self):
        b, a = ss.butter(4, 0.3)
        x = RNG.randn(2, 500).astype(np.float32)
        got = np.asarray(iir.filtfilt(b, a, x, simd=True))
        want = ss.filtfilt(b, a, x.astype(np.float64), axis=-1)
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_explicit_padlen_and_fir(self):
        from veles.simd_tpu.ops import filters as fl

        taps = fl.firwin(21, 0.4)
        x = RNG.randn(300).astype(np.float32)
        got = np.asarray(iir.filtfilt(taps, [1.0], x, padlen=50,
                                      simd=True))
        want = ss.filtfilt(taps, [1.0], x.astype(np.float64), padlen=50)
        np.testing.assert_allclose(got, want, atol=2e-4)


class TestLfilterZi:
    def test_matches_scipy(self):
        for b, a in (ss.butter(4, 0.2), ss.cheby1(3, 1, 0.3),
                     ss.ellip(5, 0.5, 40, 0.25)):
            np.testing.assert_allclose(iir.lfilter_zi(b, a),
                                       ss.lfilter_zi(b, a), atol=1e-12)

    def test_settled_step_response(self):
        """lfilter seeded by zi*x[0] has no start-up transient — the
        property the function exists for (host check via the oracle)."""
        b, a = ss.butter(3, 0.1)
        zi = iir.lfilter_zi(b, a)
        y, _ = ss.lfilter(b, a, np.ones(100), zi=zi * 1.0)
        np.testing.assert_allclose(y, np.ones(100), atol=1e-9)

    def test_fir_only(self):
        np.testing.assert_allclose(
            iir.lfilter_zi([1.0, 0.5, 0.25], [1.0]),
            ss.lfilter_zi([1.0, 0.5, 0.25], [1.0]), atol=1e-12)

    def test_integrator_pole_raises(self):
        with pytest.raises(ValueError, match="pole at z=1"):
            iir.lfilter_zi([1.0], [1.0, -1.0])
