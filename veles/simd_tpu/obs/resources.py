"""The *resource axis* of obs: what the compiled programs cost.

obs v1 counted *what was decided*, obs v2 measured *where the host time
goes*; this module answers the third question TPU sizing actually runs
on — per-route FLOPs, bytes moved, and peak-memory footprint — the
numbers "Large Scale Distributed Linear Algebra With Tensor Processing
Units" (arXiv:2112.09017) and the XLA-compilation pipeline papers treat
as first-class when deciding whether a workload is compute- or
bandwidth-bound.  Two pieces:

* :func:`instrumented_jit` — the ONE compile helper compute modules use
  instead of raw ``jax.jit`` (``tools/lint.py`` forbids the raw form in
  ``ops/``/``parallel/``).  It behaves exactly like ``jax.jit`` — same
  tracing, same executable, byte-identical jaxprs (the obs contract) —
  but, while telemetry is enabled, the first eager call per argument
  geometry also lowers the function ahead-of-time and harvests
  ``compiled.cost_analysis()`` (flops, bytes accessed) and
  ``compiled.memory_analysis()`` (argument/output/temp/generated-code
  bytes) into the resource registry keyed by ``(op, route)``.  Calls
  made under an outer trace skip the harvest (a tracer has no concrete
  buffers to lower against); telemetry off costs one flag check.
  Being the single compile site also makes it the single LOAD site:
  when the AOT artifact store is armed
  (:mod:`veles.simd_tpu.runtime.artifacts`,
  ``VELES_SIMD_ARTIFACTS=on|readonly``), the first call per geometry
  consults the store before tracing — a hit dispatches the packed
  executable (``artifact_hit``/``artifact`` decision event), a miss
  in ``on`` mode exports the fresh compile back into the pack.

* a **cache-introspection registry** — every memoized compile cache in
  the library (the batched handle LRU, the pallas2d OOM-rejection
  LRU, this module's own analysis memo, ...) registers a snapshot
  provider under a name, and :func:`caches_snapshot` returns one
  unified ``{name: {size, capacity, hits, misses, evictions}}`` view,
  exported through ``obs.snapshot()`` / Prometheus / ``report()``.

Derived metrics per ``(op, route)``: arithmetic intensity (flops per
byte accessed) and an *attainable* roofline % — the time-free fraction
of the MXU bound the roofline model says this program could reach at
that intensity (``min(1, AI · HBM_BW / bound)``).  (Distinct from the
*achieved* analytical % ``bench.py`` derives from XLA flops over
measured time and prints next to the hand-constant measured % — >15%
disagreement there warns, the drift detector for
``utils/benchmark.py``'s constants.)

Like the rest of the obs storage layer this module imports neither jax
nor numpy at module scope; jax is reached only inside the instrumented
wrapper (whose callers imported it long before) and the roofline
constants are read lazily from :mod:`veles.simd_tpu.utils.benchmark`.

NB: the facade function ``obs.resources()`` shadows this module as a
package attribute, and BOTH from-imports and dotted attribute access
after a plain import resolve to the function (Python binds the package
attribute first).  The only reliable handle on the module itself is
``sys.modules["veles.simd_tpu.obs.resources"]``.
"""

from __future__ import annotations

import itertools
import threading

from veles.simd_tpu.obs.lru import LRUSet

__all__ = [
    "InstrumentedJit", "instrumented_jit", "ResourceRegistry",
    "RESOURCES", "record_resources", "resources_snapshot",
    "register_cache", "caches_snapshot", "set_active", "active",
    "jsonify", "ANALYSIS_MEMO_MAXSIZE",
]

# toggled by obs.enable()/disable() (and the VELES_SIMD_TELEMETRY env
# default) so the disabled wrapper path is one module-global check —
# the same discipline as every other obs helper
_ACTIVE = False

# geometries already analyzed, keyed (op, route, abstract signature).
# Bounded: a shape-churning service must not grow an unbounded memo —
# an evicted geometry simply pays one more AOT lowering if it returns.
ANALYSIS_MEMO_MAXSIZE = 1024


def set_active(on: bool) -> None:
    """Arm/disarm resource capture (wired to obs.enable/disable)."""
    global _ACTIVE
    _ACTIVE = bool(on)


def active() -> bool:
    return _ACTIVE


def jsonify(value):
    """Deep-convert ``value`` to JSON-native structures (tuples become
    lists, dict keys become strings, exotic leaves become ``repr``) so
    snapshots survive a JSON round trip *equal*, not merely similar."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): jsonify(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        seq = sorted(value, key=repr) if isinstance(
            value, (set, frozenset)) else value
        return [jsonify(v) for v in seq]
    return repr(value)


# the analysis memo: the shared bounded LRU membership set (also a
# registered cache, so it shows up in :func:`caches_snapshot` like
# every other compile cache)
_ANALYZED = LRUSet(ANALYSIS_MEMO_MAXSIZE)

# per-wrapper artifact-decision memo bound: one verdict (a loaded
# runner, or "use the fresh compile") per argument geometry
_ARTIFACT_MEMO_MAXSIZE = 256

# the artifact subsystem (runtime/artifacts.py), imported lazily ONCE:
# the obs package must stay importable without the runtime package
# resolved, and the artifact path is one `.artifacts_mode()` attribute
# call per dispatch once bound
_ARTIFACTS_MOD = None


def _artifacts():
    global _ARTIFACTS_MOD
    if _ARTIFACTS_MOD is None:
        from veles.simd_tpu.runtime import artifacts as _a

        _ARTIFACTS_MOD = _a
    return _ARTIFACTS_MOD

# monotonic wrapper ids keying the memo (see InstrumentedJit._token)
_INSTANCE_SEQ = itertools.count()


class ResourceRegistry:
    """Latest compiled-program analytics per ``(op, route)``.

    One locked dict like :class:`~veles.simd_tpu.obs.registry.\
MetricsRegistry`; an entry is replaced wholesale on each harvest (the
    *latest* geometry's numbers are the ones a dashboard wants next to
    the latest timings) while ``compiles`` accumulates.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple, dict] = {}

    def record(self, op: str, route: str, entry: dict) -> None:
        key = (str(op), str(route))
        with self._lock:
            prev = self._entries.get(key)
            entry["analyses"] = (prev["analyses"] + 1) if prev else 1
            self._entries[key] = entry

    def snapshot(self) -> list:
        """JSON-native list of entries sorted by (op, route)."""
        with self._lock:
            return [dict(e, op=op, route=route)
                    for (op, route), e in sorted(self._entries.items())]

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def __repr__(self):
        # stable (no memory address, no live entry count): this
        # singleton's repr lands in generated docs, which are
        # committed and freshness-gated in a shared test process
        return "ResourceRegistry()"


RESOURCES = ResourceRegistry()


def _roofline_attainable_pct(flops, bytes_accessed):
    """Analytical roofline: the % of the f32 MXU bound attainable at
    this program's arithmetic intensity, per the classic model
    ``min(peak, AI * BW)``.  Constants come from
    :mod:`veles.simd_tpu.utils.benchmark` (env-overridable per
    hardware generation); returns None when they are unavailable or
    the byte count is degenerate."""
    if not flops or not bytes_accessed:
        return None
    try:
        from veles.simd_tpu.utils.benchmark import (
            hbm_bw_gbps, mxu_f32_bound_tflops)
        from veles.simd_tpu.utils.config import get_config

        bound = mxu_f32_bound_tflops(get_config().conv_precision)
        bw = hbm_bw_gbps() * 1e9
    except Exception:  # noqa: BLE001 — analytics must never raise
        return None
    ai = flops / bytes_accessed
    attainable_tflops = min(bound, ai * bw / 1e12)
    return 100.0 * attainable_tflops / bound


def record_resources(op: str, route: str, shapes: str, cost,
                     mem) -> None:
    """Fold one AOT harvest into :data:`RESOURCES`.

    ``cost`` is the (first module's) ``cost_analysis()`` dict or None;
    ``mem`` the ``memory_analysis()`` stats object or None.  Missing
    pieces record as None — a CPU backend without memory stats still
    contributes its FLOP count.
    """
    flops = bytes_accessed = None
    if cost:
        flops = cost.get("flops")
        bytes_accessed = cost.get("bytes accessed")
    entry = {
        "shapes": shapes,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arith_intensity": (flops / bytes_accessed
                            if flops and bytes_accessed else None),
        "attainable_pct_of_roofline": _roofline_attainable_pct(
            flops, bytes_accessed),
        "transcendentals": cost.get("transcendentals") if cost else None,
    }
    peak = None
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(mem, attr, None)
        entry[name] = v
        if v is not None and name != "alias_bytes":
            peak = (peak or 0) + int(v)
    if peak is not None and entry["alias_bytes"]:
        # aliased (donated) buffers are counted in BOTH argument and
        # output sizes; subtract one copy so peak reflects real
        # residency (argument + output + temp + codegen - alias)
        peak -= int(entry["alias_bytes"])
    entry["peak_bytes"] = peak
    RESOURCES.record(op, route, entry)


def resources_snapshot() -> list:
    return RESOURCES.snapshot()


def reset() -> None:
    """Clear harvested analytics and the analysis memo (obs.reset)."""
    RESOURCES.reset()
    _ANALYZED.reset()


# ---------------------------------------------------------------------------
# cache introspection
# ---------------------------------------------------------------------------

_cache_lock = threading.Lock()
_cache_providers: dict[str, object] = {}


def register_cache(name: str, provider) -> None:
    """Register (or replace) a compile-cache snapshot provider.

    ``provider`` is a zero-arg callable returning a JSON-native-ish
    dict — conventionally ``{size, capacity, hits, misses,
    evictions}``; extra keys ride along.  Used by every memoized
    compile cache in the library so ``obs.caches()`` is the one place
    to see them all.
    """
    with _cache_lock:
        _cache_providers[str(name)] = provider


def caches_snapshot() -> dict:
    """``{name: stats}`` for every registered cache, JSON-native.  A
    provider that raises reports its error instead of killing the
    snapshot (introspection must never take the service down)."""
    with _cache_lock:
        providers = dict(_cache_providers)
    out = {}
    for name, provider in sorted(providers.items()):
        try:
            stats = dict(provider())
        except Exception as e:  # noqa: BLE001
            stats = {"error": repr(e)}
        if "capacity" not in stats and "maxsize" in stats:
            stats["capacity"] = stats["maxsize"]
        out[name] = jsonify(stats)
    return out


register_cache("obs_analysis_memo", _ANALYZED.info)


# ---------------------------------------------------------------------------
# the instrumented compile helper
# ---------------------------------------------------------------------------

_tracer_cls = None            # jax.core.Tracer, resolved lazily once


def _abstract_key(args, kwargs, statics_by_value: bool):
    """Hashable geometry signature of a call, or None when the call is
    not analyzable eagerly (tracer args, unhashable statics).

    ``statics_by_value`` mirrors how ``jax.jit`` will key this call's
    non-array leaves: a wrapper WITH ``static_argnames``/``argnums``
    compiles per static VALUE, so values key the memo too; a wrapper
    WITHOUT statics treats Python scalars as dynamic weak-typed
    operands — one executable per TYPE — and keying the memo by value
    there would re-run the AOT harvest (a full compile) for every
    distinct gain/offset a caller sweeps through."""
    global _tracer_cls
    if _tracer_cls is None:
        import jax

        _tracer_cls = jax.core.Tracer
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, dict(kwargs)))
    descr = []
    for leaf in leaves:
        if isinstance(leaf, _tracer_cls):
            return None
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            descr.append(("a", tuple(shape), str(dtype)))
        elif statics_by_value:
            try:
                hash(leaf)
            except TypeError:
                return None
            descr.append(("s", leaf))
        else:
            descr.append(("t", type(leaf).__name__))
    return (str(treedef), tuple(descr))


class InstrumentedJit:
    """``jax.jit`` plus first-call-per-geometry resource harvesting.

    Transparent to execution: every call delegates to the wrapped
    jitted function, so jaxprs and executables are byte-identical with
    telemetry on or off (``tests/test_obs.py`` pins this).  The AOT
    lowering used for analytics shares the function and abstract
    signature with the real call — it observes, it does not reroute.
    """

    __slots__ = ("_jfn", "fn", "op", "route", "_statics_by_value",
                 "_token", "_artifact_ident", "_artifact_memo",
                 "__dict__")

    def __init__(self, fn, op=None, route=None, artifact_key=None,
                 **jit_kwargs):
        import functools

        import jax

        self.fn = fn
        self.op = str(op) if op is not None else getattr(
            fn, "__name__", "anonymous").lstrip("_")
        self.route = str(route) if route is not None else "default"
        self._statics_by_value = bool(
            jit_kwargs.get("static_argnames")
            or jit_kwargs.get("static_argnums"))
        # the artifact-store identity of this wrapper's program, or
        # None when the site cannot be keyed safely across processes.
        # ``artifact_key`` is the caller's own cache key (the batched
        # handle-LRU key, a pipeline's (name, block_len)) — REQUIRED
        # for closures, whose baked-in parameters are invisible to any
        # fingerprint we could take.  Module-level functions without
        # free variables self-identify by qualname + a bytecode
        # digest (so an edited function body invalidates its packed
        # executables).  Donating or static-arg wrappers are excluded:
        # donation does not survive the export round trip, and a
        # static-baking wrapper's loaded runner would take a different
        # call convention.  Excluded sites stay covered by the
        # persistent-compile-cache leg.
        self._artifact_ident = _artifact_ident(
            fn, artifact_key, self._statics_by_value,
            bool(jit_kwargs.get("donate_argnums")
                 or jit_kwargs.get("donate_argnames")))
        self._artifact_memo = {}
        # per-instance memo token: two wrappers sharing (op, route) —
        # e.g. batched builder closures baking different up/down into
        # the SAME-shaped call, or data_parallel around two user fns —
        # compile different programs and must not collide in the memo
        # (module-level decorated functions are singletons, so their
        # token is stable for the process)
        self._token = next(_INSTANCE_SEQ)
        self._jfn = jax.jit(fn, **jit_kwargs)
        try:
            functools.update_wrapper(self, fn)
        except Exception:  # noqa: BLE001 — builtins without __name__
            pass

    def __call__(self, *args, **kwargs):
        art = (self._artifact_ident is not None
               and _artifacts().artifacts_mode() != "off")
        if not _ACTIVE and not art:
            return self._jfn(*args, **kwargs)
        key = _abstract_key(args, kwargs, self._statics_by_value)
        runner = None
        if art and key is not None:
            runner = self._artifact_runner(key, args, kwargs)
        if _ACTIVE and key is not None and not _ANALYZED.check_and_add(
                (self._token, key)):
            if runner is not None:
                # the packed executable IS the compiled program this
                # geometry dispatches: harvest its analytics directly
                # — re-tracing self._jfn for the AOT harvest would pay
                # exactly the compile the artifact load just skipped
                self._harvest_compiled(runner, key)
            else:
                self._analyze(args, kwargs, key)
        if runner is not None:
            try:
                return runner(*args, **kwargs)
            except Exception:  # noqa: BLE001 — a packed program that
                # cannot execute here (layout/device drift the stamps
                # missed) must degrade to the fresh compile, never
                # fault dispatch
                from veles.simd_tpu import obs as _obs

                _obs.count("artifact_exec_error", op=self.op,
                           route=self.route)
                self._artifact_memo[key] = None
        return self._jfn(*args, **kwargs)

    def _artifact_runner(self, key, args, kwargs):
        """The load-before-compile path: first encounter of a
        geometry consults the artifact store (hit -> dispatch runs the
        packed executable; miss in ``on`` mode -> export this
        wrapper's fresh compile back into the store), every outcome a
        counter (``artifact_hit/miss/stale/load_error``) and an
        ``artifact`` decision event.  The verdict is memoized per
        geometry — steady state is one dict probe."""
        memo = self._artifact_memo
        if key in memo:
            return memo[key]
        from veles.simd_tpu import obs as _obs

        art = _artifacts()
        runner = None
        if all(d[0] == "a" for d in key[1]):
            skey = "|".join((self.op, self.route, self._artifact_ident,
                             key[0], repr(key[1])))
            runner, outcome = art.lookup_runner(skey)
            _obs.count(f"artifact_{outcome}", op=self.op,
                       route=self.route)
            stored = None
            if runner is None and art.artifacts_mode() == "on":
                stored = art.export_and_store(
                    self._jfn, skey, args, kwargs, op=self.op,
                    route=self.route)
            _obs.record_decision(
                "artifact", outcome, site=self.op, route=self.route,
                shapes=_shapes_str(key),
                **({"stored": stored} if stored is not None else {}))
        if len(memo) >= _ARTIFACT_MEMO_MAXSIZE:
            memo.pop(next(iter(memo)))
        memo[key] = runner
        return runner

    def lower(self, *args, **kwargs):
        """AOT lowering passthrough (``jax.jit(fn).lower``)."""
        return self._jfn.lower(*args, **kwargs)

    def _analyze(self, args, kwargs, key) -> None:
        """Harvest cost/memory analytics for this geometry.  Best
        effort by contract: a backend that cannot lower eagerly or
        report stats must never break dispatch."""
        try:
            compiled = self._jfn.lower(*args, **kwargs).compile()
        except Exception:  # noqa: BLE001
            record_resources(self.op, self.route, _shapes_str(key),
                             None, None)
            return
        self._harvest_compiled(compiled, key)

    def _harvest_compiled(self, compiled, key) -> None:
        """Fold one already-compiled executable's analytics into the
        registry (shared by the fresh-AOT path and the artifact-loaded
        path — a packed runner reports the same ``cost_analysis()`` /
        ``memory_analysis()`` surface)."""
        cost = mem = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            cost = ca
        except Exception:  # noqa: BLE001
            pass
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001
            pass
        record_resources(self.op, self.route, _shapes_str(key), cost,
                         mem)

    def __repr__(self):
        return (f"InstrumentedJit({self.op!r}, route={self.route!r}, "
                f"fn={getattr(self.fn, '__name__', self.fn)!r})")


def _artifact_ident(fn, artifact_key, statics: bool,
                    donates: bool) -> str | None:
    """The cross-process identity of a wrapper's program for the
    artifact store, or None when the site cannot be keyed safely.

    An explicit ``artifact_key`` (the caller's own compile-cache key)
    always wins — it is the only safe identity for closures, whose
    baked-in parameters (filter taps, up/down factors) produce
    different programs from identical-looking calls.  Without one, a
    module-level function with no free variables identifies as
    ``module.qualname@<bytecode digest>`` — the digest ties packed
    executables to the function BODY, so editing it invalidates them.
    Static-baking and donating wrappers return None EVEN WITH an
    explicit key (a loaded runner takes a different call convention
    than a static-baking wrapper, and donation does not survive the
    export round trip — silently dropping an opted-in memory
    optimization would be worse than a cold compile); closures
    without an explicit key return None too."""
    if statics or donates:
        return None
    if artifact_key is not None:
        return f"k:{artifact_key}"
    code = getattr(fn, "__code__", None)
    if code is None or getattr(fn, "__closure__", None):
        return None
    import hashlib

    code_t = type(code)

    def stable(c):
        # a nested code object's repr carries a memory address —
        # recurse into its bytecode instead, so the digest is
        # deterministic across processes
        if isinstance(c, code_t):
            return ("code", c.co_code,
                    tuple(stable(x) for x in c.co_consts))
        return repr(c)

    digest = hashlib.sha256(
        repr((code.co_code, stable(code)[2],
              code.co_names)).encode()).hexdigest()[:16]
    return (f"f:{getattr(fn, '__module__', '?')}."
            f"{getattr(fn, '__qualname__', '?')}@{digest}")


def _shapes_str(key) -> str:
    """Compact human form of an abstract signature for snapshots."""
    parts = []
    for d in key[1]:
        if d[0] == "a":
            parts.append("%s[%s]" % (d[2],
                                     ",".join(str(s) for s in d[1])))
        else:
            parts.append(repr(d[1]))
    return " ".join(parts)


def instrumented_jit(fn=None, *, op=None, route=None,
                     artifact_key=None, **jit_kwargs):
    """The library's compile site: ``jax.jit`` with resource capture.

    Usable exactly like ``jax.jit`` — bare decorator, decorator
    factory via ``functools.partial``, or direct call — plus ``op=`` /
    ``route=`` labels that key the resource registry (defaults: the
    function's name, route "default").  All other keyword arguments
    (``static_argnames``, ``donate_argnums``, ...) pass through to
    ``jax.jit`` untouched.

    ``artifact_key`` opts a CLOSURE-built site into the AOT artifact
    store (:mod:`veles.simd_tpu.runtime.artifacts`): pass the site's
    own compile-cache key (the batched handle-LRU key, a pipeline's
    ``(name, block_len)``) so packed executables are keyed exactly
    like the in-memory handles.  Module-level functions participate
    automatically; see :class:`InstrumentedJit`.
    """
    if fn is None:
        def deco(f):
            return InstrumentedJit(f, op=op, route=route,
                                   artifact_key=artifact_key,
                                   **jit_kwargs)
        return deco
    return InstrumentedJit(fn, op=op, route=route,
                           artifact_key=artifact_key, **jit_kwargs)
