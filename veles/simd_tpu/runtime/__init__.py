"""veles.simd_tpu.runtime — cross-op runtime policies.

The ops layer owns *what* to compute (route tables, selectors,
oracles); this package owns the runtime policies every op family
shares.  Residents: :mod:`~veles.simd_tpu.runtime.faults`, the fault-policy
engine — one demote-and-remember implementation for Mosaic compile
rejections, bounded retry-with-backoff for transient device faults
(deadline-budget-clipped when the caller threads a request budget in),
and the deterministic fault-injection harness that exercises both on
CPU CI — :mod:`~veles.simd_tpu.runtime.breaker`, the per-``(site,
shape-class)`` circuit breakers that send persistently-failing
buckets straight to their fallback instead of burning the retry
ladder per call — and :mod:`~veles.simd_tpu.runtime.routing`, the
unified routing engine: declarative candidate-route tables, the
shared selector, and the measured autotuner with its persistent tune
cache.
"""

from veles.simd_tpu.runtime import breaker
from veles.simd_tpu.runtime import faults
from veles.simd_tpu.runtime import routing

__all__ = ["breaker", "faults", "routing"]
