"""Tracing & plan-cache persistence (the framework's *tracing* layer).

Scope: this module answers **where the device time goes** inside a
step — XLA profiler timelines and persistent compile caching.  It is
NOT the observability layer as a whole: **what was decided** (algorithm
selections, XLA-vs-oracle dispatch tallies, compile/cache hit counts)
and **how long the host-side dispatch took** (``obs.span`` latency
histograms, warmup vs. steady-state, Chrome-trace export via
``obs.save_trace``) live in :mod:`veles.simd_tpu.obs`, the runtime
telemetry package.  The split: spans time the *Python dispatch layer*
with ~µs granularity and zero device involvement; this module's
:func:`trace` captures the *device* timeline with XLA's profiler.  The
two meet in the middle — while a :func:`trace` capture is live, every
``obs.span`` also opens a ``jax.profiler.TraceAnnotation``, so the
host-side span names appear inside the XLA timeline.  Use all three
together: telemetry tells you *which* path served your traffic, spans
tell you *what it cost at the dispatch layer*, a trace tells you *why
the device work cost what it did*.

The reference's entire profiling story is ``std::chrono`` around
synchronous calls (``/root/reference/tests/benchmark.inc:74-107``) and
its only persistent state is in-memory FFT plans
(``inc/simd/convolve_structs.h:39-74``).  The TPU equivalents:

* :func:`trace` / :func:`annotate` — the XLA profiler (SURVEY.md §5
  "can hook the XLA profiler"): captures a TensorBoard-loadable trace of
  device compute, HBM traffic, and per-op timelines.
* :func:`enable_compilation_cache` — persistent compiled-executable
  cache, the durable analog of the reference's FFT plan reuse: a fresh
  process re-loads compiled XLA/Mosaic binaries from disk instead of
  recompiling (first compiles cost 10-40 s through a remote-relay
  backend, so this is the difference between instant and minute-scale
  warmup for repeat workloads).  Now a DELEGATING SHIM: the
  configuration's one home is :func:`veles.simd_tpu.runtime.artifacts.\
enable_persistent_compile_cache`, the fallback leg of the AOT
  artifact store (``jax.export``-serialized executables shipped as
  warm packs — the zero-warmup cold-start subsystem).  With telemetry
  enabled (``obs.enable()``), cache hit/miss counts and retrieval
  times show up in the ``compile.*`` metrics via the
  ``jax.monitoring`` bridge (:mod:`veles.simd_tpu.obs.compile`).

Wall-clock timing belongs to :mod:`veles.simd_tpu.utils.benchmark`
(``device_time_chained``); this module is for *where the time goes*, not
how much there is nor what was decided.
"""

from __future__ import annotations

import contextlib

__all__ = ["trace", "annotate", "enable_compilation_cache"]


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace into ``log_dir``.

    Usage::

        with profiler.trace("/tmp/veles-trace"):
            convolve(handle, x, h)

    View with TensorBoard (``tensorboard --logdir /tmp/veles-trace``) or
    Perfetto.  Nested :func:`annotate` scopes appear as named spans, and
    while the capture is live every enabled ``obs.span`` bridges to a
    ``jax.profiler.TraceAnnotation`` too (the host dispatch names land
    in the device timeline).
    """
    import jax

    from veles.simd_tpu.obs import spans as _obs_spans

    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    _obs_spans.set_xla_trace_active(True)
    try:
        yield log_dir
    finally:
        _obs_spans.set_xla_trace_active(False)
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Name a region so it shows up as a span inside a :func:`trace`
    capture (``jax.profiler.TraceAnnotation``)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Persist compiled executables across processes.

    DEPRECATED SHIM: persistent-compile configuration now has ONE home
    in the AOT artifact subsystem —
    :func:`veles.simd_tpu.runtime.artifacts.\
enable_persistent_compile_cache` — which this delegates to unchanged
    (``cache_dir`` still defaults to ``$VELES_SIMD_CACHE_DIR`` or
    ``~/.cache/veles_simd_tpu``; returns the directory in use; safe to
    call more than once).  The artifact store arms the same machinery
    at ``<store>/xla_cache`` when ``VELES_SIMD_ARTIFACTS`` is on, so
    one warm pack ships serialized executables AND backend-compile
    cache entries; call the artifacts entry point directly in new
    code.  With telemetry enabled (``obs.enable()``), cache hit/miss
    counts and retrieval times show up in the ``compile.*`` metrics
    via the ``jax.monitoring`` bridge
    (:mod:`veles.simd_tpu.obs.compile`).
    """
    from veles.simd_tpu.runtime import artifacts

    return artifacts.enable_persistent_compile_cache(cache_dir)
