"""veles — namespace root for the TPU-native rebuild of veles.simd."""
