# Top-level driver — parity with the reference's autotools targets
# (/root/reference/Makefile.am:30-43): `make tests` runs every suite with
# a timeout + peak-RSS + log + failure gate, `make benchmarks` prints the
# relative-speedup lines, `make cshim` builds the native C ABI.

PYTHON ?= python

.PHONY: all tests tests-quick benchmarks bench bench-regress \
        bench-multichip bench-serve bench-goodput bench-rpc \
        serve-smoke \
        chaos-smoke chaos-replicas chaos-replicas-rpc chaos-scale \
        cshim cshim-check \
        wavelet-tables \
        lint docs obs-report obs-dash obs-query autotune-pack \
        warm-pack \
        cold-start install install-hooks clean

all: cshim

tests:
	$(PYTHON) tools/run_tests.py

# inner-loop signal in ~3 min: everything except the @pytest.mark.slow
# suites (sharded-mesh sweeps, multi-process gates, examples, the C
# suite).  The full gate (`make tests`) stays the CI/judging bar.
tests-quick:
	VELES_SIMD_PLATFORM=cpu $(PYTHON) -m pytest tests/ -q -m "not slow"

benchmarks:
	$(PYTHON) tools/benchmark_suite.py

bench:
	$(PYTHON) bench.py --all

# fold the latest bench run into BENCH_HISTORY.jsonl and fail (rc=1) on
# a headline/suite regression vs the trailing-median baseline — the CI
# gate after `make bench`.  Knobs: tools/bench_regress.py --help
bench-regress:
	$(PYTHON) tools/bench_regress.py

# the MULTICHIP bench family: pod-scale Fourier rows (sharded_rfft
# matmul-DFT vs local FFT, sharded_stft above the matmul cutoff) on a
# device mesh, written to MULTICHIP_DETAILS.json with per-route
# roofline % and per-stage all_to_all ICI bytes.  Gate with
# `python tools/bench_regress.py --details MULTICHIP_DETAILS.json`.
bench-multichip:
	$(PYTHON) tools/bench_multichip.py

# the SERVE bench family: loadgen traffic (Poisson + bursts, mixed
# tenants/shapes) through the serving layer, written to
# SERVE_DETAILS.json (throughput + inverse-p99 rows; rc=1 on any
# lost/double-answered request).  Gate with
# `python tools/bench_regress.py --details SERVE_DETAILS.json`.
bench-serve:
	$(PYTHON) tools/loadgen.py --details SERVE_DETAILS.json

# the GOODPUT bench family: the saturation A/B campaign — one
# heavy-tailed mixed-shape schedule served flat-out with continuous
# batching + ragged packing OFF (the padding-waste baseline) then ON,
# written to GOODPUT_DETAILS.json (sample goodput, waste-recovery
# multiple, inverse-p99; rc=1 unless the measured padding waste
# recovers >= 2x with p99 held).  Gate with
# `python tools/bench_regress.py --details GOODPUT_DETAILS.json`.
bench-goodput:
	VELES_SIMD_PLATFORM=cpu $(PYTHON) tools/loadgen.py --saturation \
		--details GOODPUT_DETAILS.json
	$(PYTHON) tools/bench_regress.py --details GOODPUT_DETAILS.json

# the RPC bench family: identical loadgen traffic through an
# in-process 2-replica group vs a spawn="subprocess" group over the
# RPC data plane (serve/rpc.py), written to RPC_DETAILS.json
# (subprocess/thread throughput ratio + inverse added-p50 rows; rc=1
# if the wire adds more than the p50 budget or any request fails).
# Gate with `python tools/bench_regress.py --details RPC_DETAILS.json`.
bench-rpc:
	VELES_SIMD_PLATFORM=cpu $(PYTHON) tools/loadgen.py --rpc-overhead \
		--details RPC_DETAILS.json
	$(PYTHON) tools/bench_regress.py --details RPC_DETAILS.json

# seconds-long CPU sanity run of the serving layer (accounting +
# oracle parity gate, including pipeline-invocation streams with
# state threading); the chaos variant arms VELES_SIMD_FAULT_PLAN
serve-smoke:
	VELES_SIMD_PLATFORM=cpu $(PYTHON) tools/loadgen.py --smoke

# the scripted chaos campaign on CPU: overload -> poisoned served
# PIPELINE class (its breaker opens while plain ops stay ok) ->
# mid-campaign device loss (one poisoned serve class + the sharded
# mesh) -> recovery, gating on zero lost / zero double-answered
# requests, typed errors only, bounded deadline misses, breaker
# open->half-open->closed (serve, pipeline, and mesh breakers), and
# mesh_degrade + recovery (tools/chaos.py; CHAOS_DETAILS.json rows
# gate via `python tools/bench_regress.py --details CHAOS_DETAILS.json`)
chaos-smoke:
	VELES_SIMD_PLATFORM=cpu VELES_SIMD_FAULT_BACKOFF=0 \
		$(PYTHON) tools/chaos.py --smoke

# the REPLICATED chaos campaign on CPU: 3 in-process server replicas
# behind the breaker-aware front router — one replica killed abruptly
# (no drain) mid-traffic with its queued work failing over onto
# survivors (original deadlines carried), then another drained
# gracefully — gating zero lost / zero double-answered requests across
# the GROUP, terminal traces on the killed replica's requests,
# survivor absorption, and a live router-level /healthz throughout
# (tools/chaos.py --replicas; REPLICA_DETAILS.json rows gate via
# `python tools/bench_regress.py --details REPLICA_DETAILS.json`)
chaos-replicas:
	VELES_SIMD_PLATFORM=cpu VELES_SIMD_FAULT_BACKOFF=0 \
		$(PYTHON) tools/chaos.py --replicas --smoke

# the same replicated campaign over the RPC DATA PLANE: three child
# processes behind the front router (serve/rpc.py pooled connections),
# the abrupt kill a real SIGKILL mid-traffic — zero lost / zero
# double-answered, failover deadlines carried, and the lifecycle
# reconstructable from the journal pack must all hold across the wire
# (tools/chaos.py --replicas --spawn subprocess; spawn-suffixed rows in
# REPLICA_RPC_DETAILS.json gate via `python tools/bench_regress.py
# --details REPLICA_RPC_DETAILS.json`)
chaos-replicas-rpc:
	VELES_SIMD_PLATFORM=cpu VELES_SIMD_FAULT_BACKOFF=0 \
		$(PYTHON) tools/chaos.py --replicas --spawn subprocess --smoke

# the CONTROL-AXIS chaos campaign on CPU (obs v7): a ~10x diurnal
# traffic ramp over a scaler-armed ReplicaGroup — the SLO-driven
# autoscaler must spawn under the peak's queue backlog, retire back to
# min through the sustained-idle window, keep p99 + SLO hit rate in
# budget, hold replica-seconds within a factor of the oracle schedule,
# produce ZERO actions under a synthetic flap-storm, and leave a
# journal pack from which the whole incident -> action -> effect chain
# reconstructs offline (tools/chaos.py --scale; SCALE_DETAILS.json
# rows gate via `python tools/bench_regress.py --details
# SCALE_DETAILS.json`)
chaos-scale:
	VELES_SIMD_PLATFORM=cpu VELES_SIMD_FAULT_BACKOFF=0 \
		$(PYTHON) tools/chaos.py --scale --smoke

cshim:
	$(MAKE) -C csrc all

cshim-check:
	$(MAKE) -C csrc check

wavelet-tables:
	$(PYTHON) tools/gen_wavelet_tables.py

lint:
	$(PYTHON) tools/lint.py

docs:
	$(PYTHON) tools/gen_docs.py

# pretty-print a saved telemetry snapshot (obs.save(...) output or a
# bench.py BENCH_DETAILS.json); override with SNAPSHOT=path
SNAPSHOT ?= BENCH_DETAILS.json
obs-report:
	$(PYTHON) tools/obs_report.py $(SNAPSHOT)

# live dashboard against a serving process's scrape endpoint
# (obs/http.py, armed via VELES_SIMD_OBS_PORT or Server(obs_port=...));
# override with OBS_PORT=9100 or pass --url via tools/obs_dash.py
obs-dash:
	$(PYTHON) tools/obs_dash.py $(if $(OBS_PORT),--port $(OBS_PORT),)

# offline postmortem queries over a durable journal pack (obs v6,
# written by any process running with $VELES_SIMD_JOURNAL_DIR set):
# merged fleet timeline, per-rid/replica/site/time filters, incident
# postmortems, Chrome-trace export.  Override with
# JOURNAL=path/to/pack and QUERY='--postmortem all' etc.
JOURNAL ?= journal_pack
obs-query:
	$(PYTHON) tools/obs_query.py $(JOURNAL) $(QUERY)

# build the pre-warmed autotune pack: measure every routed family's
# candidates on THIS device and persist the winners so production
# processes (VELES_SIMD_AUTOTUNE=readonly + _AUTOTUNE_CACHE=pack)
# never pay route exploration.  Override with PACK=path.
PACK ?= autotune_pack.json
autotune-pack:
	$(PYTHON) tools/autotune_pack.py --out $(PACK)

# build the pre-warmed AOT ARTIFACT pack: export every serving shape
# class's compiled executable (jax.export, stamped + sha256'd) plus
# the persistent-XLA-cache leg into one directory a fresh process
# preloads at serve.Server.start — zero-warmup cold start
# (VELES_SIMD_ARTIFACTS=readonly + VELES_SIMD_ARTIFACT_DIR=pack).
# Override with WARM_PACK=path.
WARM_PACK ?= warm_pack
warm-pack:
	$(PYTHON) tools/warm_pack.py --dir $(WARM_PACK)

# the cold-start bench family: process-birth -> first-request wall
# clock of a fresh subprocess server, warm pack vs cold, written to
# COLD_START_DETAILS.json with artifact hit/stale/miss evidence.
# Gate with `python tools/bench_regress.py --details
# COLD_START_DETAILS.json`.
cold-start:
	$(PYTHON) tools/cold_start.py

# Installs the commit gate: `make tests-quick` must be green before any
# code commit (round-4 postmortem: snapshot 8182983 landed red at HEAD).
install-hooks:
	install -m 755 tools/git-hooks/pre-commit "$$(git rev-parse --git-path hooks)/pre-commit"
	@echo "pre-commit quick-gate hook installed"

# pip-installs the Python/XLA core, then the C ABI (PREFIX=/usr/local).
# --no-build-isolation: build with the environment's setuptools so the
# install works air-gapped (pip's isolated build env needs network).
install:
	$(PYTHON) -m pip install --no-build-isolation .
	$(MAKE) -C csrc install

clean:
	$(MAKE) -C csrc clean
	rm -f tests.log test_results_*.xml COLD_START_DETAILS.json
	rm -rf warm_pack
