"""veles.simd_tpu.serve — the resilient request path in front of the ops.

The "millions of users" front half (ROADMAP item 1): every op in this
library is a one-shot call, which at short-signal sizes is
dispatch-bound by design — the throughput form of heterogeneous
traffic is *coalesced* dispatch.  This package is the serving loop
that does the coalescing and, more importantly, keeps answering when
the traffic or the hardware misbehaves:

* :class:`~veles.simd_tpu.serve.server.Server` — submit
  :class:`~veles.simd_tpu.serve.server.Request`\\ s
  (op + signal + params + tenant), get
  :class:`~veles.simd_tpu.serve.server.Ticket`\\ s; requests are
  bucketed by shape class, zero-padded to power-of-two buckets, and
  dispatched as batches through the
  :mod:`veles.simd_tpu.ops.batched` compiled-handle LRU;
* :mod:`~veles.simd_tpu.serve.batcher` — the dynamic-batching policy:
  a bucket dispatches when full (``max_batch``) or when its oldest
  request hits the latency deadline (``max_wait``), whichever fires
  first;
* :mod:`~veles.simd_tpu.serve.admission` — bounded global/per-tenant
  queue depth; over-limit submits are answered *immediately* with a
  typed :class:`~veles.simd_tpu.serve.admission.Overloaded` (never
  queued to time out), or block-with-deadline when the caller opts
  into backpressure;
* :mod:`~veles.simd_tpu.serve.health` — the HEALTHY/DEGRADED state
  machine over :func:`veles.simd_tpu.runtime.faults.guarded`
  dispatch: transient device faults retry, persistent ones degrade
  the server to the NumPy oracle (parity-correct answers, flight
  recorder armed) while zero-retry probes hunt for recovery;
* :mod:`~veles.simd_tpu.serve.cluster` — the replica layer above one
  server: a :class:`~veles.simd_tpu.serve.cluster.ReplicaGroup` of N
  named replicas (independent per-replica breakers/health,
  heartbeat-driven wedge detection, graceful drain vs abrupt kill,
  one aggregation ``/healthz``) behind a breaker-aware
  :class:`~veles.simd_tpu.serve.cluster.FrontRouter` — least-loaded
  placement per shape class, failover re-submission with the
  original deadline carried, group-wide zero-double-answer dedup
  (``make chaos-replicas`` is the scripted proof);
* :mod:`~veles.simd_tpu.serve.rpc` — the RPC data plane (PR 20):
  ``spawn="subprocess"`` replicas grow a ``POST /submit`` route on
  their obs endpoint serving the full request surface, and the
  router submits through a pooled persistent-connection
  :class:`~veles.simd_tpu.serve.rpc.RpcClient` — binary npy framing
  (never base64-JSON), deadlines re-stamped as remaining budget on
  the wire, the typed-error surface crossing losslessly, transport
  failures answering as ``closed`` tickets the failover hook
  re-routes (``make chaos-replicas-rpc`` is the scripted proof, ``make
  bench-rpc`` the gated overhead bench);
* :mod:`~veles.simd_tpu.serve.scaler` — the control axis (obs v7): an
  SLO-driven autoscaler on the group (``ReplicaGroup(scaler=True)``
  or ``VELES_SIMD_SCALER=1``) that reads only the typed
  ``obs.signals()`` contract, acts only through the group verbs
  (spawn/retire/restart) under hysteresis + cooldown + min/max
  bounds, and emits every decision — action or typed no-op — as a
  journaled ``scaler`` event (``make chaos-scale`` is the scripted
  proof);
* **end-to-end deadlines + per-class breakers** —
  ``submit(deadline_ms=...)`` (default
  ``VELES_SIMD_SERVE_DEADLINE_MS``) bounds a request's total time in
  the system: expired requests shed with a typed
  :class:`~veles.simd_tpu.serve.server.DeadlineExceeded` before
  dispatch, and the remaining budget clips the guarded retry loop.
  Each shape class dispatches through its own circuit breaker
  (:mod:`veles.simd_tpu.runtime.breaker`): a persistently-failing
  class goes straight to the oracle without burning retries while
  sibling classes dispatch normally.

Knobs (constructor args override the environment):
``VELES_SIMD_SERVE_MAX_BATCH``, ``VELES_SIMD_SERVE_MAX_WAIT_MS``,
``VELES_SIMD_SERVE_QUEUE_DEPTH``, ``VELES_SIMD_SERVE_TENANT_DEPTH``,
``VELES_SIMD_SERVE_DEADLINE_MS``, plus the breaker window/threshold
knobs (``VELES_SIMD_BREAKER_*``).  Chaos: ``VELES_SIMD_FAULT_PLAN``
sites ``serve.dispatch`` (device_lost/timeout -> retry/degrade;
``serve.dispatch@<op>`` poisons one op's classes) and
``serve.admission`` (overload -> deterministic shed), with
``label=entries;...`` phase schedules for scripted campaigns.
``tools/loadgen.py`` drives all of it (Poisson + burst arrivals,
mixed tenants) as the traffic source; ``tools/chaos.py`` (``make
chaos-smoke``) is the scripted chaos-campaign gate.
"""

from veles.simd_tpu.serve.admission import (DEFAULT_QUEUE_DEPTH,
                                            DEFAULT_TENANT_DEPTH,
                                            QUEUE_DEPTH_ENV,
                                            TENANT_DEPTH_ENV,
                                            AdmissionController,
                                            Overloaded)
from veles.simd_tpu.serve.batcher import (DEFAULT_MAX_BATCH,
                                          DEFAULT_MAX_WAIT_MS,
                                          MAX_BATCH_ENV, MAX_WAIT_ENV,
                                          Batcher, bucket_length)
from veles.simd_tpu.serve.health import (DEGRADED, HEALTHY,
                                         HealthMonitor)
from veles.simd_tpu.serve.cluster import (HEARTBEAT_MS_ENV,
                                          REPLICAS_ENV,
                                          ROUTER_POLICY_ENV,
                                          FrontRouter,
                                          NoReplicaAvailable,
                                          ReplicaGroup, RouterTicket)
from veles.simd_tpu.serve.rpc import (RPC_CONNS_ENV, RPC_TIMEOUT_ENV,
                                      RpcClient, RpcTicket)
from veles.simd_tpu.serve.scaler import ARM_ENV as SCALER_ARM_ENV
from veles.simd_tpu.serve.scaler import \
    TICK_MS_ENV as SCALER_TICK_MS_ENV
from veles.simd_tpu.serve.scaler import ScalerEngine
from veles.simd_tpu.serve.server import (DEADLINE_ENV, SUPPORTED_OPS,
                                         DeadlineExceeded, Request,
                                         Server, ServerClosed, Ticket,
                                         env_deadline_ms)

__all__ = [
    "Server", "Request", "Ticket", "ServerClosed", "Overloaded",
    "DeadlineExceeded", "AdmissionController", "Batcher",
    "HealthMonitor", "bucket_length", "env_deadline_ms",
    "SUPPORTED_OPS", "HEALTHY", "DEGRADED",
    "ReplicaGroup", "FrontRouter", "RouterTicket",
    "NoReplicaAvailable", "ScalerEngine",
    "RpcClient", "RpcTicket",
    "RPC_CONNS_ENV", "RPC_TIMEOUT_ENV",
    "SCALER_ARM_ENV", "SCALER_TICK_MS_ENV",
    "MAX_BATCH_ENV", "MAX_WAIT_ENV", "QUEUE_DEPTH_ENV",
    "TENANT_DEPTH_ENV", "DEADLINE_ENV", "REPLICAS_ENV",
    "ROUTER_POLICY_ENV", "HEARTBEAT_MS_ENV",
    "DEFAULT_MAX_BATCH", "DEFAULT_MAX_WAIT_MS",
    "DEFAULT_QUEUE_DEPTH", "DEFAULT_TENANT_DEPTH",
]
