"""1D local-extrema extraction.

TPU-native rebuild of ``/root/reference/src/detect_peaks.c`` +
``inc/simd/detect_peaks.h``.  Semantics preserved exactly from
``check_peak`` (``src/detect_peaks.c:41-56``): an interior sample ``c`` at
index ``i ∈ [1, size-2]`` is an extremum iff ``(c - prev)·(c - next) > 0``
(strict — plateaus are never peaks), reported as a maximum when
``c > prev`` and a minimum when ``c < prev``, filtered by the
``ExtremumType`` bitmask (MAXIMUM=1, MINIMUM=2, BOTH=3,
``inc/simd/detect_peaks.h:41-45``).

The reference returns a realloc-grown array of ``ExtremumPoint``
(``src/detect_peaks.c:19-39``).  XLA cannot return data-dependent shapes
(SURVEY.md §7 step 6), so there are two entry points:

* :func:`detect_peaks` — the user-facing API: jitted fixed-shape mask +
  values on device, host-side compaction; returns ``(positions, values)``
  variable-length arrays exactly like the C API.
* :func:`detect_peaks_fixed` — the jit-composable TPU-native form:
  returns ``(positions, values, count)`` with a static ``max_peaks``
  bound, positions beyond ``count`` filled with -1.  This is the version
  used inside larger jitted pipelines.
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu.utils.config import resolve_simd

__all__ = ["ExtremumType", "detect_peaks", "detect_peaks_na",
           "detect_peaks_fixed"]


class ExtremumType(enum.IntFlag):
    """``ExtremumType`` (``inc/simd/detect_peaks.h:41-45``)."""

    MAXIMUM = 1
    MINIMUM = 2
    BOTH = 3


@functools.partial(jax.jit, static_argnames=("type",))
def _peak_mask(data, type):
    """Boolean mask over the full signal (interior-only can be True)."""
    prev = data[..., :-2]
    curr = data[..., 1:-1]
    nxt = data[..., 2:]
    d1 = curr - prev
    d2 = curr - nxt
    is_ext = d1 * d2 > 0
    want = jnp.zeros_like(is_ext)
    if type & ExtremumType.MAXIMUM:
        want = want | (d1 > 0)
    if type & ExtremumType.MINIMUM:
        want = want | (d1 < 0)
    inner = is_ext & want
    pad = [(0, 0)] * (data.ndim - 1) + [(1, 1)]
    return jnp.pad(inner, pad)


def _compact_row(mask, data, max_peaks):
    """Cumsum+scatter compaction of one signal: O(n), stays on device.

    Each peak's output slot is its rank among peaks (cumsum of the mask);
    the scatter has no write conflicts because ranks are unique, and
    everything else lands in a trash slot that is sliced off.
    """
    n = mask.shape[-1]
    idx = jnp.arange(n, dtype=jnp.int32)
    rank = jnp.cumsum(mask) - 1
    dest = jnp.where(mask & (rank < max_peaks), rank, max_peaks)
    positions = jnp.full((max_peaks + 1,), -1, jnp.int32).at[dest].set(idx)
    values = jnp.zeros((max_peaks + 1,), data.dtype).at[dest].set(data)
    # the trash slot may hold a non-peak; everything below stays exact
    return positions[:max_peaks], values[:max_peaks]


# compaction-route crossover: top_k wins while max_peaks <= n/4, the
# rank-scatter wins at larger capacities (measured on v5e, 1M signal:
# top_k 1.1-3.0 ms vs scatter's flat ~5.2 ms up to n/4; 8.6 vs 5.2 ms at
# full capacity)
_TOPK_CAP_FRACTION = 4


def _compact_topk(mask, data, max_peaks):
    """Small-capacity compaction via ``lax.top_k`` (TPU-optimized sort
    network): peak indices are the top ``max_peaks`` of ``n - idx`` over
    peaks only, which yields them in ascending order.  O(n log k) but
    wins over the O(n) rank-scatter because XLA's TPU scatter is serial.
    """
    n = mask.shape[-1]
    idx = jnp.arange(n, dtype=jnp.int32)
    keys = jnp.where(mask, idx, n)              # non-peaks sort last
    vals_k, _ = jax.lax.top_k(n - keys, max_peaks)
    pos = n - vals_k                            # ascending peak indices
    count = jnp.sum(mask, axis=-1)
    valid = jnp.arange(max_peaks) < count[..., None]
    positions = jnp.where(valid, pos, -1).astype(jnp.int32)
    values = jnp.where(
        valid, jnp.take_along_axis(data, pos.clip(0, n - 1), axis=-1),
        jnp.zeros((), data.dtype))
    return positions, values, count


@functools.partial(jax.jit, static_argnames=("type", "max_peaks"))
def _peaks_fixed(data, type, max_peaks):
    mask = _peak_mask(data, type)
    n = data.shape[-1]
    if max_peaks * _TOPK_CAP_FRACTION <= n:
        return _compact_topk(mask, data, max_peaks)
    count = jnp.sum(mask, axis=-1)
    flat_mask = mask.reshape(-1, n)
    flat_data = data.reshape(-1, n)
    positions, values = jax.vmap(
        lambda m, d: _compact_row(m, d, max_peaks))(flat_mask, flat_data)
    out_shape = data.shape[:-1] + (max_peaks,)
    return (positions.reshape(out_shape), values.reshape(out_shape), count)


def detect_peaks_fixed(data, type=ExtremumType.BOTH, max_peaks=None):
    """Jit-composable fixed-capacity peak extraction.

    Returns ``(positions[int32, ..., max_peaks], values[..., max_peaks],
    count[...])``; unused slots hold position -1 / value 0.  ``max_peaks``
    defaults to the static worst case ``n - 2`` (an alternating signal
    makes every interior point an extremum).  A caller-supplied
    ``max_peaks`` is honored exactly — slots beyond ``n - 2`` are simply
    always empty — so a jitted pipeline gets the same output shape across
    signals of different lengths.
    """
    data = jnp.asarray(data)
    n = data.shape[-1]
    if n < 3:
        raise ValueError("size must be > 2 (src/detect_peaks.c:64 contract)")
    if max_peaks is None:
        # worst case: every interior point (alternating signal)
        max_peaks = n - 2
    return _peaks_fixed(data, ExtremumType(int(type)), int(max_peaks))


def detect_peaks_na(data, type=ExtremumType.BOTH):
    """NumPy oracle (``src/detect_peaks.c:128-139`` scalar loop).

    Returns ``(positions, values)`` 1D arrays (1D input only, like the C
    API)."""
    data = np.asarray(data, np.float32)
    if data.ndim != 1:
        raise ValueError("oracle path is 1D like the C API")
    if data.shape[-1] < 3:
        raise ValueError("size must be > 2 (src/detect_peaks.c:64 contract)")
    positions, values = [], []
    t = ExtremumType(int(type))
    for i in range(1, len(data) - 1):
        d1 = data[i] - data[i - 1]
        d2 = data[i] - data[i + 1]
        if d1 * d2 > 0:
            if (d1 > 0 and t & ExtremumType.MAXIMUM) or \
                    (d1 < 0 and t & ExtremumType.MINIMUM):
                positions.append(i)
                values.append(data[i])
    return (np.asarray(positions, np.int32), np.asarray(values, np.float32))


def detect_peaks(data, type=ExtremumType.BOTH, simd=None):
    """User-facing API (``detect_peaks``, ``inc/simd/detect_peaks.h:47-60``):
    returns variable-length ``(positions, values)``."""
    if not resolve_simd(simd):
        return detect_peaks_na(data, type)
    data = jnp.asarray(data)
    if data.ndim != 1:
        raise ValueError("detect_peaks is 1D; use detect_peaks_fixed for "
                         "batched fixed-shape extraction")
    if data.shape[-1] < 3:
        raise ValueError("size must be > 2 (src/detect_peaks.c:64 contract)")
    # compaction happens on device (cumsum+scatter in _peaks_fixed); the
    # host only slices the already-compacted prefix
    positions, values, count = _peaks_fixed(
        data, ExtremumType(int(type)), data.shape[-1] - 2)
    k = int(count)
    return (np.asarray(positions[:k], np.int32),
            np.asarray(values[:k], np.float32))
