#!/usr/bin/env python
"""MULTICHIP bench family: the pod-scale Fourier rows.

Measures the ``parallel/fourier.py`` routes on a device mesh —
``sharded_rfft`` (factorized matmul-DFT vs the local-FFT fallback) and
``sharded_stft`` at an above-cutoff frame size (the Cooley-Tukey local
frame transform vs raw ``jnp.fft``) — and writes
``MULTICHIP_DETAILS.json``: one row per metric with the per-route
roofline %, the per-stage ``all_to_all`` ICI byte counts, and the
decision events behind each number, plus a tail entry with the mesh
shape.  The row format matches ``BENCH_DETAILS.json``, so
``tools/bench_regress.py --details MULTICHIP_DETAILS.json`` gates the
trajectory with the same machinery (the ``sharded`` rows ship
``DEFAULT_NOISE`` thresholds there).

On hosts with fewer devices than requested, a virtual CPU mesh is
provisioned (``utils.platform.cpu_devices``, the ``dryrun_multichip``
discipline) — the numbers then validate plumbing, not ICI.

Run:  python tools/bench_multichip.py [--devices 8] [--quick]
      [--out MULTICHIP_DETAILS.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu import obs
from veles.simd_tpu.utils.benchmark import (
    a2a_ici_bytes, device_time, dft_matmul_roofline, host_time,
    rfft_flops, mxu_f32_bound_tflops, stft_roofline)


def _decisions(ops) -> list:
    """Last event per distinct (op, decision, forced) — a raw tail
    would be N repeats of whichever route was timed last, evicting
    the selected route's event (the one carrying ``ici_bytes``)."""
    last = {}
    for e in obs.events():
        if e.get("op") in ops:
            last[(e.get("op"), e.get("decision"),
                  e.get("forced"))] = e
    return [{k: v for k, v in e.items() if v is not None}
            for e in last.values()]


def _fft_roofline(samples_per_s: float, n: int) -> dict:
    """Local-FFT twin of :func:`dft_matmul_roofline` (split-radix
    useful-FLOP count against the same MXU bound, so the two routes'
    %s are comparable on one scale)."""
    bound = mxu_f32_bound_tflops()
    eff = rfft_flops(n) / n * samples_per_s / 1e12
    return {"tflops_effective": eff, "roofline_bound_tflops": bound,
            "pct_of_roofline": 100.0 * eff / bound,
            "precision": "highest"}


def bench_sharded_rfft(mesh, axis, n, rows_out):
    """Row 1: sharded_rfft, matmul-DFT vs local FFT on the same
    geometry.  ``value`` is the ENGINE-SELECTED route's throughput;
    ``baseline`` the forced local_fft one, so ``vs_baseline`` is the
    realized pod-scale speedup."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import fourier as fr

    s = mesh.shape[axis]
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(n).astype(np.float32))
    factor = sp.ct_factor(n, multiple=s)
    sel = fr._select_fourier_route(
        "rfft", n, s, 1, *(factor or (0, 0)))

    # correctness first: the selected route against the NumPy oracle
    from veles.simd_tpu.utils.platform import to_host
    want = np.fft.rfft(np.asarray(x).astype(np.float64))
    got = to_host(fr.sharded_rfft(x, mesh, axis=axis, route=sel))
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    if rel > 1e-4:
        raise RuntimeError(f"sharded_rfft {sel} rel err {rel:.2e}")
    print(f"MULTICHIP-CHECK sharded_rfft[{sel}] n={n}: ok "
          f"(rel {rel:.1e})", file=sys.stderr)

    obs.reset()
    times = {}
    for route in dict.fromkeys([sel, "sharded_matmul_dft",
                                "local_fft"]):
        if route == "sharded_matmul_dft" and not factor:
            continue
        times[route] = device_time(lambda r=route: jnp.abs(
            fr.sharded_rfft(x, mesh, axis=axis, route=r)).mean())
    t_base = host_time(
        lambda: np.fft.rfft(np.asarray(x, np.float64)), repeats=2)
    decisions = _decisions({"sharded_rfft", "autotune"})

    bytes_a2a = a2a_ici_bytes(n, 8, s)
    roofs = {}
    for route, t in times.items():
        if not np.isfinite(t):
            continue
        if route == "sharded_matmul_dft":
            roofs[route] = dft_matmul_roofline(n / t, *factor)
        else:
            roofs[route] = _fft_roofline(n / t, n)
    row = {
        "metric": f"sharded rfft {n // 1024}k x{s}",
        "unit": "Msamples/s",
        "value": n / times[sel] / 1e6,
        "baseline": n / times["local_fft"] / 1e6,
        "vs_baseline": times["local_fft"] / times[sel],
        "route": sel,
        "cpu_oracle_msamples_per_s": n / t_base / 1e6,
        "roofline_routes": roofs,
        "ici": {"a2a_per_dispatch": 2 if factor else 0,
                "bytes_per_a2a": bytes_a2a,
                "total_ici_bytes": 2 * bytes_a2a if factor else 0,
                "n1": factor[0] if factor else 0,
                "n2": factor[1] if factor else 0},
        "decisions": decisions[-8:],
    }
    rows_out.append(row)
    print(f"MULTICHIP sharded_rfft[{sel}]: "
          f"{row['value']:.1f} Ms/s vs local_fft "
          f"{row['baseline']:.1f} Ms/s ({row['vs_baseline']:.2f}x), "
          f"{2 * bytes_a2a / 1e6:.1f} MB ICI/dispatch",
          file=sys.stderr)

    # the precision row: the factorized pipeline at bf16_comp
    # (split/compensated stage matmuls, split-bf16 a2a payload) vs the
    # highest-precision one on the same geometry — error-budget-gated
    # before timing, per-precision roofline in the row
    if not factor:
        return
    from veles.simd_tpu.runtime import precision as prx

    comp = "sharded_matmul_dft_bf16_comp"
    got = to_host(fr.sharded_rfft(x, mesh, axis=axis, route=comp))
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    if rel > prx.ERROR_BUDGETS["bf16_comp"]:
        raise RuntimeError(
            f"sharded_rfft {comp} rel err {rel:.2e} > "
            f"{prx.ERROR_BUDGETS['bf16_comp']:.0e}")
    print(f"MULTICHIP-CHECK sharded_rfft[{comp}] n={n}: ok "
          f"(rel {rel:.1e})", file=sys.stderr)
    t_comp = device_time(lambda: jnp.abs(
        fr.sharded_rfft(x, mesh, axis=axis, route=comp)).mean())
    t_hi = times.get("sharded_matmul_dft")
    if t_hi is None or not (np.isfinite(t_comp)
                            and np.isfinite(t_hi)):
        return
    comp_row = {
        "metric": f"sharded rfft bf16_comp {n // 1024}k x{s}",
        "unit": "Msamples/s",
        "value": n / t_comp / 1e6,
        "baseline": n / t_hi / 1e6,
        "vs_baseline": t_hi / t_comp,
        "route": comp,
        "roofline_precisions": {
            "bf16_comp": dft_matmul_roofline(
                n / t_comp, *factor, precision="bf16_comp"),
            "highest": roofs.get("sharded_matmul_dft")},
        "ici": {"a2a_per_dispatch": 2,
                "bytes_per_a2a": a2a_ici_bytes(
                    n, fr.A2A_PAYLOAD_BYTES["bf16_comp"], s)},
    }
    rows_out.append(comp_row)
    print(f"MULTICHIP sharded_rfft[{comp}]: "
          f"{comp_row['value']:.1f} Ms/s vs highest "
          f"{comp_row['baseline']:.1f} Ms/s "
          f"({comp_row['vs_baseline']:.2f}x)", file=sys.stderr)


def bench_sharded_stft_above_cutoff(mesh, axis, n, frame, hop,
                                    rows_out):
    """Row 2: sharded_stft at a frame size past the single-chip matmul
    cutoff — the local per-frame transform is the engine-selected
    Cooley-Tukey matmul (``ct_matmul``); baseline forces the raw
    ``jnp.fft`` body via the family opt-out env."""
    import jax.numpy as jnp

    from veles.simd_tpu import parallel as par
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import fourier as fr

    s = mesh.shape[axis]
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(n).astype(np.float32))
    sel = fr.select_frame_route(frame)
    frames = sp.frame_count(n, frame, hop)

    obs.reset()

    def run():
        return jnp.abs(par.sharded_stft(x, frame, hop, mesh,
                                        axis=axis)).mean()

    t_sel = device_time(run)
    decisions = _decisions({"sharded_stft_local", "sharded_stft"})
    env = sp._DFT_MATMUL_ENV
    prev = os.environ.get(env)
    os.environ[env] = "1"
    try:
        fft_route = fr.select_frame_route(frame)
        t_fft = device_time(run)
    finally:
        if prev is None:
            os.environ.pop(env, None)
        else:
            os.environ[env] = prev

    roofs = {}
    if np.isfinite(t_sel):
        if sel == "ct_matmul":
            n1, n2 = sp.ct_factor(frame)
            roofs[sel] = dft_matmul_roofline(frames * frame / t_sel,
                                             n1, n2)
        else:
            roofs[sel] = stft_roofline(frames / t_sel, frame,
                                       route=sel)
    if np.isfinite(t_fft):
        roofs[fft_route] = _fft_roofline(frames * frame / t_fft,
                                         frame)
    row = {
        "metric": f"sharded stft {frame}/{hop} x{s} above-cutoff",
        "unit": "Msamples/s",
        "value": n / t_sel / 1e6,
        "baseline": n / t_fft / 1e6,
        "vs_baseline": t_fft / t_sel,
        "route": sel,
        "roofline_routes": roofs,
        # the sharded STFT's collective is the halo ppermute, not an
        # all_to_all: the ICI entry records that the frame transform
        # itself is collective-free (frames are shard-local)
        "ici": {"a2a_per_dispatch": 0, "bytes_per_a2a": 0,
                "halo_bytes": 4 * (frame - hop) * s},
        "decisions": decisions[-8:],
    }
    rows_out.append(row)
    print(f"MULTICHIP sharded_stft[{sel}] frame={frame}: "
          f"{row['value']:.1f} Ms/s vs {fft_route} "
          f"{row['baseline']:.1f} Ms/s ({row['vs_baseline']:.2f}x)",
          file=sys.stderr)


def run_bench(n_devices: int, out_path: str, quick: bool) -> int:
    import jax

    from veles.simd_tpu import parallel as par
    from veles.simd_tpu.utils.platform import cpu_devices

    obs.enable()
    obs.reset()
    rows: list = []
    with cpu_devices(n_devices) as devices:
        mesh = par.make_mesh({"sp": len(devices)}, devices=devices)
        s = len(devices)
        n_rfft = (1 << 14) if quick else (1 << 18)
        bench_sharded_rfft(mesh, "sp", n_rfft, rows)
        frame = 8192
        hop = 2048
        n_stft = max(s * 16384, frame * 2) if quick else s * 65536
        bench_sharded_stft_above_cutoff(mesh, "sp", n_stft, frame,
                                        hop, rows)
        tail = {"n_devices": s,
                "mesh": {k: int(v) for k, v in mesh.shape.items()},
                "device": str(devices[0])}
    for r in rows:
        # unresolved timers yield NaN; null the numbers (strict JSON)
        # and flag the row, the BENCH_DETAILS discipline
        if not all(isinstance(r.get(k), (int, float))
                   and np.isfinite(r[k])
                   for k in ("value", "baseline", "vs_baseline")):
            r["flagged"] = "unresolved measurement"
            for k in ("value", "baseline", "vs_baseline"):
                if isinstance(r.get(k), float) \
                        and not np.isfinite(r[k]):
                    r[k] = None
    with open(out_path, "w") as f:
        json.dump(rows + [tail], f, indent=2, allow_nan=False)
    print(f"wrote {out_path} ({len(rows)} rows)", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default="MULTICHIP_DETAILS.json")
    ap.add_argument("--quick", action="store_true",
                    help="small shapes (CI smoke)")
    args = ap.parse_args(argv)
    from veles.simd_tpu.utils.platform import maybe_override_platform

    maybe_override_platform()
    return run_bench(args.devices, args.out, args.quick)


if __name__ == "__main__":
    sys.exit(main())
