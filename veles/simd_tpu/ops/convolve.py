"""1D linear convolution: direct / FFT / overlap-save with auto-selection.

TPU-native rebuild of ``/root/reference/src/convolve.c`` +
``/root/reference/inc/simd/convolve.h``.  The reference ships three
algorithms behind a handle-based auto-select API
(``src/convolve.c:328-366``):

* brute-force direct form (``src/convolve.c:40-101``),
* full-signal FFT — pad to pow2 ≥ x+h−1, forward FFT of X and H, complex
  multiply, inverse, scale 1/M (``src/convolve.c:231-326``),
* overlap-save — block filtering with L = 2^(⌊log2 h⌋+2), step L−(h−1),
  one forward FFT / complex-mul / inverse FFT **per block, sequentially**
  (``src/convolve.c:103-229``, deliberately not parallel ``:179-180``).

The TPU formulation keeps the same three algorithms and the same handle API
but maps each to what the hardware actually wants:

* direct form → ``lax.conv_general_dilated``: the sliding window becomes an
  im2col-style matmul tiled onto the MXU, not a per-output-sample dot loop.
* FFT → ``jnp.fft.rfft``/``irfft`` (real FFTs, replacing FFTF entirely —
  SURVEY.md §7 step 4).
* overlap-save → **batched-frames FFT**: all blocks are gathered into a
  ``[n_blocks, L]`` array and transformed in a single batched real FFT, so
  the reference's sequential hot loop (``src/convolve.c:181-228``) becomes
  one fused FFT·multiply·IFFT over a batch dimension.  The same frame
  decomposition is what shards across chips in
  :mod:`veles.simd_tpu.parallel` (halo = the M−1 overlap).  For
  short/medium filters the spectral form is replaced by an MXU block
  matmul (``os_matmul``), served on TPU by a fused Pallas kernel that
  streams x through VMEM once with the M−1 halo carried between grid
  steps (:func:`_use_pallas_os`; XLA frames-matmul fallback behind the
  same auto-select).

Result length is always ``x_length + h_length - 1`` (full linear
convolution).  All entry points accept leading batch dimensions; the
reference's 1D API is the ``ndim == 1`` case.

Algorithm-selection thresholds are re-derived for TPU (the reference's
constants at ``src/convolve.c:328-364`` are ISA-specific — AVX picks FFT
above x>350, NEON above x>50).  On TPU the single-signal direct form never
tiles well onto the MXU, so the auto-select prefers overlap-save/FFT much
earlier than the reference; the measured crossover sweep is recorded at
the ``AUTO_*`` constants below.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.runtime import precision as prx
from veles.simd_tpu.utils.config import get_config, resolve_simd
from veles.simd_tpu.utils.memory import (
    next_highest_power_of_2, zeropadding_length)

__all__ = [
    "ConvolutionAlgorithm", "ConvolutionHandle",
    "convolve_simd", "convolve_na",
    "convolve_fft", "convolve_fft_initialize", "convolve_fft_finalize",
    "convolve_overlap_save", "convolve_overlap_save_initialize",
    "convolve_overlap_save_finalize",
    "convolve", "convolve_initialize", "convolve_finalize",
    "fftconvolve", "oaconvolve",
    "overlap_save_block_length", "tpu_block_length", "select_algorithm",
    "os_precision", "StreamingConvolution",
    "streaming_carry_len", "select_stream_route", "causal_stream_block",
    "causal_stream_block_na",
]


class ConvolutionAlgorithm(enum.Enum):
    """Mirrors ``ConvolutionAlgorithm`` at
    ``/root/reference/inc/simd/convolve_structs.h:39-46``."""

    BRUTE_FORCE = "brute_force"
    FFT = "fft"
    OVERLAP_SAVE = "overlap_save"


# TPU-tuned auto-select thresholds (reference's AVX/NEON constants at
# src/convolve.c:328-364 do not transfer).  Re-derived from a chained
# on-device crossover sweep on v5e (us/op, device_time_chained):
#
#        x      h |   direct     fft      os
#      256    256 |    298.2    10.0       -
#     1000     50 |     63.2     9.6     5.7
#     2000    950 |   9549.5    10.7    30.6
#     4096    512 |   3212.8    13.2     6.3
#     8192   1024 |  12284.7    18.0    25.0
#    16384   2047 |  49133.8   170.3    90.0
#    65536    511 |  46437.3   793.1     9.4
#
# The single-signal direct form ([1,1,n] x [1,1,k] conv) never tiles well
# onto the MXU and loses everywhere except the latency floor (~10 us), so
# the policy is: overlap-save when the halo is amortized (x >= 8h — the
# only loss in the sweep is 8192x1024 at 1.4x, while 4096x512 and
# 16384x2047 at the same ratio win), FFT for balanced problems above the
# latency floor, brute force only below it where every algorithm costs
# the same ~10 us dispatch.
AUTO_OVERLAP_SAVE_MIN_RATIO = 8     # x >= ratio*h -> overlap-save
AUTO_FFT_MIN_PRODUCT = 1 << 13      # x*h beyond which spectral wins
# within overlap-save: MXU block-matmul for filters up to this many taps,
# batched-frames FFT beyond (measured crossover on v5e, see BASELINE.md)
AUTO_OS_MATMUL_MAX_H = 1 << 14


def overlap_save_step(h_length: int) -> int:
    """Output-block size for the MXU overlap-save variant.

    Each output sample's dot spans ``step+k-1`` frame columns, so total
    MACs = ``out_len * (step+k-1)`` — *larger* steps mean more redundant
    work, while MXU tiling wants the step dimension near the 256-lane
    sweet spot.  Round-5 hardware sweep (v5e, 1M signal, chained device
    timing, ``tools/tune_overlap_save.py`` 2026-07-31):

        k=127   HIGHEST: 256 -> 22980 Ms/s  512 -> 18446  1024 -> 10563
        k=127   high:    256 -> 35345       512 -> 28997  1024 -> 17315
        k=2047  HIGHEST: 256 ->  5542       512 ->  5397  1024 ->  1027
        k=2047  high:    256 ->  8778       512 ->  9571  1024 ->  7641

    Winners: step 256 everywhere except k=2047/high where 512 leads by
    9%.  Rule: a quarter of the filter's padded length, clamped to
    [256, 512] (the earlier [512, 2048] rule cost 1.2-5x depending on
    config).  Rerun the sweep on new hardware generations.
    """
    return max(256, min(next_highest_power_of_2(int(h_length)) // 4, 512))


def overlap_save_block_length(h_length: int) -> int:
    """Reference block size: L = 2^(⌊log2 h⌋ + 2) — the same bit-count loop
    as the FFT padding helper (``src/convolve.c:115-121`` vs
    ``src/memory.c:131-137``)."""
    h_length = int(h_length)
    if h_length < 1:
        raise ValueError("h_length must be positive")
    return zeropadding_length(h_length)


def tpu_block_length(h_length: int, x_length: int) -> int:
    """TPU-tuned overlap-save block size.

    The reference's L = 2·nextpow2(h) means every block is ~50% halo —
    fine when the per-block FFT dominates on a CPU, but on TPU the batched
    FFT is cheap and the halo redundancy is pure waste.  Measured on v5e
    (1M-point signal, h ∈ {127..32767}): multipliers 8-32× beat the
    reference rule ~2× in throughput, flat within noise; 8× the reference
    length is used, capped so a block never exceeds the whole problem."""
    base = overlap_save_block_length(h_length)
    cap = next_highest_power_of_2(x_length + h_length - 1)
    return max(base, min(base * 8, cap))


def _fft_length(x_length: int, h_length: int) -> int:
    """Pad target for the full-FFT method: next pow2 ≥ x+h−1, keeping exact
    powers of two (``src/convolve.c:237-244``)."""
    return next_highest_power_of_2(x_length + h_length - 1)


# Algorithm-level candidate table (the unified routing engine,
# runtime/routing.py): the TPU re-derivation of the reference
# heuristic src/convolve.c:328-364, as priority-ordered predicates.
# Note x >= 8h implies h < x//2, the overlap-save handle contract
# (integer division, src/convolve.c:105), so the selected algorithm's
# initializer always accepts the lengths.
_ALGO_FAMILY = routing.family("convolve", (
    routing.Route(
        "brute_force",
        predicate=lambda x_length, h_length, **_:
            x_length * h_length < AUTO_FFT_MIN_PRODUCT,
        doc="latency floor: every algorithm costs the same ~10us "
            "dispatch"),
    routing.Route(
        "overlap_save",
        predicate=lambda x_length, h_length, **_:
            x_length >= AUTO_OVERLAP_SAVE_MIN_RATIO * h_length,
        doc="long signal, comparatively short filter: halo amortized"),
    routing.Route(
        "fft",
        doc="large balanced problems above the latency floor"),
))


def select_algorithm(x_length: int, h_length: int) -> ConvolutionAlgorithm:
    """TPU re-derivation of the reference heuristic
    (``src/convolve.c:328-364``), served from the ``convolve``
    candidate table: long signal with comparatively short filter →
    overlap-save; large balanced problem → FFT; otherwise direct
    (MXU)."""
    return ConvolutionAlgorithm(_ALGO_FAMILY.static_select(
        x_length=int(x_length), h_length=int(h_length)))


# --------------------------------------------------------------------------
# jitted XLA kernels (cached by (shapes, static lengths))
# --------------------------------------------------------------------------

# Direct-form candidate table: the Pallas shifted-MAC kernel measured
# 5.6-9.3x over the XLA conv lowering on v5e for batched signals with
# <=256-tap filters; single-signal calls, long filters, and rows too
# long for a 1-row VMEM tile stay on the XLA/MXU path.
_DIRECT_FAMILY = routing.family("convolve.direct", (
    routing.Route(
        "direct_pallas",
        predicate=lambda rows, n, k, **_: (
            k <= _pk.PALLAS_DIRECT_MAX_H
            and _pk.should_route(rows, (n + 2 * (k - 1))
                                 + (n + k - 1))),
        doc="VPU shifted-MAC Pallas kernel (batched, short filters)"),
    routing.Route("direct_mxu",
                  doc="lax.conv_general_dilated im2col on the MXU"),
))


def _use_pallas_direct(x_shape, k: int) -> bool:
    """Route batched direct convolution through the Pallas shifted-MAC
    kernel — thin delegate into the ``convolve.direct`` candidate
    table (runtime/routing.py), where the tap bound and VMEM-tile gate
    live.  Tests monkeypatch this gate to exercise the kernel on
    CPU."""
    rows = int(np.prod(x_shape[:-1])) if len(x_shape) > 1 else 1
    return _DIRECT_FAMILY.gate("direct_pallas", rows=rows,
                               n=int(x_shape[-1]), k=int(k))


@functools.partial(obs.instrumented_jit, op="convolve",
                   route="direct_pallas",
                   static_argnames=("reverse",))
def _conv_direct_pallas(x, h, reverse=False):
    """Direct-form full convolution as a VPU shifted-MAC Pallas kernel
    (C=1 instance of the DWT/SWT filter-bank kernel)."""
    n, k = x.shape[-1], h.shape[-1]
    kernel = h if reverse else jnp.flip(h, axis=-1)
    x_ext = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(k - 1, k - 1)])
    (y,) = _pk.filter_bank_pallas(x_ext, kernel.reshape(1, k), 1, 1,
                                  n + k - 1)
    return y


def _direct_runners(x, h, reverse):
    """Route name -> zero-arg core call, the ONE home of the
    direct-form candidate call expressions: dispatch runs
    ``runners[chosen]()`` and the measured autotuner probes the same
    thunks, so a probe can never measure a different computation than
    dispatch executes."""
    return {
        "direct_pallas":
            lambda: _conv_direct_pallas(x, h, reverse=reverse),
        "direct_mxu": lambda: _conv_direct(x, h, reverse=reverse),
    }


def _direct(x, h, reverse=False):
    """Direct-form dispatch: Pallas shifted-MAC when the gate admits the
    shape, XLA/MXU conv otherwise (single home for the routing — used by
    ``convolve_simd``, the BRUTE_FORCE handle path, and
    ``correlate.cross_correlate_simd``).  Under
    ``VELES_SIMD_AUTOTUNE=on`` the engine probes both candidates once
    per geometry class and the measured winner persists."""
    n, k = int(x.shape[-1]), int(h.shape[-1])
    rows = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
    eligible = (["direct_pallas", "direct_mxu"]
                if _use_pallas_direct(x.shape, h.shape[-1])
                else ["direct_mxu"])
    runners = _direct_runners(x, h, reverse)
    # rows/n are pow2-bucketed so a length-churning service shares a
    # finite set of tune classes; k (the filter design) keys exactly
    chosen = _DIRECT_FAMILY.select(
        eligible=eligible, runners=runners, probe_operand=x,
        rows=routing.pow2_bucket(rows), n=routing.pow2_bucket(n), k=k)
    return runners[chosen]()


@functools.partial(obs.instrumented_jit, op="convolve",
                   route="direct_mxu",
                   static_argnames=("reverse",))
def _conv_direct(x, h, reverse=False):
    """Direct-form full convolution on the MXU.

    ``lax.conv_general_dilated`` computes cross-correlation, so convolution
    flips ``h`` — and cross-correlation (``reverse=True``) uses ``h``
    unflipped, the same flip-reuse trick as ``src/correlate.c:37-72``.
    """
    batch_shape = x.shape[:-1]
    n = x.shape[-1]
    k = h.shape[-1]
    lhs = x.reshape((-1, 1, n)).astype(jnp.float32)          # [N, C=1, W]
    kernel = h if reverse else jnp.flip(h, axis=-1)
    rhs = kernel.reshape((1, 1, k)).astype(jnp.float32)      # [O=1, I=1, W]
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(k - 1, k - 1)],
        precision=prx.HIGHEST)
    return out.reshape(batch_shape + (n + k - 1,))


@functools.partial(obs.instrumented_jit, op="convolve", route="fft",
                   static_argnames=("m", "reverse"))
def _conv_fft(x, h, m, reverse=False):
    """Full-FFT method (``src/convolve.c:289-326``) with real FFTs."""
    n = x.shape[-1]
    k = h.shape[-1]
    kernel = jnp.flip(h, axis=-1) if reverse else h
    spec = jnp.fft.rfft(x, m, axis=-1) * jnp.fft.rfft(kernel, m, axis=-1)
    return jnp.fft.irfft(spec, m, axis=-1)[..., : n + k - 1].astype(
        jnp.float32)


def os_precision() -> str:
    """The MXU precision the overlap-save block matmul will use
    (``Config.conv_precision``)."""
    return get_config().conv_precision


# filter lengths whose fused overlap-save compile OOMed Mosaic's
# scoped-vmem stack (consulted by _run_xla's route).  Bounded LRU like
# every rejection cache (a long-running service cycling filter designs
# must not grow an unbounded set; an evicted length just pays one more
# failed compile if it returns), snapshot in obs.caches() with
# hit/miss/eviction counters.  Tests may substitute a plain set — the
# provider re-reads the module global per snapshot.
_PALLAS_OS_MAXSIZE = 64
_PALLAS_OS_REJECTED = obs.LRUSet(_PALLAS_OS_MAXSIZE)
faults.register_rejection_cache(
    "pallas_os_rejected", lambda: _PALLAS_OS_REJECTED,
    _PALLAS_OS_MAXSIZE)


# Overlap-save candidate table: the fused Pallas kernel vs the XLA
# block-matmul.  The XLA formulation materializes its frames operand
# as J ~ 1 + h/step shifted copies of the signal through HBM, while
# the fused kernel streams each x block through VMEM once with the
# h-1 halo carried between grid steps; long filters only (short ones
# are barely duplicated and already compute-bound on the XLA path).
# The rejection cache + injection site ride the table so the
# demote-and-remember policy and the fault harness share one source
# of truth with the selector.
_OS_FAMILY = routing.family("convolve.os", (
    routing.Route(
        "pallas_fused",
        predicate=lambda h_length, **_: (
            _pk.pallas_available() and _pk.pallas_os_allowed()
            and h_length >= _pk.PALLAS_OS_MIN_H
            and _pk.fits_vmem_os(h_length)),
        fault_site="convolve.os_pallas",
        rejection_cache=lambda: _PALLAS_OS_REJECTED,
        rejection_key=lambda h_length, **_: h_length,
        roofline={"kind": "conv"},
        doc="fused Pallas overlap-save: x streamed through VMEM once, "
            "h-1 halo carried between grid steps "
            "(VELES_SIMD_DISABLE_PALLAS_OS opts out)"),
    routing.Route(
        "xla_matmul",
        roofline={"kind": "conv"},
        doc="MXU block matmul over gather-free shifted frames"),
    # precision-variant candidates sit AFTER the terminal fallback:
    # the static prior (autotune off) never changes, but the measured
    # autotuner probes them like any candidate and a tune-cache winner
    # steers dispatch — precision as just another route the engine
    # selects and defends empirically (runtime/precision.py)
    routing.Route(
        "xla_matmul_bf16_comp",
        predicate=lambda **_: prx.precision_allowed("bf16_comp"),
        disable_env=prx.BF16_COMP_ENV,
        roofline={"kind": "conv"},
        doc="the block matmul at bf16_comp: split/compensated bf16 "
            "accumulation, ~fp32 accuracy at 3 MXU passes instead of "
            "highest's 6 (VELES_SIMD_DISABLE_BF16_COMP opts out)"),
))


def _use_pallas_os(h_length: int) -> bool:
    """Route the overlap-save block matmul through the fused Pallas
    kernel (:func:`~veles.simd_tpu.ops.pallas_kernels.\
overlap_save_pallas`) — thin delegate into the ``convolve.os``
    candidate table (runtime/routing.py), where the filter-length and
    VMEM-residency gates and the ``VELES_SIMD_DISABLE_PALLAS_OS``
    opt-out live.  Tests monkeypatch this gate to exercise the kernel
    on CPU."""
    return _OS_FAMILY.gate("pallas_fused", h_length=int(h_length))


@functools.partial(obs.instrumented_jit, op="convolve",
                   route="os_pallas",
                   static_argnames=("reverse", "precision"))
def _conv_os_pallas(x, h, reverse=False, precision=None):
    """Overlap-save as the fused Pallas kernel (same contract as
    :func:`_conv_os_matmul`; the step is the kernel's own
    ``PALLAS_OS_STEP`` — its redundancy/tiling trade-off differs from
    the XLA path's, see the constant's note)."""
    kernel = jnp.flip(h, axis=-1) if reverse else h
    # the Mosaic kernel contracts at XLA's own knobs only — a
    # compensated-precision config falls back to "highest" here (the
    # comp variant is the XLA block matmul's route, not the kernel's)
    if precision not in prx.JAX_PRECISIONS:
        precision = "highest"
    return _pk.overlap_save_pallas(x, kernel, precision=precision)


@functools.partial(obs.instrumented_jit, op="convolve",
                   route="os_matmul",
                   static_argnames=("step", "reverse", "precision"))
def _conv_os_matmul(x, h, step, reverse=False, precision=None):
    """Overlap-save with the per-block filter as one MXU matmul.

    The reference's overlap-save runs an FFT·multiply·IFFT per block
    (``src/convolve.c:181-228``).  On TPU the direct form wins for all but
    very long filters: a 2047-tap 1M-point convolution is ~4 GFLOP of MAC
    work, which the MXU finishes in well under a millisecond while XLA's
    TPU FFT needs ~9 ms.  Formulation: outputs are computed in blocks of
    ``step`` samples; block i needs input samples ``[i*step - (k-1),
    i*step + step)``, so the signal is framed into overlapping rows
    ``frames[i, a] = x_ext[i*step + a]`` (``x_ext`` = signal with ``k-1``
    leading zeros) and each block is ``frames @ M`` with
    ``M[a, t] = h[t + k - 1 - a]`` — a ``[B, step+k-1] x [step+k-1, step]``
    matmul whose both free dims are large enough to tile onto the MXU.

    Both operands are materialized *gather-free* (TPU gathers are ~100x
    slower than the matmul itself — measured 37 ms for the frame gather
    vs 0.17 ms for the matmul):

    * frames = J shifted row-blocks of the zero-padded signal reshaped to
      ``[B+J, step]``, concatenated along columns;
    * the Toeplitz ``M`` (as its transpose MT) via a tile trick: rows of
      MT are ``flip(h)`` shifted right by t, and tiling
      ``w = [flip(h), zeros(step+1)]`` ``step`` times then reshaping to
      ``[step, k+step]`` yields exactly those shifts, because
      ``t*(k+step) ≡ -t (mod k+step+1)``.

    ``precision`` trades MXU passes for accuracy (``None`` → "highest";
    the handle/public paths pass ``Config.conv_precision`` explicitly via
    :func:`os_precision`) — measured on v5e against a float64 oracle
    (1M x 2047, randn):

    * HIGHEST (6-pass bf16 = full f32): ~4.8e-7 rel., 3.08 GSamples/s
      at step 2048, 4.33 at step 1024;
    * HIGH (3-pass): ~1.3e-5 rel. — inside every correctness gate
      (1e-4 TPU smoke, reference test epsilons) — 7.57 GSamples/s at
      step 1024;
    * DEFAULT (1-pass bf16): ~2.6e-3, NOT acceptable for the oracle
      tests; available only by passing it explicitly.
    """
    n = x.shape[-1]
    k = h.shape[-1]
    s = step
    out_len = n + k - 1
    n_blocks = -(-out_len // s)
    J = -(-(s + k - 1) // s)

    kernel = jnp.flip(h, axis=-1) if reverse else h
    # frames[..., i, a] = x_ext[..., i*s + a], a in [0, s+k-1)
    pad_tail = (n_blocks + J) * s - (n + k - 1)
    x_ext = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(k - 1, pad_tail)])
    Z = x_ext.reshape(x.shape[:-1] + (n_blocks + J, s))
    frames = jnp.concatenate(
        [Z[..., j:j + n_blocks, :] for j in range(J)],
        axis=-1)[..., : s + k - 1]
    # MT[t, a] = kernel_rev[a - t]; kernel_rev[m] = kernel[k-1-m] so that
    # y[i*s+t] = sum_a frames[i, a] * kernel[t + k - 1 - a]
    w = jnp.pad(jnp.flip(kernel, axis=-1), (0, s + 1))       # len k+s+1
    MT = jnp.tile(w, s)[: s * (k + s)].reshape(s, k + s)[:, : s + k - 1]
    # public callers resolve Config.conv_precision via os_precision()
    # before the jit cache key forms (reading config here would bake a
    # stale value); a direct call omitting precision gets plain
    # "highest".  The precision layer also serves the compensated
    # names ("bf16_comp" — the xla_matmul_bf16_comp route — and
    # forced "bf16"/"int8").
    y = prx.p_einsum("...ba,ta->...bt", frames, MT,
                     precision=precision or "highest")
    y = y.reshape(y.shape[:-2] + (n_blocks * s,))
    return y[..., :out_len].astype(jnp.float32)


@functools.partial(obs.instrumented_jit, op="convolve",
                   route="os_fft",
                   static_argnames=("block_len", "reverse"))
def _conv_overlap_save(x, h, block_len, reverse=False):
    """Overlap-save as a single batched-frames FFT (the long-filter path).

    The reference runs one FFT per L-sample block in a sequential loop
    (``src/convolve.c:181-228``); here every block is a row of a
    ``[n_blocks, L]`` array and one batched rfft/irfft covers them all —
    the frame gather is the only data movement XLA can't fuse away.
    """
    n = x.shape[-1]
    k = h.shape[-1]
    L = block_len
    step = L - (k - 1)
    out_len = n + k - 1
    n_blocks = -(-out_len // step)  # ceil

    kernel = jnp.flip(h, axis=-1) if reverse else h
    H = jnp.fft.rfft(kernel, L, axis=-1)

    # X_ext = [zeros(k-1), x, zeros(tail)]; frame i = X_ext[i*step : i*step+L]
    pad_tail = (n_blocks - 1) * step + L - (k - 1) - n
    x_ext = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(k - 1, pad_tail)])
    idx = jnp.arange(n_blocks)[:, None] * step + jnp.arange(L)[None, :]
    frames = jnp.take(x_ext, idx, axis=-1)                   # [..., B, L]

    spec = jnp.fft.rfft(frames, L, axis=-1) * H[..., None, :]
    blocks = jnp.fft.irfft(spec, L, axis=-1)[..., k - 1:]    # [..., B, step]
    flat = blocks.reshape(blocks.shape[:-2] + (n_blocks * step,))
    return flat[..., :out_len].astype(jnp.float32)


# --------------------------------------------------------------------------
# NumPy oracles (reference scalar semantics)
# --------------------------------------------------------------------------

def convolve_na(x, h):
    """Direct-form oracle (``src/convolve.c:49-100`` scalar branch)."""
    x = np.asarray(x, np.float32)
    h = np.asarray(h, np.float32)
    if x.ndim == 1:
        return np.convolve(x, h, mode="full").astype(np.float32)
    flat = x.reshape(-1, x.shape[-1])
    out = np.stack([np.convolve(row, h, mode="full") for row in flat])
    return out.reshape(x.shape[:-1] + (x.shape[-1] + h.shape[-1] - 1,)
                       ).astype(np.float32)


def _conv_fft_na(x, h, m, reverse=False):
    x = np.asarray(x, np.float32)
    h = np.asarray(h, np.float32)
    if reverse:
        h = h[..., ::-1]
    n, k = x.shape[-1], h.shape[-1]
    spec = np.fft.rfft(x, m, axis=-1) * np.fft.rfft(h, m, axis=-1)
    return np.fft.irfft(spec, m, axis=-1)[..., : n + k - 1].astype(np.float32)


def _conv_overlap_save_na(x, h, block_len, reverse=False):
    x = np.asarray(x, np.float32)
    h = np.asarray(h, np.float32)
    if reverse:
        h = h[..., ::-1]
    n, k = x.shape[-1], h.shape[-1]
    L = block_len
    step = L - (k - 1)
    out_len = n + k - 1
    n_blocks = -(-out_len // step)
    H = np.fft.rfft(h, L, axis=-1)
    pad_tail = (n_blocks - 1) * step + L - (k - 1) - n
    x_ext = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(k - 1, pad_tail)])
    idx = np.arange(n_blocks)[:, None] * step + np.arange(L)[None, :]
    frames = np.take(x_ext, idx, axis=-1)
    blocks = np.fft.irfft(np.fft.rfft(frames, L, axis=-1) * H[..., None, :],
                          L, axis=-1)[..., k - 1:]
    flat = blocks.reshape(blocks.shape[:-2] + (n_blocks * step,))
    return flat[..., :out_len].astype(np.float32)


# --------------------------------------------------------------------------
# handle API (parity with inc/simd/convolve.h:41-126)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ConvolutionHandle:
    """Compiled-plan handle (``inc/simd/convolve_structs.h:39-74``).

    The reference caches FFTF plans + scratch buffers; here the "plan" is
    the jitted XLA executable cached by (shape, static lengths), so the
    handle only pins the problem geometry and chosen algorithm.
    """

    x_length: int
    h_length: int
    algorithm: ConvolutionAlgorithm
    reverse: bool = False
    # derived static sizes (FFT pad / overlap-save block length)
    fft_length: int | None = None
    block_length: int | None = None
    # overlap-save variant: MXU block-matmul (short/medium filters) vs
    # batched-frames FFT (very long filters); step = output block size of
    # the matmul variant
    os_matmul: bool = False
    step: int | None = None

    @property
    def result_length(self) -> int:
        return self.x_length + self.h_length - 1


def _make_handle(x_length, h_length, algorithm, reverse):
    x_length, h_length = int(x_length), int(h_length)
    if x_length < 1 or h_length < 1:
        raise ValueError("convolve: lengths must be positive "
                         "(src/convolve.c:44-48 assert contract)")
    forced = algorithm is not None
    if algorithm is None:
        algorithm = select_algorithm(x_length, h_length)
    algorithm = ConvolutionAlgorithm(algorithm)
    fft_len = block_len = step = None
    os_matmul = False
    if algorithm is ConvolutionAlgorithm.FFT:
        fft_len = _fft_length(x_length, h_length)
    elif algorithm is ConvolutionAlgorithm.OVERLAP_SAVE:
        if not h_length < x_length // 2:
            raise ValueError(
                "overlap-save requires h_length < x_length / 2 "
                "(src/convolve.c:105 assert contract, integer division)")
        block_len = tpu_block_length(h_length, x_length)
        os_matmul = h_length <= AUTO_OS_MATMUL_MAX_H
        step = overlap_save_step(h_length)
    obs.record_decision(
        "convolve", algorithm.value, x_length=x_length,
        h_length=h_length, forced=forced, fft_length=fft_len,
        block_length=block_len, os_matmul=os_matmul, step=step,
        reverse=bool(reverse))
    return ConvolutionHandle(x_length, h_length, algorithm, reverse,
                             fft_len, block_len, os_matmul, step)


def _check_lengths(handle, x, h):
    if not get_config().check_arguments:
        return
    if x.shape[-1] != handle.x_length or h.shape[-1] != handle.h_length:
        raise ValueError(
            f"handle is for x_length={handle.x_length}, "
            f"h_length={handle.h_length}; got {x.shape[-1]}, {h.shape[-1]}")


def _run(handle: ConvolutionHandle, x, h, simd=None):
    if resolve_simd(simd, op="convolve"):
        # host-side span around the whole XLA dispatch: route choice +
        # executable call.  Python-only (no jax ops), so the traced
        # program is untouched — test_obs.py pins jaxpr identity.
        # faults.breaker_guarded applies the transient-fault policy
        # (bounded retry on device-lost/timeout, then graceful
        # degradation to the NumPy oracle) around the whole XLA side,
        # behind the shape class's circuit breaker — a persistently
        # failing class answers straight from the oracle instead of
        # paying the retry ladder per call (churning x_length is
        # pow2-bucketed so classes stay finite; h_length gates routes
        # exactly, so it keys exactly)
        with obs.span("convolve.dispatch",
                      algo=handle.algorithm.value,
                      os_matmul=handle.os_matmul):
            return faults.breaker_guarded(
                "convolve.dispatch",
                (handle.algorithm.value, handle.h_length,
                 routing.pow2_bucket(handle.x_length)),
                lambda: _run_xla(handle, x, h),
                fallback=lambda: _run_oracle(handle, x, h))
    return _run_oracle(handle, x, h)


def _run_oracle(handle: ConvolutionHandle, x, h):
    """The NumPy-oracle side of :func:`_run` — also the fault policy's
    degradation target when the device path exhausts its retries."""
    x, h = np.asarray(x), np.asarray(h)
    _check_lengths(handle, x, h)
    if handle.reverse:
        h = h[..., ::-1]
    if handle.algorithm is ConvolutionAlgorithm.BRUTE_FORCE:
        return convolve_na(x, h)
    if handle.algorithm is ConvolutionAlgorithm.FFT:
        return _conv_fft_na(x, h, handle.fft_length)
    return _conv_overlap_save_na(x, h, handle.block_length)


def _run_xla(handle: ConvolutionHandle, x, h):
    """The XLA side of :func:`_run` (factored out so the dispatch span
    wraps route selection and the executable call in one scope)."""
    x, h = jnp.asarray(x), jnp.asarray(h)
    _check_lengths(handle, x, h)
    if handle.algorithm is ConvolutionAlgorithm.BRUTE_FORCE:
        return _direct(x, h, reverse=handle.reverse)
    if handle.algorithm is ConvolutionAlgorithm.FFT:
        return _conv_fft(x, h, handle.fft_length, reverse=handle.reverse)
    if handle.os_matmul:
        # the ONE home of the overlap-save candidate call expressions:
        # dispatch and the autotune probes run the same thunks (the
        # bare instrumented cores — no spans or decision events,
        # forcing routes uniformly as the engine contract asks), so a
        # probe can never measure a different computation than
        # dispatch executes
        runners = {
            "pallas_fused": lambda: _conv_os_pallas(
                x, h, reverse=handle.reverse,
                precision=os_precision()),
            "xla_matmul": lambda: _conv_os_matmul(
                x, h, handle.step, reverse=handle.reverse,
                precision=os_precision()),
            "xla_matmul_bf16_comp": lambda: _conv_os_matmul(
                x, h, handle.step, reverse=handle.reverse,
                precision="bf16_comp"),
        }

        def _os_matmul(route="xla_matmul"):
            # default route keeps this a valid zero-arg demotion
            # fallback for the pallas path below
            obs.record_decision(
                "convolve_os_route", route,
                x_length=handle.x_length, h_length=handle.h_length,
                step=handle.step)
            with obs.span("convolve.os_route", route=route):
                return runners[route]()

        pallas_ok = ((_use_pallas_os(handle.h_length)
                      or faults.armed("convolve.os_pallas"))
                     and handle.h_length not in _PALLAS_OS_REJECTED)
        eligible = (["pallas_fused", "xla_matmul"] if pallas_ok
                    else ["xla_matmul"])
        if _OS_FAMILY.gate("xla_matmul_bf16_comp",
                           h_length=handle.h_length):
            # the compensated-precision candidate: never the static
            # prior (it sits after the terminal route), but the
            # measured autotuner may crown it per geometry class
            eligible.append("xla_matmul_bf16_comp")
        # rows/x_length are pow2-bucketed (finite tune classes under
        # batch/length churn; rows matters — the pallas-vs-matmul
        # crossover shifts with batch: per-row VMEM halo vs
        # rows-scaled HBM frame duplication); h_length/step — the
        # gate dimensions and the rejection-cache key — stay exact.
        # precision keys the class too: both runners read
        # Config.conv_precision, and a winner measured at 'highest'
        # (multi-pass matmul) must not steer 'high' dispatches.
        os_rows = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
        chosen = _OS_FAMILY.select(
            eligible=eligible, runners=runners, probe_operand=x,
            rows=routing.pow2_bucket(os_rows),
            x_length=routing.pow2_bucket(handle.x_length),
            h_length=handle.h_length, step=handle.step,
            precision=os_precision())
        if chosen == "pallas_fused":
            def _os_pallas():
                with obs.span("convolve.os_route",
                              route="pallas_fused"):
                    out = runners["pallas_fused"]()
                # recorded AFTER the attempt resolves, so a demotion
                # never misattributes the executed route
                obs.record_decision(
                    "convolve_os_route", "pallas_fused",
                    x_length=handle.x_length,
                    h_length=handle.h_length,
                    step=_pk.PALLAS_OS_STEP)
                return out

            # Mosaic's scoped-vmem cap is not predictable from shape
            # arithmetic (convolve2d learned this on hardware): the
            # shared engine demotes the filter length to the XLA path
            # on the specific vmem-OOM compile error and remembers it.
            # Under an OUTER jit the compile error surfaces
            # uncatchably at the outer compile — traced callers rely
            # on fits_vmem_os's margin and the
            # VELES_SIMD_DISABLE_PALLAS_OS escape hatch; eager callers
            # (bench, handle API) get this fallback.
            return faults.demote_and_remember(
                "convolve.os_pallas", _os_pallas, _os_matmul,
                cache=_PALLAS_OS_REJECTED, key=handle.h_length,
                route="pallas_fused", fallback_route="xla_matmul",
                counter="pallas_os_demotion")
        return _os_matmul(chosen)
    return _conv_overlap_save(x, h, handle.block_length,
                              reverse=handle.reverse)


# ---- brute force ----------------------------------------------------------

def convolve_simd(x, h, simd=None):
    """Direct-form full convolution (``convolve_simd``,
    ``inc/simd/convolve.h:41-56``)."""
    if resolve_simd(simd, op="convolve_simd"):
        return _direct(jnp.asarray(x), jnp.asarray(h))
    return convolve_na(x, h)


# ---- FFT method -----------------------------------------------------------

def convolve_fft_initialize(x_length, h_length, *, reverse=False):
    """``inc/simd/convolve.h:58-76`` — plan handle for the full-FFT method."""
    return _make_handle(x_length, h_length, ConvolutionAlgorithm.FFT, reverse)


def convolve_fft(handle, x, h, simd=None):
    return _run(handle, x, h, simd)


def convolve_fft_finalize(handle):
    """No-op: XLA executables are cached/collected by the runtime
    (``convolve_fft_finalize``, ``src/convolve.c:280-287``)."""


# ---- overlap-save ---------------------------------------------------------

def convolve_overlap_save_initialize(x_length, h_length, *, reverse=False):
    """``inc/simd/convolve.h:78-96``."""
    return _make_handle(x_length, h_length,
                        ConvolutionAlgorithm.OVERLAP_SAVE, reverse)


def convolve_overlap_save(handle, x, h, simd=None):
    return _run(handle, x, h, simd)


def convolve_overlap_save_finalize(handle):
    """No-op (``src/convolve.c:148-154``)."""


# ---- auto-select ----------------------------------------------------------

def convolve_initialize(x_length, h_length, algorithm=None, *,
                        reverse=False):
    """``inc/simd/convolve.h:98-115`` — picks the algorithm via
    :func:`select_algorithm` unless forced.  ``reverse=True`` makes the
    handle cross-correlate (``src/correlate.c:128-143``)."""
    return _make_handle(x_length, h_length, algorithm, reverse=reverse)


def _check_mode(mode):
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"mode must be 'full', 'same' or 'valid', "
                         f"got {mode!r}")
    return mode


def _mode_slice(out, n, k, mode, correlate=False):
    """Slice a FULL conv/correlation result to numpy's ``mode``.

    numpy's 'same' window for ``correlate(x, h)`` with ``len(x) <
    len(h)`` comes from its swap-and-reverse evaluation, landing one
    sample later than convolution's centered slice — hence the
    ``correlate`` flag."""
    if mode == "full":
        return out
    lo, hi = min(n, k), max(n, k)
    if mode == "same":
        start = lo // 2 if (correlate and n < k) else (lo - 1) // 2
        return out[..., start:start + hi]
    return out[..., lo - 1: hi]  # valid


def convolve(handle_or_x, x_or_h, h=None, simd=None, *, mode="full"):
    """Linear convolution.

    Two call forms, mirroring the reference's two entry styles:

    * ``convolve(handle, x, h)`` — handle API (``inc/simd/convolve.h:117-126``)
    * ``convolve(x, h)`` — convenience: auto-select per call

    ``mode`` ('full' default, 'same', 'valid' — the numpy/scipy
    convention) slices the full result; the reference API itself is
    full-only.
    """
    _check_mode(mode)
    if isinstance(handle_or_x, ConvolutionHandle):
        out = _run(handle_or_x, x_or_h, h, simd)
        # a reverse=True handle computes correlation, whose 'same'
        # window differs — key off the handle, not the wrapper called
        return _mode_slice(out, handle_or_x.x_length,
                           handle_or_x.h_length, mode,
                           correlate=handle_or_x.reverse)
    x, h_ = handle_or_x, x_or_h
    if h is not None:       # convolve(x, h, simd) positional form
        simd = h
    handle = convolve_initialize(np.shape(x)[-1], np.shape(h_)[-1])
    return _mode_slice(_run(handle, x, h_, simd),
                       np.shape(x)[-1], np.shape(h_)[-1], mode)


def convolve_finalize(handle):
    """No-op (``src/convolve.c:368-379``)."""


def fftconvolve(x, h, mode: str = "full", simd=None):
    """scipy's ``fftconvolve`` by name: convolution via the spectral
    method.  1D taps (``h[k]``, leading batch dims on ``x`` ride
    along) use the padded-rfft path; a 2D kernel routes to
    :func:`veles.simd_tpu.ops.convolve2d.convolve2d` with the fft
    algorithm; higher-rank kernels are rejected (scipy computes true
    N-d convolution there — silently convolving one axis would be a
    wrong answer, not a subset).  ``mode`` as in :func:`convolve`."""
    if np.ndim(h) > 2:
        raise ValueError(
            f"kernels of rank {np.ndim(h)} are not supported (1D taps "
            "or a 2D kernel; scipy's N-d fftconvolve has no equivalent "
            "here)")
    if np.ndim(h) == 2:
        from veles.simd_tpu.ops import convolve2d as cv2

        return cv2.convolve2d(x, h, algorithm="fft", simd=simd,
                              mode=mode)
    handle = convolve_fft_initialize(np.shape(x)[-1], np.shape(h)[-1])
    return convolve(handle, x, h, simd=simd, mode=mode)


def oaconvolve(x, h, mode: str = "full", simd=None):
    """scipy's ``oaconvolve`` by name: block-overlap convolution for
    long signals.  Runs the overlap-SAVE formulation (identical
    results to scipy's overlap-add; this library's blocked method is
    the MXU block-matmul / batched-frame-FFT overlap-save,
    ``tools/tune_overlap_save.py``-tuned); a 2D kernel routes to the
    2D fft path like :func:`fftconvolve`.  Sizes outside the blocked
    method's contract (short signals / long kernels, where blocking
    buys nothing) fall back to :func:`fftconvolve`, as scipy's
    oaconvolve does internally."""
    if np.ndim(h) == 1:
        try:
            handle = convolve_overlap_save_initialize(
                np.shape(x)[-1], np.shape(h)[-1])
        except ValueError:
            return fftconvolve(x, h, mode=mode, simd=simd)
        return convolve(handle, x, h, simd=simd, mode=mode)
    # 2D routes to the spectral 2D path; rank > 2 is rejected there
    return fftconvolve(x, h, mode=mode, simd=simd)


# --------------------------------------------------------------------------
# streaming convolution — NEW capability beyond the reference
# --------------------------------------------------------------------------

def streaming_carry_len(h_length: int) -> int:
    """Input-history samples a causal streaming FIR must carry between
    blocks (the overlap-save halo): ``h_length - 1``.  The pipeline
    compiler's state-sizing hook."""
    return max(int(h_length) - 1, 0)


def select_stream_route(x_length: int, h_length: int,
                        tune_geom: dict | None = None) -> str:
    """Compile-time route for one causal streaming-FIR block — the
    pipeline compiler's hook into the ``convolve`` candidate table
    (autotuned winners and the tune cache steer the fused step too;
    ``tune_geom`` lets the caller stamp its own tune class, e.g. the
    pipeline-stage class).  Consults, never probes: the fused step is
    compiled once and the per-route thunks the autotuner would time
    are not what the pipeline dispatches."""
    return _ALGO_FAMILY.select(
        x_length=int(x_length), h_length=int(h_length),
        tune_geom=tune_geom)


def causal_stream_block(x_ext, h, route: str, reverse: bool = False):
    """TRACEABLE causal-FIR block over a halo-extended signal — the
    pipeline compiler's state-export hook.

    ``x_ext[..., carry + b]`` is the previous block's ``h_length - 1``
    trailing input samples (zero-seeded at stream start — exactly the
    one-shot convolution's left zero pad) followed by the new block;
    returns the ``b`` causal outputs ``y[t] = sum_k h[k] x[t - k]``
    for the block's samples.  ``route`` comes from
    :func:`select_stream_route`; all three algorithm routes run the
    same ``obs.instrumented_jit`` cores dispatch uses, so they inline
    into a fused outer jit with no extra dispatch (the Pallas direct
    kernel is excluded — the fused step must stay outer-jit-safe on
    every backend).
    """
    k = int(h.shape[-1])
    n = int(x_ext.shape[-1])
    if route == "fft":
        full = _conv_fft(x_ext, h, _fft_length(n, k), reverse=reverse)
    elif route == "overlap_save" and k <= AUTO_OS_MATMUL_MAX_H:
        full = _conv_os_matmul(x_ext, h, overlap_save_step(k),
                               reverse=reverse,
                               precision=os_precision())
    else:
        # brute_force — and the terminal fallback for an overlap-save
        # selection whose very long filter has no matmul step
        full = _conv_direct(x_ext, h, reverse=reverse)
    return full[..., k - 1:n]


def causal_stream_block_na(x_ext, h, reverse: bool = False):
    """NumPy float64 oracle twin of :func:`causal_stream_block` (the
    pipeline's stage-by-stage degradation path)."""
    x_ext = np.asarray(x_ext, np.float64)
    h = np.asarray(h, np.float64)
    if reverse:
        h = h[..., ::-1]
    k = h.shape[-1]
    n = x_ext.shape[-1]
    full = convolve_na(x_ext, h)
    return full[..., k - 1:n]


class StreamingConvolution:
    """Chunked streaming convolution with carried overlap state.

    The reference's overlap-save decomposes one in-memory signal into
    blocks (``src/convolve.c:181-228``); streaming is the same
    decomposition over *time* — chunks arrive one at a time, the state
    between calls is the last ``h_length - 1`` input samples, and the
    concatenated outputs equal the one-shot full convolution exactly::

        sc = StreamingConvolution(h, chunk_length=4096)
        ys = [sc.process(c) for c in chunks]   # len(c) == chunk_length
        ys.append(sc.flush())                  # final h_length-1 samples
        # jnp.concatenate(ys) == convolve(x, h)

    Every ``process`` call reuses one compiled executable (fixed chunk
    length ⇒ one jit cache entry); chunks may carry leading batch dims,
    fixed across calls.  ``reverse=True`` streams cross-correlation,
    mirroring ``src/correlate.c:37-72``.
    """

    def __init__(self, h, chunk_length: int, *, reverse: bool = False,
                 simd=None):
        self._h = np.asarray(h, np.float32)
        if self._h.ndim != 1:
            raise ValueError("h must be 1D")
        self._k = int(self._h.shape[-1])
        self._chunk_length = int(chunk_length)
        if self._chunk_length < 1:
            raise ValueError("chunk_length must be positive")
        self._reverse = bool(reverse)
        # backend resolved ONCE at construction (a stateful stream must
        # not switch backends mid-flight); the oracle path then stays
        # pure NumPy — no jax import/backend init at all
        self._use_xla = resolve_simd(simd, op="streaming_convolve")
        self._xp = jnp if self._use_xla else np
        # per-chunk plan through the module's auto-select (overlap-save /
        # FFT / direct all reuse one compiled executable per shape)
        k = self._k
        self._chunk_handle = convolve_initialize(
            self._chunk_length + k - 1, k, reverse=reverse)
        self._flush_handle = convolve_initialize(k - 1, k, reverse=reverse) \
            if k > 1 else None
        self._carry = None          # [..., k-1] trailing input samples
        self._done = False

    @property
    def h_length(self) -> int:
        return self._k

    @property
    def chunk_length(self) -> int:
        return self._chunk_length

    def process(self, chunk):
        """Feed the next ``chunk_length`` samples; returns the same count
        of output samples (the convolution is causal: output t depends on
        inputs ≤ t)."""
        if self._done:
            raise ValueError("stream already flushed")
        xp = self._xp
        chunk = xp.asarray(chunk, xp.float32)
        if chunk.shape[-1] != self._chunk_length:
            raise ValueError(
                f"chunk length {chunk.shape[-1]} != {self._chunk_length} "
                "(fixed so every call reuses one compiled executable)")
        k = self._k
        if self._carry is None:
            self._carry = xp.zeros(chunk.shape[:-1] + (k - 1,), xp.float32)
        if self._carry.shape[:-1] != chunk.shape[:-1]:
            raise ValueError(
                f"batch shape changed mid-stream: {chunk.shape[:-1]} vs "
                f"{self._carry.shape[:-1]}")
        if k == 1:
            return _run(self._chunk_handle, chunk, self._h,
                        simd=self._use_xla)
        x_ext = xp.concatenate([self._carry, chunk], axis=-1)
        full = _run(self._chunk_handle, x_ext, self._h, simd=self._use_xla)
        self._carry = x_ext[..., -(k - 1):]
        return full[..., k - 1:k - 1 + self._chunk_length]

    def flush(self):
        """Emit the final ``h_length - 1`` output samples (the tail that
        depends only on already-seen inputs).  The stream cannot be used
        afterwards.  Degenerate cases return an empty array: a stream
        that never saw a chunk, or ``h_length == 1`` (a one-tap filter
        has no tail).  The C binding zero-fills its fixed-size tail
        buffer in those cases instead."""
        if self._done:
            raise ValueError("stream already flushed")
        self._done = True
        k = self._k
        if self._carry is None or k == 1:
            shape = ((0,) if self._carry is None
                     else self._carry.shape[:-1] + (0,))
            return self._xp.zeros(shape, self._xp.float32)
        full = _run(self._flush_handle, self._carry, self._h,
                    simd=self._use_xla)
        return full[..., k - 1:]
