"""Tests for the platform layer (``utils/platform.py``).

The backend-selection knowledge concentrated here (config-level pins
that beat the sitecustomize, subprocess probes that can't hang, restore
semantics) is what every entry point leans on — worth direct coverage.
"""

import os

import numpy as np
import pytest

from veles.simd_tpu.utils import platform as plat


def test_set_cpu_env_replaces_count_flag():
    old = os.environ.get("XLA_FLAGS")
    try:
        os.environ["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=3 --other_flag=1")
        plat.set_cpu_env(5)
        flags = os.environ["XLA_FLAGS"].split()
        assert "--xla_force_host_platform_device_count=5" in flags
        assert "--other_flag=1" in flags
        assert sum("device_count" in f for f in flags) == 1
    finally:
        if old is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = old


def test_probe_device_count_sees_pinned_cpu():
    # conftest pinned this process to an 8-device CPU platform via
    # jax.config; the probe must replicate that pin into its subprocess
    # (env alone would be stomped by the sitecustomize) — all 8 virtual
    # devices visible, not just "some platform answered"
    assert plat.probe_device_count(timeout=120.0) == 8


def test_require_reachable_device_passes_here():
    plat.require_reachable_device(timeout=120.0)  # must not raise


def test_backend_live_is_true_under_pytest():
    # conftest initialized the CPU backend at session start
    assert plat._backend_live()


def test_probe_subprocess_failure_detail():
    # unreasonably small timeout forces the TimeoutExpired branch
    count, detail = plat._probe_subprocess(timeout=0.01)
    assert count == 0
    assert "timed out" in detail


def test_cpu_devices_uses_live_backend_without_teardown():
    import jax

    before = jax.devices()
    with plat.cpu_devices(4) as devices:
        assert len(devices) == 4
    assert jax.devices() == before  # no provisioning, no restore


def test_require_reachable_device_wait_retries(monkeypatch, capsys):
    """The wait budget keeps re-probing and returns as soon as a device
    appears; with no budget it exits immediately."""
    from veles.simd_tpu.utils import platform as plat

    calls = []

    def fake_probe(timeout):
        calls.append(timeout)
        return (0, "wedged") if len(calls) < 3 else (1, "")

    monkeypatch.setattr(plat, "_probe_subprocess", fake_probe)
    import time as _time
    monkeypatch.setattr(_time, "sleep", lambda s: None)
    plat.require_reachable_device(wait=3600.0)
    assert len(calls) == 3
    assert "retrying" in capsys.readouterr().err

    calls.clear()

    def always_down(timeout):
        calls.append(timeout)
        return (0, "wedged")

    monkeypatch.setattr(plat, "_probe_subprocess", always_down)
    with pytest.raises(SystemExit):
        plat.require_reachable_device(wait=0.0)
    assert len(calls) == 1


def test_device_wait_env_overrides_and_malformed_warns(monkeypatch, capsys):
    from veles.simd_tpu.utils import platform as plat

    calls = []
    monkeypatch.setattr(plat, "_probe_subprocess",
                        lambda t: (calls.append(t), (0, "down"))[1])
    # env=0 overrides a caller wait -> single probe, fail fast
    monkeypatch.setenv("VELES_SIMD_DEVICE_WAIT", "0")
    with pytest.raises(SystemExit):
        plat.require_reachable_device(wait=3600.0)
    assert len(calls) == 1

    # malformed env warns and keeps the caller's budget (0 here)
    calls.clear()
    monkeypatch.setenv("VELES_SIMD_DEVICE_WAIT", "10m")
    with pytest.raises(SystemExit):
        plat.require_reachable_device(wait=0.0)
    assert "malformed" in capsys.readouterr().err
    assert len(calls) == 1


class TestComplexTransferHelpers:
    """to_host / to_device: the complex-relay-gap workaround (round 5).

    The axon relay cannot move complex buffers in either direction and
    one attempt poisons the process; these helpers move real/imag as
    two real transfers.  On the CPU test backend both paths are plain
    transfers — these tests pin semantics, not the relay behavior."""

    def test_to_host_complex_roundtrip(self):
        import jax.numpy as jnp

        from veles.simd_tpu.utils.platform import to_host

        want = (np.arange(6) + 1j * np.arange(6)[::-1]).astype(
            np.complex64).reshape(2, 3)
        got = to_host(jnp.asarray(want))
        assert isinstance(got, np.ndarray)
        assert got.dtype == np.complex64
        np.testing.assert_array_equal(got, want)

    def test_to_host_real_and_numpy_passthrough(self):
        import jax.numpy as jnp

        from veles.simd_tpu.utils.platform import to_host

        x = np.arange(4, dtype=np.float32)
        assert to_host(x) is x                      # numpy passthrough
        np.testing.assert_array_equal(to_host(jnp.asarray(x)), x)

    def test_to_device_complex_upload(self):
        import jax
        import jax.numpy as jnp

        from veles.simd_tpu.utils.platform import to_device

        want = (np.random.RandomState(0).randn(8)
                + 1j * np.random.RandomState(1).randn(8))
        d = to_device(want, jnp.complex64)
        assert isinstance(d, jax.Array)
        assert d.dtype == jnp.complex64
        np.testing.assert_allclose(np.asarray(jnp.real(d)),
                                   want.real.astype(np.float32))
        np.testing.assert_allclose(np.asarray(jnp.imag(d)),
                                   want.imag.astype(np.float32))

    def test_to_device_dtype_policy(self):
        import jax
        import jax.numpy as jnp

        from veles.simd_tpu.utils.platform import to_device

        # complex input without a target: canonicalizes like
        # jnp.asarray (complex64 when x64 is off)
        d = to_device(np.zeros(3, np.complex128))
        assert d.dtype == (jnp.complex128 if jax.config.jax_enable_x64
                           else jnp.complex64)
        # complex -> real target is a contract error, not a silent cast
        with pytest.raises(TypeError, match="real dtype"):
            to_device(np.zeros(3, np.complex64), jnp.float32)
        # real input passes straight through with the requested dtype
        r = to_device(np.arange(3), jnp.float32)
        assert r.dtype == jnp.float32
        # device-resident arrays pass through untouched
        dd = jnp.asarray(np.ones(2, np.float32))
        assert to_device(dd) is dd
