"""Compensated-precision matmul layer: `bf16_comp` (and `int8`) as
first-class, error-budget-gated MXU precisions.

The MXU's native bf16 (and int8) throughput is a multiple of its
f32-emulation rate, and "Large Scale Distributed Linear Algebra With
TPUs" (arXiv:2112.09017) shows dense linear algebra reaching
fp32-class accuracy from bf16 multiplies via split/compensated
accumulation; TINA (arXiv:2408.16551) makes the same case for mapping
signal processing onto accelerator matmul primitives at their native
precisions.  This module is the ONE home of that machinery — and of
every raw MXU-precision literal the compute layers used to carry
(``tools/lint.py``'s precision rule forbids ``jax.lax.Precision`` /
``preferred_element_type`` literals in ``ops/``/``parallel/`` compute
cores outside this layer, alias-tracked like the jit/time rules):

* ``highest`` / ``high`` / ``default`` — XLA's f32-emulation knobs
  (6-/3-/1-pass bf16), passed straight through to the contraction;

* ``bf16`` — plain 1-pass bf16 multiplies, f32 accumulate: full MXU
  rate, ~2.4e-3 rel err on a randn 512-GEMM — fails every oracle gate,
  so it is FORCEABLE but never engine-eligible (the historical
  ``matrix.matrix_multiply(fast=True)`` semantics, now a shim);

* ``bf16_comp`` — the compensated route: each f32 operand splits into
  a bf16 high part and a bf16 residual (``x = hi + lo`` with ``lo =
  x - f32(hi)``), and the product is the three-term sum ``hi@hi +
  lo@hi + hi@lo`` (the ``lo@lo`` term is ~2^-16 relative and dropped)
  accumulated in f32 — 3 bf16 MXU passes recovering ~fp32 accuracy
  (measured ~5e-6 rel err on the randn 512-GEMM vs 2.4e-3 for plain
  bf16; 461x better on a large-dynamic-range adversarial input).
  Inside the 1e-4 error budget with margin at half the 6-pass
  ``highest`` cost;

* ``int8`` — dynamically scaled symmetric per-tensor quantization
  (round to [-127, 127], int8 multiplies, int32 accumulate, rescale):
  ~2x the bf16 MXU rate but ~1.6e-2 rel err, so it is REFUSED for
  engine eligibility unless the operator opts in with
  ``VELES_SIMD_ENABLE_INT8=1`` — and even then only geometries whose
  error budget tolerates it should route there.

Routes named ``<base>_bf16_comp`` ride the existing ``routing.family``
tables AFTER the terminal fallback: the static prior (autotune off)
never changes, the measured autotuner probes them like any other
candidate and persists per-geometry winners in the stamped tune cache,
and the oracle-twin parity suites gate every (route, precision) pair
at its :data:`ERROR_BUDGETS` bound (``tests/test_precision.py``).
``VELES_SIMD_DISABLE_BF16_COMP=1`` closes every ``bf16_comp`` gate
family-wide.

Everything here is pure traceable jax — the helpers are called inside
the ops' ``obs.instrumented_jit`` cores, never compiled here, so the
resource/time telemetry axes keep seeing one compile site per route.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = [
    "HIGHEST", "PRECISIONS", "JAX_PRECISIONS", "COMP_PRECISIONS",
    "ERROR_BUDGETS", "BF16_COMP_ENV", "INT8_ENV",
    "precision_allowed", "comp_route", "base_route", "split_bf16",
    "p_einsum", "p_matmul", "p_dot", "p_conv",
]

# the ONE home of the raw literal (the compute-module lint rule bans
# it everywhere in ops//parallel outside this layer)
HIGHEST = jax.lax.Precision.HIGHEST

# XLA's own f32-emulation knobs — pass through to the contraction
JAX_PRECISIONS = ("highest", "high", "default")
# the split/quantized routes this layer implements
COMP_PRECISIONS = ("bf16", "bf16_comp", "int8")
PRECISIONS = JAX_PRECISIONS + COMP_PRECISIONS

# family-wide escape hatch for the compensated routes, mirroring
# VELES_SIMD_DISABLE_DFT_MATMUL for the matmul-DFT routes
BF16_COMP_ENV = "VELES_SIMD_DISABLE_BF16_COMP"
# int8 is opt-IN (not opt-out): its ~1.6e-2 rel err exceeds every
# oracle gate, so engine eligibility requires an explicit operator
# decision — forced dispatch (precision="int8") stays available
INT8_ENV = "VELES_SIMD_ENABLE_INT8"

# per-precision relative-error budgets vs the float64 oracles
# (max-normalized, the tune tools' metric): the parity suites gate
# every (route, precision) pair at its bound, and the sweep tools
# refuse winners that exceed it.  "highest"/"high" bounds restate the
# measured v5e figures in ops/convolve.py's precision table.
ERROR_BUDGETS = {
    "highest": 1e-6,
    "high": 5e-5,
    "default": 5e-2,
    "bf16_comp": 1e-4,
    "bf16": 5e-2,
    "int8": 5e-2,
}

_COMP_SUFFIX = "_bf16_comp"


def _env_truthy(name: str) -> bool:
    # routing.env_truthy's parser, inlined: this module must stay
    # importable without pulling the routing engine (it sits below it)
    return os.environ.get(name, "0").strip().lower() in (
        "1", "true", "yes", "on")


def precision_allowed(precision: str) -> bool:
    """May IMPLICIT routing (engine eligibility) use ``precision``?

    ``bf16_comp`` unless ``VELES_SIMD_DISABLE_BF16_COMP`` is truthy;
    ``int8`` only when ``VELES_SIMD_ENABLE_INT8`` is truthy; plain
    ``bf16`` NEVER (it fails every oracle budget — forced dispatch
    only); the XLA knobs always.  Forced routes (an explicit
    ``precision=`` / ``route=`` argument) bypass this, like every
    forced route in the engine."""
    if precision == "bf16_comp":
        return not _env_truthy(BF16_COMP_ENV)
    if precision == "int8":
        return _env_truthy(INT8_ENV)
    if precision == "bf16":
        return False
    return precision in JAX_PRECISIONS


def comp_route(base: str) -> str:
    """The ``bf16_comp`` variant's route name for a base route —
    ``rdft_matmul`` -> ``rdft_matmul_bf16_comp``.  One spelling shared
    by the family tables, the runners, and the tune tools."""
    return base + _COMP_SUFFIX


def base_route(name: str) -> str:
    """Inverse of :func:`comp_route` (identity for plain routes)."""
    return name[:-len(_COMP_SUFFIX)] if name.endswith(_COMP_SUFFIX) \
        else name


def split_bf16(x):
    """``(hi, lo)`` bf16 split of a float operand: ``hi = bf16(x)``,
    ``lo = bf16(x - f32(hi))``.  ``f32(hi) + f32(lo)`` reconstructs x
    to ~2^-16 relative — the split/compensated-accumulation operands
    of arXiv:2112.09017."""
    x = x.astype(jnp.float32)
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _quantize_int8(x):
    """Symmetric per-tensor int8 quantization with a dynamic scale
    (traceable).  A zero tensor gets scale 1 so the rescale never
    divides by zero."""
    x = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x)) / 127.0
    scale = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _check(precision: str) -> str:
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {sorted(PRECISIONS)}, got "
            f"{precision!r}")
    return precision


def _contract(fn, a, b, precision):
    """Shared body: ``fn(a, b, **kw)`` under one precision scheme.
    ``fn`` is a two-operand contraction taking ``precision=`` /
    ``preferred_element_type=`` keywords (einsum/matmul/dot
    partials)."""
    if precision in JAX_PRECISIONS:
        return fn(a, b, precision=precision)
    if precision == "bf16":
        return fn(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                  preferred_element_type=jnp.float32)
    if precision == "bf16_comp":
        ahi, alo = split_bf16(a)
        bhi, blo = split_bf16(b)
        pet = jnp.float32
        return (fn(ahi, bhi, preferred_element_type=pet)
                + fn(alo, bhi, preferred_element_type=pet)
                + fn(ahi, blo, preferred_element_type=pet))
    # int8
    qa, sa = _quantize_int8(a)
    qb, sb = _quantize_int8(b)
    acc = fn(qa, qb, preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (sa * sb)


def p_einsum(spec: str, a, b, precision: str = "highest"):
    """Two-operand einsum at a named precision — the contraction every
    matmul-heavy route core goes through (f32 output for every
    scheme)."""
    _check(precision)

    def fn(x, y, **kw):
        return jnp.einsum(spec, x, y, **kw)

    return _contract(fn, a, b, precision)


def p_matmul(a, b, precision: str = "highest"):
    """``jnp.matmul`` at a named precision (batch dims broadcast as
    matmul does)."""
    _check(precision)
    return _contract(jnp.matmul, a, b, precision)


def p_dot(m, v, precision: str = "highest"):
    """``jnp.dot`` at a named precision (the gemv form)."""
    _check(precision)
    return _contract(jnp.dot, m, v, precision)


def p_conv(lhs, rhs, precision: str = "highest", **conv_kwargs):
    """``lax.conv_general_dilated`` at a named precision — the im2col
    conv cores' form (``window_strides``/``padding``/dilations pass
    through).  The compensated scheme applies the same three-term
    split as the matmuls: convolution is bilinear, so ``hi*hi +
    lo*hi + hi*lo`` recovers ~fp32 accuracy from bf16 passes."""
    _check(precision)

    def fn(a, b, precision=None, preferred_element_type=None):
        kw = dict(conv_kwargs)
        if precision is not None:
            kw["precision"] = precision
        if preferred_element_type is not None:
            kw["preferred_element_type"] = preferred_element_type
        return jax.lax.conv_general_dilated(a, b, **kw)

    return _contract(fn, lhs, rhs, precision)
