"""The history axis (obs v6): a durable append-only event journal.

Every other obs axis is a bounded in-process ring — the decision event
log, the span trace, the request exemplars, the fleet series — and all
of them die with the process.  After a ``chaos-replicas`` kill (or a
real production wedge) the dead replica's story is gone except for
whatever a rate-limited flight bundle happened to catch.  This module
is the axis that *survives*: a process-wide append-only JSONL journal
of every decision event that flows through ``obs.record_decision`` —
breaker transitions, replica lifecycle edges, SLO breaches,
fault/retry/degrade steps, autotune and artifact outcomes, chaos
phases, incident open/close — written line-atomically to disk so
``tools/obs_query.py`` can reconstruct the fleet's timeline after the
replicas that lived it are dead.

Contract (the TuneCache corruption discipline applied to history):

* **Off by default.**  Armed by ``$VELES_SIMD_JOURNAL_DIR`` or
  ``obs.configure(journal_dir=...)``; while disarmed, :func:`emit` is
  a single attribute + env check and nothing touches the filesystem.
* **Schema-stamped records.**  Every line is one JSON object carrying
  ``schema`` (:data:`SCHEMA`), a per-process monotonically rising
  ``seq``, BOTH clocks (``t_mono`` for intra-process deltas, ``t_wall``
  for cross-process merge ordering), ``pid``, and the replica identity
  (:func:`set_replica` — subprocess replicas stamp their own name).
  The event payload lives under its own ``data`` key, so a payload
  field (``replica=`` on a lifecycle event names the *subject*) can
  never collide with the writer's identity stamp.
* **Line-atomic appends.**  One locked ``write()`` + ``flush()`` of a
  complete ``\\n``-terminated line per record; concurrent dispatch
  threads interleave *lines*, never bytes mid-record.
* **Bounded disk.**  Segments rotate at
  ``$VELES_SIMD_JOURNAL_MAX_BYTES`` (default 4 MiB) and the writer
  prunes its own oldest segments to keep its total under
  ``$VELES_SIMD_JOURNAL_MAX_TOTAL_BYTES`` (default 64 MiB).  A writer
  only ever deletes files it named itself (``journal-<pid>-*``) — a
  shared pack directory is safe across replicas.
* **Torn tails are counted, not fatal.**  A replica killed mid-write
  leaves at most one torn line; :func:`read_file` / :func:`read_pack`
  recover every parseable record and count the rest (``skipped``),
  mirroring the artifact store's corrupt-manifest discipline.
* **One file per process.**  Subprocess replicas inherit the armed
  env var and journal to their own ``journal-<pid>-<seq>.jsonl``
  files in the shared pack; :func:`discover` finds them all.

Write failures (read-only dir, disk full) are *counted drops* — the
journal must never take down the dispatch path it is recording.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

__all__ = [
    "SCHEMA", "JOURNAL_DIR_ENV", "MAX_BYTES_ENV", "MAX_TOTAL_BYTES_ENV",
    "DEFAULT_MAX_BYTES", "DEFAULT_MAX_TOTAL_BYTES", "TAIL_KEEP",
    "JournalWriter",
    "armed", "journal_dir", "configure_dir", "set_replica", "replica",
    "emit", "emit_decision", "cursor", "tail", "stats",
    "prune_foreign",
    "discover", "read_file", "read_pack",
]

SCHEMA = "veles-simd-journal-v1"
JOURNAL_DIR_ENV = "VELES_SIMD_JOURNAL_DIR"
MAX_BYTES_ENV = "VELES_SIMD_JOURNAL_MAX_BYTES"
MAX_TOTAL_BYTES_ENV = "VELES_SIMD_JOURNAL_MAX_TOTAL_BYTES"

# 4 MiB segments: large enough that rotation is rare at decision-event
# rates, small enough that pruning one segment frees meaningful space
DEFAULT_MAX_BYTES = 4 * 1024 * 1024
# 64 MiB per process: days of decision events, bounded like every
# other obs buffer — history that grows without bound is an outage
DEFAULT_MAX_TOTAL_BYTES = 64 * 1024 * 1024

# in-memory tail retained for flight bundles: enough records to tell
# the story right before a crash even after the journal rotated
TAIL_KEEP = 64

_FILE_RE = re.compile(r"^journal-(\d+)-(\d+)\.jsonl$")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        return default
    return value if value > 0 else default


class JournalWriter:
    """One process's journal: the current segment file handle, the
    rotation/prune state, and the in-memory tail.  All appends go
    through one lock; every public method is exception-proof where the
    contract demands it (:meth:`append` counts failures as drops)."""

    def __init__(self, directory: str,
                 max_bytes: int | None = None,
                 max_total_bytes: int | None = None):
        self.dir = str(directory)
        self.max_bytes = int(max_bytes) if max_bytes \
            else _env_int(MAX_BYTES_ENV, DEFAULT_MAX_BYTES)
        self.max_total_bytes = int(max_total_bytes) if max_total_bytes \
            else _env_int(MAX_TOTAL_BYTES_ENV, DEFAULT_MAX_TOTAL_BYTES)
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self._seg = self._next_segment()
        self._seq = 0
        self.records = 0
        self.dropped = 0
        self.rotations = 0
        self.pruned = 0
        self.last_t_mono: float | None = None
        self._tail: list = []

    # -- naming ------------------------------------------------------------

    def _next_segment(self) -> int:
        """First segment number: one past anything this pid already
        wrote (a reconfigured writer must never clobber its own past)."""
        top = 0
        try:
            for name in os.listdir(self.dir):
                m = _FILE_RE.match(name)
                if m and int(m.group(1)) == self.pid:
                    top = max(top, int(m.group(2)))
        except OSError:
            pass
        return top + 1

    def _segment_path(self, seg: int) -> str:
        return os.path.join(self.dir, "journal-%d-%06d.jsonl"
                            % (self.pid, seg))

    @property
    def current_file(self) -> str:
        return self._segment_path(self._seg)

    # -- writes ------------------------------------------------------------

    def append(self, record: dict) -> bool:
        """Stamp and append one record as one line; returns False on a
        counted drop (write failure).  Never raises."""
        try:
            with self._lock:
                self._seq += 1
                t_mono = time.monotonic()
                stamped = {"schema": SCHEMA, "seq": self._seq,
                           "t_mono": t_mono, "t_wall": time.time(),
                           "pid": self.pid, "replica": replica()}
                stamped.update(record)
                line = json.dumps(stamped, separators=(",", ":"),
                                  default=str) + "\n"
                data = line.encode("utf-8")
                if self._fh is None \
                        or self._size + len(data) > self.max_bytes:
                    self._rotate_locked()
                self._fh.write(data)
                self._fh.flush()
                self._size += len(data)
                self.records += 1
                self.last_t_mono = t_mono
                self._tail.append(stamped)
                if len(self._tail) > TAIL_KEEP:
                    del self._tail[0]
                return True
        except Exception:  # noqa: BLE001 — the journal never takes
            with self._lock:  # down the path it records
                self.dropped += 1
            return False

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._seg += 1
            self.rotations += 1
        os.makedirs(self.dir, exist_ok=True)
        self._fh = open(self.current_file, "ab")
        self._size = self._fh.tell()
        self._prune_locked()

    def _prune_locked(self) -> None:
        """Keep THIS pid's total bytes under the budget by unlinking
        its oldest segments (never the current one).  Other replicas'
        files in a shared pack are never touched."""
        own = []
        try:
            for name in os.listdir(self.dir):
                m = _FILE_RE.match(name)
                if m and int(m.group(1)) == self.pid:
                    path = os.path.join(self.dir, name)
                    try:
                        own.append((int(m.group(2)), path,
                                    os.path.getsize(path)))
                    except OSError:
                        continue
        except OSError:
            return
        own.sort()
        total = sum(size for _, _, size in own)
        for seg, path, size in own:
            if total <= self.max_total_bytes or seg >= self._seg:
                break
            try:
                os.unlink(path)
                total -= size
                self.pruned += 1
            except OSError:
                break

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- reads -------------------------------------------------------------

    def cursor(self) -> dict:
        """Where the journal is NOW: the current segment file, the byte
        offset within it, and the per-process record count — embedded
        in flight bundles and incident records so a postmortem can seek
        straight to the moment."""
        with self._lock:
            return {"file": os.path.basename(self.current_file),
                    "dir": self.dir, "offset": self._size,
                    "segment": self._seg, "records": self.records}

    def tail(self, n: int = TAIL_KEEP) -> list:
        """The last ``n`` stamped records (newest last) from memory —
        what a flight bundle embeds so it stays self-diagnosing even
        after the on-disk journal rotates past the incident."""
        with self._lock:
            return [dict(r) for r in self._tail[-int(n):]]

    def stats(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "records": self.records,
                    "dropped": self.dropped,
                    "rotations": self.rotations, "pruned": self.pruned,
                    "segment": self._seg, "bytes": self._size,
                    "max_bytes": self.max_bytes,
                    "max_total_bytes": self.max_total_bytes,
                    "last_t_mono": self.last_t_mono}


# -- the process-wide writer (the facade's funnel) ---------------------------

_lock = threading.Lock()
_configured_dir: str | None = None
_replica: str | None = None
_writer: JournalWriter | None = None
_pruned_foreign = 0


def journal_dir() -> str | None:
    """Where the journal goes: the configured dir, else
    ``$VELES_SIMD_JOURNAL_DIR``, else None (disarmed)."""
    d = _configured_dir
    if d is not None:
        return d or None
    env = os.environ.get(JOURNAL_DIR_ENV, "").strip()
    return env or None


def armed() -> bool:
    """Is the journal writing?  One attribute + env check — the
    disarmed cost on every decision event."""
    return journal_dir() is not None


def configure_dir(path: str | None) -> None:
    """Runtime override of ``$VELES_SIMD_JOURNAL_DIR`` (pass ``""`` to
    restore the environment lookup, None is the same).  Wired to
    ``obs.configure(journal_dir=...)``.  Changing the destination
    closes the current writer; the next :func:`emit` reopens in the
    new pack."""
    global _configured_dir, _writer
    with _lock:
        _configured_dir = str(path) if path is not None else None
        if _writer is not None:
            _writer.close()
            _writer = None


def set_replica(name: str | None) -> None:
    """Stamp this process's replica identity into every subsequent
    record (subprocess replicas call this with their spec name; the
    router process usually leaves it None)."""
    global _replica
    _replica = str(name) if name else None


def replica() -> str | None:
    return _replica


def _ensure_writer() -> JournalWriter | None:
    global _writer
    d = journal_dir()
    if d is None:
        return None
    w = _writer
    if w is not None and w.dir == d:
        return w
    with _lock:
        if _writer is None or _writer.dir != d:
            if _writer is not None:
                _writer.close()
            _writer = JournalWriter(d)
        return _writer


def emit(kind: str, fields: dict | None = None, **top) -> bool:
    """Append one ``kind``-tagged record when armed (no-op returning
    False otherwise).  The writer stamps schema/seq/clocks/pid/replica;
    ``fields`` is the JSON-native payload (landing under ``data``);
    ``top`` adds promoted top-level keys (``op``/``decision``).  Never
    raises."""
    try:
        w = _ensure_writer()
        if w is None:
            return False
        rec = {"kind": str(kind)}
        rec.update(top)
        rec["data"] = dict(fields) if fields else {}
        return w.append(rec)
    except Exception:  # noqa: BLE001
        return False


def emit_decision(op: str, decision: str, fields: dict) -> bool:
    """The ``obs.record_decision`` tap: one ``decision`` record per
    event.  Every breaker transition, replica lifecycle edge, SLO
    breach, fault/retry/degrade step, autotune/artifact outcome, and
    chaos phase already flows through that funnel — so it flows
    through here."""
    return emit("decision", fields, op=str(op), decision=str(decision))


def cursor() -> dict | None:
    """The live writer's :meth:`JournalWriter.cursor` (None while
    disarmed or before the first record)."""
    w = _writer
    return w.cursor() if w is not None else None


def tail(n: int = TAIL_KEEP) -> list:
    """The live writer's in-memory tail (empty while disarmed)."""
    w = _writer
    return w.tail(n) if w is not None else []


def stats(now: float | None = None) -> dict:
    """Journal health for ``obs.snapshot()`` and the signals bundle:
    armed flag, record/drop/rotation counts, and ``lag_s`` — seconds
    since the last record landed (None before the first)."""
    w = _writer
    out = {"armed": armed(), "dir": journal_dir(),
           "records": 0, "dropped": 0, "rotations": 0, "pruned": 0,
           "pruned_foreign": _pruned_foreign, "lag_s": None}
    if w is None:
        return out
    s = w.stats()
    out.update({"records": s["records"], "dropped": s["dropped"],
                "rotations": s["rotations"], "pruned": s["pruned"],
                "bytes": s["bytes"], "segment": s["segment"]})
    if s["last_t_mono"] is not None:
        t = now if now is not None else time.monotonic()
        out["lag_s"] = max(0.0, t - s["last_t_mono"])
    return out


def _pid_alive(pid: int) -> bool:
    """Liveness probe without touching the process: signal 0.  A pid we
    cannot signal for *permission* reasons exists (someone else's
    process in a shared pack) — treat it as alive; only a confirmed
    ``ProcessLookupError`` counts as dead."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def prune_foreign(directory: str | None = None,
                  max_total_bytes: int | None = None,
                  live_pids=()) -> int:
    """Reclaim dead-pid segments from a shared journal pack.

    Per-writer rotation prunes own-pid files only, so segments from
    killed subprocess replicas strand on disk forever.  The group
    owner (the fleet collector thread) calls this to delete dead
    writers' segments oldest-first (by mtime) until the *pack* total
    is back under the ``$VELES_SIMD_JOURNAL_MAX_TOTAL_BYTES`` budget.
    Never touches this process's own files, any pid in ``live_pids``,
    or any pid that answers a signal-0 probe.  Returns the number of
    files unlinked (also counted in ``stats()['pruned_foreign']``).
    Never raises."""
    global _pruned_foreign
    try:
        d = directory if directory is not None else journal_dir()
        if d is None:
            return 0
        budget = int(max_total_bytes) if max_total_bytes \
            else _env_int(MAX_TOTAL_BYTES_ENV, DEFAULT_MAX_TOTAL_BYTES)
        protected = {os.getpid()}
        protected.update(int(p) for p in live_pids)
        entries = []  # (mtime, path, size, pid)
        total = 0
        try:
            names = os.listdir(d)
        except OSError:
            return 0
        for name in names:
            m = _FILE_RE.match(name)
            if not m:
                continue
            path = os.path.join(d, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            total += st.st_size
            pid = int(m.group(1))
            if pid in protected:
                continue
            entries.append((st.st_mtime, path, st.st_size, pid))
        if total <= budget:
            return 0
        alive_cache: dict = {}
        entries.sort()
        pruned = 0
        for _, path, size, pid in entries:
            if total <= budget:
                break
            if pid not in alive_cache:
                alive_cache[pid] = _pid_alive(pid)
            if alive_cache[pid]:
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            pruned += 1
        if pruned:
            with _lock:
                _pruned_foreign += pruned
        return pruned
    except Exception:  # noqa: BLE001 — reclamation never takes down
        return 0  # the collector thread that calls it


def _reset_for_tests() -> None:
    """Close and forget the process writer (files stay on disk)."""
    global _writer, _replica, _pruned_foreign
    with _lock:
        if _writer is not None:
            _writer.close()
            _writer = None
        _pruned_foreign = 0
    _replica = None


# -- the reader (offline reconstruction; tools/obs_query.py) -----------------

def discover(directory: str) -> list:
    """Journal files in a pack directory, sorted ``(pid, segment)`` —
    one process's segments stay contiguous, different replicas' files
    interleave deterministically."""
    found = []
    try:
        for name in os.listdir(directory):
            m = _FILE_RE.match(name)
            if m:
                found.append((int(m.group(1)), int(m.group(2)),
                              os.path.join(directory, name)))
    except OSError:
        return []
    found.sort()
    return [path for _, _, path in found]


def read_file(path: str) -> tuple:
    """``(records, skipped)`` from one journal file.  Corrupt lines and
    the torn tail a killed replica leaves behind are *counted*, never
    fatal — every parseable record is recovered (the TuneCache
    discipline).  A complete-JSON final line without its newline still
    counts as a record (the write made it; only the flush of the
    newline boundary is in doubt on some filesystems)."""
    records, skipped = [], 0
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], 1
    for raw in data.split(b"\n"):
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw.decode("utf-8", errors="strict"))
        except (ValueError, UnicodeDecodeError):
            skipped += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            skipped += 1
    return records, skipped


def read_pack(directory: str) -> tuple:
    """``(records, skipped)`` across every journal file in the pack,
    merged into one fleet timeline ordered by wall clock (the only
    clock shared across processes; ties break on ``(pid, seq)``).
    Each record gains a ``_file`` provenance key."""
    merged, skipped = [], 0
    for path in discover(directory):
        recs, skip = read_file(path)
        skipped += skip
        base = os.path.basename(path)
        for r in recs:
            r["_file"] = base
            merged.append(r)
    merged.sort(key=lambda r: (r.get("t_wall", 0.0),
                               r.get("pid", 0), r.get("seq", 0)))
    return merged, skipped
