"""Device timing utilities (the framework's profiling layer).

The reference's only profiling is ``std::chrono`` around synchronous CPU
calls (``/root/reference/tests/benchmark.inc:74-107``).  On an
asynchronous accelerator runtime that pattern silently measures dispatch,
not compute — ``block_until_ready`` is not reliable through remote-relay
PJRT transports either (observed on the axon tunnel: a 3-second
convolution "completed" in 40µs).

:func:`device_time` therefore uses **pipelined burst timing**: issue the
op once vs K times back-to-back (single-stream TPU execution serializes
them), force completion with a scalar fetch, and report
``(t_K - t_1) / (K - 1)`` — the marginal per-op device time, with
dispatch latency and the fetch round-trip subtracted out.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["device_time", "host_time"]


def _sync(out):
    """Force completion of `out` (any jax array / pytree leaf)."""
    import jax

    leaves = jax.tree.leaves(out)
    last = leaves[-1]
    np.asarray(last.ravel()[-1:] if hasattr(last, "ravel") else last)


def device_time(fn, *, burst: int = 8, repeats: int = 3,
                warmup: int = 2) -> float:
    """Marginal per-call device time of ``fn`` (which must return a jax
    array or pytree of them)."""
    for _ in range(warmup):
        _sync(fn())

    def burst_time(k):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = None
            for _ in range(k):
                out = fn()
            _sync(out)
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = burst_time(1)
    tk = burst_time(burst)
    per_op = (tk - t1) / (burst - 1)
    # degenerate case (dispatch-dominated tiny op): fall back to t1
    return max(per_op, 1e-9) if per_op > 0 else t1


def host_time(fn, *, repeats: int = 3, warmup: int = 1) -> float:
    """Best-of-N wall time for a synchronous host function."""
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best
