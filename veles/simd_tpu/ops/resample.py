"""Sample-rate conversion: polyphase rational resampling + Fourier method.

NEW capability beyond the reference: the reference's filtering stack
(``/root/reference/src/convolve.c``) stops at same-rate FIR; rate
conversion is the next classic DSP need (decimate a sensor stream,
upsample before correlation against a higher-rate template).

TPU-first design: the entire polyphase up-filter-down cascade is ONE
``lax.conv_general_dilated`` call — ``lhs_dilation=up`` zero-stuffs,
``window_strides=down`` decimates, and XLA's conv lowering never
materializes the zero-stuffed signal (the polyphase decomposition is
what the compiler's dilated-conv tiling computes).  The anti-aliasing
FIR is a host-side windowed-sinc constant.

Conventions (match scipy.signal.resample_poly / resample so users can
port): output length ``ceil(n * up / down)``, group delay compensated
(centered odd-length filter), DC gain exactly ``up``-compensated.

Oracle twins are float64 NumPy implementing the textbook definitions
directly (explicit zero-stuffing, full convolve, slice) — deliberately a
different algorithm than the dilated conv, so the cross-validation is
meaningful (the reference's two-implementations discipline,
``/root/reference/tests/matrix.cc:94-98``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import on_tpu, resolve_simd
from veles.simd_tpu.runtime import precision as prx

__all__ = [
    "design_lowpass", "resample_poly", "resample_poly_na", "upfirdn",
    "upfirdn_na", "upsample",
    "decimate", "resample_fourier", "resample_fourier_na",
    "resample_length",
    "resample_stream_plan", "resample_stream_step",
    "resample_stream_step_na", "resample_stream_oracle",
]


def design_lowpass(num_taps: int, cutoff: float) -> np.ndarray:
    """Windowed-sinc (Hamming) linear-phase lowpass FIR.

    ``cutoff`` in (0, 1] is the passband edge as a fraction of the
    Nyquist frequency.  Unit DC gain.  Host-side float64.
    """
    if num_taps < 1:
        raise ValueError(f"num_taps must be >= 1, got {num_taps}")
    if not 0.0 < cutoff <= 1.0:
        raise ValueError(f"cutoff must be in (0, 1], got {cutoff}")
    m = np.arange(num_taps) - (num_taps - 1) / 2.0
    h = cutoff * np.sinc(cutoff * m)
    h *= np.hamming(num_taps)
    return h / h.sum()


def resample_length(n: int, up: int, down: int) -> int:
    """Output length of :func:`resample_poly`: ``ceil(n * up / down)``."""
    return -((-n * up) // down)


def _resample_taps(up: int, down: int, num_taps) -> np.ndarray:
    """Anti-aliasing filter for an up/down conversion: cutoff at the
    tighter of the two Nyquists, gain ``up`` (to restore amplitude after
    zero-stuffing), odd length (integer group delay)."""
    q = max(up, down)
    if num_taps is None:
        num_taps = 20 * q + 1  # 10 zero-crossings per side, scipy-like
    if num_taps % 2 == 0:
        num_taps += 1  # odd taps -> integer group delay, exact centering
    return up * design_lowpass(num_taps, 1.0 / q)


def _normalize_resample_args(n, up, down, taps):
    """Shared argument pipeline for the single-chip and sharded paths:
    gcd-reduce the rate, validate, resolve default taps.  Returns
    ``(up, down, taps_float64)`` (taps is None only for the 1/1 rate).
    """
    up, down = int(up), int(down)
    if up < 1 or down < 1:
        raise ValueError(f"up and down must be >= 1, got {up}, {down}")
    g = math.gcd(up, down)
    up, down = up // g, down // g
    if n == 0:
        raise ValueError("empty signal")
    if up == 1 and down == 1:
        return up, down, None
    if taps is None:
        taps = _resample_taps(up, down, None)
    taps = np.asarray(taps, np.float64)
    if taps.ndim != 1 or len(taps) % 2 == 0:
        raise ValueError(
            f"taps must be a 1D odd-length filter, got shape {taps.shape}")
    return up, down, taps


@functools.partial(obs.instrumented_jit,
                   static_argnames=("up", "down", "out_len", "pad"))
def _resample_conv(x, taps, up, down, out_len, pad=None):
    """The polyphase core: ONE dilated/strided correlation.

    ``pad`` overrides the (left, right) dilated-domain padding — the
    sharded path (``parallel.sharded_resample_poly``) uses a negative
    left pad to crop its halo-extended block into global alignment
    while running this exact same kernel.
    """
    k = taps.shape[0]
    n = x.shape[-1]
    dilated = (n - 1) * up + 1
    if pad is None:
        pad_l = (k - 1) // 2  # group delay of the centered odd filter
        # right padding sized so the final stride window (output index
        # out_len - 1, offset (out_len-1)*down .. +k-1) stays in bounds
        pad = (pad_l, max(0, (out_len - 1) * down + k - pad_l - dilated))
    rhs = taps[::-1].reshape((1, 1, k))
    if up > 1 and down > 1 and not on_tpu():
        # XLA's CPU lowering miscompiles lhs_dilation combined with
        # window_strides > 1 for small filters (observed jax 0.4.37:
        # k <= ~256 returns the UNSTRIDED output's prefix — strided
        # result != stride-1 result [::down] from the SAME call, an
        # internal inconsistency; k >= ~481 takes a different, correct
        # path).  Off-TPU, zero-stuff explicitly (concat/reshape — no
        # scatter, see iir._delay) and stride a plain conv: identical
        # MAC count per output, one extra n*up buffer.  TPU keeps the
        # fused form — every resample smoke is green on real hardware
        # (BASELINE.md round 5) and it never materializes the stuffed
        # signal.
        stuffed = jnp.concatenate(
            [x[..., None], jnp.zeros(x.shape + (up - 1,), x.dtype)],
            axis=-1).reshape(x.shape[:-1] + (n * up,))[..., :dilated]
        lhs = stuffed.reshape((-1, 1, dilated))
        lhs_dil = (1,)
    else:
        lhs = x.reshape((-1, 1, n))
        lhs_dil = (up,)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(down,), padding=[pad],
        lhs_dilation=lhs_dil, precision=prx.HIGHEST)
    return out.reshape(x.shape[:-1] + (out.shape[-1],))[..., :out_len]


def resample_poly(x, up: int, down: int, taps=None, simd=None):
    """Rational-rate resampling by ``up/down`` with polyphase filtering.

    ``x[..., n] -> [..., ceil(n * up / down)]``.  ``taps`` overrides the
    default windowed-sinc anti-aliasing filter (pass a host array with
    DC gain ``up`` and odd length for transparent substitution).

    Edge semantics: the signal is ZERO-EXTENDED beyond its support —
    output samples within half a filter length of either end see zeros
    outside the signal, so they roll off toward the edges (identical on
    the XLA path, the oracle, and the sharded path, which all extend
    the same way; pinned by ``tests/test_resample.py``'s full-range
    edge test).  This matches ``scipy.signal.resample_poly``'s default
    zero-padding; the remaining difference from scipy is the
    anti-aliasing filter design (windowed-sinc Hamming here vs scipy's
    Kaiser), which shifts interior values by ~1e-3 — pass scipy's taps
    via ``taps=`` for exact scipy parity everywhere.
    """
    up, down, taps = _normalize_resample_args(np.shape(x)[-1], up, down,
                                              taps)
    if up == 1 and down == 1:
        return (jnp.asarray(x, jnp.float32)
                if resolve_simd(simd, op="resample")
                else np.asarray(x, np.float32))
    n = np.shape(x)[-1]
    out_len = resample_length(n, up, down)
    if resolve_simd(simd, op="resample"):
        with obs.span("resample_poly.dispatch", up=up, down=down):
            return _resample_conv(jnp.asarray(x, jnp.float32),
                                  jnp.asarray(taps, jnp.float32),
                                  up, down, out_len)
    return resample_poly_na(x, up, down, taps).astype(np.float32)


def resample_poly_na(x, up: int, down: int, taps=None):
    """Float64 oracle twin: explicit zero-stuff, full convolve, stride."""
    up, down = int(up), int(down)
    g = math.gcd(up, down)
    up, down = up // g, down // g
    x = np.asarray(x, np.float64)
    n = x.shape[-1]
    if up == 1 and down == 1:
        return x.copy()
    if taps is None:
        taps = _resample_taps(up, down, None)
    taps = np.asarray(taps, np.float64)
    pad = (len(taps) - 1) // 2
    out_len = resample_length(n, up, down)
    full = _zero_stuff_convolve(x, taps, up)
    # centered: drop the group delay, then stride
    y = full[..., pad:][..., ::down]
    out = np.zeros(x.shape[:-1] + (out_len,), np.float64)
    m = min(out_len, y.shape[-1])
    out[..., :m] = y[..., :m]
    return out


def upfirdn(h, x, up: int = 1, down: int = 1, simd=None):
    """The raw polyphase primitive (scipy's ``upfirdn``): upsample by
    ``up`` (zero-stuffing), FIR filter with ``h``, downsample by
    ``down`` — WITHOUT :func:`resample_poly`'s group-delay centering
    or gcd reduction.  Output length ``ceil(((n-1)*up + len(h)) /
    down)`` (the full convolution span, strided), exactly scipy's.

    Runs as the same single dilated/strided device correlation as
    :func:`resample_poly` with the padding overridden to the
    uncentered full span.
    """
    up, down = int(up), int(down)
    if up < 1 or down < 1:
        raise ValueError(f"up and down must be >= 1, got {up}, {down}")
    h = np.asarray(h, np.float64)
    if h.ndim != 1 or len(h) == 0:
        raise ValueError("h must be a non-empty 1D filter")
    n = np.shape(x)[-1]
    if n == 0:
        raise ValueError("empty signal")
    k = len(h)
    dilated = (n - 1) * up + 1
    out_len = -(-(dilated + k - 1) // down)
    if resolve_simd(simd, op="resample"):
        # full output span: left pad k-1 (conv start), right pad to
        # cover the last strided window
        pad = (k - 1, max(0, (out_len - 1) * down + k
                          - (k - 1) - dilated))
        with obs.span("upfirdn.dispatch", up=up, down=down):
            return _resample_conv(jnp.asarray(x, jnp.float32),
                                  jnp.asarray(h, jnp.float32), up, down,
                                  out_len, pad=pad)
    return upfirdn_na(h, x, up, down).astype(np.float32)


def _zero_stuff_convolve(x, h, up: int):
    """Shared float64 oracle core: zero-stuff ``x`` by ``up`` and FULL
    convolve each row with ``h`` (both the centered resample oracle and
    the raw upfirdn oracle stride this)."""
    x = np.asarray(x, np.float64)
    n = x.shape[-1]
    stuffed = np.zeros(x.shape[:-1] + ((n - 1) * up + 1,), np.float64)
    stuffed[..., ::up] = x
    flat = stuffed.reshape(-1, stuffed.shape[-1])
    full = np.stack([np.convolve(row, h) for row in flat])
    return full.reshape(x.shape[:-1] + (full.shape[-1],))


def upfirdn_na(h, x, up: int = 1, down: int = 1):
    """Float64 oracle twin of :func:`upfirdn` (explicit zero-stuff,
    full convolve, stride)."""
    h = np.asarray(h, np.float64)
    return _zero_stuff_convolve(x, h, int(up))[..., ::int(down)]


# --------------------------------------------------------------------------
# streaming resample — the pipeline compiler's state-export hooks
# --------------------------------------------------------------------------

def resample_stream_plan(up: int, down: int, block: int,
                         taps=None) -> dict:
    """Host-side streaming plan for a rational resampler stage.

    The one-shot :func:`resample_poly` samples the zero-stuffed full
    convolution ``full`` on the centered grid ``full[pad + j*down]``
    (``pad = (taps - 1) // 2``).  Streaming in fixed ``block``-sample
    input chunks with an ``hist``-sample input-history carry
    (zero-seeded — one-shot's left zero pad), each chunk determines
    exactly ``out_block = block * up / down`` NEW grid samples, but
    the centered filter looks ``pad`` stuffed samples ahead, so the
    stream runs ``preroll = pad // down`` output samples EARLY: the
    emitted grid starts at ``full[pad - preroll*down]`` — the filter's
    left transient — and streamed output ``m`` equals one-shot output
    ``m - preroll`` from ``m = preroll`` on.  Returns the plan dict
    (``up/down/taps/hist/out_block/preroll/pad`` — ``pad`` is the
    dilated-domain (left, right) override for the shared
    :func:`resample_stream_step` conv, derived so the step's output
    window lands exactly on the emitted grid slice).

    Requires ``block * up % down == 0`` (a fixed-shape step needs a
    constant per-block emission) and a block at least as long as the
    carried history (the halo must be fully refreshed every step).
    """
    up, down, taps = _normalize_resample_args(int(block), up, down,
                                              taps)
    if up == 1 and down == 1:
        raise ValueError("1/1 rate needs no resample stage")
    block = int(block)
    if (block * up) % down != 0:
        raise ValueError(
            f"block {block} * up {up} must be divisible by down "
            f"{down} (fixed-shape streaming needs a constant "
            "per-block emission)")
    k = len(taps)
    pad_c = (k - 1) // 2
    hist = -(-(k - 1) // up)          # ceil: halo covers the filter
    if block < hist:
        raise ValueError(
            f"block {block} shorter than the {hist}-sample carry for "
            f"{k} taps (choose a larger block or shorter filter)")
    out_block = block * up // down
    preroll = pad_c // down
    # emitted grid within the halo-extended chunk's full convolution:
    # first sample at full_ext[r_start], stepping by down (constant
    # for every block — the halo shifts with the stream)
    r_start = pad_c + hist * up - preroll * down
    pad_l = k - 1 - r_start
    dilated_ext = (hist + block - 1) * up + 1
    pad_r = max(0, (out_block - 1) * down + k - pad_l - dilated_ext)
    return {"up": up, "down": down, "taps": taps, "hist": hist,
            "out_block": out_block, "preroll": preroll,
            "pad": (pad_l, pad_r)}


def resample_stream_step(x_ext, taps, plan: dict):
    """TRACEABLE one-block resample step: ``x_ext[..., hist + block]``
    (carry + new chunk) -> ``[..., out_block]`` on the streaming grid
    of :func:`resample_stream_plan`.  Runs the same single
    dilated/strided ``obs.instrumented_jit`` correlation core as
    :func:`resample_poly`, so it inlines into a fused outer jit."""
    return _resample_conv(x_ext, taps, plan["up"], plan["down"],
                          plan["out_block"], pad=plan["pad"])


def resample_stream_step_na(x_ext, plan: dict):
    """NumPy float64 oracle twin of :func:`resample_stream_step`
    (the pipeline's stage-by-stage degradation path): the same
    emitted-grid slice of the zero-stuffed full convolution, derived
    from the SAME plan — the grid math lives here, next to the pad
    derivation it mirrors, so the pair cannot drift apart."""
    x_ext = np.asarray(x_ext, np.float64)
    full = _zero_stuff_convolve(x_ext, plan["taps"], plan["up"])
    r_start = len(plan["taps"]) - 1 - plan["pad"][0]
    need = r_start + (plan["out_block"] - 1) * plan["down"] + 1
    if need > full.shape[-1]:
        wpad = ([(0, 0)] * (full.ndim - 1)
                + [(0, need - full.shape[-1])])
        full = np.pad(full, wpad)
    return full[..., r_start::plan["down"]][..., :plan["out_block"]]


def resample_stream_oracle(x, plan: dict):
    """NumPy float64 one-shot oracle of the STREAMING grid: what
    chunked :func:`resample_stream_step` calls emit over the whole
    signal, computed whole-signal (the pipeline parity reference and
    the stage-by-stage degradation path)."""
    x = np.asarray(x, np.float64)
    up, down = plan["up"], plan["down"]
    n = x.shape[-1]
    if (n * up) % down != 0:
        raise ValueError("signal length must be whole blocks")
    total = n * up // down
    k = len(plan["taps"])
    pad_c = (k - 1) // 2
    start = pad_c - plan["preroll"] * down
    full = _zero_stuff_convolve(x, plan["taps"], up)
    need = start + (total - 1) * down + 1
    if need > full.shape[-1]:
        wpad = [(0, 0)] * (full.ndim - 1) + [(0, need - full.shape[-1])]
        full = np.pad(full, wpad)
    return full[..., start::down][..., :total]


def upsample(x, factor: int, taps=None, simd=None):
    """Integer-rate interpolation: ``resample_poly(x, factor, 1)``."""
    return resample_poly(x, factor, 1, taps=taps, simd=simd)


def decimate(x, factor: int, taps=None, ftype: str = "fir",
             zero_phase: bool = True, simd=None):
    """Integer-rate anti-aliased decimation.

    ``ftype='fir'`` (default here): polyphase
    ``resample_poly(x, 1, factor)`` — one strided device conv, the
    TPU-native formulation (``zero_phase`` has no effect; the centered
    linear-phase FIR already has none).  ``ftype='iir'``: scipy
    ``decimate``'s default path — an order-8 Chebyshev-I (0.05 dB)
    lowpass at ``0.8/factor`` Nyquist, applied zero-phase
    (``sosfiltfilt``) or causally (``sosfilt``), then sliced
    ``[..., ::factor]``.  NOTE scipy defaults to 'iir'; the default
    differs here because the polyphase form does the anti-aliasing
    work at the DECIMATED rate.
    """
    factor = int(factor)
    if ftype == "fir":
        return resample_poly(x, 1, factor, taps=taps, simd=simd)
    if ftype != "iir":
        raise ValueError(f"ftype must be 'fir' or 'iir', got {ftype!r}")
    if factor < 1:
        raise ValueError("factor must be >= 1")
    if taps is not None:
        raise ValueError("taps only applies to ftype='fir'")
    from veles.simd_tpu.ops import iir as _iir

    sos = _iir.cheby1(8, 0.05, 0.8 / factor)
    if zero_phase:
        y = _iir.sosfiltfilt(sos, x, simd=simd)
    else:
        y = _iir.sosfilt(sos, x, simd=simd)
    return y[..., ::factor]


@functools.partial(obs.instrumented_jit, static_argnames=("num",))
def _resample_fourier_xla(x, num):
    n = x.shape[-1]
    spec = jnp.fft.rfft(x, axis=-1)
    bins_in = n // 2 + 1
    bins_out = num // 2 + 1
    if num < n:
        kept = spec[..., :bins_out]
        # the output Nyquist bin folds the kept ±f_nyq pair: their joint
        # time contribution is 2*Re(X[num/2])*(-1)^t (X and conj(X))
        if num % 2 == 0:
            kept = kept.at[..., -1].set(2 * kept[..., -1].real + 0j)
    elif num == n:
        kept = spec
    else:
        pad = [(0, 0)] * (spec.ndim - 1) + [(0, bins_out - bins_in)]
        kept = jnp.pad(spec, pad)
        # the input's even-n Nyquist bin becomes an interior bin whose
        # Hermitian partner is now explicit in the implied full
        # spectrum: split its (real) weight between the ±f pair
        if n % 2 == 0:
            kept = kept.at[..., bins_in - 1].set(
                kept[..., bins_in - 1] * 0.5)
    return (jnp.fft.irfft(kept, num, axis=-1)
            * (num / n)).astype(jnp.float32)


def resample_fourier(x, num: int, simd=None):
    """Fourier-domain resampling to exactly ``num`` samples (the
    scipy.signal.resample method): truncate or zero-pad the spectrum.
    Exact for signals bandlimited below the output Nyquist; assumes
    periodicity (use :func:`resample_poly` for streaming data)."""
    num = int(num)
    if num < 1:
        raise ValueError(f"num must be >= 1, got {num}")
    if np.shape(x)[-1] == 0:
        raise ValueError("empty signal")
    if resolve_simd(simd, op="resample"):
        return _resample_fourier_xla(jnp.asarray(x, jnp.float32), num)
    return resample_fourier_na(x, num).astype(np.float32)


def resample_fourier_na(x, num: int):
    """Float64 oracle twin of :func:`resample_fourier`."""
    x = np.asarray(x, np.float64)
    n = x.shape[-1]
    spec = np.fft.rfft(x, axis=-1)
    bins_in, bins_out = n // 2 + 1, num // 2 + 1
    if num < n:
        kept = spec[..., :bins_out].copy()
        if num % 2 == 0:  # fold the kept ±f_nyq pair (see XLA twin)
            kept[..., -1] = 2 * kept[..., -1].real
    elif num == n:
        kept = spec
    else:
        kept = np.zeros(spec.shape[:-1] + (bins_out,), np.complex128)
        kept[..., :bins_in] = spec
        if n % 2 == 0:  # old Nyquist becomes interior: split its weight
            kept[..., bins_in - 1] *= 0.5
    return np.fft.irfft(kept, num, axis=-1) * (num / n)
