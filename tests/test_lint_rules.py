"""The spectral route-dispatch lint rule (PR 5): every *_ROUTES table
entry must reach an instrumented_jit core, and public dispatchers must
index the table inside a ``with obs.span(...)`` scope."""

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402

GOOD = '''
import functools
from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core_xla(x):
    return x


def _run_xla(x):
    return _core_xla(x)


def _run_pallas(x):
    return _pk.stft_pallas(x, 256, 128)


_STFT_ROUTES = {"xla_fft": _run_xla, "pallas_fused": _run_pallas}


def stft(x, route):
    with obs.span("stft.dispatch", route=route):
        return _STFT_ROUTES[route](x)
'''

UNINSTRUMENTED = '''
from veles.simd_tpu import obs


def _run_raw(x):
    return x + 1


_STFT_ROUTES = {"raw": _run_raw}


def stft(x, route):
    with obs.span("stft.dispatch"):
        return _STFT_ROUTES[route](x)
'''

UNSPANNED = '''
import functools
from veles.simd_tpu import obs


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core(x):
    return x


def _run(x):
    return _core(x)


_STFT_ROUTES = {"xla_fft": _run}


def stft(x, route):
    return _STFT_ROUTES[route](x)
'''

NO_TABLES = '''
def stft(x):
    return x
'''


def _errors(src):
    return lint.spectral_dispatch_errors(ast.parse(src), "spectral.py")


def test_good_module_passes():
    assert _errors(GOOD) == []


def test_uninstrumented_runner_flagged():
    errs = _errors(UNINSTRUMENTED)
    assert any("instrumented_jit" in e for e in errs)


def test_unspanned_dispatch_flagged():
    errs = _errors(UNSPANNED)
    assert any("obs.span" in e for e in errs)


def test_missing_tables_flagged():
    errs = _errors(NO_TABLES)
    assert any("_ROUTES" in e for e in errs)


def test_real_spectral_module_is_clean():
    src = (REPO / "veles/simd_tpu/ops/spectral.py").read_text()
    assert lint.spectral_dispatch_errors(
        ast.parse(src), "veles/simd_tpu/ops/spectral.py") == []
