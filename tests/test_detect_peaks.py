"""Tests for veles.simd_tpu.ops.detect_peaks.

Port of ``tests/detect_peaks.cc``: analytic sin() peak positions
(``:43-75``), adversarial flat-signal cases (``:77-100``), XLA-vs-oracle
over the simd flag (``:102``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import detect_peaks as dp

RNG = np.random.RandomState(41)


def test_sin_peaks_analytic():
    """Peaks of sin() land at π/2 + 2πk (tests/detect_peaks.cc:43-75)."""
    t = np.arange(0, 4 * np.pi, 0.01, dtype=np.float32)
    x = np.sin(t)
    pos, vals = dp.detect_peaks(x, dp.ExtremumType.MAXIMUM, simd=True)
    expected = [np.pi / 2, np.pi / 2 + 2 * np.pi]
    assert len(pos) == 2
    for p, e in zip(pos, expected):
        assert abs(t[p] - e) < 0.02
        assert abs(vals[list(pos).index(p)] - 1.0) < 1e-3


def test_min_and_both():
    t = np.arange(0, 4 * np.pi, 0.01, dtype=np.float32)
    x = np.sin(t)
    pos_min, _ = dp.detect_peaks(x, dp.ExtremumType.MINIMUM, simd=True)
    assert len(pos_min) == 2
    pos_both, _ = dp.detect_peaks(x, dp.ExtremumType.BOTH, simd=True)
    assert len(pos_both) == 4


def test_flat_signal_no_peaks():
    """Plateaus are not peaks — strict inequality
    (tests/detect_peaks.cc:77-100)."""
    x = np.zeros(64, np.float32)
    pos, vals = dp.detect_peaks(x, dp.ExtremumType.BOTH, simd=True)
    assert len(pos) == 0
    x2 = np.array([0, 1, 1, 0], np.float32)  # flat-topped: no strict peak
    pos2, _ = dp.detect_peaks(x2, dp.ExtremumType.BOTH, simd=True)
    assert len(pos2) == 0


def test_endpoints_never_peaks():
    x = np.array([5.0, 1.0, 4.0], np.float32)
    pos, vals = dp.detect_peaks(x, dp.ExtremumType.BOTH, simd=True)
    np.testing.assert_array_equal(pos, [1])
    np.testing.assert_allclose(vals, [1.0])


@pytest.mark.parametrize("type", [dp.ExtremumType.MAXIMUM,
                                  dp.ExtremumType.MINIMUM,
                                  dp.ExtremumType.BOTH])
def test_xla_vs_oracle(type):
    x = RNG.randn(997).astype(np.float32)
    pos_x, val_x = dp.detect_peaks(x, type, simd=True)
    pos_na, val_na = dp.detect_peaks_na(x, type)
    np.testing.assert_array_equal(pos_x, pos_na)
    np.testing.assert_allclose(val_x, val_na)


def test_fixed_shape_variant():
    """The jit-composable (positions, values, count) form."""
    x = np.array([0, 2, 0, -3, 0, 5, 4, 6, 1], np.float32)
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.BOTH,
                                             max_peaks=6)
    pos, vals = np.asarray(pos), np.asarray(vals)
    assert int(count) == 5
    np.testing.assert_array_equal(pos[:5], [1, 3, 5, 6, 7])
    np.testing.assert_allclose(vals[:5], [2, -3, 5, 4, 6])
    np.testing.assert_array_equal(pos[5:], [-1])


def test_fixed_shape_batched():
    x = RNG.randn(4, 257).astype(np.float32)
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.MAXIMUM)
    assert pos.shape == vals.shape == (4, 255)  # worst case n-2
    for b in range(4):
        pos_na, val_na = dp.detect_peaks_na(x[b], dp.ExtremumType.MAXIMUM)
        c = int(count[b])
        assert c == len(pos_na)
        np.testing.assert_array_equal(np.asarray(pos)[b, :c], pos_na)


def test_fixed_truncation():
    """More peaks than max_peaks: first max_peaks kept, count reports all."""
    x = np.tile(np.array([0.0, 1.0], np.float32), 20)  # alternating
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.BOTH,
                                             max_peaks=4)
    assert int(count) == 38
    np.testing.assert_array_equal(np.asarray(pos), [1, 2, 3, 4])


def test_fixed_default_capacity_holds_alternating():
    """Default max_peaks must fit the alternating worst case (n-2)."""
    x = np.tile(np.array([0.0, 1.0], np.float32), 20)
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.BOTH)
    assert int(count) == 38
    assert int((np.asarray(pos) >= 0).sum()) == 38


def test_fixed_overlarge_max_peaks_honored():
    """A caller-supplied capacity is honored exactly (not clamped to n-2),
    so jitted pipelines keep one output shape across signal lengths; the
    impossible slots are always empty."""
    x = np.array([0, 2, 0], np.float32)
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.BOTH,
                                             max_peaks=50)
    assert pos.shape == (50,) and int(count) == 1
    assert int(pos[0]) == 1 and np.all(np.asarray(pos[1:]) == -1)
    assert np.all(np.asarray(vals[1:]) == 0)


def test_contract_violation():
    with pytest.raises(ValueError):
        dp.detect_peaks(np.zeros(2, np.float32), simd=True)
    with pytest.raises(ValueError):
        dp.detect_peaks_na(np.zeros(1, np.float32))


def test_compaction_routes_agree():
    """The top_k route (max_peaks <= n/4) and the rank-scatter route must
    produce identical outputs for the same capacity."""
    import jax.numpy as jnp

    x = RNG.randn(3, 512).astype(np.float32)
    for t in (dp.ExtremumType.BOTH, dp.ExtremumType.MAXIMUM):
        mask = np.asarray(dp._peak_mask(jnp.asarray(x), t))
        cap = 64  # 64*4 <= 512 -> the fixed path takes top_k
        pos_tk, val_tk, cnt_tk = dp._compact_topk(
            jnp.asarray(mask), jnp.asarray(x), cap)
        rows = [dp._compact_row(jnp.asarray(mask[b]), jnp.asarray(x[b]), cap)
                for b in range(3)]
        pos_sc = np.stack([np.asarray(r[0]) for r in rows])
        val_sc = np.stack([np.asarray(r[1]) for r in rows])
        np.testing.assert_array_equal(np.asarray(pos_tk), pos_sc)
        np.testing.assert_allclose(np.asarray(val_tk), val_sc)
        np.testing.assert_array_equal(np.asarray(cnt_tk),
                                      mask.sum(axis=-1))


def test_topk_route_vs_oracle_large():
    """End-to-end top_k route (small cap, long signal) vs the oracle."""
    x = np.cumsum(RNG.randn(4096)).astype(np.float32)
    pos, vals, count = dp.detect_peaks_fixed(x, dp.ExtremumType.BOTH,
                                             max_peaks=128)
    pos_na, val_na = dp.detect_peaks_na(x, dp.ExtremumType.BOTH)
    assert int(count) == len(pos_na)
    np.testing.assert_array_equal(np.asarray(pos), pos_na[:128])
    np.testing.assert_allclose(np.asarray(vals), val_na[:128])


class TestFindPeaks:
    """scipy-style filtered peak finding + the sparse-table prominence."""

    X = np.random.RandomState(91).randn(2000).astype(np.float32)

    def test_raw_peaks_match_scipy(self):
        from scipy import signal as ss

        got, _ = dp.find_peaks(self.X)
        want, _ = ss.find_peaks(self.X)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("kw", [
        {"height": 1.0}, {"height": (0.5, 2.0)}, {"threshold": 0.3},
        {"threshold": (0.1, 2.0)}, {"distance": 20},
        {"prominence": 1.0}, {"prominence": (0.5, 3.0)},
        {"height": 0.2, "distance": 10, "prominence": 0.8},
        # height+threshold combinations: the threshold branch must
        # refilter peak_heights too (round-3 advisor finding — it kept
        # the pre-threshold length, and adding distance then crashed)
        {"height": 0.5, "threshold": 0.2},
        {"height": 0.5, "threshold": 0.2, "distance": 15},
        {"height": (0.2, 2.5), "threshold": (0.05, 3.0), "distance": 8,
         "prominence": 0.3},
    ])
    def test_filters_match_scipy(self, kw):
        from scipy import signal as ss

        got, gp = dp.find_peaks(self.X, **kw)
        want, wp = ss.find_peaks(self.X.astype(np.float64), **kw)
        np.testing.assert_array_equal(got, want)
        for key in ("peak_heights", "left_thresholds",
                    "right_thresholds", "left_bases", "right_bases"):
            if key in wp:
                assert len(gp[key]) == len(got)
                np.testing.assert_allclose(gp[key], wp[key], atol=1e-6)
        if "prominences" in wp:
            assert len(gp["prominences"]) == len(got)
            np.testing.assert_allclose(gp["prominences"],
                                       wp["prominences"], atol=1e-5)

    @pytest.mark.parametrize("use_simd", [True, False])
    def test_bases_match_scipy(self, use_simd):
        """left/right_bases (attached with prominences, as scipy does)
        match scipy's outward-walk tie semantics on both paths."""
        from scipy import signal as ss

        got, gp = dp.find_peaks(self.X, prominence=0.5, simd=use_simd)
        want, wp = ss.find_peaks(self.X.astype(np.float64),
                                 prominence=0.5)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(gp["left_bases"],
                                      wp["left_bases"])
        np.testing.assert_array_equal(gp["right_bases"],
                                      wp["right_bases"])

    def test_prominence_device_vs_scipy(self):
        from scipy import signal as ss

        peaks, _ = dp.find_peaks(self.X)
        got = np.asarray(dp.peak_prominences(self.X, peaks, simd=True))
        want = ss.peak_prominences(self.X.astype(np.float64), peaks)[0]
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_prominence_oracle_exact(self):
        from scipy import signal as ss

        peaks, _ = dp.find_peaks(self.X)
        got = dp.peak_prominences_na(self.X, peaks)
        want = ss.peak_prominences(self.X.astype(np.float64), peaks)[0]
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_prominence_textbook_case(self):
        """Hand-checkable terrain: the side summit's prominence is its
        height above the saddle separating it from the main summit."""
        x = np.array([0, 5, 2, 8, 1, 0], np.float32)
        prom = np.asarray(dp.peak_prominences(x, [1, 3], simd=True))
        np.testing.assert_allclose(prom, [3.0, 8.0], atol=1e-6)

    def test_edge_cases(self):
        empty, props = dp.find_peaks(np.zeros(10, np.float32),
                                     height=1.0)
        assert len(empty) == 0
        with pytest.raises(ValueError, match="1D"):
            dp.find_peaks(np.zeros((2, 10), np.float32))
        with pytest.raises(ValueError, match="distance"):
            dp.find_peaks(self.X, distance=0)
        with pytest.raises(ValueError, match="range"):
            dp.peak_prominences(self.X, [len(self.X)])

    def test_non_peak_index_prominence_zero(self):
        """A queried index whose neighbour is higher has prominence 0
        on BOTH paths (review regression: the oracle returned -inf)."""
        x = np.array([1.0, 3.0, 2.0], np.float32)
        np.testing.assert_allclose(dp.peak_prominences_na(x, [2]), [0.0])
        np.testing.assert_allclose(
            np.asarray(dp.peak_prominences(x, [2], simd=True)), [0.0],
            atol=1e-7)

    def test_distance_tie_break_matches_scipy(self):
        """Equal-height peaks within `distance`: scipy keeps the LATER
        one (review regression: we kept the earlier)."""
        from scipy import signal as ss

        x = np.array([0, 1, 0, 1, 0], np.float64)
        got, _ = dp.find_peaks(x.astype(np.float32), distance=3)
        want, _ = ss.find_peaks(x, distance=3)
        np.testing.assert_array_equal(got, want)

    def test_array_interval_condition(self):
        from scipy import signal as ss

        got, _ = dp.find_peaks(self.X, height=np.array([0.5, 2.0]))
        want, _ = ss.find_peaks(self.X.astype(np.float64),
                                height=np.array([0.5, 2.0]))
        # scipy broadcasts a (2,) array per-peak when exactly 2 peaks
        # remain — but as an interval otherwise; we always mean interval
        got2, _ = dp.find_peaks(self.X, height=(0.5, 2.0))
        np.testing.assert_array_equal(got, got2)
        with pytest.raises(ValueError, match="per-peak"):
            dp.find_peaks(self.X, height=np.zeros(3))

    def test_widths_match_scipy(self):
        from scipy import signal as ss

        peaks, _ = dp.find_peaks(self.X)
        for rh in (0.3, 0.5, 0.75, 0.95):
            got = [np.asarray(a) for a in
                   dp.peak_widths(self.X, peaks, rel_height=rh,
                                  simd=True)]
            want = ss.peak_widths(self.X.astype(np.float64), peaks,
                                  rel_height=rh)
            for g, w, tol in zip(got, want, (2e-3, 1e-5, 1e-3, 1e-3)):
                np.testing.assert_allclose(g, w, atol=tol)

    def test_widths_oracle_exact(self):
        from scipy import signal as ss

        peaks, _ = dp.find_peaks(self.X)
        got = dp.peak_widths_na(self.X, peaks, 0.5)
        want = ss.peak_widths(self.X.astype(np.float64), peaks,
                              rel_height=0.5)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=1e-10)

    def test_widths_textbook_case(self):
        """A symmetric triangle peak of height 4 and half-width 4 has
        FWHM 4 at rel_height 0.5."""
        x = np.r_[np.linspace(0, 4, 5), np.linspace(4, 0, 5)[1:]] \
            .astype(np.float32)
        w, h, li, ri = (np.asarray(a) for a in
                        dp.peak_widths(x, [4], rel_height=0.5,
                                       simd=True))
        np.testing.assert_allclose(w, [4.0], atol=1e-5)
        np.testing.assert_allclose(h, [2.0], atol=1e-6)

    def test_widths_contracts(self):
        with pytest.raises(ValueError, match="rel_height"):
            dp.peak_widths(self.X, [10], rel_height=1.0)
        with pytest.raises(ValueError, match="range"):
            dp.peak_widths(self.X, [len(self.X)])

    def test_width_condition_matches_scipy(self):
        from scipy import signal as ss

        for kw in ({"width": 2.0}, {"width": (1.5, 4.0)},
                   {"width": 2.0, "rel_height": 0.7},
                   {"prominence": 0.5, "width": 1.0}):
            got, gp = dp.find_peaks(self.X, **kw)
            want, wp = ss.find_peaks(self.X.astype(np.float64), **kw)
            np.testing.assert_array_equal(got, want)
            np.testing.assert_allclose(gp["widths"], wp["widths"],
                                       atol=2e-3)
            np.testing.assert_allclose(gp["left_ips"], wp["left_ips"],
                                       atol=2e-3)

    def test_width_attaches_prominences(self):
        from scipy import signal as ss

        got, gp = dp.find_peaks(self.X, width=2.0)
        want, wp = ss.find_peaks(self.X.astype(np.float64), width=2.0)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(gp["prominences"], wp["prominences"],
                                   atol=1e-5)
