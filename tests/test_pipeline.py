"""The pipeline compiler (``veles/simd_tpu/pipeline/``).

The three contracts the tentpole makes:

* **streaming-state correctness** — block-streamed output of the
  fused step matches the ONE-SHOT whole-signal oracle bit-for-block
  across block sizes, including boundaries straddling IIR ``zi``
  state, the FIR/overlap-save halo, STFT frame overlap, and resampler
  history — and across a mid-stream injected fault at
  ``pipeline.dispatch`` (the degraded block comes from the
  stage-by-stage oracle twin with exact state threading);
* **one dispatch per block** — the fused step is ONE
  ``obs.instrumented_jit`` program: exactly one compiled executable,
  one ``pipeline.dispatch`` span per block, one ``(op="pipeline")``
  resource entry, and NO per-stage op entries during steady-state
  streaming;
* **pipelines serve as first-class units** — registered pipelines
  batch through the deadline batcher with per-pipeline-class breakers
  (a poisoned pipeline class degrades while plain-op traffic stays
  "ok"), and state threads exactly through served invocations.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from veles.simd_tpu import obs, pipeline as pl, serve  # noqa: E402
from veles.simd_tpu.ops import detect_peaks as dp  # noqa: E402
from veles.simd_tpu.ops import iir  # noqa: E402
from veles.simd_tpu.ops import resample as rs  # noqa: E402
from veles.simd_tpu.runtime import breaker, faults  # noqa: E402

RNG = np.random.RandomState(11)
SOS = iir.butterworth(4, 0.25, "lowpass")


@pytest.fixture
def telemetry(monkeypatch):
    monkeypatch.setenv("VELES_SIMD_FAULT_BACKOFF", "0")
    obs.enable(compile_listeners=False)
    obs.reset()
    faults.reset_fault_history()
    yield
    obs.disable()
    obs.reset()
    faults.reset_fault_history()
    faults.set_fault_plan(None)


@pytest.fixture(autouse=True)
def _clean_breakers():
    faults.reset_fault_history()
    yield
    faults.reset_fault_history()
    faults.set_fault_plan(None)


def _rel(got, want):
    got = np.asarray(got, np.complex128)
    want = np.asarray(want, np.complex128)
    scale = float(np.max(np.abs(want))) or 1.0
    return float(np.max(np.abs(got - want)) / scale)


def _sensor_chain(name="sensor"):
    """The acceptance chain: resampler history + IIR zi + STFT
    overlap all carried (every boundary regime the satellite names)."""
    return pl.Pipeline(
        [pl.resample_poly(2, 1), pl.sosfilt(SOS), pl.stft(256, 64),
         pl.power()], name=name)


def _fir_chain(h_len=1031, name="firline"):
    h = np.random.RandomState(3).randn(h_len).astype(np.float32)
    return pl.Pipeline([pl.fir(h)], name=name)


# ---------------------------------------------------------------------------
# chain declaration / compile-time validation
# ---------------------------------------------------------------------------

class TestDeclaration:
    def test_empty_chain_rejected(self):
        with pytest.raises(ValueError, match="at least one stage"):
            pl.Pipeline([])

    def test_terminal_stage_must_be_last(self):
        with pytest.raises(ValueError, match="terminal"):
            pl.Pipeline([pl.detect_peaks(max_peaks=4),
                         pl.sosfilt(SOS)])

    def test_duplicate_stage_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            pl.Pipeline([pl.sosfilt(SOS), pl.sosfilt(SOS)])

    def test_block_must_divide_resample_rate(self):
        with pytest.raises(ValueError, match="divisible"):
            pl.Pipeline([pl.resample_poly(3, 2)]).compile(511)

    def test_stft_block_must_align_to_hop(self):
        with pytest.raises(ValueError, match="hop"):
            pl.Pipeline([pl.stft(256, 64)]).compile(100)

    def test_savgol_rejected_in_samples_mode(self):
        with pytest.raises(ValueError, match="per-row"):
            pl.Pipeline([pl.savgol(7, 2)]).compile(512)

    def test_stream_stage_cannot_follow_rows(self):
        with pytest.raises(ValueError, match="samples"):
            pl.Pipeline([pl.welch(nperseg=128),
                         pl.sosfilt(SOS)]).compile(512)

    def test_wrong_block_length_rejected_at_process(self):
        cp = pl.Pipeline([pl.sosfilt(SOS)]).compile(256)
        with pytest.raises(ValueError, match="block length"):
            cp.process(np.zeros(128, np.float32))

    def test_recompiling_pipeline_never_corrupts_earlier(self):
        # compile() takes private stage copies: a second compile at
        # another block size must not rewrite the first's geometry
        chain = pl.Pipeline([pl.resample_poly(2, 4)],
                            name="recompile")
        cp1 = chain.compile(128, name="rc128")
        x = RNG.randn(256).astype(np.float32)
        want = cp1.oracle(x)
        cp2 = chain.compile(64, name="rc64")
        got, _ = cp1.stream(x)
        assert cp1.block_len == 128 and cp2.block_len == 64
        assert got.shape == want.shape == (128,)
        assert _rel(got, want) <= 1e-5

    def test_describe_and_routes(self):
        cp = _sensor_chain().compile(512)
        d = cp.describe()
        assert d["block_len"] == 512
        assert [s["stage"] for s in d["stages"]] == [
            "resample_poly", "sosfilt", "stft", "power"]
        assert cp.routes()["stft"] in ("rdft_matmul", "xla_fft")
        assert "pipeline_compile" in {
            e["op"] for e in obs.events()} or not obs.enabled()


# ---------------------------------------------------------------------------
# streaming-state correctness: streamed == one-shot oracle
# ---------------------------------------------------------------------------

class TestStreamingParity:
    # 320 straddles nothing evenly; 512/1024 exercise pow2 blocks —
    # ≥3 block sizes per the acceptance criterion
    SENSOR_BLOCKS = (320, 512, 1024)

    @pytest.mark.parametrize("block", SENSOR_BLOCKS)
    def test_sensor_chain_matches_oracle(self, block):
        cp = _sensor_chain().compile(block)
        x = RNG.randn(5 * block).astype(np.float32)
        got, _ = cp.stream(x)
        want = cp.oracle(x)
        assert got.shape == np.asarray(want).shape
        assert _rel(got, want) <= 1e-5

    @pytest.mark.parametrize("block", (256, 512))
    def test_fir_halo_straddles_blocks(self, block):
        # h - 1 = 1030 halo > one whole 256-block: the hardest
        # carry regime (state longer than the block)
        cp = _fir_chain().compile(block)
        x = RNG.randn(6 * block).astype(np.float32)
        got, _ = cp.stream(x)
        assert _rel(got, cp.oracle(x)) <= 1e-5

    def test_fir_matches_causal_convolution(self):
        h = np.random.RandomState(4).randn(63).astype(np.float32)
        cp = pl.Pipeline([pl.fir(h)], name="fircheck").compile(128)
        x = RNG.randn(1024).astype(np.float32)
        got, _ = cp.stream(x)
        from veles.simd_tpu.ops import convolve as cv

        want = np.asarray(cv.convolve_na(
            x.astype(np.float64), h.astype(np.float64)))[:1024]
        assert _rel(got, want) <= 1e-5

    @pytest.mark.parametrize("up,down", [(2, 1), (1, 2), (3, 2),
                                         (160, 147)])
    def test_resample_streaming_grid(self, up, down):
        block = 147 * 4 if down == 147 else 588
        cp = pl.Pipeline([pl.resample_poly(up, down)],
                         name=f"rs{up}_{down}").compile(block)
        x = RNG.randn(4 * block).astype(np.float32)
        got, _ = cp.stream(x)
        want = cp.oracle(x)
        assert got.shape == want.shape == (4 * block * up // down,)
        assert _rel(got, want) <= 1e-5

    def test_resample_preroll_aligns_with_one_shot(self):
        cp = pl.Pipeline([pl.resample_poly(2, 1)],
                         name="rsalign").compile(512)
        x = RNG.randn(4096).astype(np.float32)
        got, _ = cp.stream(x)
        lat = cp.describe()["stages"][0]["latency"]
        one = np.asarray(rs.resample_poly_na(
            x.astype(np.float64), 2, 1))
        assert lat > 0
        assert _rel(got[lat:], one[:len(got) - lat]) <= 1e-5

    def test_sosfilt_zi_straddles_blocks(self):
        cp = pl.Pipeline([pl.sosfilt(SOS)], name="sosline")
        x = RNG.randn(2048).astype(np.float32)
        outs = [cp.compile(b, name=f"sos{b}").stream(x)[0]
                for b in (128, 256, 2048)]
        want = np.asarray(iir.sosfilt_na(SOS, x.astype(np.float64)))
        for got in outs:
            assert _rel(got, want) <= 1e-5

    def test_stft_overlap_straddles_blocks(self):
        chain = pl.Pipeline([pl.stft(256, 64)], name="stftline")
        x = RNG.randn(4096).astype(np.float32)
        ref = None
        for b in (64, 256, 1024):
            got, _ = chain.compile(b, name=f"stft{b}").stream(x)
            if ref is None:
                ref = got
            assert got.shape == ref.shape
            assert _rel(got, ref) <= 1e-5
        from veles.simd_tpu.ops import spectral as sp

        want = sp.stft_stream_oracle(x, 256, 64)
        assert _rel(ref, want) <= 1e-5

    def test_medfilt_welch_peaks_chain(self):
        chain = pl.Pipeline(
            [pl.medfilt(5), pl.detrend("linear"), pl.sosfilt(SOS),
             pl.welch(fs=2000.0, nperseg=256), pl.power_db(),
             pl.savgol(7, 2), pl.detect_peaks(max_peaks=16)],
            name="monitor")
        cp = chain.compile(1024)
        x = RNG.randn(4096).astype(np.float32)
        outs, _ = cp.stream(x)
        pos, vals, count = cp.oracle(x)
        g_pos, g_vals, g_count = outs
        np.testing.assert_array_equal(g_pos, pos)
        np.testing.assert_array_equal(
            np.asarray(g_count), np.asarray(count))
        assert _rel(g_vals[g_pos >= 0], vals[pos >= 0]) <= 1e-4

    def test_batched_streams_independent(self):
        cp = _sensor_chain("batched").compile(512)
        x = RNG.randn(3, 2048).astype(np.float32)
        got, _ = cp.stream(x)
        for r in range(3):
            want = cp.oracle(x[r])
            assert _rel(got[r], want) <= 1e-5

    def test_state_roundtrips_through_numpy(self):
        # a served state does a device->numpy->device round trip per
        # invocation (and a batch-marshal round trip); parity must
        # survive it
        cp = _sensor_chain("roundtrip").compile(512)
        x = RNG.randn(2048).astype(np.float32)
        state = None
        outs = []
        for i in range(4):
            out, state = cp.process(x[i * 512:(i + 1) * 512], state)
            batched = cp.batch_states([_np_state(state)], 2)
            state = cp.state_rows(batched, 1)[0]
            outs.append(out)
        got = cp.assemble(outs)
        assert _rel(got, cp.oracle(x)) <= 1e-5


def _np_state(node):
    if isinstance(node, tuple):
        return tuple(_np_state(t) for t in node)
    return np.asarray(node)


# ---------------------------------------------------------------------------
# ONE dispatch per block (the fusion proof)
# ---------------------------------------------------------------------------

class TestOneDispatch:
    def test_single_program_single_span_per_block(self, telemetry):
        cp = _sensor_chain("fuseproof").compile(512)
        x = RNG.randn(8 * 512).astype(np.float32)
        blocks = [x[i:i + 512] for i in range(0, len(x), 512)]
        state = cp.init_state()
        out, state = cp.process(blocks[0], state)   # compile here
        np.asarray(out)
        size_after_warmup = cp.compile_cache_size()
        obs.reset()
        for b in blocks[1:]:
            out, state = cp.process(b, state)
        np.asarray(out)
        # no recompiles in steady state: still ONE executable
        assert size_after_warmup == 1
        assert cp.compile_cache_size() == 1
        # exactly one dispatch span per block, all fused
        spans = [e for e in obs.trace_events()
                 if e.get("name") == "pipeline.dispatch"]
        assert len(spans) == len(blocks) - 1
        # steady-state streaming harvests at most the one fused
        # program — NO per-stage op entries appear (the stages run
        # inside it, never as their own dispatches)
        assert {(r["op"], r["route"]) for r in obs.resources()} <= {
            ("pipeline", "fuseproof")}

    def test_resources_single_pipeline_entry(self, telemetry):
        cp = _sensor_chain("resproof").compile(512)
        x = RNG.randn(1024).astype(np.float32)
        cp.stream(x)
        entries = {(r["op"], r["route"]) for r in obs.resources()}
        assert entries == {("pipeline", "resproof")}

    def test_unfused_dispatches_per_stage(self, telemetry):
        cp = _sensor_chain("unfused").compile(512)
        x = RNG.randn(1024).astype(np.float32)
        cp.stream(x, fused=False)
        ops = {r["op"] for r in obs.resources()}
        assert ops == {"pipeline_stage"}
        routes = {r["route"] for r in obs.resources()}
        assert len(routes) == 4      # one compiled program per stage

    def test_step_jaxpr_identical_with_telemetry_on_and_off(self):
        # the obs contract extends to the fused step: telemetry
        # on/off never changes the one traced program
        import jax

        cp = pl.Pipeline([pl.sosfilt(SOS), pl.power()],
                         name="jaxprline").compile(256)
        x = np.zeros(256, np.float32)
        state = cp.init_state()
        j_off = str(jax.make_jaxpr(cp._step.fn)(x, state))
        obs.enable(compile_listeners=False)
        try:
            j_on = str(jax.make_jaxpr(cp._step.fn)(x, state))
        finally:
            obs.disable()
        assert j_off == j_on


# ---------------------------------------------------------------------------
# fault injection at pipeline.dispatch
# ---------------------------------------------------------------------------

class TestFaults:
    def test_mid_stream_fault_degrades_one_block(self, telemetry):
        cp = _sensor_chain("faultline").compile(512)
        x = RNG.randn(6 * 512).astype(np.float32)
        blocks = [x[i:i + 512] for i in range(0, len(x), 512)]
        state = cp.init_state()
        outs = []
        for i, b in enumerate(blocks):
            if i == 2:
                with faults.fault_plan(
                        "pipeline.dispatch:device_lost:99"):
                    out, state = cp.process(b, state)
            else:
                out, state = cp.process(b, state)
            outs.append(out)
        got = cp.assemble(outs)
        # the degraded block came from the oracle twin with exact
        # state threading: whole-stream parity still holds
        assert _rel(got, cp.oracle(x)) <= 1e-5
        assert obs.counter_value("fault_degraded",
                                 site="pipeline.dispatch",
                                 to="oracle") == 1

    def test_transient_fault_retries_then_succeeds(self, telemetry):
        cp = pl.Pipeline([pl.sosfilt(SOS)],
                         name="retryline").compile(256)
        x = RNG.randn(256).astype(np.float32)
        cp.process(x)               # warm
        with faults.fault_plan("pipeline.dispatch:device_lost:1"):
            out, _ = cp.process(x)
        assert obs.counter_value("fault_retry",
                                 site="pipeline.dispatch") == 1
        assert obs.counter_value("fault_degraded",
                                 site="pipeline.dispatch",
                                 to="oracle") == 0

    def test_persistent_fault_opens_pipeline_breaker(self, telemetry):
        cp = pl.Pipeline([pl.sosfilt(SOS)],
                         name="poisonline").compile(256)
        x = RNG.randn(256).astype(np.float32)
        state = None
        with faults.fault_plan("pipeline.dispatch:device_lost:9999"):
            for _ in range(6):
                out, state = cp.process(x, state)
        br = breaker.lookup("pipeline.dispatch", ("poisonline", 256))
        assert br is not None and br.state == breaker.OPEN
        # open breaker short-circuits: zero retries in steady state
        before = obs.counter_value("fault_retry",
                                   site="pipeline.dispatch")
        with faults.fault_plan("pipeline.dispatch:device_lost:9999"):
            out, state = cp.process(x, state)
        assert obs.counter_value(
            "fault_retry", site="pipeline.dispatch") == before

    def test_subsite_poisons_one_pipeline_only(self, telemetry):
        cp_a = pl.Pipeline([pl.sosfilt(SOS)], name="pa").compile(256)
        cp_b = pl.Pipeline([pl.sosfilt(SOS)], name="pb").compile(256)
        x = RNG.randn(256).astype(np.float32)
        cp_a.process(x)
        cp_b.process(x)
        with faults.fault_plan(
                "pipeline.dispatch@pa:device_lost:9999"):
            for _ in range(4):
                cp_a.process(x)
            cp_b.process(x)
        assert obs.counter_value("fault_degraded",
                                 site="pipeline.dispatch",
                                 to="oracle") >= 1
        br_b = breaker.lookup("pipeline.dispatch", ("pb", 256))
        assert br_b is None or br_b.state == breaker.CLOSED


# ---------------------------------------------------------------------------
# pipelines as first-class served units
# ---------------------------------------------------------------------------

class TestServing:
    def test_register_validates(self):
        srv = serve.Server()
        with pytest.raises(TypeError, match="CompiledPipeline"):
            srv.register_pipeline("x", object())
        cp = pl.Pipeline([pl.sosfilt(SOS)], name="ok").compile(256)
        with pytest.raises(ValueError, match="bad pipeline name"):
            srv.register_pipeline("a:b", cp)
        assert srv.register_pipeline("ok", cp) == "pipeline:ok"

    def test_unregistered_pipeline_op_rejected(self):
        with serve.Server() as srv:
            with pytest.raises(ValueError, match="unregistered"):
                srv.submit(op="pipeline:nope",
                           x=np.zeros(256, np.float32))

    def test_wrong_block_length_rejected(self):
        srv = serve.Server()
        cp = pl.Pipeline([pl.sosfilt(SOS)], name="blk").compile(256)
        op = srv.register_pipeline("blk", cp)
        with srv:
            with pytest.raises(ValueError, match="block"):
                srv.submit(op=op, x=np.zeros(128, np.float32))

    def test_malformed_state_rejected_at_submit(self):
        # a bad state must fail ITS caller synchronously — inside the
        # worker it would error every co-batched stream untyped
        cp = pl.Pipeline([pl.sosfilt(SOS)],
                         name="valid8").compile(256)
        srv = serve.Server()
        op = srv.register_pipeline("valid8", cp)
        x = np.zeros(256, np.float32)
        with srv:
            with pytest.raises(ValueError, match="shape"):
                srv.submit(op=op, x=x, params={
                    "state": (np.zeros((3, 2), np.float32),)})
            with pytest.raises(ValueError, match="tuple"):
                srv.submit(op=op, x=x,
                           params={"state": np.zeros(4, np.float32)})

    def test_served_stream_matches_oracle(self, telemetry):
        cp = _sensor_chain("served").compile(512)
        x = RNG.randn(6 * 512).astype(np.float32)
        with serve.Server(max_batch=4, max_wait_ms=1.0,
                          workers=2) as srv:
            op = srv.register_pipeline("served", cp)
            state, outs = None, []
            for i in range(6):
                t = srv.submit(op=op, x=x[i * 512:(i + 1) * 512],
                               params={"state": state})
                y, state = t.result(timeout=60.0)
                assert t.status == "ok"
                outs.append(y)
        got = cp.assemble(outs)
        assert _rel(got, cp.oracle(x)) <= 1e-5

    def test_batched_streams_share_one_dispatch(self, telemetry):
        cp = pl.Pipeline([pl.sosfilt(SOS)],
                         name="batchserve").compile(256)
        sigs = {k: RNG.randn(1024).astype(np.float32)
                for k in ("s0", "s1", "s2")}
        with serve.Server(max_batch=8, max_wait_ms=20.0,
                          workers=1) as srv:
            op = srv.register_pipeline("batchserve", cp)
            states = {k: None for k in sigs}
            outs = {k: [] for k in sigs}
            for i in range(4):
                tickets = {k: srv.submit(
                    op=op, x=sig[i * 256:(i + 1) * 256],
                    params={"state": states[k]}, tenant=k)
                    for k, sig in sigs.items()}
                for k, t in tickets.items():
                    y, st = t.result(timeout=60.0)
                    outs[k].append(y)
                    states[k] = st
        for k, sig in sigs.items():
            got = cp.assemble(outs[k])
            assert _rel(got, cp.oracle(sig)) <= 1e-5
        # coalescing happened: fewer batches than requests
        batches = obs.counter_value("serve_batches",
                                    op="pipeline:batchserve")
        assert 0 < batches <= 8

    def test_poisoned_pipeline_class_degrades_alone(self, telemetry):
        cp = pl.Pipeline([pl.sosfilt(SOS)],
                         name="chaospipe").compile(256)
        x = RNG.randn(256).astype(np.float32)
        with serve.Server(max_batch=2, max_wait_ms=1.0,
                          workers=1) as srv:
            op = srv.register_pipeline("chaospipe", cp)
            # warm both classes
            srv.submit(op=op, x=x,
                       params={"state": None}).result(timeout=60.0)
            srv.submit(op="sosfilt", x=x,
                       params={"sos": SOS}).result(timeout=60.0)
            with faults.fault_plan(
                    "pipeline.dispatch@chaospipe:device_lost:9999"):
                degraded = 0
                for _ in range(5):
                    t = srv.submit(op=op, x=x,
                                   params={"state": None})
                    t.result(timeout=60.0)
                    degraded += int(t.status == "degraded")
                assert degraded == 5       # answered, degraded, typed
                t2 = srv.submit(op="sosfilt", x=x,
                                params={"sos": SOS})
                t2.result(timeout=60.0)
                assert t2.status == "ok"   # sibling class untouched
            br = breaker.lookup("pipeline.dispatch",
                                ("chaospipe", 256))
            assert br is not None and br.state == breaker.OPEN
            assert srv.stats()["counts"]["degraded_answers"] >= 1

    def test_loadgen_pipeline_streams_accounting(self, telemetry):
        import loadgen

        compiled = loadgen.build_pipeline("lgline")
        with serve.Server(max_batch=4, max_wait_ms=1.0,
                          workers=2) as srv:
            op = srv.register_pipeline("lgline", compiled)
            rep = loadgen.run_pipeline_streams(
                srv, op, compiled, np.random.RandomState(0),
                streams=2, blocks=3)
        assert rep["requests"] == 6
        assert rep["ok"] == 6
        assert rep["lost"] == 0
        assert rep["double_answered"] == 0
        assert rep["parity_failures"] == 0
