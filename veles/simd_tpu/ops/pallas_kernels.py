"""Pallas TPU kernels for the hot VPU ops.

The reference's hand-written per-order AVX/NEON wavelet kernels
(``/root/reference/src/wavelet.c:384-1941``) exist because the compiler
could not be trusted with the inner loop; the TPU analog of that layer is
a hand-written Mosaic kernel where XLA's generic lowering leaves
bandwidth on the table.  The place that happens here is the small-FIR
filter bank: ``lax.conv_general_dilated`` with a 2..256-tap filter lowers
to an im2col matmul that moves each input sample ``order`` times, while
the arithmetic is trivially VPU-bound — a shifted-MAC kernel reads each
sample once from HBM and keeps every intermediate in VMEM.  Measured on
v5e: 3.0-3.6x on the DWT benchmark workload (512x4096 daub8), 5.6-9.3x
on batched direct convolution (vs the XLA conv lowering).

One kernel family serves all the FIR-shaped ops:

* DWT  — C=2 channels (hi, lo), stride 2, dilation 1
* SWT  — C=2 channels, stride 1, dilation 2^(level-1)
* direct convolution / correlation — C=1, stride 1, dilation 1
  (caller pre-pads and pre-flips, exactly like the XLA path)

The kernel computes, per output channel c::

    out[c][b, i] = sum_j f[c][j] * x_ext[b, i*stride + j*dilation]

The tap *values* live in SMEM (runtime data — a new filter does not
recompile, matching the library contract that ``h`` is an argument); the
tap *count* is static and the loop fully unrolled, each step a
scalar*vector MAC — the Pallas analog of the reference's unrolled
``_mm256_dp_ps`` loops.  Accumulation goes statement-by-statement into
the output ref: a single summed expression keeps every tap slice live at
once and overflows the Mosaic stack for large orders (observed at 33).

Mosaic lowers neither strided vector slices nor unaligned dynamic lane
offsets, so decimation never happens inside the kernel: for stride s > 1
the input is deinterleaved into s phase arrays *outside* (XLA strided
slice), the taps are split by parity (``f[j]`` lands on phase ``j % s``
at offset ``j // s``), and the kernel emits already-decimated outputs —
every in-kernel slice is unit-stride at a static offset.

Boundary extension stays in XLA (``ops/wavelet._extend``): it is a cheap
concat that XLA fuses into the surrounding program, and keeping it out of
the kernel keeps the kernel oblivious to the four extension modes.

CPU fallback: ``pallas_call(interpret=True)`` runs the same kernel in the
interpreter, which is how the unit tests (pinned to the CPU platform by
``conftest.py``) cross-validate it against the NumPy oracles; the
compiled Mosaic path is exercised on real hardware by ``bench.py
--check`` (the TPU smoke gate).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import on_tpu

__all__ = ["filter_bank_pallas", "filter_2d_pallas",
           "cascade_bank_pallas", "overlap_save_pallas",
           "stft_pallas",
           "pallas_available",
           "pallas2d_compiled_allowed", "pallas_os_allowed",
           "stft_pallas_allowed",
           "fits_vmem_os", "fits_vmem_stft",
           "PALLAS_MIN_ROWS", "PALLAS_DIRECT_MAX_H",
           "PALLAS_2D_MAX_KERNEL_AREA",
           "PALLAS_OS_STEP", "PALLAS_OS_ROWS", "PALLAS_OS_MIN_H",
           "PALLAS_STFT_ROWS", "PALLAS_STFT_MIN_FRAMES"]

# the kernel wins when the batch tile fills VPU sublanes; below this the
# dispatch/layout overhead dominates and the XLA conv path is used
PALLAS_MIN_ROWS = 8
# direct-convolution routing bound: the unrolled VPU kernel does k MACs
# per sample, so very long filters belong to the MXU/FFT algorithms (and
# unrolled compile time grows with k); measured wins up to k=129 on v5e
# (5.6-9.3x), bound set with margin
PALLAS_DIRECT_MAX_H = 256
# 2D analog: kernel area cap for the unrolled taps (16x16)
PALLAS_2D_MAX_KERNEL_AREA = 256
# batch rows per grid step: Pallas double-buffers every in/out block, so
# the steady-state VMEM footprint is ~2*(inputs + outputs) per row plus
# accumulator temps; budget well under the 16 MB/core limit
_MAX_ROWS_PER_TILE = 256
_VMEM_BUDGET_BYTES = 10 << 20   # for 2*(in+out) + temps
# Mosaic's scoped-vmem stack is a real compile-time cap (measured
# round 5: 1-image 128^2 k=15x15 fails with "scoped allocation 22.34M
# > 16.00M limit") — but it is NOT predictable from shape arithmetic:
# the area*out_tile model that explains that failure (225 * 80KB =
# 18M) is contradicted by 8x512^2 k=9x9 (81 * 1.08MB = 87M by the same
# formula) compiling fine and winning at 5,897 Msamples/s.  The
# admission gate therefore checks only residency; the scoped cap is
# handled empirically — the routing layer attempts the compiled kernel
# and falls back on the specific vmem-OOM compile error, caching the
# rejection per shape class (convolve2d._PALLAS2D_OOM_REJECTED).


def pallas_available() -> bool:
    """Compiled Mosaic path available (real TPU backend)?"""
    return on_tpu()


# ---- fused overlap-save (MXU) routing constants ---------------------------
# output-block width of the fused overlap-save kernel: the per-shift
# factors are [step, step] matmuls, so step must be a lane multiple;
# 256 keeps the Toeplitz redundancy (k + step MACs per output sample
# vs k useful) low for the long filters this path serves — at k=2047
# the ceiling is k/(k+step) = 89% of the useful-FLOPs roofline, vs 80%
# at step 512
PALLAS_OS_STEP = 256
# signal rows (output blocks) per grid step: [rows, step] x-tiles feed
# [rows, step] @ [step, step] MXU dots; 256 amortizes the resident
# Toeplitz factors over a full MXU-height operand
PALLAS_OS_ROWS = 256
# below this many taps the frames duplication the fused kernel removes
# is <= 2x and the XLA block-matmul path is already compute-bound;
# keep the compiled-kernel routing surface to the shapes it was built
# for (the reference's long-filter overlap-save domain)
PALLAS_OS_MIN_H = 256
_PALLAS_OS_ENV = "VELES_SIMD_DISABLE_PALLAS_OS"


def pallas_os_allowed() -> bool:
    """May implicit routing use the compiled fused overlap-save kernel?
    True unless explicitly disabled (mirrors the 2D kernel's
    ``VELES_SIMD_DISABLE_PALLAS2D`` opt-out)."""
    return os.environ.get(_PALLAS_OS_ENV, "0").strip().lower() not in (
        "1", "true", "yes", "on")


def fits_vmem_os(h_length: int, step: int = PALLAS_OS_STEP,
                 rows: int = PALLAS_OS_ROWS) -> bool:
    """Does the fused overlap-save kernel's resident state fit VMEM?

    Residency: the ``[n_j, step, step]`` Toeplitz factors (constant
    across grid steps), the ``[jb + rows, step]`` window scratch + the
    ``[jb, step]`` carry, and the double-buffered in/out tiles."""
    jb = -(-(int(h_length) - 1) // int(step))
    n_j = jb + 1
    mb_bytes = n_j * step * step * 4
    scratch_bytes = (jb + rows + jb) * step * 4
    tile_bytes = 2 * 2 * rows * step * 4     # in + out, double-buffered
    return mb_bytes + scratch_bytes + tile_bytes <= _VMEM_BUDGET_BYTES


# ---- fused STFT (MXU matmul-DFT) routing constants ------------------------
# frame rows per grid step of the fused STFT kernel: each output row is
# one frame's [1, hop] x [hop, 2*bins_pad] shift-dots, so 256 rows feed
# full MXU-height operands exactly like the overlap-save kernel
PALLAS_STFT_ROWS = 256
# below this many frames the fused kernel's dispatch/layout overhead
# dominates and the rdft-matmul (XLA frames @ basis) route is already
# compute-bound; the kernel's win is removing the materialized frames
# tensor, which only matters once frames*frame_length is real traffic
PALLAS_STFT_MIN_FRAMES = 64
_PALLAS_STFT_ENV = "VELES_SIMD_DISABLE_STFT_PALLAS"


def stft_pallas_allowed() -> bool:
    """May implicit routing use the compiled fused STFT kernel?  True
    unless explicitly disabled (mirrors ``VELES_SIMD_DISABLE_PALLAS_OS``
    for the fused overlap-save kernel)."""
    return os.environ.get(_PALLAS_STFT_ENV, "0").strip().lower() not in (
        "1", "true", "yes", "on")


def fits_vmem_stft(frame_length: int, hop: int,
                   rows: int = PALLAS_STFT_ROWS) -> bool:
    """Does the fused STFT kernel's resident state fit VMEM?

    Residency: the ``[r, hop, 2*bins_pad]`` windowed DFT basis blocks
    (constant across grid steps), the ``[r-1 + rows, hop]`` window
    scratch + ``[r-1, hop]`` overlap carry, and the double-buffered
    in/out tiles (``r = frame_length // hop``)."""
    L, s = int(frame_length), int(hop)
    r = L // s
    bins_pad = -(-(L // 2 + 1) // 128) * 128
    basis_bytes = r * s * 2 * bins_pad * 4
    scratch_bytes = (2 * (r - 1) + rows) * s * 4
    tile_bytes = 2 * rows * (s + 2 * bins_pad) * 4
    return basis_bytes + scratch_bytes + tile_bytes <= _VMEM_BUDGET_BYTES


# The compiled 2D Mosaic kernel's first-ever hardware execution
# (2026-07-31 00:59Z window) coincided with the axon relay wedging, and
# Default ON since round 5: tools/repro_pallas2d.py recorded a clean
# compiled hardware pass (2026-07-31, all 8 stages OK incl. the round-3
# wedge shape, ledger in repro_pallas2d.json) — and the same live window
# showed the round-3 wedge reproduces with the plain XLA direct conv2d
# at large kernels instead (TPU worker crash at 512x512 k=65 direct),
# exonerating this kernel.  Measured on the gated domain the compiled
# kernel then beat the XLA conv route 10-400x and the FFT route 7-56x
# (table at convolve2d.select_algorithm2d).  VELES_SIMD_DISABLE_PALLAS2D=1
# restores the XLA fallback if a future backend misbehaves.
_PALLAS2D_ENV = "VELES_SIMD_DISABLE_PALLAS2D"


def pallas2d_compiled_allowed() -> bool:
    """May implicit routing use the *compiled* 2D Mosaic kernel?
    True unless explicitly disabled (see the env note above)."""
    return os.environ.get(_PALLAS2D_ENV, "0").strip().lower() not in (
        "1", "true", "yes", "on")


def _tile_rows(n_rows: int, row_elems: int) -> int:
    """Rows per grid step given total f32 elements per row (in + out)."""
    budget_rows = _VMEM_BUDGET_BYTES // (3 * 4 * row_elems)
    rows = min(n_rows, _MAX_ROWS_PER_TILE, max(1, budget_rows))
    if rows > 8:
        rows &= ~7          # keep full 8-sublane tiles
    return max(rows, 1)


def fits_vmem2d(in_elems: int, out_elems: int, kernel_area: int) -> bool:
    """2D admission: residency (in + out) within the tile budget.  The
    Mosaic scoped-stack cap is enforced empirically by the caller's
    OOM-fallback (see the note above ``fits_vmem``) — shape arithmetic
    proved unable to predict it (``kernel_area`` kept for signature
    stability and future models)."""
    del kernel_area
    return fits_vmem(in_elems + out_elems)


def fits_vmem(row_elems: int) -> bool:
    """Can a single batch row of ``row_elems`` f32 (inputs + outputs)
    fit the kernel's VMEM budget?  A row too large for even a 1-row tile
    would fail Mosaic compilation; :func:`filter_bank_pallas` rejects
    such shapes at the API boundary and routing gates pre-check via
    :func:`should_route` to keep them on the XLA path."""
    return 3 * 4 * row_elems <= _VMEM_BUDGET_BYTES


def should_route(rows: int, row_elems: int) -> bool:
    """Single home for the compiled-path routing policy: Mosaic backend
    available, enough batch rows to fill VPU sublanes, and one row's
    inputs+outputs (``row_elems`` f32) within the VMEM tile budget.
    Callers (``wavelet._use_pallas``, ``convolve._use_pallas_direct``)
    add op-specific terms on top."""
    return (pallas_available() and rows >= PALLAS_MIN_ROWS
            and fits_vmem(row_elems))


def _fb_kernel(*refs, tap_counts, dilation, n_out, stacked=False):
    """Shifted-MAC filter bank over VMEM tiles.

    ``refs`` = per-phase SMEM tap refs ([C, n_taps_p]), then per-phase
    VMEM input tiles, then C output tiles (or ONE [rows, C*n_out] tile
    when ``stacked`` — channel c at lane offset c*n_out, which the
    caller guarantees is 128-lane aligned).  ``out[c] = sum_p sum_m
    taps_p[c, m] * phase_p[:, m*dilation : m*dilation + n_out]`` — all
    slices unit-stride at static offsets; tap values are runtime SMEM
    scalars.
    """
    n_phases = len(tap_counts)
    tap_refs = refs[:n_phases]
    in_refs = refs[n_phases:2 * n_phases]
    out_refs = refs[2 * n_phases:]
    phases = [r[...] for r in in_refs]
    if stacked:
        n_ch = tap_refs[0].shape[0]
        ref0 = out_refs[0]
        out_refs = [ref0.at[:, c * n_out:(c + 1) * n_out]
                    for c in range(n_ch)]
    for c, ref in enumerate(out_refs):
        first = True
        for p, xv in enumerate(phases):
            for m in range(tap_counts[p]):
                t = jax.lax.slice_in_dim(
                    xv, m * dilation, m * dilation + n_out, axis=1)
                term = tap_refs[p][c, m] * t
                # statement-by-statement accumulation bounds Mosaic
                # stack temporaries (see module docstring).  Round-5
                # A/B on hardware re-confirmed this design: sequential
                # REGISTER accumulation (acc = acc + term, one final
                # store) measured SLOWER at order 8 (31.3 vs 33.8
                # GS/s) and failed to compile at order 129 with 160 MB
                # of register-allocator spill slots — Mosaic keeps the
                # in-flight accumulator live across all unrolled slots,
                # while the through-ref chain lets it recycle.
                ref[...] = term if first else ref[...] + term
                first = False


@functools.partial(
    obs.instrumented_jit, op="pallas", route="filter_bank",
    static_argnames=("tap_counts", "dilation", "n_out", "interpret"))
def _fb_call(phases, taps, tap_counts, dilation, n_out, interpret):
    n_rows = phases[0].shape[0]
    n_ch = taps[0].shape[0]
    row_elems = sum(p.shape[1] for p in phases) + n_ch * n_out
    rows = _tile_rows(n_rows, row_elems)
    pad_rows = (-n_rows) % rows
    if pad_rows:
        phases = [jnp.pad(p, ((0, pad_rows), (0, 0))) for p in phases]
    grid = (phases[0].shape[0] // rows,)
    # Stacked single-buffer output when every channel's lane offset is
    # 128-aligned: the bands come back as adjacent slices of ONE
    # [rows, C*n_out] buffer, so a downstream concat of the bands in
    # order can fold to identity instead of a second full copy of the
    # outputs through HBM.  Round-5 hardware A/B: neutral-to-positive
    # (config-5 within the relay's ±15% run noise; daub16 512x4096
    # measured 22-38 GS/s across runs vs 20 before) — kept for the
    # structural win at zero measured cost.
    stacked = n_ch > 1 and n_out % 128 == 0
    kernel = functools.partial(_fb_kernel, tap_counts=tap_counts,
                               dilation=dilation, n_out=n_out,
                               stacked=stacked)
    order = sum(tap_counts)
    if stacked:
        out_specs = [pl.BlockSpec((rows, n_ch * n_out),
                                  lambda i: (i, 0))]
        out_shape = [jax.ShapeDtypeStruct(
            (phases[0].shape[0], n_ch * n_out), jnp.float32)]
    else:
        out_specs = [pl.BlockSpec((rows, n_out),
                                  lambda i: (i, 0))] * n_ch
        out_shape = [jax.ShapeDtypeStruct(
            (phases[0].shape[0], n_out), jnp.float32)] * n_ch
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=(
            [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(phases)
            + [pl.BlockSpec((rows, p.shape[1]), lambda i: (i, 0))
               for p in phases]),
        out_specs=out_specs,
        out_shape=out_shape,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_ch * order * phases[0].shape[0] * n_out,
            bytes_accessed=4 * phases[0].shape[0] * row_elems,
            transcendentals=0),
        interpret=interpret,
    )(*[t.astype(jnp.float32) for t in taps],
      *[p.astype(jnp.float32) for p in phases])
    if stacked:
        outs = [outs[0][:, c * n_out:(c + 1) * n_out]
                for c in range(n_ch)]
    if pad_rows:
        outs = [o[:n_rows] for o in outs]
    return tuple(outs)


def _cb_kernel(*refs, plans, n_phases, n_out):
    """Multi-channel cascade bank: each output channel accumulates
    ``taps_c[slot] * phase[p][:, off : off + n_out]`` over its static
    ``plan`` of (phase, offset) slots.  The generalization of
    :func:`_fb_kernel` to per-channel tap assignments — what lets a
    whole multi-level DWT cascade read its input once (every slice
    unit-stride at a static offset, tap values runtime SMEM data)."""
    n_ch = len(plans)
    tap_refs = refs[:n_ch]
    in_refs = refs[n_ch:n_ch + n_phases]
    out_refs = refs[n_ch + n_phases:]
    phases = [r[...] for r in in_refs]
    for c, (ref, plan) in enumerate(zip(out_refs, plans)):
        first = True
        for slot, (p, off) in enumerate(plan):
            t = jax.lax.slice_in_dim(phases[p], off, off + n_out,
                                     axis=1)
            term = tap_refs[c][slot] * t
            ref[...] = term if first else ref[...] + term
            first = False


@functools.partial(obs.instrumented_jit, op="pallas",
                   route="cascade_bank",
                   static_argnames=("plans", "n_out", "interpret"))
def _cb_call(phases, taps, plans, n_out, interpret):
    n_rows = phases[0].shape[0]
    n_ch = len(plans)
    row_elems = sum(p.shape[1] for p in phases) + n_ch * n_out
    rows = _tile_rows(n_rows, row_elems)
    pad_rows = (-n_rows) % rows
    if pad_rows:
        phases = [jnp.pad(p, ((0, pad_rows), (0, 0))) for p in phases]
    grid = (phases[0].shape[0] // rows,)
    kernel = functools.partial(_cb_kernel, plans=plans,
                               n_phases=len(phases), n_out=n_out)
    n_macs = sum(len(pl) for pl in plans)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=(
            [pl.BlockSpec(memory_space=pltpu.SMEM)] * n_ch
            + [pl.BlockSpec((rows, p.shape[1]), lambda i: (i, 0))
               for p in phases]),
        out_specs=[pl.BlockSpec((rows, n_out), lambda i: (i, 0))] * n_ch,
        out_shape=[jax.ShapeDtypeStruct((phases[0].shape[0], n_out),
                                        jnp.float32)] * n_ch,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_macs * phases[0].shape[0] * n_out,
            bytes_accessed=4 * phases[0].shape[0] * row_elems,
            transcendentals=0),
        interpret=interpret,
    )(*[t.astype(jnp.float32) for t in taps],
      *[p.astype(jnp.float32) for p in phases])
    if pad_rows:
        outs = [o[:n_rows] for o in outs]
    return tuple(outs)


def cascade_bank_pallas(x_ext, taps_list, plans, n_split, n_out,
                        interpret=None):
    """Run a static multi-channel plan over the ``n_split``-phase
    deinterleave of ``x_ext``: channel c computes ``out_c[..., i] =
    sum_slot taps_c[slot] * x_ext[..., i * n_split + off * n_split +
    p]`` for its plan slots ``(p, off)`` — i.e. arbitrary FIR channels
    at stride ``n_split``, all from ONE pass over the input.  The
    multi-level DWT cascade maps onto this with composed per-level
    filters (see ``ops.wavelet._fused_cascade``).

    ``plans`` must be a tuple of tuples of (phase, offset) pairs;
    ``taps_list`` the per-channel tap vectors in plan-slot order.
    """
    plans = tuple(tuple((int(p), int(o)) for p, o in plan)
                  for plan in plans)
    if len(taps_list) != len(plans):
        raise ValueError("one tap vector per plan channel")
    for t, plan in zip(taps_list, plans):
        if len(plan) == 0:
            # an empty channel would return uninitialized VMEM
            raise ValueError("every plan channel needs >= 1 slot")
        if np.shape(t) != (len(plan),):
            raise ValueError("tap vector length must equal its plan's "
                             "slot count")
        for p, o in plan:
            if not 0 <= p < n_split or o < 0:
                raise ValueError(
                    f"plan slot (phase={p}, offset={o}) outside "
                    f"[0, {n_split}) x [0, inf)")
    if interpret is None:
        interpret = not pallas_available()
    batch_shape = x_ext.shape[:-1]
    x2d = jnp.asarray(x_ext).reshape((-1, x_ext.shape[-1]))
    max_off = {p: 0 for p in range(n_split)}
    for plan in plans:
        for p, o in plan:
            max_off[p] = max(max_off[p], o)
    lengths = [n_out + max_off[p] for p in range(n_split)]
    need = max((p + (ln - 1) * n_split + 1)
               for p, ln in enumerate(lengths))
    if x_ext.shape[-1] < need:
        raise ValueError(f"x_ext too short: {x_ext.shape[-1]} < {need}")
    phases = [x2d[:, p::n_split][:, :ln]
              for p, ln in zip(range(n_split), lengths)]
    row_elems = sum(lengths) + len(plans) * n_out
    if not interpret and not fits_vmem(row_elems):
        raise ValueError(
            f"row of {row_elems} f32 elements exceeds the kernel VMEM "
            "tile budget; keep this shape on the XLA path")
    outs = _cb_call(phases, [jnp.asarray(t) for t in taps_list], plans,
                    int(n_out), bool(interpret))
    return tuple(o.reshape(batch_shape + (n_out,)) for o in outs)


def _phase_plan(order, stride, dilation, n_out):
    """Static plan: per-phase tap counts + input slice lengths.

    Phase p holds ``x_ext[p::stride]``; tap j of any channel lands on
    phase ``j % stride`` at offset ``j // stride`` (requires dilation 1
    when stride > 1 — the DWT case; SWT/direct use stride 1).  Non-empty
    phases always form a prefix of ``range(stride)`` because tap indices
    are contiguous from 0.
    """
    if stride == 1:
        return (order,), [(n_out - 1) + (order - 1) * dilation + 1], dilation
    if dilation != 1:
        raise ValueError("stride > 1 requires dilation == 1")
    counts, lengths = [], []
    for p in range(stride):
        n_taps = len(range(p, order, stride))
        if n_taps == 0:
            break
        counts.append(n_taps)
        lengths.append((n_out - 1) + n_taps)
    return tuple(counts), lengths, 1


def _f2d_kernel(h_ref, x_ref, o_ref, *, k0, k1, n_out0, n_out1):
    """2D shifted-MAC: ``out[b, i, j] = Σ_{p,q} h[p,q] ·
    x_ext[b, i+p, j+q]`` — k0·k1 statically-unrolled scalar*plane MACs
    (taps in SMEM), every slice unit-stride at a static offset."""
    xv = x_ref[...]
    first = True
    for p in range(k0):
        for q in range(k1):
            t = jax.lax.slice(
                xv, (0, p, q),
                (xv.shape[0], p + n_out0, q + n_out1))
            term = h_ref[p, q] * t
            o_ref[...] = term if first else o_ref[...] + term
            first = False


@functools.partial(obs.instrumented_jit, op="pallas",
                   route="filter_2d",
                   static_argnames=("n_out0", "n_out1",
                                    "interpret"))
def _f2d_call(x3d, kernel2d, n_out0, n_out1, interpret):
    n_imgs, n0e, n1e = x3d.shape
    k0, k1 = kernel2d.shape
    # one image per row of the budget formula: a 2D tile already fills
    # sublanes x lanes, so images (not batch rows) are the grid unit
    imgs = _tile_rows(n_imgs, n0e * n1e + n_out0 * n_out1)
    pad = (-n_imgs) % imgs
    if pad:
        x3d = jnp.pad(x3d, ((0, pad), (0, 0), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_f2d_kernel, k0=k0, k1=k1, n_out0=n_out0,
                          n_out1=n_out1),
        grid=(x3d.shape[0] // imgs,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((imgs, n0e, n1e), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((imgs, n_out0, n_out1),
                               lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((x3d.shape[0], n_out0, n_out1),
                                       jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * k0 * k1 * x3d.shape[0] * n_out0 * n_out1,
            bytes_accessed=4 * x3d.shape[0] * (n0e * n1e
                                               + n_out0 * n_out1),
            transcendentals=0),
        interpret=interpret,
    )(kernel2d.astype(jnp.float32), x3d.astype(jnp.float32))
    return out[:n_imgs] if pad else out


def filter_2d_pallas(x_ext, kernel2d, n_out0, n_out1, interpret=None):
    """2D FIR correlation as one Pallas kernel (the image analog of
    :func:`filter_bank_pallas`): ``out[..., i, j] = Σ_{p,q}
    kernel2d[p, q] · x_ext[..., i+p, j+q]``.  ``x_ext`` carries the
    caller's boundary handling; tap values are runtime SMEM data.
    Unlike the 1D kernel, no minimum batch applies — one image already
    fills the VPU's sublane x lane tile."""
    kernel2d = jnp.asarray(kernel2d, jnp.float32)
    if kernel2d.ndim != 2:
        raise ValueError("kernel2d must be [k0, k1]")
    k0, k1 = kernel2d.shape
    if x_ext.ndim < 2:
        raise ValueError("x_ext must be [..., n0_ext, n1_ext]")
    if (x_ext.shape[-2] < n_out0 + k0 - 1
            or x_ext.shape[-1] < n_out1 + k1 - 1):
        raise ValueError(
            f"x_ext too short: {x_ext.shape[-2:]} < "
            f"{(n_out0 + k0 - 1, n_out1 + k1 - 1)}")
    if interpret is None:
        interpret = not pallas_available()
    if not interpret and not fits_vmem2d(
            x_ext.shape[-2] * x_ext.shape[-1], n_out0 * n_out1, k0 * k1):
        raise ValueError("image exceeds the kernel VMEM tile budget; "
                         "keep this shape on the XLA path (Mosaic's "
                         "scoped-stack cap is separate and surfaces as "
                         "a compile error — see fits_vmem2d)")
    batch_shape = x_ext.shape[:-2]
    x3d = jnp.asarray(x_ext).reshape((-1,) + x_ext.shape[-2:])
    out = _f2d_call(x3d, kernel2d, int(n_out0), int(n_out1),
                    bool(interpret))
    return out.reshape(batch_shape + (n_out0, n_out1))


def filter_bank_pallas(x_ext, filters, stride, dilation, n_out,
                       interpret=None):
    """Multi-channel FIR filter bank as one Pallas kernel.

    ``x_ext``: [..., n_ext] pre-extended signal (boundary handling is the
    caller's).  ``filters``: [C, order] tap matrix (runtime data — only
    its *shape* keys the compile cache).  Returns a tuple of C arrays
    shaped [..., n_out] where ``out[c][..., i] = sum_j filters[c, j] *
    x_ext[..., i*stride + j*dilation]``.

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, interpreter
    elsewhere (the CPU test path).
    """
    filters = jnp.asarray(filters, jnp.float32)
    if filters.ndim != 2:
        raise ValueError("filters must be [channels, order]")
    order = filters.shape[1]
    need = (n_out - 1) * stride + (order - 1) * dilation + 1
    if x_ext.shape[-1] < need:
        raise ValueError(
            f"x_ext too short: {x_ext.shape[-1]} < {need} for "
            f"n_out={n_out}, stride={stride}, dilation={dilation}")
    if interpret is None:
        interpret = not pallas_available()
    stride, dilation, n_out = int(stride), int(dilation), int(n_out)
    batch_shape = x_ext.shape[:-1]
    x2d = jnp.asarray(x_ext).reshape((-1, x_ext.shape[-1]))
    tap_counts, lengths, kern_dilation = _phase_plan(
        order, stride, dilation, n_out)
    n_ch = filters.shape[0]
    if not interpret and not fits_vmem(sum(lengths) + n_ch * n_out):
        raise ValueError(
            f"row of {sum(lengths) + n_ch * n_out} f32 elements exceeds "
            "the kernel VMEM tile budget even at 1 row/tile; keep this "
            "shape on the XLA path (see should_route)")
    if stride == 1:
        phases = [x2d[:, :lengths[0]]]
        taps = [filters]
    else:
        phases = [x2d[:, p::stride][:, :ln]
                  for p, ln in zip(range(stride), lengths)]
        taps = [filters[:, p::stride] for p in range(len(tap_counts))]
    outs = _fb_call(phases, taps, tap_counts, kern_dilation, n_out,
                    bool(interpret))
    return tuple(o.reshape(batch_shape + (n_out,)) for o in outs)


# ---------------------------------------------------------------------------
# fused overlap-save convolution (MXU block matmul, halo carried in VMEM)
# ---------------------------------------------------------------------------


def _os_kernel(mbt_ref, x_ref, o_ref, w_ref, carry_ref, *, n_j, rows,
               precision):
    """One overlap-save tile: ``rows`` output blocks of ``step`` samples.

    The Toeplitz matmul ``y[r, t] = sum_a frames[r, a] * M[a, t]`` is
    evaluated WITHOUT materializing frames: split the frame column
    ``a = j*step + b`` and each shift j becomes a ``[rows, step] @
    [step, step]`` MXU dot against a row-block of the window

        y[r, t] = sum_j sum_b W[jb - j + r, b] * taps[j*step + t - b]

    where ``W = [carry; x_tile]`` is the tile's input rows prefixed by
    the last ``jb = n_j - 1`` rows of the PREVIOUS tile — the M-1 halo,
    carried across grid steps in a VMEM scratch instead of re-read
    (grid steps run sequentially on a TPU core, so the carry written by
    step t is exactly what step t+1 reads).  ``mbt_ref[j][t, b] =
    taps[j*step + t - b]`` are the per-shift Toeplitz factors, VMEM-
    resident and shared by every grid step.  Every slice is unit-stride
    at a static offset; accumulation goes statement-by-statement into
    the output ref (the module's Mosaic-stack discipline).
    """
    jb = n_j - 1
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        # first tile of each batch row: zero history (src/convolve.c:
        # 194-196 zero-pads the first block the same way)
        carry_ref[...] = jnp.zeros(carry_ref.shape, carry_ref.dtype)

    w_ref[0:jb, :] = carry_ref[...]
    w_ref[jb:, :] = x_ref[0]
    for j in range(n_j):
        lhs = w_ref[jb - j:jb - j + rows, :]
        term = jax.lax.dot_general(
            lhs, mbt_ref[j],
            dimension_numbers=(((1,), (1,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        o_ref[0] = term if j == 0 else o_ref[0] + term
    carry_ref[...] = x_ref[0, rows - jb:, :]


@functools.partial(obs.instrumented_jit, op="pallas",
                   route="overlap_save",
                   static_argnames=("n_j", "rows", "precision",
                                    "interpret"))
def _os_call(x3d, taps, n_j, rows, precision, interpret):
    B, n_rows_pad, s = x3d.shape
    k = taps.shape[-1]
    # MT[t, a] = taps[t + k - 1 - a] via the gather-free tile trick
    # (ops/convolve._conv_os_matmul documents why: t*(k+s) = -t mod
    # k+s+1), then front-pad and reshape the frame columns into the
    # per-shift [step, step] factors mbt[j][t, b] = taps[j*s + t - b]
    w = jnp.pad(jnp.flip(taps, axis=-1), (0, s + 1))
    mt = jnp.tile(w, s)[: s * (k + s)].reshape(s, k + s)[:, : s + k - 1]
    mtp = jnp.pad(mt, [(0, 0), (n_j * s - (s + k - 1), 0)])
    mbt = jnp.moveaxis(jnp.flip(mtp.reshape(s, n_j, s), axis=1), 1, 0)
    kernel = functools.partial(_os_kernel, n_j=n_j, rows=rows,
                               precision=jax.lax.Precision(precision))
    return pl.pallas_call(
        kernel,
        grid=(B, n_rows_pad // rows),
        in_specs=[pl.BlockSpec((n_j, s, s), lambda b, t: (0, 0, 0)),
                  pl.BlockSpec((1, rows, s), lambda b, t: (b, t, 0))],
        out_specs=pl.BlockSpec((1, rows, s), lambda b, t: (b, t, 0)),
        out_shape=jax.ShapeDtypeStruct((B, n_rows_pad, s), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n_j - 1 + rows, s), jnp.float32),
                        pltpu.VMEM((n_j - 1, s), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * B * n_rows_pad * s * n_j * s,
            bytes_accessed=4 * (2 * B * n_rows_pad * s + n_j * s * s),
            transcendentals=0),
        interpret=interpret,
    )(mbt, x3d)


def overlap_save_pallas(x, taps, step: int = PALLAS_OS_STEP,
                        rows: int = PALLAS_OS_ROWS, precision="highest",
                        interpret=None):
    """Full linear convolution ``y[..., n+k-1] = x * taps`` as a fused
    overlap-save Pallas kernel.

    The XLA block-matmul formulation
    (:func:`veles.simd_tpu.ops.convolve._conv_os_matmul`) materializes
    its frames operand as J concatenated shifted copies of the signal —
    J ~ 1 + k/step passes of x through HBM before the matmul reads it.
    This kernel streams x through VMEM exactly once: each grid step
    loads ``rows`` signal blocks, keeps the k-1-sample halo from the
    previous step in a VMEM carry, and runs the same Toeplitz matmul as
    ``n_j`` per-shift ``[rows, step] @ [step, step]`` MXU dots over
    row-blocks of the in-VMEM window (derivation at :func:`_os_kernel`).

    ``taps`` must be 1D in CONVOLUTION orientation (callers flip for
    correlation, like the other kernels here) with at least 2 taps
    (a 1-tap filter has no halo — use the direct path).  ``precision``
    is the MXU pass count (``"highest"`` = 6-pass bf16 = full f32).
    Leading batch dims on ``x`` ride along (each batch row restarts the
    carry).  ``interpret=None`` auto-selects: compiled Mosaic on TPU,
    interpreter elsewhere (the CPU test path).
    """
    taps = jnp.asarray(taps, jnp.float32)
    if taps.ndim != 1:
        raise ValueError("taps must be 1D")
    k = taps.shape[-1]
    if k < 2:
        raise ValueError("overlap-save needs >= 2 taps (no halo to "
                         "carry at k=1; use the direct path)")
    s = int(step)
    if s % 128 != 0:
        raise ValueError(f"step {s} must be a 128-lane multiple")
    n = x.shape[-1]
    out_len = n + k - 1
    jb = -(-(k - 1) // s)
    n_j = jb + 1
    if interpret is None:
        interpret = not pallas_available()
    n_rows = -(-out_len // s)
    # shrink the row tile for short signals (8-sublane multiples), but
    # never below the halo row count the carry update slices
    r = min(int(rows), max(8, ((n_rows + 7) // 8) * 8))
    r = max(r, ((jb + 7) // 8) * 8)
    if not interpret and not fits_vmem_os(k, s, r):
        raise ValueError(
            f"overlap-save factors for k={k}, step={s} exceed the "
            "kernel VMEM budget; keep this shape on the XLA path")
    rows_pad = -(-n_rows // r) * r
    batch_shape = x.shape[:-1]
    x2d = jnp.asarray(x, jnp.float32).reshape(-1, n)
    x3d = jnp.pad(x2d, [(0, 0), (0, rows_pad * s - n)]).reshape(
        -1, rows_pad, s)
    out = _os_call(x3d, taps, n_j, r, str(precision), bool(interpret))
    return out.reshape(x2d.shape[0], rows_pad * s)[
        :, :out_len].reshape(batch_shape + (out_len,))


# ---------------------------------------------------------------------------
# fused STFT (matmul DFT on the MXU, frame overlap carried in VMEM)
# ---------------------------------------------------------------------------


def _stft_basis_blocks(frame_length: int, hop: int,
                       window) -> np.ndarray:
    """``[r, hop, 2*bins_pad]`` windowed real-DFT basis blocks.

    Shift ``j`` holds rows ``j*hop .. (j+1)*hop`` of the ``[L, 2*bp]``
    basis whose columns ``[0:bins]`` accumulate ``Re X[k] = sum_n
    w[n] x[n] cos(2 pi n k / L)`` and columns ``[bins_pad:bins_pad +
    bins]`` accumulate ``Im X[k] = -sum_n w[n] x[n] sin(...)`` — the
    window is folded in, and ``bins`` is padded to the 128-lane
    boundary so every shift-dot is a full-lane MXU operand."""
    L, s = int(frame_length), int(hop)
    bins = L // 2 + 1
    bins_pad = -(-bins // 128) * 128
    n = np.arange(L)[:, None]
    k = np.arange(bins)[None, :]
    ang = 2.0 * np.pi * n * k / L
    w = np.asarray(window, np.float64)[:, None]
    full = np.zeros((L, 2 * bins_pad), np.float32)
    full[:, :bins] = (w * np.cos(ang)).astype(np.float32)
    full[:, bins_pad:bins_pad + bins] = (
        -w * np.sin(ang)).astype(np.float32)
    return full.reshape(L // s, s, 2 * bins_pad)


def _stft_kernel(basis_ref, x_ref, o_ref, w_ref, carry_ref, *, r, rows,
                 precision):
    """One STFT tile: ``rows`` frames of ``2*bins_pad`` DFT lanes.

    Frame f covers hop-blocks ``[f, f + r)``; with ``W = [carry;
    x_tile]`` (the previous tile's last ``r - 1`` blocks prefixed, the
    overlap carried across grid steps in VMEM exactly like
    :func:`_os_kernel`'s halo), output row i is frame ``t*rows - (r-1)
    + i`` and decomposes into r shift-dots

        out[i] = sum_j W[i + j] @ basis[j]

    each a ``[rows, hop] x [hop, 2*bins_pad]`` MXU dot against the
    VMEM-resident windowed basis block — the window multiply and the
    DFT happen inside the same dots, and the frames tensor the XLA
    routes materialize never exists.  The first ``r - 1`` output rows
    of each batch row read zero carry (leading frames that start
    before the signal) and are sliced off by the caller."""
    jb = r - 1
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        # first tile of each batch row: zero overlap history
        carry_ref[...] = jnp.zeros(carry_ref.shape, carry_ref.dtype)

    w_ref[0:jb, :] = carry_ref[...]
    w_ref[jb:, :] = x_ref[0]
    for j in range(r):
        lhs = w_ref[j:j + rows, :]
        term = jax.lax.dot_general(
            lhs, basis_ref[j],
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)
        o_ref[0] = term if j == 0 else o_ref[0] + term
    carry_ref[...] = x_ref[0, rows - jb:, :]


@functools.partial(obs.instrumented_jit, op="stft",
                   route="pallas_fused",
                   static_argnames=("r", "rows", "bins", "precision",
                                    "interpret"))
def _stft_call(x3d, basis, r, rows, bins, precision, interpret):
    B, blocks_pad, s = x3d.shape
    bp2 = basis.shape[-1]
    kernel = functools.partial(_stft_kernel, r=r, rows=rows,
                               precision=jax.lax.Precision(precision))
    out = pl.pallas_call(
        kernel,
        grid=(B, blocks_pad // rows),
        in_specs=[pl.BlockSpec((r, s, bp2), lambda b, t: (0, 0, 0)),
                  pl.BlockSpec((1, rows, s), lambda b, t: (b, t, 0))],
        out_specs=pl.BlockSpec((1, rows, bp2), lambda b, t: (b, t, 0)),
        out_shape=jax.ShapeDtypeStruct((B, blocks_pad, bp2),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((r - 1 + rows, s), jnp.float32),
                        pltpu.VMEM((r - 1, s), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * B * blocks_pad * r * s * bp2,
            bytes_accessed=4 * (B * blocks_pad * (s + bp2)
                                + r * s * bp2),
            transcendentals=0),
        interpret=interpret,
    )(basis, x3d)
    half = bp2 // 2
    return jax.lax.complex(out[..., :bins], out[..., half:half + bins])


def stft_pallas(x, frame_length: int, hop: int, window=None,
                rows: int = PALLAS_STFT_ROWS, precision="highest",
                interpret=None, basis=None):
    """Short-time Fourier transform as one fused Pallas kernel:
    ``x[..., n] -> complex64 [..., frames, frame_length // 2 + 1]``
    with ``frames = 1 + (n - frame_length) // hop`` (the
    :func:`veles.simd_tpu.ops.spectral.stft` contract).

    The XLA routes materialize a ``[frames, frame_length]`` tensor —
    ``frame_length / hop`` copies of the signal through HBM before the
    window multiply and transform read it.  This kernel streams x
    through VMEM exactly once: each grid step loads ``rows`` hop-blocks,
    keeps the ``frame_length - hop`` sample overlap from the previous
    step in a VMEM carry, and evaluates window-multiply + real-DFT as
    ``r = frame_length // hop`` per-shift ``[rows, hop] @ [hop,
    2*bins_pad]`` MXU dots against the resident windowed basis
    (derivation at :func:`_stft_kernel`).

    Contract: ``hop`` must divide ``frame_length`` (the standard STFT
    overlap family — the carry is whole hop-blocks), be a 128-lane
    multiple, and ``frame_length > hop`` (no overlap means no carry —
    use the rdft-matmul route).  ``window`` is resolved like
    :func:`~veles.simd_tpu.ops.spectral.stft` (None = periodic Hann);
    ``basis`` overrides the windowed basis blocks (the spectral
    dispatch layer passes its LRU-cached copy).  ``precision`` is the
    MXU pass count; ``interpret=None`` auto-selects compiled Mosaic on
    TPU, interpreter elsewhere (the CPU test path)."""
    L, s = int(frame_length), int(hop)
    if L % s != 0:
        raise ValueError(
            f"fused STFT needs hop | frame_length, got {s}, {L} "
            "(use the rdft_matmul route for non-dividing hops)")
    if s % 128 != 0:
        raise ValueError(f"hop {s} must be a 128-lane multiple")
    r = L // s
    if r < 2:
        raise ValueError("fused STFT needs frame_length > hop (no "
                         "overlap to carry; use the rdft_matmul route)")
    n = x.shape[-1]
    if n < L:
        raise ValueError(f"signal length {n} < frame_length {L}")
    frames = 1 + (n - L) // s
    jb = r - 1
    if interpret is None:
        interpret = not pallas_available()
    blocks = -(-n // s)
    # shrink the row tile for short signals (8-sublane multiples), but
    # never below the carry's block count
    r_tile = min(int(rows), max(8, -(-blocks // 8) * 8))
    r_tile = max(r_tile, -(-jb // 8) * 8)
    if not interpret and not fits_vmem_stft(L, s, r_tile):
        raise ValueError(
            f"fused STFT basis for frame_length={L}, hop={s} exceeds "
            "the kernel VMEM budget; keep this shape on the XLA path")
    blocks_pad = -(-blocks // r_tile) * r_tile
    batch_shape = x.shape[:-1]
    x2d = jnp.asarray(x, jnp.float32).reshape(-1, n)
    x3d = jnp.pad(x2d, [(0, 0), (0, blocks_pad * s - n)]).reshape(
        -1, blocks_pad, s)
    if basis is None:
        from veles.simd_tpu.ops.spectral import _resolve_window

        basis = _stft_basis_blocks(L, s, _resolve_window(window, L))
    out = _stft_call(x3d, jnp.asarray(basis), r, r_tile, L // 2 + 1,
                     str(precision), bool(interpret))
    out = out[:, jb:jb + frames, :]
    return out.reshape(batch_shape + (frames, L // 2 + 1))
