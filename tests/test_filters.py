"""Nonlinear/smoothing filter family vs scipy and the oracle twins.

The reference has no nonlinear filtering (its toolkit is linear
convolution, ``/root/reference/src/convolve.c``) — this family is a new
capability.  scipy.signal is the external ground truth; the ``*_na``
twins cross-validate the XLA gather/sort and conv paths (the
reference's two-implementations discipline,
``/root/reference/tests/matrix.cc:94-98``).
"""

import numpy as np
import pytest

from scipy import signal as ss

from veles.simd_tpu.ops import filters as fl

RNG = np.random.RandomState(81)


class TestMedianRank:
    @pytest.mark.parametrize("k", [3, 5, 9, 15])
    def test_medfilt_matches_scipy(self, k):
        x = RNG.randn(301)
        got = np.asarray(fl.medfilt(x.astype(np.float32), k, simd=True))
        np.testing.assert_allclose(got, ss.medfilt(x, k), atol=1e-6)

    def test_medfilt_oracle(self):
        x = RNG.randn(2, 128)
        np.testing.assert_allclose(fl.medfilt_na(x, 7),
                                   np.stack([ss.medfilt(r, 7) for r in x]),
                                   atol=1e-12)

    def test_impulse_rejection(self):
        """The defining property: isolated spikes vanish entirely —
        no linear filter does this."""
        x = np.zeros(100, np.float32)
        x[30] = 100.0
        y = np.asarray(fl.medfilt(x, 5, simd=True))
        assert np.max(np.abs(y)) == 0.0

    def test_order_filter_matches_scipy(self):
        x = RNG.randn(200)
        for rank in (0, 2, 6):
            got = np.asarray(fl.order_filter(x.astype(np.float32), rank,
                                             7, simd=True))
            want = ss.order_filter(x, np.ones(7), rank)
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_order_filter_min_max(self):
        """rank 0 is a running min, rank k-1 a running max (erosion /
        dilation)."""
        x = RNG.randn(64).astype(np.float32)
        lo = np.asarray(fl.order_filter(x, 0, 3, simd=True))
        hi = np.asarray(fl.order_filter(x, 2, 3, simd=True))
        assert np.all(lo <= x + 1e-6)
        assert np.all(hi >= x - 1e-6)

    @pytest.mark.parametrize("ksize", [3, 5, (3, 7), (5, 3)])
    def test_medfilt2d_matches_scipy(self, ksize):
        img = RNG.randn(24, 37)
        got = np.asarray(fl.medfilt2d(img.astype(np.float32), ksize,
                                      simd=True))
        np.testing.assert_allclose(got, ss.medfilt2d(img, ksize),
                                   atol=1e-6)

    def test_medfilt2d_batched(self):
        imgs = RNG.randn(3, 16, 20)
        got = np.asarray(fl.medfilt2d(imgs.astype(np.float32), 3,
                                      simd=True))
        want = np.stack([ss.medfilt2d(i, 3) for i in imgs])
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_contracts(self):
        with pytest.raises(ValueError, match="odd"):
            fl.medfilt(np.zeros(8, np.float32), 4)
        with pytest.raises(ValueError, match="rank"):
            fl.order_filter(np.zeros(8, np.float32), 7, 7)
        with pytest.raises(ValueError, match="H, W"):
            fl.medfilt2d(np.zeros(8, np.float32), 3)


class TestSavgol:
    CASES = [(11, 3, 0), (9, 2, 1), (15, 4, 2), (5, 4, 0)]

    @pytest.mark.parametrize("wl,po,deriv", CASES)
    def test_coeffs_match_scipy(self, wl, po, deriv):
        np.testing.assert_allclose(
            fl.savgol_coeffs(wl, po, deriv),
            ss.savgol_coeffs(wl, po, deriv=deriv), atol=1e-12)

    @pytest.mark.parametrize("mode", ["interp", "constant", "nearest"])
    @pytest.mark.parametrize("wl,po,deriv", CASES[:3])
    def test_filter_matches_scipy(self, wl, po, deriv, mode):
        x = RNG.randn(2, 180).astype(np.float32)
        got = np.asarray(fl.savgol_filter(x, wl, po, deriv=deriv,
                                          mode=mode, simd=True))
        want = ss.savgol_filter(x.astype(np.float64), wl, po,
                                deriv=deriv, mode=mode, axis=-1)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_oracle_matches_scipy(self):
        x = RNG.randn(150)
        np.testing.assert_allclose(
            fl.savgol_filter_na(x, 11, 3),
            ss.savgol_filter(x, 11, 3), atol=1e-10)

    def test_polynomial_passthrough(self):
        """A degree-<=polyorder polynomial is reproduced exactly
        (including the interp edges) — the SG defining property."""
        t = np.linspace(-1, 1, 101)
        x = (0.3 + 1.7 * t - 2.0 * t ** 2 + 0.5 * t ** 3)
        y = np.asarray(fl.savgol_filter(x.astype(np.float32), 13, 3,
                                        simd=True))
        np.testing.assert_allclose(y, x, atol=1e-4)

    def test_derivative_of_ramp(self):
        """d/dt of a ramp is its slope everywhere."""
        x = 0.25 * np.arange(80, dtype=np.float32)
        d = np.asarray(fl.savgol_filter(x, 9, 2, deriv=1, simd=True))
        np.testing.assert_allclose(d, 0.25, atol=1e-4)

    def test_contracts(self):
        x = np.zeros(20, np.float32)
        with pytest.raises(ValueError, match="polyorder"):
            fl.savgol_filter(x, 5, 5)
        with pytest.raises(ValueError, match="interp"):
            fl.savgol_filter(x, 21, 2)
        with pytest.raises(ValueError, match="mode"):
            fl.savgol_filter(x, 5, 2, mode="wrap")


class TestFirwin:
    CASES = [
        ((33, 0.4), {}),
        ((32, 0.25), {}),
        ((33, 0.3), {"pass_zero": False}),
        ((41, [0.2, 0.5]), {"pass_zero": False}),
        ((41, [0.2, 0.5]), {"pass_zero": True}),
        ((21, 0.6), {"window": "hann"}),
        ((55, [0.1, 0.3, 0.6]), {}),
        ((33, 0.3), {"pass_zero": "highpass"}),
        ((33, 0.4), {"pass_zero": "lowpass"}),
        ((41, [0.2, 0.5]), {"pass_zero": "bandpass"}),
        ((41, [0.2, 0.5]), {"pass_zero": "bandstop"}),
        ((32, [0.2, 0.5]), {"pass_zero": False}),  # even-tap bandpass
    ]

    @pytest.mark.parametrize("args,kw", CASES)
    def test_matches_scipy(self, args, kw):
        np.testing.assert_allclose(fl.firwin(*args, **kw),
                                   ss.firwin(*args, **kw), atol=1e-12)

    def test_lowpass_dc_gain(self):
        h = fl.firwin(51, 0.35)
        assert abs(np.sum(h) - 1.0) < 1e-12

    def test_contracts(self):
        with pytest.raises(ValueError, match="odd"):
            fl.firwin(32, 0.3, pass_zero=False)   # highpass, even
        with pytest.raises(ValueError, match="odd"):
            fl.firwin(32, [0.2, 0.5], pass_zero=True)  # bandstop, even
        with pytest.raises(ValueError, match="increasing"):
            fl.firwin(31, [0.5, 0.2])
        with pytest.raises(ValueError, match="window"):
            fl.firwin(31, 0.3, window="kaiser")
        with pytest.raises(ValueError, match="pass_zero"):
            fl.firwin(31, 0.3, pass_zero="notch")
        with pytest.raises(ValueError, match="cutoff"):
            fl.firwin(31, [0.2, 0.5], pass_zero="highpass")

    @pytest.mark.parametrize("window", [
        ("kaiser", 8.6), ("kaiser", 2.0), ("tukey", 0.3),
        "blackman", "flattop", "bartlett"])
    def test_general_windows_match_scipy(self, window):
        np.testing.assert_allclose(
            fl.firwin(41, 0.35, window=window),
            ss.firwin(41, 0.35, window=window), atol=1e-12)

    def test_window_array_and_bad_shape(self):
        win = np.hamming(31)
        np.testing.assert_allclose(fl.firwin(31, 0.4, window=win),
                                   ss.firwin(31, 0.4), atol=1e-12)
        with pytest.raises(ValueError, match="shape"):
            fl.firwin(31, 0.4, window=np.ones(30))
        with pytest.raises(ValueError, match="no parameter"):
            fl.firwin(31, 0.4, window=("hamming", 1.0))

    def test_kaiserord_design_flow(self):
        """The classic attenuation-driven flow: kaiserord -> firwin
        with a kaiser window, parity with scipy at every step."""
        numtaps, beta = fl.kaiserord(65.0, 0.08)
        nt_s, beta_s = ss.kaiserord(65.0, 0.08)
        assert (numtaps, beta) == (nt_s, beta_s)
        got = fl.firwin(numtaps, 0.4, window=("kaiser", beta))
        want = ss.firwin(nt_s, 0.4, window=("kaiser", beta_s))
        np.testing.assert_allclose(got, want, atol=1e-12)
        assert fl.kaiser_beta(65.0) == ss.kaiser_beta(65.0)
        assert np.isclose(fl.kaiser_atten(numtaps, 0.08),
                          ss.kaiser_atten(nt_s, 0.08))
        with pytest.raises(ValueError, match="too small"):
            fl.kaiserord(5.0, 0.1)

    def test_firwin2_kaiser_window(self):
        got = fl.firwin2(65, [0.0, 0.3, 0.5, 1.0], [1.0, 1.0, 0.0, 0.0],
                         window=("kaiser", 6.0))
        want = ss.firwin2(65, [0.0, 0.3, 0.5, 1.0],
                          [1.0, 1.0, 0.0, 0.0], window=("kaiser", 6.0))
        np.testing.assert_allclose(got, want, atol=1e-7)

    def test_usable_with_lfilter(self):
        """Design → filter end-to-end: firwin taps through the IIR
        module's FIR path attenuate an out-of-band tone."""
        from veles.simd_tpu.ops import iir

        t = np.arange(2048)
        x = (np.sin(0.1 * np.pi * t) + np.sin(0.8 * np.pi * t)) \
            .astype(np.float32)
        h = fl.firwin(101, 0.4)
        y = np.asarray(iir.lfilter(h, [1.0], x, simd=True))
        # steady state: low tone passes, high tone gone
        core = y[200:]
        hi_resid = core - np.sin(0.1 * np.pi * t[200:] - 0.1 * np.pi * 50)
        assert np.sqrt(np.mean(hi_resid ** 2)) < 0.02


class TestWiener:
    def test_matches_scipy(self):
        x = RNG.randn(500)
        for k in (3, 7, 11):
            got = np.asarray(fl.wiener(x.astype(np.float32), k,
                                       simd=True))
            np.testing.assert_allclose(got, ss.wiener(x, k), atol=1e-4)

    def test_fixed_noise_oracle_exact(self):
        x = RNG.randn(300)
        np.testing.assert_allclose(fl.wiener_na(x, 5, noise=0.5),
                                   ss.wiener(x, 5, noise=0.5),
                                   atol=1e-12)

    def test_adaptive_behaviour(self):
        """Flat regions are smoothed toward the mean; a strong edge is
        preserved far better than a boxcar of the same size."""
        n = 400
        step = np.r_[np.zeros(n // 2), np.ones(n // 2)]
        x = (step + 0.05 * RNG.randn(n)).astype(np.float32)
        y = np.asarray(fl.wiener(x, 11))
        flat_rms = np.sqrt(np.mean((y[50:150] - 0.0) ** 2))
        assert flat_rms < 0.02          # noise crushed on the flat
        assert y[n // 2 + 6] > 0.9      # edge still sharp shortly after

    def test_batched(self):
        x = RNG.randn(3, 200).astype(np.float32)
        got = np.asarray(fl.wiener(x, 7, simd=True))
        want = np.stack([ss.wiener(r.astype(np.float64), 7) for r in x])
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_contracts(self):
        with pytest.raises(ValueError, match="mysize"):
            fl.wiener(np.zeros(8, np.float32), 4)

    def test_dc_offset_precision(self):
        """f32 E[x^2]-mean^2 would catastrophically cancel at a 1e3 DC
        offset; the windowed-demeaned form must not (review regression,
        including an XLA-refusion variant that broke a decomposed
        formulation under jit)."""
        x = 1000.0 + 0.1 * RNG.randn(2000)
        got = np.asarray(fl.wiener(x.astype(np.float32), 11,
                                   noise=0.01, simd=True))
        want = ss.wiener(x, 11, noise=0.01)
        assert np.max(np.abs(got - want)) < 5e-3

    def test_long_signal_precision(self):
        """No global-accumulator error growth on a 1M-sample signal."""
        x = RNG.randn(1 << 20)
        got = np.asarray(fl.wiener(x.astype(np.float32), 9, simd=True))
        want = ss.wiener(x, 9)
        assert np.max(np.abs(got[100:-100] - want[100:-100])) < 1e-4


class TestFirwin2Deconvolve:
    @pytest.mark.parametrize("args", [
        (65, [0, 0.3, 0.3, 1], [1, 1, 0, 0]),      # brick-wall lowpass
        (64, [0, 0.5, 1], [1, 1, 0]),              # even taps, 0 at Nyq
        (33, [0, 0.2, 0.5, 1], [0, 1, 0.5, 0]),    # shaped response
    ])
    def test_firwin2_matches_scipy(self, args):
        np.testing.assert_allclose(fl.firwin2(*args), ss.firwin2(*args),
                                   atol=1e-12)

    def test_firwin2_response_tracks_breakpoints(self):
        h = fl.firwin2(101, [0, 0.4, 0.5, 1], [1, 1, 0, 0])
        from veles.simd_tpu.ops import iir

        _, resp = iir.frequency_response(h, [1.0], 512)
        w = np.linspace(0, 1, 512, endpoint=False)
        assert np.abs(resp[w < 0.35]).min() > 0.98
        assert np.abs(resp[w > 0.6]).max() < 0.01

    def test_firwin2_contracts(self):
        with pytest.raises(ValueError, match="start at 0"):
            fl.firwin2(33, [0.1, 1], [1, 0])
        with pytest.raises(ValueError, match="Type II"):
            fl.firwin2(32, [0, 1], [1, 1])
        with pytest.raises(ValueError, match="nondecreasing"):
            fl.firwin2(33, [0, 0.5, 0.3, 1], [1, 1, 0, 0])

    def test_deconvolve_matches_scipy(self):
        num = np.convolve([1, 2, 3, 4, 5.0], [1, 0.5, 0.25])
        num = num + np.r_[np.zeros(5), [1e-2, -2e-2]]
        gq, gr = fl.deconvolve(num, [1, 0.5, 0.25])
        wq, wr = ss.deconvolve(num, [1, 0.5, 0.25])
        np.testing.assert_allclose(gq, wq, atol=1e-12)
        np.testing.assert_allclose(gr, wr, atol=1e-12)

    def test_deconvolve_round_trip(self):
        rng = np.random.RandomState(14)
        q = rng.randn(20)
        d = np.r_[1.0, rng.randn(4) * 0.3]
        sig = np.convolve(d, q)
        gq, gr = fl.deconvolve(sig, d)
        np.testing.assert_allclose(gq, q, atol=1e-10)
        np.testing.assert_allclose(gr, 0.0, atol=1e-10)

    def test_deconvolve_contracts(self):
        with pytest.raises(ValueError, match="nonzero"):
            fl.deconvolve([1.0, 2.0], [0.0, 1.0])
        q, r = fl.deconvolve([1.0], [1.0, 2.0, 3.0])
        assert len(q) == 0 and np.array_equal(r, [1.0])  # scipy: empty
        with pytest.raises(ValueError, match="1D"):
            fl.deconvolve(np.ones((2, 3)), [1.0])

    def test_firwin2_grid_aligned_breakpoint(self):
        """A brick wall landing exactly on an interpolation grid point
        must sample the jump midpoint like scipy (review regression:
        the symmetric eps nudge)."""
        args = (65, [0, 0.25, 0.25, 1], [1, 1, 0, 0])
        np.testing.assert_allclose(fl.firwin2(*args), ss.firwin2(*args),
                                   atol=1e-12)


class TestRemez:
    """Parks-McClellan equiripple design vs scipy: the achieved
    weighted minimax ripple must match (the optimum is unique; tap
    differences are just each implementation's convergence noise)."""

    CASES = [
        (65, [0, 0.18, 0.22, 0.5], [1, 0], None),
        (64, [0, 0.18, 0.22, 0.5], [1, 0], None),
        (101, [0, 0.1, 0.15, 0.35, 0.4, 0.5], [0, 1, 0], [1, 1, 1]),
        (33, [0, 0.2, 0.3, 0.5], [1, 0], [1, 10]),
        (75, [0.05, 0.12, 0.18, 0.3, 0.36, 0.45], [1, 0, 1], [1, 5, 1]),
        (17, [0, 0.1, 0.2, 0.5], [1, 0], None),
        (48, [0, 0.15, 0.25, 0.35, 0.42, 0.5], [1, 0.5, 0], None),
    ]

    @staticmethod
    def _ripple(taps, bands, desired, weight):
        from scipy import signal as ss

        w, h = ss.freqz(taps, worN=8192, fs=1.0)
        h = np.abs(h)
        rr = 0.0
        for b, d in enumerate(desired):
            m = (w >= bands[2 * b]) & (w <= bands[2 * b + 1])
            wt = 1.0 if weight is None else weight[b]
            rr = max(rr, wt * float(np.max(np.abs(h[m] - d))))
        return rr

    @pytest.mark.parametrize("numtaps,bands,desired,weight", CASES)
    def test_achieves_scipy_ripple(self, numtaps, bands, desired,
                                   weight):
        from scipy import signal as ss

        mine = fl.remez(numtaps, bands, desired, weight=weight)
        sp = ss.remez(numtaps, bands, desired, weight=weight, fs=1.0)
        rm = self._ripple(mine, bands, desired, weight)
        rs = self._ripple(sp, bands, desired, weight)
        assert len(mine) == numtaps
        assert rm <= rs * 1.02 + 1e-12

    def test_linear_phase_symmetry(self):
        taps = fl.remez(51, [0, 0.2, 0.3, 0.5], [1, 0])
        np.testing.assert_allclose(taps, taps[::-1], atol=1e-12)
        taps2 = fl.remez(50, [0, 0.2, 0.3, 0.5], [1, 0])
        np.testing.assert_allclose(taps2, taps2[::-1], atol=1e-12)

    def test_fs_scaling(self):
        a = fl.remez(41, [0, 180, 220, 500], [1, 0], fs=1000.0)
        b = fl.remez(41, [0, 0.18, 0.22, 0.5], [1, 0])
        np.testing.assert_allclose(a, b, atol=1e-12)

    def test_usable_with_lfilter(self):
        """Design -> filter: a stopband tone is crushed."""
        from veles.simd_tpu.ops import iir

        taps = fl.remez(65, [0, 0.18, 0.25, 0.5], [1, 0])
        t = np.arange(2048)
        tone = np.cos(2 * np.pi * 0.4 * t).astype(np.float32)
        out = np.asarray(iir.lfilter(taps, [1.0], tone, simd=True))
        assert np.max(np.abs(out[200:])) < 1e-2

    def test_contracts(self):
        with pytest.raises(ValueError, match="even number"):
            fl.remez(33, [0, 0.2, 0.3], [1, 0])
        with pytest.raises(ValueError, match="increase"):
            fl.remez(33, [0, 0.3, 0.2, 0.5], [1, 0])
        with pytest.raises(ValueError, match="desired"):
            fl.remez(33, [0, 0.2, 0.3, 0.5], [1, 0, 1])
        with pytest.raises(ValueError, match="weight"):
            fl.remez(33, [0, 0.2, 0.3, 0.5], [1, 0], weight=[1, -1])
        with pytest.raises(ValueError, match="Nyquist|zero gain"):
            fl.remez(32, [0, 0.2, 0.3, 0.5], [1, 1])


class TestRankNetwork:
    """The Batcher compare-exchange path must agree with the sort path
    and scipy across ranks and window sizes (round-5 fast path)."""

    @pytest.mark.parametrize("k", [3, 5, 7, 9, 15, 21, 31])
    def test_every_rank_matches_sort(self, k):
        rng = np.random.RandomState(k)
        x = rng.randn(4, 257).astype(np.float32)
        for rank in (0, k // 2, k - 1):
            got = np.asarray(fl.order_filter(x, rank, k, simd=True))
            want = fl.order_filter_na(x, rank, k)
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_large_k_uses_sort_path(self):
        rng = np.random.RandomState(99)
        x = rng.randn(300).astype(np.float32)
        got = np.asarray(fl.medfilt(x, 35, simd=True))   # 35 > 32
        np.testing.assert_allclose(got, fl.medfilt_na(x, 35), atol=1e-6)

    def test_medfilt2d_network_vs_scipy(self):
        import scipy.signal as ss

        rng = np.random.RandomState(100)
        img = rng.randn(31, 45).astype(np.float32)
        for k in (3, 5):
            got = np.asarray(fl.medfilt2d(img, k, simd=True))
            want = ss.medfilt2d(img, k)
            np.testing.assert_allclose(got, want, atol=1e-6)
        got = np.asarray(fl.medfilt2d(img, 7, simd=True))  # 49 > 32
        np.testing.assert_allclose(got, ss.medfilt2d(img, 7), atol=1e-6)

    def test_nan_semantics_match_sort_path(self):
        """NaNs order last (jnp.sort semantics) on the network path too
        — review finding: raw min/max smeared NaN across the window."""
        x = np.array([1, np.nan, 2, 3, 4], np.float32)
        got = np.asarray(fl.medfilt(x, 3, simd=True))
        win = fl._window_view_1d(x, 3, np)
        want = np.sort(win, axis=-1)[..., 1].astype(np.float32)
        np.testing.assert_array_equal(got, want)
        # all-NaN window -> NaN out (rank beyond the non-NaN count)
        xa = np.array([np.nan, np.nan, np.nan, 1.0], np.float32)
        got = np.asarray(fl.medfilt(xa, 3, simd=True))
        wina = fl._window_view_1d(xa, 3, np)
        wanta = np.sort(wina, axis=-1)[..., 1].astype(np.float32)
        np.testing.assert_array_equal(got, wanta)

    def test_wiener_large_window_fallback(self):
        """mysize > the lane cap takes the window-matrix path; parity
        with scipy must hold on both."""
        import scipy.signal as ss

        rng = np.random.RandomState(101)
        x = rng.randn(400).astype(np.float32)
        for k in (31, 35):          # straddle _RANK_NETWORK_MAX_K
            got = np.asarray(fl.wiener(x, k, simd=True))
            want = ss.wiener(x.astype(np.float64), k)
            np.testing.assert_allclose(got, want, atol=1e-4)
