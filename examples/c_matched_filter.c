/* c_matched_filter.c — standalone C consumer of libveles_simd.so.
 *
 * The end-to-end workflow a C user of the original veles.simd library
 * would port: build a matched filter, stream a long signal through it
 * chunk by chunk, and locate the embedded pulse.  Compute runs on the
 * XLA backend (TPU when available) through the embedded-CPython bridge.
 *
 * Build + run:   make -C csrc demo
 * (or)          cc examples/c_matched_filter.c -Icsrc -Lcsrc/build \
 *                  -lveles_simd -Wl,-rpath,csrc/build -lm -o demo && \
 *               VELES_SIMD_PYROOT=. ./demo
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "veles_simd.h"

int main(void) {
  const size_t n = 1 << 16, k = 127, chunk = 8192, pos = 40000;
  float *x = mallocf(n), *h = mallocf(k), *y = mallocf(n + k - 1);
  if (!x || !h || !y) return 1;

  /* template: a chirp burst; signal: noise + the template at `pos` */
  srand(7);
  for (size_t i = 0; i < k; i++)
    h[i] = sinf(0.002f * (float)i * (float)i);
  for (size_t i = 0; i < n; i++)
    x[i] = 0.1f * ((float)rand() / (float)RAND_MAX - 0.5f);
  for (size_t i = 0; i < k; i++) x[pos + i] += h[i];

  /* stream the cross-correlation chunk by chunk (reverse=1) */
  VelesStreamingConvolution *sc =
      streaming_convolve_initialize(h, k, chunk, /*reverse=*/1, /*simd=*/1);
  if (!sc) {
    fprintf(stderr, "init failed: %s\n", veles_simd_last_error());
    return 1;
  }
  for (size_t i = 0; i < n; i += chunk) {
    if (streaming_convolve_process(sc, x + i, y + i) != 0) {
      fprintf(stderr, "process failed: %s\n", veles_simd_last_error());
      return 1;
    }
  }
  if (streaming_convolve_flush(sc, y + n) != 0) return 1;
  streaming_convolve_finalize(sc);

  /* peak of the matched-filter output marks the pulse */
  size_t best = 0;
  for (size_t i = 1; i < n + k - 1; i++)
    if (y[i] > y[best]) best = i;
  printf("pulse planted at %zu, matched filter peak at %zu (- (k-1) = %zu)\n",
         pos, best, best - (k - 1));
  int ok = (best - (k - 1)) == pos;

  /* sanity: the oracle path agrees on the peak */
  float *y0 = mallocf(n + k - 1);
  if (cross_correlate_simd(0, x, n, h, k, y0) != 0) return 1;
  size_t best0 = 0;
  for (size_t i = 1; i < n + k - 1; i++)
    if (y0[i] > y0[best0]) best0 = i;
  ok = ok && best0 == best;
  printf("oracle peak agrees: %s\n", ok ? "yes" : "NO");

  free(x); free(h); free(y); free(y0);
  return ok ? 0 : 1;
}
