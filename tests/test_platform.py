"""Tests for the platform layer (``utils/platform.py``).

The backend-selection knowledge concentrated here (config-level pins
that beat the sitecustomize, subprocess probes that can't hang, restore
semantics) is what every entry point leans on — worth direct coverage.
"""

import os


from veles.simd_tpu.utils import platform as plat


def test_set_cpu_env_replaces_count_flag():
    old = os.environ.get("XLA_FLAGS")
    try:
        os.environ["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=3 --other_flag=1")
        plat.set_cpu_env(5)
        flags = os.environ["XLA_FLAGS"].split()
        assert "--xla_force_host_platform_device_count=5" in flags
        assert "--other_flag=1" in flags
        assert sum("device_count" in f for f in flags) == 1
    finally:
        if old is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = old


def test_probe_device_count_sees_pinned_cpu():
    # conftest pinned this process to an 8-device CPU platform via
    # jax.config; the probe must replicate that pin into its subprocess
    # (env alone would be stomped by the sitecustomize) — all 8 virtual
    # devices visible, not just "some platform answered"
    assert plat.probe_device_count(timeout=120.0) == 8


def test_require_reachable_device_passes_here():
    plat.require_reachable_device(timeout=120.0)  # must not raise


def test_backend_live_is_true_under_pytest():
    # conftest initialized the CPU backend at session start
    assert plat._backend_live()


def test_probe_subprocess_failure_detail():
    # unreasonably small timeout forces the TimeoutExpired branch
    count, detail = plat._probe_subprocess(timeout=0.01)
    assert count == 0
    assert "timed out" in detail


def test_cpu_devices_uses_live_backend_without_teardown():
    import jax

    before = jax.devices()
    with plat.cpu_devices(4) as devices:
        assert len(devices) == 4
    assert jax.devices() == before  # no provisioning, no restore
