"""Sharded ops: sequence-parallel convolution, TP GEMM, DP batching.

The distributed re-expression of the reference's hot paths (SURVEY.md §5
"long-context" analog): overlap-save block filtering
(``/root/reference/src/convolve.c:103-229``) becomes ``shard_map`` over a
sequence axis with a ``ppermute`` halo exchange; the GEMM column loop
(``src/matrix.c:200-226``) becomes a contracting-dim-sharded
``dot_general`` + ``psum``.  Everything here is pure SPMD: one jitted
program, XLA inserts the collectives, ICI carries them.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["sharded_convolve", "sharded_matmul", "data_parallel",
           "halo_exchange_left"]


def halo_exchange_left(x_local, halo_len: int, axis_name: str):
    """Bring the last ``halo_len`` samples of the left neighbour's shard.

    The first shard receives zeros (``ppermute`` drops absent sources) —
    exactly the zero history the overlap-save formulation wants
    (``src/convolve.c:194-196`` zero-pads the first block).
    """
    n_shards = jax.lax.axis_size(axis_name)
    block = x_local.shape[-1]
    tail = x_local[..., block - halo_len:]  # empty when halo_len == 0
    perm = [(i, i + 1) for i in range(n_shards - 1)]
    return jax.lax.ppermute(tail, axis_name, perm)


def _local_full_conv(x_ext, h):
    """VALID cross-correlation-with-flipped-h of the halo-extended block:
    yields exactly the block's span of the global full convolution."""
    k = h.shape[-1]
    lhs = x_ext.reshape((1, 1, x_ext.shape[-1]))
    rhs = jnp.flip(h, -1).reshape((1, 1, k))
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding="VALID",
        precision=jax.lax.Precision.HIGHEST)
    return out.reshape(x_ext.shape[:-1] + (out.shape[-1],))


def sharded_convolve(x, h, mesh: Mesh, axis: str = "sp"):
    """Sequence-parallel full linear convolution over ``mesh[axis]``.

    The signal is sharded along its length; each device convolves its
    block after a one-hop left-halo exchange of ``h−1`` samples.  Returns
    the full ``n + h - 1`` result (same semantics as
    :func:`veles.simd_tpu.ops.convolve.convolve`).

    This is the distributed overlap-save: reference blocks-with-overlap
    (``src/convolve.c:181-228``) → shards-with-halo; the intra-block FFT
    pipeline stays whatever XLA picks locally.
    """
    x = jnp.asarray(x, jnp.float32)
    h = jnp.asarray(h, jnp.float32)
    if x.ndim != 1:
        raise ValueError("sharded_convolve shards a single 1D signal; "
                         "use data_parallel for batches")
    n, k = x.shape[-1], h.shape[-1]
    n_shards = mesh.shape[axis]
    out_len = n + k - 1
    pad_to = -(-out_len // n_shards) * n_shards
    if k - 1 > pad_to // n_shards:
        raise ValueError(
            f"filter halo h_length-1={k - 1} exceeds the per-shard block "
            f"({pad_to // n_shards}); the one-hop halo exchange needs "
            f"h_length-1 <= signal_length/{n_shards} — use fewer shards or "
            f"the single-chip convolve")
    x_pad = jnp.pad(x, (0, pad_to - n))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P()), out_specs=P(axis))
    def _run(x_local, h_full):
        halo = halo_exchange_left(x_local, k - 1, axis)
        x_ext = jnp.concatenate([halo, x_local], axis=-1)
        return _local_full_conv(x_ext, h_full)

    return _run(x_pad, h)[..., :out_len]


def sharded_matmul(a, b, mesh: Mesh, axis: str = "tp"):
    """Tensor-parallel GEMM: contracting dim sharded, ``psum`` over ICI.

    ``a [m, K] @ b [K, n]`` with K split across ``mesh[axis]``; each chip
    computes a partial ``[m, n]`` on its MXU and the partials are
    all-reduced.  (K must be divisible by the axis size.)
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.shape[-1] != b.shape[-2]:
        raise ValueError(f"contracting dims differ: {a.shape} @ {b.shape}")
    if a.shape[-1] % mesh.shape[axis]:
        raise ValueError(
            f"K={a.shape[-1]} not divisible by mesh axis {axis} "
            f"({mesh.shape[axis]})")

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)), out_specs=P(None, None))
    def _run(a_local, b_local):
        partial = jnp.dot(a_local, b_local,
                          precision=jax.lax.Precision.HIGHEST)
        return jax.lax.psum(partial, axis)

    return _run(a, b)


def data_parallel(fn, mesh: Mesh, axis: str = "dp"):
    """Wrap a batched op so its leading batch axis is sharded over
    ``mesh[axis]`` — jit + sharding constraint, XLA partitions the rest.

    >>> dwt = data_parallel(lambda x: wavelet_apply(DAUB, 8, PERIODIC, x),
    ...                     mesh)
    >>> hi, lo = dwt(batch_of_signals)   # batch split across chips
    """
    jfn = jax.jit(fn)

    def wrapper(batch, *args, **kwargs):
        batch = jnp.asarray(batch)
        spec = P(axis, *([None] * (batch.ndim - 1)))
        batch = jax.device_put(batch, NamedSharding(mesh, spec))
        with mesh:
            return jfn(batch, *args, **kwargs)

    return wrapper
