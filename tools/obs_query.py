#!/usr/bin/env python
"""Offline fleet reconstruction over a journal pack (obs v6).

The durable journal (:mod:`veles.simd_tpu.obs.journal`) is only worth
its disk if the history can be *read back* after the replicas that
wrote it are dead.  This tool is that reader: point it at a pack
directory (``$VELES_SIMD_JOURNAL_DIR``) and it merges every
``journal-<pid>-<seg>.jsonl`` file — one per process, subprocess
replicas included — into one wall-clock-ordered fleet timeline, then:

* **timeline** (default) — human-readable, one line per record, with
  per-record provenance (pid/replica) and the decision payload;
* ``--summary`` — record counts per kind/op/replica, file inventory,
  torn/corrupt line counts (recovered-past, never fatal);
* ``--trace OUT.json`` — Chrome trace-event JSON: one track per
  process, every journal record an instant event, every reconstructed
  incident an explicit open→close span — same conventions as the
  request-axis fleet stitcher
  (:func:`veles.simd_tpu.obs.timeseries.stitch_fleet_trace`), loads
  directly in Perfetto;
* ``--postmortem [ID|all]`` — renders each incident's story purely
  from on-disk records: the trigger detail at open, every breaker
  transition / replica lifecycle edge / fault-policy step that landed
  while it was open, the close reason — and, since obs v7, the
  control axis: scaler decisions that carried this incident's id are
  rendered as an action timeline plus the signal deltas across the
  effect window, so a scale-up that closed an ``slo_burn`` incident
  reads as one causal incident → action → effect story.

Filters compose: ``--rid`` / ``--replica`` / ``--site`` / ``--op`` /
``--kind`` / ``--since`` / ``--until`` (wall-clock seconds) /
``--last`` (trailing window).  ``make chaos-replicas`` gates on this
module's functions — the kill/drain/restart/breaker cycles and the
incidents a campaign provoked must be reconstructible from the pack
alone, with every in-memory ring gone.

Usage:  python tools/obs_query.py PACK_DIR
        python tools/obs_query.py PACK_DIR --summary
        python tools/obs_query.py PACK_DIR --trace fleet.json
        python tools/obs_query.py PACK_DIR --postmortem all
        make obs-query DIR=journal-pack
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

from veles.simd_tpu.obs import journal  # noqa: E402

# record kinds a postmortem renders inside an incident's open window
ACTIVITY_OPS = ("breaker_transition", "replica_lifecycle",
                "fault_policy", "fault_phase", "router_failover",
                "serve_lifecycle", "slo")


# -- filtering ---------------------------------------------------------------

def filter_records(records: list, *, rid=None, replica=None, site=None,
                   op=None, kind=None, since=None, until=None,
                   last=None) -> list:
    """Apply the CLI's filters to a merged record list.  ``rid`` and
    ``site`` match the ``data`` payload; ``replica`` matches the
    writer's identity stamp OR the payload's subject (a router's
    ``kill r0`` record and r0's own records both answer
    ``--replica r0``); ``since``/``until`` bound ``t_wall``; ``last``
    keeps the trailing N seconds relative to the newest record."""
    if last is not None and records:
        newest = max(r.get("t_wall", 0.0) for r in records)
        since = max(since or 0.0, newest - float(last))
    out = []
    for r in records:
        data = r.get("data") or {}
        if rid is not None and str(data.get("rid")) != str(rid):
            continue
        if replica is not None \
                and str(r.get("replica")) != str(replica) \
                and str(data.get("replica")) != str(replica):
            continue
        if site is not None and str(data.get("site")) != str(site):
            continue
        if op is not None and str(r.get("op")) != str(op):
            continue
        if kind is not None and str(r.get("kind")) != str(kind):
            continue
        t = r.get("t_wall", 0.0)
        if since is not None and t < since:
            continue
        if until is not None and t > until:
            continue
        out.append(r)
    return out


# -- incident reconstruction -------------------------------------------------

def incidents_from(records: list) -> list:
    """Reconstruct incidents purely from journaled ``incident``
    open/close decision events, matched by id.  Returns
    ``[{"id", "rule", "open", "close"}, ...]`` oldest-open first;
    ``close`` is None for incidents still open when the journal
    stopped."""
    opened: dict = {}
    done = []
    for r in records:
        if r.get("kind") != "decision" or r.get("op") != "incident":
            continue
        data = r.get("data") or {}
        iid = data.get("id")
        if r.get("decision") == "open":
            opened[iid] = {"id": iid, "rule": data.get("rule"),
                           "open": r, "close": None}
        elif r.get("decision") == "close" and iid in opened:
            inc = opened.pop(iid)
            inc["close"] = r
            done.append(inc)
    return done + list(opened.values())


def scaler_actions(records: list, incident_id) -> list:
    """Journaled scaler *actions* (noop ticks excluded) whose decision
    event carried this incident's id — the control-axis half of the
    incident's story."""
    out = []
    for r in records:
        if r.get("kind") != "decision" or r.get("op") != "scaler":
            continue
        if r.get("decision") in (None, "noop"):
            continue
        if (r.get("data") or {}).get("incident_id") == incident_id:
            out.append(r)
    return out


# the input-vector keys whose before→after deltas summarize whether a
# scaling action actually MOVED the signals it fired on
EFFECT_KEYS = ("burn_max", "queue_depth_total", "queue_velocity",
               "alive", "goodput")


def scaler_effect(records: list, actions: list, t_end: float) -> list:
    """The effect window: the input vector the first linked action saw
    vs the last journaled scaler tick at/before the incident's close
    (every scaler decision event — noops included — carries the full
    inputs, so the journal alone answers "did it work?").  Returns
    ``[(key, before, after), ...]`` for :data:`EFFECT_KEYS`."""
    if not actions:
        return []
    before = (actions[0].get("data") or {}).get("inputs") or {}
    after = None
    for r in records:
        if r.get("kind") != "decision" or r.get("op") != "scaler":
            continue
        inputs = (r.get("data") or {}).get("inputs")
        if not inputs:
            continue
        if r.get("t_wall", 0.0) <= t_end \
                and r.get("t_wall", 0.0) >= actions[-1].get("t_wall",
                                                            0.0):
            after = inputs
    if after is None:
        after = (actions[-1].get("data") or {}).get("inputs") or {}
    return [(k, before.get(k), after.get(k)) for k in EFFECT_KEYS]


def postmortem(records: list, incident: dict) -> str:
    """One incident's story from the pack: trigger, the
    breaker/lifecycle/fault activity inside its open window, the
    linked scaler action timeline + effect-window signal deltas
    (obs v7), close reason."""
    o, c = incident["open"], incident["close"]
    t0 = o.get("t_wall", 0.0)
    t1 = c.get("t_wall") if c else max(
        (r.get("t_wall", t0) for r in records), default=t0)
    lines = ["=" * 64,
             f"incident {incident['id']}  rule={incident['rule']}",
             f"  opened  {_stamp(t0)}  by {o.get('replica') or 'router'}"
             f" (pid {o.get('pid')})"]
    trigger = {k: v for k, v in (o.get("data") or {}).items()
               if k not in ("id", "rule")}
    lines.append(f"  trigger {json.dumps(trigger, default=str)}")
    activity = [r for r in records
                if r.get("kind") == "decision"
                and r.get("op") in ACTIVITY_OPS
                and t0 <= r.get("t_wall", 0.0) <= t1]
    lines.append(f"  activity during ({len(activity)} records):")
    for r in activity:
        lines.append("    " + _record_line(r, base_wall=t0))
    acts = scaler_actions(records, incident["id"])
    if acts:
        lines.append(f"  scaler actions linked ({len(acts)}):")
        for r in acts:
            d = r.get("data") or {}
            lines.append(
                f"    +{r.get('t_wall', 0.0) - t0:7.3f}s  "
                f"{r.get('decision')}  rule={d.get('rule')}  "
                f"replica={d.get('replica')}")
        effect = scaler_effect(records, acts, t1)
        if effect:
            lines.append("  effect window (signals across the "
                         "action(s)):")
            for key, before, after in effect:
                lines.append(f"    {key:<20} "
                             f"{_num(before)} -> {_num(after)}")
    if c is not None:
        lines.append(f"  closed  {_stamp(t1)}  "
                     f"reason={(c.get('data') or {}).get('reason')}  "
                     f"open for {t1 - t0:.2f}s")
    else:
        lines.append("  still open when the journal ended")
    return "\n".join(lines)


def _num(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


# -- Chrome-trace export -----------------------------------------------------

def fleet_timeline_trace(records: list) -> dict:
    """The merged pack as Chrome trace-event JSON — the offline
    counterpart of :func:`veles.simd_tpu.obs.timeseries.
    stitch_fleet_trace`, same conventions (one track per participant,
    instant events with the payload under ``args``, ``displayTimeUnit``
    ms) so both load identically in Perfetto.  Tracks are one per
    writing process (pid/replica); reconstructed incidents get
    explicit open→close duration events on a dedicated track."""
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"fleet": True, "records": 0}}
    base = min(r.get("t_wall", 0.0) for r in records)
    tracks: dict = {}
    events = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
               "args": {"name": "veles.simd_tpu journal pack"}}]

    def _tid(r):
        key = (r.get("pid"), r.get("replica"))
        if key not in tracks:
            tracks[key] = len(tracks) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": 0,
                "tid": tracks[key],
                "args": {"name": f"{key[1] or 'router'} "
                                 f"(pid {key[0]})"}})
        return tracks[key]

    for r in records:
        name = r.get("op") or r.get("kind", "?")
        if r.get("decision"):
            name = f"{name}/{r['decision']}"
        events.append({
            "name": name, "cat": r.get("kind", "journal"), "ph": "i",
            "s": "t", "ts": (r.get("t_wall", base) - base) * 1e6,
            "pid": 0, "tid": _tid(r),
            "args": {"seq": r.get("seq"), "pid": r.get("pid"),
                     "replica": r.get("replica"),
                     **(r.get("data") or {})}})
    inc_tid = len(tracks) + 1
    incidents = incidents_from(records)
    if incidents:
        events.append({"name": "thread_name", "ph": "M", "pid": 0,
                       "tid": inc_tid, "args": {"name": "incidents"}})
    newest = max(r.get("t_wall", base) for r in records)
    for inc in incidents:
        t0 = inc["open"].get("t_wall", base)
        t1 = inc["close"].get("t_wall", newest) if inc["close"] \
            else newest
        events.append({
            "name": f"incident {inc['rule']}", "cat": "incident",
            "ph": "X", "ts": (t0 - base) * 1e6,
            "dur": max(t1 - t0, 1e-9) * 1e6, "pid": 0, "tid": inc_tid,
            "args": {"id": inc["id"], "rule": inc["rule"],
                     "closed": inc["close"] is not None}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"fleet": True, "records": len(records),
                          "tracks": len(tracks),
                          "incidents": len(incidents)}}


# -- rendering ---------------------------------------------------------------

def _stamp(t_wall: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(t_wall)) \
        + ("%.3f" % (t_wall % 1.0))[1:]


def _record_line(r: dict, base_wall: float | None = None) -> str:
    who = r.get("replica") or "router"
    head = f"+{r.get('t_wall', 0.0) - base_wall:7.3f}s" \
        if base_wall is not None else _stamp(r.get("t_wall", 0.0))
    name = r.get("op") or r.get("kind", "?")
    if r.get("decision"):
        name = f"{name}/{r['decision']}"
    return (f"{head}  {who:<10} pid={r.get('pid')}  {name}  "
            f"{json.dumps(r.get('data') or {}, default=str)}")


def summary(records: list, skipped: int, directory: str) -> str:
    kinds: dict = {}
    ops: dict = {}
    replicas: dict = {}
    for r in records:
        kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
        key = r.get("op") or "-"
        ops[key] = ops.get(key, 0) + 1
        who = r.get("replica") or f"pid-{r.get('pid')}"
        replicas[who] = replicas.get(who, 0) + 1
    files = journal.discover(directory)
    lines = [f"journal pack: {directory}",
             f"  files: {len(files)}   records: {len(records)}   "
             f"skipped (torn/corrupt): {skipped}"]
    if records:
        span = max(r.get("t_wall", 0.0) for r in records) \
            - min(r.get("t_wall", 0.0) for r in records)
        lines.append(f"  span: {span:.2f}s wall clock")
    for title, table in (("kinds", kinds), ("ops", ops),
                         ("writers", replicas)):
        lines.append(f"  {title}:")
        for k, n in sorted(table.items(), key=lambda kv: -kv[1]):
            lines.append(f"    {k:<24} {n}")
    return "\n".join(lines)


# -- entry point -------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline fleet reconstruction over a journal pack")
    ap.add_argument("dir", help="journal pack directory "
                                "($VELES_SIMD_JOURNAL_DIR)")
    ap.add_argument("--rid", default=None, help="filter: request id")
    ap.add_argument("--replica", default=None,
                    help="filter: replica identity")
    ap.add_argument("--site", default=None,
                    help="filter: dispatch/breaker site")
    ap.add_argument("--op", default=None, help="filter: decision op")
    ap.add_argument("--kind", default=None,
                    help="filter: record kind (decision, incident...)")
    ap.add_argument("--since", type=float, default=None,
                    help="filter: wall-clock seconds (unix)")
    ap.add_argument("--until", type=float, default=None)
    ap.add_argument("--last", type=float, default=None,
                    help="filter: trailing window in seconds")
    ap.add_argument("--limit", type=int, default=200,
                    help="timeline line cap (0 = unlimited)")
    ap.add_argument("--summary", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="raw merged records as JSON lines")
    ap.add_argument("--trace", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--postmortem", metavar="ID", nargs="?",
                    const="all", default=None,
                    help="render incident postmortems ('all' or one id)")
    args = ap.parse_args(argv)

    all_records, skipped = journal.read_pack(args.dir)
    records = filter_records(
        all_records, rid=args.rid, replica=args.replica,
        site=args.site, op=args.op, kind=args.kind, since=args.since,
        until=args.until, last=args.last)
    if not journal.discover(args.dir):
        print(f"no journal files in {args.dir}", file=sys.stderr)
        return 2

    if args.summary:
        print(summary(records, skipped, args.dir))
        return 0
    if args.json:
        for r in records:
            print(json.dumps(r, default=str))
        return 0
    if args.trace:
        from veles.simd_tpu.obs import export
        from veles.simd_tpu.obs.atomic import atomic_write_text

        atomic_write_text(args.trace, export.to_json(
            fleet_timeline_trace(records), indent=None))
        print(f"wrote {args.trace} "
              f"({len(records)} records) — open in Perfetto")
        return 0
    if args.postmortem is not None:
        incs = incidents_from(records)
        if args.postmortem != "all":
            incs = [i for i in incs if i["id"] == args.postmortem]
        if not incs:
            print("no matching incidents in the pack",
                  file=sys.stderr)
            return 1
        for inc in incs:
            # the postmortem window needs the unfiltered pack: the
            # activity during an incident is the point
            print(postmortem(all_records, inc))
        return 0

    if skipped:
        print(f"note: {skipped} torn/corrupt line(s) skipped "
              f"(recovered past them)", file=sys.stderr)
    shown = records if not args.limit else records[-args.limit:]
    if len(shown) < len(records):
        print(f"... {len(records) - len(shown)} earlier records "
              f"(raise --limit)", file=sys.stderr)
    for r in shown:
        print(_record_line(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
