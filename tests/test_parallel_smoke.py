"""Quick-tier sharded smoke: one compile+run of each collective pattern.

VERDICT r4 weak #5: the inner-loop gate (`make tests-quick`) never
compiled a single ``shard_map``, so a regression in ``parallel/ops.py``
(the repo's largest file) surfaced only in the slow tier or the driver
dryrun.  This file is the fix — a 2-device CPU-mesh subset covering the
four collective patterns the layer is built from, kept tiny (~30 s):

* halo exchange (``ppermute`` both ways)   -> ``sharded_convolve``
* ring pipeline (iterated ``ppermute``)    -> ``sharded_convolve_ring``
* all-to-all distributed transpose         -> ``sharded_wavelet_apply2d``
* psum reduction + associative scan        -> ``sharded_sosfilt``

The heavy sweeps (8-device meshes, every family, every extension) stay
in the slow-marked ``test_parallel.py``; this file is breadth-only.
"""

import numpy as np

from veles.simd_tpu import parallel as par
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import iir
from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

RNG = np.random.RandomState(505)
# make_mesh lays out ALL visible devices (8 on the virtual CPU mesh);
# the smoke shards over a 2-way "sp" axis and leaves "dp" idle.
MESH = par.make_mesh({"dp": -1, "sp": 2})


def test_halo_conv_smoke():
    x = RNG.randn(512).astype(np.float32)
    h = RNG.randn(17).astype(np.float32)
    got = np.asarray(par.sharded_convolve(x, h, MESH))
    want = np.asarray(cv.convolve_simd(x, h, simd=True))
    np.testing.assert_allclose(got, want, atol=1e-4 * np.abs(want).max())


def test_ring_conv_smoke():
    x = RNG.randn(512).astype(np.float32)
    h = RNG.randn(64).astype(np.float32)
    got = np.asarray(par.sharded_convolve_ring(x, h, MESH))
    want = np.convolve(x.astype(np.float64), h.astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32),
                               atol=1e-3 * np.abs(want).max())


def test_a2a_wavelet2d_smoke():
    img = RNG.randn(16, 16).astype(np.float32)
    ll, lh, hl, hh = par.sharded_wavelet_apply2d(
        WaveletType.DAUBECHIES, 4, wv.ExtensionType.PERIODIC, img, MESH)
    ll1, lh1, hl1, hh1 = wv.wavelet_apply2d(
        WaveletType.DAUBECHIES, 4, wv.ExtensionType.PERIODIC, img,
        simd=True)
    for got, want in ((ll, ll1), (lh, lh1), (hl, hl1), (hh, hh1)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4)


def test_psum_normalize_smoke():
    from veles.simd_tpu.ops import normalize as nm

    img = (RNG.rand(32, 24) * 255).astype(np.uint8)
    got = np.asarray(par.sharded_normalize2d(img, MESH))
    want = np.asarray(nm.normalize2D(img, simd=True))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_scan_sosfilt_smoke():
    sos = iir.butterworth(4, 0.2)
    x = RNG.randn(1024).astype(np.float32)
    got = np.asarray(par.sharded_sosfilt(sos, x, MESH))
    want = np.asarray(iir.sosfilt(sos, x, simd=True))
    np.testing.assert_allclose(got, want, atol=1e-4 * np.abs(want).max())
