"""Perfect-reconstruction tests for the synthesis (inverse) transforms.

The reference is analysis-only; synthesis is this framework's exact
adjoint-based inverse for PERIODIC extension.  Round-tripping
analysis→synthesis is also the strongest possible correctness check of
the analysis filter bank itself: any tap, phase, or scale error breaks
reconstruction.
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import wavelet as wv

# slow tier: full extension x order x level sweeps — excluded from `make tests-quick`
pytestmark = pytest.mark.slow

RNG = np.random.RandomState(11)
EXT = wv.ExtensionType.PERIODIC


@pytest.mark.parametrize("type,order", [
    ("daub", 2), ("daub", 8), ("daub", 16), ("daub", 76),
    ("sym", 8), ("sym", 22), ("coif", 6), ("coif", 30)])
@pytest.mark.parametrize("simd", [True, False])
def test_dwt_round_trip(type, order, simd):
    x = RNG.randn(256).astype(np.float32)
    hi, lo = wv.wavelet_apply(type, order, EXT, x, simd=simd)
    rec = wv.wavelet_reconstruct(type, order, hi, lo, simd=simd)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


@pytest.mark.parametrize("level", [1, 2, 4])
@pytest.mark.parametrize("simd", [True, False])
def test_swt_round_trip(level, simd):
    x = RNG.randn(256).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply("daub", 8, level, EXT, x, simd=simd)
    rec = wv.stationary_wavelet_reconstruct("daub", 8, level, hi, lo,
                                            simd=simd)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


@pytest.mark.parametrize("levels", [1, 3, 5])
def test_dwt_cascade_round_trip(levels):
    x = RNG.randn(512).astype(np.float32)
    coeffs = wv.wavelet_transform("sym", 8, EXT, x, levels, simd=True)
    rec = wv.wavelet_inverse_transform("sym", 8, coeffs, simd=True)
    np.testing.assert_allclose(np.asarray(rec), x, atol=5e-4)


@pytest.mark.parametrize("levels", [1, 3])
def test_swt_cascade_round_trip(levels):
    x = RNG.randn(256).astype(np.float32)
    coeffs = wv.stationary_wavelet_transform("coif", 12, EXT, x, levels,
                                             simd=True)
    rec = wv.stationary_wavelet_inverse_transform("coif", 12, coeffs,
                                                  simd=True)
    np.testing.assert_allclose(np.asarray(rec), x, atol=5e-4)


def test_batched_round_trip():
    x = RNG.randn(8, 128).astype(np.float32)
    hi, lo = wv.wavelet_apply("daub", 8, EXT, x, simd=True)
    rec = wv.wavelet_reconstruct("daub", 8, hi, lo, simd=True)
    assert rec.shape == x.shape
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


def test_xla_vs_oracle_synthesis():
    m = 64
    hi = RNG.randn(3, m).astype(np.float32)
    lo = RNG.randn(3, m).astype(np.float32)
    a = np.asarray(wv.wavelet_reconstruct("daub", 12, hi, lo, simd=True))
    b = wv.wavelet_reconstruct_na("daub", 12, hi, lo)
    np.testing.assert_allclose(a, b, atol=5e-5)
    a = np.asarray(wv.stationary_wavelet_reconstruct(
        "sym", 6, 2, hi, lo, simd=True))
    b = wv.stationary_wavelet_reconstruct_na("sym", 6, 2, hi, lo)
    np.testing.assert_allclose(a, b, atol=5e-5)


def test_order_longer_than_signal_folds():
    # order*dilation > n: the periodic fold wraps more than once
    x = RNG.randn(16).astype(np.float32)
    hi, lo = wv.wavelet_apply("daub", 24, EXT, x, simd=True)
    rec = wv.wavelet_reconstruct("daub", 24, hi, lo, simd=True)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


def test_contract_violations():
    hi = np.zeros(8, np.float32)
    with pytest.raises(ValueError, match="differ"):
        wv.wavelet_reconstruct("daub", 8, hi, np.zeros(9, np.float32))
    with pytest.raises(ValueError, match="unsupported"):
        wv.wavelet_reconstruct("daub", 7, hi, hi)
    with pytest.raises(ValueError, match="level"):
        wv.stationary_wavelet_reconstruct("daub", 8, 0, hi, hi)
    with pytest.raises(ValueError, match="hi_1"):
        wv.wavelet_inverse_transform("daub", 8, [hi])


@pytest.mark.parametrize("simd", [True, False])
def test_minimum_signal_round_trip(simd):
    # length-2 signal -> length-1 bands: the degenerate lhs-dilated conv
    # used to NaN on the TPU lowering (clamped to dilation 1 now)
    x = np.float32([1, 2])
    hi, lo = wv.wavelet_apply("daub", 2, EXT, x, simd=simd)
    rec = wv.wavelet_reconstruct("daub", 2, hi, lo, simd=simd)
    np.testing.assert_allclose(np.asarray(rec), x, atol=1e-5)


def test_cascade_to_length_one_lowpass():
    # 512 samples, 9 levels: the deepest reconstruct runs on length-1
    # bands (the degenerate case the dilation clamp guards)
    x = RNG.randn(512).astype(np.float32)
    coeffs = wv.wavelet_transform("daub", 2, EXT, x, 9, simd=True)
    assert coeffs[-1].shape == (1,)
    rec = wv.wavelet_inverse_transform("daub", 2, coeffs, simd=True)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-3)


# --------------------------------------------------------------------------
# separable 2D transform
# --------------------------------------------------------------------------

@pytest.mark.parametrize("simd", [True, False])
def test_2d_round_trip(simd):
    img = RNG.randn(32, 48).astype(np.float32)
    ll, lh, hl, hh = wv.wavelet_apply2d("daub", 8, EXT, img, simd=simd)
    assert np.shape(ll) == (16, 24)
    rec = wv.wavelet_reconstruct2d("daub", 8, ll, lh, hl, hh, simd=simd)
    np.testing.assert_allclose(np.asarray(rec), img, atol=5e-4)


def test_2d_energy_conservation():
    img = RNG.randn(64, 64).astype(np.float32)
    bands = wv.wavelet_apply2d("daub", 4, EXT, img, simd=True)
    ein = float(np.sum(img.astype(np.float64) ** 2))
    eout = sum(float(np.sum(np.asarray(b, np.float64) ** 2))
               for b in bands)
    assert abs(ein - eout) / ein < 1e-4


def test_2d_separability_vs_oracle():
    """Each output pixel equals the separable double transform computed
    directly with the 1D oracle."""
    img = RNG.randn(16, 20).astype(np.float32)
    ll, lh, hl, hh = wv.wavelet_apply2d("daub", 4, EXT, img, simd=True)
    hi_r, lo_r = wv.wavelet_apply_na("daub", 4, EXT, img)
    hh0, hl0 = (o.swapaxes(-1, -2) for o in wv.wavelet_apply_na(
        "daub", 4, EXT, hi_r.swapaxes(-1, -2)))
    lh0, ll0 = (o.swapaxes(-1, -2) for o in wv.wavelet_apply_na(
        "daub", 4, EXT, lo_r.swapaxes(-1, -2)))
    np.testing.assert_allclose(np.asarray(ll), ll0, atol=5e-4)
    np.testing.assert_allclose(np.asarray(lh), lh0, atol=5e-4)
    np.testing.assert_allclose(np.asarray(hl), hl0, atol=5e-4)
    np.testing.assert_allclose(np.asarray(hh), hh0, atol=5e-4)


def test_2d_batched():
    imgs = RNG.randn(3, 16, 16).astype(np.float32)
    ll, lh, hl, hh = wv.wavelet_apply2d("sym", 6, EXT, imgs, simd=True)
    assert np.shape(ll) == (3, 8, 8)
    rec = wv.wavelet_reconstruct2d("sym", 6, ll, lh, hl, hh, simd=True)
    np.testing.assert_allclose(np.asarray(rec), imgs, atol=5e-4)


def test_2d_needs_two_dims():
    with pytest.raises(ValueError, match="n0, n1"):
        wv.wavelet_apply2d("daub", 8, EXT, np.zeros(16, np.float32))


@pytest.mark.parametrize("levels", [1, 2, 3])
def test_2d_pyramid_round_trip(levels):
    img = RNG.randn(64, 64).astype(np.float32)
    coeffs = wv.wavelet_transform2d("daub", 4, EXT, img, levels, simd=True)
    assert len(coeffs) == levels + 1
    assert np.shape(coeffs[-1]) == (64 >> levels, 64 >> levels)
    rec = wv.wavelet_inverse_transform2d("daub", 4, coeffs, simd=True)
    np.testing.assert_allclose(np.asarray(rec), img, atol=1e-3)


def test_2d_pyramid_zeroing_error_equals_dropped_energy():
    """Daubechies 2D pyramid is orthonormal (PERIODIC): zeroing a band
    produces exactly that band's energy as squared reconstruction error
    — the compression-use-case identity."""
    img = RNG.randn(64, 64).astype(np.float32)
    coeffs = wv.wavelet_transform2d("daub", 8, EXT, img, 2, simd=True)
    dropped = sum(float(np.sum(np.asarray(b, np.float64) ** 2))
                  for b in coeffs[0])
    zeroed = [tuple(np.zeros_like(np.asarray(b)) for b in coeffs[0])] \
        + coeffs[1:]
    rec = np.asarray(wv.wavelet_inverse_transform2d("daub", 8, zeroed,
                                                    simd=True))
    err_energy = float(np.sum((rec.astype(np.float64) - img) ** 2))
    np.testing.assert_allclose(err_energy, dropped, rtol=1e-4)


def test_2d_pyramid_contract():
    with pytest.raises(ValueError, match="ll_L"):
        wv.wavelet_inverse_transform2d("daub", 4, [np.zeros((4, 4))])


def test_every_family_order_round_trips_oracle():
    """Exhaustive: all 81 (family, order) pairs reconstruct exactly on
    the oracle path (fast — no jit), pinning the adjoint + c2 math for
    every published filter."""
    x = RNG.randn(128).astype(np.float32)
    for fam in ("daub", "sym", "coif"):
        for order in wv.supported_orders(wv.WaveletType(fam)):
            hi, lo = wv.wavelet_apply(fam, order, EXT, x, simd=False)
            rec = wv.wavelet_reconstruct(fam, order, hi, lo, simd=False)
            np.testing.assert_allclose(
                rec, x, atol=5e-4,
                err_msg=f"{fam}{order} failed round trip")


# --------------------------------------------------------------------------
# non-PERIODIC extensions (Woodbury boundary-corrected least squares)
# --------------------------------------------------------------------------

NONPERIODIC = [wv.ExtensionType.MIRROR, wv.ExtensionType.CONSTANT,
               wv.ExtensionType.ZERO]


@pytest.mark.parametrize("ext", NONPERIODIC)
@pytest.mark.parametrize("level", [1, 2, 3])
@pytest.mark.parametrize("simd", [True, False])
def test_swt_round_trip_nonperiodic(ext, level, simd):
    """The SWT frame stays full-rank under every extension, so the
    least-squares synthesis reconstructs the signal — to the boundary
    subsystem's condition number times f32 coefficient rounding
    (measured ~1e-4 relative; see the wavelet.py section comment)."""
    x = RNG.randn(256).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply("daub", 8, level, ext, x,
                                         simd=simd)
    rec = wv.stationary_wavelet_reconstruct("daub", 8, level, hi, lo,
                                            simd=simd, ext=ext)
    np.testing.assert_allclose(np.asarray(rec), x, atol=5e-3)


@pytest.mark.parametrize("type,order", [
    ("daub", 8), ("sym", 12), ("coif", 6), ("daub", 24)])
@pytest.mark.parametrize("ext", NONPERIODIC)
@pytest.mark.parametrize("simd", [True, False])
def test_dwt_nonperiodic_least_squares_consistency(type, order, ext, simd):
    """The reference's fixed-size non-periodic DWT analysis is provably
    rank-deficient (order/2 - 1 zero singular values), so no synthesis
    can recover the signal exactly.  The least-squares reconstruction's
    guarantee is CONSISTENCY: re-analyzing it reproduces the given
    coefficients to f32 precision."""
    x = RNG.randn(256).astype(np.float32)
    hi, lo = wv.wavelet_apply(type, order, ext, x, simd=simd)
    rec = wv.wavelet_reconstruct(type, order, hi, lo, simd=simd, ext=ext)
    hi2, lo2 = wv.wavelet_apply(type, order, ext, np.asarray(rec),
                                simd=simd)
    scale = np.max(np.abs(np.asarray(hi))) + np.max(np.abs(np.asarray(lo)))
    tol = 5e-4 if simd else 5e-6
    assert np.max(np.abs(np.asarray(hi2) - np.asarray(hi))) < tol * scale
    assert np.max(np.abs(np.asarray(lo2) - np.asarray(lo))) < tol * scale


@pytest.mark.parametrize("ext", NONPERIODIC)
def test_dwt_nonperiodic_projection_idempotent(ext):
    """reconstruct∘analyze is a projection: applying it twice equals
    applying it once (the recoverable row-space component is stable)."""
    x = RNG.randn(128).astype(np.float32)
    hi, lo = wv.wavelet_apply_na("daub", 8, ext, x)
    p1 = wv.wavelet_reconstruct_na("daub", 8, hi, lo, ext=ext)
    hi2, lo2 = wv.wavelet_apply_na("daub", 8, ext, p1)
    p2 = wv.wavelet_reconstruct_na("daub", 8, hi2, lo2, ext=ext)
    np.testing.assert_allclose(p2, p1, atol=2e-5)


@pytest.mark.parametrize("ext", list(wv.ExtensionType))
def test_order2_all_extensions_exact(ext):
    """Haar windows never cross the boundary, so every extension mode
    coincides and reconstruction is exact."""
    x = RNG.randn(64).astype(np.float32)
    hi, lo = wv.wavelet_apply_na("daub", 2, ext, x)
    rec = wv.wavelet_reconstruct_na("daub", 2, hi, lo, ext=ext)
    np.testing.assert_allclose(rec, x, atol=1e-5)


@pytest.mark.parametrize("ext", NONPERIODIC)
def test_nonperiodic_2d_and_cascade(ext):
    """Separable 2D + multi-level cascades accept ext and stay
    consistent (2D/cascade compose the 1D least-squares steps)."""
    img = RNG.randn(64, 48).astype(np.float32)
    ll, lh, hl, hh = wv.wavelet_apply2d("daub", 4, ext, img, simd=False)
    rec = wv.wavelet_reconstruct2d("daub", 4, ll, lh, hl, hh, simd=False,
                                   ext=ext)
    ll2, lh2, hl2, hh2 = wv.wavelet_apply2d("daub", 4, ext,
                                            np.asarray(rec), simd=False)
    np.testing.assert_allclose(np.asarray(ll2), np.asarray(ll), atol=2e-3)
    coeffs = wv.wavelet_transform("daub", 4, ext,
                                  RNG.randn(256).astype(np.float32), 2,
                                  simd=False)
    rec1 = wv.wavelet_inverse_transform("daub", 4, coeffs, simd=False,
                                        ext=ext)
    assert np.asarray(rec1).shape == (256,)


def test_nonperiodic_too_short_raises():
    hi = np.zeros(4, np.float32)  # n = 8 < 2*order = 16
    with pytest.raises(ValueError, match="non-periodic"):
        wv.wavelet_reconstruct_na("daub", 8, hi, hi,
                                  ext=wv.ExtensionType.MIRROR)
    with pytest.raises(ValueError, match="non-periodic"):
        wv.stationary_wavelet_reconstruct_na(
            "daub", 8, 3, np.zeros(32, np.float32),
            np.zeros(32, np.float32), ext=wv.ExtensionType.ZERO)


@pytest.mark.parametrize("type,order,ext", [
    ("daub", 76, wv.ExtensionType.MIRROR),
    ("coif", 30, wv.ExtensionType.ZERO),
    ("sym", 40, wv.ExtensionType.CONSTANT)])
def test_high_order_nonperiodic_consistency(type, order, ext):
    """The Woodbury precompute scales to the largest table orders
    (r = order-2 boundary rows)."""
    x = RNG.randn(512).astype(np.float32)
    hi, lo = wv.wavelet_apply_na(type, order, ext, x)
    rec = wv.wavelet_reconstruct_na(type, order, hi, lo, ext=ext)
    hi2, _ = wv.wavelet_apply_na(type, order, ext, rec)
    scale = float(np.max(np.abs(hi))) + 1e-3
    assert float(np.max(np.abs(hi2 - hi))) < 1e-4 * scale


def test_deep_level_swt_nonperiodic_roundtrip():
    x = RNG.randn(512).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply_na(
        "daub", 8, 4, wv.ExtensionType.CONSTANT, x)
    rec = wv.stationary_wavelet_reconstruct_na(
        "daub", 8, 4, hi, lo, ext=wv.ExtensionType.CONSTANT)
    np.testing.assert_allclose(rec, x, atol=5e-3)


def test_nonperiodic_under_jit_raises_clearly():
    """The hybrid host-f64 correction cannot trace; the error must name
    the restriction instead of surfacing a TracerArrayConversionError."""
    import jax

    b = np.zeros(64, np.float32)
    with pytest.raises(ValueError, match="outside jit|PERIODIC"):
        jax.jit(lambda a, c: wv.wavelet_reconstruct(
            "daub", 8, a, c, ext=wv.ExtensionType.MIRROR))(b, b)


# --------------------------------------------------------------------------
# wavelet packets (full binary tree)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("levels", [1, 2, 3])
@pytest.mark.parametrize("simd", [True, False])
def test_packet_round_trip(levels, simd):
    x = RNG.randn(256).astype(np.float32)
    leaves = wv.wavelet_packet_transform("daub", 8, EXT, x, levels,
                                         simd=simd)
    assert len(leaves) == 2 ** levels
    assert all(np.asarray(b).shape == (256 // 2 ** levels,)
               for b in leaves)
    rec = wv.wavelet_packet_inverse_transform("daub", 8, leaves,
                                              simd=simd)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


def test_packet_two_levels_match_manual_quarters():
    """Level-2 leaves equal the manual hihi/hilo/lohi/lolo construction —
    the layout wavelet_recycle_source (src/wavelet.c:138-165) quarters
    buffers for."""
    x = RNG.randn(128).astype(np.float32)
    hi, lo = wv.wavelet_apply_na("daub", 8, EXT, x)
    want = (wv.wavelet_apply_na("daub", 8, EXT, hi)
            + wv.wavelet_apply_na("daub", 8, EXT, lo))
    got = wv.wavelet_packet_transform("daub", 8, EXT, x, 2, simd=False)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, atol=1e-6)


def test_packet_conserves_energy_daub():
    """The daub table is orthonormal (sums to sqrt(2)); the full packet
    tree is an orthogonal map, so leaf energy equals signal energy."""
    x = RNG.randn(256).astype(np.float32)
    leaves = wv.wavelet_packet_transform("daub", 8, EXT, x, 3, simd=False)
    e = sum(float(np.sum(np.asarray(b).astype(np.float64) ** 2))
            for b in leaves)
    assert abs(e - float(np.sum(x.astype(np.float64) ** 2))) < 1e-3 * e


def test_packet_contracts():
    with pytest.raises(ValueError, match="2\\^levels"):
        wv.wavelet_packet_inverse_transform(
            "daub", 8, [np.zeros(8, np.float32)] * 3)
    with pytest.raises(ValueError, match="levels"):
        wv.wavelet_packet_transform("daub", 8, EXT,
                                    np.zeros(64, np.float32), 0)


# --------------------------------------------------------------------------
# 2D stationary (undecimated) transform
# --------------------------------------------------------------------------


@pytest.mark.parametrize("level", [1, 2])
@pytest.mark.parametrize("simd", [True, False])
def test_swt2d_round_trip(level, simd):
    img = RNG.randn(64, 48).astype(np.float32)
    ll, lh, hl, hh = wv.stationary_wavelet_apply2d("daub", 8, level, EXT,
                                                   img, simd=simd)
    assert np.asarray(ll).shape == img.shape   # undecimated: full size
    rec = wv.stationary_wavelet_reconstruct2d("daub", 8, level, ll, lh,
                                              hl, hh, simd=simd)
    np.testing.assert_allclose(np.asarray(rec), img, atol=5e-4)


def test_swt2d_matches_manual_separable():
    """Band (row_band, col_band) equals applying the 1D SWT along n1
    then along n0 — the separability contract."""
    img = RNG.randn(32, 40).astype(np.float32)
    hi_r, lo_r = wv.stationary_wavelet_apply_na("daub", 4, 1, EXT, img)
    hh_m, _ = wv.stationary_wavelet_apply_na(
        "daub", 4, 1, EXT, np.ascontiguousarray(hi_r.swapaxes(-1, -2)))
    ll, lh, hl, hh = wv.stationary_wavelet_apply2d("daub", 4, 1, EXT, img,
                                                   simd=False)
    np.testing.assert_allclose(np.asarray(hh),
                               hh_m.swapaxes(-1, -2), atol=1e-5)


@pytest.mark.parametrize("ext", [wv.ExtensionType.MIRROR,
                                 wv.ExtensionType.ZERO])
def test_swt2d_nonperiodic_round_trip(ext):
    """Full-rank per axis, so every extension round-trips (within the
    boundary conditioning)."""
    img = RNG.randn(48, 48).astype(np.float32)
    ll, lh, hl, hh = wv.stationary_wavelet_apply2d("daub", 6, 1, ext, img,
                                                   simd=False)
    rec = wv.stationary_wavelet_reconstruct2d("daub", 6, 1, ll, lh, hl,
                                              hh, simd=False, ext=ext)
    np.testing.assert_allclose(np.asarray(rec), img, atol=2e-2)


@pytest.mark.parametrize("levels", [1, 2])
@pytest.mark.parametrize("simd", [True, False])
def test_packet2d_round_trip(levels, simd):
    img = RNG.randn(64, 32).astype(np.float32)
    leaves = wv.wavelet_packet_transform2d("daub", 4, EXT, img, levels,
                                           simd=simd)
    assert len(leaves) == 4 ** levels
    assert all(np.asarray(b).shape ==
               (64 // 2 ** levels, 32 // 2 ** levels) for b in leaves)
    rec = wv.wavelet_packet_inverse_transform2d("daub", 4, leaves,
                                                simd=simd)
    np.testing.assert_allclose(np.asarray(rec), img, atol=5e-4)


def test_packet2d_leaf0_is_llll():
    """Natural order: leaf 0 at 2 levels is LL-of-LL."""
    img = RNG.randn(32, 32).astype(np.float32)
    leaves = wv.wavelet_packet_transform2d("daub", 4, EXT, img, 2,
                                           simd=False)
    ll1 = wv.wavelet_apply2d("daub", 4, EXT, img, simd=False)[0]
    llll = wv.wavelet_apply2d("daub", 4, EXT, np.asarray(ll1),
                              simd=False)[0]
    np.testing.assert_allclose(np.asarray(leaves[0]), np.asarray(llll),
                               atol=1e-6)


def test_packet2d_contracts():
    with pytest.raises(ValueError, match="4\\^levels"):
        wv.wavelet_packet_inverse_transform2d(
            "daub", 4, [np.zeros((4, 4), np.float32)] * 3)
