#!/usr/bin/env python
"""Bench-regression gate: fold runs into BENCH_HISTORY.jsonl and fail
on a headline/suite slowdown.

The bench trajectory used to be write-only — ``bench.py`` emitted
``BENCH_DETAILS.json`` per run and nothing ever looked back, so a PR
that regressed the 1M-convolve headline was only caught by a human
rereading numbers.  This tool closes the loop:

1. **Fold**: read the newest run's rows (metric, value, unit,
   vs_baseline) from ``BENCH_DETAILS.json`` and append them as exactly
   ONE JSONL record to the append-only ``BENCH_HISTORY.jsonl``.  A run
   that fails the gate is still recorded (the trajectory must show the
   regression, not pretend the run never happened) but its regressed
   rows are marked and **excluded from future baselines** — re-running
   a red gate can never launder a regression into the new normal; only
   a row that passes rejoins the median.
2. **Compare**: for every row, form a trailing baseline — the median of
   that metric's values over the previous ``--window`` records that
   contain it — and flag a regression when the new value falls below
   ``baseline * (1 - threshold)``.  All rows here are throughput
   (higher is better).  The threshold is per-row: ``--noise
   METRIC_SUBSTRING=FRAC`` overrides the ``--threshold`` default for
   rows whose metric name contains the substring (device-time rows are
   noisier than host-time rows; the headline deserves a tighter gate
   than the smoke-sized configs).  The spectral rows ship built-in
   defaults (``DEFAULT_NOISE``); CLI overrides apply after them, so
   the last matching substring still wins.
3. **Gate**: exit 0 when every row is within noise or improved (or has
   no baseline yet), 1 when any row regressed, 2 when there was
   nothing to compare (missing/empty details file).  ``make
   bench-regress`` wires this as the CI gate after ``make bench``.

Rows whose value is null (bench flagged an unresolved measurement) are
reported but never counted as regressions — a wedged relay is
``bench.py``'s rc=2 story, not a performance signal.

Usage:  python tools/bench_regress.py
        python tools/bench_regress.py --details BENCH_DETAILS.json \\
            --history BENCH_HISTORY.jsonl --window 5 --threshold 0.10 \\
            --noise "convolve 1M=0.08" --noise "elementwise=0.25"
        python tools/bench_regress.py --no-append   # compare only
        make bench-regress
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

DEFAULT_DETAILS = "BENCH_DETAILS.json"
DEFAULT_HISTORY = "BENCH_HISTORY.jsonl"
DEFAULT_WINDOW = 5
DEFAULT_THRESHOLD = 0.10
# built-in per-row noise thresholds, applied BEFORE the CLI --noise
# overrides (later matches win, so the CLI always has the last word).
# The spectral rows are device-time rows at smaller work totals than
# the 1M headline, so their chained-timer jitter is wider; the batched
# ratio row divides two measurements and is the noisiest of all.
DEFAULT_NOISE = [
    ("stft", 0.15),
    ("istft round-trip", 0.15),
    ("spectrogram", 0.15),
    ("batched stft", 0.25),
]


def load_rows(details_path: str) -> list:
    """The comparable rows of one bench run: every BENCH_DETAILS.json
    entry with a ``metric`` key (the tail ``skipped_stages`` entry and
    other non-row records are ignored)."""
    with open(details_path) as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"{details_path}: expected a list of configs")
    return [e for e in entries if isinstance(e, dict) and "metric" in e]


def rows_to_record(rows: list, source: str,
                   regressed: list = ()) -> dict:
    """One append-only history record for this run.  ``regressed``
    names the rows that failed the gate this run — recorded for the
    trajectory, skipped by :func:`trailing_baseline` so a red run
    cannot drag the future baseline down."""
    return {
        "ts": time.time(),
        "source": source,
        "device": next((r.get("device") for r in rows
                        if r.get("device")), None),
        "regressed": sorted(regressed),
        "rows": {
            r["metric"]: {
                "value": r.get("value"),
                "unit": r.get("unit"),
                "vs_baseline": r.get("vs_baseline"),
            } for r in rows
        },
    }


def read_history(history_path: str) -> list:
    """All prior records, oldest first.  Unparseable lines (a crashed
    writer predating atomic appends, manual edits) are skipped with a
    warning rather than poisoning the gate forever."""
    records = []
    if not os.path.exists(history_path):
        return records
    with open(history_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                print(f"bench_regress: {history_path}:{lineno}: "
                      f"skipping unparseable record", file=sys.stderr)
    return records


def append_history(history_path: str, record: dict) -> None:
    """Append exactly one JSONL record (single write + flush; JSONL
    appends are atomic at sane record sizes, and a torn tail line is
    skipped by :func:`read_history`)."""
    with open(history_path, "a") as f:
        f.write(json.dumps(record, allow_nan=False) + "\n")


def trailing_baseline(history: list, metric: str, window: int):
    """Median of the metric's values over the newest ``window`` prior
    records that measured it (None values, absent rows, and rows that
    were REGRESSED when recorded are skipped — a red run never becomes
    baseline).  Returns (baseline, n_samples); baseline None when
    unmeasured."""
    values = []
    for rec in reversed(history):
        if metric in rec.get("regressed", ()):
            continue
        row = rec.get("rows", {}).get(metric)
        if row and isinstance(row.get("value"), (int, float)):
            values.append(float(row["value"]))
            if len(values) == window:
                break
    if not values:
        return None, 0
    return statistics.median(values), len(values)


def row_threshold(metric: str, default: float, overrides: list) -> float:
    """Per-row noise threshold: the last ``--noise substring=frac``
    whose substring appears in the metric name wins; the global
    ``--threshold`` otherwise."""
    thr = default
    for substr, frac in overrides:
        if substr in metric:
            thr = frac
    return thr


def compare(rows: list, history: list, window: int, default_thr: float,
            overrides: list) -> tuple:
    """Judge every row against its trailing baseline.

    Returns ``(regressions, report_lines)`` where ``regressions`` is
    the list of regressed metric names."""
    regressions = []
    lines = []
    for r in rows:
        metric = r["metric"]
        value = r.get("value")
        unit = r.get("unit", "")
        baseline, n = trailing_baseline(history, metric, window)
        thr = row_threshold(metric, default_thr, overrides)
        if value is None:
            verdict = "UNRESOLVED (null value; not gated)"
        elif baseline is None:
            verdict = "no baseline yet"
        else:
            delta = (value - baseline) / baseline
            floor = baseline * (1.0 - thr)
            if value < floor:
                verdict = (f"REGRESSION {delta:+.1%} vs median of "
                           f"{n} (threshold -{thr:.0%})")
                regressions.append(metric)
            elif delta > thr:
                verdict = f"improved {delta:+.1%} vs median of {n}"
            else:
                verdict = (f"within noise {delta:+.1%} "
                           f"(threshold -{thr:.0%})")
        val_s = "null" if value is None else f"{value:.1f}"
        base_s = "-" if baseline is None else f"{baseline:.1f}"
        lines.append(f"  {metric:40s} {val_s:>10s} {unit:11s} "
                     f"baseline {base_s:>10s}  {verdict}")
    return regressions, lines


def parse_noise(spec: str) -> tuple:
    if "=" not in spec:
        raise argparse.ArgumentTypeError(
            f"--noise wants METRIC_SUBSTRING=FRACTION, got {spec!r}")
    substr, _, frac = spec.rpartition("=")
    try:
        frac_f = float(frac)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--noise fraction {frac!r} is not a number")
    if not 0 <= frac_f < 1:
        raise argparse.ArgumentTypeError(
            f"--noise fraction {frac_f} must be in [0, 1)")
    return substr, frac_f


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate on bench regressions vs BENCH_HISTORY.jsonl")
    ap.add_argument("--details", default=DEFAULT_DETAILS,
                    help="bench.py output to fold in (default: "
                         f"{DEFAULT_DETAILS})")
    ap.add_argument("--history", default=DEFAULT_HISTORY,
                    help="append-only JSONL trajectory (default: "
                         f"{DEFAULT_HISTORY})")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing records forming the baseline median "
                         f"(default: {DEFAULT_WINDOW})")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="default per-row noise fraction (default: "
                         f"{DEFAULT_THRESHOLD})")
    ap.add_argument("--noise", action="append", default=[],
                    type=parse_noise, metavar="SUBSTRING=FRAC",
                    help="per-row threshold override (repeatable; "
                         "last matching substring wins)")
    ap.add_argument("--no-append", action="store_true",
                    help="compare only; do not record this run")
    args = ap.parse_args(argv)

    try:
        rows = load_rows(args.details)
    except (OSError, ValueError) as e:
        print(f"bench_regress: cannot read run rows: {e}",
              file=sys.stderr)
        return 2
    if not rows:
        print(f"bench_regress: {args.details} holds no metric rows "
              "(bench captured nothing)", file=sys.stderr)
        return 2

    history = read_history(args.history)
    overrides = DEFAULT_NOISE + list(args.noise)
    regressions, lines = compare(rows, history, args.window,
                                 args.threshold, overrides)
    if not args.no_append:
        append_history(args.history,
                       rows_to_record(rows, args.details,
                                      regressed=regressions))

    print(f"bench_regress: {len(rows)} rows vs {len(history)} prior "
          f"records in {args.history}"
          + (" (not recorded)" if args.no_append else ""))
    for line in lines:
        print(line)
    if regressions:
        print(f"bench_regress: REGRESSION in {len(regressions)} "
              f"row(s): {', '.join(regressions)}", file=sys.stderr)
        return 1
    print("bench_regress: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
