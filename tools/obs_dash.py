#!/usr/bin/env python
"""Live dashboard for a serving process's scrape endpoint.

The terminal twin of ``tools/obs_report.py``: where obs_report renders
a SAVED snapshot, this hits a LIVE endpoint
(:mod:`veles.simd_tpu.obs.http`, armed via ``$VELES_SIMD_OBS_PORT`` or
``serve.Server(obs_port=...)``) and renders one compact screen from
its three routes:

* ``/healthz`` — health state (the HTTP code alone says
  healthy/degraded), breaker registry, admission depths, batcher
  classes;
* ``/metrics`` — the serving counters/gauges that matter at a glance
  (submitted/completed by status, sheds, deadline misses, queue
  depths, SLO burn rates), parsed from the Prometheus text;
* ``/debug/requests`` — the request axis: per-status tallies, the
  slowest-per-op exemplars with their phase decomposition, recent
  degraded traces.

``--fleet`` switches to the fleet axis: one screen rendered from
``/signals`` (the typed ``obs.signals()`` bundle the ``ReplicaGroup``
collector feeds) — per-replica health/staleness/queue-depth/breaker
rows, the RPC data plane's per-replica in-flight / connection-reuse /
transport-error block (subprocess groups), goodput by shape class,
SLO burn + velocity, and a unicode
sparkline over the last-N windowed samples of each per-replica series.
When the process runs an armed autoscaler the frame adds the control
axis from ``/scaler`` (obs v7): tick count, alive vs bounds, cooldown,
per-action/no-op tallies, and the last few decisions.  Point it at the
ROUTER's aggregation endpoint; a lone server answers with an empty
fleet.

One shot by default; ``--watch N`` redraws every N seconds until
interrupted (``--fleet`` included).  rc=1 when the endpoint is
unreachable — the dashboard doubles as a liveness probe in scripts.

Usage::

    python tools/obs_dash.py --port 9100
    python tools/obs_dash.py --url http://127.0.0.1:9100 --watch 2
    python tools/obs_dash.py --port 9100 --fleet --watch 1
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.obs import export  # noqa: E402


def fetch(url: str, timeout: float = 10.0) -> tuple:
    """``(status_code, body_text)`` — HTTP errors are still answers
    (503 from /healthz means DEGRADED, not unreachable)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8", "replace")


def _fmt_s(v) -> str:
    return "-" if v is None else "%.1e" % v


def _metric_lines(prom: str) -> list:
    """The at-a-glance serving rows out of a /metrics scrape."""
    parsed = export.parse_prometheus(prom)
    rows = []
    for (name, labels), value in sorted(parsed.items()):
        short = name.replace(export.PROMETHEUS_PREFIX, "")
        if not short.startswith(("serve_submitted", "serve_completed",
                                 "serve_shed", "serve_deadline_miss",
                                 "serve_queue_depth",
                                 "serve_degraded_batch",
                                 "serve_refilled",
                                 "slo_burn_rate", "slo_hit_rate")):
            continue
        if short.endswith(("_bucket", "_sum", "_count")):
            continue
        lab = ",".join("%s=%s" % kv for kv in labels)
        rows.append("  %-52s %12g"
                    % (short + ("{%s}" % lab if lab else ""), value))
    return rows


def _compile_lines(prom: str) -> list:
    """Warm-vs-cold compile tallies out of a /metrics scrape: how many
    executables this process LOADED (artifact hits + persistent-cache
    hits) vs COMPILED cold (artifact misses/stale/load errors +
    backend compiles that missed the cache) — the zero-warmup
    subsystem's at-a-glance scoreboard."""
    parsed = export.parse_prometheus(prom)
    tally: dict = {}
    for (name, labels), value in parsed.items():
        short = name.replace(export.PROMETHEUS_PREFIX, "")
        if short.endswith("_total"):        # counter suffix
            short = short[:-len("_total")]
        if short.startswith("artifact_") or \
                short.startswith("compile_"):
            if short.endswith(("_bucket", "_sum", "_count")):
                continue
            tally[short] = tally.get(short, 0) + value
    if not tally:
        return []
    # NB: artifact_preload is NOT summed into warm — every preloaded
    # entry that later dispatches also counts an artifact_hit, and
    # double-counting would overstate warm coverage
    warm = (tally.get("artifact_hit", 0)
            + tally.get("compile_cache_hits", 0))
    cold = (tally.get("artifact_miss", 0)
            + tally.get("artifact_stale", 0)
            + tally.get("artifact_load_error", 0)
            + tally.get("compile_cache_misses", 0))
    lines = ["compiles (warm vs cold): loaded=%g cold=%g"
             % (warm, cold)]
    for k in sorted(tally):
        if k.startswith("artifact_") or k in (
                "compile_cache_hits", "compile_cache_misses",
                "compile_backend_compile"):
            lines.append("  %-52s %12g" % (k, tally[k]))
    return lines


def _goodput_lines(prom: str) -> list:
    """The live goodput-recovery scoreboard out of a /metrics scrape:
    per shape class, useful vs dispatched SAMPLES and the padding
    waste between them — the footprint continuous batching + ragged
    packing exist to recover (``serve_refilled_rows`` in the metrics
    block above tallies the refill half)."""
    parsed = export.parse_prometheus(prom)
    per: dict = {}
    for (name, labels), value in parsed.items():
        short = name.replace(export.PROMETHEUS_PREFIX, "")
        if short.endswith("_total"):        # counter suffix
            short = short[:-len("_total")]
        if short not in ("serve_useful_samples",
                         "serve_dispatched_samples"):
            continue
        lab = dict(labels)
        key = "%s|%s" % (lab.get("op", "?"), lab.get("bucket", "?"))
        d = per.setdefault(key, [0.0, 0.0])
        d[0 if short == "serve_useful_samples" else 1] += value
    if not per:
        return []
    lines = ["goodput by shape class (useful/dispatched samples):"]
    tot_u = tot_d = 0.0
    for key in sorted(per):
        u, d = per[key]
        tot_u += u
        tot_d += d
        gp = u / d if d else None
        lines.append(
            "  %-28s useful=%-10g dispatched=%-10g goodput=%-7s "
            "waste=%s" % (
                key, u, d,
                "-" if gp is None else "%.4f" % gp,
                "-" if gp is None else "%.1f%%" % (100 * (1 - gp))))
    if tot_d:
        lines.append("  %-28s goodput=%.4f waste=%.1f%%" % (
            "overall", tot_u / tot_d, 100 * (1 - tot_u / tot_d)))
    return lines


# eight levels is what a terminal cell resolves; the ramp is the
# conventional one every sparkline tool uses
_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 32) -> str:
    """The last ``width`` samples as a unicode sparkline.  Scaled to
    the rendered window's own min..max (a flat series renders as all-
    low, which reads correctly as 'nothing moving')."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * len(_SPARK)))]
        for v in vals)


def render_fleet(base_url: str) -> tuple:
    """One fleet-axis frame from ``/signals``; ``(text, reachable)``."""
    lines = [f"== fleet dash @ {base_url} =="]
    try:
        code, body = fetch(base_url + "/signals")
    except Exception as e:  # noqa: BLE001 — unreachable is the answer
        return (f"{lines[0]}\nendpoint unreachable: {e!r}\n", False)
    if code != 200:
        lines.append(f"/signals answered HTTP {code}")
        return "\n".join(lines) + "\n", True
    try:
        sig = json.loads(body)
    except ValueError:
        return f"{lines[0]}\n  (unparseable /signals body)\n", True
    lines.append(
        "ticks=%s  tick=%.3gs  window=%s  queue_total=%s" % (
            sig.get("ticks"), sig.get("tick_s") or 0.0,
            sig.get("window"), sig.get("queue_depth_total")))
    burn = sig.get("slo_burn") or {}
    vel = sig.get("slo_burn_velocity") or {}
    for tenant in sorted(burn):
        lines.append("  slo burn %-20s %10.4g  velocity %s" % (
            tenant, burn[tenant], _fmt_s(vel.get(tenant))))
    health = sig.get("health") or {}
    stale = sig.get("staleness_s") or {}
    depth = sig.get("queue_depth") or {}
    b_open = sig.get("breaker_open") or {}
    b_flaps = sig.get("breaker_flaps") or {}
    scrape = sig.get("scrape_stale") or {}
    if health:
        lines.append("replicas:")
    for rid in sorted(health):
        lines.append(
            "  %-8s %-9s stale=%-8s depth=%-6s breaker_open=%-3s "
            "flaps=%-3s scrape_stale=%s" % (
                rid, health[rid], _fmt_s(stale.get(rid)),
                depth.get(rid, "-"), b_open.get(rid, 0),
                b_flaps.get(rid, 0), scrape.get(rid, 0)))
    rpc = sig.get("rpc") or {}
    if rpc:
        # the RPC data plane (subprocess groups): what the router's
        # pooled client sees per replica — alongside scrape staleness,
        # this is the "is the wire healthy" read
        lines.append("rpc data plane:")
        for rid in sorted(rpc):
            row = rpc[rid] or {}
            ratio = row.get("reuse_ratio")
            lines.append(
                "  %-8s in_flight=%-5s conn_reuse=%-8s "
                "transport_errors=%s" % (
                    rid, row.get("in_flight", "-"),
                    "-" if ratio is None else "%.2f" % ratio,
                    row.get("transport_errors", 0)))
    occ = sig.get("occupancy") or {}
    if occ:
        # the padding-aware placement signal: rows already queued in
        # a replica's forming batches — the router's occupancy bonus
        # steers same-class work here so batches fill instead of pad
        lines.append("open-batch occupancy (rows in forming "
                     "batches):")
        for rid in sorted(occ):
            lines.append("  %-8s %g" % (rid, occ[rid]))
    good = sig.get("goodput") or {}
    overall = sig.get("goodput_overall")
    if good or overall is not None:
        lines.append("goodput (useful rows / dispatched rows):")
        if overall is not None:
            waste = sig.get("padding_waste")
            lines.append("  %-40s %8.4f%s" % (
                "overall", overall,
                "" if waste is None
                else "  (padding waste %.1f%%)" % (100 * waste)))
        for key in sorted(good):
            lines.append("  %-40s %8.4f" % (key, good[key]))
    # -- the history axis (obs v6): open incidents + journal health --
    incidents = sig.get("incidents") or []
    if incidents:
        lines.append("open incidents:")
        for inc in incidents:
            lines.append(
                "  %-16s %-20s firing=%-4s opened %s" % (
                    inc.get("id"), inc.get("rule"),
                    inc.get("ticks_firing"),
                    _fmt_s(inc.get("opened_t_mono"))))
    journal = sig.get("journal") or {}
    if journal.get("armed"):
        lines.append(
            "journal: armed  records=%-8s dropped=%-4s "
            "rotations=%-3s lag=%s" % (
                journal.get("records"), journal.get("dropped"),
                journal.get("rotations"),
                _fmt_s(journal.get("lag_s"))))
    else:
        lines.append("journal: disarmed")
    # -- the control axis (obs v7): the autoscaler's own route ------
    # (a pre-v7 endpoint 404s here — render nothing rather than die)
    try:
        s_code, s_body = fetch(base_url + "/scaler")
        scaler = json.loads(s_body) if s_code == 200 else None
    except Exception:  # noqa: BLE001 — optional route
        scaler = None
    if scaler and scaler.get("armed"):
        rep = scaler.get("replicas") or {}
        lines.append(
            "scaler: armed  ticks=%-7s alive=%s [%s..%s]  "
            "cooldown=%ss" % (
                scaler.get("ticks"), rep.get("alive"),
                rep.get("min"), rep.get("max"),
                "%g" % scaler.get("cooldown_remaining_s", 0.0)))
        acts = scaler.get("actions") or {}
        noops = scaler.get("noops") or {}
        if acts or noops:
            lines.append("  actions " + " ".join(
                "%s=%s" % kv for kv in sorted(acts.items())) +
                "  noops " + " ".join(
                "%s=%s" % kv for kv in sorted(noops.items())))
        last = scaler.get("last_action")
        if last:
            lines.append(
                "  last action %-10s rule=%-14s replica=%-6s "
                "incident=%s" % (
                    last.get("action"), last.get("rule"),
                    last.get("replica"), last.get("incident_id")))
        for d in (scaler.get("decisions") or [])[-5:]:
            lines.append(
                "  tick %-10s %-10s rule=%-14s reason=%s" % (
                    "%g" % d.get("t", 0.0), d.get("action") or "-",
                    d.get("rule") or "-", d.get("reason")))
    elif scaler is not None:
        lines.append("scaler: disarmed")
    series = sig.get("series") or {}
    if series:
        lines.append("series (last-N window):")
    for rid in sorted(series):
        for name in sorted(series[rid]):
            samples = series[rid][name] or []
            vals = [s[1] for s in samples]
            lines.append("  %-8s %-16s %10s  %s" % (
                rid, name, "%g" % vals[-1] if vals else "-",
                sparkline(vals)))
    return "\n".join(lines) + "\n", True


def render(base_url: str) -> tuple:
    """One dashboard frame; returns ``(text, reachable)``."""
    lines = [f"== obs dash @ {base_url} =="]
    try:
        code, health = fetch(base_url + "/healthz")
    except Exception as e:  # noqa: BLE001 — unreachable is the answer
        return (f"{lines[0]}\nendpoint unreachable: {e!r}\n", False)
    state = "HEALTHY" if code == 200 else \
        ("DEGRADED" if code == 503 else f"HTTP {code}")
    lines.append(f"health: {state}")
    try:
        h = json.loads(health)
        counts = h.get("counts", {})
        if counts:
            lines.append("  " + "  ".join(
                "%s=%s" % kv for kv in sorted(counts.items())))
        for b in h.get("breakers", []):
            lines.append("  breaker %-44s %s"
                         % (b.get("key"), b.get("state")))
        adm = h.get("admission", {})
        if adm:
            lines.append("  queue %s/%s  tenants %s"
                         % (adm.get("depth"), adm.get("max_depth"),
                            adm.get("tenants")))
    except ValueError:
        lines.append("  (unparseable /healthz body)")
    # the server can die between fetches (that is what a liveness
    # probe is for): any later-route failure degrades to the same
    # graceful unreachable answer instead of a traceback
    try:
        _, prom = fetch(base_url + "/metrics")
        _, reqs = fetch(base_url + "/debug/requests")
    except Exception as e:  # noqa: BLE001 — unreachable is the answer
        lines.append(f"endpoint lost mid-scrape: {e!r}")
        return "\n".join(lines) + "\n", False
    rows = _metric_lines(prom)
    if rows:
        lines.append("metrics:")
        lines += rows
    lines += _compile_lines(prom)
    lines += _goodput_lines(prom)
    try:
        r = json.loads(reqs)
        summary = r.get("summary", {})
        lines.append("requests: " + "  ".join(
            "%s=%s" % kv for kv in sorted(summary.items())
            if kv[0] != "by_status"))
        for status, n in sorted(
                (summary.get("by_status") or {}).items()):
            lines.append(f"  {status}={n}")
        slowest = r.get("slowest_by_op", {})
        if slowest:
            lines.append("slowest by op (phases, s):")
            for op, tr in sorted(slowest.items()):
                p = tr.get("phases", {})
                lines.append(
                    "  %-16s rid=%-6s total=%s queue=%s batch=%s "
                    "device=%s" % (
                        op, tr.get("rid"), _fmt_s(p.get("total_s")),
                        _fmt_s(p.get("queue_wait_s")),
                        _fmt_s(p.get("batch_wait_s")),
                        _fmt_s(p.get("device_s"))))
        degraded = r.get("degraded", [])
        if degraded:
            lines.append(f"degraded exemplars ({len(degraded)}):")
            for tr in degraded[-5:]:
                lines.append(
                    "  rid=%-6s %-14s tenant=%-10s events=%s" % (
                        tr.get("rid"), tr.get("op"), tr.get("tenant"),
                        ">".join(e.get("event", "?")
                                 for e in tr.get("events", []))))
    except ValueError:
        lines.append("  (unparseable /debug/requests body)")
    return "\n".join(lines) + "\n", True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", default=None,
                    help="endpoint base url (overrides --port)")
    ap.add_argument("--port", type=int, default=None,
                    help="localhost port (default: "
                         "$VELES_SIMD_OBS_PORT)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="redraw every N seconds (0 = one shot)")
    ap.add_argument("--fleet", action="store_true",
                    help="render the fleet axis from /signals "
                         "(point at the router's aggregation "
                         "endpoint)")
    args = ap.parse_args(argv)
    base = args.url
    if base is None:
        port = args.port
        if port is None:
            from veles.simd_tpu.obs import http as obs_http

            port = obs_http.env_port()
        if port is None:
            print("obs_dash: no endpoint (--url/--port/"
                  "$VELES_SIMD_OBS_PORT)", file=sys.stderr)
            return 2
        base = f"http://127.0.0.1:{port}"
    base = base.rstrip("/")
    frame = render_fleet if args.fleet else render
    while True:
        text, reachable = frame(base)
        sys.stdout.write(text)
        sys.stdout.flush()
        if not reachable:
            return 1
        if args.watch <= 0:
            return 0
        time.sleep(args.watch)
        sys.stdout.write("\n")


if __name__ == "__main__":
    sys.exit(main())
