"""Shared atomic file writer for every obs exporter.

``obs.save`` (JSON snapshots), ``obs.save_trace`` (Chrome trace JSON)
and the flight recorder (:mod:`veles.simd_tpu.obs.flightrec`) all write
artifacts that other tools parse later — a crash mid-write (a wedged
bench run, an OOM-killed server, the very exception a flight bundle is
documenting) must never leave a truncated file where a consumer expects
a complete one.  This module is the single home for the
write-temp-then-``os.replace`` discipline the exporters used to
duplicate per call site.
"""

from __future__ import annotations

import itertools
import os
import threading

__all__ = ["atomic_write_text", "atomic_write_bytes"]

_TMP_SEQ = itertools.count()


def atomic_write_text(path: str, text: str) -> str:
    """Write ``text`` to ``path`` atomically; returns ``path``.

    The temp name is unique per write (pid + thread + sequence), so
    concurrent saves to the same path from different threads cannot
    collide on — or unlink — each other's temp file; last
    ``os.replace`` wins.  If serialization already happened (``text``
    is a complete string) the only failure modes left are filesystem
    ones, and those leave the previous file intact.
    """
    return atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Binary form of the shared writer — the artifact store's
    serialized executables (:mod:`veles.simd_tpu.runtime.artifacts`)
    ride the same temp+``os.replace`` discipline, so a crash mid-write
    can never leave a torn ``.bin`` where a loader expects a complete
    one (the loader's sha256 gate is the second line of defense).
    :func:`atomic_write_text` delegates here: one copy of the
    discipline, not two to keep in sync."""
    tmp = "%s.%d.%d.%d.tmp" % (path, os.getpid(),
                               threading.get_ident(), next(_TMP_SEQ))
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # the write itself failed mid-flight
            os.unlink(tmp)
    return path
