"""veles.simd_tpu.obs — runtime telemetry: *what was decided*, counted.

The framework's headline feature is automatic best-algorithm selection
(``ops/convolve.py`` re-derives ``src/convolve.c:328-364`` for TPU), but
selection you cannot observe is selection you cannot tune.  This package
is the accounting layer for every dispatch-time decision:

* **counters / gauges / timing histograms** —
  :class:`~veles.simd_tpu.obs.registry.MetricsRegistry`: XLA-vs-oracle
  dispatches per op, compile counts, cache hits;
* **a bounded structured event log** —
  :class:`~veles.simd_tpu.obs.events.EventLog`: one event per algorithm
  decision (convolution algorithm + geometry, STFT framing path, wavelet
  kernel route, shard geometry);
* **compile tracking** — :mod:`~veles.simd_tpu.obs.compile` bridges
  ``jax.monitoring`` into the registry, so backend compiles and
  persistent-cache hit/miss traffic finally show up in numbers;
* **host-side spans — the time axis** —
  :mod:`~veles.simd_tpu.obs.spans`: nested, thread-local
  ``obs.span("convolve.dispatch", algo=...)`` scopes that feed the
  registry's latency histograms (warmup vs. steady-state tagged
  separately), bridge to ``jax.profiler.TraceAnnotation`` while an XLA
  trace is active, and export as Perfetto-loadable Chrome trace-event
  JSON via :func:`save_trace`;
* **compiled-program resources — the resource axis** —
  :mod:`~veles.simd_tpu.obs.resources`: every compile site in
  ``ops/``/``parallel/`` goes through :func:`instrumented_jit`, which
  harvests XLA's own ``cost_analysis()`` (FLOPs, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/generated-code bytes)
  per ``(op, route)``, derives arithmetic intensity and an attainable
  roofline %, and snapshots every memoized compile cache through
  :func:`caches`;
* **request traces + SLOs — the request axis** —
  :mod:`~veles.simd_tpu.obs.requests`: every ``serve.Server.submit``
  mints a :func:`request_trace` carried across threads on the pending
  record; lifecycle edges (admitted / bucketed / batch-formed /
  dispatched / retried / degraded / terminal) build a causal chain
  whose phase decomposition (queue wait / batch wait / device) lands
  in bounded per-(op, tenant) histograms, with slowest-per-op and
  degraded exemplars retained as full traces and per-tenant SLO
  accounting (:func:`slo`: burn-rate gauges, breach decision events);
* **a live scrape endpoint** — :mod:`~veles.simd_tpu.obs.http`: a
  stdlib ``http.server`` serving ``/metrics`` (Prometheus text),
  ``/healthz`` (server health + breakers, 503 while degraded),
  ``/debug/requests`` (recent traces + exemplars + SLO accounts), and
  ``/signals`` (the typed fleet-signal bundle);
  armed by ``serve.Server.start`` via ``$VELES_SIMD_OBS_PORT``;
* **fleet time series + typed signals — the fleet axis** —
  :mod:`~veles.simd_tpu.obs.timeseries`: bounded per-(replica,
  series) sample rings with windowed derivatives (rates, deltas,
  EWMA) and flap counting, fed by the
  :class:`~veles.simd_tpu.serve.cluster.ReplicaGroup` collector
  thread; :func:`signals` assembles the typed
  :class:`~veles.simd_tpu.obs.timeseries.FleetSignals` bundle (slo
  burn + velocity, queue depths, breaker open/flaps, goodput,
  per-replica health/staleness) — the autoscaler input contract —
  and :func:`stitch_fleet_trace` merges a failed-over request's
  per-replica traces into one Perfetto-loadable fleet trace
  (``save_trace(path, fleet=ticket)``);
* **a crash flight recorder** — :mod:`~veles.simd_tpu.obs.flightrec`:
  an exception escaping a top-level dispatch span (or an explicit
  :func:`dump_debug_bundle` call) atomically writes config, platform,
  decision events, span ring, cache stats, and resource snapshots to
  ``$VELES_SIMD_FLIGHT_DIR`` — the post-mortem that survives the
  process;
* **exporters** — :mod:`~veles.simd_tpu.obs.export`: lossless JSON
  snapshot, Prometheus text format (histograms as proper
  ``_bucket``/``_sum``/``_count`` series), and a human ``report()``
  table with p50/p95/p99 latency columns.

Contract with the compute layer (enforced by ``tools/lint.py``):

* ops modules touch telemetry ONLY through :func:`record_decision`,
  :func:`count`, :func:`span`, :func:`instrumented_jit`, and
  :func:`register_cache`, and ONLY at the Python dispatch layer —
  never inside traced/jitted code.  Telemetry on or off, jaxprs and
  compiled artifacts are byte-identical (``tests/test_obs.py`` pins
  this).  Raw ``jax.jit`` / ``.lower().compile()`` compile sites in
  ``ops/``/``parallel/`` are a lint failure: compiles that bypass
  :func:`instrumented_jit` are compiles the resource axis cannot see.
* Off by default.  Enable with ``VELES_SIMD_TELEMETRY=1`` in the
  environment or :func:`enable` at runtime; when disabled every helper
  is a single attribute check, and when enabled the cost is one locked
  dict increment per public call — except :func:`instrumented_jit`
  call sites, which additionally build an argument-geometry key and
  probe the analysis memo per call while enabled (microseconds,
  against dispatch work that costs tens).

Usage::

    from veles.simd_tpu import obs
    obs.enable()
    convolve(x, h)                      # decisions recorded as they run
    print(obs.report())                 # human table, p50/p95/p99
    obs.save("telemetry.json")          # snapshot for tools/obs_report.py
    obs.save_trace("trace.json")        # open in Perfetto
    text = obs.to_prometheus()          # scrape endpoint body
    obs.resources()                     # per-(op, route) FLOPs/bytes/mem
    obs.caches()                        # every compile cache, one view
    obs.dump_debug_bundle()             # flight-recorder bundle on demand

Scope note: this module answers *what was decided, how often, and how
long the host-side dispatch took*; :mod:`veles.simd_tpu.utils.profiler`
(XLA traces) answers *where the device time goes* inside a step.  The
two meet at :func:`span`'s TraceAnnotation bridge, but they remain
separate layers.
"""

from __future__ import annotations

import os
import sys
import time

from veles.simd_tpu.obs import compile as _compile
from veles.simd_tpu.obs import export as _export
from veles.simd_tpu.obs import flightrec as _flightrec
from veles.simd_tpu.obs import incidents as _incidents
from veles.simd_tpu.obs import journal as _journal
from veles.simd_tpu.obs import requests as _requests_mod
from veles.simd_tpu.obs import resources as _resources
from veles.simd_tpu.obs import spans as _spans_mod
from veles.simd_tpu.obs import timeseries as _timeseries
from veles.simd_tpu.obs.atomic import atomic_write_text as _atomic_write
from veles.simd_tpu.obs.events import EventLog
from veles.simd_tpu.obs.lru import LRUSet
from veles.simd_tpu.obs.registry import MetricsRegistry
from veles.simd_tpu.obs.requests import RequestTrace, RequestTracer
from veles.simd_tpu.obs.resources import (InstrumentedJit,
                                          instrumented_jit,
                                          register_cache)
from veles.simd_tpu.obs.spans import SpanTracer
from veles.simd_tpu.obs.timeseries import (FleetSeries, FleetSignals,
                                           stitch_fleet_trace)

__all__ = [
    "enable", "disable", "enabled", "configure",
    "count", "gauge", "observe", "record_decision", "span",
    "counter_value", "quantiles", "events", "snapshot", "reset",
    "to_json", "to_prometheus", "report", "save", "load",
    "save_trace", "trace_events",
    "request_trace", "slo", "slo_snapshot", "request_snapshot",
    "request_summary",
    "signals", "fleet_record", "fleet_series", "stitch_fleet_trace",
    "journal_stats", "journal_cursor", "journal_tail",
    "incidents_snapshot", "scaler_snapshot",
    "install_compile_listeners",
    "instrumented_jit", "resources", "caches", "register_cache",
    "dump_debug_bundle",
    "MetricsRegistry", "EventLog", "SpanTracer", "InstrumentedJit",
    "RequestTrace", "RequestTracer", "LRUSet",
    "FleetSeries", "FleetSignals",
]

_TRUTHY = ("1", "true", "yes", "on")

_registry = MetricsRegistry()
_fleet = _timeseries.FleetSeries()
_events = EventLog()
_spans = SpanTracer(_registry.observe)
_spans.on_crash = _flightrec.maybe_record_crash


def _requests_decision(op: str, decision: str, **fields) -> None:
    """Decision sink for the request tracer (SLO breach events) —
    bound to the CURRENT event log through the module global, so
    ``configure(max_events=...)`` swaps are honored."""
    if _journal.armed():
        _journal.emit_decision(op, decision, fields)
    _events.record(op, decision, **fields)
    _registry.count("decisions", op=op, decision=decision)


def _requests_breach(tenant: str, burn: float) -> None:
    """Flight-recorder arm for SLO breaches: one budgeted bundle per
    crossing, with the request exemplars embedded (the bundle builder
    reads them through the facade)."""
    _flightrec.maybe_record(f"slo_breach:{tenant}", None)


_requests = RequestTracer(_registry, decision=_requests_decision,
                          on_breach=_requests_breach)
# request tracing armed while telemetry is on?  configure(
# request_axis=False) disarms the tracer alone — metrics/spans keep
# recording (the tracer's load-shedding knob)
_request_axis = True
_enabled = os.environ.get("VELES_SIMD_TELEMETRY",
                          "0").strip().lower() in _TRUTHY
if _enabled:
    _resources.set_active(True)
    # the env var is documented as equivalent to enable(): compile/cache
    # metrics must flow too.  Tolerate jax-free processes (the rest of
    # the telemetry layer works without an accelerator runtime).
    try:
        _compile.install()
    except ImportError:
        pass


# -- lifecycle ---------------------------------------------------------------

def enabled() -> bool:
    """Is telemetry currently recording?"""
    return _enabled


def enable(compile_listeners: bool = True) -> None:
    """Turn telemetry on (idempotent).

    ``compile_listeners=True`` (default) also bridges ``jax.monitoring``
    compile/cache events into the registry — a one-time, irreversible
    process-level registration (the callbacks themselves stay gated on
    :func:`enabled`, so :func:`disable` still silences them).  Pass
    False in jax-free processes.
    """
    global _enabled
    _enabled = True
    _resources.set_active(True)
    if compile_listeners:
        _compile.install()


def disable() -> None:
    """Stop recording.  Existing metrics/events are kept (snapshot still
    works); use :func:`reset` to clear them."""
    global _enabled
    _enabled = False
    _resources.set_active(False)


def configure(max_events: int | None = None,
              max_spans: int | None = None,
              flight_dir: str | None = None,
              max_traces: int | None = None,
              max_exemplars: int | None = None,
              request_axis: bool | None = None,
              journal_dir: str | None = None) -> None:
    """Adjust telemetry limits.  ``max_events`` replaces the decision
    log with a fresh bound (history is cleared — resizing a ring buffer
    in place would silently reorder it); ``max_spans`` does the same
    for the span trace buffer.  ``flight_dir`` overrides
    ``$VELES_SIMD_FLIGHT_DIR`` as the crash-bundle destination (pass
    ``""`` to restore the environment lookup).  ``max_traces`` /
    ``max_exemplars`` re-bound the request-axis retention rings
    (:mod:`veles.simd_tpu.obs.requests`; the trace default also reads
    ``$VELES_SIMD_OBS_MAX_TRACES``).  ``request_axis=False`` disarms
    request tracing (every :func:`request_trace` returns the shared
    null trace) while counters/gauges/spans keep recording — the
    tracer's load-shedding knob, and the off side of the ``serve
    tracing overhead`` bench row's A/B.  NB: the terminal request
    metrics (``serve.request_latency{op, status}``,
    ``serve_completed``, ``serve_deadline_miss``) ride the trace's
    terminal edge by design (one accounting home, lint-enforced), so
    disarming the axis pauses them too.  ``journal_dir`` overrides
    ``$VELES_SIMD_JOURNAL_DIR`` as the durable event-journal pack
    (:mod:`veles.simd_tpu.obs.journal`; pass ``""`` to restore the
    environment lookup)."""
    global _events, _spans, _request_axis
    if max_events is not None:
        _events = EventLog(max_events)
    if max_spans is not None:
        _spans = SpanTracer(_registry.observe, max_spans)
        _spans.on_crash = _flightrec.maybe_record_crash
    if flight_dir is not None:
        _flightrec.configure_flight_dir(flight_dir or None)
    if max_traces is not None or max_exemplars is not None:
        _requests.configure(max_traces=max_traces,
                            max_exemplars=max_exemplars)
    if request_axis is not None:
        _request_axis = bool(request_axis)
    if journal_dir is not None:
        _journal.configure_dir(journal_dir or None)


def install_compile_listeners() -> bool:
    """Explicitly install the ``jax.monitoring`` bridge (normally done
    by :func:`enable`).  Returns True on first installation."""
    return _compile.install()


# -- recording helpers (the ONLY entry points ops modules may call) ----------

def count(name: str, n: int = 1, **labels) -> None:
    """Increment counter ``name`` (no-op while disabled)."""
    if not _enabled:
        return
    _registry.count(name, n, **labels)


def gauge(name: str, value: float, **labels) -> None:
    """Set gauge ``name`` to ``value`` (no-op while disabled)."""
    if not _enabled:
        return
    _registry.gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    """Record one timing-histogram sample (no-op while disabled)."""
    if not _enabled:
        return
    _registry.observe(name, value, **labels)


def span(name: str, **attrs):
    """Time a host-side dispatch scope (context manager).

    While telemetry is off this returns a shared no-op context manager
    after a single flag check — the advertised disabled cost.  While
    on, the completed span feeds the ``span.<name>`` latency histogram
    (first completion per (name, attrs) class tagged
    ``phase="warmup"`` — where tracing and compiles land — the rest
    ``"steady"``), lands in the Chrome-trace buffer behind
    :func:`save_trace`, and bridges to
    ``jax.profiler.TraceAnnotation`` while an XLA trace is active.
    ``attrs`` (JSON-native scalars) travel only into the trace event's
    ``args`` — never into histogram labels.  Spans nest; use them at
    the Python dispatch layer only, never inside traced/jitted code.
    """
    if not _enabled:
        return _spans_mod.NULL_SPAN
    return _spans.span(name, **attrs)


def request_trace(op: str, tenant: str = "default", *,
                  shape_class=None, deadline_s=None):
    """Mint one request-axis trace (:class:`~veles.simd_tpu.obs.
    requests.RequestTrace`) — the serving layer calls this per
    ``Server.submit`` and carries the trace on the pending record
    across threads; every lifecycle edge appends via
    ``trace.event(...)`` and exactly one terminal ``trace.finish
    (status)`` records the phase histograms, the
    ``serve.request_latency{op, status}`` sample, SLO accounting, and
    exemplar retention.  While telemetry is off this returns the
    shared :data:`~veles.simd_tpu.obs.requests.NULL_REQUEST` after one
    flag check — every edge on it is a no-op (likewise while the
    request axis alone is disarmed via ``configure(
    request_axis=False)``)."""
    if not _enabled or not _request_axis:
        return _requests_mod.NULL_REQUEST
    return _requests.start(op, tenant, shape_class=shape_class,
                           deadline_s=deadline_s)


def slo(tenant: str, target_ms: float,
        hit_rate: float = _requests_mod.DEFAULT_SLO_HIT_RATE) -> dict:
    """Register ``tenant``'s SLO: answered within ``target_ms``
    end-to-end at ``hit_rate`` (shed/expired/errored requests are
    misses).  Terminal request traces update the tenant's account and
    export ``slo_hit_rate`` / ``slo_burn_rate`` gauges; the first
    crossing into burn > 1 records an ``slo``/``breach`` decision
    event and arms a flight-recorder bundle.  Unregistered tenants
    fall back to ``$VELES_SIMD_SLO_MS`` / ``$VELES_SIMD_SLO_HIT_RATE``
    when set."""
    return _requests.set_slo(tenant, target_ms, hit_rate)


def slo_snapshot() -> dict:
    """Per-tenant SLO state: registered targets, live accounts
    (requests/good/deadline misses), observed hit rate, burn rate."""
    return _requests.slo_snapshot()


def request_summary() -> dict:
    """Compact request-axis tally (started/finished/open, per-status
    counts, retention sizes) — the form embedded in
    :func:`snapshot`."""
    return _requests.summary()


def request_snapshot(recent: int = 50) -> dict:
    """The full request axis for the live endpoint and flight
    bundles: the last ``recent`` completed traces, slowest-per-op and
    degraded exemplars (full causal event chains), and the SLO
    accounts."""
    return _requests.traces_snapshot(recent)


def fleet_series() -> _timeseries.FleetSeries:
    """The live fleet store (obs v5): bounded per-(replica, series)
    sample rings.  The :class:`veles.simd_tpu.serve.cluster.
    ReplicaGroup` collector thread writes it via :func:`fleet_record`;
    read it through :func:`signals` (the typed contract) or this
    handle (tests, tooling)."""
    return _fleet


def fleet_record(replica: str, series: str, value: float,
                 t_s: float) -> None:
    """Record one fleet-axis sample (no-op while disabled) — the
    collector's write funnel: ``(replica, series)`` names the ring,
    ``t_s`` is the sweep's shared monotonic stamp."""
    if not _enabled:
        return
    _fleet.record(replica, series, value, t_s)


def signals() -> _timeseries.FleetSignals:
    """One consistent read of the fleet axis: the typed
    :class:`~veles.simd_tpu.obs.timeseries.FleetSignals` bundle
    (slo burn + velocity, queue depths, breaker open/flap counts,
    goodput per shape class, per-replica health/staleness) — the
    documented autoscaler input contract, also served as ``/signals``
    on the scrape endpoint and rendered by ``tools/obs_dash.py
    --fleet``.  Built from the fleet store, the metrics registry, and
    the SLO accounts; cheap enough to poll on the collector cadence.
    Since obs v6 the bundle also carries the history axis: the open
    incidents (:mod:`veles.simd_tpu.obs.incidents`) and journal
    health (armed/records/dropped/``lag_s``); since obs v7, the
    control axis summary (:func:`scaler_snapshot`'s compact form)."""
    now = time.monotonic()
    return _timeseries.FleetSignals.from_sources(
        _fleet, _registry.snapshot(), _requests.slo_snapshot(),
        now=now, incidents=_incidents.open_incidents(),
        journal=_journal.stats(now), scaler=_scaler_summary())


def journal_stats() -> dict:
    """History-axis health (:mod:`veles.simd_tpu.obs.journal`): armed
    flag, pack dir, record/drop/rotation/prune counts, and ``lag_s``
    since the last durable record."""
    return _journal.stats()


def journal_cursor() -> dict | None:
    """Where the durable journal is NOW (file/offset/record count;
    None while disarmed) — what incidents and flight bundles snapshot
    so a postmortem can seek straight to the moment."""
    return _journal.cursor()


def journal_tail(n: int = _journal.TAIL_KEEP) -> list:
    """The last ``n`` journal records from the in-memory tail (empty
    while disarmed)."""
    return _journal.tail(n)


def incidents_snapshot() -> dict:
    """The incident engine's JSON-native state — the ``/incidents``
    route body (:mod:`veles.simd_tpu.obs.incidents`): schema stamp,
    tick count, open/closed tallies, and the typed incident records."""
    return _incidents.snapshot()


# mirrored from veles.simd_tpu.serve.scaler.SCHEMA — the obs layer
# must stay importable without serve (layering, lint-enforced), so the
# disarmed /scaler shell stamps the schema from this literal
_SCALER_SCHEMA = "veles-simd-scaler-v1"


def _scaler_module():
    """The serve-layer scaler module IF something already imported it —
    obs never imports serve (layering), so control-axis state is read
    through ``sys.modules`` or not at all."""
    return sys.modules.get("veles.simd_tpu.serve.scaler")


def scaler_snapshot() -> dict:
    """The control axis (obs v7) — the ``/scaler`` route body: the
    registered :class:`veles.simd_tpu.serve.scaler.ScalerEngine`'s
    schema-stamped state (tick count, per-action streaks, cooldown,
    bounds, recent decisions), or the disarmed shell when no serve
    layer / no armed scaler is in this process."""
    mod = _scaler_module()
    if mod is not None:
        try:
            return mod.snapshot()
        except Exception:  # noqa: BLE001 — a wedged engine must not
            pass  # take down the scrape endpoint
    return {"schema": _SCALER_SCHEMA, "armed": False, "running": False,
            "ticks": 0, "actions": {}, "noops": {},
            "last_action": None, "decisions": []}


def _scaler_summary() -> dict:
    """The compact control-axis summary embedded in :func:`signals`."""
    mod = _scaler_module()
    if mod is not None:
        try:
            return mod.summary()
        except Exception:  # noqa: BLE001
            pass
    return {"armed": False, "running": False, "ticks": 0,
            "actions": {}, "last_action": None}


def record_decision(op: str, decision: str, **fields) -> None:
    """Log one dispatch decision (no-op while disabled).

    ``op`` is the public entry point ("convolve", "stft", ...),
    ``decision`` the chosen algorithm/path, ``fields`` the JSON-native
    geometry that explains it (lengths, block sizes, shard counts).
    Also bumps the ``decisions`` counter labeled by (op, decision) so
    aggregates survive event-log wraparound.

    With the history axis armed (``$VELES_SIMD_JOURNAL_DIR`` /
    ``configure(journal_dir=...)``), every event is ALSO appended to
    the durable journal — independent of :func:`enabled`, because the
    journal's whole point is surviving processes whose in-memory
    telemetry never existed (subprocess replicas arm it by inherited
    env alone).
    """
    if _journal.armed():
        _journal.emit_decision(op, decision, fields)
    if not _enabled:
        return
    _events.record(op, decision, **fields)
    _registry.count("decisions", op=op, decision=decision)


# -- reads / exports ---------------------------------------------------------

def counter_value(name: str, **labels) -> int:
    """Current value of one counter (0 if never incremented)."""
    return _registry.counter_value(name, **labels)


def quantiles(name: str, qs=(0.5, 0.95, 0.99), **labels):
    """Quantile estimates for one live histogram, or None if it has
    never been observed: ``{"p50": s, "p95": s, "p99": s}`` with
    Prometheus ``histogram_quantile`` semantics (upper bucket bound —
    see :func:`veles.simd_tpu.obs.export.histogram_quantile`).

    ``name`` is the histogram name (``"span.serve.dispatch"``,
    ``"serve.request_latency"``, ...); ``labels`` must match the
    recorded label set exactly (a span histogram carries
    ``phase="warmup"|"steady"``).  The serving layer's ``stats()`` and
    ``tools/loadgen.py`` read their p99 gates through this instead of
    re-deriving quantiles from raw samples."""
    want = {str(k): str(v) for k, v in labels.items()}
    for h in _registry.snapshot()["histograms"]:
        if h["name"] == name and h["labels"] == want:
            return _export.histogram_quantiles(h, qs)
    return None


def events() -> list:
    """Oldest-first copy of the retained decision events."""
    return _events.events()


def snapshot() -> dict:
    """One JSON-native dict of everything: counters, gauges, histograms
    (including the ``span.*`` latency distributions), events, drop
    counts, per-``(op, route)`` compiled-program resources, cache
    stats, and the enabled flag.  The span *trace* (per-span start/
    duration records) is exported separately by :func:`save_trace`."""
    snap = _registry.snapshot()
    snap["events"] = _events.events()
    snap["events_dropped"] = _events.dropped
    snap["spans_dropped"] = _spans.dropped
    snap["resources"] = _resources.resources_snapshot()
    snap["caches"] = _resources.caches_snapshot()
    snap["requests"] = _requests.summary()
    snap["slo"] = _requests.slo_snapshot()
    snap["fleet"] = _fleet.snapshot()
    snap["journal"] = _journal.stats()
    snap["incidents"] = _incidents.snapshot()
    snap["scaler"] = scaler_snapshot()
    snap["enabled"] = _enabled
    return snap


def resources() -> list:
    """Per-``(op, route)`` compiled-program analytics harvested by
    :func:`instrumented_jit`: FLOPs, bytes accessed, arithmetic
    intensity, attainable roofline %, and the argument/output/temp/
    generated-code memory breakdown (see
    :mod:`veles.simd_tpu.obs.resources`).

    NB: this facade function shadows the ``obs.resources`` SUBMODULE
    as a package attribute (deliberately — it completes the
    ``events()``/``caches()``/``resources()`` snapshot family), and
    the shadowing wins for from-imports AND dotted access after a
    plain import alike; reach the module itself only via
    ``sys.modules["veles.simd_tpu.obs.resources"]``."""
    return _resources.resources_snapshot()


def caches() -> dict:
    """Unified snapshot of every registered memoized compile cache:
    ``{name: {size, capacity, hits, misses, evictions, ...}}``.
    Caches self-register via :func:`register_cache` (the batched
    handle LRU, the pallas2d OOM-rejection LRU, the resource-analysis
    memo, ...)."""
    return _resources.caches_snapshot()


def dump_debug_bundle(path: str | None = None,
                      reason: str = "explicit",
                      exc: BaseException | None = None) -> str:
    """Atomically write a flight-recorder debug bundle NOW; returns the
    written path (see :mod:`veles.simd_tpu.obs.flightrec`)."""
    return _flightrec.dump_debug_bundle(path, reason, exc)


def reset() -> None:
    """Clear all metrics, events, spans, request traces, and harvested
    resources; the enabled flag is untouched (and request ids keep
    rising — a reset never mints duplicate rids)."""
    _registry.reset()
    _events.reset()
    _spans.reset()
    _resources.reset()
    _requests.reset()
    _fleet.reset()


def to_json(snap: dict | None = None, indent: int | None = 2) -> str:
    return _export.to_json(snapshot() if snap is None else snap, indent)


def to_prometheus(snap: dict | None = None) -> str:
    return _export.to_prometheus(snapshot() if snap is None else snap)


def report(snap: dict | None = None, max_events: int = 20) -> str:
    return _export.report(snapshot() if snap is None else snap,
                          max_events)


def save(path: str, snap: dict | None = None) -> str:
    """Atomically write a JSON snapshot to ``path`` (read back with
    :func:`load` or pretty-printed by ``tools/obs_report.py``);
    returns ``path``.  Uses the shared temp+``os.replace`` writer
    (:mod:`veles.simd_tpu.obs.atomic`) so a crash mid-write never
    truncates an existing snapshot."""
    return _atomic_write(path, to_json(snap if snap is not None
                                       else snapshot()))


def save_trace(path: str, fleet=None) -> str:
    """Atomically write Chrome trace-event JSON.

    Default: the retained spans — one complete ("X") event per span,
    per-thread tracks, warmup/steady phase and the span's attributes
    under ``args``.  With ``fleet=`` (a failed-over
    :class:`~veles.simd_tpu.serve.cluster.RouterTicket`, or an
    already-stitched dict from :func:`stitch_fleet_trace`): the
    cross-replica fleet trace instead — every attempt's edges on its
    own track with failover hops and carried deadlines visible.
    Either way the file loads directly in Perfetto
    (https://ui.perfetto.dev) or ``chrome://tracing``.  Returns
    ``path``."""
    if fleet is not None:
        stitched = fleet if isinstance(fleet, dict) \
            else _timeseries.stitch_fleet_trace(fleet)
        return _atomic_write(
            path, _export.to_json(stitched, indent=None))
    return _atomic_write(
        path, _export.to_json(_spans.to_chrome_trace(), indent=None))


def trace_events() -> list:
    """The retained spans as Chrome trace events (the ``traceEvents``
    list :func:`save_trace` writes) — for tests and in-process
    consumers."""
    return _spans.to_chrome_trace()["traceEvents"]


def load(path: str) -> dict:
    """Read a snapshot written by :func:`save`."""
    with open(path) as f:
        return _export.from_json(f.read())
