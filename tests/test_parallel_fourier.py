"""Pod-scale Fourier (PR 8): the Cooley-Tukey sharded DFT on the
virtual 8-device CPU mesh.

Parity discipline: ``sharded_rfft``/``sharded_dft``/``sharded_irfft``
must match the NumPy float64 oracles across N1*N2 splits (square,
non-square, odd-factor), dtypes f32/c64, and round-trip; route
selection must be provably ENGINE-driven (decision events + tune-cache
introspection, the test_routing stft pattern) and mesh-keyed (a winner
measured on one topology never steers another).
"""

import numpy as np
import pytest

import jax

from veles.simd_tpu import obs
from veles.simd_tpu import parallel as par
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.parallel import fourier as fr
from veles.simd_tpu.runtime import routing
from veles.simd_tpu.utils.platform import to_host

RNG = np.random.RandomState(83)


@pytest.fixture(scope="module")
def mesh8():
    return par.make_mesh({"sp": 8})


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    path = str(tmp_path / "tune.json")
    monkeypatch.setenv(routing.AUTOTUNE_CACHE_ENV, path)
    routing.set_cache_path(None)
    yield path
    routing.set_cache_path(None)


def _rel(got, want):
    return np.max(np.abs(got - want)) / max(1e-30,
                                            np.max(np.abs(want)))


# ---------------------------------------------------------------------------
# factorization helper
# ---------------------------------------------------------------------------

class TestCtFactor:
    def test_balanced_split(self):
        assert sp.ct_factor(4096) == (64, 64)
        n1, n2 = sp.ct_factor(12288)
        assert n1 * n2 == 12288 and n1 >= n2

    def test_prime_has_no_split(self):
        assert sp.ct_factor(13) is None
        assert sp.ct_factor(4099) is None       # prime > cutoff

    def test_multiple_constraint(self):
        n1, n2 = sp.ct_factor(960, multiple=8)
        assert n1 % 8 == 0 and n2 % 8 == 0 and n1 * n2 == 960
        # 24 = 8 * 3: no split has BOTH factors divisible by 8
        assert sp.ct_factor(24, multiple=8) is None

    def test_max_factor_bound(self):
        assert sp.ct_factor(1 << 26) is None    # 8192 * 8192 minimum


# ---------------------------------------------------------------------------
# parity: the acceptance suite (forced factorized route vs the NumPy
# oracle; <= 1e-4 rel err everywhere)
# ---------------------------------------------------------------------------

class TestFactorizedParity:
    # square, non-square, and odd-factor splits, all with both
    # factors divisible by the 8-way mesh
    @pytest.mark.parametrize("n", [512, 1024, 960, 1536, 4096])
    def test_sharded_rfft_matches_numpy(self, mesh8, n):
        x = RNG.randn(n).astype(np.float32)
        got = to_host(fr.sharded_rfft(x, mesh8,
                                      route="sharded_matmul_dft"))
        want = np.fft.rfft(x.astype(np.float64))
        assert got.shape == (n // 2 + 1,)
        assert _rel(got, want) <= 1e-4

    def test_sharded_rfft_batched(self, mesh8):
        x = RNG.randn(3, 1024).astype(np.float32)
        got = to_host(fr.sharded_rfft(x, mesh8,
                                      route="sharded_matmul_dft"))
        want = np.fft.rfft(x.astype(np.float64))
        assert _rel(got, want) <= 1e-4

    def test_sharded_dft_complex64(self, mesh8):
        x = (RNG.randn(512) + 1j * RNG.randn(512)).astype(np.complex64)
        got = to_host(fr.sharded_dft(x, mesh8,
                                     route="sharded_matmul_dft"))
        want = np.fft.fft(x.astype(np.complex128))
        assert _rel(got, want) <= 1e-4

    def test_sharded_dft_real_input(self, mesh8):
        x = RNG.randn(960).astype(np.float32)
        got = to_host(fr.sharded_dft(x, mesh8,
                                     route="sharded_matmul_dft"))
        assert _rel(got, np.fft.fft(x.astype(np.float64))) <= 1e-4

    @pytest.mark.parametrize("n", [512, 960])
    def test_roundtrip_irfft_rfft_is_identity(self, mesh8, n):
        x = RNG.randn(n).astype(np.float32)
        spec = to_host(fr.sharded_rfft(x, mesh8,
                                       route="sharded_matmul_dft"))
        rec = np.asarray(fr.sharded_irfft(
            spec.astype(np.complex64), n, mesh8,
            route="sharded_matmul_dft"))
        assert rec.shape == (n,)
        assert np.max(np.abs(rec - x)) <= 1e-4 * max(
            1.0, np.max(np.abs(x)))

    def test_local_fft_route_parity(self, mesh8):
        x = RNG.randn(1000).astype(np.float32)   # no mesh-div split
        got = to_host(fr.sharded_rfft(x, mesh8, route="local_fft"))
        assert _rel(got, np.fft.rfft(x.astype(np.float64))) <= 1e-4

    def test_forced_matmul_without_split_raises(self, mesh8):
        with pytest.raises(ValueError, match="Cooley-Tukey"):
            fr.sharded_rfft(RNG.randn(1000).astype(np.float32),
                            mesh8, route="sharded_matmul_dft")

    def test_irfft_bin_count_checked(self, mesh8):
        with pytest.raises(ValueError, match="bins"):
            fr.sharded_irfft(np.zeros(10, np.complex64), 512, mesh8)

    def test_unknown_route_raises(self, mesh8):
        with pytest.raises(ValueError, match="route"):
            fr.sharded_rfft(RNG.randn(512).astype(np.float32),
                            mesh8, route="bogus")


@pytest.mark.slow
class TestFactorizedParityLarge:
    @pytest.mark.parametrize("n", [12288, 1 << 17])
    def test_large_n_parity(self, mesh8, n):
        x = RNG.randn(n).astype(np.float32)
        got = to_host(fr.sharded_rfft(x, mesh8,
                                      route="sharded_matmul_dft"))
        assert _rel(got, np.fft.rfft(x.astype(np.float64))) <= 1e-4

    def test_large_n_auto_selects_matmul(self, mesh8):
        """At pod-scale N the ICI-aware static predicate itself picks
        the factorized route — no forcing, no tuner."""
        n = 1 << 17
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(n).astype(np.float32)
            got = to_host(fr.sharded_rfft(x, mesh8))
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_rfft"][-1]
            assert ev["decision"] == "sharded_matmul_dft"
            assert ev["ici_bytes"] > 0 and ev["a2a"] == 2
            assert ev["roofline"] == "dft_matmul"
            assert _rel(got,
                        np.fft.rfft(x.astype(np.float64))) <= 1e-4
        finally:
            obs.disable()
            obs.reset()


# ---------------------------------------------------------------------------
# routing: static prior, opt-out, and the engine-driven acceptance
# (decision events + tune-cache introspection on the mesh-keyed class)
# ---------------------------------------------------------------------------

class TestFourierRouting:
    def test_static_prior_is_mesh_and_size_aware(self):
        assert fr._select_fourier_route(
            "rfft", 1 << 18, 8, 1, 512, 512) == "sharded_matmul_dft"
        # too small: collective latency can't pay for itself
        assert fr._select_fourier_route(
            "rfft", 1024, 8, 1, 32, 32) == "local_fft"
        # no factorization (prime)
        assert fr._select_fourier_route(
            "rfft", 1 << 18, 8, 1, 0, 0) == "local_fft"
        # single chip: nothing to shard over
        assert fr._select_fourier_route(
            "rfft", 1 << 18, 1, 1, 512, 512) == "local_fft"

    def test_env_opt_out(self, monkeypatch):
        monkeypatch.setenv(fr.SHARDED_DFT_ENV, "1")
        assert fr._select_fourier_route(
            "rfft", 1 << 18, 8, 1, 512, 512) == "local_fft"

    def test_predicate_respects_ici_bandwidth(self, monkeypatch):
        """The selector really consults the ICI term: strangling the
        modeled interconnect flips the decision to local_fft (the
        mesh-awareness acceptance, without hardware)."""
        geom = dict(n=1 << 18, n_shards=8, rows=1, n1=512, n2=512)
        assert fr._matmul_dft_viable(**geom)
        monkeypatch.setenv("VELES_SIMD_ICI_BW_GBPS", "0.0001")
        assert not fr._matmul_dft_viable(**geom)

    def test_engine_driven_selection_persisted_mesh_keyed(
            self, mesh8, fresh_cache, monkeypatch):
        """ACCEPTANCE: with VELES_SIMD_AUTOTUNE=on the measured winner
        is selected, recorded as a decision event, persisted under a
        MESH-KEYED tune class with the topology stamp, and served
        without re-probing — and a different mesh shape does NOT
        consult that winner."""
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "on")
        n = 4096       # matmul predicate holds; both routes eligible
        x = RNG.randn(n).astype(np.float32)
        probes = []

        def timer(thunk, name):
            probes.append(name)
            thunk()
            # steer AGAINST the static prior so the selection is
            # provably measured, not the table order (the bf16_comp
            # precision candidate rides along, slower than both)
            return {"sharded_matmul_dft": 9.0, "local_fft": 1.0,
                    "sharded_matmul_dft_bf16_comp": 12.0}[name]

        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(timer):
                to_host(fr.sharded_rfft(x, mesh8))
            assert {"sharded_matmul_dft",
                    "local_fft"} <= set(probes)
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_rfft"][-1]
            assert ev["decision"] == "local_fft"
            tune_ev = [e for e in obs.events()
                       if e["op"] == "autotune"][-1]
            assert tune_ev["family"] == "parallel.fourier"
            assert tune_ev["static"] == "sharded_matmul_dft"
            # the persisted class is mesh-keyed AND mesh-stamped
            token = routing.mesh_class(mesh8, "sp")
            entries = routing.tune_cache().entries()
            keys = [k for k in entries
                    if k.startswith("parallel.fourier|")]
            assert len(keys) == 1
            assert f"mesh={token}" in keys[0]
            assert entries[keys[0]]["mesh"] == token
            # second dispatch: cached winner, zero probes
            before = len(probes)
            with routing.probe_timer(timer):
                to_host(fr.sharded_rfft(x, mesh8))
            assert len(probes) == before
            assert obs.counter_value("autotune_cache_hit",
                                     family="parallel.fourier") >= 1
            # a 4-device mesh is a DIFFERENT class: the 8-chip winner
            # is not consulted (fresh probe round, new entry)
            mesh4 = par.make_mesh({"sp": 4},
                                  devices=jax.devices()[:4])
            with routing.probe_timer(timer):
                to_host(fr.sharded_rfft(x, mesh4))
            assert len(probes) > before
            keys4 = [k for k in routing.tune_cache().entries()
                     if k.startswith("parallel.fourier|")]
            assert len(keys4) == 2
        finally:
            obs.disable()
            obs.reset()


# ---------------------------------------------------------------------------
# the local frame-transform family (sharded stft/istft/welch bodies)
# ---------------------------------------------------------------------------

class TestFrameRoutes:
    def test_frame_route_ladder(self, monkeypatch):
        assert fr.select_frame_route(512) == "rdft_matmul"
        assert fr.select_frame_route(6144) == "ct_matmul"
        assert fr.select_frame_route(4099) == "xla_fft"  # prime
        monkeypatch.setenv(sp._DFT_MATMUL_ENV, "1")
        assert fr.select_frame_route(512) == "xla_fft"

    def test_sharded_stft_records_local_route(self, mesh8):
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(8 * 256).astype(np.float32)
            got = to_host(par.sharded_stft(x, 64, 16, mesh8))
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_stft_local"][-1]
            assert ev["decision"] == "rdft_matmul"
            want = sp.stft_na(x, 64, 16)
            assert _rel(got, want) <= 1e-4
        finally:
            obs.disable()
            obs.reset()

    def test_sharded_stft_above_cutoff_rides_ct(self, mesh8):
        """frame > AUTO_DFT_MATMUL_MAX_FRAME: the local transform is
        the Cooley-Tukey matmul, and parity holds."""
        fl, hop = 6144, 1536
        n = 8 * fl
        assert fr.select_frame_route(fl) == "ct_matmul"
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(n).astype(np.float32)
            got = to_host(par.sharded_stft(x, fl, hop, mesh8))
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_stft_local"][-1]
            assert ev["decision"] == "ct_matmul"
            want = sp.stft_na(x, fl, hop)
            assert _rel(got, want) <= 1e-4
            # synthesis side: the ct inverse closes the round trip
            rec = np.asarray(par.sharded_istft(
                want.astype(np.complex64), n, fl, hop, mesh8))
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_istft_local"][-1]
            assert ev["decision"] == "ct_matmul"
            wrec = sp.istft_na(want, n, fl, hop)
            assert _rel(rec, wrec) <= 1e-3
        finally:
            obs.disable()
            obs.reset()

    def test_sharded_welch_records_local_route(self, mesh8):
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(8 * 256).astype(np.float32)
            _, p = par.sharded_welch(x, mesh8, nperseg=64,
                                     noverlap=48)
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_welch_local"][-1]
            assert ev["decision"] == "rdft_matmul"
            _, pw = sp.welch_na(x, nperseg=64, noverlap=48)
            assert _rel(np.asarray(p), pw) <= 1e-4
        finally:
            obs.disable()
            obs.reset()

    def test_sharded_istft_rdft_roundtrip(self, mesh8):
        x = RNG.randn(8 * 256).astype(np.float32)
        fl, hop = 64, 16
        spec = sp.stft_na(x, fl, hop).astype(np.complex64)
        rec = np.asarray(par.sharded_istft(spec, len(x), fl, hop,
                                           mesh8))
        wrec = sp.istft_na(spec, len(x), fl, hop)
        assert _rel(rec, wrec) <= 1e-4


# ---------------------------------------------------------------------------
# morlet_cwt rides the factorized matmul DFT above the dense cutoff
# ---------------------------------------------------------------------------

class TestCwtCtRoute:
    def test_ct_route_selected_above_dense_cutoff(self):
        assert sp._CWT_FAMILY.static_select(
            n=sp.CWT_MATMUL_MAX_N * 2) == "ct_matmul"
        assert sp._CWT_FAMILY.static_select(n=512) == "matmul_dft"

    def test_ct_route_parity(self):
        x = RNG.randn(2048).astype(np.float32)
        scales = [4.0, 8.0, 16.0]
        got = to_host(sp.morlet_cwt(x, scales, simd=True,
                                    route="ct_matmul"))
        want = sp.morlet_cwt_na(x, scales)
        assert _rel(got, want) <= 1e-4

    def test_auto_route_records_decision(self):
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(2048).astype(np.float32)
            to_host(sp.morlet_cwt(x, [4.0], simd=True))
            ev = [e for e in obs.events()
                  if e["op"] == "morlet_cwt_route"][-1]
            assert ev["decision"] == "ct_matmul"
        finally:
            obs.disable()
            obs.reset()

    def test_forced_ct_without_split_raises(self):
        """Forcing ct_matmul on an unfactorizable length is the clear
        ValueError every forced dispatcher raises, not a TypeError
        out of the factor unpack."""
        x = RNG.randn(1031).astype(np.float32)   # prime
        with pytest.raises(ValueError, match="Cooley-Tukey"):
            sp.morlet_cwt(x, [4.0], simd=True, route="ct_matmul")


class TestProgramCache:
    def test_ct_program_reused_across_dispatches(self, mesh8):
        """Repeat dispatches of one CT class reuse ONE shard_map
        program (the compiled-handle discipline): without this the
        autotuner's probe bursts charge the matmul candidate
        per-iteration re-tracing the local_fft core never pays."""
        x = RNG.randn(1024).astype(np.float32)
        fr.sharded_rfft(x, mesh8, route="sharded_matmul_dft")
        before = dict(fr._program_stats)
        fr.sharded_rfft(x, mesh8, route="sharded_matmul_dft")
        fr.sharded_rfft(x, mesh8, route="sharded_matmul_dft")
        after = dict(fr._program_stats)
        assert after["misses"] == before["misses"]
        assert after["hits"] >= before["hits"] + 2
        assert "fourier_program_lru" in obs.caches()
