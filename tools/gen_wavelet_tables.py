#!/usr/bin/env python
"""Generate and cache the wavelet coefficient tables.

Derives every supported (family, order) filter from its mathematical
definition (see ``veles/simd_tpu/ops/wavelet_coeffs.py``) and stores the
result in ``_wavelet_tables.npz`` next to that module, so library imports
don't pay the generation cost (the order-76 symlet search alone is a few
seconds).  Re-run after changing the generator:

    python tools/gen_wavelet_tables.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.ops import wavelet_coeffs as wc


def main():
    tables = {}
    for wtype in wc.WaveletType:
        for order in wc.supported_orders(wtype):
            t0 = time.time()
            key = f"{wtype.value}{order}"
            # bypass the npz cache: generate from scratch
            if wtype is wc.WaveletType.DAUBECHIES:
                h = wc._gen_daubechies(order)
            elif wtype is wc.WaveletType.SYMLET:
                h = wc._gen_symlet(order) / np.sqrt(2)
            else:
                h = wc._gen_coiflet(order) / np.sqrt(2)
            tables[key] = h
            target = 1.0 if wtype is not wc.WaveletType.DAUBECHIES \
                else np.sqrt(2)
            orth = max(
                abs(np.dot(h[: len(h) - 2 * k], h[2 * k:]) * 2 / target ** 2
                    - (1.0 if k == 0 else 0.0))
                for k in range(len(h) // 2))
            print(f"{key:8s} len={len(h):3d} sum_err={abs(h.sum()-target):.1e}"
                  f" orth_err={orth:.1e}  ({time.time()-t0:.1f}s)")
            assert abs(h.sum() - target) < 1e-12, key
            assert orth < 1e-10, key
    np.savez(wc._TABLE_PATH, **tables)
    print(f"wrote {len(tables)} tables -> {wc._TABLE_PATH}")


if __name__ == "__main__":
    main()
