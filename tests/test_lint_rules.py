"""The spectral route-dispatch lint rule (PR 5): every *_ROUTES table
entry must reach an instrumented_jit core, and public dispatchers must
index the table inside a ``with obs.span(...)`` scope."""

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402

GOOD = '''
import functools
from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core_xla(x):
    return x


def _run_xla(x):
    return _core_xla(x)


def _run_pallas(x):
    return _pk.stft_pallas(x, 256, 128)


_STFT_ROUTES = {"xla_fft": _run_xla, "pallas_fused": _run_pallas}


def stft(x, route):
    with obs.span("stft.dispatch", route=route):
        return _STFT_ROUTES[route](x)
'''

UNINSTRUMENTED = '''
from veles.simd_tpu import obs


def _run_raw(x):
    return x + 1


_STFT_ROUTES = {"raw": _run_raw}


def stft(x, route):
    with obs.span("stft.dispatch"):
        return _STFT_ROUTES[route](x)
'''

UNSPANNED = '''
import functools
from veles.simd_tpu import obs


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core(x):
    return x


def _run(x):
    return _core(x)


_STFT_ROUTES = {"xla_fft": _run}


def stft(x, route):
    return _STFT_ROUTES[route](x)
'''

NO_TABLES = '''
def stft(x):
    return x
'''


def _errors(src):
    return lint.spectral_dispatch_errors(ast.parse(src), "spectral.py")


def test_good_module_passes():
    assert _errors(GOOD) == []


def test_uninstrumented_runner_flagged():
    errs = _errors(UNINSTRUMENTED)
    assert any("instrumented_jit" in e for e in errs)


def test_unspanned_dispatch_flagged():
    errs = _errors(UNSPANNED)
    assert any("obs.span" in e for e in errs)


def test_missing_tables_flagged():
    errs = _errors(NO_TABLES)
    assert any("_ROUTES" in e for e in errs)


def test_real_spectral_module_is_clean():
    src = (REPO / "veles/simd_tpu/ops/spectral.py").read_text()
    assert lint.spectral_dispatch_errors(
        ast.parse(src), "veles/simd_tpu/ops/spectral.py") == []


# --------------------------------------------------------------------------
# the fault-policy rule (PR 6): no raw `except Exception` around
# pallas/compile call sites in ops//parallel — failure policy lives in
# runtime/faults.py
# --------------------------------------------------------------------------

FAULT_BAD_PALLAS = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.stft_pallas(x, 256, 128)
    except Exception:
        return None
'''

FAULT_BAD_PALLAS_ALIAS = '''
import veles.simd_tpu.ops.pallas_kernels as pkmod


def run(x):
    try:
        return pkmod.overlap_save_pallas(x, x)
    except Exception as e:
        raise
'''

FAULT_BAD_INSTRUMENTED = '''
import functools
from veles.simd_tpu import obs


@functools.partial(obs.instrumented_jit, op="conv", route="pallas")
def _core(x):
    return x


def run(x):
    try:
        return _core(x)
    except Exception:
        return None
'''

FAULT_BAD_BARE_EXCEPT = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.filter_2d_pallas(x, x, 4, 4)
    except:  # noqa: E722
        return None
'''

FAULT_OK_NARROW = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.stft_pallas(x, 256, 128)
    except ValueError:
        return None
'''

FAULT_OK_NO_COMPILE_SITE = '''
def load():
    try:
        return open("table.npz").read()
    except Exception:
        return None
'''


def _fault_errors(src):
    return lint.fault_handler_errors(ast.parse(src), "mod.py")


def test_fault_rule_flags_pallas_except():
    assert any("fault-policy" in e for e in _fault_errors(
        FAULT_BAD_PALLAS))


def test_fault_rule_tracks_import_alias():
    assert _fault_errors(FAULT_BAD_PALLAS_ALIAS)


def test_fault_rule_flags_instrumented_call():
    assert _fault_errors(FAULT_BAD_INSTRUMENTED)


def test_fault_rule_flags_bare_except():
    assert _fault_errors(FAULT_BAD_BARE_EXCEPT)


def test_fault_rule_allows_narrow_handler():
    assert _fault_errors(FAULT_OK_NARROW) == []


def test_fault_rule_ignores_non_compile_sites():
    assert _fault_errors(FAULT_OK_NO_COMPILE_SITE) == []


def test_real_compute_modules_have_no_inline_fault_handlers():
    """Acceptance gate: zero hand-rolled demote try/except blocks
    remain anywhere in ops/ or parallel/ — all three demotion paths
    (convolve os, convolve2d, stft) went through runtime/faults.py."""
    for sub in ("ops", "parallel"):
        for path in sorted((REPO / "veles/simd_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO).as_posix()
            errs = lint.fault_handler_errors(
                ast.parse(path.read_text()), rel)
            assert errs == [], errs
