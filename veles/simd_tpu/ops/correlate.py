"""1D cross-correlation — convolution with a reversed kernel.

TPU-native rebuild of ``/root/reference/src/correlate.c`` +
``/root/reference/inc/simd/correlate.h``.  The reference implements
cross-correlation by reusing every convolution engine with a ``reverse``
flag that flips ``h`` before the FFT (``src/correlate.c:37-72``, consumed
at ``src/convolve.c:167-171,302-306``), plus a direct SIMD form
(``src/correlate.c:74-126``).  Semantics: with ``j`` indexing the
``x_length + h_length - 1`` output,

    result[j] = Σ_m x[m] · h[m + h_length - 1 - j]

which is exactly ``convolve(x, reverse(h))`` — the identity this module is
built on.  The same three algorithms and handle API as
:mod:`veles.simd_tpu.ops.convolve` apply; ``reverse=True`` folds the flip
into the already-jitted kernels.
"""

from __future__ import annotations

import numpy as np

from veles.simd_tpu.ops import convolve as _conv
# re-exported: the reference's correlate.h pulls in convolve_structs.h, so
# both types are reachable through either header
from veles.simd_tpu.ops.convolve import (
    ConvolutionAlgorithm, ConvolutionHandle)
from veles.simd_tpu.utils.config import resolve_simd

__all__ = [
    "ConvolutionAlgorithm", "ConvolutionHandle",
    "cross_correlate_simd", "cross_correlate_na",
    "cross_correlate_fft", "cross_correlate_fft_initialize",
    "cross_correlate_fft_finalize",
    "cross_correlate_overlap_save", "cross_correlate_overlap_save_initialize",
    "cross_correlate_overlap_save_finalize",
    "cross_correlate", "cross_correlate_initialize",
    "cross_correlate_finalize", "correlation_lags",
]


def cross_correlate_na(x, h):
    """Direct-form oracle (``src/correlate.c:118-124`` scalar branch)."""
    h = np.asarray(h, np.float32)
    return _conv.convolve_na(x, h[..., ::-1])


def cross_correlate_simd(x, h, simd=None):
    """Direct form (``inc/simd/correlate.h:41-56``)."""
    if resolve_simd(simd, op="correlate"):
        import jax.numpy as jnp

        return _conv._direct(jnp.asarray(x), jnp.asarray(h), reverse=True)
    return cross_correlate_na(x, h)


def cross_correlate_fft_initialize(x_length, h_length):
    """``src/correlate.c:37-43`` — FFT plan with ``reverse=1``."""
    return _conv.convolve_fft_initialize(x_length, h_length, reverse=True)


def cross_correlate_fft(handle, x, h, simd=None):
    return _conv.convolve_fft(handle, x, h, simd)


def cross_correlate_fft_finalize(handle):
    """No-op (``src/correlate.c:50-52``)."""


def cross_correlate_overlap_save_initialize(x_length, h_length):
    """``src/correlate.c:54-60``."""
    return _conv.convolve_overlap_save_initialize(x_length, h_length,
                                                  reverse=True)


def cross_correlate_overlap_save(handle, x, h, simd=None):
    return _conv.convolve_overlap_save(handle, x, h, simd)


def cross_correlate_overlap_save_finalize(handle):
    """No-op (``src/correlate.c:69-72``)."""


def cross_correlate_initialize(x_length, h_length, algorithm=None):
    """``src/correlate.c:128-143`` — auto-select with reverse set."""
    return _conv.convolve_initialize(x_length, h_length, algorithm,
                                     reverse=True)


def cross_correlate(handle_or_x, x_or_h, h=None, simd=None, *,
                    mode="full"):
    """``src/correlate.c:145-159``; also accepts the convenience
    ``cross_correlate(x, h)`` form like :func:`convolve`, and numpy's
    ``mode`` ('full'/'same'/'valid') slicing of the full result."""
    _conv._check_mode(mode)
    if isinstance(handle_or_x, ConvolutionHandle):
        out = _conv._run(handle_or_x, x_or_h, h, simd)
        return _conv._mode_slice(out, handle_or_x.x_length,
                                 handle_or_x.h_length, mode,
                                 correlate=handle_or_x.reverse)
    x, h_ = handle_or_x, x_or_h
    if h is not None:
        simd = h
    handle = cross_correlate_initialize(np.shape(x)[-1], np.shape(h_)[-1])
    return _conv._mode_slice(_conv._run(handle, x, h_, simd),
                             np.shape(x)[-1], np.shape(h_)[-1], mode,
                             correlate=True)


def cross_correlate_finalize(handle):
    """No-op (``src/correlate.c:159-161``)."""


def correlation_lags(in_len: int, in2_len: int, mode: str = "full"):
    """Lag axis for :func:`cross_correlate` output: entry ``i`` of the
    correlation corresponds to displacement ``lags[i]`` of the second
    input relative to the first.  Host-side int array.

    Follows THIS module's (numpy.correlate) mode convention — 'same'
    returns ``max(in_len, in2_len)`` lags; scipy.signal's
    ``correlation_lags`` differs when ``in_len < in2_len`` because its
    ``correlate(..., 'same')`` keeps ``len(in1)`` instead.
    """
    in_len, in2_len = int(in_len), int(in2_len)
    if in_len < 1 or in2_len < 1:
        raise ValueError("lengths must be >= 1")
    _conv._check_mode(mode)
    # slice the full lag axis with the SAME windowing the correlation
    # output goes through — alignment holds by construction
    return _conv._mode_slice(np.arange(-(in2_len - 1), in_len),
                             in_len, in2_len, mode, correlate=True)
