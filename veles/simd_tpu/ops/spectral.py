"""Spectral analysis: STFT / ISTFT, spectrogram, Hilbert envelope, CWT.

NEW capability beyond the reference: ``/root/reference`` stops at 1D
convolution/correlation and discrete wavelets, but a signal-processing
user's next asks — time-frequency analysis (STFT/spectrogram), the
analytic signal (matched-filter envelope detection pairs with
``ops/correlate``), and the continuous wavelet transform — are all
batched-FFT workloads, which is exactly what the TPU formulation wants:
one ``rfft`` / elementwise multiply / ``irfft`` pipeline per op, fused by
XLA, no host round-trips.

Design notes (TPU-first):

* **Route selection** (the paper's best-algorithm-per-op mechanism,
  extended from the convolve family to spectral): ``stft``/``istft``
  pick between ``rdft_matmul`` — precomputed real-DFT basis matrices
  (window folded in, LRU-cached per geometry) so the transform is a
  dense ``frames @ W`` MXU matmul, the formulation "Large-Scale
  Discrete Fourier Transform on TPUs" (arXiv:2002.03260) and TINA
  (arXiv:2408.16551) show these accelerators want at STFT frame
  sizes — ``pallas_fused`` (the fused framing+window+DFT Mosaic
  kernel, :func:`~veles.simd_tpu.ops.pallas_kernels.stft_pallas`),
  and ``xla_fft`` (XLA's FFT lowering, the long-frame fallback).
  ``hilbert``/``morlet_cwt`` gain the same ``matmul_dft`` route for
  short signals.  Every route is labeled through
  ``obs.instrumented_jit`` and recorded as a ``*_route`` decision
  event; selectors live in :func:`_use_matmul_dft` /
  :func:`_use_pallas_stft`, opt-outs in ``VELES_SIMD_DISABLE_DFT_MATMUL``
  and ``VELES_SIMD_DISABLE_STFT_PALLAS``.
* **Framing** is a static gather: the ``[frames, frame_length]`` index
  matrix is built host-side at trace time, so XLA sees one fused
  ``gather → window-multiply → rfft`` program with static shapes.
* **Overlap-add** (ISTFT) is a ``.at[].add`` scatter — the adjoint of
  the framing gather — followed by division by the precomputed
  window-overlap envelope (COLA normalization).  The envelope is a
  host-side NumPy constant: shapes are static, so it never needs to be
  traced.
* **CWT** computes the wavelet filter bank in the frequency domain
  host-side (``[scales, bins]`` f32 constants) and runs one batched
  ``fft → multiply → ifft`` on device; scales dimension rides the VPU
  lanes.

Oracle twins (``*_na``) are NumPy float64 implementations of the same
definitions, keeping the reference's SIMD-vs-``_na`` cross-validation
discipline (``/root/reference/tests/matrix.cc:94-98``).
"""

from __future__ import annotations

import collections
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.runtime import precision as prx
from veles.simd_tpu.utils.config import resolve_simd
# complex host<->device moves MUST go through to_device/to_host: the
# axon relay cannot transfer complex buffers in either direction and one
# attempt poisons the process (utils/platform.py docstrings).
from veles.simd_tpu.utils.platform import to_device

__all__ = [
    "stft", "stft_na", "istft", "istft_na", "spectrogram",
    "spectrogram_na", "hilbert", "hilbert_na", "envelope", "envelope_na",
    "morlet_cwt", "morlet_cwt_na", "hann_window", "frame_count",
    "detrend", "detrend_na", "welch", "welch_na", "periodogram",
    "periodogram_na", "csd", "csd_na", "coherence", "coherence_na",
    "czt", "czt_na", "zoom_fft", "lombscargle",
    "lombscargle_na", "ct_factor", "ct_apply", "ct_basis_parts",
    "ct_basis_device", "dft_basis_parts", "twiddle_parts",
    "hermitian_extend",
    "stft_stream_carry", "select_stft_stream_route",
    "stft_stream_step", "stft_stream_oracle",
]


# ---------------------------------------------------------------------------
# host-side constant cache (DFT bases, analytic multipliers, wavelet
# banks) + route-selection constants
# ---------------------------------------------------------------------------

# matmul-DFT routing bound: the [L, 2*bins] basis holds L*(L+2) ~ L^2
# f32 = ~4*L^2 bytes resident in HBM (67 MB at L=4096) and the
# per-frame MAC count grows as L^2 vs the FFT's L log L — but at STFT
# frame sizes the MXU's throughput advantage over XLA's TPU FFT dwarfs
# the op-count gap (arXiv:2002.03260 measures matmul-DFT at this
# regime; XLA's 1D FFT leaves the MXU idle)
AUTO_DFT_MATMUL_MAX_FRAME = 4096
# hilbert's circulant analytic-signal operator is a dense [n, n] pair —
# 8 MB at n=1024; beyond that the FFT's O(n log n) wins outright
HILBERT_MATMUL_MAX_N = 1024
# same residency math for the CWT's positive-frequency basis pair
CWT_MATMUL_MAX_N = 1024
_DFT_MATMUL_ENV = "VELES_SIMD_DISABLE_DFT_MATMUL"


def dft_matmul_allowed() -> bool:
    """May implicit routing use the matmul-DFT routes (stft/istft
    ``rdft_matmul``, hilbert/cwt ``matmul_dft``)?  True unless
    explicitly disabled — the family-wide escape hatch mirroring
    ``VELES_SIMD_DISABLE_PALLAS_OS`` for the fused conv kernel.

    The gate itself lives on the route tables' ``disable_env``; this
    public query delegates to the same engine check so the two can
    never drift."""
    return not routing.env_truthy(_DFT_MATMUL_ENV)


# Host-side constants used to be rebuilt per call (the analytic
# multiplier, the Morlet bank) — harmless for one-shot scripts, pure
# waste for a service hitting the same geometry per request.  One
# bounded LRU holds them all: DFT bases keyed by (kind, geometry,
# window bytes), multipliers/banks by (kind, geometry).  64 entries
# covers a steady state while keeping eviction observable.
_HOST_CACHE_MAXSIZE = 64
_host_cache: "collections.OrderedDict[tuple, object]" = \
    collections.OrderedDict()
_host_lock = threading.Lock()
_host_stats = {"hits": 0, "misses": 0, "evictions": 0}


def _cached_host(key, build):
    """LRU lookup of a host-side constant; ``build()`` makes it on a
    miss (outside the lock — basis construction can be milliseconds;
    worst case two threads race the same key and one value wins)."""
    with _host_lock:
        hit = _host_cache.get(key)
        if hit is not None:
            _host_cache.move_to_end(key)
            _host_stats["hits"] += 1
            return hit
        _host_stats["misses"] += 1
    value = build()
    with _host_lock:
        existing = _host_cache.get(key)
        if existing is not None:
            return existing
        _host_cache[key] = value
        while len(_host_cache) > _HOST_CACHE_MAXSIZE:
            _host_cache.popitem(last=False)
            _host_stats["evictions"] += 1
    return value


def _host_cache_info() -> dict:
    with _host_lock:
        return {"size": len(_host_cache),
                "capacity": _HOST_CACHE_MAXSIZE, **_host_stats,
                "keys": [k[0] for k in _host_cache]}


obs.register_cache("spectral_host_lru", _host_cache_info)

# Device-resident twin: the host LRU dedupes CONSTRUCTION of a basis,
# this one dedupes the UPLOAD — ``jnp.asarray`` on a cached numpy
# array still transfers a fresh device buffer every call (~67 MB per
# stft at L=4096).  Smaller bound than the host cache because entries
# pin HBM; eviction just means one re-upload if the geometry returns.
_DEVICE_CACHE_MAXSIZE = 16
_device_cache: "collections.OrderedDict[tuple, object]" = \
    collections.OrderedDict()
_device_lock = threading.Lock()
_device_stats = {"hits": 0, "misses": 0, "evictions": 0}


def _cached_device(key, build_device):
    """LRU lookup of a device-resident constant; ``build_device()``
    uploads (and may first host-build via :func:`_cached_host`) on a
    miss.  Same race discipline as the host cache.

    Under an ACTIVE trace ``jnp.asarray`` yields a tracer, not a
    buffer — caching it would leak the tracer into later eager calls
    (UnexpectedTracerError), so traced uploads are returned uncached;
    the first eager call for the geometry populates the cache."""
    with _device_lock:
        hit = _device_cache.get(key)
        if hit is not None:
            _device_cache.move_to_end(key)
            _device_stats["hits"] += 1
            return hit
        _device_stats["misses"] += 1
    value = build_device()
    leaves = value if isinstance(value, tuple) else (value,)
    if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
        return value
    with _device_lock:
        existing = _device_cache.get(key)
        if existing is not None:
            return existing
        _device_cache[key] = value
        while len(_device_cache) > _DEVICE_CACHE_MAXSIZE:
            _device_cache.popitem(last=False)
            _device_stats["evictions"] += 1
    return value


def _device_cache_info() -> dict:
    with _device_lock:
        return {"size": len(_device_cache),
                "capacity": _DEVICE_CACHE_MAXSIZE, **_device_stats,
                "keys": [k[0] for k in _device_cache]}


obs.register_cache("spectral_device_lru", _device_cache_info)


def hann_window(frame_length: int, dtype=np.float32) -> np.ndarray:
    """Periodic Hann window.  Squared windows overlap-add to a constant
    for hop <= frame_length / 4; at hop = frame_length / 2 the envelope
    ripples but stays strictly positive, so the normalized overlap-add
    in :func:`istft` is still exact."""
    n = np.arange(frame_length)
    return (0.5 - 0.5 * np.cos(2 * np.pi * n / frame_length)).astype(dtype)


def frame_count(n: int, frame_length: int, hop: int) -> int:
    """Number of full frames a length-``n`` signal yields (no padding)."""
    if n < frame_length:
        return 0
    return 1 + (n - frame_length) // hop


def _check_stft_args(n, frame_length, hop):
    if frame_length <= 0 or hop <= 0:
        raise ValueError(f"frame_length and hop must be positive, got "
                         f"{frame_length} and {hop}")
    if hop > frame_length:
        raise ValueError(
            f"hop {hop} > frame_length {frame_length} drops samples "
            "(and makes ISTFT ill-posed)")
    if frame_count(n, frame_length, hop) == 0:
        raise ValueError(f"signal length {n} < frame_length {frame_length}")


def _frame_indices(n, frame_length, hop):
    frames = frame_count(n, frame_length, hop)
    return (np.arange(frames)[:, None] * hop
            + np.arange(frame_length)[None, :])


def _resolve_window(window, length: int, dtype=np.float32) -> np.ndarray:
    """Window argument -> ``length`` samples: None = periodic Hann,
    a :func:`waveforms.get_window` name or ``(name, param)`` tuple
    (scipy convention; NOTE get_window is symmetric where scipy's
    spectral default is periodic — PORTING.md divergence table), or an
    explicit array."""
    if window is None:
        return hann_window(length, dtype)
    # only str/tuple are window SPECS (scipy's convention) — a numeric
    # list is window samples and falls through to the array path
    if isinstance(window, (str, tuple)):
        from veles.simd_tpu.ops.waveforms import get_window

        return get_window(window, length).astype(dtype)
    window = np.asarray(window, dtype)
    if window.shape != (length,):
        raise ValueError(f"window shape {window.shape} != ({length},)")
    return window


def _framing_r(frame_length: int, hop: int) -> int:
    """Reshape-decomposition order for the device framing paths: ``r =
    frame_length // hop`` when that path applies, else 0 (gather).

    The SINGLE home of the decision :func:`_take_frames`, its adjoint
    :func:`_overlap_add`, and the telemetry layer all share — r bounds
    the unroll (r slices + an r-operand stack); past ~16 the op-count
    cost eats the gather win (measured win was at r=4).  Retune here,
    nowhere else."""
    r = frame_length // hop if frame_length % hop == 0 else 0
    return r if 1 <= r <= 16 else 0


def _framing_path(frame_length: int, hop: int) -> str:
    """Telemetry name for the framing decision (the 99x STFT PR),
    computed OUTSIDE traced code so the public entry points can record
    it per call."""
    return ("reshape_interleave" if _framing_r(frame_length, hop)
            else "gather")


def _take_frames(x, frame_length, hop):
    """``[..., n] -> [..., frames, frame_length]`` on device.

    When ``frame_length % hop == 0`` the frame matrix decomposes into
    ``r = frame_length // hop`` contiguous reshapes (group ``o`` holds
    frames ``f ≡ o (mod r)``, which tile ``x[o*hop:]`` back to back),
    interleaved by one stack+reshape — contiguous copies instead of a
    row gather.  Measured on v5e (128k signal, fl=1024, hop=256): the
    ``jnp.take`` gather was 91% of STFT time (3,730 of 4,092 us); this
    form cut the whole STFT to 40 us — 33 -> 3,262 Msamples/s (99x).
    Other hops keep the gather (routing lives in :func:`_framing_r`)."""
    n = x.shape[-1]
    frames = frame_count(n, frame_length, hop)
    r = _framing_r(frame_length, hop)
    if r == 0:
        idx = jnp.asarray(_frame_indices(n, frame_length, hop))
        return jnp.take(x, idx, axis=-1)
    c_max = -(-frames // r)
    groups = []
    for o in range(r):
        c_o = max(0, -(-(frames - o) // r))
        g = jax.lax.slice_in_dim(x, o * hop, o * hop
                                 + c_o * frame_length, axis=-1)
        g = g.reshape(x.shape[:-1] + (c_o, frame_length))
        if c_o < c_max:
            padw = [(0, 0)] * (g.ndim - 2) + [(0, c_max - c_o), (0, 0)]
            g = jnp.pad(g, padw)
        groups.append(g)
    inter = jnp.stack(groups, axis=-2)      # [..., c_max, r, fl]
    inter = inter.reshape(x.shape[:-1] + (c_max * r, frame_length))
    return jax.lax.slice_in_dim(inter, 0, frames, axis=-2)


def _rdft_basis(frame_length: int, window) -> np.ndarray:
    """``[frame_length, 2*bins]`` real-DFT analysis basis with the
    window folded in: ``frames @ basis`` gives ``[Re X | Im X]``
    (``Re X[k] = sum_n w[n] f[n] cos(2 pi n k / L)``, ``Im X[k] =
    -sum_n w[n] f[n] sin(...)``).  LRU-cached per (frame_length,
    window) — the ``rdft_matmul`` route's whole point is that this
    matrix is built once and the transform is a dense MXU matmul."""
    L = int(frame_length)
    window = np.asarray(window, np.float32)
    key = ("rdft_fwd", L, window.tobytes())

    def build():
        bins = L // 2 + 1
        n = np.arange(L)[:, None]
        k = np.arange(bins)[None, :]
        ang = 2.0 * np.pi * n * k / L
        w = np.asarray(window, np.float64)[:, None]
        return np.concatenate([w * np.cos(ang), -w * np.sin(ang)],
                              axis=1).astype(np.float32)

    return _cached_host(key, build)


def _rdft_inv_basis(frame_length: int, window) -> np.ndarray:
    """``[2*bins, frame_length]`` real-DFT synthesis basis with the
    window folded in: ``[Re X | Im X] @ inv_basis`` gives the
    window-multiplied time frame ``w[n] * (1/L) [X[0] + 2 sum_k (Re
    cos - Im sin) + X[Nyq] (-1)^n]`` — the irfft as one matmul,
    feeding the existing overlap-add."""
    L = int(frame_length)
    window = np.asarray(window, np.float32)
    key = ("rdft_inv", L, window.tobytes())

    def build():
        bins = L // 2 + 1
        alpha = np.full(bins, 2.0)
        alpha[0] = 1.0
        if L % 2 == 0:
            alpha[-1] = 1.0
        k = np.arange(bins)[:, None]
        n = np.arange(L)[None, :]
        ang = 2.0 * np.pi * k * n / L
        w = np.asarray(window, np.float64)[None, :]
        scale = (alpha / L)[:, None]
        return np.concatenate([scale * np.cos(ang) * w,
                               -scale * np.sin(ang) * w],
                              axis=0).astype(np.float32)

    return _cached_host(key, build)


# ---------------------------------------------------------------------------
# Cooley-Tukey factorized matmul DFT (the pod-scale Fourier building
# blocks: per-factor DFT bases + twiddles, shared by the local
# ``ct_matmul`` routes here and the sharded stages in
# ``parallel/fourier.py`` — arXiv:2002.03260's formulation)
# ---------------------------------------------------------------------------


def ct_factor(n: int, max_factor: int | None = None,
              multiple: int = 1):
    """Balanced Cooley-Tukey split ``n = n1 * n2`` with both factors
    ``<= max_factor`` (default :data:`AUTO_DFT_MATMUL_MAX_FRAME`, the
    basis-residency bound) and both divisible by ``multiple`` (the
    sharded stages need each factor to split over the mesh axis for
    the ``all_to_all`` transposes).  Returns ``(n1, n2)`` with
    ``n1 >= n2`` minimizing ``max(n1, n2)``, or ``None`` when no such
    factorization exists (prime ``n``, or ``n`` too large for the
    factor bound)."""
    n = int(n)
    if max_factor is None:
        max_factor = AUTO_DFT_MATMUL_MAX_FRAME
    multiple = max(1, int(multiple))
    if n < 4:
        return None
    best = None
    d = 1
    while d * d <= n:
        if n % d == 0:
            for n2 in (d, n // d):
                n1 = n // n2
                if n1 < n2:
                    continue
                if n1 > max_factor or n2 < 2:
                    continue
                if n1 % multiple or n2 % multiple:
                    continue
                if best is None or n1 < best[0]:
                    best = (n1, n2)
        d += 1
    return best


def dft_basis_parts(n: int):
    """Host-cached ``(cos, sin)`` float32 ``[n, n]`` pair of the dense
    DFT basis angles ``2 pi j k / n`` — the forward basis is
    ``cos - i sin``, the inverse ``(cos + i sin) / n``; keeping the
    parts REAL means no complex buffer ever crosses the host/device
    boundary (the axon relay cannot transfer complex either way)."""
    n = int(n)

    def build():
        j = np.arange(n, dtype=np.float64)
        ang = 2.0 * np.pi * np.outer(j, j) / n
        return (np.cos(ang).astype(np.float32),
                np.sin(ang).astype(np.float32))

    return _cached_host(("dft_parts", n), build)


def twiddle_parts(n1: int, n2: int):
    """Host-cached ``(cos, sin)`` float32 ``[n2, n1]`` twiddle grid
    ``2 pi k2 n1_idx / (n1 n2)`` — the inter-stage factor of the
    ``n = n1 * n2`` Cooley-Tukey factorization (row = stage-1 output
    index, column = the other factor's index)."""
    n1, n2 = int(n1), int(n2)

    def build():
        ang = (2.0 * np.pi / (n1 * n2)
               * np.outer(np.arange(n2, dtype=np.float64),
                          np.arange(n1, dtype=np.float64)))
        return (np.cos(ang).astype(np.float32),
                np.sin(ang).astype(np.float32))

    return _cached_host(("twiddle", n1, n2), build)


def ct_basis_parts(n1: int, n2: int):
    """The full 6-tuple of float32 constants one ``n = n1 * n2``
    factorized DFT needs: ``(cos2, sin2, cos1, sin1, twc, tws)`` —
    stage bases ``[n2, n2]`` / ``[n1, n1]`` plus the ``[n2, n1]``
    twiddle grid.  Serves forward AND inverse (the inverse swaps the
    stage roles and flips the sign — :func:`ct_apply`)."""
    c2, s2 = dft_basis_parts(n2)
    c1, s1 = dft_basis_parts(n1)
    twc, tws = twiddle_parts(n1, n2)
    return c2, s2, c1, s1, twc, tws


def ct_basis_device(n1: int, n2: int):
    """Device-cached upload of :func:`ct_basis_parts` (same dedup
    discipline as the rdft bases: host LRU for construction, device
    LRU for the upload)."""
    key = ("ct_basis", int(n1), int(n2))
    return _cached_device(
        key, lambda: tuple(jnp.asarray(a)
                           for a in ct_basis_parts(n1, n2)))


def _ct_stage(vre, vim, cos, sin, sign, axis_spec):
    """One DFT stage as real matmuls: contract ``vre/vim`` with the
    ``cos + i * sign * sin`` basis along the axis named by
    ``axis_spec`` (an einsum triple).  ``vim=None`` means real input
    (stage 1 of a forward rfft: two matmuls instead of four)."""
    hi = prx.HIGHEST
    e = functools.partial(jnp.einsum, axis_spec, precision=hi)
    if vim is None:
        return e(vre, cos), sign * e(vre, sin)
    return (e(vre, cos) - sign * e(vim, sin),
            sign * e(vre, sin) + e(vim, cos))


def ct_apply(x, n1: int, n2: int, parts, inverse: bool = False):
    """Traceable length-``n1*n2`` Cooley-Tukey DFT along the LAST axis
    as two dense MXU matmul stages + a twiddle multiply — the
    single-chip form of the pod-scale factorization (no collectives;
    the sharded twin lives in ``parallel/fourier.py``).  ``x`` real or
    complex; ``parts`` from :func:`ct_basis_device` (or host parts
    uploaded by the caller).  Returns ``(re, im)`` float32 arrays —
    callers build complex64 (or take ``re`` for an inverse of a
    Hermitian spectrum) themselves, so no complex constant is ever
    materialized on the host side."""
    c2, s2, c1, s1, twc, tws = parts
    n1, n2 = int(n1), int(n2)
    sign = 1.0 if inverse else -1.0
    if jnp.iscomplexobj(x):
        xre, xim = jnp.real(x), jnp.imag(x)
    else:
        xre, xim = x, None
    if inverse:
        # inverse = the same pipeline with stage roles swapped
        # (input viewed [n1, n2], stage 1 over the n1 axis) and the
        # twiddle grid transposed; 1/n fold applied at the end
        ga, gb = n1, n2
        ca, sa, cb, sb = c1, s1, c2, s2
        twc_g, tws_g = twc.T, tws.T
    else:
        ga, gb = n2, n1
        ca, sa, cb, sb = c2, s2, c1, s1
        twc_g, tws_g = twc, tws
    vre = xre.reshape(xre.shape[:-1] + (ga, gb))
    vim = xim.reshape(xim.shape[:-1] + (ga, gb)) if xim is not None \
        else None
    # stage 1: length-ga DFT down the -2 axis
    yre, yim = _ct_stage(vre, vim, ca, sa, sign, "...gf,gh->...hf")
    # twiddle: elementwise [ga, gb] grid
    tre, tim = twc_g, sign * tws_g
    zre = yre * tre - yim * tim
    zim = yre * tim + yim * tre
    # stage 2: length-gb DFT along the last axis
    wre, wim = _ct_stage(zre, zim, cb, sb, sign, "...hf,fk->...hk")
    # natural order: out[k_b * ga + k_a] = w[k_a, k_b]
    wre = jnp.swapaxes(wre, -1, -2).reshape(xre.shape[:-1]
                                            + (ga * gb,))
    wim = jnp.swapaxes(wim, -1, -2).reshape(xre.shape[:-1]
                                            + (ga * gb,))
    if inverse:
        scale = np.float32(1.0 / (n1 * n2))
        return wre * scale, wim * scale
    return wre, wim


def hermitian_extend(spec, n: int):
    """Full length-``n`` spectrum from the one-sided ``n//2 + 1`` bins
    of a real signal (``X[k] = conj(X[n-k])``) — the irfft front half
    the ct_matmul inverse routes share."""
    bins = n // 2 + 1
    tail = jnp.conj(spec[..., 1:n - bins + 1])[..., ::-1]
    return jnp.concatenate([spec, tail], axis=-1)


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft",
                   static_argnames=("frame_length", "hop"))
def _stft_xla(x, window, frame_length, hop):
    frames = _take_frames(x, frame_length, hop)
    return jnp.fft.rfft(frames * window, axis=-1)


def _stft_rdft_body(x, basis, frame_length, hop, precision):
    """Shared traceable body of the rdft routes — the precision knob
    is the ONLY difference between ``rdft_matmul`` and its
    ``bf16_comp`` variant (runtime/precision.py)."""
    frames = _take_frames(x, frame_length, hop)
    out = prx.p_einsum("...fl,lb->...fb", frames, basis,
                       precision=precision)
    bins = frame_length // 2 + 1
    return jax.lax.complex(out[..., :bins], out[..., bins:])


@functools.partial(obs.instrumented_jit, op="stft",
                   route="rdft_matmul",
                   static_argnames=("frame_length", "hop"))
def _stft_rdft(x, basis, frame_length, hop):
    return _stft_rdft_body(x, basis, frame_length, hop, "highest")


@functools.partial(obs.instrumented_jit, op="stft",
                   route="rdft_matmul_bf16_comp",
                   static_argnames=("frame_length", "hop"))
def _stft_rdft_comp(x, basis, frame_length, hop):
    return _stft_rdft_body(x, basis, frame_length, hop, "bf16_comp")


# (frame_length, hop) classes whose fused-STFT compile OOMed Mosaic's
# scoped-vmem stack — the demote-and-remember discipline shared with
# the conv routes, now through the one engine (runtime/faults.py).
# Bounded LRU with hit/miss/eviction counters in obs.caches(); an
# evicted class just pays one more failed compile if it returns.
_STFT_PALLAS_MAXSIZE = 256
_STFT_PALLAS_REJECTED = obs.LRUSet(_STFT_PALLAS_MAXSIZE)
faults.register_rejection_cache(
    "stft_pallas_rejected", lambda: _STFT_PALLAS_REJECTED,
    _STFT_PALLAS_MAXSIZE)


# The spectral candidate-route tables (the unified engine,
# runtime/routing.py): priority order IS the static selection order,
# predicates are the single home of the route constants, the fused
# kernel's rejection cache + injection site ride the table so the
# demote-and-remember policy and the fault harness see one source of
# truth, and the measured autotuner (VELES_SIMD_AUTOTUNE=on) probes
# exactly these candidates.
_STFT_FAMILY = routing.family("stft", (
    routing.Route(
        "pallas_fused",
        predicate=lambda frame_length, hop, frames=0, **_: (
            _pk.pallas_available() and _pk.stft_pallas_allowed()
            and frame_length % hop == 0 and hop % 128 == 0
            and frame_length // hop >= 2
            and frames >= _pk.PALLAS_STFT_MIN_FRAMES
            and _pk.fits_vmem_stft(frame_length, hop)),
        fault_site="spectral.stft_pallas",
        rejection_cache=lambda: _STFT_PALLAS_REJECTED,
        rejection_key=lambda frame_length, hop, **_: (frame_length,
                                                      hop),
        roofline={"kind": "stft"},
        doc="fused framing+window+DFT Mosaic kernel; x streamed "
            "through VMEM once, overlap carried between grid steps"),
    routing.Route(
        "rdft_matmul",
        predicate=lambda frame_length, **_:
            frame_length <= AUTO_DFT_MATMUL_MAX_FRAME,
        disable_env=_DFT_MATMUL_ENV,
        roofline={"kind": "stft"},
        doc="precomputed real-DFT basis matmul on the MXU (window "
            "folded in, basis LRU-cached per geometry)"),
    routing.Route(
        "xla_fft",
        roofline={"kind": "stft"},
        doc="XLA FFT lowering — the long-frame terminal fallback"),
    # precision-variant candidate AFTER the terminal fallback: the
    # static prior never changes, the measured autotuner probes it
    # like any candidate and a tune-cache winner steers dispatch
    # (runtime/precision.py; the same pattern across every
    # matmul-heavy family)
    routing.Route(
        "rdft_matmul_bf16_comp",
        predicate=lambda frame_length, **_: (
            frame_length <= AUTO_DFT_MATMUL_MAX_FRAME
            and dft_matmul_allowed()
            and prx.precision_allowed("bf16_comp")),
        disable_env=prx.BF16_COMP_ENV,
        roofline={"kind": "stft"},
        doc="the basis matmul at bf16_comp: split/compensated bf16 "
            "accumulation, ~fp32 accuracy at 3 MXU passes "
            "(VELES_SIMD_DISABLE_BF16_COMP opts out)"),
))

_ISTFT_FAMILY = routing.family("istft", (
    routing.Route(
        "rdft_matmul",
        predicate=lambda frame_length, **_:
            frame_length <= AUTO_DFT_MATMUL_MAX_FRAME,
        disable_env=_DFT_MATMUL_ENV,
        doc="inverse-basis matmul feeding the shared overlap-add"),
    routing.Route("xla_fft", doc="XLA irfft + overlap-add"),
    routing.Route(
        "rdft_matmul_bf16_comp",
        predicate=lambda frame_length, **_: (
            frame_length <= AUTO_DFT_MATMUL_MAX_FRAME
            and dft_matmul_allowed()
            and prx.precision_allowed("bf16_comp")),
        disable_env=prx.BF16_COMP_ENV,
        doc="inverse-basis matmul at bf16_comp (split/compensated "
            "accumulation)"),
))

_HILBERT_FAMILY = routing.family("hilbert", (
    routing.Route(
        "matmul_dft",
        predicate=lambda n, **_: n <= HILBERT_MATMUL_MAX_N,
        disable_env=_DFT_MATMUL_ENV,
        doc="dense circulant analytic-signal operator as two MXU "
            "matmuls (no complex transfers through the relay)"),
    routing.Route("xla_fft", doc="fft -> multiplier -> ifft"),
    routing.Route(
        "matmul_dft_bf16_comp",
        predicate=lambda n, **_: (
            n <= HILBERT_MATMUL_MAX_N and dft_matmul_allowed()
            and prx.precision_allowed("bf16_comp")),
        disable_env=prx.BF16_COMP_ENV,
        doc="the circulant operator at bf16_comp (split/compensated "
            "accumulation)"),
))

_CWT_FAMILY = routing.family("morlet_cwt", (
    routing.Route(
        "matmul_dft",
        predicate=lambda n, **_: n <= CWT_MATMUL_MAX_N,
        disable_env=_DFT_MATMUL_ENV,
        doc="positive-frequency DFT basis pair as dense MXU matmuls"),
    routing.Route(
        "ct_matmul",
        predicate=lambda n, **_: (n > CWT_MATMUL_MAX_N
                                  and ct_factor(n) is not None),
        disable_env=_DFT_MATMUL_ENV,
        doc="Cooley-Tukey factorized matmul DFT (two per-factor MXU "
            "stages + twiddle) — the pod-scale formulation's "
            "single-chip form, for transform sizes past the dense "
            "basis-residency cutoff"),
    routing.Route("xla_fft", doc="batched fft -> bank -> ifft"),
    routing.Route(
        "matmul_dft_bf16_comp",
        predicate=lambda n, **_: (
            n <= CWT_MATMUL_MAX_N and dft_matmul_allowed()
            and prx.precision_allowed("bf16_comp")),
        disable_env=prx.BF16_COMP_ENV,
        doc="the positive-frequency basis pair at bf16_comp "
            "(split/compensated accumulation)"),
))


def _use_matmul_dft(frame_length: int) -> bool:
    """Route a spectral transform through the precomputed real-DFT
    basis matmul — the MXU-native formulation for the frame sizes STFT
    actually uses (arXiv:2002.03260, TINA).  Thin delegate into the
    ``stft`` candidate table (runtime/routing.py), where the
    ``AUTO_DFT_MATMUL_MAX_FRAME`` bound and the
    ``VELES_SIMD_DISABLE_DFT_MATMUL`` opt-out live."""
    return _STFT_FAMILY.gate("rdft_matmul",
                             frame_length=int(frame_length))


def _use_pallas_stft(frame_length: int, hop: int, frames: int) -> bool:
    """Route STFT through the fused Pallas kernel
    (:func:`~veles.simd_tpu.ops.pallas_kernels.stft_pallas`).  Thin
    delegate into the ``stft`` candidate table: rejection memory
    outranks everything (a demoted (frame, hop) class skips the doomed
    route without re-raising), an armed fault plan opens the gate so
    the demote path runs on CPU CI, then the kernel's geometry gates
    (dividing 128-multiple hop, enough frames, VMEM residency) and the
    ``VELES_SIMD_DISABLE_STFT_PALLAS`` opt-out decide."""
    return _STFT_FAMILY.route_allowed(
        "pallas_fused", frame_length=int(frame_length), hop=int(hop),
        frames=int(frames))


def _select_stft_route(frame_length: int, hop: int, frames: int) -> str:
    """The STATIC stft route decision, in table priority order (single
    home — the public entry point, ``batched.batched_stft``, and bench
    all ask here; the autotuner treats it as the cold-start prior)."""
    return _STFT_FAMILY.static_select(
        frame_length=int(frame_length), hop=int(hop),
        frames=int(frames))


def _stft_tune_class(frame_length: int, hop: int, frames: int,
                     rows: int) -> dict:
    """The stft tune-cache geometry CLASS (shared by :func:`stft` and
    ``batched.batched_stft`` so one pack entry steers both): frames
    bucketed at the pallas gate's threshold — the only
    frames-dependence any route has — so variable-length signals at
    one (frame, hop) share one entry; rows pow2-bucketed because the
    matmul-vs-fft crossover shifts with batch."""
    return {"frame_length": int(frame_length), "hop": int(hop),
            "rows": routing.pow2_bucket(int(rows)),
            "frames_class": (_pk.PALLAS_STFT_MIN_FRAMES
                             if frames >= _pk.PALLAS_STFT_MIN_FRAMES
                             else 0)}


def _stft_route_for(frame_length: int, hop: int, frames: int,
                    rows: int) -> str:
    """Engine-selected stft route WITHOUT probing: honors a tune-cache
    winner (autotune on/readonly) over the static prior.  The batched
    entry point asks here — it compiles its own handle, so it consults
    the pack but never probes (the non-batched runners it would time
    are not what it dispatches)."""
    return _STFT_FAMILY.select(
        eligible=_STFT_FAMILY.eligible(
            frame_length=int(frame_length), hop=int(hop),
            frames=int(frames)),
        **_stft_tune_class(frame_length, hop, frames, rows))


def _device_basis(kind, length, window, build_host):
    """Device-cached windowed basis: construction deduped by the host
    LRU (inside ``build_host``), upload deduped here."""
    window = np.asarray(window, np.float32)
    key = (kind, int(length), window.tobytes())
    return _cached_device(key, lambda: jnp.asarray(build_host()))


def _run_stft_xla(x, window, frame_length, hop, forced=False):
    del forced
    return _stft_xla(jnp.asarray(x, jnp.float32), jnp.asarray(window),
                     frame_length, hop)


def _run_stft_rdft(x, window, frame_length, hop, forced=False):
    del forced
    basis = _device_basis("rdft_fwd", frame_length, window,
                          lambda: _rdft_basis(frame_length, window))
    return _stft_rdft(jnp.asarray(x, jnp.float32), basis,
                      frame_length, hop)


def _run_stft_rdft_comp(x, window, frame_length, hop, forced=False):
    del forced
    basis = _device_basis("rdft_fwd", frame_length, window,
                          lambda: _rdft_basis(frame_length, window))
    return _stft_rdft_comp(jnp.asarray(x, jnp.float32), basis,
                           frame_length, hop)


def _stft_pallas_basis(frame_length, hop, window):
    window = np.asarray(window, np.float32)
    key = ("stft_pallas", int(frame_length), int(hop), window.tobytes())
    host = _cached_host(key, lambda: _pk._stft_basis_blocks(
        frame_length, hop, window))
    return _cached_device(key, lambda: jnp.asarray(host))


def _run_stft_pallas(x, window, frame_length, hop, forced=False):
    """The fused-kernel route, through the shared demote-and-remember
    engine (runtime/faults.py): the scoped-stack cap is not
    predictable from shape arithmetic, so the specific Mosaic vmem-OOM
    compile error demotes this (frame, hop) class to the matmul/FFT
    route and records the demotion (decision event + counter) so the
    executed route is never misattributed.  A FORCED pallas route
    still remembers the rejection but re-raises — a caller who pinned
    the kernel (benchmark, bisect) must never silently get another
    route's numbers."""
    basis = _stft_pallas_basis(frame_length, hop, window)
    fb_route = ("rdft_matmul" if _use_matmul_dft(frame_length)
                else "xla_fft")

    def _demoted():
        obs.record_decision(
            "stft_route", fb_route, frame_length=int(frame_length),
            hop=int(hop), demoted_from="pallas_fused")
        return _STFT_ROUTES[fb_route](x, window, frame_length, hop)

    return faults.demote_and_remember(
        "spectral.stft_pallas",
        lambda: _pk.stft_pallas(x, frame_length, hop, basis=basis),
        _demoted,
        cache=_STFT_PALLAS_REJECTED,
        key=(int(frame_length), int(hop)),
        route="pallas_fused", fallback_route=fb_route,
        counter="stft_pallas_demotion", forced=forced)


_STFT_ROUTES = {"xla_fft": _run_stft_xla,
                "rdft_matmul": _run_stft_rdft,
                "rdft_matmul_bf16_comp": _run_stft_rdft_comp,
                "pallas_fused": _run_stft_pallas}


# -- streaming STFT hooks (the pipeline compiler's state-export API) --------

def stft_stream_carry(frame_length: int, hop: int) -> int:
    """Input-history samples a streaming STFT carries between blocks:
    ``frame_length - hop`` (the inter-frame overlap).  Zero-seeded at
    stream start, so the stream computes the STFT of the zero-prefixed
    signal: streamed frame ``f`` equals one-shot frame
    ``f - (frame_length/hop - 1)`` once real samples fill the carry.
    Requires ``hop | frame_length`` and ``hop | block`` (a fixed-shape
    step needs a constant ``block/hop`` frames per block)."""
    frame_length, hop = int(frame_length), int(hop)
    _check_stft_args(frame_length, frame_length, hop)
    if frame_length % hop != 0:
        raise ValueError(
            f"streaming STFT needs hop {hop} dividing frame_length "
            f"{frame_length} (frame-aligned carry)")
    return frame_length - hop


def select_stft_stream_route(frame_length: int, hop: int, frames: int,
                             tune_geom: dict | None = None) -> str:
    """Compile-time route for the streaming STFT stage — the pipeline
    compiler's hook into the ``stft`` candidate table.  Eligibility is
    restricted to the outer-jit-safe routes (``rdft_matmul`` /
    ``xla_fft``): the fused Pallas kernel carries its own grid-step
    state, which cannot thread through a fused pipeline step.
    Consults the tune cache, never probes."""
    eligible = [name for name in _STFT_FAMILY.eligible(
        frame_length=int(frame_length), hop=int(hop),
        frames=int(frames)) if name != "pallas_fused"]
    return _STFT_FAMILY.select(
        eligible=eligible or ["xla_fft"], tune_geom=tune_geom,
        frame_length=int(frame_length), hop=int(hop),
        frames=int(frames))


def stft_stream_step(x_ext, frame_length: int, hop: int, window,
                     route: str):
    """TRACEABLE one-block STFT step: ``x_ext[..., (L - hop) + block]``
    (carry + new chunk) -> complex64 ``[..., block/hop, L//2 + 1]``.
    Runs the same ``obs.instrumented_jit`` route cores one-shot
    :func:`stft` dispatches, so it inlines into a fused outer jit."""
    if route in ("rdft_matmul", "rdft_matmul_bf16_comp"):
        basis = _device_basis(
            "rdft_fwd", frame_length, window,
            lambda: _rdft_basis(frame_length, window))
        core = (_stft_rdft_comp if route == "rdft_matmul_bf16_comp"
                else _stft_rdft)
        return core(x_ext, basis, frame_length, hop)
    return _stft_xla(x_ext, jnp.asarray(window, jnp.float32),
                     frame_length, hop)


def stft_stream_oracle(x, frame_length: int, hop: int, window=None):
    """NumPy float64 one-shot oracle of the STREAMING frame grid (the
    zero-prefixed signal's STFT) — the pipeline parity reference and
    stage-by-stage degradation path."""
    x = np.asarray(x, np.float64)
    carry = stft_stream_carry(frame_length, hop)
    pre = np.zeros(x.shape[:-1] + (carry,), np.float64)
    return stft_na(np.concatenate([pre, x], axis=-1), frame_length,
                   hop, window)


def stft(x, frame_length: int, hop: int, window=None, simd=None,
         route=None):
    """Short-time Fourier transform.

    ``x[..., n] -> complex64 [..., frames, frame_length // 2 + 1]`` with
    ``frames = 1 + (n - frame_length) // hop`` (no padding — trailing
    samples short of a full frame are dropped, symmetric with
    :func:`istft`).  ``window`` defaults to the periodic Hann window.

    ``route`` forces one of ``rdft_matmul`` / ``pallas_fused`` /
    ``xla_fft`` (None auto-selects via :func:`_select_stft_route`);
    the chosen route is recorded as a ``stft_route`` decision event.
    """
    x_np = np.asarray(x) if not hasattr(x, "shape") else x
    _check_stft_args(x_np.shape[-1], frame_length, hop)
    window = _resolve_window(window, frame_length)
    if resolve_simd(simd, op="stft"):
        n = int(x_np.shape[-1])
        frames = frame_count(n, frame_length, hop)
        forced = route is not None
        if forced and route not in _STFT_ROUTES:
            raise ValueError(
                f"route must be one of {sorted(_STFT_ROUTES)}, "
                f"got {route!r}")
        if forced:
            chosen = route
        else:
            # probe thunks call the route runners as FORCED routes
            # (vmem-OOM during a probe is remembered + skipped, never
            # silently rerouted); the engine invokes the factory only
            # when the measured mode will really probe, and refuses
            # under an outer trace (probe_operand check).
            # Eligibility uses the true frame count; the tune-cache
            # geometry CLASS (_stft_tune_class, shared with
            # batched_stft) buckets frames and rows so shape churn
            # shares finite entries instead of probing per length
            rows = (int(np.prod(x_np.shape[:-1]))
                    if len(x_np.shape) > 1 else 1)
            chosen = _STFT_FAMILY.select(
                eligible=_STFT_FAMILY.eligible(
                    frame_length=int(frame_length), hop=int(hop),
                    frames=int(frames)),
                runners=lambda: {
                    name: (lambda fn=fn: fn(x_np, window,
                                            frame_length, hop,
                                            forced=True))
                    for name, fn in _STFT_ROUTES.items()},
                probe_operand=x_np,
                **_stft_tune_class(frame_length, hop, frames, rows))
        path = _framing_path(frame_length, hop)
        obs.record_decision(
            "stft_route", chosen, n=n, frame_length=int(frame_length),
            hop=int(hop), frames=int(frames), forced=forced)
        # the framing-path decision stays the LAST event (the 99x-STFT
        # telemetry contract, pinned by test_obs.py)
        obs.record_decision(
            "stft", path, n=n, frame_length=int(frame_length),
            hop=int(hop))
        with obs.span("stft.dispatch", route=chosen, path=path):
            # x_np, not x: every runner needs .shape (lists/tuples are
            # supported inputs, same as the pre-route code).  The
            # transient-fault policy (bounded retry on device-lost/
            # timeout, then graceful degradation to the float64
            # oracle) wraps the whole route call, behind the shape
            # class's circuit breaker (frame/hop gate routes exactly,
            # so they key exactly).  A FORCED route gets the retries
            # but never the oracle fallback — a caller who pinned a
            # route (bench per-route rows) must never silently get
            # another implementation's numbers; with its breaker open
            # it dispatches as a zero-retry trial.
            return faults.breaker_guarded(
                "stft.dispatch",
                (chosen, int(frame_length), int(hop)),
                lambda: _STFT_ROUTES[chosen](x_np, window,
                                             frame_length, hop,
                                             forced=forced),
                fallback=None if forced else lambda: stft_na(
                    x_np, frame_length, hop,
                    window).astype(np.complex64))
    return stft_na(x, frame_length, hop, window).astype(np.complex64)


def stft_na(x, frame_length: int, hop: int, window=None):
    """NumPy float64 oracle twin of :func:`stft` (complex128 out)."""
    x = np.asarray(x, np.float64)
    _check_stft_args(x.shape[-1], frame_length, hop)
    window = _resolve_window(window, frame_length, np.float64)
    idx = _frame_indices(x.shape[-1], frame_length, hop)
    frames = x[..., idx] * window
    return np.fft.rfft(frames, axis=-1)


def _ola_envelope(n, frame_length, hop, window):
    """Sum of squared windows at each output sample (COLA envelope)."""
    idx = _frame_indices(n, frame_length, hop)
    env = np.zeros(n, np.float64)
    np.add.at(env, idx, (np.asarray(window, np.float64) ** 2)[None, :])
    return env


def _env_inv(n, frame_length, hop, window):
    """Pseudo-inverse of the COLA envelope (float64): zero where the
    window overlap vanishes, 1/env elsewhere.  The single definition the
    device ISTFT, the oracle, and the sharded ISTFT all share."""
    env = _ola_envelope(n, frame_length, hop, window)
    return np.where(env > 1e-8, 1.0 / np.maximum(env, 1e-8), 0.0)


def _overlap_add(frames, n, frame_length, hop):
    """``[..., F, frame_length] -> [..., n]`` overlap-add — the adjoint
    of :func:`_take_frames`, with the same decomposition: for dividing
    hops, frames of one residue class mod ``r`` tile WITHOUT overlap,
    so each class is a reshape placed at its offset and the scatter
    becomes ``r`` full-length adds (the ``.at[].add`` scatter was the
    whole ISTFT cost on v5e: 4,758 of 4,800 us at 128k/1024/256).
    Other hops keep the scatter (routing lives in :func:`_framing_r`)."""
    F = frames.shape[-2]
    r = _framing_r(frame_length, hop)
    if r == 0:
        idx = jnp.asarray(_frame_indices(n, frame_length, hop))
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        return out.at[..., idx].add(frames)
    total = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
    for o in range(r):
        c_o = max(0, -(-(F - o) // r))
        if c_o == 0:
            continue
        g = frames[..., o::r, :][..., :c_o, :]
        seg = g.reshape(frames.shape[:-2] + (c_o * frame_length,))
        padw = ([(0, 0)] * (seg.ndim - 1)
                + [(o * hop, n - o * hop - c_o * frame_length)])
        total = total + jnp.pad(seg, padw)
    return total


@functools.partial(obs.instrumented_jit, op="istft", route="xla_fft",
                   static_argnames=("n", "frame_length", "hop"))
def _istft_xla(spec, window, env_inv, n, frame_length, hop):
    frames = jnp.fft.irfft(spec, frame_length, axis=-1) * window
    return _overlap_add(frames, n, frame_length, hop) * env_inv


def _istft_rdft_body(spec, inv_basis, env_inv, n, frame_length, hop,
                     precision):
    parts = jnp.concatenate([jnp.real(spec), jnp.imag(spec)], axis=-1)
    frames = prx.p_einsum("...fb,bl->...fl", parts, inv_basis,
                          precision=precision)
    return _overlap_add(frames, n, frame_length, hop) * env_inv


@functools.partial(obs.instrumented_jit, op="istft",
                   route="rdft_matmul",
                   static_argnames=("n", "frame_length", "hop"))
def _istft_rdft(spec, inv_basis, env_inv, n, frame_length, hop):
    return _istft_rdft_body(spec, inv_basis, env_inv, n,
                            frame_length, hop, "highest")


@functools.partial(obs.instrumented_jit, op="istft",
                   route="rdft_matmul_bf16_comp",
                   static_argnames=("n", "frame_length", "hop"))
def _istft_rdft_comp(spec, inv_basis, env_inv, n, frame_length, hop):
    return _istft_rdft_body(spec, inv_basis, env_inv, n,
                            frame_length, hop, "bf16_comp")


def _run_istft_xla(spec, window, env_inv, n, frame_length, hop,
                   forced=False):
    del forced
    return _istft_xla(to_device(spec, jnp.complex64),
                      jnp.asarray(window), jnp.asarray(env_inv),
                      n, frame_length, hop)


def _run_istft_rdft(spec, window, env_inv, n, frame_length, hop,
                    forced=False):
    del forced
    inv_basis = _device_basis(
        "rdft_inv", frame_length, window,
        lambda: _rdft_inv_basis(frame_length, window))
    return _istft_rdft(to_device(spec, jnp.complex64),
                       inv_basis, jnp.asarray(env_inv),
                       n, frame_length, hop)


def _run_istft_rdft_comp(spec, window, env_inv, n, frame_length, hop,
                         forced=False):
    del forced
    inv_basis = _device_basis(
        "rdft_inv", frame_length, window,
        lambda: _rdft_inv_basis(frame_length, window))
    return _istft_rdft_comp(to_device(spec, jnp.complex64),
                            inv_basis, jnp.asarray(env_inv),
                            n, frame_length, hop)


_ISTFT_ROUTES = {"xla_fft": _run_istft_xla,
                 "rdft_matmul": _run_istft_rdft,
                 "rdft_matmul_bf16_comp": _run_istft_rdft_comp}


def istft(spec, n: int, frame_length: int, hop: int, window=None,
          simd=None, route=None):
    """Inverse STFT by windowed overlap-add with COLA normalization.

    Reconstructs the length-``n`` signal from ``stft(x, ...)`` output.
    Exact (to f32 round-off) wherever the window-overlap envelope is
    nonzero; with the default Hann window and ``hop = frame_length / 2**k``
    that is every sample except the first/last ``frame_length - hop``
    (where fewer windows overlap — there the least-squares estimate is
    still returned, normalized by the partial envelope).

    ``route`` forces ``rdft_matmul`` (inverse-basis matmul feeding the
    overlap-add) or ``xla_fft`` (None auto-selects; the chosen route is
    recorded as an ``istft_route`` decision event).
    """
    _check_stft_args(n, frame_length, hop)
    window = _resolve_window(window, frame_length)
    env_inv = _env_inv(n, frame_length, hop, window).astype(np.float32)
    frames = frame_count(n, frame_length, hop)
    spec_np = spec if hasattr(spec, "shape") else np.asarray(spec)
    if spec_np.shape[-2:] != (frames, frame_length // 2 + 1):
        raise ValueError(
            f"spec shape {spec_np.shape[-2:]} inconsistent with n={n}, "
            f"frame_length={frame_length}, hop={hop} (expect "
            f"{(frames, frame_length // 2 + 1)})")
    if resolve_simd(simd, op="istft"):
        forced = route is not None
        if forced and route not in _ISTFT_ROUTES:
            raise ValueError(
                f"route must be one of {sorted(_ISTFT_ROUTES)}, "
                f"got {route!r}")
        if forced:
            chosen = route
        else:
            # no istft route depends on the frame count, so the
            # tune-cache geometry class is (frame_length, hop) plus
            # the pow2-bucketed batch (the matmul-vs-fft crossover
            # shifts with rows, like stft/hilbert)
            rows = (int(np.prod(spec_np.shape[:-2]))
                    if len(spec_np.shape) > 2 else 1)
            chosen = _ISTFT_FAMILY.select(
                runners=lambda: {
                    name: (lambda fn=fn: fn(spec, window, env_inv, n,
                                            frame_length, hop,
                                            forced=True))
                    for name, fn in _ISTFT_ROUTES.items()},
                probe_operand=spec_np,
                frame_length=int(frame_length), hop=int(hop),
                rows=routing.pow2_bucket(rows))
        # the adjoint decomposition: framing gather <-> overlap-add
        # scatter, framing reshape <-> per-phase reshape adds
        path = ("scatter" if _framing_path(frame_length, hop) == "gather"
                else "reshape_overlap_add")
        obs.record_decision(
            "istft_route", chosen, n=int(n),
            frame_length=int(frame_length), hop=int(hop),
            forced=forced)
        # the overlap-add path decision stays the LAST event (the
        # telemetry contract test_obs.py pins)
        obs.record_decision(
            "istft", path, n=int(n), frame_length=int(frame_length),
            hop=int(hop))
        with obs.span("istft.dispatch", route=chosen, path=path):
            # forced routes retry but never degrade (see stft);
            # breaker-gated per (route, frame, hop) class like stft
            return faults.breaker_guarded(
                "istft.dispatch",
                (chosen, int(frame_length), int(hop)),
                lambda: _ISTFT_ROUTES[chosen](spec, window, env_inv,
                                              n, frame_length, hop,
                                              forced=forced),
                fallback=None if forced else lambda: istft_na(
                    spec_np, n, frame_length, hop,
                    window).astype(np.float32))
    return istft_na(spec, n, frame_length, hop, window).astype(np.float32)


def istft_na(spec, n: int, frame_length: int, hop: int, window=None):
    """NumPy float64 oracle twin of :func:`istft`."""
    _check_stft_args(n, frame_length, hop)
    window = _resolve_window(window, frame_length, np.float64)
    spec = np.asarray(spec)
    frames = np.fft.irfft(spec, frame_length, axis=-1) * window
    idx = _frame_indices(n, frame_length, hop)
    out = np.zeros(spec.shape[:-2] + (n,), np.float64)
    # np.add.at over the leading batch dims one frame-row at a time
    for f in range(idx.shape[0]):
        out[..., idx[f]] += frames[..., f, :]
    return out * _env_inv(n, frame_length, hop, window)


def spectrogram(x, frame_length: int, hop: int, window=None, simd=None,
                route=None):
    """Power spectrogram ``|STFT|^2`` -> f32 [..., frames, bins].
    ``route`` passes through to :func:`stft`."""
    s = stft(x, frame_length, hop, window, simd=simd, route=route)
    if resolve_simd(simd, op="spectrogram"):
        return (s.real ** 2 + s.imag ** 2).astype(jnp.float32)
    return (np.abs(s) ** 2).astype(np.float32)


def spectrogram_na(x, frame_length: int, hop: int, window=None):
    s = stft_na(x, frame_length, hop, window)
    return np.abs(s) ** 2


def _analytic_multiplier(n: int) -> np.ndarray:
    """Frequency-domain step for the analytic signal: keep DC (and
    Nyquist when n is even) at 1, double positive frequencies, zero the
    negatives.  Cached per length (was rebuilt every call)."""
    def build():
        h = np.zeros(n, np.float32)
        h[0] = 1.0
        if n % 2 == 0:
            h[n // 2] = 1.0
            h[1:n // 2] = 2.0
        else:
            h[1:(n + 1) // 2] = 2.0
        return h

    return _cached_host(("analytic_mult", int(n)), build)


def _hilbert_basis(n: int) -> np.ndarray:
    """``[2, n, n]`` real/imag circulant of the analytic-signal
    operator ``ifft(diag(mult) fft)``: row a, column b holds
    ``ifft(mult)[(b - a) mod n]``, so the whole transform is two dense
    [n, n] MXU matmuls — and, unlike the FFT route, moves no complex
    buffers (the axon relay cannot transfer complex either way)."""
    def build():
        m = np.fft.ifft(np.asarray(_analytic_multiplier(n), np.float64))
        idx = (np.arange(n)[None, :] - np.arange(n)[:, None]) % n
        circ = m[idx]
        return np.stack([circ.real, circ.imag]).astype(np.float32)

    return _cached_host(("hilbert_matmul", int(n)), build)


@functools.partial(obs.instrumented_jit, op="hilbert", route="xla_fft")
def _hilbert_xla(x, mult):
    return jnp.fft.ifft(jnp.fft.fft(x, axis=-1) * mult, axis=-1)


def _hilbert_matmul_body(x, basis, precision):
    re = prx.p_einsum("...n,nm->...m", x, basis[0],
                      precision=precision)
    im = prx.p_einsum("...n,nm->...m", x, basis[1],
                      precision=precision)
    return jax.lax.complex(re, im)


@functools.partial(obs.instrumented_jit, op="hilbert",
                   route="matmul_dft")
def _hilbert_matmul(x, basis):
    return _hilbert_matmul_body(x, basis, "highest")


@functools.partial(obs.instrumented_jit, op="hilbert",
                   route="matmul_dft_bf16_comp")
def _hilbert_matmul_comp(x, basis):
    return _hilbert_matmul_body(x, basis, "bf16_comp")


def _run_hilbert_matmul(x):
    n = np.shape(x)[-1]
    basis = _cached_device(
        ("hilbert_matmul", int(n)),
        lambda: jnp.asarray(_hilbert_basis(n)))
    return _hilbert_matmul(jnp.asarray(x, jnp.float32), basis)


def _run_hilbert_matmul_comp(x):
    n = np.shape(x)[-1]
    basis = _cached_device(
        ("hilbert_matmul", int(n)),
        lambda: jnp.asarray(_hilbert_basis(n)))
    return _hilbert_matmul_comp(jnp.asarray(x, jnp.float32), basis)


def _run_hilbert_xla(x):
    n = np.shape(x)[-1]
    mult = _cached_device(
        ("analytic_mult", int(n)),
        lambda: jnp.asarray(_analytic_multiplier(n)))
    return _hilbert_xla(jnp.asarray(x, jnp.float32), mult)


_HILBERT_ROUTES = {"matmul_dft": _run_hilbert_matmul,
                   "matmul_dft_bf16_comp": _run_hilbert_matmul_comp,
                   "xla_fft": _run_hilbert_xla}


def hilbert(x, simd=None, route=None):
    """Analytic signal ``x + i * H[x]`` (complex64 [..., n]).

    The imaginary part is the Hilbert transform; :func:`envelope` is its
    magnitude.  Frequency-domain construction (zero negative
    frequencies), the standard DFT definition.  Short signals
    (``n <= HILBERT_MATMUL_MAX_N``) route through the dense circulant
    operator on the MXU (``matmul_dft``); ``route`` forces either path.
    """
    n = np.shape(x)[-1]
    if n == 0:
        raise ValueError("empty signal")
    if resolve_simd(simd, op="hilbert"):
        forced = route is not None
        if forced and route not in _HILBERT_ROUTES:
            raise ValueError(
                f"route must be one of {sorted(_HILBERT_ROUTES)}, "
                f"got {route!r}")
        if forced:
            chosen = route
        else:
            rows = int(np.prod(np.shape(x)[:-1])) \
                if np.ndim(x) > 1 else 1
            # eligibility needs the exact n (the <= MATMUL_MAX_N
            # predicate); the tune CLASS pow2-buckets it so a
            # length-churning service shares finite entries instead
            # of probing — and rewriting the pack — per distinct n
            chosen = _HILBERT_FAMILY.select(
                runners=lambda: {
                    name: (lambda fn=fn: fn(x))
                    for name, fn in _HILBERT_ROUTES.items()},
                probe_operand=x, n=int(n),
                rows=routing.pow2_bucket(rows),
                tune_geom={"n": routing.pow2_bucket(int(n)),
                           "rows": routing.pow2_bucket(rows)})
        obs.record_decision("hilbert_route", chosen, n=int(n),
                            forced=forced)
        with obs.span("hilbert.dispatch", route=chosen):
            return _HILBERT_ROUTES[chosen](x)
    return hilbert_na(x).astype(np.complex64)


def hilbert_na(x):
    """NumPy float64 oracle twin of :func:`hilbert` (complex128)."""
    x = np.asarray(x, np.float64)
    return np.fft.ifft(np.fft.fft(x, axis=-1)
                       * _analytic_multiplier(x.shape[-1]), axis=-1)


def envelope(x, simd=None):
    """Instantaneous amplitude ``|analytic(x)|`` (f32 [..., n]) — the
    classic matched-filter post-processing step."""
    a = hilbert(x, simd=simd)
    if resolve_simd(simd, op="envelope"):
        return jnp.abs(a).astype(jnp.float32)
    return np.abs(a).astype(np.float32)


def envelope_na(x):
    return np.abs(hilbert_na(x))


def _morlet_hat(scales, n, w0):
    """Frequency response of the (analytic) Morlet wavelet at each scale:
    ``pi^-1/4 * exp(-(s*omega - w0)^2 / 2)`` for positive omega, with the
    L2 normalization ``sqrt(2 pi s / dt)`` (dt = 1).  Cached per
    (scales, n, w0) — was rebuilt every call."""
    scales = np.asarray(scales, np.float64)
    key = ("morlet_hat", scales.tobytes(), int(n), float(w0))

    def build():
        omega = 2 * np.pi * np.fft.fftfreq(n)  # [n]
        s = scales[:, None]  # [S, 1]
        hat = (np.pi ** -0.25) * np.exp(-0.5 * (s * omega - w0) ** 2)
        hat *= (omega > 0)  # analytic: positive frequencies only
        hat *= np.sqrt(2 * np.pi * s)
        return hat  # [S, n] float64

    return _cached_host(key, build)


def _cwt_basis(n: int):
    """Positive-frequency DFT basis pair for the short-signal matmul
    CWT: ``fwd`` [n, 2K] maps x to ``[Re X | Im X]`` at the K strictly
    positive frequencies (the only ones the analytic Morlet bank keeps
    — ``_morlet_hat`` zeroes omega <= 0), ``ic``/``is_`` [K, n] are the
    cos/sin inverse-DFT factors with the 1/n fold.  Cached per n."""
    def build():
        kpos = np.arange(1, (n + 1) // 2)
        m = np.arange(n)
        ang = 2.0 * np.pi * m[:, None] * kpos[None, :] / n
        fwd = np.concatenate([np.cos(ang), -np.sin(ang)],
                             axis=1).astype(np.float32)
        angi = 2.0 * np.pi * kpos[:, None] * m[None, :] / n
        ic = (np.cos(angi) / n).astype(np.float32)
        is_ = (np.sin(angi) / n).astype(np.float32)
        return fwd, ic, is_

    return _cached_host(("cwt_matmul", int(n)), build)


@functools.partial(obs.instrumented_jit, op="morlet_cwt",
                   route="xla_fft")
def _cwt_xla(x, hat):
    spec = jnp.fft.fft(x, axis=-1)
    return jnp.fft.ifft(spec[..., None, :] * hat, axis=-1)


def _cwt_matmul_body(x, fwd, hat, ic, is_, precision):
    e = functools.partial(prx.p_einsum, precision=precision)
    K = hat.shape[-1]
    xf = e("...n,nk->...k", x, fwd)
    a = xf[..., None, :K] * hat          # [..., S, K] Re X * hat
    b = xf[..., None, K:] * hat          # [..., S, K] Im X * hat
    out_re = (e("...sk,km->...sm", a, ic)
              - e("...sk,km->...sm", b, is_))
    out_im = (e("...sk,km->...sm", a, is_)
              + e("...sk,km->...sm", b, ic))
    return jax.lax.complex(out_re, out_im)


@functools.partial(obs.instrumented_jit, op="morlet_cwt",
                   route="matmul_dft")
def _cwt_matmul(x, fwd, hat, ic, is_):
    return _cwt_matmul_body(x, fwd, hat, ic, is_, "highest")


@functools.partial(obs.instrumented_jit, op="morlet_cwt",
                   route="matmul_dft_bf16_comp")
def _cwt_matmul_comp(x, fwd, hat, ic, is_):
    return _cwt_matmul_body(x, fwd, hat, ic, is_, "bf16_comp")


def _cwt_matmul_operands(x, hat):
    n = np.shape(x)[-1]
    fwd, ic, is_ = _cached_device(
        ("cwt_matmul", int(n)),
        lambda: tuple(jnp.asarray(a) for a in _cwt_basis(n)))
    K = ic.shape[0]
    hatp = np.ascontiguousarray(hat[:, 1:1 + K]).astype(np.float32)
    return (jnp.asarray(x, jnp.float32), fwd, jnp.asarray(hatp), ic,
            is_)


def _run_cwt_matmul(x, hat):
    return _cwt_matmul(*_cwt_matmul_operands(x, hat))


def _run_cwt_matmul_comp(x, hat):
    return _cwt_matmul_comp(*_cwt_matmul_operands(x, hat))


def _run_cwt_xla(x, hat):
    return _cwt_xla(jnp.asarray(x, jnp.float32),
                    to_device(hat, jnp.complex64))


@functools.partial(obs.instrumented_jit, op="morlet_cwt",
                   route="ct_matmul",
                   static_argnames=("n1", "n2"))
def _cwt_ct(x, hat, c2, s2, c1, s1, twc, tws, n1, n2):
    parts = (c2, s2, c1, s1, twc, tws)
    fre, fim = ct_apply(x, n1, n2, parts)
    spec = jax.lax.complex(fre, fim)
    prod = spec[..., None, :] * hat          # hat real [S, n]
    re, im = ct_apply(prod, n1, n2, parts, inverse=True)
    return jax.lax.complex(re, im)


def _run_cwt_ct(x, hat):
    n = np.shape(x)[-1]
    n1, n2 = ct_factor(n)
    parts = ct_basis_device(n1, n2)
    return _cwt_ct(jnp.asarray(x, jnp.float32),
                   jnp.asarray(np.asarray(hat, np.float32)),
                   *parts, n1, n2)


_CWT_ROUTES = {"matmul_dft": _run_cwt_matmul,
               "matmul_dft_bf16_comp": _run_cwt_matmul_comp,
               "ct_matmul": _run_cwt_ct,
               "xla_fft": _run_cwt_xla}


def morlet_cwt(x, scales, w0: float = 6.0, simd=None, route=None):
    """Continuous wavelet transform with the analytic Morlet wavelet.

    ``x[..., n] -> complex64 [..., scales, n]``.  ``scales`` are in
    samples (pseudo-frequency ≈ ``w0 / (2 pi s)`` cycles/sample).  The
    whole scale bank is one batched ``fft -> multiply -> ifft``; the
    ``[S, n]`` wavelet bank is a host-side constant.  Short signals
    (``n <= CWT_MATMUL_MAX_N``) route through the positive-frequency
    DFT basis pair as dense MXU matmuls (``matmul_dft``); longer
    factorizable ``n`` ride the Cooley-Tukey factorized matmul DFT
    (``ct_matmul``, two per-factor MXU stages + twiddle — the
    pod-scale formulation's single-chip form).  Neither moves complex
    buffers through the relay; ``route`` forces any path.
    """
    scales = np.atleast_1d(np.asarray(scales, np.float64))
    if scales.ndim != 1 or len(scales) == 0 or np.any(scales <= 0):
        raise ValueError(f"scales must be a non-empty 1D positive array, "
                         f"got {scales!r}")
    n = np.shape(x)[-1]
    hat = _morlet_hat(scales, n, w0)
    if resolve_simd(simd, op="morlet_cwt"):
        forced = route is not None
        if forced and route not in _CWT_ROUTES:
            raise ValueError(
                f"route must be one of {sorted(_CWT_ROUTES)}, "
                f"got {route!r}")
        if forced and route == "ct_matmul" and ct_factor(n) is None:
            raise ValueError(
                f"n={n} has no Cooley-Tukey split with both factors "
                f"<= {AUTO_DFT_MATMUL_MAX_FRAME}")
        if forced:
            chosen = route
        else:
            # the scale count keys the tune class too (pow2-bucketed:
            # scale-churning callers share finite classes): matmul_dft
            # is dominated by the dense [scales, bins] hat matmul
            # while xla_fft batches over the scales axis, so the
            # crossover moves with len(scales)
            # exact n for the eligibility predicate; pow2-bucketed
            # into the tune class (like hilbert) so length churn
            # shares finite cache entries
            chosen = _CWT_FAMILY.select(
                runners=lambda: {
                    name: (lambda fn=fn: fn(x, hat))
                    for name, fn in _CWT_ROUTES.items()},
                probe_operand=x, n=int(n),
                scales=routing.pow2_bucket(len(scales)),
                tune_geom={
                    "n": routing.pow2_bucket(int(n)),
                    "scales": routing.pow2_bucket(len(scales))})
        obs.record_decision("morlet_cwt_route", chosen, n=int(n),
                            scales=len(scales), forced=forced)
        with obs.span("morlet_cwt.dispatch", route=chosen):
            return _CWT_ROUTES[chosen](x, hat)
    return morlet_cwt_na(x, scales, w0).astype(np.complex64)


def morlet_cwt_na(x, scales, w0: float = 6.0):
    """NumPy float64 oracle twin of :func:`morlet_cwt` (complex128)."""
    x = np.asarray(x, np.float64)
    scales = np.atleast_1d(np.asarray(scales, np.float64))
    hat = _morlet_hat(scales, x.shape[-1], w0)
    spec = np.fft.fft(x, axis=-1)
    return np.fft.ifft(spec[..., None, :] * hat, axis=-1)


# ---------------------------------------------------------------------------
# spectral estimation (periodogram / Welch / CSD / coherence)
# ---------------------------------------------------------------------------


def detrend(x, type: str = "linear", simd=None,  # noqa: A002
            axis: int = -1):
    """Remove a constant or least-squares linear trend along ``axis``
    (scipy's ``detrend``; default last axis).  The linear projection is
    a host-side closed form (2-column Vandermonde pseudo-inverse),
    applied as one matmul on device."""
    if type not in ("linear", "constant"):
        raise ValueError(f"type must be 'linear' or 'constant', "
                         f"got {type!r}")
    if axis not in (-1, np.ndim(x) - 1):
        xp = jnp if resolve_simd(simd, op="detrend") else np
        moved = xp.moveaxis(xp.asarray(x), axis, -1)
        return xp.moveaxis(detrend(moved, type, simd=simd), -1, axis)
    n = np.shape(x)[-1]
    if resolve_simd(simd, op="detrend"):
        xj = jnp.asarray(x, jnp.float32)
        if type == "constant":
            return xj - jnp.mean(xj, axis=-1, keepdims=True)
        # rank-2 LSQ fit: O(n) via the [2, n] pseudo-inverse, never the
        # [n, n] projector (a 1M-point signal would need 4 TB for it)
        a = np.c_[np.arange(n, dtype=np.float64), np.ones(n)]
        pinva = jnp.asarray(np.linalg.pinv(a), jnp.float32)   # [2, n]
        aj = jnp.asarray(a, jnp.float32)                       # [n, 2]
        coef = jnp.einsum("cn,...n->...c", pinva, xj,
                          precision=prx.HIGHEST)
        return xj - jnp.einsum("nc,...c->...n", aj, coef,
                               precision=prx.HIGHEST)
    return detrend_na(x, type).astype(np.float32)


def detrend_na(x, type: str = "linear"):  # noqa: A002
    """NumPy float64 oracle twin of :func:`detrend`."""
    x = np.asarray(x, np.float64)
    if type == "constant":
        return x - x.mean(axis=-1, keepdims=True)
    if type != "linear":
        raise ValueError(f"type must be 'linear' or 'constant', "
                         f"got {type!r}")
    n = x.shape[-1]
    a = np.c_[np.arange(n, dtype=np.float64), np.ones(n)]
    coef = np.einsum("ck,...k->...c", np.linalg.pinv(a), x)
    return x - np.einsum("nc,...c->...n", a, coef)


def _welch_args(n, nperseg, noverlap, window):
    nperseg = int(min(nperseg, n))
    if noverlap is None:
        noverlap = nperseg // 2
    noverlap = int(noverlap)
    if not 0 <= noverlap < nperseg:
        raise ValueError(f"noverlap {noverlap} must be in [0, nperseg "
                         f"= {nperseg})")
    window = _resolve_window(window, nperseg, np.float64)
    return nperseg, nperseg - noverlap, window


def _onesided_scale(nperseg, fs, window, scaling) -> np.ndarray:
    """Per-bin factor for a one-sided PSD of real input: the
    density/spectrum normalization times the doubling of every bin
    except DC (and Nyquist when ``nperseg`` is even).  The single
    definition the single-chip estimators and ``sharded_welch`` share."""
    if scaling == "density":
        scale = 1.0 / (fs * np.sum(window ** 2))
    elif scaling == "spectrum":
        scale = 1.0 / np.sum(window) ** 2
    else:
        raise ValueError(f"scaling must be 'density' or 'spectrum', "
                         f"got {scaling!r}")
    mult = np.full(nperseg // 2 + 1, 2.0)
    mult[0] = 1.0
    if nperseg % 2 == 0:
        mult[-1] = 1.0
    return mult * scale


def _segment_ffts(x, y, fs, nperseg, noverlap, window, detrend_type,
                  scaling, simd):
    """Segment + detrend + window + rfft both inputs ONCE; returns
    ``(freqs, fx, fy, scale_mult)`` with ``fy is fx`` when ``y is x``
    and ``scale_mult`` the combined density/one-sided factor per bin."""
    n = np.shape(x)[-1]
    if np.shape(y)[-1] != n:
        raise ValueError("x and y lengths differ")
    nperseg, hop, window = _welch_args(n, nperseg, noverlap, window)
    freqs = np.fft.rfftfreq(nperseg, 1.0 / fs)
    scale_mult = _onesided_scale(nperseg, fs, window, scaling)

    def segments(v, xp):
        if xp is jnp:
            segs = _take_frames(v, nperseg, hop)   # reshape fast path
        else:
            segs = v[..., _frame_indices(n, nperseg, hop)]
        if detrend_type is not None:
            segs = (detrend(segs, detrend_type, simd=True) if xp is jnp
                    else detrend_na(segs, detrend_type))
        return segs * (xp.asarray(window, jnp.float32) if xp is jnp
                       else window)

    if simd:
        fx = jnp.fft.rfft(segments(jnp.asarray(x, jnp.float32), jnp),
                          axis=-1)
        fy = fx if y is x else jnp.fft.rfft(
            segments(jnp.asarray(y, jnp.float32), jnp), axis=-1)
        return freqs, fx, fy, jnp.asarray(scale_mult, jnp.float32)
    fx = np.fft.rfft(segments(np.asarray(x, np.float64), np), axis=-1)
    fy = fx if y is x else np.fft.rfft(
        segments(np.asarray(y, np.float64), np), axis=-1)
    return freqs, fx, fy, scale_mult


def _spectral_helper(x, y, fs, nperseg, noverlap, window, detrend_type,
                     scaling, simd):
    """Shared segment-average machinery for welch/csd (scipy's
    ``_spectral_helper`` shape, rebuilt on the framing gather)."""
    freqs, fx, fy, scale_mult = _segment_ffts(
        x, y, fs, nperseg, noverlap, window, detrend_type, scaling, simd)
    xp = jnp if simd else np
    if fy is fx:  # auto-spectrum: |fx|^2, skip the complex multiply
        return freqs, xp.mean(xp.abs(fx) ** 2, axis=-2) * scale_mult
    return freqs, xp.mean(xp.conj(fx) * fy, axis=-2) * scale_mult


def welch(x, fs: float = 1.0, nperseg: int = 256, noverlap=None,
          window=None, detrend_type: str = "constant",
          scaling: str = "density", simd=None):
    """Welch power-spectral-density estimate (scipy's ``welch``).

    Segment (Hann window, 50% overlap by default), detrend each
    segment, average one-sided periodograms.  Returns ``(freqs, Pxx)``
    with ``Pxx`` real f32 ``[..., min(nperseg, n) // 2 + 1]``
    (``nperseg`` is clamped to the signal length, scipy-style);
    ``freqs`` is a host-side float64 array.  The segment pipeline is
    the same framing gather + batched rfft as :func:`stft`.
    """
    use = resolve_simd(simd, op="welch")
    f, p = _spectral_helper(x, x, float(fs), nperseg, noverlap, window,
                            detrend_type, scaling, use)
    if use:
        return f, jnp.real(p).astype(jnp.float32)
    return f, np.real(p).astype(np.float32)


def welch_na(x, fs: float = 1.0, nperseg: int = 256, noverlap=None,
             window=None, detrend_type: str = "constant",
             scaling: str = "density"):
    """NumPy float64 oracle twin of :func:`welch`."""
    f, p = _spectral_helper(x, x, float(fs), nperseg, noverlap, window,
                            detrend_type, scaling, False)
    return f, np.real(p)


def periodogram(x, fs: float = 1.0, window=None, scaling: str = "density",
                detrend_type: str = "constant", simd=None):
    """Single-segment PSD (scipy's ``periodogram``: boxcar window,
    constant detrend by default).  Pass ``detrend_type=None`` to keep
    the raw DC bin."""
    n = np.shape(x)[-1]
    window = (np.ones(n, np.float64) if window is None
              else _resolve_window(window, n, np.float64))
    use = resolve_simd(simd, op="periodogram")
    f, p = _spectral_helper(x, x, float(fs), n, 0, window, detrend_type,
                            scaling, use)
    if use:
        return f, jnp.real(p).astype(jnp.float32)
    return f, np.real(p).astype(np.float32)


def periodogram_na(x, fs: float = 1.0, window=None,
                   scaling: str = "density",
                   detrend_type: str = "constant"):
    n = np.shape(x)[-1]
    window = (np.ones(n, np.float64) if window is None
              else _resolve_window(window, n, np.float64))
    f, p = _spectral_helper(x, x, float(fs), n, 0, window, detrend_type,
                            scaling, False)
    return f, np.real(p)


def csd(x, y, fs: float = 1.0, nperseg: int = 256, noverlap=None,
        window=None, detrend_type: str = "constant",
        scaling: str = "density", simd=None):
    """Cross-spectral density ``Pxy`` (scipy's ``csd``): complex64
    ``[..., bins]``."""
    use = resolve_simd(simd, op="csd")
    f, p = _spectral_helper(x, y, float(fs), nperseg, noverlap, window,
                            detrend_type, scaling, use)
    if use:
        return f, p.astype(jnp.complex64)
    return f, p.astype(np.complex64)


def csd_na(x, y, fs: float = 1.0, nperseg: int = 256, noverlap=None,
           window=None, detrend_type: str = "constant",
           scaling: str = "density"):
    f, p = _spectral_helper(x, y, float(fs), nperseg, noverlap, window,
                            detrend_type, scaling, False)
    return f, p


def _coherence_impl(x, y, fs, nperseg, noverlap, window, simd):
    """Pxx/Pyy/Pxy from ONE segmentation+rfft of each input (the naive
    csd+welch+welch composition would run every FFT pipeline twice);
    the scale factors cancel in the ratio but are kept for clarity."""
    freqs, fx, fy, scale_mult = _segment_ffts(
        x, y, float(fs), nperseg, noverlap, window, "constant",
        "density", simd)
    xp = jnp if simd else np
    pxx = xp.mean(xp.abs(fx) ** 2, axis=-2) * scale_mult
    pyy = xp.mean(xp.abs(fy) ** 2, axis=-2) * scale_mult
    pxy = xp.mean(xp.conj(fx) * fy, axis=-2) * scale_mult
    return freqs, xp.abs(pxy) ** 2 / (pxx * pyy)


def coherence(x, y, fs: float = 1.0, nperseg: int = 256, noverlap=None,
              window=None, simd=None):
    """Magnitude-squared coherence ``|Pxy|^2 / (Pxx Pyy)`` in [0, 1]
    (scipy's ``coherence``)."""
    use = resolve_simd(simd, op="coherence")
    f, coh = _coherence_impl(x, y, fs, nperseg, noverlap, window, use)
    if use:
        return f, coh.astype(jnp.float32)
    return f, coh.astype(np.float32)


def coherence_na(x, y, fs: float = 1.0, nperseg: int = 256,
                 noverlap=None, window=None):
    return _coherence_impl(x, y, fs, nperseg, noverlap, window, False)


# ---------------------------------------------------------------------------
# chirp-Z transform / zoom FFT (Bluestein)
# ---------------------------------------------------------------------------


def _czt_constants(n, m, w, a):
    """Host-side Bluestein chirp constants (complex128 -> complex64).

    ``X[k] = w^(k^2/2) * sum_n (x[n] a^-n w^(n^2/2)) w^(-(k-n)^2/2)`` —
    the quadratic-phase decomposition ``nk = (n^2 + k^2 - (k-n)^2)/2``
    turns the non-uniform DFT into ONE linear convolution of length
    ``n + m - 1``, which runs as a padded FFT multiply on device.
    """
    w, a = complex(w), complex(a)
    nmax = max(n, m)
    k2 = np.arange(nmax, dtype=np.float64) ** 2 / 2.0
    # w^(j^2/2) for j in [-(n-1), m-1] (the convolution kernel support)
    j = np.arange(-(n - 1), m, dtype=np.float64)
    kern = w ** (-(j * j) / 2.0)
    pre = (a ** -np.arange(n, dtype=np.float64)) * w ** k2[:n]
    post = w ** k2[:m]
    nfft = 1 << int(np.ceil(np.log2(n + m - 1)))
    kern_f = np.fft.fft(kern, nfft)
    return (pre.astype(np.complex64), kern_f.astype(np.complex64),
            post.astype(np.complex64), nfft)


@functools.partial(obs.instrumented_jit, static_argnames=("m", "nfft"))
def _czt_xla(x, pre, kern_f, post, m, nfft):
    n = x.shape[-1]
    y = x.astype(jnp.complex64) * pre
    yf = jnp.fft.fft(y, nfft, axis=-1)
    conv = jnp.fft.ifft(yf * kern_f, axis=-1)
    return conv[..., n - 1: n - 1 + m] * post


def czt(x, m=None, w=None, a=1.0, simd=None):
    """Chirp-Z transform (scipy's ``czt``): ``m`` samples of the
    z-transform along the spiral ``z = a * w^-k``.

    Defaults (``m = n``, ``w = exp(-2j pi / m)``, ``a = 1``) reproduce
    the DFT on arbitrary lengths.  Runs as Bluestein's algorithm — one
    linear convolution against a quadratic-phase chirp, with all chirp
    constants host-side.  Returns complex64 ``[..., m]``.
    """
    n = np.shape(x)[-1]
    if n < 1:
        raise ValueError("empty signal")
    m = int(m) if m is not None else n
    if m < 1:
        raise ValueError("m must be >= 1")
    if w is None:
        w = np.exp(-2j * np.pi / m)
    pre, kern_f, post, nfft = _czt_constants(n, m, w, a)
    if resolve_simd(simd, op="czt"):
        return _czt_xla(to_device(x), to_device(pre),
                        to_device(kern_f), to_device(post), m, nfft)
    # host fallback: the SAME Bluestein convolution in float64 numpy —
    # NOT the O(n*m) direct-sum oracle, which would materialize an
    # [m, n] matrix (33 GB for zoom_fft of a 1M-sample signal)
    xc = np.asarray(x, np.complex128)
    wc, ac = complex(w), complex(a)
    nmax = np.arange(n, dtype=np.float64)
    pre64 = ac ** -nmax * wc ** (nmax * nmax / 2.0)
    j = np.arange(-(n - 1), m, dtype=np.float64)
    kern64 = np.fft.fft(wc ** (-(j * j) / 2.0), nfft)
    k = np.arange(m, dtype=np.float64)
    post64 = wc ** (k * k / 2.0)
    conv = np.fft.ifft(np.fft.fft(xc * pre64, nfft, axis=-1) * kern64,
                       axis=-1)
    return (conv[..., n - 1: n - 1 + m] * post64).astype(np.complex64)


def czt_na(x, m=None, w=None, a=1.0):
    """NumPy complex128 oracle twin of :func:`czt` — the DIRECT O(n m)
    z-transform sum, deliberately a different algorithm than Bluestein
    so the cross-validation is meaningful.  O(n*m) memory: intended for
    test-sized inputs, not the public fallback path."""
    x = np.asarray(x, np.complex128)
    n = x.shape[-1]
    if n < 1:
        raise ValueError("empty signal")
    m = int(m) if m is not None else n
    if m < 1:
        raise ValueError("m must be >= 1")
    if w is None:
        w = np.exp(-2j * np.pi / m)
    w, a = complex(w), complex(a)
    k = np.arange(m)
    z = a * w ** -k                                   # [m] spiral points
    pows = z[..., :, None] ** -np.arange(n)[None, :]  # [m, n]
    return np.einsum("kn,...n->...k", pows, x)


def zoom_fft(x, fn, m=None, fs: float = 2.0, simd=None):
    """Zoomed DFT over a band (scipy's ``zoom_fft``): ``m`` uniformly
    spaced frequency samples spanning ``fn = [f1, f2]`` (or ``[0, fn]``)
    at sample rate ``fs`` — fine frequency resolution over a narrow band
    without computing (or padding to) a huge full-length FFT.

    Returns ``(freqs, X)``; ``freqs`` is host-side float64.
    """
    n = np.shape(x)[-1]
    f = np.ravel(np.asarray(fn, np.float64))
    if f.size == 1:
        f1, f2 = 0.0, float(f[0])
    elif f.size == 2:
        f1, f2 = float(f[0]), float(f[1])
    else:
        raise ValueError("fn must be a scalar or a (f1, f2) pair")
    if not 0.0 <= f1 < f2 <= fs / 2:
        raise ValueError(f"band [{f1}, {f2}] must satisfy "
                         f"0 <= f1 < f2 <= fs/2 = {fs / 2}")
    m = int(m) if m is not None else n
    # scipy's default endpoint=False convention: step (f2-f1)/m, f2
    # itself excluded (like np.fft.fftfreq's grid)
    step = (f2 - f1) / m
    freqs = f1 + np.arange(m) * step
    w = np.exp(-2j * np.pi * step / fs)
    a = np.exp(2j * np.pi * f1 / fs)
    return freqs, czt(x, m, w, a, simd=simd)


# ---------------------------------------------------------------------------
# Lomb-Scargle (unevenly-sampled periodogram)
# ---------------------------------------------------------------------------


def _check_lombscargle_args(t, x, freqs, weights=None):
    """Shared validation for the single-chip and sharded Lomb-Scargle
    paths: float64 views of (t, x, freqs, weights) or ValueError.
    ``weights`` defaults to all-ones; zero weights exclude samples
    exactly (the padding channel the sharded path uses)."""
    t = np.asarray(t, np.float64)
    x = np.asarray(x, np.float64)
    freqs = np.asarray(freqs, np.float64)
    if t.ndim != 1 or x.ndim != 1 or len(t) != len(x):
        raise ValueError("t and x must be 1D of equal length")
    if freqs.ndim != 1 or len(freqs) == 0:
        raise ValueError("freqs must be a non-empty 1D array")
    if np.any(freqs <= 0):
        raise ValueError("freqs must be positive (angular) frequencies")
    if weights is None:
        weights = np.ones_like(t)
    else:
        weights = np.asarray(weights, np.float64)
        if weights.shape != t.shape:
            raise ValueError(
                f"weights shape {weights.shape} != t shape {t.shape}")
        if np.any(weights < 0):
            raise ValueError("weights must be non-negative")
        if not np.any(weights > 0):
            raise ValueError("at least one weight must be positive")
    return t, x, freqs, weights


@obs.instrumented_jit
def _lombscargle_xla(t, x, freqs, w):
    # [m, n] phase grids: the whole periodogram is a handful of
    # elementwise trig ops + reductions over the sample axis — dense
    # MXU/VPU work with no FFT and no uniform-sampling requirement.
    # Every sum carries the weights channel; w==1 reproduces the
    # textbook formula, w==0 removes a sample exactly.
    wt = freqs[:, None] * t[None, :]
    # Scargle's tau makes the estimate phase-invariant
    tau = jnp.arctan2(jnp.sum(w * jnp.sin(2 * wt), axis=-1),
                      jnp.sum(w * jnp.cos(2 * wt), axis=-1)) / 2.0
    arg = wt - tau[:, None]
    c, s = jnp.cos(arg), jnp.sin(arg)
    xc = jnp.sum((w * x)[None, :] * c, axis=-1)
    xs = jnp.sum((w * x)[None, :] * s, axis=-1)
    cc = jnp.sum(w * c * c, axis=-1)
    ss = jnp.sum(w * s * s, axis=-1)
    return 0.5 * (xc * xc / cc + xs * xs / ss)


def lombscargle(t, x, freqs, simd=None, weights=None):
    """Lomb-Scargle periodogram for UNEVENLY sampled data (scipy's
    ``lombscargle`` with its default normalization): power of the
    least-squares sinusoid fit at each angular frequency in ``freqs``.

    No FFT and no resampling: the [m, n] trig evaluation is exactly the
    dense-compute shape the TPU wants.  ``t``/``freqs`` in reciprocal
    units (``freqs`` are ANGULAR frequencies, scipy convention).

    ``weights`` (optional, non-negative, same shape as ``t``) scales
    every sample's contribution to all five Scargle sums; a zero weight
    excludes the sample exactly.  Beyond the reference/scipy surface —
    it exists so padded samples can be neutralized (the sharded path
    uses it for arbitrary lengths) and for per-sample confidence.
    """
    t, x_np, freqs, w_np = _check_lombscargle_args(t, x, freqs, weights)
    if resolve_simd(simd, op="lombscargle"):
        # center the time base in float64 BEFORE the f32 cast: Scargle's
        # tau makes the estimate exactly time-shift invariant, and raw
        # offset timestamps (e.g. Julian dates ~2.45e6) would otherwise
        # push the phase grid to values where f32 spacing exceeds a
        # radian.  Weighted mean so zero-weight padding can't shift it.
        t = t - (w_np @ t) / w_np.sum()
        return _lombscargle_xla(jnp.asarray(t, jnp.float32),
                                jnp.asarray(x_np, jnp.float32),
                                jnp.asarray(freqs, jnp.float32),
                                jnp.asarray(w_np, jnp.float32))
    return lombscargle_na(t, x_np, freqs, w_np).astype(np.float32)


def lombscargle_na(t, x, freqs, weights=None):
    """NumPy float64 oracle twin (per-frequency loop, the textbook
    Scargle formula, optional weights channel)."""
    t = np.asarray(t, np.float64)
    x = np.asarray(x, np.float64)
    wts = (np.ones_like(t) if weights is None
           else np.asarray(weights, np.float64))
    out = np.empty(len(freqs))
    for i, w in enumerate(np.asarray(freqs, np.float64)):
        tau = np.arctan2(np.sum(wts * np.sin(2 * w * t)),
                         np.sum(wts * np.cos(2 * w * t))) / (2.0)
        arg = w * t - tau
        c, s = np.cos(arg), np.sin(arg)
        out[i] = 0.5 * (((wts * x) @ c) ** 2 / ((wts * c) @ c)
                        + ((wts * x) @ s) ** 2 / ((wts * s) @ s))
    return out
