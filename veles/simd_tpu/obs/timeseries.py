"""The fleet axis (obs v5): bounded time series + typed fleet signals.

Every other obs axis answers "now" — the registry is a point-in-time
scrape, the router aggregation endpoint a point-in-time JSON blob.
This module adds *time*: a bounded per-``(replica, series)`` ring of
periodic metric samples (:class:`FleetSeries`) with windowed
derivatives (:func:`rate`, :func:`delta`, :func:`ewma`) and flap
counting (:func:`flaps`), fed by the collector thread
:class:`veles.simd_tpu.serve.cluster.ReplicaGroup` runs while started
(cadence ``$VELES_SIMD_FLEET_TICK_MS``, ring bound
``$VELES_SIMD_FLEET_WINDOW``).  In-process replicas are sampled
directly (depth / health / completed counts / open breakers);
subprocess replicas are scraped over their existing ``/metrics``
endpoints — a failed scrape is *counted staleness*, never a crash.

On top of the store sit two fleet-level products:

* :class:`FleetSignals` — the typed, windowed signal bundle
  (``obs.signals()``): per-tenant SLO burn **and its velocity**, queue
  depths, breaker open/flap counts, goodput per shape class, and
  per-replica health/staleness.  This is the documented input
  contract for the elastic-autoscaling controller (ROADMAP item 2) —
  served as ``/signals`` on the router aggregation endpoint and
  rendered by ``tools/obs_dash.py --fleet``;
* :func:`stitch_fleet_trace` — cross-replica trace stitching: a
  failed-over :class:`~veles.simd_tpu.serve.cluster.RouterTicket`
  carries the dead replicas' terminal traces in ``prior_traces``;
  stitching merges them with the surviving replica's trace into ONE
  Perfetto-loadable fleet trace (one track per attempt, failover hops
  marked, the carried deadline stamped per attempt) — written by
  ``obs.save_trace(path, fleet=ticket)``.

Like :mod:`veles.simd_tpu.obs.registry`, everything here is plain
Python under one lock — no jax, no numpy — so the fleet axis stays
importable (and cheap) in accelerator-free processes.
"""

from __future__ import annotations

import collections
import os
import threading

__all__ = [
    "FleetSeries", "FleetSignals", "stitch_fleet_trace",
    "rate", "delta", "ewma", "flaps",
    "FLEET_TICK_MS_ENV", "FLEET_WINDOW_ENV",
    "DEFAULT_TICK_MS", "DEFAULT_WINDOW", "SIGNALS_SCHEMA",
    "env_tick_s", "env_window",
]

# the /signals contract version: bumped whenever FleetSignals gains,
# loses, or re-types a field, so dashboards can detect drift instead
# of mis-parsing (obs v6 added incidents + journal; obs v7 added
# replica_count + birth_age_s + scaler)
SIGNALS_SCHEMA = "veles-simd-signals-v4"

FLEET_TICK_MS_ENV = "VELES_SIMD_FLEET_TICK_MS"
FLEET_WINDOW_ENV = "VELES_SIMD_FLEET_WINDOW"

# 100 ms ticks match the heartbeat default: the collector rides the
# same "notice a dead replica in ~hundreds of ms" budget while costing
# a handful of lock-cheap reads per replica per tick
DEFAULT_TICK_MS = 100.0
# 120 samples x 100 ms = a 12 s sliding window — long enough for burn
# velocity and flap counting, small enough that N replicas x a dozen
# series stays a few thousand floats
DEFAULT_WINDOW = 120

# a replica whose newest sample is older than this many ticks reads
# as "stale" in the signals (the collector kept sweeping but this
# replica stopped yielding samples)
STALE_TICKS = 3.0


def env_tick_s() -> float:
    """Collector cadence in seconds from ``$VELES_SIMD_FLEET_TICK_MS``
    (default 100 ms; non-positive / malformed falls back)."""
    raw = os.environ.get(FLEET_TICK_MS_ENV, "").strip()
    if not raw:
        return DEFAULT_TICK_MS / 1e3
    try:
        value = float(raw)
    except ValueError:
        return DEFAULT_TICK_MS / 1e3
    return (value if value > 0 else DEFAULT_TICK_MS) / 1e3


def env_window() -> int:
    """Ring bound (samples per series) from
    ``$VELES_SIMD_FLEET_WINDOW`` (default 120)."""
    raw = os.environ.get(FLEET_WINDOW_ENV, "").strip()
    if not raw:
        return DEFAULT_WINDOW
    try:
        value = int(raw)
    except ValueError:
        return DEFAULT_WINDOW
    return value if value >= 2 else DEFAULT_WINDOW


# -- windowed derivatives (pure functions over [(t_s, value), ...]) ----------

def delta(samples) -> float | None:
    """Last value minus first value over the window (None if fewer
    than two samples)."""
    if len(samples) < 2:
        return None
    return samples[-1][1] - samples[0][1]


def rate(samples) -> float | None:
    """Windowed derivative in value-units per second: ``delta /
    elapsed`` across the window (None without two time-separated
    samples).  For cumulative counters this is the classic
    Prometheus-style ``rate()``."""
    if len(samples) < 2:
        return None
    dt = samples[-1][0] - samples[0][0]
    if dt <= 0:
        return None
    return (samples[-1][1] - samples[0][1]) / dt


def ewma(samples, alpha: float = 0.3) -> float | None:
    """Exponentially-weighted moving average of the windowed values
    (None on an empty window).  ``alpha`` is the new-sample weight."""
    if not samples:
        return None
    acc = samples[0][1]
    for _, v in samples[1:]:
        acc = alpha * v + (1.0 - alpha) * acc
    return acc


def flaps(samples, eps: float = 1e-9) -> int:
    """How many times the series CHANGED value across the window —
    the flap count for state-like series (breaker open counts,
    up/down health bits).  A series that went 0→1→0 flapped twice."""
    n = 0
    for i in range(1, len(samples)):
        if abs(samples[i][1] - samples[i - 1][1]) > eps:
            n += 1
    return n


class FleetSeries:
    """Bounded per-``(replica, series)`` sample rings behind one lock.

    The collector calls :meth:`record` with a shared monotonic
    timestamp per sweep and :meth:`tick` once per sweep; readers get
    JSON-native copies (:meth:`samples`, :meth:`snapshot`) or windowed
    derivatives (:meth:`rate` / :meth:`delta` / :meth:`ewma` /
    :meth:`flaps`).  ``tick_s`` is stamped by whoever drives the
    sweeps so staleness can be expressed in collector ticks."""

    def __init__(self, window: int | None = None):
        self.window = int(window) if window else env_window()
        if self.window < 2:
            raise ValueError("fleet window must be >= 2 samples")
        self.tick_s: float | None = None
        self.ticks = 0
        self._lock = threading.Lock()
        self._rings: dict = {}      # (replica, series) -> deque[(t, v)]

    # -- writes ------------------------------------------------------------

    def record(self, replica: str, series: str, value: float,
               t_s: float) -> None:
        key = (str(replica), str(series))
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = collections.deque(
                    maxlen=self.window)
            ring.append((float(t_s), float(value)))

    def tick(self) -> None:
        """Count one completed collector sweep."""
        with self._lock:
            self.ticks += 1

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self.ticks = 0

    def forget(self, replica: str) -> int:
        """Drop every ring belonging to ``replica`` (returns how many
        were dropped).  The collector calls this for replicas that
        left group membership (``ReplicaGroup.retire``) — without it,
        a retired replica's aging samples would read as a "stale"
        replica in the signals forever."""
        replica = str(replica)
        with self._lock:
            doomed = [k for k in self._rings if k[0] == replica]
            for k in doomed:
                del self._rings[k]
            return len(doomed)

    # -- reads -------------------------------------------------------------

    def samples(self, replica: str, series: str) -> list:
        """Oldest-first ``[(t_s, value), ...]`` copy of one ring."""
        with self._lock:
            ring = self._rings.get((str(replica), str(series)))
            return list(ring) if ring else []

    def value(self, replica: str, series: str) -> float | None:
        """The newest value of one series (None if never recorded)."""
        s = self.samples(replica, series)
        return s[-1][1] if s else None

    def rate(self, replica: str, series: str) -> float | None:
        return rate(self.samples(replica, series))

    def delta(self, replica: str, series: str) -> float | None:
        return delta(self.samples(replica, series))

    def ewma(self, replica: str, series: str,
             alpha: float = 0.3) -> float | None:
        return ewma(self.samples(replica, series), alpha)

    def flaps(self, replica: str, series: str) -> int:
        return flaps(self.samples(replica, series))

    def replicas(self) -> list:
        """Replica names with at least one recorded series (sorted)."""
        with self._lock:
            return sorted({r for r, _ in self._rings})

    def names(self, replica: str) -> list:
        """Series recorded for ``replica`` (sorted)."""
        replica = str(replica)
        with self._lock:
            return sorted(s for r, s in self._rings if r == replica)

    def staleness_s(self, replica: str, now: float) -> float | None:
        """Seconds since ``replica``'s newest sample across ALL its
        series (None if it never produced one) — the "this replica
        stopped yielding" signal."""
        replica = str(replica)
        newest = None
        with self._lock:
            for (r, _), ring in self._rings.items():
                if r == replica and ring:
                    t = ring[-1][0]
                    if newest is None or t > newest:
                        newest = t
        return None if newest is None else max(0.0, now - newest)

    def snapshot(self) -> dict:
        """JSON-native copy: ``{"window", "ticks", "tick_s",
        "series": {replica: {series: [[t_s, value], ...]}}}``."""
        with self._lock:
            series: dict = {}
            for (r, s), ring in sorted(self._rings.items()):
                series.setdefault(r, {})[s] = [list(tv) for tv in ring]
            return {"window": self.window, "ticks": self.ticks,
                    "tick_s": self.tick_s, "series": series}


class FleetSignals:
    """The typed fleet-signal bundle — ``obs.signals()``'s return
    value, and the documented input contract for the autoscaling
    controller (GUIDE, "The fleet axis").  One instance is one
    consistent read of the fleet store + registry + SLO accounts:

    ===================== ==================================================
    field                 meaning
    ===================== ==================================================
    ``at_s``              monotonic stamp of this read
    ``ticks``             completed collector sweeps so far
    ``tick_s``            collector cadence (None = collector never armed)
    ``window``            ring bound (samples per series)
    ``slo_burn``          {tenant: current burn rate}
    ``slo_burn_velocity`` {tenant: d(burn)/dt over the window, 1/s}
    ``queue_depth``       {replica: newest admitted depth}
    ``queue_depth_total`` summed fleet queue depth
    ``occupancy``         {replica: rows queued in forming batches}
    ``breaker_open``      {replica: newest open-breaker count}
    ``breaker_flaps``     {replica: open-count changes over the window}
    ``goodput``           {"op|class": useful/dispatched rows gauge}
    ``goodput_overall``   fleet useful/dispatched rows (None = no batches)
    ``padding_waste``     1 - goodput_overall (None = no batches)
    ``health``            {replica: healthy|degraded|down|stale|unknown}
    ``staleness_s``       {replica: age of its newest sample}
    ``scrape_stale``      {replica: failed-scrape count (subprocess mode)}
    ``replica_count``     {"up"/"draining"/"down": group membership now}
    ``birth_age_s``       {replica: seconds since its Replica was born}
    ``rpc``               {replica: {in_flight, reuse_ratio,
                          transport_errors}} — the RPC data plane's
                          health per subprocess replica (empty for
                          thread-mode groups)
    ``incidents``         open incidents (obs v6 incident engine)
    ``journal``           journal health: armed/records/dropped/lag_s
    ``scaler``            control-axis summary (obs v7): armed/ticks/
                          actions/last_action
    ===================== ==================================================
    """

    __slots__ = ("at_s", "ticks", "tick_s", "window", "slo_burn",
                 "slo_burn_velocity", "queue_depth",
                 "queue_depth_total", "occupancy", "breaker_open",
                 "breaker_flaps", "goodput", "goodput_overall",
                 "padding_waste", "health", "staleness_s",
                 "scrape_stale", "replica_count", "birth_age_s",
                 "rpc", "incidents", "journal", "scaler", "series")

    def __init__(self, **kw):
        missing = [n for n in self.__slots__ if n not in kw]
        if missing:
            raise TypeError(f"missing signal fields: {missing}")
        for name in self.__slots__:
            setattr(self, name, kw.pop(name))
        if kw:
            raise TypeError(f"unknown signal fields: {sorted(kw)}")

    @classmethod
    def from_sources(cls, fleet: FleetSeries, registry_snapshot: dict,
                     slo_snapshot: dict, now: float,
                     incidents: list | None = None,
                     journal: dict | None = None,
                     scaler: dict | None = None) -> "FleetSignals":
        """Assemble one consistent bundle from the live sources: the
        fleet store (windowed series), a registry snapshot (goodput
        gauges + scrape-staleness counters), and the SLO accounts
        (current burn; velocity comes from the store's windowed
        ``slo_burn:<tenant>`` series).  ``incidents`` / ``journal`` /
        ``scaler`` are the history and control axes' contributions
        (``obs.signals()`` passes the open-incident list, journal
        health, and the scaler summary; callers wiring the sources by
        hand may omit them)."""
        burn: dict = {}
        for tenant, acct in sorted(
                (slo_snapshot.get("accounts") or {}).items()):
            if acct.get("burn_rate") is not None:
                burn[tenant] = acct["burn_rate"]
        velocity = {}
        for series in fleet.names("_fleet"):
            if series.startswith("slo_burn:"):
                v = fleet.rate("_fleet", series)
                if v is not None:
                    velocity[series.split(":", 1)[1]] = v
        replicas = [r for r in fleet.replicas() if r != "_fleet"]
        depth = {}
        occupancy = {}
        b_open = {}
        b_flaps = {}
        health = {}
        stale = {}
        ages = {}
        tick_s = fleet.tick_s
        stale_after = (STALE_TICKS * tick_s) if tick_s else None
        rpc = {}
        for r in replicas:
            d = fleet.value(r, "depth")
            if d is not None:
                depth[r] = d
            inflight = fleet.value(r, "rpc_in_flight")
            if inflight is not None:
                rpc[r] = {
                    "in_flight": int(inflight),
                    "reuse_ratio": fleet.value(r, "rpc_reuse_ratio"),
                    "transport_errors": int(
                        fleet.value(r, "rpc_transport_errors")
                        or 0),
                }
            occ = fleet.value(r, "occupancy")
            if occ is not None:
                occupancy[r] = occ
            bo = fleet.value(r, "breaker_open")
            if bo is not None:
                b_open[r] = int(bo)
                b_flaps[r] = fleet.flaps(r, "breaker_open")
            age = fleet.staleness_s(r, now)
            if age is not None:
                stale[r] = age
            born = fleet.value(r, "birth_age_s")
            if born is not None:
                ages[r] = born
            up = fleet.value(r, "up")
            healthy = fleet.value(r, "healthy")
            if up is None and healthy is None:
                health[r] = "unknown"
            elif up is not None and up < 0.5:
                health[r] = "down"
            elif stale_after is not None and age is not None \
                    and age > stale_after:
                health[r] = "stale"
            elif healthy is not None and healthy < 0.5:
                health[r] = "degraded"
            else:
                health[r] = "healthy"
        goodput = {}
        for g in registry_snapshot.get("gauges", []):
            if g["name"] == "serve.goodput":
                lbl = g.get("labels") or {}
                key = "|".join(str(lbl[k]) for k in sorted(lbl))
                goodput[key or "all"] = g["value"]
        useful = dispatched = 0
        scrape_stale = {}
        for c in registry_snapshot.get("counters", []):
            if c["name"] == "serve_useful_rows":
                useful += c["value"]
            elif c["name"] == "serve_dispatched_rows":
                dispatched += c["value"]
            elif c["name"] == "fleet_scrape_stale":
                rid = (c.get("labels") or {}).get("replica", "?")
                scrape_stale[rid] = scrape_stale.get(rid, 0) \
                    + c["value"]
        overall = (useful / dispatched) if dispatched else None
        # group membership: the collector's replica_count_* series
        # when present (a started ReplicaGroup), else derived from
        # the per-replica health map (hand-wired stores, tests)
        counts = {}
        for state in ("up", "draining", "down"):
            v = fleet.value("_fleet", f"replica_count_{state}")
            if v is not None:
                counts[state] = int(v)
        if not counts:
            counts = {
                "up": sum(1 for h in health.values()
                          if h not in ("down", "unknown")),
                "draining": 0,
                "down": sum(1 for h in health.values()
                            if h == "down"),
            }
        return cls(
            at_s=now, ticks=fleet.ticks, tick_s=tick_s,
            window=fleet.window, slo_burn=burn,
            slo_burn_velocity=velocity, queue_depth=depth,
            queue_depth_total=sum(depth.values()),
            occupancy=occupancy,
            breaker_open=b_open, breaker_flaps=b_flaps,
            goodput=goodput, goodput_overall=overall,
            padding_waste=(None if overall is None
                           else 1.0 - overall),
            health=health, staleness_s=stale,
            scrape_stale=scrape_stale,
            replica_count=counts, birth_age_s=ages, rpc=rpc,
            incidents=list(incidents or []),
            journal=dict(journal or {"armed": False}),
            scaler=dict(scaler or {"armed": False}),
            series=fleet.snapshot()["series"])

    def to_dict(self) -> dict:
        """JSON-native form — the ``/signals`` route body (includes
        the raw windowed ``series`` tails so dashboards can sparkline
        without keeping client-side history), stamped with
        :data:`SIGNALS_SCHEMA` so consumers can detect contract
        drift."""
        body = {"schema": SIGNALS_SCHEMA}
        body.update((name, getattr(self, name))
                    for name in self.__slots__)
        return body

    def __repr__(self):
        return ("FleetSignals(replicas=%d, ticks=%d, burn=%s, "
                "goodput=%s)" % (len(self.health), self.ticks,
                                 self.slo_burn, self.goodput_overall))


# -- cross-replica trace stitching -------------------------------------------

def stitch_fleet_trace(ticket) -> dict:
    """Merge a failed-over router ticket's request traces into ONE
    Chrome-trace JSON dict: the dead replicas' terminal traces
    (``ticket.prior_traces``) plus the surviving replica's trace, one
    track (tid) per attempt, every lifecycle edge as an instant
    event, an explicit ``failover_hop`` marker at each dead attempt's
    terminal edge, and the per-attempt deadline stamps
    (``deadlines_ms`` — the carried-deadline proof: entries only ever
    shrink) under ``otherData``.  Attempts are aligned on the shared
    process-monotonic clock their traces were minted on, so the
    failover timeline reads true in Perfetto.  Write it with
    ``obs.save_trace(path, fleet=ticket)``."""
    prior = [t for t in (getattr(ticket, "prior_traces", None) or [])
             if t is not None]
    final = getattr(ticket, "trace", None)
    attempts = prior + ([final] if final is not None else [])
    names = list(getattr(ticket, "attempt_replicas", None) or [])
    deadlines = list(getattr(ticket, "deadlines_ms", None) or [])
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "tid": 0,
               "args": {"name": "veles.simd_tpu fleet request "
                                f"{getattr(ticket, 'rid', '?')}"}}]
    mints = [getattr(tr, "_t0", None) for tr in attempts]
    known = [m for m in mints if m is not None]
    base = min(known) if known else 0.0
    for i, tr in enumerate(attempts):
        tid = i + 1
        off_s = (mints[i] - base) if mints[i] is not None else 0.0
        replica = names[i] if i < len(names) else (
            getattr(ticket, "replica", None) if tr is final else None)
        status = getattr(tr, "status", None)
        evs = tr.events() if hasattr(tr, "events") else []
        t_last = max([e.get("t_s", 0.0) for e in evs] or [0.0])
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"attempt {i} @ "
                                        f"{replica or '?'} "
                                        f"({status or 'open'})"}})
        events.append({
            "name": f"{getattr(tr, 'op', None) or getattr(ticket, 'op', '?')}"
                    f" attempt {i}",
            "cat": "fleet", "ph": "X", "ts": off_s * 1e6,
            "dur": max(t_last, 1e-9) * 1e6, "pid": pid, "tid": tid,
            "args": {"replica": replica, "status": status,
                     "rid": getattr(tr, "rid", None),
                     "deadline_s": getattr(tr, "deadline_s", None),
                     "deadline_ms": (deadlines[i]
                                     if i < len(deadlines) else None),
                     "attempt": i}})
        for e in evs:
            events.append({
                "name": e.get("event", "?"), "cat": "fleet",
                "ph": "i", "s": "t",
                "ts": (off_s + e.get("t_s", 0.0)) * 1e6,
                "pid": pid, "tid": tid,
                "args": {k: v for k, v in e.items()
                         if k not in ("event", "t_s")}})
        if i < len(attempts) - 1:
            events.append({
                "name": "failover_hop", "cat": "fleet", "ph": "i",
                "s": "p", "ts": (off_s + t_last) * 1e6, "pid": pid,
                "tid": tid,
                "args": {"from_attempt": i, "to_attempt": i + 1,
                         "from_replica": replica,
                         "to_replica": (names[i + 1]
                                        if i + 1 < len(names)
                                        else None)}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "fleet": True,
            "rid": getattr(ticket, "rid", None),
            "op": getattr(ticket, "op", None),
            "status": getattr(ticket, "status", None),
            "failovers": getattr(ticket, "failovers", 0),
            "attempts": len(attempts),
            "replicas": names,
            "deadlines_ms": deadlines,
        },
    }
