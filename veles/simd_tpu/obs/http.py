"""Live scrape endpoint: the obs layer, served over stdlib HTTP.

Until now every export was a file (``obs.save``, flight bundles,
``BENCH_DETAILS.json``) — fine for post-mortems, blind for a *running*
service.  This module is the live surface: read-only routes on a
daemon-threaded stdlib ``http.server`` (no new dependencies, same rule
as the rest of the tooling):

* ``GET /metrics`` — ``obs.to_prometheus()`` verbatim (counters,
  gauges incl. the per-tenant ``slo_*`` family, histograms incl.
  ``serve.request_latency{op, status}`` and the ``request.*`` phase
  family, resources, caches) — point a Prometheus scraper at it;
* ``GET /healthz`` — JSON: endpoint liveness plus whatever the owning
  process registered as its health provider (the serving layer wires
  ``Server.stats()`` in: health machine state, breaker registry,
  admission depths, batcher classes).  Status 200 while the provider
  reports ``healthy`` (or no provider is registered), 503 once it
  reports ``degraded`` — load balancers can act on the code alone;
* ``GET /debug/requests`` — JSON: the request axis
  (:mod:`veles.simd_tpu.obs.requests`): recent completed traces,
  slowest-per-op and degraded exemplars, and the per-tenant SLO
  accounts;
* ``GET /signals`` — JSON: the fleet axis
  (:mod:`veles.simd_tpu.obs.timeseries`): the typed
  ``obs.signals()`` bundle — slo burn + velocity, queue depths,
  breaker open/flap counts, goodput, per-replica health/staleness,
  plus the raw windowed series tails (``tools/obs_dash.py --fleet``
  sparklines from exactly this body).  Meaningful on the router
  aggregation endpoint (the ``ReplicaGroup`` collector feeds the
  store); on a lone server it answers with an empty fleet;
* ``GET /incidents`` — JSON: the incident engine's typed open→closed
  records (:mod:`veles.simd_tpu.obs.incidents`) — which rule fired,
  the trigger detail, the journal cursor and flight bundle captured
  at open, and the close reason once quiet;
* ``GET /scaler`` — JSON: the control axis
  (:func:`veles.simd_tpu.obs.scaler_snapshot`): the registered
  autoscaler engine's state — tick count, per-action streaks,
  cooldown, bounds, and the recent decision records with their full
  input vectors — or the disarmed shell when no scaler runs here;
* ``POST /submit`` — the one WRITE route, armed only when the owning
  process registered a submit handler (the serving layer binds
  :func:`veles.simd_tpu.serve.rpc.serve_submit`): binary npy-framed
  request in, binary npy-framed response out — the RPC data plane a
  ``spawn="subprocess"`` replica serves router traffic over.  The
  endpoint speaks HTTP/1.1 so the router's pooled connections
  persist across requests.

The JSON routes are schema-stamped (``veles-simd-signals-v4``,
``veles-simd-requests-v1``, ``veles-simd-incidents-v1``,
``veles-simd-scaler-v1``) so a dashboard can detect contract drift
instead of mis-parsing.

Arming: :meth:`veles.simd_tpu.serve.Server.start` reads
``$VELES_SIMD_OBS_PORT`` (or its ``obs_port=`` argument; port 0 binds
an ephemeral port — the test idiom) and owns the endpoint's lifetime;
any other process can call :func:`start` directly.  The endpoint binds
localhost only — it serves operators on the host, not the internet;
put a real reverse proxy in front for anything wider.
"""

from __future__ import annotations

import http.server
import json
import os
import threading

__all__ = ["ObsEndpoint", "EndpointUnavailable", "start", "env_port",
           "OBS_PORT_ENV", "BIND_HOST", "REQUESTS_SCHEMA"]

OBS_PORT_ENV = "VELES_SIMD_OBS_PORT"
BIND_HOST = "127.0.0.1"
# the /debug/requests contract version (the /signals and /incidents
# stamps live with their producers: timeseries.SIGNALS_SCHEMA,
# incidents.SCHEMA) — dashboards check these instead of guessing
REQUESTS_SCHEMA = "veles-simd-requests-v1"


def env_port() -> int | None:
    """The scrape-endpoint port from ``$VELES_SIMD_OBS_PORT`` (unset /
    empty / malformed / negative = None = endpoint disarmed; 0 = bind
    an ephemeral port)."""
    raw = os.environ.get(OBS_PORT_ENV, "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    return port if port >= 0 else None


class EndpointUnavailable(OSError):
    """The scrape endpoint could not bind its port — typed and
    actionable, raised OUT of :meth:`veles.simd_tpu.serve.Server.
    start` (and :class:`ObsEndpoint`) instead of dying later in the
    serving thread.  An :class:`OSError` subclass: existing callers
    that handled the raw bind error keep working, new ones get the
    typed form.  The usual cause is another process (or another
    replica in this one) already holding the port: with N replicas
    each able to arm an endpoint, a fixed ``$VELES_SIMD_OBS_PORT`` is
    a collision waiting to happen — use port 0 (ephemeral) per
    endpoint, or arm exactly one aggregation endpoint (the
    ``serve.cluster.ReplicaGroup`` pattern).  ``port`` carries the
    refused port number."""

    def __init__(self, message: str, *, port: int | None = None):
        super().__init__(message)
        self.port = port


class _Handler(http.server.BaseHTTPRequestHandler):
    """The read-only routes plus the one write route (``POST
    /submit``, armed only when the owner registered a submit
    handler).  Every handler is exception-proofed into a 500 — a
    scrape must never kill the serving process, and a half-written
    response must never wedge the scraper."""

    # HTTP/1.1 so the RPC data plane's pooled connections actually
    # persist (HTTP/1.0 closes after every exchange); every response
    # below sends Content-Length, which 1.1 keep-alive requires
    protocol_version = "HTTP/1.1"

    # headers and body leave as separate writes; without TCP_NODELAY
    # that is a Nagle + delayed-ACK stall (~40ms) on EVERY rpc
    # exchange — latency the data plane cannot afford
    disable_nagle_algorithm = True

    # the endpoint belongs to telemetry; its access log does not get
    # to spam the serving process's stderr
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send_bytes(self, code: int, data: bytes,
                    ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send(self, code: int, body: str, ctype: str) -> None:
        self._send_bytes(code, body.encode("utf-8"), ctype)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            path = self.path.split("?", 1)[0]
            submit = self.server.owner.submit_handler
            if path != "/submit" or submit is None:
                self._send(404, json.dumps(
                    {"error": "unknown path",
                     "routes": (["/submit"] if submit is not None
                                else [])}),
                    "application/json")
                return
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length > 0 else b""
            code, payload = submit(body)
            self._send_bytes(int(code), payload,
                             "application/octet-stream")
        except BrokenPipeError:
            pass        # client hung up mid-response: its problem
        except Exception as e:  # noqa: BLE001 — a request never kills
            try:
                self._send(500, json.dumps({"error": repr(e)}),
                           "application/json")
            except Exception:  # noqa: BLE001
                pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                from veles.simd_tpu import obs

                self._send(200, obs.to_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                body, code = self.server.owner.healthz()
                self._send(code, json.dumps(body, indent=2,
                                            default=str),
                           "application/json")
            elif path == "/debug/requests":
                from veles.simd_tpu import obs

                body = {"schema": REQUESTS_SCHEMA}
                body.update(obs.request_snapshot())
                self._send(200, json.dumps(body, indent=2,
                                           default=str),
                           "application/json")
            elif path == "/signals":
                from veles.simd_tpu import obs

                self._send(200, json.dumps(obs.signals().to_dict(),
                                           indent=2, default=str),
                           "application/json")
            elif path == "/incidents":
                from veles.simd_tpu import obs

                self._send(200, json.dumps(obs.incidents_snapshot(),
                                           indent=2, default=str),
                           "application/json")
            elif path == "/scaler":
                from veles.simd_tpu import obs

                self._send(200, json.dumps(obs.scaler_snapshot(),
                                           indent=2, default=str),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path",
                     "routes": ["/metrics", "/healthz",
                                "/debug/requests", "/signals",
                                "/incidents", "/scaler"]}),
                    "application/json")
        except BrokenPipeError:
            pass        # scraper hung up mid-response: its problem
        except Exception as e:  # noqa: BLE001 — a scrape never kills
            try:
                self._send(500, json.dumps({"error": repr(e)}),
                           "application/json")
            except Exception:  # noqa: BLE001
                pass


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # restarting a serving process on the same port must not wait out
    # TIME_WAIT
    allow_reuse_address = True


class ObsEndpoint:
    """One armed scrape endpoint: the bound port, the serving daemon
    thread, and :meth:`stop`.  ``health`` is an optional zero-arg
    callable returning a JSON-native dict for ``/healthz`` (the
    serving layer passes its ``stats``).  ``submit`` is an optional
    ``(body_bytes) -> (http_code, response_bytes)`` callable arming
    the ``POST /submit`` RPC route (the serving layer passes
    ``serve.rpc.serve_submit`` bound to its server; None leaves the
    endpoint read-only)."""

    def __init__(self, port: int, health=None, submit=None):
        self._health = health
        self.submit_handler = submit
        try:
            self._httpd = _Server((BIND_HOST, int(port)), _Handler)
        except OSError as e:
            # EADDRINUSE and friends: surface a typed, actionable
            # error at arm time (Server.start) — never an opaque
            # OSError out of a server that half-started
            raise EndpointUnavailable(
                f"obs scrape endpoint could not bind "
                f"{BIND_HOST}:{int(port)} ({e.strerror or e}) — the "
                f"port is likely held by another process or replica; "
                f"use obs_port=0 for an ephemeral port, pick a free "
                f"one, or disarm with a negative obs_port / unset "
                f"${OBS_PORT_ENV}", port=int(port)) from e
        self._httpd.owner = self
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"veles-obs-http-{self.port}")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{BIND_HOST}:{self.port}"

    def healthz(self) -> tuple:
        """``(body, http_code)`` for ``/healthz``: 503 once the health
        provider reports a degraded state, 200 otherwise."""
        body = {"endpoint": "ok", "port": self.port}
        code = 200
        if self._health is not None:
            try:
                provided = self._health()
            except Exception as e:  # noqa: BLE001 — report, not crash
                return ({**body, "provider_error": repr(e)}, 500)
            body.update(provided if isinstance(provided, dict)
                        else {"health": provided})
            state = body.get("health")
            if isinstance(state, dict):
                state = state.get("state")
            if state == "degraded":
                code = 503
        return body, code

    def stop(self) -> None:
        """Shut the listener down and join the serving thread."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __repr__(self):
        return f"ObsEndpoint(port={self.port})"


def start(port: int | None = None, health=None,
          submit=None) -> ObsEndpoint | None:
    """Arm the endpoint on ``port`` (None = ``$VELES_SIMD_OBS_PORT``;
    still None = disarmed, returns None; 0 = ephemeral).  ``submit``
    arms the ``POST /submit`` RPC route (see :class:`ObsEndpoint`).
    Returns the live :class:`ObsEndpoint` — the caller owns
    :meth:`stop`."""
    if port is None:
        port = env_port()
    if port is None:
        return None
    return ObsEndpoint(int(port), health=health, submit=submit)
