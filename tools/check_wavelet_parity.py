#!/usr/bin/env python
"""Recover & verify the symlet root selections from the published table.

This is the recovery tool referenced by
``veles/simd_tpu/ops/wavelet_coeffs.py`` (the ``_SYMLET_SELECTIONS`` map):
for each symlet order it classifies, per root orbit of the Daubechies
half-band polynomial, whether the *published* filter
(``/root/reference/src/symlets.c:38-39``, shipped in
``ops/_wavelet_tables.npz``) kept the min-phase root (bit 0) or its
reciprocal (bit 1), by evaluating the published row's z-transform at both
candidate roots with scale-normalized residuals.  Orbits whose residual
ratio is not decisive are brute-forced over both values; a selection is
accepted only when rebuilding from it in exact arithmetic reproduces the
published row (to a tolerance that tracks the published table's own
double-precision generation error — ≤5e-10 up to order 50, growing to
~2e-5 at 76).

Run:  python tools/check_wavelet_parity.py [--orders 8 24 76]

Exit status is non-zero if any recovered selection fails reconstruction or
disagrees with the checked-in ``_SYMLET_SELECTIONS`` map.
"""
from __future__ import annotations

import argparse
import functools
import itertools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from veles.simd_tpu.ops import wavelet_coeffs as wc  # noqa: E402

# one mpmath root-finding per order; selection rebuilds reuse it
wc._daubechies_zroots = functools.lru_cache(maxsize=None)(
    wc._daubechies_zroots)


def _ztransform_residual(h, z, mp):
    """|H(z)| / Σ|h_n||z^-n| — scale-free closeness of z to a root of H."""
    num = mp.mpc(0)
    den = mp.mpf(0)
    zi = mp.mpc(1)
    for c in h:
        num += mp.mpf(float(c)) * zi
        den += abs(mp.mpf(float(c))) * abs(zi)
        zi /= z
    return float(abs(num) / den)


def _classify(order, published):
    """Recover (mirror, bits) for one order from the published row.

    Returns (mirror, bits, max_abs_err, ambiguous_orbit_count).
    """
    mp = wc._mp()
    p = order // 2
    zr = wc._daubechies_zroots(p)
    orbits = wc._root_orbits(zr)

    # Per-orbit residuals of the published row at the inside root and at its
    # reciprocal.  The evaluation Σ p_n z^{-n} vanishes when 1/z is a root of
    # the row's polynomial, so (for an unmirrored row, which stores ascending
    # coefficients of the kept-root polynomial) a vanishing residual at the
    # *inside* root means the *outside* root was kept — bit 1.  A mirrored
    # row reverses the polynomial, reciprocating every root, which flips all
    # bits; both (mirror, flip) pairings are tried below.
    decisive, free = [], []
    for k, orb in enumerate(orbits):
        z = orb[0]
        r_in = _ztransform_residual(published, z, mp)
        r_out = _ztransform_residual(published, 1 / mp.conj(z), mp)
        lo, hi = min(r_in, r_out), max(r_in, r_out)
        if lo < 1e-4 * hi:
            decisive.append("1" if r_in < r_out else "0")
        else:
            decisive.append(None)
            free.append(k)

    best = (np.inf, None, None)
    for fill in itertools.product("01", repeat=len(free)):
        bits = list(decisive)
        for k, b in zip(free, fill):
            bits[k] = b
        bits = "".join(bits)
        flipped = "".join("1" if b == "0" else "0" for b in bits)
        for mirror, mb in ((0, bits), (1, flipped), (0, flipped), (1, bits)):
            h = wc._symlet_from_selection(order, mirror, mb)
            err = float(np.max(np.abs(h - published)))
            if err < best[0]:
                best = (err, mirror, mb)
        if best[0] < 1e-9:
            break
    err, mirror, bits = best
    return mirror, bits, err, len(free)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--orders", type=int, nargs="*", default=None,
                    help="symlet orders to check (default: all ≥ 4)")
    args = ap.parse_args(argv)

    tables = wc._tables()
    orders = args.orders or [o for o in wc.supported_orders(
        wc.WaveletType.SYMLET) if o >= 4]
    bad = 0
    for order in orders:
        # the .npz ships the published rows normalized to Σh = 1; the
        # selection machinery works at the reference's Σh = √2 scale
        published = np.asarray(tables[f"sym{order}"],
                               np.float64) * np.sqrt(2.0)
        mirror, bits, err, n_amb = _classify(order, published)
        # the published table's own generation error grows with order; the
        # acceptance bound tracks its orthonormality residual envelope
        tol = 5e-9 if order <= 50 else 5e-5
        checked_in = wc._SYMLET_SELECTIONS.get(order)
        if checked_in is None:
            # orders below 4 have a single orbit and no map entry
            agree = False
        else:
            # (mirror, bits) and (1-mirror, ~bits) denote the same filter
            # (mirroring reciprocates every root), so compare the filters
            h_checked = wc._symlet_from_selection(order, *checked_in)
            h_found = wc._symlet_from_selection(order, mirror, bits)
            agree = float(np.max(np.abs(h_checked - h_found))) < 1e-12
        status = "ok" if (err < tol and agree) else "FAIL"
        if status == "FAIL":
            bad += 1
        amb = f" ambiguous={n_amb}" if n_amb else ""
        print(f"sym{order:<3d} mirror={mirror} bits={bits:<20s} "
              f"max|Δ|={err:.2e}{amb} "
              + ("== _SYMLET_SELECTIONS" if agree
                 else "!= " + repr(checked_in))
              + f"  [{status}]")
    if bad:
        print(f"{bad} order(s) failed", file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
