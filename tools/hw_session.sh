#!/bin/sh
# One-shot hardware validation session: run every device-pending item in
# priority order the moment the axon relay is reachable.  Each step is
# independently logged and failure-isolated; the bench headline (the
# driver's BENCH_r03 artifact input) goes first so a short device window
# still captures it.
#
#   sh tools/hw_session.sh [outdir]        # default /tmp/hw_session
#
# Steps (pallas2d — the round-3 wedge suspect — is excluded from every
# smoke stage via VELES_SIMD_SMOKE_SKIP and runs ONLY in the final
# bisect step, so a wedge there cannot cost anything else):
#   1. bench.py            -> headline JSON + BENCH_DETAILS.json + the
#                             embedded smoke (minus pallas2d)
#   2. tools/tpu_smoke.py  -> retry ONLY the families still lacking a
#                             green hardware run (as of late 2026-07-31:
#                             pallas1d/parallel plus everything added in
#                             round 3 — iir, filters, waveforms,
#                             detect_peaks' new analysis, the spectral
#                             estimation layer), in case the
#                             bench-embedded smoke got cut
#   3. tools/benchmark_suite.py --quick -> per-family timed entries
#                             (IIR/filters/spectral/resample/waveforms/
#                             peaks/fused-cascade vs level-loop)
#   4. tools/tune_conv2d.py --quick   -> 2D crossover measurement
#   5. tools/tune_overlap_save.py --quick  -> 1D step-size re-check
#   6. tools/repro_pallas2d.py  -> the pallas2d bisect, DEAD LAST; its
#                             JSON ledger survives even if it wedges
set -u
OUT=${1:-/tmp/hw_session}
mkdir -p "$OUT"
OUT=$(cd "$OUT" && pwd)   # absolutize before the repo-root cd below
cd "$(dirname "$0")/.."

echo "== hw_session $(date -u +%FT%TZ) -> $OUT"

run() {
  name=$1; shift
  echo "== $name: $*"
  start=$(date +%s)
  "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  rc=$?
  echo "== $name: rc=$rc (${name}.out/.err, $(($(date +%s) - start))s)"
  return 0
}

# every step under a hard `timeout -k` (TERM then KILL — an in-flight
# device call on a wedged relay blocks forever in native code, observed
# 2026-07-31, and only process death clears it).  bench.py also
# self-watchdogs per stage.  The smoke retry covers only the families
# without a green hardware run yet — a wedge-prone family must not be
# able to burn the window twice (update the list as families go green).
#
# pallas2d (the round-3 wedge suspect) is held out of EVERY stage via
# VELES_SIMD_SMOKE_SKIP and runs dead last through the bisect harness:
# if it wedges the relay again, everything else was already captured.
export VELES_SIMD_SMOKE_SKIP=pallas2d
run bench        timeout -k 60 3000 python bench.py --all
cp -f BENCH_DETAILS.json "$OUT/" 2>/dev/null || true
run smoke        timeout -k 60 1500 python tools/tpu_smoke.py \
                   --family=iir --family=filters --family=waveforms \
                   --family=spectral --family=resample \
                   --family=detect_peaks \
                   --family=pallas1d --family=parallel
# per-family timed entries (IIR, filters, spectral, resample,
# waveforms, peaks, cascade fused-vs-loop, ...) — the table VERDICT r3
# item 1 asks for; --quick keeps it inside a short window
run suite        timeout -k 60 2400 python tools/benchmark_suite.py --quick
run tune_conv2d  timeout -k 60 1800 python tools/tune_conv2d.py --quick
run tune_os      timeout -k 60 1800 python tools/tune_overlap_save.py --quick
run repro_p2d    timeout -k 60 2400 python tools/repro_pallas2d.py \
                   --out "$OUT/repro_pallas2d.json"
cp -f "$OUT/repro_pallas2d.json" . 2>/dev/null || true

echo "== headline:"
head -1 "$OUT/bench.out" 2>/dev/null
echo "== done $(date -u +%FT%TZ)"
