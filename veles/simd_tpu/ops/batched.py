"""Batched-throughput layer: many short signals, one dispatch.

BASELINE.md's round-5 suite measured single-signal ``resample_poly`` /
``sosfilt`` at 4-128k samples in the single-digit Msamples/s — those
entry points are dispatch-bound BY DESIGN at such sizes (one ~66 us
relay round trip per call dwarfs the math).  The throughput form of
every short-signal workload on a TPU is the batched one: stack the
signals on a leading axis, compile ONE program for that ``(batch,
length)`` geometry, and reuse it call after call — the reformulation
both "Large-Scale Discrete Fourier Transform on TPUs" (arxiv
2002.03260) and "TINA" (arxiv 2408.16551) use to keep signal loops
MXU/VPU-resident.

This module is that entry surface, the compiled-handle analog of the
reference's plan-handle API (``inc/simd/convolve.h:58-76``):

* **One executable per geometry, LRU-bounded.**  ``jax.jit`` keeps an
  unbounded per-function cache; a service cycling through shapes leaks
  executables.  Handles here live in an explicit LRU
  (:data:`BATCHED_CACHE_MAXSIZE`, default 64) with hit/miss telemetry
  under ``obs`` — evicting a handle frees nothing until XLA drops the
  executable, but bounds the *live* set a long-running server touches.
* **Opt-in donated input buffers.**  With ``donate=True`` the signal
  batch is donated to the executable (``donate_argnums``) on TPU, so
  the output can reuse the input's HBM allocation instead of doubling
  resident memory per call — the difference between fitting 2N and N
  signals on-chip mid-pipeline.  Donation INVALIDATES the caller's
  array (standard jax donation semantics: a device-resident input is
  deleted once the executable consumes it), which is why it is opt-in
  rather than implicit.  (Donation is skipped on CPU, where the
  backend cannot honor it and jax would warn.)
* **Same numerics as the single-signal ops.**  Each handle wraps the
  exact jitted core the public op dispatches to
  (:func:`~veles.simd_tpu.ops.resample._resample_conv`, the
  ``iir`` associative scans), so the oracle-parity tests transfer.

Usage::

    from veles.simd_tpu.ops import batched

    ys = batched.batched_resample_poly(xs, 160, 147)   # xs: [B, n]
    ys = batched.batched_sosfilt(sos, xs)              # one dispatch
    ys = batched.batched_lfilter(b, a, xs)

The ``simd=`` flag works as everywhere else (falsy runs the NumPy
oracle twin, batched trivially).
"""

from __future__ import annotations

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import on_tpu, resolve_simd
from veles.simd_tpu.runtime import precision as prx

__all__ = [
    "BatchedHandle", "batched_resample_poly", "batched_sosfilt",
    "batched_lfilter", "batched_stft", "handle_cache_info",
    "clear_handle_cache", "BATCHED_CACHE_MAXSIZE",
]

# live compiled-handle bound: a handle is ~a closure + a jit cache
# entry; 64 distinct (op, batch, length, params) geometries covers a
# service's steady state while keeping eviction observable in tests
BATCHED_CACHE_MAXSIZE = 64

_cache: "collections.OrderedDict[tuple, BatchedHandle]" = \
    collections.OrderedDict()
_cache_lock = threading.Lock()
# per-key construction locks: concurrent misses on the SAME key build
# once (the serving layer fans submit() threads into these ops, so the
# old build-outside-the-lock race would trace duplicate programs and
# evict live handles); entries are dropped once the build finishes
_build_locks: dict = {}
_cache_stats = {"hits": 0, "misses": 0, "evictions": 0}
# unified cache introspection: handle_cache_info already has the
# size/capacity/hits/misses/evictions shape obs.caches() wants
obs.register_cache("batched_handle_lru",
                   lambda: handle_cache_info())


class BatchedHandle:
    """One compiled program pinned to a batched-op geometry.

    ``key`` is the full cache key (op name + batch rows + length +
    op-static parameters); ``fn`` the jitted callable.  Handles are
    created by :func:`_get_handle` and shared — treat as immutable.
    """

    __slots__ = ("key", "fn")

    def __init__(self, key, fn):
        self.key = key
        self.fn = fn

    def __call__(self, *args):
        return self.fn(*args)

    def __repr__(self):  # pragma: no cover - debugging nicety
        return f"BatchedHandle{self.key!r}"


def _get_handle(key, builder) -> BatchedHandle:
    """LRU lookup of the compiled handle for ``key``; ``builder()``
    makes the jitted callable on a miss.  Hits/misses/evictions are
    counted under ``batched_handle_cache`` and a decision event is
    recorded per compile (so a shape-churning caller shows up in the
    obs report as a stream of misses, not silence).

    Construction is race-free: concurrent callers of the same key
    serialize on a per-key build lock (builds of DIFFERENT keys still
    overlap — tracing can be slow, so the global map lock is never
    held across ``builder()``), exactly one thread builds, and the
    losers return the winner's handle as a hit.  Before the serving
    layer this was best-effort ("one handle wins the insert"): two
    threads could trace the same program twice and double-bump the
    LRU, evicting a live neighbor.
    """
    with _cache_lock:
        handle = _cache.get(key)
        if handle is not None:
            _cache.move_to_end(key)
            _cache_stats["hits"] += 1
            obs.count("batched_handle_cache", op=key[0], event="hit")
            return handle
        build_lock = _build_locks.setdefault(key, threading.Lock())
    with build_lock:
        with _cache_lock:
            handle = _cache.get(key)
            if handle is not None:
                # another caller finished the build while we waited:
                # a cache hit from this thread's point of view
                _cache.move_to_end(key)
                _cache_stats["hits"] += 1
                obs.count("batched_handle_cache", op=key[0],
                          event="hit")
                return handle
            _cache_stats["misses"] += 1
        try:
            fn = builder()
        except BaseException:
            # a failed build must not leave the key permanently locked
            # (the next caller gets a fresh shot); dropping the entry
            # is safe — a waiter holding this lock object re-checks
            # the cache under _cache_lock and misses cleanly
            with _cache_lock:
                _build_locks.pop(key, None)
            raise
        handle = BatchedHandle(key, fn)
        obs.count("batched_handle_cache", op=key[0], event="miss")
        obs.record_decision("batched", key[0], key=repr(key[1:]))
        with _cache_lock:
            _cache[key] = handle
            # drop the build-lock entry ATOMICALLY with the insert: a
            # pop before the handle lands would open a window where a
            # fresh caller mints a new lock and traces the same
            # program twice (the exact race this lock exists to close)
            _build_locks.pop(key, None)
            while len(_cache) > BATCHED_CACHE_MAXSIZE:
                _cache.popitem(last=False)
                _cache_stats["evictions"] += 1
                obs.count("batched_handle_cache", op=key[0],
                          event="eviction")
    return handle


def handle_cache_info() -> dict:
    """Snapshot of the handle cache: size, capacity, hits/misses/
    evictions, and the resident keys oldest-first."""
    with _cache_lock:
        return {"size": len(_cache), "maxsize": BATCHED_CACHE_MAXSIZE,
                **_cache_stats, "keys": list(_cache)}


def clear_handle_cache() -> None:
    """Drop every cached handle and zero the stats (tests; a server
    rolling new geometry sets can also use it as a coarse reset)."""
    with _cache_lock:
        _cache.clear()
        _build_locks.clear()
        for k in _cache_stats:
            _cache_stats[k] = 0


def _donate_argnums(donate: bool) -> tuple:
    """Donation argnums for the signal batch: only when the caller
    opted in (donation deletes a device-resident input array) AND on
    TPU (the CPU backend cannot honor donation and jax warns per
    call)."""
    return (0,) if (donate and on_tpu()) else ()


def _as_batch2d(x):
    """Validate a leading-batch-dim signal array and flatten to
    ``[rows, n]``; returns ``(x2d_host_or_device, batch_shape, n)``."""
    shape = np.shape(x)
    if len(shape) < 2:
        raise ValueError(
            f"batched ops take [..., batch, n] signals, got shape "
            f"{shape} — use the single-signal op for 1D input")
    n = shape[-1]
    if n == 0:
        raise ValueError("empty signal")
    if 0 in shape[:-1]:
        # a zero-row batch would otherwise surface as an opaque XLA
        # shape error deep in the compiled core; the serving layer's
        # batcher relies on this contract (it never dispatches B=0,
        # and a bug that tries must fail loudly, not cryptically)
        raise ValueError(
            f"empty batch (B=0): batched ops need at least one "
            f"signal, got shape {shape}")
    return shape[:-1], n


# ---------------------------------------------------------------------------
# resample
# ---------------------------------------------------------------------------


def batched_resample_poly(x, up: int, down: int, taps=None, simd=None,
                          donate: bool = False):
    """Rational-rate resampling of a BATCH of equal-length signals in
    one dispatch: ``x[..., batch, n] -> [..., batch, ceil(n*up/down)]``.

    Same numerics/conventions as
    :func:`~veles.simd_tpu.ops.resample.resample_poly` (the handle
    wraps the same dilated-conv core); the anti-aliasing taps stay
    runtime data, so switching filters does NOT recompile — only a new
    ``(batch, n, up, down, len(taps))`` geometry does.  ``donate=True``
    donates the signal batch to the executable on TPU — the caller's
    ``x`` becomes invalid afterwards (see the module note).
    """
    from veles.simd_tpu.ops import resample as rs

    batch_shape, n = _as_batch2d(x)
    up, down, taps = rs._normalize_resample_args(n, up, down, taps)
    if not resolve_simd(simd, op="batched_resample_poly"):
        return rs.resample_poly_na(x, up, down, taps).astype(np.float32)
    if up == 1 and down == 1:
        return jnp.asarray(x, jnp.float32)
    out_len = rs.resample_length(n, up, down)
    rows = int(np.prod(batch_shape))
    donation = _donate_argnums(donate)
    key = ("resample_poly", rows, n, up, down, len(taps), donation)

    def build():
        def run(xb, tapsj):
            return rs._resample_conv(xb, tapsj, up, down, out_len)

        return obs.instrumented_jit(run, op="batched_resample_poly",
                                    route="batched",
                                    artifact_key=repr(key),
                                    donate_argnums=donation)

    with obs.span("batched.resample_poly.dispatch"):
        handle = _get_handle(key, build)
        x2d = jnp.asarray(x, jnp.float32).reshape(rows, n)
        out = handle(x2d, jnp.asarray(taps, jnp.float32))
    return out.reshape(batch_shape + (out_len,))


# ---------------------------------------------------------------------------
# IIR cascades / transfer functions
# ---------------------------------------------------------------------------


def batched_sosfilt(sos, x, simd=None, donate: bool = False):
    """Second-order-section cascade over a BATCH of equal-length
    signals in one dispatch: ``x[..., batch, n] -> same shape``.

    Same associative-scan numerics as
    :func:`~veles.simd_tpu.ops.iir.sosfilt` (zero initial state — the
    streaming/zi form stays on the single-signal API).  The section
    coefficients are part of the compiled program (they parameterize
    the scan's companion matrices), so the handle key includes them:
    one executable per (filter, batch, length).  ``donate=True``
    donates the signal batch on TPU (invalidates the caller's ``x`` —
    module note).
    """
    from veles.simd_tpu.ops import iir

    sos = iir._check_sos(sos)
    batch_shape, n = _as_batch2d(x)
    if not resolve_simd(simd, op="batched_sosfilt"):
        return iir.sosfilt_na(sos, x).astype(np.float32)
    sos_key = tuple(tuple(float(v) for v in row) for row in sos)
    rows = int(np.prod(batch_shape))
    donation = _donate_argnums(donate)
    key = ("sosfilt", rows, n, sos_key, donation)

    def build():
        sos_rows = np.asarray(sos_key, np.float32)

        def run(xb):
            return iir._sos_scan(xb, sos_rows)

        return obs.instrumented_jit(run, op="batched_sosfilt",
                                    route="batched",
                                    artifact_key=repr(key),
                                    donate_argnums=donation)

    with obs.span("batched.sosfilt.dispatch"):
        handle = _get_handle(key, build)
        out = handle(jnp.asarray(x, jnp.float32).reshape(rows, n))
    return out.reshape(batch_shape + (n,))


def batched_lfilter(b, a, x, simd=None, donate: bool = False):
    """Direct-form transfer-function filter over a BATCH of
    equal-length signals in one dispatch (the batched form of
    :func:`~veles.simd_tpu.ops.iir.lfilter`, same companion-matrix
    scan and order bound).  Coefficients key the compiled program,
    like :func:`batched_sosfilt`; ``donate=True`` donates the signal
    batch on TPU (invalidates the caller's ``x`` — module note).
    """
    from veles.simd_tpu.ops import iir

    b, a = iir._normalize_ba(b, a)
    p = len(a) - 1
    if p > iir._LFILTER_MAX_ORDER:
        raise ValueError(
            f"denominator order {p} > {iir._LFILTER_MAX_ORDER}: use "
            "batched_sosfilt (cascaded second-order sections) for "
            "high-order filters")
    batch_shape, n = _as_batch2d(x)
    if not resolve_simd(simd, op="batched_lfilter"):
        return iir.lfilter_na(b, a, x).astype(np.float32)
    if p == 0:
        a = np.concatenate([a, [0.0]])  # pure FIR: drive only
    b_key = tuple(float(v) for v in b)
    a_key = tuple(float(v) for v in a)
    rows = int(np.prod(batch_shape))
    donation = _donate_argnums(donate)
    key = ("lfilter", rows, n, b_key, a_key, donation)

    def build():
        def run(xb):
            return iir._lfilter_xla(xb, b_key, a_key)

        return obs.instrumented_jit(run, op="batched_lfilter",
                                    route="batched",
                                    artifact_key=repr(key),
                                    donate_argnums=donation)

    with obs.span("batched.lfilter.dispatch"):
        handle = _get_handle(key, build)
        out = handle(jnp.asarray(x, jnp.float32).reshape(rows, n))
    return out.reshape(batch_shape + (n,))


# ---------------------------------------------------------------------------
# spectral
# ---------------------------------------------------------------------------


def batched_stft(x, frame_length: int, hop: int, window=None,
                 simd=None):
    """STFT of a BATCH of equal-length signals in one dispatch:
    ``x[..., batch, n] -> complex64 [..., batch, frames, bins]``.

    Same numerics/route family as
    :func:`~veles.simd_tpu.ops.spectral.stft`: the route comes from
    ``spectral._stft_route_for`` (the same engine selection ``stft``
    uses, so a tune-cache winner steers both entry points — this path
    consults the pack but never probes) and the ``rdft_matmul`` /
    ``xla_fft`` routes compile through the handle LRU keyed ``(rows,
    n, frame_length, hop, route)`` — the DFT basis and the window are
    runtime operands, so switching windows does NOT recompile, only a
    new geometry does.  A ``pallas_fused`` selection delegates to
    ``spectral.stft`` (the fused kernel is already one dispatch per
    batch and holds its own compile cache; the handle LRU would add
    nothing).  No ``donate=``: the complex output cannot alias the f32
    input buffer, so donation would be a no-op warning.
    """
    from veles.simd_tpu.ops import spectral as sp

    frame_length, hop = int(frame_length), int(hop)
    batch_shape, n = _as_batch2d(x)
    sp._check_stft_args(n, frame_length, hop)
    window = sp._resolve_window(window, frame_length)
    if not resolve_simd(simd, op="batched_stft"):
        return sp.stft_na(x, frame_length, hop,
                          window).astype(np.complex64)
    rows = int(np.prod(batch_shape))
    frames = sp.frame_count(n, frame_length, hop)
    route = sp._stft_route_for(frame_length, hop, frames, rows)
    if route == "pallas_fused":
        return sp.stft(x, frame_length, hop, window=window, simd=True)
    bins = frame_length // 2 + 1
    key = ("stft", rows, n, frame_length, hop, route)

    rdft_routes = ("rdft_matmul", "rdft_matmul_bf16_comp")

    def build():
        if route in rdft_routes:
            # a pack winner may be the bf16_comp precision variant
            # (runtime/precision.py): same basis matmul, compensated
            # contraction
            p = ("bf16_comp" if route == "rdft_matmul_bf16_comp"
                 else "highest")

            def run(xb, basis):
                fr = sp._take_frames(xb, frame_length, hop)
                out = prx.p_einsum("...fl,lb->...fb", fr, basis,
                                   precision=p)
                return jax.lax.complex(out[..., :bins],
                                       out[..., bins:])
        else:
            def run(xb, w):
                fr = sp._take_frames(xb, frame_length, hop)
                return jnp.fft.rfft(fr * w, axis=-1)

        return obs.instrumented_jit(run, op="batched_stft",
                                    route=route,
                                    artifact_key=repr(key))

    with obs.span("batched.stft.dispatch"):
        handle = _get_handle(key, build)
        x2d = jnp.asarray(x, jnp.float32).reshape(rows, n)
        operand = (sp._device_basis(
            "rdft_fwd", frame_length, window,
            lambda: sp._rdft_basis(frame_length, window))
            if route in rdft_routes else jnp.asarray(window))
        out = handle(x2d, operand)
    return out.reshape(batch_shape + (frames, bins))
