#!/bin/sh
# Poll the axon relay and fire the full hardware session the moment a
# window opens.  Relay windows have been observed to be short (~3 min on
# 2026-07-31) and rare (hours-long wedges either side), so an unattended
# trigger beats a human noticing.
#
#   sh tools/watch_device.sh [outdir] [interval_s]   # defaults: /tmp/hw_session 480
#
# Probes via veles.simd_tpu.utils.platform.probe_device_count (a killable
# subprocess probe — an in-process jax.devices() on a wedged relay hangs
# unrecoverably).  One line per probe goes to stdout; on success it execs
# tools/hw_session.sh and exits with its status.
set -u
OUT=${1:-/tmp/hw_session}
INTERVAL=${2:-480}
mkdir -p "$OUT"
OUT=$(cd "$OUT" && pwd)   # absolutize before the repo-root cd below
cd "$(dirname "$0")/.."

while :; do
  n=$(timeout 120 python -c "
from veles.simd_tpu.utils.platform import probe_device_count
print(probe_device_count(timeout=90.0))" 2>/dev/null || echo 0)
  echo "$(date -u +%FT%TZ) devices=$n"
  if [ "${n:-0}" -gt 0 ] 2>/dev/null; then
    echo "$(date -u +%FT%TZ) window open -> hw_session"
    exec sh tools/hw_session.sh "$OUT"
  fi
  sleep "$INTERVAL"
done
