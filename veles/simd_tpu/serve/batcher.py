"""Deadline batcher: shape-class buckets, max_batch/max_wait coalescing.

The dynamic-batching policy every serving stack converges on (TINA's
keep-the-MXU-busy discipline, arXiv:2408.16551, applied to the request
path): queued requests are grouped by **shape class** — the (op,
params, :func:`bucket_length`) triple that keys one compiled handle in
:mod:`veles.simd_tpu.ops.batched` — and a group is dispatched when
EITHER

* it holds ``max_batch`` requests (the batch is full — waiting longer
  buys nothing), OR
* its oldest request has waited ``max_wait`` seconds (the latency
  deadline — waiting longer costs p99).

Items may additionally carry an **end-to-end request deadline**
(``deadline`` attribute, a :func:`faults.monotonic` stamp; None = no
deadline): an item whose deadline passed while queued is *expired
stale work* — dispatching it would waste device time answering a
caller who already gave up.  :meth:`next_batch` sheds expired items
to the ``on_expired`` callback (the server answers them with a typed
``DeadlineExceeded``) *before* forming a batch, re-evaluating bucket
readiness afterwards — an expired head neither dispatches stale work
nor wedges its bucket, and the condition wait wakes at the earliest
head deadline (request or batching) so expiry is noticed promptly.

Workers may additionally **refill** a just-formed batch's free row
slots from the same shape class's queue (:meth:`take_refill`) — the
continuous-batching path: a request taken this way dispatches
immediately on a batch that was leaving anyway instead of zero-padding
riding in its place.  :meth:`depth_for` exposes the per-class queue
depth (open-batch occupancy) that the cluster's padding-aware
placement score reads.

``close()`` makes every queued request immediately ready (drain), and
:meth:`next_batch` returns None only when the batcher is closed AND
empty — the worker-loop exit condition, so no request can be left
behind in a bucket.

Signals inside a class are zero-padded to the class's pow-of-two
bucket length (:func:`veles.simd_tpu.runtime.routing.pow2_bucket`) —
exactly the boundary padding the ops already apply implicitly, so the
sliced-back outputs are the unpadded answers — which keeps the live
set of compiled programs logarithmic in the length spread instead of
linear in distinct lengths.

All deadline arithmetic reads
:func:`veles.simd_tpu.runtime.faults.monotonic` (the serve lint rule
bans raw ``time.*`` here); waits park on one condition variable, so an
idle batcher costs nothing.
"""

from __future__ import annotations

import collections
import os
import threading

from veles.simd_tpu.runtime import faults, routing

__all__ = [
    "Batcher", "bucket_length",
    "MAX_BATCH_ENV", "MAX_WAIT_ENV",
    "DEFAULT_MAX_BATCH", "DEFAULT_MAX_WAIT_MS", "env_policy",
]

MAX_BATCH_ENV = "VELES_SIMD_SERVE_MAX_BATCH"
MAX_WAIT_ENV = "VELES_SIMD_SERVE_MAX_WAIT_MS"

# max_batch 8 fills a handle-LRU geometry without starving mixed
# traffic; 2 ms max_wait trades ~one dispatch round trip of added
# latency for up-to-8x fewer dispatches.  Both env-tunable.
DEFAULT_MAX_BATCH = 8
DEFAULT_MAX_WAIT_MS = 2.0

# minimum condition wait: a sub-ms residual deadline must not spin
_MIN_WAIT_S = 0.0005


def _env_pos(name: str, default, cast):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = cast(raw)
    except ValueError:
        return default
    return value if value > 0 else default


def env_policy() -> tuple:
    """``(max_batch, max_wait_s)`` from the environment
    (``$VELES_SIMD_SERVE_MAX_BATCH`` / ``_MAX_WAIT_MS``), falling back
    to the defaults."""
    return (_env_pos(MAX_BATCH_ENV, DEFAULT_MAX_BATCH, int),
            _env_pos(MAX_WAIT_ENV, DEFAULT_MAX_WAIT_MS, float) / 1e3)


def bucket_length(n: int) -> int:
    """The padded signal length of ``n``'s shape class (next power of
    two — the same classing the autotune cache uses, so a serve bucket
    and a tune-cache geometry class coincide)."""
    return routing.pow2_bucket(int(n))


class Batcher:
    """Bucketed FIFO queues + the deadline policy behind one condition.

    Items are opaque to the batcher except for two attributes:
    ``enq``, the :func:`faults.monotonic` enqueue stamp the batching
    deadline is measured from, and (optionally) ``deadline``, the
    request's absolute end-to-end deadline on the same clock (None =
    none) — the server's pending-request record carries both.
    ``on_expired`` receives lists of expired items as
    :meth:`next_batch` sheds them (called with the batcher lock held;
    it must answer tickets/release admission, never call back into
    the batcher).
    """

    def __init__(self, max_batch: int | None = None,
                 max_wait_s: float | None = None,
                 on_expired=None):
        env_b, env_w = env_policy()
        self.max_batch = int(max_batch) if max_batch else env_b
        self.max_wait_s = (float(max_wait_s) if max_wait_s is not None
                           else env_w)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_s < 0:
            raise ValueError("max_wait_s must be >= 0")
        self._on_expired = on_expired
        self._cond = threading.Condition()
        self._buckets: "collections.OrderedDict[object, collections.deque]" \
            = collections.OrderedDict()
        self._closed = False
        # any queued item carrying a request deadline?  Deadline-free
        # traffic must not pay the expiry sweep per wakeup.
        self._deadlines_queued = 0

    # -- producer side -----------------------------------------------------

    def put(self, key, item) -> None:
        """Queue ``item`` under shape-class ``key``; wakes a worker.
        Raises RuntimeError once closed (the server translates that
        into a typed shutdown answer).  NB for trace-minded callers:
        any request-trace edge for the enqueue must be recorded
        BEFORE calling this — once the item is queued a worker may
        form its batch concurrently, and a post-put edge would race
        the worker's ``batch_formed`` edge out of causal order."""
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            q = self._buckets.get(key)
            if q is None:
                q = self._buckets[key] = collections.deque()
            q.append(item)
            if getattr(item, "deadline", None) is not None:
                self._deadlines_queued += 1
            self._cond.notify()

    def close(self) -> None:
        """Stop accepting; every queued request becomes immediately
        ready (drain) and workers unblock."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- worker side -------------------------------------------------------

    @staticmethod
    def _expired(item, now: float) -> bool:
        dl = getattr(item, "deadline", None)
        return dl is not None and now >= dl

    def _shed_expired(self, now: float) -> list:
        """Pop every already-expired item (head-of-line AND mid-bucket
        — a batch must never carry stale work) under the lock; empty
        buckets vanish so readiness re-evaluates cleanly."""
        expired = []
        for key in list(self._buckets):
            q = self._buckets[key]
            live = collections.deque()
            for it in q:
                (expired if self._expired(it, now)
                 else live).append(it)
            if len(live) != len(q):
                if live:
                    self._buckets[key] = live
                else:
                    del self._buckets[key]
        self._deadlines_queued -= len(expired)
        return expired

    def _ready_key(self, now: float):
        """The ready bucket with the oldest head (fairness), or None.
        Ready = full, past its head's deadline, or draining."""
        best, best_enq = None, None
        for key, q in self._buckets.items():
            head_enq = q[0].enq
            ready = (self._closed or len(q) >= self.max_batch
                     or now - head_enq >= self.max_wait_s)
            if ready and (best is None or head_enq < best_enq):
                best, best_enq = key, head_enq
        return best

    def _next_deadline(self, now: float) -> float | None:
        """Seconds until the earliest deadline — a head's batching
        wait, or ANY queued item's request deadline (a mid-bucket
        request can expire before every head's wait, and its typed
        answer must not stall until the next put); None = no queued
        work, wait for a put."""
        soonest = None
        for q in self._buckets.values():
            remaining = q[0].enq + self.max_wait_s - now
            if self._deadlines_queued:
                for it in q:
                    dl = getattr(it, "deadline", None)
                    if dl is not None:
                        remaining = min(remaining, dl - now)
            if soonest is None or remaining < soonest:
                soonest = remaining
        return soonest

    def next_batch(self):
        """Block until one shape class is ready; returns ``(key,
        [items...])`` (FIFO within the class, at most ``max_batch``),
        or None when closed and fully drained.  Expired items are shed
        to ``on_expired`` first — never returned in a batch."""
        with self._cond:
            while True:
                now = faults.monotonic()
                if self._deadlines_queued:
                    expired = self._shed_expired(now)
                    if expired and self._on_expired is not None:
                        self._on_expired(expired)
                key = self._ready_key(now)
                if key is not None:
                    q = self._buckets[key]
                    take = min(self.max_batch, len(q))
                    batch = [q.popleft() for _ in range(take)]
                    if not q:
                        del self._buckets[key]
                    self._deadlines_queued -= sum(
                        1 for it in batch
                        if getattr(it, "deadline", None) is not None)
                    return key, batch
                if self._closed and not self._buckets:
                    return None
                wait = self._next_deadline(now)
                if wait is not None:
                    wait = max(wait, _MIN_WAIT_S)
                self._cond.wait(wait)

    def take_refill(self, key, limit: int, now: float | None = None) -> list:
        """Continuous-batching refill: pop up to ``limit`` queued items
        of shape class ``key`` *right now*, without waiting for the
        class to become ready.  A worker that just formed a batch whose
        row count sits below its pow-of-two row class calls this to
        fill the otherwise-zero-padded row slots — the refilled
        requests ride a dispatch that was happening anyway, so they
        skip their remaining batching wait entirely (the Orca-style
        slot-refill trick applied at dispatch grain).

        Expired items encountered while refilling are shed to
        ``on_expired`` exactly as :meth:`next_batch` would shed them
        (with the lock held) — a refill must never smuggle stale work
        onto the device.  Returns the taken items in FIFO order
        (possibly empty)."""
        if limit <= 0:
            return []
        with self._cond:
            q = self._buckets.get(key)
            if not q:
                return []
            if now is None:
                now = faults.monotonic()
            taken, expired = [], []
            while q and len(taken) < limit:
                it = q.popleft()
                (expired if self._expired(it, now) else taken).append(it)
            if not q:
                del self._buckets[key]
            self._deadlines_queued -= sum(
                1 for it in taken + expired
                if getattr(it, "deadline", None) is not None)
            if expired and self._on_expired is not None:
                self._on_expired(expired)
            return taken

    # -- introspection -----------------------------------------------------

    def depth_for(self, key) -> int:
        """Requests currently queued under one shape class — the
        **open-batch occupancy** the cluster's padding-aware placement
        reads: a nonzero depth means a dispatch here would complete a
        forming batch rather than open a fresh one."""
        with self._cond:
            q = self._buckets.get(key)
            return len(q) if q else 0

    def pending(self) -> int:
        """Requests currently queued across every shape class."""
        with self._cond:
            return sum(len(q) for q in self._buckets.values())

    def snapshot(self) -> dict:
        """JSON-native view: policy knobs + per-class queue lengths."""
        with self._cond:
            return {"max_batch": self.max_batch,
                    "max_wait_s": self.max_wait_s,
                    "closed": self._closed,
                    "pending": sum(len(q)
                                   for q in self._buckets.values()),
                    "classes": {repr(k): len(q)
                                for k, q in self._buckets.items()}}
