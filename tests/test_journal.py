"""History-axis tests (obs v6): the durable journal + incident engine.

Contracts pinned here:

* journal records are schema-stamped with both clocks, pid, and the
  writer's replica identity, with the event payload isolated under
  ``data`` (a lifecycle event's ``replica=`` subject never clobbers
  the identity stamp), and the journal runs independently of the
  telemetry enable flag;
* a torn tail (replica killed mid-write) is counted, never fatal —
  every parseable record is recovered;
* segments rotate at the size bound and the writer prunes its own
  oldest segments to hold the total-disk budget, never the current
  segment and never another pid's files;
* concurrent dispatch threads racing through the facade interleave
  LINES, never bytes — every record parses, none are lost;
* a subprocess replica inheriting the armed env journals to its own
  per-pid file in the shared pack, and ``read_pack`` merges the fleet
  timeline;
* incident hysteresis: an alternating flap storm never opens; a
  sustained storm opens exactly ONE incident; it closes only after
  the full quiet period, and a re-fire resets the quiet counter;
* the ``/signals`` / ``/debug/requests`` / ``/incidents`` bodies are
  schema-stamped and carry the history-axis fields.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from veles.simd_tpu import obs, serve  # noqa: E402
from veles.simd_tpu.obs import http as obs_http  # noqa: E402
from veles.simd_tpu.obs import incidents as obs_incidents  # noqa: E402
from veles.simd_tpu.obs import journal as obs_journal  # noqa: E402
from veles.simd_tpu.obs import timeseries as obs_ts  # noqa: E402
from veles.simd_tpu.ops import iir  # noqa: E402

RNG = np.random.RandomState(11)
SOS = iir.butterworth(4, 0.25, "lowpass")


@pytest.fixture
def pack(tmp_path, monkeypatch):
    """A fresh armed journal pack, fully disarmed afterwards."""
    monkeypatch.delenv(obs_journal.JOURNAL_DIR_ENV, raising=False)
    obs_journal._reset_for_tests()
    obs_incidents._reset_for_tests()
    obs.configure(journal_dir=str(tmp_path))
    yield str(tmp_path)
    obs.configure(journal_dir="")
    obs_journal._reset_for_tests()
    obs_incidents._reset_for_tests()
    obs.reset()


# ---------------------------------------------------------------------------
# record schema / arming
# ---------------------------------------------------------------------------

class TestJournalRecords:
    def test_stamped_with_payload_isolated(self, pack):
        obs_journal.set_replica("writer-a")
        obs.record_decision("replica_lifecycle", "kill", replica="r0")
        records, skipped = obs_journal.read_pack(pack)
        assert skipped == 0 and len(records) == 1
        r = records[0]
        assert r["schema"] == obs_journal.SCHEMA
        assert r["kind"] == "decision"
        assert r["op"] == "replica_lifecycle"
        assert r["decision"] == "kill"
        assert r["pid"] == os.getpid()
        assert r["seq"] == 1
        assert r["t_mono"] > 0 and r["t_wall"] > 0
        # the event's subject lands under data; the writer identity
        # stamp survives beside it
        assert r["replica"] == "writer-a"
        assert r["data"]["replica"] == "r0"

    def test_journal_independent_of_telemetry_enable(self, pack):
        obs.disable()
        obs.record_decision("breaker_transition", "open",
                            site="serve.dispatch")
        records, _ = obs_journal.read_pack(pack)
        assert [r["decision"] for r in records] == ["open"]

    def test_disarmed_by_default(self, tmp_path, monkeypatch):
        monkeypatch.delenv(obs_journal.JOURNAL_DIR_ENV, raising=False)
        obs_journal._reset_for_tests()
        obs.configure(journal_dir="")
        assert not obs_journal.armed()
        assert obs_journal.emit_decision("x", "y", {}) is False
        assert obs.journal_stats()["armed"] is False
        assert obs.journal_cursor() is None

    def test_env_arms(self, tmp_path, monkeypatch):
        obs_journal._reset_for_tests()
        obs.configure(journal_dir="")
        monkeypatch.setenv(obs_journal.JOURNAL_DIR_ENV, str(tmp_path))
        try:
            assert obs_journal.armed()
            obs_journal.emit("chaos_phase", {"phase": "baseline"})
            records, _ = obs_journal.read_pack(str(tmp_path))
            assert records[0]["kind"] == "chaos_phase"
            assert records[0]["data"]["phase"] == "baseline"
        finally:
            monkeypatch.delenv(obs_journal.JOURNAL_DIR_ENV)
            obs_journal._reset_for_tests()

    def test_write_failure_is_counted_drop(self, tmp_path, monkeypatch):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("occupied")
        obs_journal._reset_for_tests()
        obs.configure(journal_dir=str(blocker))
        try:
            assert obs_journal.emit("decision", {}) is False
            assert obs.journal_stats()["dropped"] == 1
        finally:
            obs.configure(journal_dir="")
            obs_journal._reset_for_tests()


# ---------------------------------------------------------------------------
# torn tails / rotation / disk budget
# ---------------------------------------------------------------------------

class TestJournalDurability:
    def test_torn_tail_counted_not_fatal(self, pack):
        for i in range(5):
            obs_journal.emit("decision", {"i": i})
        current = os.path.join(pack, obs.journal_cursor()["file"])
        with open(current, "ab") as f:
            f.write(b'{"schema": "veles-simd-journal-v1", "tru')
        records, skipped = obs_journal.read_file(current)
        assert len(records) == 5
        assert skipped == 1
        assert [r["data"]["i"] for r in records] == list(range(5))

    def test_rotation_under_total_disk_bound(self, tmp_path):
        w = obs_journal.JournalWriter(str(tmp_path), max_bytes=512,
                                      max_total_bytes=2048)
        payload = {"filler": "x" * 64}
        for _ in range(200):
            assert w.append({"kind": "decision", "data": payload})
        stats = w.stats()
        assert stats["rotations"] > 0
        assert stats["pruned"] > 0
        assert stats["dropped"] == 0
        own = [tmp_path / n for n in os.listdir(tmp_path)]
        total = sum(p.stat().st_size for p in own)
        # prune runs at rotation: between rotations the pack can
        # overshoot by at most one segment
        assert total <= 2048 + 512
        # the current segment is never pruned
        assert os.path.basename(w.current_file) in \
            {p.name for p in own}
        w.close()
        # every surviving record still parses
        records, skipped = obs_journal.read_pack(str(tmp_path))
        assert skipped == 0 and len(records) > 0

    def test_reconfigured_writer_never_clobbers_own_past(self, pack):
        obs_journal.emit("decision", {"run": 1})
        first = obs.journal_cursor()["segment"]
        # disarm and re-arm the same pack: the fresh writer must
        # continue PAST its old segment, not overwrite it
        obs.configure(journal_dir="")
        obs.configure(journal_dir=pack)
        obs_journal.emit("decision", {"run": 2})
        assert obs.journal_cursor()["segment"] > first
        records, _ = obs_journal.read_pack(pack)
        assert [r["data"]["run"] for r in records] == [1, 2]

    def test_concurrent_writers_interleave_lines(self, pack):
        threads, per = 8, 50
        barrier = threading.Barrier(threads)

        def race(tid):
            barrier.wait()
            for i in range(per):
                obs_journal.emit_decision(
                    "fault_policy", "retry",
                    {"tid": tid, "i": i, "pad": "y" * 32})

        ts = [threading.Thread(target=race, args=(t,))
              for t in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        records, skipped = obs_journal.read_pack(pack)
        assert skipped == 0
        assert len(records) == threads * per
        # per-process seq is a total order with no duplicates
        seqs = sorted(r["seq"] for r in records)
        assert seqs == list(range(1, threads * per + 1))

    def test_subprocess_replica_journals_own_file(self, pack):
        obs_journal.set_replica("router")
        obs_journal.emit_decision("replica_lifecycle", "kill",
                                  {"replica": "r9"})
        child = (
            "from veles.simd_tpu.obs import journal\n"
            "journal.set_replica('child-r9')\n"
            "journal.emit_decision('serve_lifecycle', 'start',"
            " {'workers': 1})\n"
            "journal.emit_decision('serve_lifecycle', 'stop',"
            " {'drain': True})\n"
        )
        env = dict(os.environ)
        env[obs_journal.JOURNAL_DIR_ENV] = pack
        env.setdefault("JAX_PLATFORMS", "cpu")
        subprocess.run([sys.executable, "-c", child], check=True,
                       env=env, cwd=str(REPO), timeout=120)
        files = obs_journal.discover(pack)
        pids = {int(os.path.basename(f).split("-")[1]) for f in files}
        assert os.getpid() in pids and len(pids) == 2
        records, skipped = obs_journal.read_pack(pack)
        assert skipped == 0
        by_replica = {r["replica"] for r in records}
        assert by_replica == {"router", "child-r9"}
        child_ops = [r["op"] for r in records
                     if r["replica"] == "child-r9"]
        assert child_ops == ["serve_lifecycle", "serve_lifecycle"]


# ---------------------------------------------------------------------------
# dead-pid segment reclamation (the group owner's prune_foreign sweep)
# ---------------------------------------------------------------------------

def _dead_pid() -> int:
    """A pid that is definitely gone: a just-exited child."""
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    return p.pid


def _plant_segment(pack, pid, seq, size, mtime=None) -> Path:
    path = Path(pack) / ("journal-%d-%06d.jsonl" % (pid, seq))
    path.write_bytes(b"x" * size)
    if mtime is not None:
        os.utime(path, (mtime, mtime))
    return path


class TestPruneForeign:
    def test_reclaims_dead_pid_oldest_first(self, pack):
        obs_journal.emit("decision", {"mine": True})
        own = os.path.basename(obs.journal_cursor()["file"])
        dead = _dead_pid()
        old = _plant_segment(pack, dead, 1, 600, mtime=1_000)
        new = _plant_segment(pack, dead, 2, 600, mtime=2_000)
        # budget admits ONE of the two dead segments: only the
        # oldest-by-mtime goes
        own_size = (Path(pack) / own).stat().st_size
        pruned = obs_journal.prune_foreign(
            directory=pack, max_total_bytes=own_size + 700)
        assert pruned == 1
        assert not old.exists() and new.exists()
        assert (Path(pack) / own).exists()
        assert obs.journal_stats()["pruned_foreign"] == 1

    def test_under_budget_is_a_noop(self, pack):
        dead = _dead_pid()
        seg = _plant_segment(pack, dead, 1, 100)
        assert obs_journal.prune_foreign(
            directory=pack, max_total_bytes=1 << 20) == 0
        assert seg.exists()
        assert obs.journal_stats()["pruned_foreign"] == 0

    def test_live_pids_are_protected(self, pack):
        """Neither an explicitly-protected pid, a signal-0-alive pid,
        nor this process's own files are ever reclaimed — even when
        the pack stays over budget because of them."""
        child = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        try:
            probed = _plant_segment(pack, child.pid, 1, 500)
            listed = _plant_segment(pack, _dead_pid(), 1, 500)
            mine = _plant_segment(pack, os.getpid(), 7, 500)
            pruned = obs_journal.prune_foreign(
                directory=pack, max_total_bytes=1,
                live_pids=[listed.name.split("-")[1]])
            assert pruned == 0
            assert probed.exists() and listed.exists() \
                and mine.exists()
        finally:
            child.kill()
            child.wait()

    def test_collector_sweep_prunes_and_counts(self, pack):
        """The ReplicaGroup collector's every-64th sweep reclaims
        dead-pid segments and bumps the journal_pruned_foreign
        counter."""
        from veles.simd_tpu.serve import cluster

        obs.enable(compile_listeners=False)
        dead = _dead_pid()
        try:
            with cluster.ReplicaGroup(1, max_wait_ms=2.0,
                                      obs_port=-1) as group:
                budget = obs_journal._env_int(
                    obs_journal.MAX_TOTAL_BYTES_ENV,
                    obs_journal.DEFAULT_MAX_TOTAL_BYTES)
                doomed = _plant_segment(pack, dead, 1, budget + 1024)
                group._sweeps = 63
                group._collect_fleet_sample()
                assert not doomed.exists()
                assert obs.counter_value(
                    "journal_pruned_foreign") >= 1
        finally:
            obs.disable()
            obs.reset()


# ---------------------------------------------------------------------------
# incident hysteresis
# ---------------------------------------------------------------------------

class _FakeSignals:
    """Duck-typed FleetSignals: only what the rules read."""

    def __init__(self, at_s, flaps=0, health="healthy"):
        self.at_s = at_s
        self.slo_burn = {}
        self.slo_burn_velocity = {}
        self.breaker_flaps = {"r0": flaps}
        self.breaker_open = {}
        self.goodput_overall = 1.0
        self.health = {"r0": health}
        self.queue_depth_total = 0.0


class TestIncidentHysteresis:
    def _engine(self):
        return obs_incidents.IncidentEngine(open_ticks=2,
                                            close_ticks=5, flaps=4)

    def test_alternating_flaps_never_open(self):
        eng = self._engine()
        for t in range(20):
            eng.tick(_FakeSignals(float(t), flaps=8 if t % 2 else 0))
        assert eng.open_incidents() == []
        assert eng.incidents() == []

    def test_storm_opens_exactly_one(self, pack):
        eng = self._engine()
        for t in range(10):
            eng.tick(_FakeSignals(float(t), flaps=9))
        open_now = eng.open_incidents()
        assert len(open_now) == 1
        inc = open_now[0]
        assert inc.rule == "breaker_flap"
        assert inc.state == "open"
        assert inc.trigger["replicas"] == {"r0": 9}
        # the open tick was the SECOND firing tick, and the storm
        # kept riding the one incident instead of minting more
        assert inc.ticks_firing == 9
        # the open edge snapshotted where the journal was
        assert inc.journal_cursor is None or \
            "file" in inc.journal_cursor

    def test_close_only_after_full_quiet_period(self):
        eng = self._engine()
        t = 0
        for _ in range(3):
            eng.tick(_FakeSignals(float(t), flaps=9))
            t += 1
        for _ in range(4):      # one short of close_ticks
            eng.tick(_FakeSignals(float(t), flaps=0))
            t += 1
        assert len(eng.open_incidents()) == 1
        eng.tick(_FakeSignals(float(t), flaps=0))
        assert eng.open_incidents() == []
        closed = eng.incidents()
        assert len(closed) == 1
        assert closed[0].state == "closed"
        assert closed[0].close_reason == "quiet_period"

    def test_refire_resets_quiet_counter(self):
        eng = self._engine()
        t = 0
        for _ in range(2):
            eng.tick(_FakeSignals(float(t), flaps=9))
            t += 1
        for _ in range(4):
            eng.tick(_FakeSignals(float(t), flaps=0))
            t += 1
        # a single re-fire mid-quiet: the quiet clock starts over
        eng.tick(_FakeSignals(float(t), flaps=9))
        t += 1
        for _ in range(4):
            eng.tick(_FakeSignals(float(t), flaps=0))
            t += 1
        assert len(eng.open_incidents()) == 1
        eng.tick(_FakeSignals(float(t), flaps=0))
        assert eng.open_incidents() == []

    def test_open_with_flight_recorder_does_not_deadlock(
            self, pack, tmp_path):
        """Regression: opening an incident arms a flight bundle whose
        body embeds obs.snapshot() -> incidents.snapshot(), which
        reads the PROCESS engine back.  With the flight dir armed,
        the open path must not tick while holding the engine lock —
        that deadlocked the ticker (and wedged every /signals and
        /incidents read) permanently."""
        from veles.simd_tpu.obs import flightrec

        fdir = tmp_path / "flight"
        flightrec.configure_flight_dir(str(fdir))
        flightrec._reset_auto_count()
        eng = obs_incidents.engine()    # the process-wide engine
        try:
            done = threading.Event()

            def _drive():
                for t in range(eng.open_ticks + 1):
                    eng.tick(_FakeSignals(float(t), health="down"))
                done.set()

            worker = threading.Thread(target=_drive, daemon=True)
            worker.start()
            assert done.wait(timeout=30.0), \
                "incident open deadlocked against its own engine lock"
            open_now = eng.open_incidents()
            assert len(open_now) == 1
            inc = open_now[0]
            # the bundle was written, and — because the engine lock is
            # released during capture — it embeds the open incident
            assert inc.bundle is not None
            body = json.loads(Path(inc.bundle).read_text())
            embedded = body["snapshot"]["incidents"]["incidents"]
            assert any(i["id"] == inc.id for i in embedded)
        finally:
            flightrec.configure_flight_dir(None)
            flightrec._reset_auto_count()

    def test_module_reset_clears_ledger(self, pack):
        """A new journal epoch (chaos arming a fresh pack) resets the
        process engine: closed incidents from an earlier epoch must
        not satisfy a later campaign's close-wait, and leftover
        streaks must not skew its hysteresis."""
        eng = obs_incidents.engine()
        t = 0
        for _ in range(3):
            eng.tick(_FakeSignals(float(t), health="down"))
            t += 1
        for _ in range(6):
            eng.tick(_FakeSignals(float(t)))
            t += 1
        assert len(eng.incidents()) == 1        # closed, in the ledger
        eng.tick(_FakeSignals(float(t), health="down"))
        obs_incidents.reset()
        assert eng.incidents() == []
        assert eng._streak["replica_down"] == 0

    def test_start_stop_reference_counted(self, pack):
        """Two holders (two ReplicaGroups in one process) — one
        stop() must not halt the other's incident detection."""
        eng = obs_incidents.start(interval_s=0.02)
        obs_incidents.start(interval_s=0.02)
        assert eng._thread is not None and eng._thread.is_alive()
        obs_incidents.stop()            # first holder releases
        assert eng._thread is not None and eng._thread.is_alive()
        obs_incidents.stop()            # last holder releases
        assert eng._thread is None or not eng._thread.is_alive()

    def test_edges_journaled_durably(self, pack):
        eng = self._engine()
        t = 0
        for _ in range(3):
            eng.tick(_FakeSignals(float(t), health="down"))
            t += 1
        for _ in range(5):
            eng.tick(_FakeSignals(float(t), health="healthy"))
            t += 1
        records, _ = obs_journal.read_pack(pack)
        edges = [(r["decision"], r["data"]["rule"]) for r in records
                 if r["op"] == "incident"]
        assert ("open", "replica_down") in edges
        assert ("close", "replica_down") in edges
        opens = [r for r in records if r["op"] == "incident"
                 and r["decision"] == "open"]
        closes = [r for r in records if r["op"] == "incident"
                  and r["decision"] == "close"]
        assert opens[0]["data"]["id"] == closes[0]["data"]["id"]
        assert closes[0]["data"]["reason"] == "quiet_period"


# ---------------------------------------------------------------------------
# schema stamps / the /incidents route / signals fields
# ---------------------------------------------------------------------------

def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestHistorySurfaces:
    def test_signals_carry_incidents_and_journal(self, pack):
        obs.enable()
        obs_journal.emit("decision", {"seed": True})
        sig = obs.signals()
        assert sig.incidents == []
        assert sig.journal["armed"] is True
        assert sig.journal["records"] >= 1
        body = sig.to_dict()
        assert body["schema"] == obs_ts.SIGNALS_SCHEMA
        assert "incidents" in body and "journal" in body

    def test_snapshot_carries_history_keys(self, pack):
        obs.enable()
        snap = obs.snapshot()
        assert snap["journal"]["armed"] is True
        assert snap["incidents"]["schema"] == obs_incidents.SCHEMA

    def test_routes_schema_stamped(self, pack):
        obs.enable()
        with serve.Server(max_batch=2, max_wait_ms=1.0, workers=1,
                          obs_port=0) as srv:
            srv.submit(serve.Request(
                "sosfilt", RNG.randn(500).astype(np.float64),
                {"sos": SOS})).result(timeout=60.0)
            base = f"http://127.0.0.1:{srv.obs_port}"
            code, body = _get(base + "/signals")
            assert code == 200
            assert json.loads(body)["schema"] == obs_ts.SIGNALS_SCHEMA
            code, body = _get(base + "/debug/requests")
            assert code == 200
            assert json.loads(body)["schema"] == \
                obs_http.REQUESTS_SCHEMA
            code, body = _get(base + "/incidents")
            assert code == 200
            inc = json.loads(body)
            assert inc["schema"] == obs_incidents.SCHEMA
            assert inc["open"] == 0 and inc["incidents"] == []
        obs.disable()

    def test_flight_bundle_embeds_journal_tail(self, pack,
                                               tmp_path_factory):
        from veles.simd_tpu.obs import flightrec
        obs.enable()
        obs_journal.emit_decision("serve_health", "degraded",
                                  {"site": "serve.dispatch"})
        bundle = flightrec.build_bundle("test", None)
        info = bundle["journal"]
        assert info["cursor"]["records"] >= 1
        assert info["tail"][-1]["op"] == "serve_health"
        assert info["stats"]["armed"] is True
        obs.disable()
