/* veles_simd.c — embedded-CPython bridge to the veles.simd_tpu XLA core.
 *
 * Architecture (SURVEY.md §7): the TPU compute path lives in Python/JAX;
 * this translation unit provides the reference-compatible C ABI
 * (/root/reference/inc/simd headers) by embedding an interpreter and calling
 * veles/simd_tpu/cshim.py with raw pointers.  Works both as a standalone
 * embedder (C program links libveles_simd.so) and when loaded inside an
 * existing Python process (dlopen from ctypes): PyGILState handles both.
 */

#include "veles_simd.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <Python.h>

static PyObject *g_mod = NULL;        /* veles.simd_tpu.cshim */
static int g_we_initialized = 0;
static char g_last_error[4096] = "";
static char g_backend[64] = "uninitialized";

const char *veles_simd_last_error(void) { return g_last_error; }

static void set_error_from_python(void) {
  PyObject *type = NULL, *value = NULL, *tb = NULL;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != NULL) {
    PyObject *s = PyObject_Str(value);
    if (s != NULL) {
      const char *msg = PyUnicode_AsUTF8(s);
      if (msg != NULL) {
        snprintf(g_last_error, sizeof(g_last_error), "%s", msg);
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

int veles_simd_init(const char *repo_root) {
  if (g_mod != NULL) {
    return 0;
  }
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = 1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  const char *root = repo_root;
  if (root == NULL) {
    root = getenv("VELES_SIMD_PYROOT");
  }
  if (root != NULL) {
    PyObject *sys_path = PySys_GetObject("path"); /* borrowed */
    PyObject *p = sys_path ? PyUnicode_FromString(root) : NULL;
    if (p != NULL) {
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
  }
  g_mod = PyImport_ImportModule("veles.simd_tpu.cshim");
  if (g_mod == NULL) {
    set_error_from_python();
    goto done;
  }
  {
    PyObject *desc = PyObject_CallMethod(g_mod, "backend_description", NULL);
    if (desc != NULL) {
      const char *s = PyUnicode_AsUTF8(desc);
      if (s != NULL) {
        snprintf(g_backend, sizeof(g_backend), "%s", s);
      }
      Py_DECREF(desc);
    } else {
      PyErr_Clear();
    }
  }
  rc = 0;
done:
  PyGILState_Release(gil);
  return rc;
}

void veles_simd_shutdown(void) {
  if (g_mod != NULL) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Py_CLEAR(g_mod);
    PyGILState_Release(gil);
  }
  if (g_we_initialized && Py_IsInitialized()) {
    Py_Finalize();
    g_we_initialized = 0;
  }
}

const char *veles_simd_backend(void) { return g_backend; }

/* Call cshim.<method>(<args per format>).  The returned object is parsed
 * into plain C data by `parse` BEFORE the GIL is released: callers may be
 * foreign threads (ctypes drops the GIL around foreign calls), so no
 * CPython API may touch the result object after PyGILState_Release.
 * Returns 0 when the call and the parse both succeeded. */
typedef int (*shim_parse_fn)(PyObject *result, void *out);

static int shim_call_parse(const char *method, shim_parse_fn parse, void *out,
                           const char *format, ...) {
  if (g_mod == NULL && veles_simd_init(NULL) != 0) {
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *result = NULL;
  int rc = -1;
  va_list va;
  va_start(va, format);
  PyObject *args = Py_VaBuildValue(format, va);
  va_end(va);
  if (args != NULL) {
    PyObject *fn = PyObject_GetAttrString(g_mod, method);
    if (fn != NULL) {
      result = PyObject_CallObject(fn, args);
      Py_DECREF(fn);
    }
    Py_DECREF(args);
  }
  if (result != NULL) {
    rc = parse == NULL ? 0 : parse(result, out);
    Py_DECREF(result);
  }
  if (rc != 0) {
    if (PyErr_Occurred()) {
      set_error_from_python();
    } else {
      /* non-Python failure (e.g. malloc in a parse callback): don't leave
       * a stale unrelated message in g_last_error */
      snprintf(g_last_error, sizeof(g_last_error),
               "%s: result parse failed", method);
    }
  }
  PyGILState_Release(gil);
  return rc;
}

/* Run a void-ish shim method; 0 on success. */
static int shim_run(const char *method, const char *format, ...) {
  if (g_mod == NULL && veles_simd_init(NULL) != 0) {
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  va_list va;
  va_start(va, format);
  PyObject *args = Py_VaBuildValue(format, va);
  va_end(va);
  if (args != NULL) {
    PyObject *fn = PyObject_GetAttrString(g_mod, method);
    if (fn != NULL) {
      PyObject *result = PyObject_CallObject(fn, args);
      if (result != NULL) {
        rc = 0;
        Py_DECREF(result);
      }
      Py_DECREF(fn);
    }
    Py_DECREF(args);
  }
  if (rc != 0) {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

#define PTR(p) ((unsigned long long)(uintptr_t)(p))

/* ---- matrix ----------------------------------------------------------- */

int matrix_add(int simd, const float *m1, const float *m2,
               size_t w, size_t h, float *res) {
  return shim_run("matrix_add", "(iKKKkk)", simd, PTR(m1), PTR(m2), PTR(res),
                  (unsigned long)w, (unsigned long)h);
}

int matrix_sub(int simd, const float *m1, const float *m2,
               size_t w, size_t h, float *res) {
  return shim_run("matrix_sub", "(iKKKkk)", simd, PTR(m1), PTR(m2), PTR(res),
                  (unsigned long)w, (unsigned long)h);
}

int matrix_multiply(int simd, const float *m1, const float *m2,
                    size_t w1, size_t h1, size_t w2, size_t h2, float *res) {
  return shim_run("matrix_multiply", "(iKKKkkkk)", simd, PTR(m1), PTR(m2),
                  PTR(res), (unsigned long)w1, (unsigned long)h1,
                  (unsigned long)w2, (unsigned long)h2);
}

int matrix_multiply_transposed(int simd, const float *m1, const float *m2,
                               size_t w1, size_t h1, size_t w2, size_t h2,
                               float *res) {
  return shim_run("matrix_multiply_transposed", "(iKKKkkkk)", simd, PTR(m1),
                  PTR(m2), PTR(res), (unsigned long)w1, (unsigned long)h1,
                  (unsigned long)w2, (unsigned long)h2);
}

/* ---- convolve / correlate --------------------------------------------- */

struct VelesConvolutionHandle {
  long id;
  size_t x_length;
  size_t h_length;
};

static int parse_long(PyObject *r, void *out) {
  long v = PyLong_AsLong(r);
  if (v == -1 && PyErr_Occurred()) {
    return -1;
  }
  *(long *)out = v;
  return 0;
}

static VelesConvolutionHandle *conv_init(size_t x_length, size_t h_length,
                                         int algorithm, int reverse) {
  long id = 0;
  if (shim_call_parse("convolve_initialize", parse_long, &id, "(kkii)",
                      (unsigned long)x_length, (unsigned long)h_length,
                      algorithm, reverse) != 0 ||
      id <= 0) {
    return NULL;
  }
  VelesConvolutionHandle *handle = malloc(sizeof(*handle));
  if (handle == NULL) {
    return NULL;
  }
  handle->id = id;
  handle->x_length = x_length;
  handle->h_length = h_length;
  return handle;
}

VelesConvolutionHandle *convolve_initialize(size_t x_length, size_t h_length,
                                            int algorithm) {
  return conv_init(x_length, h_length, algorithm, 0);
}

VelesConvolutionHandle *cross_correlate_initialize(size_t x_length,
                                                   size_t h_length,
                                                   int algorithm) {
  return conv_init(x_length, h_length, algorithm, 1);
}

int convolve(VelesConvolutionHandle *handle, const float *x, const float *h,
             float *result) {
  if (handle == NULL) {
    return -1;
  }
  return shim_run("convolve_run", "(lKKK)", handle->id, PTR(x), PTR(h),
                  PTR(result));
}

int cross_correlate(VelesConvolutionHandle *handle, const float *x,
                    const float *h, float *result) {
  return convolve(handle, x, h, result);
}

void convolve_finalize(VelesConvolutionHandle *handle) {
  if (handle != NULL) {
    shim_run("convolve_finalize", "(l)", handle->id);
    free(handle);
  }
}

void cross_correlate_finalize(VelesConvolutionHandle *handle) {
  convolve_finalize(handle);
}

static int conv2d_run(int simd, int reverse, const float *x, size_t n0,
                      size_t n1, const float *h, size_t k0, size_t k1,
                      float *result) {
  return shim_run("convolve2d", "(iiKkkKkkK)", simd, reverse, PTR(x),
                  (unsigned long)n0, (unsigned long)n1, PTR(h),
                  (unsigned long)k0, (unsigned long)k1, PTR(result));
}

int convolve2d(int simd, const float *x, size_t n0, size_t n1,
               const float *h, size_t k0, size_t k1, float *result) {
  return conv2d_run(simd, 0, x, n0, n1, h, k0, k1, result);
}

int convolve2d_mb(int simd, int reverse, const float *x, size_t n0,
                  size_t n1, const float *h, size_t k0, size_t k1,
                  int mode, int boundary, float fillvalue,
                  float *result) {
  return shim_run("convolve2d_mb", "(iiKkkKkkiifK)", simd, reverse,
                  PTR(x), (unsigned long)n0, (unsigned long)n1, PTR(h),
                  (unsigned long)k0, (unsigned long)k1, mode, boundary,
                  (double)fillvalue, PTR(result));
}

int cross_correlate2d(int simd, const float *x, size_t n0, size_t n1,
                      const float *h, size_t k0, size_t k1, float *result) {
  return conv2d_run(simd, 1, x, n0, n1, h, k0, k1, result);
}

struct VelesStreamingConvolution {
  long id;
  size_t h_length;
  size_t chunk_length;
};

VelesStreamingConvolution *streaming_convolve_initialize(
    const float *h, size_t h_length, size_t chunk_length, int reverse,
    int simd) {
  long id = 0;
  if (shim_call_parse("streaming_convolve_initialize", parse_long, &id,
                      "(Kkkii)", PTR(h), (unsigned long)h_length,
                      (unsigned long)chunk_length, reverse, simd) != 0 ||
      id <= 0) {
    return NULL;
  }
  VelesStreamingConvolution *stream = malloc(sizeof(*stream));
  if (stream == NULL) {
    return NULL;
  }
  stream->id = id;
  stream->h_length = h_length;
  stream->chunk_length = chunk_length;
  return stream;
}

int streaming_convolve_process(VelesStreamingConvolution *stream,
                               const float *chunk, float *result) {
  if (stream == NULL) {
    return -1;
  }
  return shim_run("streaming_convolve_process", "(lKK)", stream->id,
                  PTR(chunk), PTR(result));
}

int streaming_convolve_flush(VelesStreamingConvolution *stream,
                             float *tail) {
  if (stream == NULL) {
    return -1;
  }
  return shim_run("streaming_convolve_flush", "(lK)", stream->id,
                  PTR(tail));
}

void streaming_convolve_finalize(VelesStreamingConvolution *stream) {
  if (stream != NULL) {
    shim_run("streaming_convolve_finalize", "(l)", stream->id);
    free(stream);
  }
}

/* Named per-algorithm entry points (inc/simd/convolve.h:58-96,
 * inc/simd/correlate.h:57-105): same registry, forced algorithm. */

VelesConvolutionHandle *convolve_fft_initialize(size_t x_length,
                                                size_t h_length) {
  return conv_init(x_length, h_length, VELES_CONV_ALGORITHM_FFT, 0);
}

int convolve_fft(VelesConvolutionHandle *handle, const float *x,
                 const float *h, float *result) {
  return convolve(handle, x, h, result);
}

void convolve_fft_finalize(VelesConvolutionHandle *handle) {
  convolve_finalize(handle);
}

VelesConvolutionHandle *convolve_overlap_save_initialize(size_t x_length,
                                                         size_t h_length) {
  return conv_init(x_length, h_length, VELES_CONV_ALGORITHM_OVERLAP_SAVE, 0);
}

int convolve_overlap_save(VelesConvolutionHandle *handle, const float *x,
                          const float *h, float *result) {
  return convolve(handle, x, h, result);
}

void convolve_overlap_save_finalize(VelesConvolutionHandle *handle) {
  convolve_finalize(handle);
}

/* Legacy doc-comment name (inc/simd/convolve.h:123-124). */
VelesConvolutionHandle *convolve_overlap_initialize(size_t x_length,
                                                    size_t h_length) {
  return convolve_overlap_save_initialize(x_length, h_length);
}

VelesConvolutionHandle *cross_correlate_fft_initialize(size_t x_length,
                                                       size_t h_length) {
  return conv_init(x_length, h_length, VELES_CONV_ALGORITHM_FFT, 1);
}

int cross_correlate_fft(VelesConvolutionHandle *handle, const float *x,
                        const float *h, float *result) {
  return convolve(handle, x, h, result);
}

void cross_correlate_fft_finalize(VelesConvolutionHandle *handle) {
  convolve_finalize(handle);
}

VelesConvolutionHandle *cross_correlate_overlap_save_initialize(
    size_t x_length, size_t h_length) {
  return conv_init(x_length, h_length, VELES_CONV_ALGORITHM_OVERLAP_SAVE, 1);
}

int cross_correlate_overlap_save(VelesConvolutionHandle *handle,
                                 const float *x, const float *h,
                                 float *result) {
  return convolve(handle, x, h, result);
}

void cross_correlate_overlap_save_finalize(VelesConvolutionHandle *handle) {
  convolve_finalize(handle);
}

/* Legacy doc-comment name (inc/simd/correlate.h:132-134). */
VelesConvolutionHandle *cross_correlate_overlap_initialize(size_t x_length,
                                                           size_t h_length) {
  return cross_correlate_overlap_save_initialize(x_length, h_length);
}

int convolve_simd(int simd, const float *x, size_t x_length,
                  const float *h, size_t h_length, float *result) {
  return shim_run("convolve_simd", "(iKkKkK)", simd, PTR(x),
                  (unsigned long)x_length, PTR(h), (unsigned long)h_length,
                  PTR(result));
}

int cross_correlate_simd(int simd, const float *x, size_t x_length,
                         const float *h, size_t h_length, float *result) {
  return shim_run("cross_correlate_simd", "(iKkKkK)", simd, PTR(x),
                  (unsigned long)x_length, PTR(h), (unsigned long)h_length,
                  PTR(result));
}

/* ---- wavelet ---------------------------------------------------------- */

static int parse_truth(PyObject *r, void *out) {
  int v = PyObject_IsTrue(r);
  if (v < 0) {
    return -1;
  }
  *(int *)out = v;
  return 0;
}

int wavelet_validate_order(WaveletType type, int order) {
  int valid = 0;
  if (shim_call_parse("wavelet_validate_order", parse_truth, &valid, "(ii)",
                      (int)type, order) != 0) {
    return 0;
  }
  return valid == 1;
}

int wavelet_apply(int simd, WaveletType type, int order, ExtensionType ext,
                  const float *src, size_t length,
                  float *desthi, float *destlo) {
  return shim_run("wavelet_apply", "(iiiiKkKK)", simd, (int)type, order,
                  (int)ext, PTR(src), (unsigned long)length, PTR(desthi),
                  PTR(destlo));
}

int stationary_wavelet_apply(int simd, WaveletType type, int order, int level,
                             ExtensionType ext, const float *src,
                             size_t length, float *desthi, float *destlo) {
  return shim_run("stationary_wavelet_apply", "(iiiiiKkKK)", simd, (int)type,
                  order, level, (int)ext, PTR(src), (unsigned long)length,
                  PTR(desthi), PTR(destlo));
}

int wavelet_apply_na(WaveletType type, int order, ExtensionType ext,
                     const float *src, size_t length,
                     float *desthi, float *destlo) {
  return wavelet_apply(0, type, order, ext, src, length, desthi, destlo);
}

int stationary_wavelet_apply_na(WaveletType type, int order, int level,
                                ExtensionType ext, const float *src,
                                size_t length, float *desthi,
                                float *destlo) {
  return stationary_wavelet_apply(0, type, order, level, ext, src, length,
                                  desthi, destlo);
}

int wavelet_reconstruct(int simd, WaveletType type, int order,
                        ExtensionType ext, const float *desthi,
                        const float *destlo, size_t length, float *result) {
  return shim_run("wavelet_reconstruct", "(iiiiKKkK)", simd, (int)type,
                  order, (int)ext, PTR(desthi), PTR(destlo),
                  (unsigned long)length, PTR(result));
}

int stationary_wavelet_reconstruct(int simd, WaveletType type, int order,
                                   int level, ExtensionType ext,
                                   const float *desthi, const float *destlo,
                                   size_t length, float *result) {
  return shim_run("stationary_wavelet_reconstruct", "(iiiiiKKkK)", simd,
                  (int)type, order, level, (int)ext, PTR(desthi),
                  PTR(destlo), (unsigned long)length, PTR(result));
}

int wavelet_apply2d(int simd, WaveletType type, int order,
                    ExtensionType ext, const float *src, size_t n0,
                    size_t n1, float *ll, float *lh, float *hl,
                    float *hh) {
  return shim_run("wavelet_apply2d", "(iiiiKkkKKKK)", simd, (int)type,
                  order, (int)ext, PTR(src), (unsigned long)n0,
                  (unsigned long)n1, PTR(ll), PTR(lh), PTR(hl), PTR(hh));
}

int wavelet_reconstruct2d(int simd, WaveletType type, int order,
                          ExtensionType ext, const float *ll,
                          const float *lh, const float *hl,
                          const float *hh, size_t m0, size_t m1,
                          float *result) {
  return shim_run("wavelet_reconstruct2d", "(iiiiKKKKkkK)", simd,
                  (int)type, order, (int)ext, PTR(ll), PTR(lh), PTR(hl),
                  PTR(hh), (unsigned long)m0, (unsigned long)m1,
                  PTR(result));
}

int stationary_wavelet_apply2d(int simd, WaveletType type, int order,
                               int level, ExtensionType ext,
                               const float *src, size_t n0, size_t n1,
                               float *ll, float *lh, float *hl,
                               float *hh) {
  return shim_run("stationary_wavelet_apply2d", "(iiiiiKkkKKKK)", simd,
                  (int)type, order, level, (int)ext, PTR(src),
                  (unsigned long)n0, (unsigned long)n1, PTR(ll), PTR(lh),
                  PTR(hl), PTR(hh));
}

int stationary_wavelet_reconstruct2d(int simd, WaveletType type, int order,
                                     int level, ExtensionType ext,
                                     const float *ll, const float *lh,
                                     const float *hl, const float *hh,
                                     size_t m0, size_t m1, float *result) {
  return shim_run("stationary_wavelet_reconstruct2d", "(iiiiiKKKKkkK)",
                  simd, (int)type, order, level, (int)ext, PTR(ll),
                  PTR(lh), PTR(hl), PTR(hh), (unsigned long)m0,
                  (unsigned long)m1, PTR(result));
}

int wavelet_packet_transform(int simd, WaveletType type, int order,
                             ExtensionType ext, const float *src,
                             size_t length, int levels, float *leaves) {
  return shim_run("wavelet_packet_transform", "(iiiiKkiK)", simd,
                  (int)type, order, (int)ext, PTR(src),
                  (unsigned long)length, levels, PTR(leaves));
}

int wavelet_packet_inverse_transform(int simd, WaveletType type, int order,
                                     ExtensionType ext, const float *leaves,
                                     size_t length, int levels,
                                     float *result) {
  return shim_run("wavelet_packet_inverse_transform", "(iiiiKkiK)", simd,
                  (int)type, order, (int)ext, PTR(leaves),
                  (unsigned long)length, levels, PTR(result));
}

int wavelet_packet_transform2d(int simd, WaveletType type, int order,
                               ExtensionType ext, const float *src,
                               size_t m0, size_t m1, int levels,
                               float *leaves) {
  return shim_run("wavelet_packet_transform2d", "(iiiiKkkiK)", simd,
                  (int)type, order, (int)ext, PTR(src),
                  (unsigned long)m0, (unsigned long)m1, levels,
                  PTR(leaves));
}

int wavelet_packet_inverse_transform2d(int simd, WaveletType type,
                                       int order, ExtensionType ext,
                                       const float *leaves, size_t m0,
                                       size_t m1, int levels,
                                       float *result) {
  return shim_run("wavelet_packet_inverse_transform2d", "(iiiiKkkiK)",
                  simd, (int)type, order, (int)ext, PTR(leaves),
                  (unsigned long)m0, (unsigned long)m1, levels,
                  PTR(result));
}

/* ---- mathfun ---------------------------------------------------------- */

static int psv(const char *name, int simd, const float *src, size_t length,
               float *res) {
  return shim_run("mathfun", "(siKkK)", name, simd, PTR(src),
                  (unsigned long)length, PTR(res));
}

int sin_psv(int simd, const float *src, size_t length, float *res) {
  return psv("sin", simd, src, length, res);
}
int cos_psv(int simd, const float *src, size_t length, float *res) {
  return psv("cos", simd, src, length, res);
}
int log_psv(int simd, const float *src, size_t length, float *res) {
  return psv("log", simd, src, length, res);
}
int exp_psv(int simd, const float *src, size_t length, float *res) {
  return psv("exp", simd, src, length, res);
}
int sqrt_psv(int simd, const float *src, size_t length, float *res) {
  return psv("sqrt", simd, src, length, res);
}

int pow_psv(int simd, const float *base, const float *exponent,
            size_t length, float *res) {
  return shim_run("pow_psv", "(iKKkK)", simd, PTR(base), PTR(exponent),
                  (unsigned long)length, PTR(res));
}

/* ---- correlate extras ------------------------------------------------- */

size_t correlation_lags_length(size_t in_len, size_t in2_len,
                               VelesCorrMode mode) {
  size_t lo = in_len < in2_len ? in_len : in2_len;
  size_t hi = in_len < in2_len ? in2_len : in_len;
  if (lo == 0) return 0; /* empty input: no lags (avoids 0+0-1 wrap) */
  switch (mode) {
    case VELES_MODE_FULL: return in_len + in2_len - 1;
    case VELES_MODE_SAME: return hi;
    case VELES_MODE_VALID: return hi - lo + 1;
  }
  return 0;
}

int correlation_lags(size_t in_len, size_t in2_len, VelesCorrMode mode,
                     long *lags) {
  return shim_run("correlation_lags", "(kkiK)", (unsigned long)in_len,
                  (unsigned long)in2_len, (int)mode, PTR(lags));
}

int deconvolve(const double *signal, size_t sig_len,
               const double *divisor, size_t div_len,
               double *quotient, double *remainder) {
  return shim_run("deconvolve", "(KkKkKK)", PTR(signal),
                  (unsigned long)sig_len, PTR(divisor),
                  (unsigned long)div_len, PTR(quotient), PTR(remainder));
}

/* ---- waveforms -------------------------------------------------------- */

int wave_chirp(int simd, const float *t, size_t length, double f0,
               double t1, double f1, VelesChirpMethod method, double phi,
               float *result) {
  return shim_run("wave_chirp", "(iKkdddidK)", simd, PTR(t),
                  (unsigned long)length, f0, t1, f1, (int)method, phi,
                  PTR(result));
}

int wave_square(int simd, const float *t, size_t length, double duty,
                float *result) {
  return shim_run("wave_square", "(iKkdK)", simd, PTR(t),
                  (unsigned long)length, duty, PTR(result));
}

int wave_sawtooth(int simd, const float *t, size_t length, double width,
                  float *result) {
  return shim_run("wave_sawtooth", "(iKkdK)", simd, PTR(t),
                  (unsigned long)length, width, PTR(result));
}

int wave_gausspulse(int simd, const float *t, size_t length, double fc,
                    double bw, double bwr, float *result) {
  return shim_run("wave_gausspulse", "(iKkdddK)", simd, PTR(t),
                  (unsigned long)length, fc, bw, bwr, PTR(result));
}

int wave_unit_impulse(int simd, size_t n, size_t idx, float *result) {
  return shim_run("wave_unit_impulse", "(ikkK)", simd, (unsigned long)n,
                  (unsigned long)idx, PTR(result));
}

int wave_max_len_seq(int nbits, uint8_t *state_io, size_t length,
                     uint8_t *seq) {
  return shim_run("wave_max_len_seq", "(iKkK)", nbits, PTR(state_io),
                  (unsigned long)length, PTR(seq));
}

int wave_get_window(VelesWindowKind window, size_t n, double beta,
                    double *result) {
  return shim_run("wave_get_window", "(ikdK)", (int)window,
                  (unsigned long)n, beta, PTR(result));
}

/* ---- spectral --------------------------------------------------------- */

size_t stft_frame_count(size_t length, size_t frame_length, size_t hop) {
  if (frame_length == 0 || hop == 0 || length < frame_length) {
    return 0;
  }
  return 1 + (length - frame_length) / hop;
}

int stft(int simd, const float *x, size_t length, size_t frame_length,
         size_t hop, const float *window, float *spec) {
  return shim_run("stft", "(iKkkkKK)", simd, PTR(x), (unsigned long)length,
                  (unsigned long)frame_length, (unsigned long)hop,
                  PTR(window), PTR(spec));
}

int istft(int simd, const float *spec, size_t length, size_t frame_length,
          size_t hop, const float *window, float *result) {
  return shim_run("istft", "(iKkkkKK)", simd, PTR(spec),
                  (unsigned long)length, (unsigned long)frame_length,
                  (unsigned long)hop, PTR(window), PTR(result));
}

int spectrogram(int simd, const float *x, size_t length,
                size_t frame_length, size_t hop, const float *window,
                float *power) {
  return shim_run("spectrogram", "(iKkkkKK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)frame_length,
                  (unsigned long)hop, PTR(window), PTR(power));
}

int hilbert(int simd, const float *x, size_t length, float *analytic) {
  return shim_run("hilbert", "(iKkK)", simd, PTR(x), (unsigned long)length,
                  PTR(analytic));
}

int envelope(int simd, const float *x, size_t length, float *env) {
  return shim_run("envelope", "(iKkK)", simd, PTR(x), (unsigned long)length,
                  PTR(env));
}

int morlet_cwt(int simd, const float *x, size_t length,
               const double *scales, size_t n_scales, double w0,
               float *result) {
  return shim_run("morlet_cwt", "(iKkKkdK)", simd, PTR(x),
                  (unsigned long)length, PTR(scales),
                  (unsigned long)n_scales, w0, PTR(result));
}

/* ---- resample --------------------------------------------------------- */

size_t resample_length(size_t length, size_t up, size_t down) {
  if (up == 0 || down == 0) {
    return 0;
  }
  return (length * up + down - 1) / down;
}

size_t upfirdn_length(size_t length, size_t h_len, size_t up,
                      size_t down) {
  if (length == 0 || h_len == 0 || up == 0 || down == 0) {
    return 0;
  }
  return ((length - 1) * up + h_len - 1) / down + 1;
}

int upfirdn(int simd, const double *h, size_t h_len, const float *x,
            size_t length, size_t up, size_t down, float *result) {
  return shim_run("upfirdn", "(iKkKkkkK)", simd, PTR(h),
                  (unsigned long)h_len, PTR(x), (unsigned long)length,
                  (unsigned long)up, (unsigned long)down, PTR(result));
}

int spectral_czt(int simd, const float *x, size_t length, size_t m,
                 double w_re, double w_im, double a_re, double a_im,
                 float *result) {
  return shim_run("spectral_czt", "(iKkkddddK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)m, w_re, w_im,
                  a_re, a_im, PTR(result));
}

int spectral_zoom_fft(int simd, const float *x, size_t length, double f1,
                      double f2, size_t m, double fs, double *freqs,
                      float *result) {
  return shim_run("spectral_zoom_fft", "(iKkddkdKK)", simd, PTR(x),
                  (unsigned long)length, f1, f2, (unsigned long)m, fs,
                  PTR(freqs), PTR(result));
}

int spectral_lombscargle(int simd, const double *t, const float *x,
                         size_t length, const double *freqs,
                         size_t n_freqs, float *power) {
  return shim_run("spectral_lombscargle", "(iKKkKkK)", simd, PTR(t),
                  PTR(x), (unsigned long)length, PTR(freqs),
                  (unsigned long)n_freqs, PTR(power));
}

size_t welch_bins(size_t length, size_t nperseg) {
  size_t seg = nperseg < length ? nperseg : length;
  return seg / 2 + 1;
}

int spectral_detrend(int simd, const float *x, size_t length, int kind,
                     float *result) {
  return shim_run("spectral_detrend", "(iKkiK)", simd, PTR(x),
                  (unsigned long)length, kind, PTR(result));
}

int spectral_welch(int simd, const float *x, size_t length, double fs,
                   size_t nperseg, long noverlap, double *freqs,
                   float *psd) {
  return shim_run("spectral_welch", "(iKkdklKK)", simd, PTR(x),
                  (unsigned long)length, fs, (unsigned long)nperseg,
                  noverlap, PTR(freqs), PTR(psd));
}

int spectral_periodogram(int simd, const float *x, size_t length,
                         double fs, double *freqs, float *psd) {
  return shim_run("spectral_periodogram", "(iKkdKK)", simd, PTR(x),
                  (unsigned long)length, fs, PTR(freqs), PTR(psd));
}

int spectral_csd(int simd, const float *x, const float *y, size_t length,
                 double fs, size_t nperseg, long noverlap, double *freqs,
                 float *pxy) {
  return shim_run("spectral_csd", "(iKKkdklKK)", simd, PTR(x), PTR(y),
                  (unsigned long)length, fs, (unsigned long)nperseg,
                  noverlap, PTR(freqs), PTR(pxy));
}

int spectral_coherence(int simd, const float *x, const float *y,
                       size_t length, double fs, size_t nperseg,
                       long noverlap, double *freqs, float *coh) {
  return shim_run("spectral_coherence", "(iKKkdklKK)", simd, PTR(x),
                  PTR(y), (unsigned long)length, fs,
                  (unsigned long)nperseg, noverlap, PTR(freqs),
                  PTR(coh));
}

int resample_poly(int simd, const float *x, size_t length, size_t up,
                  size_t down, const float *taps, size_t num_taps,
                  float *result) {
  return shim_run("resample_poly", "(iKkkkKkK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)up,
                  (unsigned long)down, PTR(taps), (unsigned long)num_taps,
                  PTR(result));
}

int resample_fourier(int simd, const float *x, size_t length, size_t num,
                     float *result) {
  return shim_run("resample_fourier", "(iKkkK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)num, PTR(result));
}

/* ---- iir -------------------------------------------------------------- */

int iir_butterworth(size_t order, double low, double high,
                    VelesIirBandType btype, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_butterworth", parse_long, &sections, "(kddiK)",
                      (unsigned long)order, low, high, (int)btype,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_bessel(size_t order, double low, double high,
               VelesIirBandType btype, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_bessel", parse_long, &sections, "(kddiK)",
                      (unsigned long)order, low, high, (int)btype,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_cheby1(size_t order, double rp, double low, double high,
               VelesIirBandType btype, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_cheby1", parse_long, &sections, "(kdddiK)",
                      (unsigned long)order, rp, low, high, (int)btype,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_cheby2(size_t order, double rs, double low, double high,
               VelesIirBandType btype, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_cheby2", parse_long, &sections, "(kdddiK)",
                      (unsigned long)order, rs, low, high, (int)btype,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_ellip(size_t order, double rp, double rs, double low, double high,
              VelesIirBandType btype, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_ellip", parse_long, &sections, "(kddddiK)",
                      (unsigned long)order, rp, rs, low, high, (int)btype,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

static int iir_ord(const char *method, const double *wp, const double *ws,
                   size_t n_edges, double gpass, double gstop,
                   double *wn_out) {
  long order = -1;
  if (shim_call_parse("iir_ord", parse_long, &order, "(sKKkddK)", method,
                      PTR(wp), PTR(ws), (unsigned long)n_edges, gpass,
                      gstop, PTR(wn_out)) != 0) {
    return -1;
  }
  return (int)order;
}

int iir_buttord(const double *wp, const double *ws, size_t n_edges,
                double gpass, double gstop, double *wn_out) {
  return iir_ord("buttord", wp, ws, n_edges, gpass, gstop, wn_out);
}

int iir_cheb1ord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out) {
  return iir_ord("cheb1ord", wp, ws, n_edges, gpass, gstop, wn_out);
}

int iir_cheb2ord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out) {
  return iir_ord("cheb2ord", wp, ws, n_edges, gpass, gstop, wn_out);
}

int iir_ellipord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out) {
  return iir_ord("ellipord", wp, ws, n_edges, gpass, gstop, wn_out);
}

int iir_notch(double w0, double q, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_notch", parse_long, &sections, "(ddK)", w0, q,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_peak(double w0, double q, double *sos) {
  long sections = -1;
  if (shim_call_parse("iir_peak", parse_long, &sections, "(ddK)", w0, q,
                      PTR(sos)) != 0) {
    return -1;
  }
  return (int)sections;
}

int iir_sosfilt_stream(int simd, const double *sos, size_t n_sections,
                       const float *x, size_t length, double *zi_inout,
                       float *result) {
  return shim_run("iir_sosfilt_stream", "(iKkKkKK)", simd, PTR(sos),
                  (unsigned long)n_sections, PTR(x),
                  (unsigned long)length, PTR(zi_inout), PTR(result));
}

int iir_sosfilt(int simd, const double *sos, size_t n_sections,
                const float *x, size_t length, const double *zi,
                float *result) {
  return shim_run("iir_sosfilt", "(iKkKkKK)", simd, PTR(sos),
                  (unsigned long)n_sections, PTR(x),
                  (unsigned long)length, PTR(zi), PTR(result));
}

int iir_sosfiltfilt(int simd, const double *sos, size_t n_sections,
                    const float *x, size_t length, long padlen,
                    float *result) {
  return shim_run("iir_sosfiltfilt", "(iKkKklK)", simd, PTR(sos),
                  (unsigned long)n_sections, PTR(x),
                  (unsigned long)length, padlen, PTR(result));
}

int iir_sosfilt_zi(const double *sos, size_t n_sections, double *zi_out) {
  return shim_run("iir_sosfilt_zi", "(KkK)", PTR(sos),
                  (unsigned long)n_sections, PTR(zi_out));
}

int iir_lfilter(int simd, const double *b, size_t nb, const double *a,
                size_t na, const float *x, size_t length, float *result) {
  return shim_run("iir_lfilter", "(iKkKkKkK)", simd, PTR(b),
                  (unsigned long)nb, PTR(a), (unsigned long)na, PTR(x),
                  (unsigned long)length, PTR(result));
}

/* ---- filters ---------------------------------------------------------- */

int filt_medfilt(int simd, const float *x, size_t length,
                 size_t kernel_size, float *result) {
  return shim_run("filt_medfilt", "(iKkkK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)kernel_size,
                  PTR(result));
}

int filt_order_filter(int simd, const float *x, size_t length,
                      size_t rank, size_t kernel_size, float *result) {
  return shim_run("filt_order_filter", "(iKkkkK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)rank,
                  (unsigned long)kernel_size, PTR(result));
}

int filt_medfilt2d(int simd, const float *img, size_t height,
                   size_t width, size_t kh, size_t kw, float *result) {
  return shim_run("filt_medfilt2d", "(iKkkkkK)", simd, PTR(img),
                  (unsigned long)height, (unsigned long)width,
                  (unsigned long)kh, (unsigned long)kw, PTR(result));
}

int filt_savgol(int simd, const float *x, size_t length,
                size_t window_length, size_t polyorder, size_t deriv,
                double delta, VelesSavgolMode mode, float *result) {
  return shim_run("filt_savgol", "(iKkkkkdiK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)window_length,
                  (unsigned long)polyorder, (unsigned long)deriv, delta,
                  (int)mode, PTR(result));
}

int filt_wiener(int simd, const float *x, size_t length, size_t mysize,
                double noise, float *result) {
  return shim_run("filt_wiener", "(iKkkdK)", simd, PTR(x),
                  (unsigned long)length, (unsigned long)mysize, noise,
                  PTR(result));
}

int filt_savgol_coeffs(size_t window_length, size_t polyorder,
                       size_t deriv, double delta, double *taps) {
  return shim_run("filt_savgol_coeffs", "(kkkdK)",
                  (unsigned long)window_length,
                  (unsigned long)polyorder, (unsigned long)deriv, delta,
                  PTR(taps));
}

int filt_firwin(size_t numtaps, const double *cutoffs, size_t n_cutoffs,
                int pass_zero, int window, double *taps) {
  return shim_run("filt_firwin", "(kKkiiK)", (unsigned long)numtaps,
                  PTR(cutoffs), (unsigned long)n_cutoffs, pass_zero,
                  window, PTR(taps));
}

int filt_firwin_w(size_t numtaps, const double *cutoffs,
                  size_t n_cutoffs, int pass_zero, int window,
                  double beta, double *taps) {
  return shim_run("filt_firwin_w", "(kKkiidK)", (unsigned long)numtaps,
                  PTR(cutoffs), (unsigned long)n_cutoffs, pass_zero,
                  window, beta, PTR(taps));
}

int filt_kaiserord(double ripple, double width, size_t *numtaps,
                   double *beta) {
  return shim_run("filt_kaiserord", "(ddKK)", ripple, width,
                  PTR(numtaps), PTR(beta));
}

int filt_firwin2(size_t numtaps, const double *freq, const double *gain,
                 size_t n_freq, size_t nfreqs, int window, double *taps) {
  return shim_run("filt_firwin2", "(kKKkkiK)", (unsigned long)numtaps,
                  PTR(freq), PTR(gain), (unsigned long)n_freq,
                  (unsigned long)nfreqs, window, PTR(taps));
}

int filt_remez(size_t numtaps, const double *bands, size_t n_bands,
               const double *desired, const double *weight, double fs,
               double *taps) {
  return shim_run("filt_remez", "(kKkKKdK)", (unsigned long)numtaps,
                  PTR(bands), (unsigned long)n_bands, PTR(desired),
                  PTR(weight), fs, PTR(taps));
}

/* ---- normalize -------------------------------------------------------- */

int normalize2D(int simd, const uint8_t *src, size_t src_stride,
                size_t width, size_t height, float *dst, size_t dst_stride) {
  return shim_run("normalize2D", "(iKkkkKk)", simd, PTR(src),
                  (unsigned long)src_stride, (unsigned long)width,
                  (unsigned long)height, PTR(dst),
                  (unsigned long)dst_stride);
}

static int parse_long_pair(PyObject *r, void *out) {
  long *pair = (long *)out;
  return PyArg_ParseTuple(r, "ll", &pair[0], &pair[1]) ? 0 : -1;
}

static int parse_double_pair(PyObject *r, void *out) {
  double *pair = (double *)out;
  return PyArg_ParseTuple(r, "dd", &pair[0], &pair[1]) ? 0 : -1;
}

int minmax2D(int simd, const uint8_t *src, size_t src_stride,
             size_t width, size_t height, uint8_t *min, uint8_t *max) {
  long pair[2];
  if (shim_call_parse("minmax2D", parse_long_pair, pair, "(iKkkk)", simd,
                      PTR(src), (unsigned long)src_stride,
                      (unsigned long)width, (unsigned long)height) != 0) {
    return -1;
  }
  if (min != NULL) {
    *min = (uint8_t)pair[0];
  }
  if (max != NULL) {
    *max = (uint8_t)pair[1];
  }
  return 0;
}

int minmax1D(int simd, const float *src, size_t length,
             float *min, float *max) {
  double pair[2];
  if (shim_call_parse("minmax1D", parse_double_pair, pair, "(iKk)", simd,
                      PTR(src), (unsigned long)length) != 0) {
    return -1;
  }
  if (min != NULL) {
    *min = (float)pair[0];
  }
  if (max != NULL) {
    *max = (float)pair[1];
  }
  return 0;
}

int normalize2D_minmax(int simd, uint8_t min, uint8_t max,
                       const uint8_t *src, size_t src_stride,
                       size_t width, size_t height,
                       float *dst, size_t dst_stride) {
  return shim_run("normalize2D_minmax", "(iiiKkkkKk)", simd, (int)min,
                  (int)max, PTR(src), (unsigned long)src_stride,
                  (unsigned long)width, (unsigned long)height, PTR(dst),
                  (unsigned long)dst_stride);
}

/* ---- detect_peaks ----------------------------------------------------- */

struct peaks_out {
  ExtremumPoint *pts;
  size_t n;
};

static int parse_peaks(PyObject *r, void *out) {
  struct peaks_out *po = (struct peaks_out *)out;
  PyObject *pos = NULL, *vals = NULL;
  if (!PyArg_ParseTuple(r, "OO", &pos, &vals)) {
    return -1;
  }
  Py_ssize_t n = PyList_Size(pos);
  if (n < 0) {
    return -1;
  }
  if (n == 0) {
    return 0; /* no peaks: NULL + 0, reference behavior */
  }
  ExtremumPoint *pts = malloc((size_t)n * sizeof(*pts));
  if (pts == NULL) {
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    pts[i].position = (int)PyLong_AsLong(PyList_GetItem(pos, i));
    pts[i].value = (float)PyFloat_AsDouble(PyList_GetItem(vals, i));
  }
  if (PyErr_Occurred()) {
    free(pts);
    return -1;
  }
  po->pts = pts;
  po->n = (size_t)n;
  return 0;
}

int detect_peaks(int simd, const float *data, size_t size, ExtremumType type,
                 ExtremumPoint **results, size_t *results_length) {
  if (results == NULL || results_length == NULL) {
    return -1;
  }
  *results = NULL;
  *results_length = 0;
  struct peaks_out po = {NULL, 0};
  if (shim_call_parse("detect_peaks", parse_peaks, &po, "(iKki)", simd,
                      PTR(data), (unsigned long)size, (int)type) != 0) {
    return -1;
  }
  *results = po.pts;
  *results_length = po.n;
  return 0;
}

int peak_prominences(int simd, const float *x, size_t length,
                     const int64_t *peaks, size_t n_peaks,
                     float *prom_out) {
  return shim_run("peak_prominences", "(iKkKkK)", simd, PTR(x),
                  (unsigned long)length, PTR(peaks),
                  (unsigned long)n_peaks, PTR(prom_out));
}

int peak_widths(int simd, const float *x, size_t length,
                const int64_t *peaks, size_t n_peaks, double rel_height,
                float *widths, float *width_heights, float *left_ips,
                float *right_ips) {
  return shim_run("peak_widths", "(iKkKkdKKKK)", simd, PTR(x),
                  (unsigned long)length, PTR(peaks),
                  (unsigned long)n_peaks, rel_height, PTR(widths),
                  PTR(width_heights), PTR(left_ips), PTR(right_ips));
}

long find_peaks(int simd, const float *x, size_t length,
                double height_min, double height_max,
                double threshold_min, double threshold_max,
                size_t distance, double prom_min, double prom_max,
                int64_t *peaks_out, size_t max_out) {
  long count = -1;
  if (shim_call_parse("find_peaks", parse_long, &count, "(iKkddddkddKk)",
                      simd, PTR(x), (unsigned long)length, height_min,
                      height_max, threshold_min, threshold_max,
                      (unsigned long)distance, prom_min, prom_max,
                      PTR(peaks_out), (unsigned long)max_out) != 0) {
    return -1;
  }
  return count;
}

/* ---- conversions ------------------------------------------------------ */

static int convert(const char *name, int simd, const void *src, size_t length,
                   void *dst) {
  return shim_run("convert", "(siKkK)", name, simd, PTR(src),
                  (unsigned long)length, PTR(dst));
}

int int16_to_float(int simd, const int16_t *src, size_t length, float *dst) {
  return convert("int16_to_float", simd, src, length, dst);
}
int float_to_int16(int simd, const float *src, size_t length, int16_t *dst) {
  return convert("float_to_int16", simd, src, length, dst);
}
int int32_to_float(int simd, const int32_t *src, size_t length, float *dst) {
  return convert("int32_to_float", simd, src, length, dst);
}
int float_to_int32(int simd, const float *src, size_t length, int32_t *dst) {
  return convert("float_to_int32", simd, src, length, dst);
}
int int16_to_int32(int simd, const int16_t *src, size_t length,
                   int32_t *dst) {
  return convert("int16_to_int32", simd, src, length, dst);
}
int int32_to_int16(int simd, const int32_t *src, size_t length,
                   int16_t *dst) {
  return convert("int32_to_int16", simd, src, length, dst);
}
int float16_to_float(int simd, const uint16_t *src, size_t length,
                     float *dst) {
  return convert("float16_to_float", simd, src, length, dst);
}
