"""RPC data plane (``veles/simd_tpu/serve/rpc.py``, PR 20).

Pins the wire contract the FrontRouter's subprocess placement rides:

* npy-framed codec round-trips bytes-exact (dtype + shape + payload,
  tuple/map trees, numpy scalars) — never base64-JSON;
* the typed-error table crosses the boundary losslessly BOTH
  directions (``overloaded``/``deadline``/``closed``/``bad_request``/
  ``error``, cluster-scope shed round-tripping as NoReplicaAvailable);
* EVERY malformation — wrong magic, truncation, schema drift, garbage
  — answers typed (ValueError -> ``bad_request`` server-side, a
  ``closed`` ticket client-side), never a hang;
* a deadline that expires while the exchange is in flight answers
  ``expired`` (DeadlineExceeded), not a transport story;
* :class:`RpcClient` against a live in-process Server's armed
  endpoint matches the local oracle and reuses pooled connections;
* (slow) the real subprocess wire: a child's ``POST /submit`` serves
  plain ops and answers unregistered pipelines typed.
"""

import io
import json
import struct
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from veles.simd_tpu import obs, serve  # noqa: E402
from veles.simd_tpu.obs import http as obs_http  # noqa: E402
from veles.simd_tpu.ops import batched, iir  # noqa: E402
from veles.simd_tpu.runtime import breaker, faults  # noqa: E402
from veles.simd_tpu.serve import cluster, rpc  # noqa: E402
from veles.simd_tpu.serve.admission import Overloaded  # noqa: E402
from veles.simd_tpu.serve.server import (  # noqa: E402
    DeadlineExceeded, ServerClosed)

RNG = np.random.RandomState(47)
SOS = iir.butterworth(4, 0.25, "lowpass")


@pytest.fixture
def telemetry(monkeypatch):
    monkeypatch.setenv("VELES_SIMD_FAULT_BACKOFF", "0")
    obs.enable(compile_listeners=False)
    obs.reset()
    breaker.reset()
    faults.reset_fault_history()
    yield
    obs.disable()
    obs.reset()
    breaker.reset()
    faults.reset_fault_history()
    faults.set_fault_plan(None)


def _signal(n=512):
    return RNG.randn(n).astype(np.float32)


# ---------------------------------------------------------------------------
# wire codec: bytes-exact npy framing
# ---------------------------------------------------------------------------

class TestWireCodec:
    def test_request_roundtrip_bytes_exact(self):
        x = _signal(300)
        params = {
            "sos": SOS,
            "gain": np.float64(0.5),
            "taps": np.arange(7, dtype=np.int32),
            "label": "hp",
            "flag": True,
            "nothing": None,
            "nested": {"win": np.hanning(16).astype(np.float32)},
        }
        body = rpc.pack_request(
            "sosfilt", x, params, tenant="acme",
            deadline_ms=1234.5, block=True, timeout=2.0)
        req = rpc.unpack_request(body)
        assert req["op"] == "sosfilt"
        assert req["tenant"] == "acme"
        assert req["deadline_ms"] == 1234.5
        assert req["block"] is True
        assert req["timeout"] == 2.0
        assert req["x"].dtype == np.float32
        np.testing.assert_array_equal(req["x"], x)
        got = req["params"]
        assert got["sos"].dtype == SOS.dtype
        np.testing.assert_array_equal(got["sos"], SOS)
        # np.float64 subclasses float, so it rides the JSON header
        # value-exact (JSON numbers ARE doubles); true numpy scalars
        # like the int32 taps below ride npy blobs
        assert got["gain"] == 0.5
        assert got["taps"].dtype == np.int32
        np.testing.assert_array_equal(got["taps"],
                                      np.arange(7, dtype=np.int32))
        assert got["label"] == "hp" and got["flag"] is True
        assert got["nothing"] is None
        assert got["nested"]["win"].dtype == np.float32
        np.testing.assert_array_equal(
            got["nested"]["win"], np.hanning(16).astype(np.float32))

    def test_response_roundtrip_pipeline_state_tree(self):
        # a pipeline answer is an (out, state) tuple tree — tuples,
        # maps with non-string keys, and arrays must all survive
        out = _signal(128).astype(np.complex64)
        state = {("carry", 0): _signal(8),
                 "__weird__": np.int64(3)}
        body = rpc.pack_response(
            status="ok", value=(out, state), wait_s=0.012,
            events=[{"event": "enqueue", "t": 0.0}], replica="r1")
        resp = rpc.unpack_response(body)
        assert resp["status"] == "ok"
        assert resp["wait_s"] == 0.012
        assert resp["replica"] == "r1"
        assert resp["events"] == [{"event": "enqueue", "t": 0.0}]
        got_out, got_state = resp["value"]
        assert isinstance(resp["value"], tuple)
        assert got_out.dtype == np.complex64
        np.testing.assert_array_equal(got_out, out)
        np.testing.assert_array_equal(got_state[("carry", 0)],
                                      state[("carry", 0)])
        assert got_state["__weird__"] == np.int64(3)

    def test_unsupported_value_raises_valueerror(self):
        with pytest.raises(ValueError, match="cannot encode"):
            rpc.pack_request("sosfilt", _signal(),
                             {"fn": object()})

    def test_malformations_raise_valueerror_never_hang(self):
        good = rpc.pack_request("sosfilt", _signal(16),
                                {"sos": SOS})
        cases = {
            "empty": b"",
            "short": b"VS",
            "wrong magic": b"NOTRPC" + good[6:],
            "truncated header": good[:12],
            "truncated blobs": good[:-10],
            "trailing bytes": good + b"xx",
            "not bytes": None,
        }
        for label, data in cases.items():
            with pytest.raises(ValueError):
                rpc.unpack_frame(data)

    def test_header_must_be_json_with_schema(self):
        bad_json = rpc.MAGIC + struct.pack(">I", 4) + b"!!!!"
        with pytest.raises(ValueError, match="not JSON"):
            rpc.unpack_frame(bad_json)
        hdr = json.dumps({"schema": "veles-simd-rpc-v0",
                          "blobs": []}).encode()
        drift = rpc.MAGIC + struct.pack(">I", len(hdr)) + hdr
        with pytest.raises(ValueError, match="schema mismatch"):
            rpc.unpack_frame(drift)

    def test_blob_rot_raises_valueerror(self):
        # valid header, blob bytes that are not npy
        hdr = json.dumps({"schema": rpc.WIRE_SCHEMA,
                          "blobs": [4]}).encode()
        frame = rpc.MAGIC + struct.pack(">I", len(hdr)) + hdr \
            + b"rot!"
        with pytest.raises(ValueError, match="blob unparseable"):
            rpc.unpack_frame(frame)

    def test_pickle_payloads_refused(self):
        # an object-dtype npy blob needs allow_pickle — the decoder
        # must refuse it typed, not execute it
        buf = io.BytesIO()
        np.save(buf, np.array([{"a": 1}], dtype=object),
                allow_pickle=True)
        blob = buf.getvalue()
        hdr = json.dumps({"schema": rpc.WIRE_SCHEMA,
                          "blobs": [len(blob)]}).encode()
        frame = rpc.MAGIC + struct.pack(">I", len(hdr)) + hdr + blob
        with pytest.raises(ValueError, match="blob unparseable"):
            rpc.unpack_frame(frame)


# ---------------------------------------------------------------------------
# the typed-error table, pinned both directions
# ---------------------------------------------------------------------------

class TestErrorTable:
    def test_encode_direction(self):
        table = [
            (Overloaded("shed", tenant="t", scope="tenant"),
             "overloaded"),
            (DeadlineExceeded("late"), "deadline"),
            (ServerClosed("gone"), "closed"),
            (ValueError("bad shape"), "bad_request"),
            (RuntimeError("boom"), "error"),
            (KeyError("misc"), "error"),
        ]
        for exc, kind in table:
            assert rpc.encode_error(exc)["kind"] == kind, exc
        assert set(k for _, k in table) == set(rpc.ERROR_KINDS)

    def test_decode_direction(self):
        table = [
            ("overloaded", Overloaded),
            ("deadline", DeadlineExceeded),
            ("closed", ServerClosed),
            ("bad_request", ValueError),
            ("error", RuntimeError),
        ]
        for kind, typ in table:
            got = rpc.decode_error({"kind": kind, "message": "m"})
            assert type(got) is typ, kind
            assert "m" in str(got)

    def test_overloaded_carries_tenant_and_scope(self):
        info = rpc.encode_error(
            Overloaded("shed", tenant="acme", scope="tenant"))
        assert info["tenant"] == "acme" and info["scope"] == "tenant"
        back = rpc.decode_error(info)
        assert back.tenant == "acme" and back.scope == "tenant"

    def test_cluster_scope_roundtrips_as_no_replica(self):
        info = rpc.encode_error(cluster.NoReplicaAvailable(
            "all shedding", tenant="acme"))
        assert info["kind"] == "overloaded"
        assert info["scope"] == "cluster"
        back = rpc.decode_error(info)
        assert isinstance(back, cluster.NoReplicaAvailable)
        assert back.tenant == "acme"

    def test_unknown_kind_and_garbage_decode_as_runtimeerror(self):
        assert isinstance(
            rpc.decode_error({"kind": "v2-novelty", "message": "x"}),
            RuntimeError)
        assert isinstance(rpc.decode_error("not a dict"),
                          RuntimeError)


# ---------------------------------------------------------------------------
# serve_submit against a live Server (the child-side funnel)
# ---------------------------------------------------------------------------

class TestServeSubmit:
    def test_ok_roundtrip_matches_oracle(self, telemetry):
        x = _signal()
        with serve.Server(max_batch=8, max_wait_ms=2.0,
                          name="rsub") as srv:
            code, payload = rpc.serve_submit(
                srv, rpc.pack_request("sosfilt", x, {"sos": SOS},
                                      deadline_ms=60000.0))
        assert code == 200
        resp = rpc.unpack_response(payload)
        assert resp["status"] == "ok"
        assert resp["replica"] == "rsub"
        assert resp["events"], "child trace events must ride along"
        want = np.asarray(batched.batched_sosfilt(
            SOS, x[None, :], simd=False))[0]
        np.testing.assert_allclose(np.asarray(resp["value"]), want,
                                   rtol=2e-3, atol=2e-3)

    def test_malformed_body_answers_400_bad_request(self, telemetry):
        with serve.Server(max_wait_ms=1.0) as srv:
            code, payload = rpc.serve_submit(srv, b"garbage bytes")
        assert code == 400
        resp = rpc.unpack_response(payload)
        assert resp["status"] == "error"
        assert resp["error"]["kind"] == "bad_request"

    def test_unknown_op_answers_typed_bad_request(self, telemetry):
        with serve.Server(max_wait_ms=1.0) as srv:
            code, payload = rpc.serve_submit(
                srv, rpc.pack_request("no_such_op", _signal(), {}))
        assert code == 200
        resp = rpc.unpack_response(payload)
        assert resp["status"] == "error"
        assert resp["error"]["kind"] == "bad_request"

    def test_closed_server_answers_closed(self, telemetry):
        srv = serve.Server(max_wait_ms=1.0)
        srv.start()
        srv.stop()
        code, payload = rpc.serve_submit(
            srv, rpc.pack_request("sosfilt", _signal(),
                                  {"sos": SOS}))
        assert code == 200
        resp = rpc.unpack_response(payload)
        assert resp["status"] == "closed"
        assert resp["error"]["kind"] == "closed"


# ---------------------------------------------------------------------------
# RpcClient over a live in-process endpoint (real sockets, fast)
# ---------------------------------------------------------------------------

class _Wire:
    """A Server whose armed obs endpoint serves POST /submit — the
    same wiring a subprocess child arms, without the child."""

    def __init__(self, **server_kwargs):
        self.server = serve.Server(**server_kwargs)
        self.server.start()
        self.endpoint = obs_http.ObsEndpoint(
            0, submit=lambda body: rpc.serve_submit(self.server,
                                                    body))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.endpoint.stop()
        self.server.stop()
        return False


class TestRpcClient:
    def test_roundtrip_matches_oracle_and_pools_connections(
            self, telemetry):
        with _Wire(max_batch=8, max_wait_ms=2.0, name="rw") as w:
            client = rpc.RpcClient("127.0.0.1", w.endpoint.port,
                                   replica="rw", conns=2)
            try:
                x = _signal()
                tickets = [client.submit(op="sosfilt", x=x,
                                         params={"sos": SOS},
                                         deadline_ms=60000.0)
                           for _ in range(8)]
                want = np.asarray(batched.batched_sosfilt(
                    SOS, x[None, :], simd=False))[0]
                for t in tickets:
                    np.testing.assert_allclose(
                        np.asarray(t.result(timeout=60.0)), want,
                        rtol=2e-3, atol=2e-3)
                    assert t.status == "ok"
                    assert t.remote == "rw"
                stats = client.stats()
                assert stats["sends"] == 8
                assert stats["in_flight"] == 0
                # 8 sends over 2 pooled connections: at least 6 reuse
                assert stats["reused"] >= 6
                assert stats["reuse_ratio"] >= 0.75
                assert stats["transport_errors"] == 0
            finally:
                client.close()

    def test_remote_trace_absorbed_for_stitching(self, telemetry):
        with _Wire(max_wait_ms=2.0, name="rw") as w:
            client = rpc.RpcClient("127.0.0.1", w.endpoint.port,
                                   replica="rw", conns=1)
            try:
                t = client.submit(op="sosfilt", x=_signal(),
                                  params={"sos": SOS},
                                  deadline_ms=60000.0)
                t.result(timeout=60.0)
                names = [e.get("event") for e in t.trace.events()]
                assert "rpc_submit" in names
                assert "rpc_sent" in names
                # the child's lifecycle events crossed the wire,
                # tagged with the answering replica's identity
                assert any(e.get("event") == "dispatched"
                           and e.get("replica") == "rw"
                           for e in t.trace.events())
            finally:
                client.close()

    def test_refused_connection_answers_closed(self, telemetry):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        client = rpc.RpcClient("127.0.0.1", dead_port,
                               replica="rdead", conns=1,
                               timeout_s=5.0)
        try:
            t = client.submit(op="sosfilt", x=_signal(),
                              params={"sos": SOS})
            with pytest.raises(ServerClosed):
                t.result(timeout=30.0)
            assert t.status == "closed"
            assert client.stats()["transport_errors"] >= 1
        finally:
            client.close()

    def test_garbage_reply_answers_closed_never_hangs(
            self, telemetry):
        class Garbage(BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                body = b"these are not the frames"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Garbage)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        client = rpc.RpcClient("127.0.0.1", httpd.server_port,
                               replica="rgarb", conns=1)
        try:
            t = client.submit(op="sosfilt", x=_signal(),
                              params={"sos": SOS})
            with pytest.raises(ServerClosed):
                t.result(timeout=30.0)
            assert t.status == "closed"
            assert client.stats()["bad_replies"] >= 1
        finally:
            client.close()
            httpd.shutdown()

    def test_deadline_expired_in_flight_answers_expired(
            self, telemetry):
        # the peer stalls past the request's deadline then drops the
        # connection: the caller gave up already, so the ticket reads
        # DEADLINE_EXCEEDED — the transport story is noise
        class Stall(BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                time.sleep(0.5)
                self.connection.close()

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Stall)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        client = rpc.RpcClient("127.0.0.1", httpd.server_port,
                               replica="rstall", conns=1)
        try:
            t = client.submit(op="sosfilt", x=_signal(),
                              params={"sos": SOS}, deadline_ms=50.0)
            with pytest.raises(DeadlineExceeded):
                t.result(timeout=30.0)
            assert t.status == "expired"
        finally:
            client.close()
            httpd.shutdown()

    def test_close_answers_queued_and_refuses_new(self, telemetry):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        client = rpc.RpcClient("127.0.0.1", dead_port,
                               replica="rclose", conns=1)
        client.close()
        with pytest.raises(ServerClosed):
            client.submit(op="sosfilt", x=_signal(),
                          params={"sos": SOS})

    def test_malformed_request_raises_before_any_bytes(
            self, telemetry):
        client = rpc.RpcClient("127.0.0.1", 1, replica="rx",
                               conns=1)
        try:
            with pytest.raises(ValueError, match="unsupported op"):
                client.submit(op="no_such_op", x=_signal(),
                              params={})
            assert client.stats()["submitted"] == 0
        finally:
            client.close()


# ---------------------------------------------------------------------------
# the real subprocess wire (slow: the child pays a JAX import)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSubprocessWire:
    def test_child_serves_ops_and_answers_pipelines_typed(
            self, telemetry, monkeypatch):
        monkeypatch.setenv("VELES_SIMD_PLATFORM", "cpu")
        with cluster.ReplicaGroup(1, spawn="subprocess",
                                  heartbeat_ms=200,
                                  obs_port=-1) as group:
            r = group.replica("r0")
            assert r.rpc is not None
            x = _signal()
            t = r.rpc.submit(op="sosfilt", x=x,
                             params={"sos": SOS},
                             deadline_ms=60000.0)
            got = np.asarray(t.result(timeout=60.0))
            want = np.asarray(batched.batched_sosfilt(
                SOS, x[None, :], simd=False))[0]
            np.testing.assert_allclose(got, want, rtol=2e-3,
                                       atol=2e-3)
            assert t.remote == "r0"
            # an unregistered pipeline passes local classification
            # (registration is the CHILD's) and answers bad_request
            # across the wire — typed, never a hang
            t2 = r.rpc.submit(op="pipeline:never_registered", x=x,
                              params={}, deadline_ms=30000.0)
            with pytest.raises(ValueError):
                t2.result(timeout=30.0)
            assert t2.status == "error"
            # the fleet collector exports the client's health
            stats = r.rpc.stats()
            assert stats["sends"] >= 2
            assert stats["in_flight"] == 0
