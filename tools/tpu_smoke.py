#!/usr/bin/env python
"""Per-op-family XLA-vs-oracle correctness smoke on the *actual* device.

The pytest suite deliberately pins itself to a virtual CPU mesh
(``conftest.py``), so before round 2 nothing validated numerics on the real
TPU.  This harness runs each op family through its public entry point on
the default JAX device (the TPU under the driver) against the NumPy oracle
twin — the reference's SIMD-vs-``_na`` discipline
(``/root/reference/tests/matrix.cc:94-98``) on actual hardware.

Used two ways:

* ``python tools/tpu_smoke.py`` — standalone, exits nonzero on failure;
* ``bench.py`` runs it before timing (and ``bench.py --check`` runs only
  it), emitting one ``TPU-CHECK`` line per family to stderr.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _rel_err(got, want):
    # complex128 keeps imaginary parts intact (spectral family); for real
    # data it is equivalent to the float64 comparison.  to_host, NOT
    # np.asarray: complex device fetches are UNIMPLEMENTED through the
    # axon relay and one attempt poisons the process (the round-4/5
    # "9 families UNSUPPORTED-BY-BACKEND" collateral) — see
    # veles.simd_tpu.utils.platform.to_host.
    from veles.simd_tpu.utils.platform import to_host

    got = to_host(got).astype(np.complex128)
    want = np.asarray(want, np.complex128)
    scale = np.max(np.abs(want)) or 1.0
    return float(np.max(np.abs(got - want)) / scale)


def _check_arithmetic(rng):
    from veles.simd_tpu.ops import arithmetic as ar

    i16 = rng.randint(-30000, 30000, 4096).astype(np.int16)
    f = rng.randn(4096).astype(np.float32) * 100
    errs = [
        _rel_err(ar.int16_to_float(i16, simd=True),
                 ar.int16_to_float_na(i16)),
        _rel_err(ar.float_to_int16(f, simd=True), ar.float_to_int16_na(f)),
    ]
    wide = rng.randint(-1 << 20, 1 << 20, 1024).astype(np.int32)
    errs.append(_rel_err(ar.int32_to_int16(wide, simd=True),
                         ar.int32_to_int16_na(wide)))
    a = rng.randn(2048).astype(np.float32)
    b = rng.randn(2048).astype(np.float32)
    errs.append(_rel_err(ar.complex_multiply(a, b, simd=True),
                         ar.complex_multiply_na(a, b)))
    return max(errs), 1e-6


def _check_mathfun(rng):
    from veles.simd_tpu.ops import mathfun as mf

    x = np.abs(rng.randn(65536).astype(np.float32)) + 0.1
    errs = [
        _rel_err(mf.sin_psv(x, simd=True), np.sin(x)),
        _rel_err(mf.cos_psv(x, simd=True), np.cos(x)),
        _rel_err(mf.log_psv(x, simd=True), np.log(x)),
        _rel_err(mf.exp_psv(x, simd=True), np.exp(x)),
    ]
    return max(errs), 1e-5


def _check_matrix(rng):
    from veles.simd_tpu.ops import matrix as mx

    a = rng.randn(256, 192).astype(np.float32)
    b = rng.randn(192, 320).astype(np.float32)
    v = rng.randn(192).astype(np.float32)
    errs = [
        _rel_err(mx.matrix_multiply(a, b, simd=True),
                 mx.matrix_multiply_novec(a, b)),
        _rel_err(mx.matrix_multiply_transposed(a, b.T.copy(), simd=True),
                 mx.matrix_multiply_novec(a, b)),
        _rel_err(mx.matrix_vector_multiply(a, v, simd=True), a @ v),
        _rel_err(mx.matrix_add(a, a, simd=True), a + a),
    ]
    return max(errs), 1e-4


def _check_convolve(rng):
    from veles.simd_tpu.ops import convolve as cv

    x = rng.randn(20000).astype(np.float32)
    h = rng.randn(257).astype(np.float32)
    want = np.convolve(x.astype(np.float64), h.astype(np.float64))
    errs = []
    for algo in cv.ConvolutionAlgorithm:
        handle = cv.convolve_initialize(len(x), len(h), algo)
        errs.append(_rel_err(cv.convolve(handle, x, h, simd=True), want))
    return max(errs), 1e-4


def _check_convolve2d(rng):
    """2D family: direct-MXU, batched-FFT, and the 2D Pallas shifted-MAC
    kernel, plus cross_correlate2d, each vs the float64 oracle."""
    from veles.simd_tpu.ops import convolve2d as cv2

    x2 = rng.randn(96, 80).astype(np.float32)
    h2 = rng.randn(9, 13).astype(np.float32)
    want2 = cv2.convolve2d_na(x2, h2)
    errs = []
    for algo in ("direct", "fft"):
        errs.append(_rel_err(cv2.convolve2d(x2, h2, algorithm=algo,
                                            simd=True), want2))
    errs.append(_rel_err(cv2.cross_correlate2d(x2, h2, simd=True),
                         cv2.cross_correlate2d_na(x2, h2)))
    # the Pallas route explicitly (batched, the shape class it serves);
    # on TPU this executes compiled Mosaic, elsewhere it still validates
    # the routing + interpreter
    img = rng.randn(8, 128, 96).astype(np.float32)
    k2 = rng.randn(5, 7).astype(np.float32)
    errs.append(_rel_err(cv2.convolve2d(img, k2, algorithm="direct",
                                        simd=True),
                         cv2.convolve2d_na(img, k2)))
    return max(errs), 1e-4


def _check_streaming(rng):
    """StreamingConvolution: chunked == one-shot, for convolution and
    (reversed-h) correlation, including a chunk length that does not
    divide the signal."""
    from veles.simd_tpu.ops import convolve as cv

    x = rng.randn(17000).astype(np.float32)
    h = rng.randn(129).astype(np.float32)
    errs = []
    for reverse in (False, True):
        if reverse:
            want = np.correlate(np.pad(x.astype(np.float64), (128, 128)),
                                h.astype(np.float64), mode="valid")
        else:
            want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        sc = cv.StreamingConvolution(h, chunk_length=4096, reverse=reverse)
        parts = []
        for i in range(0, 16384, 4096):
            parts.append(np.asarray(sc.process(x[i:i + 4096])))
        parts.append(np.asarray(sc.process(
            np.pad(x[16384:], (0, 4096 - (len(x) - 16384))))))
        parts.append(np.asarray(sc.flush()))
        got = np.concatenate(parts)[:len(x) + len(h) - 1]
        errs.append(_rel_err(got, want))
    return max(errs), 1e-4


def _check_synthesis(rng):
    """Analysis -> synthesis round trips on-device: 1D DWT and SWT, the
    separable 2D step, and the multi-level 1D + 2D pyramids (all exact
    PERIODIC inverses; reconstruction must hit the input)."""
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    x = rng.randn(2048).astype(np.float32)
    ext = wv.ExtensionType.PERIODIC
    errs = []
    for wtype, order in ((WaveletType.DAUBECHIES, 8),
                         (WaveletType.SYMLET, 12),
                         (WaveletType.COIFLET, 6)):
        hi, lo = wv.wavelet_apply(wtype, order, ext, x, simd=True)
        errs.append(_rel_err(
            wv.wavelet_reconstruct(wtype, order, hi, lo, simd=True), x))
    shi, slo = wv.stationary_wavelet_apply(
        WaveletType.DAUBECHIES, 8, 2, ext, x, simd=True)
    errs.append(_rel_err(wv.stationary_wavelet_reconstruct(
        WaveletType.DAUBECHIES, 8, 2, shi, slo, simd=True), x))
    # multi-level pyramid round trip
    coeffs = wv.wavelet_transform(WaveletType.SYMLET, 8, ext, x, 3,
                                  simd=True)
    errs.append(_rel_err(wv.wavelet_inverse_transform(
        WaveletType.SYMLET, 8, coeffs, simd=True), x))
    # 2D: one separable step + a 2-level pyramid
    img = rng.randn(128, 96).astype(np.float32)
    ll, lh, hl, hh = wv.wavelet_apply2d(WaveletType.DAUBECHIES, 4, ext, img,
                                        simd=True)
    errs.append(_rel_err(wv.wavelet_reconstruct2d(
        WaveletType.DAUBECHIES, 4, ll, lh, hl, hh, simd=True), img))
    coeffs2 = wv.wavelet_transform2d(WaveletType.DAUBECHIES, 4, ext, img, 2,
                                     simd=True)
    errs.append(_rel_err(wv.wavelet_inverse_transform2d(
        WaveletType.DAUBECHIES, 4, coeffs2, simd=True), img))
    # non-periodic synthesis: device path (bulk adjoint on-device +
    # host-f64 boundary correction) vs the all-NumPy oracle twin — the
    # smoke's device-vs-oracle discipline; round-trip conditioning is
    # pinned separately in tests/test_wavelet_synthesis.py
    mhi, mlo = wv.stationary_wavelet_apply(
        WaveletType.DAUBECHIES, 8, 1, wv.ExtensionType.MIRROR, x, simd=True)
    rec_m = wv.stationary_wavelet_reconstruct(
        WaveletType.DAUBECHIES, 8, 1, mhi, mlo, simd=True,
        ext=wv.ExtensionType.MIRROR)
    rec_m_na = wv.stationary_wavelet_reconstruct_na(
        WaveletType.DAUBECHIES, 8, 1, np.asarray(mhi), np.asarray(mlo),
        ext=wv.ExtensionType.MIRROR)
    errs.append(_rel_err(rec_m, rec_m_na))
    return max(errs), 5e-4


def _check_correlate(rng):
    from veles.simd_tpu.ops import correlate as cr

    x = rng.randn(20000).astype(np.float32)
    h = rng.randn(257).astype(np.float32)
    want = np.correlate(np.pad(x.astype(np.float64), (256, 256)),
                        h.astype(np.float64), mode="valid")
    handle = cr.cross_correlate_initialize(len(x), len(h))
    errs = [_rel_err(cr.cross_correlate(handle, x, h, simd=True), want),
            _rel_err(cr.cross_correlate_simd(x, h, simd=True), want)]
    return max(errs), 1e-4


def _check_wavelet(rng):
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    x = rng.randn(4096).astype(np.float32)
    errs = []
    for wtype, order in ((WaveletType.DAUBECHIES, 8), (WaveletType.SYMLET, 8),
                         (WaveletType.COIFLET, 6)):
        for ext in wv.ExtensionType:
            hi, lo = wv.wavelet_apply(wtype, order, ext, x, simd=True)
            hi_na, lo_na = wv.wavelet_apply_na(wtype, order, ext, x)
            errs += [_rel_err(hi, hi_na), _rel_err(lo, lo_na)]
    shi, slo = wv.stationary_wavelet_apply(
        WaveletType.DAUBECHIES, 8, 2, wv.ExtensionType.PERIODIC, x,
        simd=True)
    shi_na, slo_na = wv.stationary_wavelet_apply_na(
        WaveletType.DAUBECHIES, 8, 2, wv.ExtensionType.PERIODIC, x)
    errs += [_rel_err(shi, shi_na), _rel_err(slo, slo_na)]
    # synthesis: perfect reconstruction on-device (periodic adjoint)
    phi, plo = wv.wavelet_apply(
        WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x, simd=True)
    rec = wv.wavelet_reconstruct(WaveletType.DAUBECHIES, 8, phi, plo,
                                 simd=True)
    errs.append(_rel_err(rec, x))
    return max(errs), 5e-4  # tests/wavelet.cc:84-86 epsilon


def _check_spectral(rng):
    """STFT round trip + Hilbert + CWT vs their float64 oracles."""
    from veles.simd_tpu.ops import spectral as sp

    x = rng.randn(4, 2048).astype(np.float32)
    errs = [_rel_err(sp.stft(x, 256, 64, simd=True),
                     sp.stft_na(x, 256, 64))]
    spec = sp.stft(x, 256, 64, simd=True)
    rec = np.asarray(sp.istft(spec, 2048, 256, 64, simd=True))
    errs.append(_rel_err(rec[:, 256:-256], x[:, 256:-256]))
    # forced spectral routes (PR 5): the matmul-DFT analysis basis and
    # the inverse-basis synthesis, each vs the same float64 oracle the
    # auto-selected route is held to
    errs.append(_rel_err(sp.stft(x, 256, 64, simd=True,
                                 route="rdft_matmul"),
                         sp.stft_na(x, 256, 64)))
    errs.append(_rel_err(sp.stft(x, 256, 64, simd=True,
                                 route="xla_fft"),
                         sp.stft_na(x, 256, 64)))
    spec64 = sp.stft_na(x, 512, 128)
    want_i = sp.istft_na(spec64, 2048, 512, 128)[:, 512:-512]
    rec_m = np.asarray(sp.istft(spec64.astype(np.complex64), 2048, 512,
                                128, simd=True, route="rdft_matmul"))
    errs.append(_rel_err(rec_m[:, 512:-512], want_i))
    errs.append(_rel_err(sp.hilbert(x, simd=True), sp.hilbert_na(x)))
    errs.append(_rel_err(
        sp.morlet_cwt(x, [4.0, 16.0, 64.0], simd=True),
        sp.morlet_cwt_na(x, [4.0, 16.0, 64.0])))
    # PSD estimation layer (Welch / CSD / coherence / detrend)
    errs.append(_rel_err(sp.detrend(x, "linear", simd=True),
                         sp.detrend_na(x, "linear")))
    errs.append(_rel_err(sp.welch(x, nperseg=256, simd=True)[1],
                         sp.welch_na(x, nperseg=256)[1]))
    errs.append(_rel_err(
        sp.csd(x, x[::-1], nperseg=256, simd=True)[1],
        sp.csd_na(x, x[::-1], nperseg=256)[1]))
    # Bluestein chirp-Z vs the direct O(nm) z-transform sum
    errs.append(_rel_err(sp.czt(x[0], 100, simd=True),
                         sp.czt_na(x[0], 100)))
    # Lomb-Scargle on uneven samples (dense trig grid, FFT-free)
    tu = np.sort(rng.uniform(0, 50, 400))
    xu = np.sin(1.3 * tu).astype(np.float32)
    fr = np.linspace(0.5, 3.0, 128)
    errs.append(_rel_err(sp.lombscargle(tu, xu, fr, simd=True),
                         sp.lombscargle_na(tu, xu, fr)))
    return max(errs), 1e-4


# The resample smoke's exact device geometries — shared with
# tests/test_smoke_shapes.py, which pins that every shape's executable
# compiles eagerly and the filter stays smoke-sized.  The BENCH_r05
# wedge: the (160, 147) case with DEFAULT taps compiles a 3201-tap
# dilated+strided conv, and that compile stalled the relay for 301 s,
# relay-skipping the whole smoke:resample stage (and, under the old
# hard-exit design, every family after it).  The rate pair stays — it
# is the classic 48k->44.1k conversion and covers the up>1 && down>1
# CPU zero-stuff path — but with an explicit short filter: the smoke
# gates PARITY (device vs the same-taps oracle), not filter quality.
RESAMPLE_SMOKE_NTAPS = 641
RESAMPLE_SMOKE_RATES = ((2, 1), (1, 2), (3, 2), (160, 147))
RESAMPLE_SMOKE_SHAPE = (4, 730)


def _resample_smoke_taps(rs, up, down):
    """Explicit taps for the big-rate smoke cases (None keeps the
    default design for the small ones, whose filters are tiny)."""
    if max(up, down) <= 4:
        return None
    return rs._resample_taps(up, down, RESAMPLE_SMOKE_NTAPS)


def _check_resample(rng):
    """Polyphase (dilated conv) + Fourier resampling vs their oracles."""
    from veles.simd_tpu.ops import resample as rs

    x = rng.randn(*RESAMPLE_SMOKE_SHAPE).astype(np.float32)
    errs = []
    for up, down in RESAMPLE_SMOKE_RATES:
        taps = _resample_smoke_taps(rs, up, down)
        errs.append(_rel_err(
            rs.resample_poly(x, up, down, taps=taps, simd=True),
            rs.resample_poly_na(x, up, down, taps)))
    errs.append(_rel_err(rs.resample_fourier(x, 333, simd=True),
                         rs.resample_fourier_na(x, 333)))
    errs.append(_rel_err(rs.resample_fourier(x, 1460, simd=True),
                         rs.resample_fourier_na(x, 1460)))
    return max(errs), 1e-4


def _check_iir(rng):
    """Associative-scan IIR vs the sequential DF2T oracle (FFT-free —
    runs even on relay sessions whose backend lacks fft)."""
    from veles.simd_tpu.ops import iir

    x = rng.randn(4, 2048).astype(np.float32)
    errs = []
    sos = iir.butterworth(4, 0.25, "lowpass")
    errs.append(_rel_err(iir.sosfilt(sos, x, simd=True),
                         iir.sosfilt_na(sos, x)))
    sos_bp = iir.butterworth(3, (0.2, 0.6), "bandpass")
    errs.append(_rel_err(iir.sosfiltfilt(sos_bp, x, simd=True),
                         iir.sosfiltfilt_na(sos_bp, x)))
    b = np.array([0.2, 0.3, 0.1])
    a = np.array([1.0, -0.5, 0.2, -0.05])
    errs.append(_rel_err(iir.lfilter(b, a, x, simd=True),
                         iir.lfilter_na(b, a, x)))
    return max(errs), 1e-3


def _check_filters(rng):
    """Median/rank (gather + sort), Savitzky-Golay (FIR) vs oracles —
    FFT-free family."""
    from veles.simd_tpu.ops import filters as fl

    x = rng.randn(4, 1024).astype(np.float32)
    img = rng.randn(64, 80).astype(np.float32)
    errs = [
        _rel_err(fl.medfilt(x, 7, simd=True), fl.medfilt_na(x, 7)),
        _rel_err(fl.order_filter(x, 1, 5, simd=True),
                 fl.order_filter_na(x, 1, 5)),
        _rel_err(fl.medfilt2d(img, (3, 5), simd=True),
                 fl.medfilt2d_na(img, (3, 5))),
        _rel_err(fl.savgol_filter(x, 11, 3, simd=True),
                 fl.savgol_filter_na(x, 11, 3)),
        _rel_err(fl.savgol_filter(x, 9, 2, deriv=1, simd=True),
                 fl.savgol_filter_na(x, 9, 2, deriv=1)),
        _rel_err(fl.wiener(x, 7, simd=True), fl.wiener_na(x, 7)),
    ]
    return max(errs), 1e-3


def _check_waveforms(rng):
    """Generators vs float64 twins (elementwise closed forms)."""
    from veles.simd_tpu.ops import waveforms as wf

    t = np.linspace(0, 1, 8192)
    ph = np.linspace(0, 40, 8192)
    errs = [
        _rel_err(wf.chirp(t, 20, 1.0, 400, simd=True),
                 wf.chirp_na(t, 20, 1.0, 400)),
        _rel_err(wf.gausspulse(t - 0.5, 100, 0.5, simd=True),
                 wf.gausspulse_na(t - 0.5, 100, 0.5)),
        # square/sawtooth: f32 phase wrap flips samples that land within
        # rounding of a cycle boundary — mask those out explicitly and
        # compare the rest directly
        _rel_err(np.asarray(wf.square(ph, 0.3, simd=True))[
                     _away_from_edges(ph, (0.0, 0.3, 1.0))],
                 wf.square_na(ph, 0.3)[
                     _away_from_edges(ph, (0.0, 0.3, 1.0))]),
        _rel_err(np.asarray(wf.sawtooth(ph, 0.5, simd=True))[
                     _away_from_edges(ph, (0.0, 0.5, 1.0))],
                 wf.sawtooth_na(ph, 0.5)[
                     _away_from_edges(ph, (0.0, 0.5, 1.0))]),
    ]
    return max(errs), 1e-3


def _away_from_edges(ph, edges, eps=1e-3):
    """Mask of phase samples whose cycle fraction is at least ``eps``
    away from every discontinuity in ``edges``."""
    frac = np.mod(np.asarray(ph, np.float64), 2 * np.pi) / (2 * np.pi)
    dist = np.min([np.abs(frac - e) for e in edges], axis=0)
    return dist > eps


def _check_normalize(rng):
    from veles.simd_tpu.ops import normalize as nz

    plane = rng.randint(0, 256, (64, 96)).astype(np.uint8)
    errs = [_rel_err(nz.normalize2D(plane, simd=True),
                     nz.normalize2D_novec(plane))]
    mn, mx = nz.minmax2D(plane, simd=True)
    mn_na, mx_na = nz.minmax2D_novec(plane)
    errs.append(0.0 if (int(mn), int(mx)) == (int(mn_na), int(mx_na))
                else 1.0)
    f = rng.randn(5000).astype(np.float32)
    fmn, fmx = nz.minmax1D(f, simd=True)
    errs.append(_rel_err([fmn, fmx], [f.min(), f.max()]))
    return max(errs), 1e-6


def _check_detect_peaks(rng):
    from veles.simd_tpu.ops import detect_peaks as dp

    x = np.cumsum(rng.randn(8192)).astype(np.float32)
    pos, vals = dp.detect_peaks(x, dp.ExtremumType.BOTH, simd=True)
    pos_na, vals_na = dp.detect_peaks_na(x, dp.ExtremumType.BOTH)
    if len(pos) != len(pos_na) or not np.array_equal(pos, pos_na):
        return 1.0, 1e-6
    errs = [_rel_err(vals, vals_na)]
    # sparse-table prominence vs the sequential saddle-walk oracle
    peaks, _ = dp.find_peaks(x)
    errs.append(_rel_err(dp.peak_prominences(x, peaks, simd=True),
                         dp.peak_prominences_na(x, peaks)))
    return max(errs), 1e-6


def _check_pallas1d(rng):
    """Compiled 1D Mosaic filter-bank kernel vs oracle on the real chip
    (the CPU suite only exercises the interpreter — tests/test_pallas.py).
    Ran green on hardware in round 2."""
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.pallas_kernels import (
        filter_bank_pallas, pallas_available)

    x = rng.randn(16, 1024).astype(np.float32)
    interp = not pallas_available()   # smoke may run on CPU standalone
    errs = []
    # DWT daub8 periodic (phase-split stride-2 path)
    x_ext = np.concatenate([x, x[:, :8]], axis=1)
    hi_f, lo_f = wv._filters("daub", 8)
    hi, lo = filter_bank_pallas(x_ext, np.stack([hi_f, lo_f]), 2, 1, 512,
                                interpret=interp)
    want_hi, want_lo = wv.wavelet_apply_na(
        "daub", 8, wv.ExtensionType.PERIODIC, x)
    errs += [_rel_err(hi, want_hi), _rel_err(lo, want_lo)]
    # SWT level 3 (dilated single-phase path)
    x_ext = np.concatenate([x, x[:, :32]], axis=1)
    shi, slo = filter_bank_pallas(x_ext, np.stack([hi_f, lo_f]), 1, 4, 1024,
                                  interpret=interp)
    want_shi, want_slo = wv.stationary_wavelet_apply_na(
        "daub", 8, 3, wv.ExtensionType.PERIODIC, x)
    errs += [_rel_err(shi, want_shi), _rel_err(slo, want_slo)]
    # integrated gate: on TPU wavelet_apply with a large batch routes
    # through the kernel (wv._use_pallas) — verify end-to-end numerics
    bhi, blo = wv.wavelet_apply("daub", 8, wv.ExtensionType.MIRROR, x,
                                simd=True)
    whi, wlo = wv.wavelet_apply_na("daub", 8, wv.ExtensionType.MIRROR, x)
    errs += [_rel_err(bhi, whi), _rel_err(blo, wlo)]
    # batched direct convolution routes through the C=1 kernel
    # (convolve._use_pallas_direct) on TPU
    from veles.simd_tpu.ops import convolve as cv

    hh = rng.randn(65).astype(np.float32)
    errs.append(_rel_err(cv.convolve_simd(x, hh, simd=True),
                         cv.convolve_na(x, hh)))
    # fused overlap-save kernel at the headline filter length, sized
    # for multiple grid steps so the VMEM halo CARRY is exercised on
    # the compiled path (4 tiles at the default 256-row tiling); on
    # TPU the handle route picks it automatically, here it is called
    # directly so the smoke pins the kernel, not the gate
    from veles.simd_tpu.ops.pallas_kernels import overlap_save_pallas

    xos = rng.randn(200000).astype(np.float32)
    hos = rng.randn(2047).astype(np.float32)
    errs.append(_rel_err(
        overlap_save_pallas(xos, hos, interpret=interp),
        np.convolve(xos.astype(np.float64), hos.astype(np.float64))))
    # fused STFT kernel at the TPU shape (512/128: r=4, so the
    # frame-overlap CARRY crosses grid steps on the compiled path);
    # direct call pins the kernel, not the routing gate
    from veles.simd_tpu.ops import spectral as spl
    from veles.simd_tpu.ops.pallas_kernels import stft_pallas

    xst = rng.randn(2, 40960).astype(np.float32)
    errs.append(_rel_err(stft_pallas(xst, 512, 128, interpret=interp),
                         spl.stft_na(xst, 512, 128)))
    # multi-level cascade: the level loop since round 5 (the fused
    # kernel measured slower and is opt-in); value-check all four bands
    got = wv.wavelet_transform("daub", 8, wv.ExtensionType.PERIODIC, x,
                               3, simd=True)
    cur, want = x, []
    for _ in range(3):
        w_hi, cur = wv.wavelet_apply_na("daub", 8,
                                        wv.ExtensionType.PERIODIC, cur)
        want.append(w_hi)
    want.append(cur)
    errs += [_rel_err(g, w) for g, w in zip(got, want)]
    return max(errs), 5e-4


def _check_serve(rng):
    """The serving layer end to end on the actual device: a small
    Server coalescing mixed sosfilt/stft traffic into batched guarded
    dispatches, answers parity-checked against the per-request NumPy
    oracle (so bucketing's pad-and-slice is validated on hardware, not
    just the virtual CPU mesh)."""
    from veles.simd_tpu import serve
    from veles.simd_tpu.ops import iir, spectral as sp

    sos = iir.butterworth(4, 0.25, "lowpass")
    errs = []
    with serve.Server(max_batch=4, max_wait_ms=10.0,
                      workers=2) as srv:
        xs = [rng.randn(n).astype(np.float32)
              for n in (300, 500, 500, 777)]
        ts = [srv.submit(serve.Request("sosfilt", x, {"sos": sos}))
              for x in xs]
        for x, t in zip(xs, ts):
            errs.append(_rel_err(t.result(timeout=120.0),
                                 iir.sosfilt_na(sos, x[None, :])[0]))
        xq = rng.randn(1024).astype(np.float32)
        tq = srv.submit(serve.Request(
            "stft", xq, {"frame_length": 128, "hop": 64}))
        errs.append(_rel_err(tq.result(timeout=120.0),
                             sp.stft_na(xq, 128, 64)))
    return max(errs), 2e-3


def _check_pallas2d(rng):
    """The 2D shifted-MAC Mosaic kernel (convolve2d direct route on TPU).

    Kept LAST in the family order as a historical precaution: its
    first-ever hardware execution (2026-07-31 00:59Z window) coincided
    with the relay wedging.  Round 5 cleared it — the full bisect
    passed (``tools/repro_pallas2d.py``, 8/8 stages) and the wedge was
    re-attributed to XLA's im2col direct conv at large kernels — so the
    compiled kernel is now default-ON for implicit routing
    (``VELES_SIMD_DISABLE_PALLAS2D=1`` opts out, in which case this
    family validates the XLA direct route instead — the assert below
    admits the opt-out explicitly)."""
    import os

    from veles.simd_tpu.ops import convolve2d as cv2
    from veles.simd_tpu.ops import pallas_kernels as _pk

    img = rng.randn(4, 64, 48).astype(np.float32)
    k2 = rng.randn(5, 7).astype(np.float32)
    # compiled pallas2d is default-on since round 5 (green bisect +
    # measured wins); this family exercises the implicit routing as-is
    assert cv2._use_pallas_direct2d(img.shape, 5, 7) or not (
        _pk.pallas_available()
        and _pk.pallas2d_compiled_allowed())  # CPU / opt-out run
    err = _rel_err(
        cv2.convolve2d(img, k2, algorithm="direct", simd=True),
        cv2.convolve2d_na(img, k2))
    return err, 5e-4


def _check_parallel(rng):
    """shard_map/collective lowering on the actual device (a 1-chip mesh
    still exercises ppermute/psum code paths through the TPU compiler)."""
    from veles.simd_tpu.parallel import (
        default_mesh, sharded_convolve, sharded_matmul)

    x = rng.randn(4096).astype(np.float32)
    h = rng.randn(33).astype(np.float32)
    want = np.convolve(x.astype(np.float64), h.astype(np.float64))
    errs = [_rel_err(sharded_convolve(x, h, default_mesh("sp"), axis="sp"),
                     want)]
    a = rng.randn(64, 96).astype(np.float32)
    b = rng.randn(96, 48).astype(np.float32)
    errs.append(_rel_err(sharded_matmul(a, b, default_mesh("tp"), axis="tp"),
                         a.astype(np.float64) @ b.astype(np.float64)))
    # round-4 sharded families: halo-exchange rank filter + psum
    # Lomb-Scargle through the device compiler
    from veles.simd_tpu.ops import filters as fl
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import (sharded_lombscargle,
                                         sharded_medfilt)

    errs.append(_rel_err(sharded_medfilt(x, 9, default_mesh("sp")),
                         fl.medfilt_na(x, 9)))
    t_ls = np.sort(rng.rand(1024)) * 50.0
    x_ls = np.sin(1.7 * t_ls).astype(np.float32)
    f_ls = np.linspace(0.5, 3.0, 32)
    errs.append(_rel_err(
        sharded_lombscargle(t_ls, x_ls, f_ls, default_mesh("sp")),
        sp.lombscargle_na(t_ls, x_ls, f_ls)))
    # ring pipelines (multi-hop ppermute streaming) on the real device
    from veles.simd_tpu.ops import convolve2d as cv2
    from veles.simd_tpu.parallel import (
        make_mesh, sharded_convolve2d_ring, sharded_convolve_ring)

    import jax

    # size the filter so the halo exceeds one block whenever >= 2
    # devices exist (on a single chip the ring degenerates to one local
    # conv — inter-device ppermute needs a real second device)
    n_dev = len(jax.devices())
    xr = rng.randn(2048).astype(np.float32)
    kr = 1500 if n_dev == 1 else (2048 // n_dev) + 600
    hr = rng.randn(kr).astype(np.float32)
    errs.append(_rel_err(
        sharded_convolve_ring(xr, hr, default_mesh("sp"), axis="sp"),
        np.convolve(xr.astype(np.float64), hr.astype(np.float64))))
    img = rng.randn(64, 64).astype(np.float32)
    k2 = rng.randn(40, 30).astype(np.float32)
    mesh2d = make_mesh({"dp": 1, "sp": -1})   # works on any device count
    errs.append(_rel_err(sharded_convolve2d_ring(img, k2, mesh2d),
                         cv2.convolve2d_na(img, k2)))
    # sequence-parallel STFT round trip (frame-halo ppermute + adjoint);
    # sized from the device count so halo <= block on any sp mesh
    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.parallel import sharded_istft, sharded_stft

    fl, hop = 128, 32
    # a multiple of n_dev * fl near 2048: block = k * fl, so block is
    # always a hop multiple and >= fl > halo on any device count
    ns = n_dev * fl * max(1, 2048 // (n_dev * fl))
    xs = rng.randn(ns).astype(np.float32)
    spec = sharded_stft(xs, fl, hop, default_mesh("sp"), axis="sp")
    errs.append(_rel_err(spec, sp.stft_na(xs, fl, hop)))
    rec = sharded_istft(spec, ns, fl, hop, default_mesh("sp"), axis="sp")
    errs.append(_rel_err(np.asarray(rec)[fl:-fl],
                         np.asarray(xs, np.float64)[fl:-fl]))
    # sequence-parallel IIR (two-level scan state handoff)
    from veles.simd_tpu.ops import iir as iir_mod
    from veles.simd_tpu.parallel import sharded_sosfilt

    sos = iir_mod.butterworth(3, 0.2, "lowpass")
    xq = rng.randn(n_dev * 256).astype(np.float32)
    errs.append(_rel_err(sharded_sosfilt(sos, xq, default_mesh("sp")),
                         iir_mod.sosfilt_na(sos, xq)))
    # sequence-parallel Welch PSD (per-shard segment FFTs + one psum)
    from veles.simd_tpu.parallel import sharded_welch

    _, pw = sharded_welch(xs, default_mesh("sp"), nperseg=fl)
    _, pw_na = sp.welch_na(np.asarray(xs), nperseg=fl)
    errs.append(_rel_err(pw, pw_na))
    # sequence-parallel polyphase resampling (dilated-conv halo blocks)
    from veles.simd_tpu.ops import resample as rs_mod
    from veles.simd_tpu.parallel import sharded_resample_poly

    xr2 = rng.randn(n_dev * 294).astype(np.float32)  # 294*160 % 147 == 0
    errs.append(_rel_err(
        sharded_resample_poly(xr2, 160, 147, default_mesh("sp")),
        rs_mod.resample_poly_na(xr2, 160, 147)))
    return max(errs), 1e-4


FAMILIES = [
    ("arithmetic", _check_arithmetic),
    ("mathfun", _check_mathfun),
    ("matrix", _check_matrix),
    ("convolve", _check_convolve),
    ("convolve2d", _check_convolve2d),
    ("streaming", _check_streaming),
    ("correlate", _check_correlate),
    ("synthesis", _check_synthesis),
    ("wavelet", _check_wavelet),
    ("spectral", _check_spectral),
    ("resample", _check_resample),
    ("iir", _check_iir),
    ("filters", _check_filters),
    ("waveforms", _check_waveforms),
    ("normalize", _check_normalize),
    ("detect_peaks", _check_detect_peaks),
    ("pallas1d", _check_pallas1d),
    ("parallel", _check_parallel),
    ("serve", _check_serve),
    ("pallas2d", _check_pallas2d),  # wedge suspect: keep last (see check)
]


def run_smoke(emit=None, families=None, on_start=None) -> bool:
    """Run every family check on the default device; True when all pass.

    ``families`` restricts to the named subset (order preserved);
    ``on_start(name)`` fires before each family begins — bench.py's
    watchdog uses it to attribute a relay wedge to the family that was
    in flight when progress stopped.

    ``$VELES_SIMD_SMOKE_SKIP`` (comma-separated family names) excludes
    families even when explicitly requested — the hardware session
    script uses it to hold the wedge-suspect ``pallas2d`` family out of
    bench.py's embedded smoke so a wedge there cannot cost the tuner
    stages that follow; the suspect then runs dead last via
    ``tools/repro_pallas2d.py``.
    """
    import jax

    if emit is None:
        emit = lambda s: print(s, file=sys.stderr)
    skip = {s.strip() for s in
            os.environ.get("VELES_SIMD_SMOKE_SKIP", "").split(",")
            if s.strip()}
    known = {n for n, _ in FAMILIES}
    for bad in sorted(skip - known):
        # a typo here would silently re-enable the wedge suspect
        emit(f"TPU-CHECK WARNING: unknown family {bad!r} in "
             f"VELES_SIMD_SMOKE_SKIP (known: {sorted(known)})")
    device = str(jax.devices()[0])
    rng = np.random.RandomState(7)
    all_ok = True
    for name, check in FAMILIES:
        if families is not None and name not in families:
            continue
        if name in skip:
            emit(f"TPU-CHECK family={name} SKIPPED "
                 "(VELES_SIMD_SMOKE_SKIP)")
            continue
        if on_start is not None:
            on_start(name)
        try:
            # the shared transient-fault policy (runtime/faults.py):
            # a device-lost/timeout mid-family gets bounded
            # retry-with-backoff before the family is reported failed
            # — the r02-r04 one-shot relay drops.  No fallback: a
            # smoke family that cannot run on the device has nothing
            # honest to report, so exhaustion re-raises into the
            # except arm below.
            from veles.simd_tpu.runtime import faults

            err, tol = faults.guarded(f"smoke.{name}",
                                      lambda: check(rng))
            ok = err <= tol
        except Exception as e:  # surface, keep checking other families
            # A backend capability gap is not a numerical failure: some
            # relay sessions ship a TPU backend with whole op families
            # missing (observed 2026-07-31: every jnp.fft.* raised
            # UNIMPLEMENTED while matmul/conv ran fine).  Report it
            # loudly but distinctly — the op never executed, so there is
            # no wrong number to flag.
            if "UNIMPLEMENTED" in str(e):
                emit(f"TPU-CHECK family={name} device={device!r} "
                     f"UNSUPPORTED-BY-BACKEND ({e!s:.120})")
                continue
            err, tol, ok = float("nan"), 0.0, False
            emit(f"TPU-CHECK family={name} EXCEPTION: {e!r}")
        all_ok &= ok
        emit(f"TPU-CHECK family={name} device={device!r} "
             f"max_rel_err={err:.2e} tol={tol:.0e} "
             f"{'ok' if ok else 'FAIL'}")
    return all_ok


if __name__ == "__main__":
    from veles.simd_tpu.utils.platform import (
        maybe_override_platform, require_reachable_device)

    maybe_override_platform()
    require_reachable_device()  # fail fast on a wedged relay, don't hang
    names = [a.split("=", 1)[1] for a in sys.argv[1:]
             if a.startswith("--family=")]
    known = {n for n, _ in FAMILIES}
    if any(n not in known for n in names):
        sys.exit(f"unknown --family; known: {sorted(known)}")
    sys.exit(0 if run_smoke(families=names or None) else 1)
