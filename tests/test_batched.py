"""Batched-throughput layer: parity with the single-signal ops + the
LRU handle-cache contract (one executable per geometry, bounded)."""

import numpy as np
import pytest

from veles.simd_tpu.ops import batched, iir, resample as rs

rng = np.random.RandomState(5)


@pytest.fixture(autouse=True)
def _fresh_cache():
    batched.clear_handle_cache()
    yield
    batched.clear_handle_cache()


class TestParity:
    @pytest.mark.parametrize("up,down", [(2, 1), (1, 2), (3, 2),
                                         (160, 147)])
    def test_resample_matches_single_signal(self, up, down):
        x = rng.randn(6, 730).astype(np.float32)
        got = np.asarray(batched.batched_resample_poly(x, up, down,
                                                       simd=True))
        want = np.asarray(rs.resample_poly(x, up, down, simd=True))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_resample_identity_rate(self):
        x = rng.randn(3, 64).astype(np.float32)
        got = np.asarray(batched.batched_resample_poly(x, 7, 7,
                                                       simd=True))
        np.testing.assert_array_equal(got, x)

    def test_resample_oracle_path(self):
        x = rng.randn(4, 300).astype(np.float32)
        got = batched.batched_resample_poly(x, 3, 2, simd=False)
        want = rs.resample_poly_na(x, 3, 2).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_sosfilt_matches_single_signal(self):
        sos = iir.butterworth(4, 0.25, "lowpass")
        x = rng.randn(8, 512).astype(np.float32)
        got = np.asarray(batched.batched_sosfilt(sos, x, simd=True))
        want = np.asarray(iir.sosfilt(sos, x, simd=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_sosfilt_oracle_path(self):
        sos = iir.butterworth(2, 0.3, "highpass")
        x = rng.randn(3, 256).astype(np.float32)
        got = batched.batched_sosfilt(sos, x, simd=False)
        want = iir.sosfilt_na(sos, x).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_lfilter_matches_single_signal(self):
        b = np.array([0.2, 0.3, 0.1])
        a = np.array([1.0, -0.5, 0.2, -0.05])
        x = rng.randn(5, 400).astype(np.float32)
        got = np.asarray(batched.batched_lfilter(b, a, x, simd=True))
        want = np.asarray(iir.lfilter(b, a, x, simd=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_lfilter_pure_fir(self):
        b = np.array([0.5, 0.25, 0.125])
        x = rng.randn(4, 128).astype(np.float32)
        got = np.asarray(batched.batched_lfilter(b, [1.0], x, simd=True))
        want = iir.lfilter_na(b, [1.0], x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_leading_dims_ride_along(self):
        sos = iir.butterworth(2, 0.2, "lowpass")
        x = rng.randn(2, 3, 128).astype(np.float32)
        got = np.asarray(batched.batched_sosfilt(sos, x, simd=True))
        assert got.shape == x.shape
        want = np.asarray(iir.sosfilt(sos, x, simd=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestHandleCache:
    def test_same_geometry_hits(self):
        x = rng.randn(4, 256).astype(np.float32)
        batched.batched_resample_poly(x, 3, 2, simd=True)
        batched.batched_resample_poly(x, 3, 2, simd=True)
        info = batched.handle_cache_info()
        assert info["misses"] == 1 and info["hits"] == 1
        assert info["size"] == 1

    def test_new_taps_do_not_recompile_resample(self):
        # taps are runtime data: a different filter of the SAME length
        # must reuse the compiled handle
        x = rng.randn(4, 256).astype(np.float32)
        t1 = rs._resample_taps(3, 2, 41)
        t2 = np.asarray(rs._resample_taps(3, 2, 41))[::-1].copy()
        batched.batched_resample_poly(x, 3, 2, taps=t1, simd=True)
        batched.batched_resample_poly(x, 3, 2, taps=t2, simd=True)
        info = batched.handle_cache_info()
        assert info["misses"] == 1 and info["hits"] == 1

    def test_new_geometry_misses(self):
        sos = iir.butterworth(2, 0.2, "lowpass")
        batched.batched_sosfilt(sos, rng.randn(4, 128), simd=True)
        batched.batched_sosfilt(sos, rng.randn(8, 128), simd=True)
        batched.batched_sosfilt(sos, rng.randn(4, 256), simd=True)
        assert batched.handle_cache_info()["misses"] == 3

    def test_lru_bound_evicts_oldest(self, monkeypatch):
        monkeypatch.setattr(batched, "BATCHED_CACHE_MAXSIZE", 2)
        sos = iir.butterworth(1, 0.2, "lowpass")
        for n in (64, 96, 128):
            batched.batched_sosfilt(sos, rng.randn(2, n), simd=True)
        info = batched.handle_cache_info()
        assert info["size"] == 2 and info["evictions"] == 1
        # the first geometry was evicted: calling it again recompiles
        batched.batched_sosfilt(sos, rng.randn(2, 64), simd=True)
        assert batched.handle_cache_info()["misses"] == 4


class TestErrors:
    def test_rejects_1d_input(self):
        with pytest.raises(ValueError, match="single-signal"):
            batched.batched_sosfilt(iir.butterworth(2, 0.2),
                                    np.ones(64, np.float32))

    def test_lfilter_order_bound(self):
        b = [1.0]
        a = np.ones(iir._LFILTER_MAX_ORDER + 2)
        with pytest.raises(ValueError, match="batched_sosfilt"):
            batched.batched_lfilter(b, a, np.ones((2, 64), np.float32))


def test_donate_is_optin_and_keys_the_handle():
    # donate=True on CPU is a no-op for the executable (donation only
    # applies on TPU) but must still compile a DISTINCT handle, and the
    # caller's array must stay valid on the CPU path
    sos = iir.butterworth(2, 0.2, "lowpass")
    x = rng.randn(4, 128).astype(np.float32)
    import jax.numpy as jnp

    xj = jnp.asarray(x)
    y1 = np.asarray(batched.batched_sosfilt(sos, xj, simd=True))
    _ = np.asarray(xj)                       # input still alive
    y2 = np.asarray(batched.batched_sosfilt(sos, xj, simd=True,
                                            donate=True))
    np.testing.assert_allclose(y1, y2, rtol=1e-6, atol=1e-6)
    info = batched.handle_cache_info()
    # on CPU _donate_argnums(True) == (): same key, one handle; on a
    # TPU run the donation tuple differs and a second handle appears
    assert info["size"] in (1, 2)


class TestEmptyBatchContract:
    # the serving layer's batcher relies on a CLEAR error for B=0
    # instead of an opaque XLA shape failure deep in the compiled core
    def test_sosfilt_empty_batch(self):
        sos = iir.butterworth(2, 0.2, "lowpass")
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_sosfilt(sos, np.empty((0, 64), np.float32))

    def test_lfilter_empty_batch(self):
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_lfilter([1.0], [1.0, -0.5],
                                    np.empty((0, 64), np.float32))

    def test_resample_empty_batch(self):
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_resample_poly(
                np.empty((0, 64), np.float32), 3, 2)

    def test_stft_empty_batch(self):
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_stft(np.empty((0, 256), np.float32),
                                 128, 64)

    def test_empty_leading_dim_also_caught(self):
        sos = iir.butterworth(2, 0.2, "lowpass")
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_sosfilt(
                sos, np.empty((0, 4, 64), np.float32))

    def test_oracle_path_same_contract(self):
        sos = iir.butterworth(2, 0.2, "lowpass")
        with pytest.raises(ValueError, match="empty batch"):
            batched.batched_sosfilt(
                sos, np.empty((0, 64), np.float32), simd=False)


class TestHandleRace:
    def test_concurrent_same_key_builds_once(self):
        # regression: before the per-key build lock two threads could
        # both miss, both trace, and the duplicate insert could evict
        # a live neighbor.  A slow builder makes the old race
        # deterministic: every thread piles into the build window.
        import threading
        import time as _time

        builds = []
        results = []
        start = threading.Barrier(6)

        def builder():
            builds.append(threading.get_ident())
            _time.sleep(0.05)          # hold the build window open
            return lambda *a: "built"

        def worker():
            start.wait()
            results.append(
                batched._get_handle(("race", "same-key"), builder))

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(builds) == 1          # exactly one construction
        assert len({id(h) for h in results}) == 1   # one shared handle
        info = batched.handle_cache_info()
        assert info["misses"] == 1 and info["hits"] == 5
        assert not batched._build_locks   # build-lock map drained

    def test_distinct_keys_build_concurrently(self):
        # the per-key locks must serialize only same-key builds: two
        # different keys' slow builds overlap in wall time
        import threading
        import time as _time

        windows = {}

        def make_builder(tag):
            def builder():
                t0 = _time.perf_counter()
                _time.sleep(0.05)
                windows[tag] = (t0, _time.perf_counter())
                return lambda *a: tag
            return builder

        threads = [
            threading.Thread(
                target=lambda tag=tag: batched._get_handle(
                    ("race2", tag), make_builder(tag)))
            for tag in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        (a0, a1), (b0, b1) = windows["a"], windows["b"]
        assert a0 < b1 and b0 < a1     # the build windows overlapped

    def test_failed_build_releases_the_key(self):
        def bad_builder():
            raise RuntimeError("trace failed")

        with pytest.raises(RuntimeError, match="trace failed"):
            batched._get_handle(("race3", "key"), bad_builder)
        assert not batched._build_locks
        # the key is retryable: a later good builder succeeds
        h = batched._get_handle(("race3", "key"),
                                lambda: (lambda *a: "ok"))
        assert h("x") == "ok"
