"""Property-based invariants (hypothesis) across op families.

The reference's tests check oracle agreement at sampled sizes; these pin
down *algebraic* contracts that hold for every input — linearity,
adjointness between convolve and correlate, filter-bank energy
conservation, normalization range — so a regression that preserves
oracle parity but breaks structure (e.g. a flipped kernel) still fails.
"""

import numpy as np
import pytest

# environments without hypothesis skip the module cleanly instead of
# erroring at collection (the driver image does not ship it)
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from veles.simd_tpu.ops import arithmetic as ar
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import convolve2d as cv2
from veles.simd_tpu.ops import correlate as cr
from veles.simd_tpu.ops import normalize as nz
from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType, scaling_coefficients

# slow tier: hypothesis sweeps — excluded from `make tests-quick`
pytestmark = pytest.mark.slow

SETTINGS = dict(max_examples=20, deadline=None)


def _signal(min_size=8, max_size=300):
    return st.lists(
        st.floats(-100, 100, width=32), min_size=min_size,
        max_size=max_size).map(lambda v: np.asarray(v, np.float32))


@settings(**SETTINGS)
@given(_signal(), st.integers(1, 7), st.floats(-5, 5, width=32))
def test_convolution_is_linear(x, klen, alpha):
    h = np.linspace(-1, 1, klen).astype(np.float32)
    lhs = np.asarray(cv.convolve_simd((alpha * x).astype(np.float32), h))
    rhs = alpha * np.asarray(cv.convolve_simd(x, h))
    np.testing.assert_allclose(lhs, rhs, atol=2e-2)


@settings(**SETTINGS)
@given(_signal(min_size=16), st.integers(2, 8))
def test_correlate_is_convolve_with_reversed_kernel(x, klen):
    h = (np.arange(klen) - klen / 3).astype(np.float32)
    corr = np.asarray(cr.cross_correlate_simd(x, h))
    conv = np.asarray(cv.convolve_simd(x, h[::-1].copy()))
    np.testing.assert_allclose(corr, conv, atol=1e-3)


@settings(**SETTINGS)
@given(_signal(min_size=32, max_size=256).filter(lambda v: len(v) % 2 == 0),
       st.sampled_from([2, 4, 8, 12]))
def test_dwt_periodic_conserves_energy(x, order):
    """Orthonormal filter bank + periodic extension: Parseval."""
    hi, lo = wv.wavelet_apply(WaveletType.DAUBECHIES, order,
                              wv.ExtensionType.PERIODIC, x)
    e_in = float(np.sum(np.asarray(x, np.float64) ** 2))
    e_out = float(np.sum(np.asarray(hi, np.float64) ** 2)
                  + np.sum(np.asarray(lo, np.float64) ** 2))
    assert e_out == pytest.approx(e_in, rel=1e-3, abs=1e-3)


@settings(**SETTINGS)
@given(st.sampled_from(["daub", "sym", "coif"]), st.data())
def test_filters_are_orthonormal_qmf(family, data):
    wtype = {"daub": WaveletType.DAUBECHIES, "sym": WaveletType.SYMLET,
             "coif": WaveletType.COIFLET}[family]
    from veles.simd_tpu.ops.wavelet_coeffs import supported_orders

    order = data.draw(st.sampled_from(sorted(supported_orders(wtype))))
    c = np.asarray(scaling_coefficients(wtype, order), np.float64)
    # the reference's tables mix conventions and ours mirror them
    # exactly: Daubechies rows sum to sqrt(2) (orthonormal, energy 1),
    # Symlets/Coiflets to 1 (DC gain 1, energy 1/2) — verified against
    # src/{daubechies,symlets,coiflets}.c row sums.  The filter bank
    # rescales internally so the transform is orthonormal either way.
    # Tolerances follow provenance: derived Daubechies/Coiflets are
    # near machine-exact; Symlets are stored verbatim from the published
    # table, whose own generation error reaches ~2e-5 at high orders
    # (measured: energy drift 2.2e-5, orthogonality 9.1e-6 at order 68).
    if wtype is WaveletType.DAUBECHIES:
        want_sum, want_energy = np.sqrt(2.0), 1.0
    else:
        want_sum, want_energy = 1.0, 0.5
    tol = 5e-5 if wtype is WaveletType.SYMLET else 1e-8
    assert np.sum(c) == pytest.approx(want_sum, abs=tol)
    assert np.sum(c * c) == pytest.approx(want_energy, abs=tol)
    # double-shift orthogonality survives any scaling
    for shift in range(2, len(c), 2):
        assert np.dot(c[:-shift], c[shift:]) == pytest.approx(
            0.0, abs=max(tol / 2, 1e-8))


@settings(**SETTINGS)
@given(_signal(min_size=3).filter(lambda v: v.max() > v.min()))
def test_minmax1d_brackets_every_sample(x):
    mn, mx = nz.minmax1D(x)
    assert float(mn) == pytest.approx(float(x.min()), abs=1e-6)
    assert float(mx) == pytest.approx(float(x.max()), abs=1e-6)


@settings(**SETTINGS)
@given(st.integers(1, 1 << 30))
def test_next_pow2_is_tight(n):
    from veles.simd_tpu.utils.memory import next_highest_power_of_2

    p = next_highest_power_of_2(n)
    assert p >= n and p & (p - 1) == 0
    assert p == 1 or p // 2 < n


@settings(**SETTINGS)
@given(st.lists(st.integers(-32768, 32767), min_size=1, max_size=200))
def test_int16_float_roundtrip_exact(vals):
    i16 = np.asarray(vals, np.int16)
    back = np.asarray(ar.float_to_int16(ar.int16_to_float(i16)))
    np.testing.assert_array_equal(back, i16)


# --------------------------------------------------------------------------
# Pallas filter-bank kernel invariants (interpret mode on CPU)
# --------------------------------------------------------------------------

def _fb(x_ext, filters, stride, dilation, n_out):
    from veles.simd_tpu.ops.pallas_kernels import filter_bank_pallas

    return [np.asarray(o) for o in filter_bank_pallas(
        x_ext, filters, stride, dilation, n_out, interpret=True)]


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.integers(2, 9),
       st.sampled_from([1, 2]), st.floats(-3, 3, width=32))
def test_pallas_filter_bank_is_linear(seed, order, stride, alpha):
    rng = np.random.RandomState(seed)
    n_out = 24
    need = (n_out - 1) * stride + order
    x_ext = rng.randn(3, need).astype(np.float32)
    f = rng.randn(2, order).astype(np.float32)
    base = _fb(x_ext, f, stride, 1, n_out)
    scaled = _fb((alpha * x_ext).astype(np.float32), f, stride, 1, n_out)
    for b, s in zip(base, scaled):
        np.testing.assert_allclose(s, alpha * b, atol=5e-3)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.integers(2, 9))
def test_pallas_superposition_over_channels(seed, order):
    """A 2-channel call equals two 1-channel calls (channels independent)."""
    rng = np.random.RandomState(seed)
    n_out = 24
    x_ext = rng.randn(2, n_out + order).astype(np.float32)
    f = rng.randn(2, order).astype(np.float32)
    both = _fb(x_ext, f, 1, 1, n_out)
    solo0 = _fb(x_ext, f[:1], 1, 1, n_out)[0]
    solo1 = _fb(x_ext, f[1:], 1, 1, n_out)[0]
    np.testing.assert_allclose(both[0], solo0, atol=1e-5)
    np.testing.assert_allclose(both[1], solo1, atol=1e-5)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.integers(1, 8))
def test_pallas_shift_equivariance(seed, shift):
    """Shifting the input by `shift` shifts a stride-1 output by `shift`."""
    rng = np.random.RandomState(seed)
    order, n_out = 5, 32
    x_ext = rng.randn(2, n_out + order + shift).astype(np.float32)
    f = rng.randn(1, order).astype(np.float32)
    (full,) = _fb(x_ext, f, 1, 1, n_out + shift)
    (shifted,) = _fb(x_ext[:, shift:], f, 1, 1, n_out)
    np.testing.assert_allclose(shifted, full[:, shift:], atol=1e-5)


@settings(max_examples=8, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.integers(2, 6), st.integers(1, 3))
def test_pallas_dilation_equals_upsampled_taps(seed, order, dilation):
    """Dilated taps == zero-upsampled taps at dilation 1 (a-trous identity,
    src/wavelet.c:211-246)."""
    rng = np.random.RandomState(seed)
    n_out = 16
    x_ext = rng.randn(2, n_out + order * dilation + 2).astype(np.float32)
    f = rng.randn(1, order).astype(np.float32)
    up = np.zeros((1, (order - 1) * dilation + 1), np.float32)
    up[0, ::dilation] = f[0]
    (dil,) = _fb(x_ext, f, 1, dilation, n_out)
    (ups,) = _fb(x_ext, up, 1, 1, n_out)
    np.testing.assert_allclose(dil, ups, atol=1e-5)


# --------------------------------------------------------------------------
# 2D convolution + wavelet synthesis invariants
# --------------------------------------------------------------------------


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.floats(-3, 3, width=32))
def test_conv2d_is_linear(seed, alpha):
    rng = np.random.RandomState(seed)
    x = rng.randn(9, 11).astype(np.float32)
    h = rng.randn(3, 2).astype(np.float32)
    lhs = np.asarray(cv2.convolve2d((alpha * x).astype(np.float32), h))
    rhs = alpha * np.asarray(cv2.convolve2d(x, h))
    np.testing.assert_allclose(lhs, rhs, atol=1e-2)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1))
def test_conv2d_commutes_with_transpose(seed):
    """conv2d(x.T, h.T) == conv2d(x, h).T — axis symmetry."""
    rng = np.random.RandomState(seed)
    x = rng.randn(8, 13).astype(np.float32)
    h = rng.randn(4, 3).astype(np.float32)
    a = np.asarray(cv2.convolve2d(np.ascontiguousarray(x.T),
                                  np.ascontiguousarray(h.T)))
    b = np.asarray(cv2.convolve2d(x, h)).T
    np.testing.assert_allclose(a, b, atol=1e-4)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1), st.sampled_from([2, 6, 8, 12]),
       st.sampled_from([16, 32, 64]))
def test_dwt_synthesis_inverts_analysis(seed, order, n):
    rng = np.random.RandomState(seed)
    x = rng.randn(n).astype(np.float32)
    hi, lo = wv.wavelet_apply("daub", order, wv.ExtensionType.PERIODIC, x)
    rec = wv.wavelet_reconstruct("daub", order, hi, lo)
    np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1))
def test_streaming_equals_one_shot(seed):
    rng = np.random.RandomState(seed)
    chunk = int(rng.randint(8, 40))
    n_chunks = int(rng.randint(1, 5))
    k = int(rng.randint(1, 3 * chunk))      # carry can exceed a chunk
    x = rng.randn(chunk * n_chunks).astype(np.float32)
    h = rng.randn(k).astype(np.float32)
    sc = cv.StreamingConvolution(h, chunk)
    parts = [np.asarray(sc.process(x[i:i + chunk]))
             for i in range(0, x.size, chunk)]
    parts.append(np.asarray(sc.flush()))
    np.testing.assert_allclose(np.concatenate(parts), cv.convolve_na(x, h),
                               atol=1e-3)


# --------------------------------------------------------------------------
# non-periodic synthesis invariants
# --------------------------------------------------------------------------


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1),
       st.sampled_from(list(wv.ExtensionType)),
       st.sampled_from([4, 8, 12]))
def test_dwt_synthesis_is_consistent_every_ext(seed, ext, order):
    """For every extension, re-analyzing the reconstruction reproduces
    the coefficients — the least-squares guarantee that holds even where
    the non-periodic analysis is rank-deficient."""
    rng = np.random.RandomState(seed)
    x = rng.randn(128).astype(np.float32)
    hi, lo = wv.wavelet_apply_na("daub", order, ext, x)
    rec = wv.wavelet_reconstruct_na("daub", order, hi, lo, ext=ext)
    hi2, lo2 = wv.wavelet_apply_na("daub", order, ext, rec)
    scale = max(float(np.max(np.abs(hi))), float(np.max(np.abs(lo))), 1e-3)
    assert float(np.max(np.abs(hi2 - hi))) < 1e-4 * scale
    assert float(np.max(np.abs(lo2 - lo))) < 1e-4 * scale


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1),
       st.sampled_from([wv.ExtensionType.MIRROR, wv.ExtensionType.CONSTANT,
                        wv.ExtensionType.ZERO]),
       st.integers(1, 3))
def test_swt_nonperiodic_synthesis_roundtrips(seed, ext, level):
    """The SWT frame stays full-rank under every extension: analysis →
    synthesis recovers the signal (within boundary conditioning)."""
    rng = np.random.RandomState(seed)
    x = rng.randn(192).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply_na("daub", 6, level, ext, x)
    rec = wv.stationary_wavelet_reconstruct_na("daub", 6, level, hi, lo,
                                               ext=ext)
    assert float(np.max(np.abs(rec - x))) < 5e-3


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2 ** 31 - 1),
       st.sampled_from(list(wv.ExtensionType)))
def test_synthesis_is_linear_every_ext(seed, ext):
    """Reconstruction is a linear map for every extension (the Woodbury
    correction is linear in the bands)."""
    rng = np.random.RandomState(seed)
    hi = rng.randn(64).astype(np.float32)
    lo = rng.randn(64).astype(np.float32)
    a = np.float32(1.7)
    r1 = wv.wavelet_reconstruct_na("daub", 8, (a * hi).astype(np.float32),
                                   (a * lo).astype(np.float32), ext=ext)
    r2 = a * wv.wavelet_reconstruct_na("daub", 8, hi, lo, ext=ext)
    np.testing.assert_allclose(r1, r2, atol=1e-3)
