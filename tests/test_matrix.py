"""Tests for veles.simd_tpu.ops.matrix.

Port of ``tests/matrix.cc``: XLA-vs-oracle cross-validation with the
reference's tolerance (ASSERT_NEAR 0.1, ``tests/matrix.cc:94-98``),
golden small-matrix GEMM (``tests/matrix.cc:100-157``), and the
parameterized size sweep (``tests/matrix.cc:159-204``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import matrix as mx

RNG = np.random.RandomState(7)

# (w1, h1, w2) with h2 = w1 — from the reference sweep plus MXU-shaped sizes
SWEEP = [
    (1, 1, 1),
    (3, 3, 3),
    (99, 99, 99),
    (125, 299, 64),
    (128, 300, 1000),
    (256, 300, 1000),
    (512, 512, 512),
]


@pytest.mark.parametrize("w,h", [(1, 1), (3, 7), (128, 64), (299, 125)])
@pytest.mark.parametrize("op", [mx.matrix_add, mx.matrix_sub])
def test_add_sub(op, w, h):
    m1 = RNG.randn(h, w).astype(np.float32)
    m2 = RNG.randn(h, w).astype(np.float32)
    np.testing.assert_allclose(np.asarray(op(m1, m2, simd=True)),
                               op(m1, m2, simd=False), rtol=1e-6)


@pytest.mark.parametrize("w1,h1,w2", SWEEP)
def test_multiply_vs_oracle(w1, h1, w2):
    m1 = RNG.randn(h1, w1).astype(np.float32)
    m2 = RNG.randn(w1, w2).astype(np.float32)
    got = np.asarray(mx.matrix_multiply(m1, m2, simd=True))
    want = mx.matrix_multiply(m1, m2, simd=False)
    assert got.shape == (h1, w2)
    np.testing.assert_allclose(got, want, atol=0.1)  # tests/matrix.cc:98


@pytest.mark.parametrize("w1,h1,w2", SWEEP)
def test_multiply_transposed_vs_oracle(w1, h1, w2):
    m1 = RNG.randn(h1, w1).astype(np.float32)
    m2t = RNG.randn(w2, w1).astype(np.float32)  # B stored transposed
    got = np.asarray(mx.matrix_multiply_transposed(m1, m2t, simd=True))
    want = mx.matrix_multiply_transposed(m1, m2t, simd=False)
    assert got.shape == (h1, w2)
    np.testing.assert_allclose(got, want, atol=0.1)


def test_transposed_agrees_with_straight():
    m1 = RNG.randn(33, 65).astype(np.float32)
    m2 = RNG.randn(65, 17).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(mx.matrix_multiply_transposed(m1, m2.T.copy(), simd=True)),
        np.asarray(mx.matrix_multiply(m1, m2, simd=True)), atol=1e-4)


def test_golden_small_gemm():
    """Small-matrix golden values (tests/matrix.cc:100-157 pattern)."""
    m1 = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    m2 = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)
    np.testing.assert_array_equal(
        np.asarray(mx.matrix_multiply(m1, m2, simd=True)),
        np.array([[19.0, 22.0], [43.0, 50.0]], np.float32))
    np.testing.assert_array_equal(
        np.asarray(mx.matrix_add(m1, m2, simd=True)),
        np.array([[6.0, 8.0], [10.0, 12.0]], np.float32))
    np.testing.assert_array_equal(
        np.asarray(mx.matrix_sub(m2, m1, simd=True)),
        np.array([[4.0, 4.0], [4.0, 4.0]], np.float32))


def test_shape_contract_violation():
    """The reference asserts on w1 != h2 (src/matrix.c:257-261); we raise."""
    m1 = np.zeros((4, 5), np.float32)
    m2 = np.zeros((4, 5), np.float32)
    with pytest.raises(ValueError):
        mx.matrix_multiply(m1, m2, simd=True)
    with pytest.raises(ValueError):
        mx.matrix_multiply_transposed(m1, np.zeros((3, 4), np.float32))


def test_gemv():
    m = RNG.randn(300, 256).astype(np.float32)
    v = RNG.randn(256).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(mx.matrix_vector_multiply(m, v, simd=True)),
        mx.matrix_vector_multiply(m, v, simd=False), atol=0.1)


def test_fast_bf16_path_close():
    """bf16 MXU path stays within loose tolerance of f32."""
    m1 = RNG.randn(128, 256).astype(np.float32)
    m2 = RNG.randn(256, 64).astype(np.float32)
    got = np.asarray(mx.matrix_multiply(m1, m2, simd=True, fast=True))
    want = mx.matrix_multiply_novec(m1, m2)
    # bf16 has ~3 decimal digits; relative error scales with sqrt(K)
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.5)
