#!/usr/bin/env python
"""Multi-resolution wavelet analysis: band energies of a chirp.

Runs a 4-level Daubechies-8 DWT cascade (``wavelet_transform``) and a
2-level stationary SWT over a chirp whose frequency rises with time, and
prints each band's energy share — low bands dominate early-signal
content, high bands the late chirp.  Demonstrates the wavelet families,
boundary extensions, and the cascade helpers.

Run:  python examples/wavelet_multires.py
      VELES_SIMD_PLATFORM=cpu python examples/wavelet_multires.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform

maybe_override_platform()

from veles.simd_tpu.ops import wavelet as wv  # noqa: E402
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType  # noqa: E402


def main():
    n = 1 << 14
    t = np.linspace(0, 1, n, dtype=np.float32)
    chirp = np.sin(2 * np.pi * (20 + 400 * t) * t).astype(np.float32)

    # decimated 4-level cascade: [hi_1, hi_2, hi_3, hi_4, lo_4]
    bands = wv.wavelet_transform(WaveletType.DAUBECHIES, 8,
                                 wv.ExtensionType.PERIODIC, chirp, 4)
    total = sum(float(np.sum(np.asarray(b, np.float64) ** 2))
                for b in bands)
    print("DWT daub8, 4 levels (finest -> coarsest + approximation):")
    for i, b in enumerate(bands):
        e = float(np.sum(np.asarray(b, np.float64) ** 2))
        label = f"detail {i + 1}" if i < 4 else "approx  4"
        print(f"  {label}: len={np.asarray(b).shape[-1]:6d} "
              f"energy={100 * e / total:5.1f}%")

    # stationary (undecimated) transform keeps every band full-length
    sbands = wv.stationary_wavelet_transform(
        WaveletType.SYMLET, 8, wv.ExtensionType.MIRROR, chirp, 2)
    print("SWT sym8, 2 levels: band lengths",
          [np.asarray(b).shape[-1] for b in sbands])

    # wavelet packets: the full binary tree splits EVERY band, giving
    # uniform-bandwidth leaves — the right tool when the interesting
    # energy is mid-band (a plain DWT only refines the low end)
    leaves = wv.wavelet_packet_transform(
        WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, chirp, 3)
    energies = [float(np.sum(np.asarray(b, np.float64) ** 2))
                for b in leaves]
    tot = sum(energies)
    peak = int(np.argmax(energies))
    print(f"packet tree, 3 levels: {len(leaves)} uniform leaves; "
          f"leaf {peak} holds {100 * energies[peak] / tot:.1f}% of the "
          "energy")
    back = wv.wavelet_packet_inverse_transform(
        WaveletType.DAUBECHIES, 8, leaves)
    assert float(np.max(np.abs(np.asarray(back) - chirp))) < 5e-4

    # oracle cross-check, the reference's testing discipline
    hi, lo = wv.wavelet_apply(WaveletType.DAUBECHIES, 8,
                              wv.ExtensionType.PERIODIC, chirp)
    hi_na, lo_na = wv.wavelet_apply_na(WaveletType.DAUBECHIES, 8,
                                       wv.ExtensionType.PERIODIC, chirp)
    err = max(float(np.max(np.abs(np.asarray(hi) - hi_na))),
              float(np.max(np.abs(np.asarray(lo) - lo_na))))
    print(f"XLA vs oracle max abs err: {err:.2e}")
    assert err < 5e-4
    print("ok")


if __name__ == "__main__":
    main()
