#!/usr/bin/env python
"""Test runner — parity with the reference's ``make tests`` harness.

The reference's runner (``/root/reference/tests/Tests.make:62-94`` +
``Makefile.am:37-43``) runs each gtest binary under ``timeout 60`` and
``/usr/bin/time -f "peak memory %M Kb"``, appends to ``tests.log``, emits
gtest XML, and fails the build if the log contains ``[FAILED]``.

This runner does the same per test *module*: per-suite timeout, peak-RSS
report, junit XML, accumulated ``tests.log``, and a failure gate — plus
line coverage: each suite runs under the stdlib tracer in
``tools/linecov.py`` (the container has neither ``coverage`` nor
``pytest-cov``), the merged per-module table lands in ``tests.log``, and
the aggregates over ``veles/simd_tpu/obs/`` and ``veles/simd_tpu/
serve/`` are gated by floors (``linecov.DEFAULT_FLOORS``: the
telemetry layer is pure host-side Python, so untested lines there are
plain negligence — VERDICT item 6 — and the serving layer's failure
handling is exactly the code that only runs during outages, so
untraced lines there are untested outage behavior).  ``--no-coverage``
restores the untraced (faster) run; the floors are then skipped.

Run:  python tools/run_tests.py [--timeout 300] [--no-coverage]
      python tools/run_tests.py --cov-floor-obs 75 --cov-floor-serve 70
"""

import argparse
import glob
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import linecov  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=300,
                    help="per-suite timeout in seconds (Tests.make used "
                    "60); doubled automatically when coverage tracing "
                    "is on")
    ap.add_argument("--log", default=os.path.join(REPO, "tests.log"))
    ap.add_argument("--no-coverage", action="store_true",
                    help="skip the line tracer (faster; no table, no "
                    "floor)")
    ap.add_argument("--cov-floor-obs", type=float,
                    default=linecov.DEFAULT_FLOORS["veles/simd_tpu/obs"],
                    help="minimum aggregate line coverage %% for "
                    "veles/simd_tpu/obs/ (0 disables)")
    ap.add_argument(
        "--cov-floor-serve", type=float,
        default=linecov.DEFAULT_FLOORS["veles/simd_tpu/serve"],
        help="minimum aggregate line coverage %% for "
        "veles/simd_tpu/serve/ (0 disables)")
    args = ap.parse_args()
    coverage = not args.no_coverage
    timeout = args.timeout * (2 if coverage else 1)

    suites = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    failures = []
    cov_files = []
    with open(args.log, "w") as log:
        for suite in suites:
            name = os.path.basename(suite)
            xml = os.path.join(REPO, f"test_results_{name[:-3]}.xml")
            pytest_args = [suite, "-q", f"--junitxml={xml}"]
            # per-test timeout well below the suite budget so a hung test
            # gets a named traceback from pytest-timeout before the outer
            # SIGKILL (which loses the XML and the test name)
            if _has_pytest_timeout():
                pytest_args.append(f"--timeout={max(30, timeout // 2)}")
            # per-suite peak RSS, like the reference's `/usr/bin/time -f
            # "peak memory %M Kb"` (Tests.make:87); GNU time isn't in the
            # image and RUSAGE_CHILDREN.ru_maxrss is a monotonic max over
            # ALL children, so the child reports its own ru_maxrss at exit
            wrapper = (
                "import atexit, resource, runpy, sys; "
                "atexit.register(lambda: print("
                "f'__peak_rss_kb={resource.getrusage("
                "resource.RUSAGE_SELF).ru_maxrss}', file=sys.stderr)); ")
            if coverage:
                cov_out = os.path.join(
                    REPO, f"coverage_{name[:-3]}.json")
                cov_files.append(cov_out)
                # the tracer installs BEFORE pytest imports veles
                # modules, so import-time lines count too
                tools_dir = os.path.dirname(os.path.abspath(__file__))
                wrapper += (
                    f"sys.path.insert(0, {tools_dir!r}); "
                    "import linecov; "
                    f"linecov.start({os.path.join(REPO, 'veles')!r}, "
                    f"{cov_out!r}); ")
            wrapper += (
                f"sys.argv = ['pytest'] + {pytest_args!r}; "
                "runpy.run_module('pytest', run_name='__main__')")
            cmd = [sys.executable, "-c", wrapper]
            try:
                proc = subprocess.run(cmd, cwd=REPO,
                                      capture_output=True, text=True,
                                      timeout=timeout + 60)
                out = proc.stdout + proc.stderr
                ok = proc.returncode == 0
            except subprocess.TimeoutExpired as e:
                out = (e.stdout or "") + (e.stderr or "") + "\n[TIMEOUT]"
                ok = False
            peak_kb = "?"
            for tok in out.splitlines():
                if tok.startswith("__peak_rss_kb="):
                    peak_kb = tok.split("=", 1)[1]
            status = "OK" if ok else "[FAILED]"
            line = f"=== {name}: {status} (peak memory {peak_kb} Kb)"
            print(line)
            log.write(line + "\n" + out + "\n")
            if not ok:
                failures.append(name)

        rc = 0
        if coverage:
            merged = linecov.merge(cov_files)
            table = linecov.table(merged, REPO, scope="veles")
            log.write("\n=== line coverage (tools/linecov.py) ===\n")
            log.write(table)
            for scope, floor in (("obs", args.cov_floor_obs),
                                 ("serve", args.cov_floor_serve)):
                pct = linecov.aggregate_pct(
                    merged, REPO, scope=os.path.join(
                        "veles", "simd_tpu", scope))
                floor_line = (f"veles/simd_tpu/{scope}/ aggregate: "
                              f"{pct:.1f}% (floor {floor:.0f}%)")
                print(floor_line)
                log.write(floor_line + "\n")
                if floor > 0 and pct < floor:
                    print(f"{scope} coverage below floor — failing "
                          "the run")
                    log.write(f"[FAILED] {scope} coverage floor\n")
                    rc = 1
            for f in cov_files:
                if os.path.exists(f):
                    os.unlink(f)

    # the reference greps tests.log for [FAILED] to gate the build
    if failures:
        print(f"\n{len(failures)} suite(s) FAILED: {', '.join(failures)}")
        return 1
    if rc:
        print(f"\nsuites green but coverage floor FAILED; log at "
              f"{args.log}")
        return rc
    print(f"\nall {len(suites)} suites passed; log at {args.log}")
    return rc


def _has_pytest_timeout():
    try:
        import pytest_timeout  # noqa: F401

        return True
    except ImportError:
        return False


if __name__ == "__main__":
    sys.exit(main())
