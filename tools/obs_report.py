#!/usr/bin/env python
"""Pretty-print a saved telemetry snapshot (``veles.simd_tpu.obs``).

Reads a JSON snapshot — either one written by ``obs.save(path)`` or a
``BENCH_DETAILS.json`` produced by ``bench.py`` (whose entries embed a
compact per-config telemetry dict) — and renders the human table the
live ``obs.report()`` call would print, followed by a dispatch-latency
section: per-op p50/p95/p99 from the ``span.*`` histograms, warmup
(first call, incl. trace+compile) separated from steady-state, and a
Serving section when the snapshot carries ``serve_*`` metrics:
queue/tenant depths, per-status outcome tallies with shed and
deadline-miss rates, per-(op, status) request-latency quantiles,
degraded-batch counts, latest breaker states, and the request-axis +
per-tenant SLO summaries (BENCH_DETAILS mode gets the per-config
``serve_*`` counter block), and a Fleet section when the snapshot
carries the fleet axis (obs v5: the ``ReplicaGroup`` collector's
per-replica windowed series — last value, delta, flap count), a
Control section when it carries the obs v7 ``scaler`` block (the
autoscaler's action/no-op tallies and decision tail), and a
goodput-recovery scoreboard for BENCH_DETAILS entries carrying
``recovered`` evidence (``GOODPUT_DETAILS.json``: padding waste
before/after per shape class).  ``--prometheus`` converts a full
snapshot to the Prometheus text exposition format instead, so a file
captured on a TPU host can be pushed through a gateway later.

Usage:  python tools/obs_report.py SNAPSHOT.json
        python tools/obs_report.py --prometheus SNAPSHOT.json
        python tools/obs_report.py BENCH_DETAILS.json
        make obs-report SNAPSHOT=telemetry.json
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

from veles.simd_tpu.obs import export  # noqa: E402


def _fmt_s(v) -> str:
    return "-" if v is None else "%.1e" % v


def _render_span_summary(spans, indent="  ") -> list:
    """Lines for a bench-style span summary dict
    (``{name: {phase: {count, total_s, p50_s, p95_s, p99_s}}}``)."""
    lines = []
    for name in sorted(spans):
        for phase in sorted(spans[name]):
            s = spans[name][phase]
            lines.append(
                "%s%-32s %-7s n=%-6d p50=%s p95=%s p99=%s total=%s"
                % (indent, name, phase, s.get("count", 0),
                   _fmt_s(s.get("p50_s")), _fmt_s(s.get("p95_s")),
                   _fmt_s(s.get("p99_s")), _fmt_s(s.get("total_s"))))
    return lines


def _latency_section(snap) -> str:
    """Per-op host-dispatch latency from a full snapshot's ``span.*``
    histograms: p50/p95/p99 seconds, warmup vs. steady-state."""
    spans = export.span_summary(snap)
    if not spans:
        return ""
    lines = ["", "dispatch latency (seconds; warmup = first call, "
             "incl. trace+compile):"]
    lines += _render_span_summary(spans)
    return "\n".join(lines) + "\n"


def _artifact_section(snap) -> str:
    """The AOT artifact store (zero-warmup cold start): path, mode,
    and hit/miss/stale/eviction traffic — rendered beside the tune
    cache whenever the store is bound or saw traffic."""
    caches = (snap.get("caches") or {})
    st = caches.get("artifact_store")
    if not isinstance(st, dict):
        return ""
    traffic = sum(int(st.get(k) or 0) for k in
                  ("hits", "misses", "stale", "load_errors", "stores",
                   "preloaded"))
    if st.get("path") is None and not traffic:
        return ""
    lines = ["", "artifact store (AOT warm pack):",
             "  path=%s  mode=%s  entries=%s/%s  runners=%s"
             % (st.get("path"), st.get("mode"), st.get("size"),
                st.get("capacity"), st.get("runners")),
             "  hits=%s misses=%s stale=%s load_errors=%s stores=%s "
             "evictions=%s preloaded=%s"
             % tuple(st.get(k, 0) for k in
                     ("hits", "misses", "stale", "load_errors",
                      "stores", "evictions", "preloaded"))]
    refused = {k: st[k] for k in ("write_refused", "save_refused",
                                  "export_unsupported")
               if st.get(k)}
    if refused:
        lines.append("  " + "  ".join("%s=%s" % kv
                                      for kv in sorted(
                                          refused.items())))
    return "\n".join(lines) + "\n"


def _serving_section(snap) -> str:
    """The serving layer's story (obs v4): depths, outcome tallies
    with shed/miss rates, per-(op, status) request-latency quantiles,
    breaker states, and the request-axis + per-tenant SLO summaries."""
    s = export.serving_summary(snap)
    if s is None:
        return ""
    lines = ["", "serving:"]
    if s["gauges"]:
        lines.append("  " + "  ".join(
            "%s=%g" % kv for kv in sorted(s["gauges"].items())
            if not kv[0].startswith("slo_")))
    outcome = "  ".join("%s=%d" % kv
                        for kv in sorted(s["by_status"].items()))
    lines.append("  submitted=%d  %s" % (s["submitted"], outcome))
    rate = "-" if s["shed_rate"] is None else \
        "%.1f%%" % (100 * s["shed_rate"])
    mrate = "-" if s["deadline_miss_rate"] is None else \
        "%.1f%%" % (100 * s["deadline_miss_rate"])
    lines.append("  shed=%d (%s)  deadline_misses=%d (%s)  "
                 "degraded_batches=%d  breaker_shed=%d"
                 % (s["shed"], rate, s["deadline_misses"], mrate,
                    s["degraded_batches"], s["breaker_shed"]))
    if s["latency"]:
        lines.append("  request latency by op/status (s):")
        for key, q in s["latency"].items():
            lines.append(
                "    %-28s n=%-6d p50=%s p95=%s p99=%s"
                % (key, q["count"], _fmt_s(q.get("p50")),
                   _fmt_s(q.get("p95")), _fmt_s(q.get("p99"))))
    if s["breaker_states"]:
        lines.append("  breaker states (latest transition):")
        for key, state in s["breaker_states"].items():
            lines.append("    %-48s %s" % (key, state))
    req = s.get("requests")
    if req:
        lines.append(
            "  request axis: started=%s finished=%s open=%s  %s"
            % (req.get("started"), req.get("finished"),
               req.get("open"),
               " ".join("%s=%s" % kv for kv in sorted(
                   (req.get("by_status") or {}).items()))))
    slo = s.get("slo") or {}
    for tenant, acct in sorted((slo.get("accounts") or {}).items()):
        lines.append(
            "  slo %-12s requests=%-6d hit_rate=%s burn=%s%s"
            % (tenant, acct.get("requests", 0),
               acct.get("hit_rate_observed"), acct.get("burn_rate"),
               "  BREACHED" if acct.get("breached") else ""))
    return "\n".join(lines) + "\n"


def _fleet_section(snap) -> str:
    """The fleet axis (obs v5): per-replica windowed series captured
    by the ``ReplicaGroup`` collector — last value, windowed delta,
    and flap count per (replica, series), plus the tick/window
    bookkeeping.  Rendered whenever the snapshot carries a non-empty
    ``fleet`` block (``obs.snapshot()`` embeds it; pre-v5 snapshots
    simply lack the key)."""
    fleet = snap.get("fleet")
    if not isinstance(fleet, dict) or not fleet.get("series"):
        return ""
    lines = ["", "fleet (windowed series, %s ticks @ %ss, window %s):"
             % (fleet.get("ticks"), fleet.get("tick_s"),
                fleet.get("window"))]
    for rid in sorted(fleet["series"]):
        for name in sorted(fleet["series"][rid]):
            samples = fleet["series"][rid][name] or []
            vals = [s[1] for s in samples]
            delta = vals[-1] - vals[0] if len(vals) >= 2 else None
            flaps = sum(1 for a, b in zip(vals, vals[1:])
                        if abs(b - a) > 1e-9)
            lines.append(
                "  %-10s %-24s last=%-10g n=%-5d delta=%s flaps=%d"
                % (rid, name, vals[-1] if vals else float("nan"),
                   len(vals),
                   "-" if delta is None else "%g" % delta, flaps))
    return "\n".join(lines) + "\n"


def _history_section(snap) -> str:
    """The history axis (obs v6): durable-journal health and the
    incident ledger.  Rendered whenever the snapshot carries a
    ``journal`` or ``incidents`` block (``obs.snapshot()`` embeds
    both; pre-v6 snapshots simply lack the keys)."""
    journal = snap.get("journal")
    incidents = snap.get("incidents")
    if not isinstance(journal, dict) \
            and not isinstance(incidents, dict):
        return ""
    lines = ["", "history (obs v6):"]
    if isinstance(journal, dict):
        if journal.get("armed"):
            lines.append(
                "  journal armed @ %s" % journal.get("dir"))
            lines.append(
                "    records=%s dropped=%s rotations=%s pruned=%s "
                "lag_s=%s" % (
                    journal.get("records"), journal.get("dropped"),
                    journal.get("rotations"), journal.get("pruned"),
                    round(journal["lag_s"], 3)
                    if isinstance(journal.get("lag_s"), float)
                    else journal.get("lag_s")))
        else:
            lines.append("  journal disarmed "
                         "($VELES_SIMD_JOURNAL_DIR unset)")
    if isinstance(incidents, dict):
        lines.append("  incidents: %s open / %s closed over %s ticks"
                     % (incidents.get("open"),
                        incidents.get("closed"),
                        incidents.get("ticks")))
        for inc in incidents.get("incidents") or []:
            lines.append(
                "    %-16s %-20s %-7s firing=%-4s close=%s" % (
                    inc.get("id"), inc.get("rule"),
                    inc.get("state"), inc.get("ticks_firing"),
                    inc.get("close_reason") or "-"))
    return "\n".join(lines) + "\n"


def _control_section(snap) -> str:
    """The control axis (obs v7): the autoscaler block
    ``obs.snapshot()`` embeds — armed/running state, bounds, action
    and typed-no-op tallies, the last committed action, and the tail
    of the decision ledger.  Pre-v7 snapshots simply lack the key."""
    scaler = snap.get("scaler")
    if not isinstance(scaler, dict):
        return ""
    lines = ["", "control (obs v7):"]
    if not scaler.get("armed"):
        lines.append("  scaler disarmed (ReplicaGroup(scaler=True) "
                     "or $VELES_SIMD_SCALER arms it)")
        return "\n".join(lines) + "\n"
    rep = scaler.get("replicas") or {}
    lines.append(
        "  scaler armed  running=%s  ticks=%s  alive=%s in "
        "[%s..%s]  cooldown_remaining=%ss" % (
            scaler.get("running"), scaler.get("ticks"),
            rep.get("alive"), rep.get("min"), rep.get("max"),
            scaler.get("cooldown_remaining_s")))
    acts = scaler.get("actions") or {}
    noops = scaler.get("noops") or {}
    if acts:
        lines.append("  actions: " + "  ".join(
            "%s=%s" % kv for kv in sorted(acts.items())))
    if noops:
        lines.append("  no-ops:  " + "  ".join(
            "%s=%s" % kv for kv in sorted(noops.items())))
    last = scaler.get("last_action")
    if last:
        lines.append(
            "  last action: %s rule=%s replica=%s incident=%s" % (
                last.get("action"), last.get("rule"),
                last.get("replica"), last.get("incident_id")))
    tail = scaler.get("decisions") or []
    if tail:
        lines.append("  decisions (last %d):" % min(len(tail), 8))
        for d in tail[-8:]:
            lines.append(
                "    t=%-12s %-10s rule=%-14s reason=%-18s "
                "replica=%s" % (
                    "%g" % (d.get("t") or 0.0),
                    d.get("action") or "-", d.get("rule") or "-",
                    d.get("reason"), d.get("replica") or "-"))
    return "\n".join(lines) + "\n"


def _bench_serving_lines(counters: dict, indent="  ") -> list:
    """The BENCH_DETAILS-mode serving block: a per-config tally of
    the ``serve_*`` counters the telemetry dict embeds."""
    serve = {k: v for k, v in sorted(counters.items())
             if k.startswith(("serve_", "slo_"))}
    if not serve:
        return []
    lines = [indent + "serving counters:"]
    for k, v in serve.items():
        lines.append("%s  %-56s %8d" % (indent, k, v))
    return lines


def _recovered_lines(rec, indent="  ") -> list:
    """The goodput-recovery scoreboard for one bench entry carrying
    ``recovered`` evidence (the saturation A/B in
    ``GOODPUT_DETAILS.json``): dispatched-footprint waste before vs
    after continuous batching + ragged packing, the refilled-row
    tally, and the per-shape-class waste table.  A class blank on one
    side re-bucketed (packing folds the short stft pow2 classes into
    ``stft|ragged``) — the fold IS the mechanism, so it renders
    as-is rather than being papered over."""
    if not isinstance(rec, dict):
        return []

    def pct(v):
        return "-" if v is None else "%.1f%%" % (100.0 * v)

    lines = ["%sgoodput recovery scoreboard:" % indent,
             "%s  padding waste %s -> %s  refilled_rows=%s  "
             "useful=%s dispatched=%s"
             % (indent, pct(rec.get("waste_before")),
                pct(rec.get("waste_after")),
                rec.get("refilled_rows"),
                rec.get("useful_samples"),
                rec.get("dispatched_samples"))]
    by = rec.get("by_class") or {}
    if by:
        lines.append("%s  waste by shape class (before -> after):"
                     % indent)
        for key in sorted(by):
            w = by[key]
            lines.append("%s    %-28s %8s -> %8s"
                         % (indent, key, pct(w.get("waste_before")),
                            pct(w.get("waste_after"))))
    return lines


def _roofline_lines(roof, indent="  ") -> list:
    """Measured vs analytical roofline % for one bench entry."""
    if not roof:
        return []
    lines = ["%sroofline: measured %.0f%% of the f32-%s bound "
             "(%.1f TFLOP/s eff)"
             % (indent, roof.get("pct_of_roofline", 0.0),
                roof.get("precision", "?").upper(),
                roof.get("tflops_effective", 0.0))]
    ana = roof.get("analytical_pct_of_roofline")
    if ana is not None:
        lines.append(
            "%sanalytical (%s, XLA flops=%.3g): %.0f%% — "
            "disagreement %.0f%%"
            % (indent, roof.get("analytical_route", "?"),
               roof.get("xla_flops", 0.0), ana,
               roof.get("disagreement_pct", 0.0)))
    return lines


def _render_bench_details(entries) -> str:
    """BENCH_DETAILS.json mode: one telemetry block per bench config."""
    lines = []
    for e in entries:
        if "metric" not in e and "telemetry" not in e:
            continue        # tail entry (skipped_stages bookkeeping)
        tel = e.get("telemetry")
        lines.append("=== %s ===" % e.get("metric", "(unnamed config)"))
        lines += _roofline_lines(e.get("roofline"))
        lines += _recovered_lines(e.get("recovered"))
        if tel is None:
            lines.append("  (no telemetry recorded)")
            continue
        lines.append("  compiles=%s cache_hits=%s cache_misses=%s "
                     "events_dropped=%s" % (
                         tel.get("compiles"), tel.get("cache_hits"),
                         tel.get("cache_misses"),
                         tel.get("events_dropped")))
        for k, v in sorted(tel.get("counters", {}).items()):
            lines.append("  %-60s %8d" % (k, v))
        lines += _bench_serving_lines(tel.get("counters", {}))
        for d in tel.get("decisions", []):
            extras = ", ".join(
                "%s=%s" % (k, v) for k, v in d.items()
                if k not in ("seq", "op", "decision"))
            lines.append("  decision: %-24s -> %-18s %s"
                         % (d.get("op"), d.get("decision"), extras))
        if tel.get("resources"):
            lines.append("  compiled-program resources:")
            lines += export.render_resources(tel["resources"],
                                             indent="    ")
        caches = tel.get("caches") or {}
        if any(isinstance(s, dict) and s.get("size")
               for s in caches.values()):
            lines.append("  compile caches:")
            lines += export.render_caches(caches, indent="    ")
        spans = tel.get("spans") or {}
        if spans:
            lines.append("  dispatch latency (s):")
            lines += _render_span_summary(spans, indent="    ")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    prometheus = "--prometheus" in argv
    argv = [a for a in argv if a != "--prometheus"]
    if len(argv) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    path = argv[0]
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):  # BENCH_DETAILS.json
        if prometheus:
            print("--prometheus needs a full obs snapshot, not "
                  "BENCH_DETAILS.json", file=sys.stderr)
            return 2
        sys.stdout.write(_render_bench_details(data))
        return 0
    if prometheus:
        sys.stdout.write(export.to_prometheus(data))
        return 0
    sys.stdout.write(export.report(data, max_events=50))
    sys.stdout.write(_latency_section(data))
    sys.stdout.write(_artifact_section(data))
    sys.stdout.write(_serving_section(data))
    sys.stdout.write(_fleet_section(data))
    sys.stdout.write(_history_section(data))
    sys.stdout.write(_control_section(data))
    return 0


if __name__ == "__main__":
    sys.exit(main())
