"""Multi-chip scale-out over a TPU mesh.

The reference is a single-thread library: its only "parallelism" is 8-wide
AVX lanes, and its long-signal story is the sequential overlap-save block
loop (``/root/reference/src/convolve.c:181-228``, SURVEY.md §2 checklist).
This package is the genuinely new TPU capability: the same decompositions,
expressed as ``shard_map`` programs over a ``jax.sharding.Mesh`` so they
scale across ICI — XLA collectives instead of any message-passing runtime.

* :func:`make_mesh` / :func:`default_mesh` — mesh construction helpers.
* :func:`sharded_convolve` — **sequence-parallel** long-signal convolution:
  the signal is sharded along its length, each chip convolves its block
  after a one-hop **halo exchange** (``ppermute``) brings in the h−1
  samples it needs from its left neighbour — the distributed form of
  overlap-save, where the reference's in-core block overlap becomes the
  inter-chip halo.
* :func:`sharded_convolve_ring` — filters **longer than a shard
  block**: x blocks stream around the ring (the ring-attention
  communication pattern) while each shard accumulates against its
  static filter segments; `sharded_convolve` auto-selects it when the
  one-hop halo cannot fit.
* :func:`sharded_convolve_batch` — **dp×sp** convolution over a 2D mesh
  tile: batch over one axis, sequence (with halo) over the other.
* :func:`sharded_convolve2d_ring` — 2D kernels **larger than a shard
  tile**: the ring generalizes per axis (inner ring along one mesh
  axis inside an outer ring along the other); `sharded_convolve2d`
  auto-selects it.
* :func:`sharded_swt` — sequence-parallel **stationary wavelet cascade**
  with ring halo exchange (periodic extension = the last→first hop).
* :func:`sharded_swt_reconstruct` / :func:`sharded_wavelet_reconstruct` —
  the **sharded synthesis** inverses: the adjoint's windows reach left,
  so each level is a left-halo ring ``ppermute`` + local dilated
  convolution, closing the distributed analysis→synthesis round trip.
* :func:`sharded_wavelet_apply2d` / :func:`sharded_wavelet_reconstruct2d`
  — the **all-to-all** (Ulysses-style) pattern: rows transform locally,
  an ``all_to_all`` transpose re-shards to columns, columns transform
  locally.  Every pass sees complete rows/columns, so all four boundary
  extensions are exact.
* :func:`sharded_stft` / :func:`sharded_istft` — sequence-parallel
  **time-frequency analysis**: frame ownership follows sample ownership
  (one right-halo ``ppermute`` of the ``frame_length - hop`` overlap),
  so a long-signal spectrogram pipeline never gathers the signal; the
  inverse overlap-adds locally and ships each shard's overhang to its
  right neighbour.
* :func:`sharded_sosfilt` — sequence-parallel **IIR**: the "inherently
  sequential" recurrence as a two-level scan — local associative scans
  per shard plus one ``all_gather`` of a 2-float exit state per biquad
  section, prefix-combined through host-precomputed block transition
  powers.
* :func:`sharded_welch` — sequence-parallel **PSD estimation**: each
  shard frames and FFTs its own segments, one ``psum`` of a ``[bins]``
  vector yields the global Welch average — collective payload
  independent of the signal length.
* :func:`sharded_resample_poly` — sequence-parallel **rate conversion**:
  each shard runs the single-chip dilated/strided polyphase conv on its
  halo-extended block; output ownership follows input ownership.
* :func:`sharded_medfilt` / :func:`sharded_order_filter` /
  :func:`sharded_savgol_filter` — sequence-parallel **nonlinear and
  smoothing filters**: pure halo exchange (the open ``ppermute`` edge
  IS the single-chip zero padding); Savitzky-Golay's ``interp`` edge
  polynomial runs as a precomputed matrix on the edge-owning shards.
* :func:`sharded_lombscargle` — sequence-parallel **irregular-sampling
  spectral estimation**: the sample axis is sharded, two ``psum``
  rounds of ``[n_freqs]`` vectors (tau sums, then projections)
  replace any gather of the samples.
* :func:`sharded_swt_apply2d` / :func:`sharded_wavelet_packet_transform2d`
  — the all-to-all transpose family extended to the undecimated 2D SWT
  and the 2D quad-tree packets (device-resident end to end).
* :func:`sharded_dft` / :func:`sharded_rfft` / :func:`sharded_irfft` —
  **pod-scale Fourier** (:mod:`~veles.simd_tpu.parallel.fourier`): the
  Cooley-Tukey ``N = N1*N2`` factorization as per-factor DFT-basis
  matmuls on the MXU with tiled ``all_to_all`` transposes between
  stages (arXiv:2002.03260), mesh-aware route selection (ICI bytes in
  the selector and the decision events) against the local-FFT
  fallback; the sharded STFT/ISTFT/Welch bodies ride the same
  engine's ``parallel.frame_dft`` table for their local transforms.
* :func:`sharded_matmul` — **tensor-parallel** GEMM: contracting dimension
  sharded (zero-padded to the axis size), partials combined with ``psum``
  over ICI.
* :func:`data_parallel` — batch-dimension sharding for any batched op
  (DWT/normalize/mathfun pipelines).
* :mod:`~veles.simd_tpu.parallel.distributed` — **multi-host** bootstrap:
  ``jax.distributed`` runtime + hybrid ICI/DCN meshes (DCN axes
  outermost so halo/psum hops stay on-slice).

All of these compile and run on any mesh size — the test-suite uses a
virtual 8-device CPU mesh (see ``conftest.py``) plus real multi-process
workers (``tests/test_distributed.py``), the driver's
``dryrun_multichip`` does the same, and on real multi-chip hardware the
identical code lays the collectives onto ICI.
"""

from veles.simd_tpu.parallel import distributed
from veles.simd_tpu.parallel.fourier import (
    sharded_dft, sharded_irfft, sharded_rfft)
from veles.simd_tpu.parallel.mesh import default_mesh, make_mesh
from veles.simd_tpu.parallel.ops import (
    data_parallel, halo_exchange_left, halo_exchange_right,
    sharded_convolve, sharded_convolve2d, sharded_convolve2d_ring,
    sharded_convolve_batch, sharded_convolve_ring, sharded_istft,
    sharded_lombscargle, sharded_matmul, sharded_medfilt,
    sharded_normalize2d,
    sharded_order_filter, sharded_resample_poly, sharded_savgol_filter,
    sharded_sosfilt, sharded_stft, sharded_welch,
    sharded_swt, sharded_swt_apply2d, sharded_swt_reconstruct,
    sharded_wavelet_apply,
    sharded_wavelet_apply2d, sharded_wavelet_inverse_transform,
    sharded_wavelet_packet_transform2d,
    sharded_wavelet_reconstruct, sharded_wavelet_reconstruct2d,
    sharded_wavelet_transform)

__all__ = ["make_mesh", "default_mesh", "sharded_convolve",
           "sharded_convolve_ring",
           "sharded_convolve_batch", "sharded_convolve2d",
           "sharded_convolve2d_ring",
           "sharded_swt", "sharded_swt_reconstruct",
           "sharded_wavelet_apply", "sharded_wavelet_transform",
           "sharded_wavelet_inverse_transform",
           "sharded_wavelet_reconstruct",
           "sharded_wavelet_apply2d",
           "sharded_wavelet_reconstruct2d",
           "sharded_swt_apply2d", "sharded_wavelet_packet_transform2d",
           "sharded_order_filter", "sharded_medfilt",
           "sharded_savgol_filter", "sharded_lombscargle",
           "sharded_matmul",
           "sharded_stft", "sharded_istft", "sharded_sosfilt",
           "sharded_welch", "sharded_resample_poly",
           "sharded_normalize2d",
           "sharded_dft", "sharded_rfft", "sharded_irfft",
           "data_parallel", "halo_exchange_left", "halo_exchange_right",
           "distributed"]
