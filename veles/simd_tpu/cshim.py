"""Python side of the native C shim (``csrc/veles_simd.c``).

The C library embeds CPython and calls the functions in this module with
raw pointers (as integers) + geometry; here they are wrapped zero-copy
with ``np.ctypeslib``, dispatched through the normal
:mod:`veles.simd_tpu` ops (XLA or oracle per the ``simd`` flag), and the
results are written back into the caller's output buffer.  This preserves
the reference's C ABI workflow (compute into caller-allocated arrays —
e.g. ``/root/reference/inc/simd/matrix.h:47-89``) while the math runs on
the TPU.

Handle-based convolution keeps a registry keyed by an integer id, the C
``VelesConvolutionHandle`` payload — the ABI analog of
``ConvolutionHandle`` (``/root/reference/inc/simd/convolve_structs.h``).
"""

from __future__ import annotations

import ctypes

import numpy as np

# The axon TPU plugin pins the JAX platform from sitecustomize before env
# vars are consulted; give C hosts an explicit override.
from veles.simd_tpu.utils.platform import (init_deadline,
                                           maybe_override_platform)

maybe_override_platform()

# Eager, deadline-guarded backend init: a wedged relay blocks forever in
# native code with no diagnostics, so a C host would otherwise hang at
# its first op call.  Failing loudly at load time (SystemExit 2 with the
# platform-pinning hint) is the contract; VELES_SIMD_INIT_DEADLINE=0
# opts out.
with init_deadline(what="jax backend init (veles.simd_tpu C bridge)"):
    import jax as _jax

    _jax.devices()

from veles.simd_tpu.ops import arithmetic as _ar
from veles.simd_tpu.ops import convolve as _cv
from veles.simd_tpu.ops import convolve2d as _cv2
from veles.simd_tpu.ops import correlate as _cr
from veles.simd_tpu.ops import detect_peaks as _dp
from veles.simd_tpu.ops import filters as _fl
from veles.simd_tpu.ops import iir as _iir
from veles.simd_tpu.ops import mathfun as _mf
from veles.simd_tpu.ops import matrix as _mx
from veles.simd_tpu.ops import normalize as _nz
from veles.simd_tpu.ops import resample as _rs
from veles.simd_tpu.ops import spectral as _sp
from veles.simd_tpu.ops import waveforms as _wf
from veles.simd_tpu.ops import wavelet as _wv
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType as _WT

_C_WAVELET_TYPES = {0: _WT.DAUBECHIES, 1: _WT.COIFLET, 2: _WT.SYMLET}
_C_EXTENSIONS = {0: _wv.ExtensionType.PERIODIC, 1: _wv.ExtensionType.MIRROR,
                 2: _wv.ExtensionType.CONSTANT, 3: _wv.ExtensionType.ZERO}
_C_ALGORITHMS = {0: None, 1: _cv.ConvolutionAlgorithm.BRUTE_FORCE,
                 2: _cv.ConvolutionAlgorithm.FFT,
                 3: _cv.ConvolutionAlgorithm.OVERLAP_SAVE}


def _arr(ptr, shape, ctype):
    return np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctype)),
        shape=tuple(int(s) for s in shape))


def _f32(ptr, *shape):
    return _arr(ptr, shape, ctypes.c_float)


def backend_description() -> str:
    import jax

    return f"xla:{jax.default_backend()}"


# ---- matrix ---------------------------------------------------------------

def matrix_add(simd, m1, m2, res, w, h):
    _f32(res, h, w)[...] = _mx.matrix_add(
        _f32(m1, h, w), _f32(m2, h, w), simd=bool(simd))
    return 0


def matrix_sub(simd, m1, m2, res, w, h):
    _f32(res, h, w)[...] = _mx.matrix_sub(
        _f32(m1, h, w), _f32(m2, h, w), simd=bool(simd))
    return 0


def matrix_multiply(simd, m1, m2, res, w1, h1, w2, h2):
    _f32(res, h1, w2)[...] = _mx.matrix_multiply(
        _f32(m1, h1, w1), _f32(m2, h2, w2), simd=bool(simd))
    return 0


def matrix_multiply_transposed(simd, m1, m2, res, w1, h1, w2, h2):
    _f32(res, h1, h2)[...] = _mx.matrix_multiply_transposed(
        _f32(m1, h1, w1), _f32(m2, h2, w2), simd=bool(simd))
    return 0


# ---- convolve / correlate -------------------------------------------------

_handles: dict[int, _cv.ConvolutionHandle] = {}
_next_handle = [1]


def convolve_initialize(x_length, h_length, algorithm, reverse):
    handle = _cv.convolve_initialize(x_length, h_length,
                                     _C_ALGORITHMS[int(algorithm)],
                                     reverse=bool(reverse))
    hid = _next_handle[0]
    _next_handle[0] += 1
    _handles[hid] = handle
    return hid


def convolve_run(hid, x, h, result):
    handle = _handles[int(hid)]
    out = _cv._run(handle, _f32(x, handle.x_length),
                   _f32(h, handle.h_length), simd=True)
    _f32(result, handle.result_length)[...] = np.asarray(out)
    return 0


def convolve_finalize(hid):
    _handles.pop(int(hid), None)
    return 0


_streams: dict[int, "_cv.StreamingConvolution"] = {}


def streaming_convolve_initialize(h, h_length, chunk_length, reverse, simd):
    stream = _cv.StreamingConvolution(
        _f32(h, h_length).copy(), int(chunk_length),
        reverse=bool(reverse), simd=bool(simd))
    sid = _next_handle[0]
    _next_handle[0] += 1
    _streams[sid] = stream
    return sid


def streaming_convolve_process(sid, chunk, result):
    stream = _streams[int(sid)]
    out = stream.process(_f32(chunk, stream.chunk_length))
    _f32(result, stream.chunk_length)[...] = np.asarray(out)
    return 0


def streaming_convolve_flush(sid, tail):
    stream = _streams[int(sid)]
    out = np.asarray(stream.flush())
    if stream.h_length > 1:
        buf = _f32(tail, stream.h_length - 1)
        # an un-fed stream flushes empty: the C tail is all zeros
        buf[...] = 0.0 if out.shape[-1] == 0 else out
    return 0


def streaming_convolve_finalize(sid):
    _streams.pop(int(sid), None)
    return 0


def convolve2d(simd, reverse, x, n0, n1, h, k0, k1, result):
    fn = _cv2.cross_correlate2d if reverse else _cv2.convolve2d
    out = fn(_arr(x, (n0, n1), ctypes.c_float),
             _arr(h, (k0, k1), ctypes.c_float), simd=bool(simd))
    _arr(result, (n0 + k0 - 1, n1 + k1 - 1), ctypes.c_float)[...] = \
        np.asarray(out)
    return 0


_C_CONV2D_MODES = {0: "full", 1: "same", 2: "valid"}
_C_CONV2D_BOUNDARIES = {0: "fill", 1: "wrap", 2: "symm"}


def convolve2d_mb(simd, reverse, x, n0, n1, h, k0, k1, mode, boundary,
                  fillvalue, result):
    if int(mode) not in _C_CONV2D_MODES:
        raise ValueError(f"mode code {int(mode)} invalid: 0 full, "
                         "1 same, 2 valid")
    if int(boundary) not in _C_CONV2D_BOUNDARIES:
        raise ValueError(f"boundary code {int(boundary)} invalid: "
                         "0 fill, 1 wrap, 2 symm")
    fn = _cv2.cross_correlate2d if reverse else _cv2.convolve2d
    out = np.asarray(fn(
        _arr(x, (n0, n1), ctypes.c_float),
        _arr(h, (k0, k1), ctypes.c_float), simd=bool(simd),
        mode=_C_CONV2D_MODES[int(mode)],
        boundary=_C_CONV2D_BOUNDARIES[int(boundary)],
        fillvalue=float(fillvalue)))
    _arr(result, out.shape, ctypes.c_float)[...] = out
    return 0


def convolve_simd(simd, x, xlen, h, hlen, result):
    out = _cv.convolve_simd(_f32(x, xlen), _f32(h, hlen), simd=bool(simd))
    _f32(result, xlen + hlen - 1)[...] = np.asarray(out)
    return 0


def cross_correlate_simd(simd, x, xlen, h, hlen, result):
    out = _cr.cross_correlate_simd(_f32(x, xlen), _f32(h, hlen),
                                   simd=bool(simd))
    _f32(result, xlen + hlen - 1)[...] = np.asarray(out)
    return 0


# ---- wavelet --------------------------------------------------------------

def wavelet_validate_order(wtype, order):
    return int(_wv.wavelet_validate_order(_C_WAVELET_TYPES[int(wtype)],
                                          int(order)))


def wavelet_apply(simd, wtype, order, ext, src, length, desthi, destlo):
    hi, lo = _wv.wavelet_apply(
        _C_WAVELET_TYPES[int(wtype)], int(order), _C_EXTENSIONS[int(ext)],
        _f32(src, length), simd=bool(simd))
    _f32(desthi, length // 2)[...] = np.asarray(hi)
    _f32(destlo, length // 2)[...] = np.asarray(lo)
    return 0


def stationary_wavelet_apply(simd, wtype, order, level, ext, src, length,
                             desthi, destlo):
    hi, lo = _wv.stationary_wavelet_apply(
        _C_WAVELET_TYPES[int(wtype)], int(order), int(level),
        _C_EXTENSIONS[int(ext)], _f32(src, length), simd=bool(simd))
    _f32(desthi, length)[...] = np.asarray(hi)
    _f32(destlo, length)[...] = np.asarray(lo)
    return 0


def wavelet_reconstruct(simd, wtype, order, ext, desthi, destlo, length,
                        result):
    rec = _wv.wavelet_reconstruct(
        _C_WAVELET_TYPES[int(wtype)], int(order), _f32(desthi, length),
        _f32(destlo, length), simd=bool(simd), ext=_C_EXTENSIONS[int(ext)])
    _f32(result, 2 * length)[...] = np.asarray(rec)
    return 0


def stationary_wavelet_reconstruct(simd, wtype, order, level, ext, desthi,
                                   destlo, length, result):
    rec = _wv.stationary_wavelet_reconstruct(
        _C_WAVELET_TYPES[int(wtype)], int(order), int(level),
        _f32(desthi, length), _f32(destlo, length), simd=bool(simd),
        ext=_C_EXTENSIONS[int(ext)])
    _f32(result, length)[...] = np.asarray(rec)
    return 0


def wavelet_apply2d(simd, wtype, order, ext, src, n0, n1, ll, lh, hl, hh):
    bands = _wv.wavelet_apply2d(
        _C_WAVELET_TYPES[int(wtype)], int(order), _C_EXTENSIONS[int(ext)],
        _f32(src, n0, n1), simd=bool(simd))
    for ptr, band in zip((ll, lh, hl, hh), bands):
        _f32(ptr, n0 // 2, n1 // 2)[...] = np.asarray(band)
    return 0


def wavelet_reconstruct2d(simd, wtype, order, ext, ll, lh, hl, hh, m0, m1,
                          result):
    rec = _wv.wavelet_reconstruct2d(
        _C_WAVELET_TYPES[int(wtype)], int(order),
        _f32(ll, m0, m1), _f32(lh, m0, m1), _f32(hl, m0, m1),
        _f32(hh, m0, m1), simd=bool(simd), ext=_C_EXTENSIONS[int(ext)])
    _f32(result, 2 * m0, 2 * m1)[...] = np.asarray(rec)
    return 0


def stationary_wavelet_apply2d(simd, wtype, order, level, ext, src, n0, n1,
                               ll, lh, hl, hh):
    bands = _wv.stationary_wavelet_apply2d(
        _C_WAVELET_TYPES[int(wtype)], int(order), int(level),
        _C_EXTENSIONS[int(ext)], _f32(src, n0, n1), simd=bool(simd))
    for ptr, band in zip((ll, lh, hl, hh), bands):
        _f32(ptr, n0, n1)[...] = np.asarray(band)
    return 0


def stationary_wavelet_reconstruct2d(simd, wtype, order, level, ext, ll,
                                     lh, hl, hh, m0, m1, result):
    rec = _wv.stationary_wavelet_reconstruct2d(
        _C_WAVELET_TYPES[int(wtype)], int(order), int(level),
        _f32(ll, m0, m1), _f32(lh, m0, m1), _f32(hl, m0, m1),
        _f32(hh, m0, m1), simd=bool(simd), ext=_C_EXTENSIONS[int(ext)])
    _f32(result, m0, m1)[...] = np.asarray(rec)
    return 0


def wavelet_packet_transform(simd, wtype, order, ext, src, length, levels,
                             leaves):
    bands = _wv.wavelet_packet_transform(
        _C_WAVELET_TYPES[int(wtype)], int(order), _C_EXTENSIONS[int(ext)],
        _f32(src, length), int(levels), simd=bool(simd))
    _f32(leaves, length)[...] = np.concatenate(
        [np.asarray(b) for b in bands])
    return 0


def wavelet_packet_inverse_transform(simd, wtype, order, ext, leaves,
                                     length, levels, result):
    n_leaves = 1 << int(levels)
    if int(length) % n_leaves:
        raise ValueError(
            f"length {length} not divisible by 2^levels = {n_leaves}")
    flat = _f32(leaves, length)
    leaf_len = int(length) // n_leaves
    bands = [flat[i * leaf_len:(i + 1) * leaf_len]
             for i in range(n_leaves)]
    rec = _wv.wavelet_packet_inverse_transform(
        _C_WAVELET_TYPES[int(wtype)], int(order), bands, simd=bool(simd),
        ext=_C_EXTENSIONS[int(ext)])
    _f32(result, length)[...] = np.asarray(rec)
    return 0


def _check_quad_divisible(m0, m1, levels):
    n_side = 1 << int(levels)
    if int(m0) % n_side or int(m1) % n_side:
        raise ValueError(
            f"image dims ({m0}, {m1}) not divisible by "
            f"2^levels = {n_side}")
    return n_side


def wavelet_packet_transform2d(simd, wtype, order, ext, src, m0, m1,
                               levels, leaves):
    _check_quad_divisible(m0, m1, levels)
    bands = _wv.wavelet_packet_transform2d(
        _C_WAVELET_TYPES[int(wtype)], int(order), _C_EXTENSIONS[int(ext)],
        _f32(src, m0, m1), int(levels), simd=bool(simd))
    _f32(leaves, int(m0) * int(m1))[...] = np.concatenate(
        [np.asarray(b).ravel() for b in bands])
    return 0


def wavelet_packet_inverse_transform2d(simd, wtype, order, ext, leaves,
                                       m0, m1, levels, result):
    n_side = _check_quad_divisible(m0, m1, levels)
    n_leaves = n_side * n_side
    l0, l1 = int(m0) // n_side, int(m1) // n_side
    flat = _f32(leaves, int(m0) * int(m1))
    bands = [flat[i * l0 * l1:(i + 1) * l0 * l1].reshape(l0, l1)
             for i in range(n_leaves)]
    rec = _wv.wavelet_packet_inverse_transform2d(
        _C_WAVELET_TYPES[int(wtype)], int(order), bands, simd=bool(simd),
        ext=_C_EXTENSIONS[int(ext)])
    _f32(result, m0, m1)[...] = np.asarray(rec)
    return 0


# ---- mathfun --------------------------------------------------------------

def mathfun(name, simd, src, length, res):
    fn = {"sin": _mf.sin_psv, "cos": _mf.cos_psv, "log": _mf.log_psv,
          "exp": _mf.exp_psv, "sqrt": _mf.sqrt_psv}[name]
    _f32(res, length)[...] = np.asarray(fn(_f32(src, length),
                                           simd=bool(simd)))
    return 0


def pow_psv(simd, base, exponent, length, res):
    _f32(res, length)[...] = np.asarray(_mf.pow_psv(
        _f32(base, length), _f32(exponent, length), simd=bool(simd)))
    return 0


# ---- spectral -------------------------------------------------------------

def _cplx_out(ptr, out, *shape):
    """Write a complex result into an interleaved (re, im) f32 buffer.

    ``to_host`` (not ``np.asarray``): complex device→host transfers are
    UNIMPLEMENTED through the axon relay and one attempt poisons the
    whole process — see ``utils/platform.py::to_host``."""
    from veles.simd_tpu.utils.platform import to_host

    out = np.ascontiguousarray(to_host(out).astype(np.complex64))
    _f32(ptr, *shape, 2)[...] = out.view(np.float32).reshape(*shape, 2)


def _window_arg(ptr, frame_length):
    return None if int(ptr) == 0 else _f32(ptr, frame_length)


def stft(simd, x, length, frame_length, hop, window, spec):
    out = _sp.stft(_f32(x, length), int(frame_length), int(hop),
                   window=_window_arg(window, frame_length),
                   simd=bool(simd))
    frames = _sp.frame_count(int(length), int(frame_length), int(hop))
    _cplx_out(spec, out, frames, int(frame_length) // 2 + 1)
    return 0


def istft(simd, spec, length, frame_length, hop, window, result):
    frames = _sp.frame_count(int(length), int(frame_length), int(hop))
    bins = int(frame_length) // 2 + 1
    spec_c = _f32(spec, frames, bins, 2).view(np.complex64)[..., 0]
    out = _sp.istft(spec_c, int(length), int(frame_length), int(hop),
                    window=_window_arg(window, frame_length),
                    simd=bool(simd))
    _f32(result, length)[...] = np.asarray(out)
    return 0


def spectrogram(simd, x, length, frame_length, hop, window, power):
    out = _sp.spectrogram(_f32(x, length), int(frame_length), int(hop),
                          window=_window_arg(window, frame_length),
                          simd=bool(simd))
    frames = _sp.frame_count(int(length), int(frame_length), int(hop))
    _f32(power, frames, int(frame_length) // 2 + 1)[...] = np.asarray(out)
    return 0


def hilbert(simd, x, length, analytic):
    out = _sp.hilbert(_f32(x, length), simd=bool(simd))
    _cplx_out(analytic, out, int(length))
    return 0


def envelope(simd, x, length, env):
    _f32(env, length)[...] = np.asarray(
        _sp.envelope(_f32(x, length), simd=bool(simd)))
    return 0


def morlet_cwt(simd, x, length, scales, n_scales, w0, result):
    sc = _arr(scales, (n_scales,), ctypes.c_double)
    out = _sp.morlet_cwt(_f32(x, length), sc, w0=float(w0),
                         simd=bool(simd))
    _cplx_out(result, out, int(n_scales), int(length))
    return 0


def spectral_detrend(simd, x, length, kind, result):
    _f32(result, length)[...] = np.asarray(
        _sp.detrend(_f32(x, length), {0: "linear", 1: "constant"}[int(kind)],
                    simd=bool(simd)))
    return 0


def spectral_welch(simd, x, length, fs, nperseg, noverlap, freqs, psd):
    nov = None if int(noverlap) < 0 else int(noverlap)
    f, p = _sp.welch(_f32(x, length), fs=float(fs),
                     nperseg=int(nperseg), noverlap=nov,
                     simd=bool(simd))
    _f64(freqs, len(f))[...] = f
    _f32(psd, len(f))[...] = np.asarray(p)
    return 0


def spectral_periodogram(simd, x, length, fs, freqs, psd):
    f, p = _sp.periodogram(_f32(x, length), fs=float(fs),
                           simd=bool(simd))
    _f64(freqs, len(f))[...] = f
    _f32(psd, len(f))[...] = np.asarray(p)
    return 0


def spectral_csd(simd, x, y, length, fs, nperseg, noverlap, freqs, pxy):
    nov = None if int(noverlap) < 0 else int(noverlap)
    f, p = _sp.csd(_f32(x, length), _f32(y, length), fs=float(fs),
                   nperseg=int(nperseg), noverlap=nov, simd=bool(simd))
    _f64(freqs, len(f))[...] = f
    _cplx_out(pxy, p, len(f))
    return 0


def spectral_coherence(simd, x, y, length, fs, nperseg, noverlap, freqs,
                       coh):
    nov = None if int(noverlap) < 0 else int(noverlap)
    f, c = _sp.coherence(_f32(x, length), _f32(y, length), fs=float(fs),
                         nperseg=int(nperseg), noverlap=nov,
                         simd=bool(simd))
    _f64(freqs, len(f))[...] = f
    _f32(coh, len(f))[...] = np.asarray(c)
    return 0


def spectral_czt(simd, x, length, m, w_re, w_im, a_re, a_im, result):
    w = None if (w_re == 0.0 and w_im == 0.0) else complex(w_re, w_im)
    out = _sp.czt(_f32(x, length), int(m), w, complex(a_re, a_im),
                  simd=bool(simd))
    _cplx_out(result, out, int(m))
    return 0


def spectral_zoom_fft(simd, x, length, f1, f2, m, fs, freqs, result):
    f, out = _sp.zoom_fft(_f32(x, length), [float(f1), float(f2)],
                          int(m), fs=float(fs), simd=bool(simd))
    _f64(freqs, int(m))[...] = f
    _cplx_out(result, out, int(m))
    return 0


def spectral_lombscargle(simd, t, x, length, freqs, n_freqs, power):
    f = _f64(freqs, n_freqs)
    out = _sp.lombscargle(_f64(t, length), _f32(x, length), f,
                          simd=bool(simd))
    _f32(power, n_freqs)[...] = np.asarray(out)
    return 0


# ---- resample -------------------------------------------------------------

def upfirdn(simd, h, h_len, x, length, up, down, result):
    out = _rs.upfirdn(_f64(h, h_len), _f32(x, length), int(up),
                      int(down), simd=bool(simd))
    _f32(result, out.shape[-1])[...] = np.asarray(out)
    return 0


def resample_poly(simd, x, length, up, down, taps, num_taps, result):
    t = None if int(taps) == 0 else _f32(taps, num_taps)
    out = _rs.resample_poly(_f32(x, length), int(up), int(down), taps=t,
                            simd=bool(simd))
    _f32(result, _rs.resample_length(int(length), int(up),
                                     int(down)))[...] = np.asarray(out)
    return 0


def resample_fourier(simd, x, length, num, result):
    out = _rs.resample_fourier(_f32(x, length), int(num), simd=bool(simd))
    _f32(result, num)[...] = np.asarray(out)
    return 0


# ---- iir ------------------------------------------------------------------

_C_BTYPES = {0: "lowpass", 1: "highpass", 2: "bandpass", 3: "bandstop"}


def _f64(ptr, *shape):
    return _arr(ptr, shape, ctypes.c_double)


def _iir_design(design, low, high, btype, sos_out):
    """Shared design shim: returns the section count; writes
    [n_sections, 6] float64 rows into ``sos_out`` when non-NULL (call
    once with NULL to size the buffer, then again to fill it)."""
    bt = _C_BTYPES[int(btype)]
    cutoff = float(low) if bt in ("lowpass", "highpass") \
        else (float(low), float(high))
    sos = design(cutoff, bt)
    if int(sos_out) != 0:
        _f64(sos_out, len(sos), 6)[...] = sos
    return len(sos)


def iir_butterworth(order, low, high, btype, sos_out):
    """Returns the section count; writes [n_sections, 6] float64 rows
    into ``sos_out`` when it is non-NULL (call once with NULL to size
    the buffer, then again to fill it).  Same contract for the cheby
    variants."""
    return _iir_design(lambda c, bt: _iir.butterworth(int(order), c, bt),
                       low, high, btype, sos_out)


def iir_bessel(order, low, high, btype, sos_out):
    return _iir_design(lambda c, bt: _iir.bessel(int(order), c, bt),
                       low, high, btype, sos_out)


def iir_cheby1(order, rp, low, high, btype, sos_out):
    return _iir_design(
        lambda c, bt: _iir.cheby1(int(order), float(rp), c, bt),
        low, high, btype, sos_out)


def iir_cheby2(order, rs, low, high, btype, sos_out):
    return _iir_design(
        lambda c, bt: _iir.cheby2(int(order), float(rs), c, bt),
        low, high, btype, sos_out)


def iir_ellip(order, rp, rs, low, high, btype, sos_out):
    return _iir_design(
        lambda c, bt: _iir.ellip(int(order), float(rp), float(rs), c, bt),
        low, high, btype, sos_out)


def iir_ord(method, wp, ws, n_edges, gpass, gstop, wn_out):
    n = int(n_edges)
    if n not in (1, 2):
        raise ValueError("n_edges must be 1 or 2")
    fn = {"buttord": _iir.buttord, "cheb1ord": _iir.cheb1ord,
          "cheb2ord": _iir.cheb2ord, "ellipord": _iir.ellipord}[method]
    wp_v = _f64(wp, n)
    ws_v = _f64(ws, n)
    order, wn = fn(wp_v if n > 1 else float(wp_v[0]),
                   ws_v if n > 1 else float(ws_v[0]),
                   float(gpass), float(gstop))
    if int(wn_out) != 0:
        _f64(wn_out, n)[...] = wn
    return int(order)


def _single_biquad(sos, sos_out):
    if int(sos_out) != 0:
        _f64(sos_out, 1, 6)[...] = sos
    return 1


def iir_notch(w0, q, sos_out):
    return _single_biquad(_iir.iirnotch(float(w0), float(q)), sos_out)


def iir_peak(w0, q, sos_out):
    return _single_biquad(_iir.iirpeak(float(w0), float(q)), sos_out)


def iir_sosfilt_stream(simd, sos, n_sections, x, length, zi_inout,
                       result):
    """One streaming block: filters with the caller's state and writes
    the exit state back into the same buffer."""
    s = _f64(sos, n_sections, 6)
    z = _f64(zi_inout, n_sections, 2)
    out, zf = _iir.sosfilt(s, _f32(x, length), zi=z.copy(),
                           simd=bool(simd), return_zf=True)
    _f32(result, length)[...] = np.asarray(out)
    z[...] = np.asarray(zf, np.float64)
    return 0


def iir_sosfilt(simd, sos, n_sections, x, length, zi, result):
    s = _f64(sos, n_sections, 6)
    z = None if int(zi) == 0 else _f64(zi, n_sections, 2)
    out = _iir.sosfilt(s, _f32(x, length), zi=z, simd=bool(simd))
    _f32(result, length)[...] = np.asarray(out)
    return 0


def iir_sosfiltfilt(simd, sos, n_sections, x, length, padlen, result):
    s = _f64(sos, n_sections, 6)
    pl = None if int(padlen) < 0 else int(padlen)
    out = _iir.sosfiltfilt(s, _f32(x, length), padlen=pl,
                           simd=bool(simd))
    _f32(result, length)[...] = np.asarray(out)
    return 0


def iir_sosfilt_zi(sos, n_sections, zi_out):
    _f64(zi_out, n_sections, 2)[...] = _iir.sosfilt_zi(
        _f64(sos, n_sections, 6))
    return 0


def iir_lfilter(simd, b, nb, a, na, x, length, result):
    out = _iir.lfilter(_f64(b, nb), _f64(a, na), _f32(x, length),
                       simd=bool(simd))
    _f32(result, length)[...] = np.asarray(out)
    return 0


# ---- filters --------------------------------------------------------------

def filt_medfilt(simd, x, length, kernel_size, result):
    _f32(result, length)[...] = np.asarray(
        _fl.medfilt(_f32(x, length), int(kernel_size), simd=bool(simd)))
    return 0


def filt_order_filter(simd, x, length, rank, kernel_size, result):
    _f32(result, length)[...] = np.asarray(
        _fl.order_filter(_f32(x, length), int(rank), int(kernel_size),
                         simd=bool(simd)))
    return 0


def filt_medfilt2d(simd, img, height, width, kh, kw, result):
    _f32(result, height, width)[...] = np.asarray(
        _fl.medfilt2d(_f32(img, height, width), (int(kh), int(kw)),
                      simd=bool(simd)))
    return 0


_C_SG_MODES = {0: "interp", 1: "constant", 2: "nearest"}


def filt_savgol(simd, x, length, window_length, polyorder, deriv, delta,
                mode, result):
    _f32(result, length)[...] = np.asarray(
        _fl.savgol_filter(_f32(x, length), int(window_length),
                          int(polyorder), deriv=int(deriv),
                          delta=float(delta), mode=_C_SG_MODES[int(mode)],
                          simd=bool(simd)))
    return 0


def filt_wiener(simd, x, length, mysize, noise, result):
    nz = None if not np.isfinite(noise) else float(noise)
    _f32(result, length)[...] = np.asarray(
        _fl.wiener(_f32(x, length), int(mysize), noise=nz,
                   simd=bool(simd)))
    return 0


def filt_savgol_coeffs(window_length, polyorder, deriv, delta, taps):
    _f64(taps, window_length)[...] = _fl.savgol_coeffs(
        int(window_length), int(polyorder), int(deriv), float(delta))
    return 0


def filt_firwin(numtaps, cutoffs, n_cutoffs, pass_zero, window, taps):
    # legacy 2-code window surface; codes 0/1 coincide with
    # _C_WINDOW_KINDS, beta is ignored by the fixed windows
    if int(window) not in (0, 1):
        raise ValueError("filt_firwin takes window 0 (hamming) or 1 "
                         "(hann); use filt_firwin_w for the full range")
    return filt_firwin_w(numtaps, cutoffs, n_cutoffs, pass_zero,
                         window, 0.0, taps)


def filt_firwin_w(numtaps, cutoffs, n_cutoffs, pass_zero, window, beta,
                  taps):
    c = _f64(cutoffs, n_cutoffs)
    cut = float(c[0]) if int(n_cutoffs) == 1 else list(map(float, c))
    kind = _C_WINDOW_KINDS[int(window)]
    win = (kind, float(beta)) if kind == "kaiser" else kind
    _f64(taps, numtaps)[...] = _fl.firwin(
        int(numtaps), cut, pass_zero=bool(pass_zero), window=win)
    return 0


def filt_kaiserord(ripple, width, numtaps_out, beta_out):
    numtaps, beta = _fl.kaiserord(float(ripple), float(width))
    _arr(numtaps_out, (1,), ctypes.c_size_t)[0] = numtaps
    _f64(beta_out, 1)[0] = beta
    return 0


def filt_firwin2(numtaps, freq, gain, n_freq, nfreqs, window, taps):
    kind = _C_WINDOW_KINDS[int(window)]
    if kind == "kaiser":
        raise ValueError("firwin2 has no beta channel; use a "
                         "non-parametric window (codes 0-4)")
    _f64(taps, numtaps)[...] = _fl.firwin2(
        int(numtaps), _f64(freq, n_freq), _f64(gain, n_freq),
        nfreqs=int(nfreqs) or None, window=kind)
    return 0


def filt_remez(numtaps, bands, n_bands, desired, weight, fs, taps):
    n = int(n_bands)
    w = None if int(weight) == 0 else _f64(weight, n)
    _f64(taps, numtaps)[...] = _fl.remez(
        int(numtaps), _f64(bands, 2 * n), _f64(desired, n), weight=w,
        fs=float(fs))
    return 0


_C_CORR_MODES = {0: "full", 1: "same", 2: "valid"}


def correlation_lags(in_len, in2_len, mode, lags):
    out = _cr.correlation_lags(int(in_len), int(in2_len),
                               _C_CORR_MODES[int(mode)])
    _i64(lags, len(out))[...] = out
    return 0


def deconvolve(signal, sig_len, divisor, div_len, quotient, remainder):
    q, r = _fl.deconvolve(_f64(signal, sig_len), _f64(divisor, div_len))
    _f64(quotient, int(sig_len) - int(div_len) + 1)[...] = q
    _f64(remainder, sig_len)[...] = r
    return 0


# ---- waveforms ------------------------------------------------------------

_C_CHIRP_METHODS = {0: "linear", 1: "quadratic", 2: "logarithmic",
                    3: "hyperbolic"}
_C_WINDOW_KINDS = {0: "hamming", 1: "hann", 2: "blackman", 3: "bartlett",
                   4: "boxcar", 5: "kaiser"}


def wave_chirp(simd, t, length, f0, t1, f1, method, phi, result):
    _f32(result, length)[...] = np.asarray(_wf.chirp(
        _f32(t, length), float(f0), float(t1), float(f1),
        _C_CHIRP_METHODS[int(method)], float(phi), simd=bool(simd)))
    return 0


def wave_square(simd, t, length, duty, result):
    _f32(result, length)[...] = np.asarray(_wf.square(
        _f32(t, length), float(duty), simd=bool(simd)))
    return 0


def wave_sawtooth(simd, t, length, width, result):
    _f32(result, length)[...] = np.asarray(_wf.sawtooth(
        _f32(t, length), float(width), simd=bool(simd)))
    return 0


def wave_gausspulse(simd, t, length, fc, bw, bwr, result):
    _f32(result, length)[...] = np.asarray(_wf.gausspulse(
        _f32(t, length), float(fc), float(bw), float(bwr),
        simd=bool(simd)))
    return 0


def wave_unit_impulse(simd, n, idx, result):
    _f32(result, n)[...] = np.asarray(_wf.unit_impulse(
        int(n), int(idx), simd=bool(simd)))
    return 0


def wave_max_len_seq(nbits, state_io, length, seq):
    state = None if int(state_io) == 0 else _u8(state_io, nbits)
    out, final = _wf.max_len_seq(int(nbits), state=state,
                                 length=int(length))
    _u8(seq, length)[...] = out
    if state is not None:
        state[...] = final
    return 0


def wave_get_window(window, n, beta, result):
    kind = _C_WINDOW_KINDS[int(window)]
    kwargs = {"beta": float(beta)} if kind == "kaiser" else {}
    _f64(result, n)[...] = _wf.get_window(kind, int(n), **kwargs)
    return 0


# ---- normalize ------------------------------------------------------------

def _u8(ptr, *shape):
    return _arr(ptr, shape, ctypes.c_uint8)


def normalize2D(simd, src, src_stride, width, height, dst, dst_stride):
    plane = _u8(src, height, src_stride)[..., :width]
    out = np.asarray(_nz.normalize2D(plane, simd=bool(simd)))
    _f32(dst, height, dst_stride)[..., :width] = out
    return 0


def minmax2D(simd, src, src_stride, width, height):
    plane = _u8(src, height, src_stride)[..., :width]
    mn, mx = _nz.minmax2D(plane, simd=bool(simd))
    return (int(mn), int(mx))


def minmax1D(simd, src, length):
    mn, mx = _nz.minmax1D(_f32(src, length), simd=bool(simd))
    return (float(mn), float(mx))


def normalize2D_minmax(simd, mn, mx, src, src_stride, width, height, dst,
                       dst_stride):
    plane = _u8(src, height, src_stride)[..., :width]
    out = np.asarray(_nz.normalize2D_minmax(int(mn), int(mx), plane,
                                            simd=bool(simd)))
    _f32(dst, height, dst_stride)[..., :width] = out
    return 0


# ---- detect_peaks ---------------------------------------------------------

def detect_peaks(simd, data, size, etype):
    pos, vals = _dp.detect_peaks(_f32(data, size),
                                 _dp.ExtremumType(int(etype)),
                                 simd=bool(simd))
    return (np.asarray(pos, np.int64).tolist(),
            np.asarray(vals, np.float64).tolist())


def _i64(ptr, *shape):
    return _arr(ptr, shape, ctypes.c_int64)


def peak_prominences(simd, x, length, peaks, n_peaks, prom_out):
    pk = _i64(peaks, n_peaks)
    _f32(prom_out, n_peaks)[...] = np.asarray(
        _dp.peak_prominences(_f32(x, length), pk, simd=bool(simd)))
    return 0


def peak_widths(simd, x, length, peaks, n_peaks, rel_height, widths,
                width_heights, left_ips, right_ips):
    pk = _i64(peaks, n_peaks)
    w, h, li, ri = _dp.peak_widths(_f32(x, length), pk,
                                   rel_height=float(rel_height),
                                   simd=bool(simd))
    _f32(widths, n_peaks)[...] = np.asarray(w)
    _f32(width_heights, n_peaks)[...] = np.asarray(h)
    _f32(left_ips, n_peaks)[...] = np.asarray(li)
    _f32(right_ips, n_peaks)[...] = np.asarray(ri)
    return 0


def find_peaks(simd, x, length, height_min, height_max, threshold_min,
               threshold_max, distance, prom_min, prom_max, peaks_out,
               max_out):
    """NaN bounds mean "unset"; distance 0 means no distance filter.
    Returns the total peak count; at most max_out indices are written."""
    def _iv(lo, hi):
        lo = None if np.isnan(lo) else float(lo)
        hi = None if np.isnan(hi) else float(hi)
        if lo is None and hi is None:
            return None
        return (lo, hi)

    peaks, _ = _dp.find_peaks(
        _f32(x, length), height=_iv(height_min, height_max),
        threshold=_iv(threshold_min, threshold_max),
        distance=None if int(distance) == 0 else int(distance),
        prominence=_iv(prom_min, prom_max), simd=bool(simd))
    n_write = min(len(peaks), int(max_out))
    if n_write:
        _i64(peaks_out, n_write)[...] = peaks[:n_write]
    return len(peaks)


# ---- conversions ----------------------------------------------------------

def convert(name, simd, src, length, dst):
    if name == "int16_to_float":
        _f32(dst, length)[...] = _ar.int16_to_float(
            _arr(src, (length,), ctypes.c_int16), simd=bool(simd))
    elif name == "float_to_int16":
        _arr(dst, (length,), ctypes.c_int16)[...] = _ar.float_to_int16(
            _f32(src, length), simd=bool(simd))
    elif name == "int32_to_float":
        _f32(dst, length)[...] = _ar.int32_to_float(
            _arr(src, (length,), ctypes.c_int32), simd=bool(simd))
    elif name == "float_to_int32":
        _arr(dst, (length,), ctypes.c_int32)[...] = _ar.float_to_int32(
            _f32(src, length), simd=bool(simd))
    elif name == "int16_to_int32":
        _arr(dst, (length,), ctypes.c_int32)[...] = _ar.int16_to_int32(
            _arr(src, (length,), ctypes.c_int16), simd=bool(simd))
    elif name == "int32_to_int16":
        _arr(dst, (length,), ctypes.c_int16)[...] = _ar.int32_to_int16(
            _arr(src, (length,), ctypes.c_int32), simd=bool(simd))
    elif name == "float16_to_float":
        _f32(dst, length)[...] = _ar.float16_to_float(
            _arr(src, (length,), ctypes.c_uint16), simd=bool(simd))
    else:
        raise ValueError(name)
    return 0
