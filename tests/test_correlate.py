"""Tests for veles.simd_tpu.ops.correlate.

Port of ``tests/correlate.cc``: golden values (``:53-71``) and
cross-validation of the FFT / overlap-save paths against the direct form
(``:130-152``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import correlate as cr

RNG = np.random.RandomState(13)


def _ref_xcorr(x, h):
    """result[j] = Σ_m x[m] h[m + hLen-1-j]  == convolve(x, rev(h))."""
    return np.convolve(np.asarray(x, np.float64),
                       np.asarray(h, np.float64)[::-1]).astype(np.float32)


def test_golden_small():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    h = np.array([4.0, 5.0], np.float32)
    # np.correlate(x, h, "full") reversed-index convention:
    want = _ref_xcorr(x, h)
    np.testing.assert_allclose(
        np.asarray(cr.cross_correlate_simd(x, h, simd=True)), want, atol=1e-5)
    np.testing.assert_allclose(cr.cross_correlate_na(x, h), want, atol=1e-6)


def test_autocorrelation_peak_centered():
    """Autocorrelation of a random signal peaks at zero lag."""
    x = RNG.randn(257).astype(np.float32)
    out = np.asarray(cr.cross_correlate_simd(x, x, simd=True))
    assert out.shape == (513,)
    assert int(np.argmax(out)) == 256


@pytest.mark.parametrize("xlen,hlen", [(60, 60), (100, 10), (1000, 50),
                                       (2000, 950), (4096, 63)])
def test_algorithms_cross_validate(xlen, hlen):
    x = RNG.randn(xlen).astype(np.float32)
    h = RNG.randn(hlen).astype(np.float32)
    want = _ref_xcorr(x, h)
    tol = 1e-3 * max(1.0, np.abs(want).max())

    for make, run in [
        (cr.cross_correlate_fft_initialize, cr.cross_correlate_fft),
        (cr.cross_correlate_overlap_save_initialize,
         cr.cross_correlate_overlap_save),
    ]:
        if make is cr.cross_correlate_overlap_save_initialize and \
                not hlen < xlen / 2:
            continue
        handle = make(xlen, hlen)
        assert handle.reverse
        for simd in (True, False):
            got = np.asarray(run(handle, x, h, simd=simd))
            np.testing.assert_allclose(got, want, atol=tol,
                                       err_msg=f"{make.__name__} {simd}")


def test_auto_handle_sets_reverse():
    handle = cr.cross_correlate_initialize(1 << 15, 64)
    assert handle.reverse
    assert handle.algorithm is cv.ConvolutionAlgorithm.OVERLAP_SAVE
    x = RNG.randn(1 << 15).astype(np.float32)
    h = RNG.randn(64).astype(np.float32)
    np.testing.assert_allclose(np.asarray(cr.cross_correlate(handle, x, h)),
                               _ref_xcorr(x, h), atol=1e-2)


def test_convenience_form():
    x = RNG.randn(128).astype(np.float32)
    h = RNG.randn(16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(cr.cross_correlate(x, h)),
                               _ref_xcorr(x, h), atol=1e-4)


class TestCorrelationLags:
    def test_matches_scipy_when_conventions_agree(self):
        from scipy import signal as ss

        for n, m in [(10, 4), (7, 7), (64, 33)]:
            for mode in ("full", "same", "valid"):
                np.testing.assert_array_equal(
                    cr.correlation_lags(n, m, mode),
                    ss.correlation_lags(n, m, mode))

    @pytest.mark.parametrize("n,m", [(64, 4), (4, 10), (7, 7)])
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    def test_aligns_with_our_output(self, n, m, mode):
        """lags length == our cross_correlate output length, and the
        peak lag names the planted template offset."""
        lags = cr.correlation_lags(n, m, mode)
        x = np.zeros(n, np.float32)
        h = np.arange(1, m + 1, dtype=np.float32)
        pos = min(2, n - m) if n >= m else 0
        x[pos:pos + min(m, n)] = h[: min(m, n)]
        y = np.asarray(cr.cross_correlate(x, h, mode=mode))
        assert len(lags) == len(y)
        if n >= m:
            assert lags[np.argmax(y)] == pos

    def test_contracts(self):
        with pytest.raises(ValueError, match="lengths"):
            cr.correlation_lags(0, 4)
        with pytest.raises(ValueError, match="mode"):
            cr.correlation_lags(4, 4, "circular")
