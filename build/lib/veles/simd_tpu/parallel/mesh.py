"""Mesh construction helpers.

Thin wrappers over ``jax.sharding.Mesh`` with the axis-name conventions
used throughout :mod:`veles.simd_tpu.parallel`:

* ``"dp"`` — data parallel (batch of independent signals/planes),
* ``"sp"`` — sequence parallel (a single long signal sharded along its
  length, the distributed overlap-save axis),
* ``"tp"`` — tensor parallel (GEMM contracting dimension).

On a real pod slice the mesh should be built from
``jax.experimental.mesh_utils.create_device_mesh`` so axes ride ICI
neighbours; on CPU test meshes the plain reshape is fine.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh"]


def make_mesh(axis_sizes: dict[str, int] | None = None,
              devices=None) -> Mesh:
    """Build a mesh from ``{axis_name: size}`` (sizes must multiply to the
    device count; a single ``-1`` size is inferred)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"dp": n}
    names = list(axis_sizes)
    sizes = [int(s) for s in axis_sizes.values()]
    if sizes.count(-1) == 1:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"axis sizes {dict(zip(names, sizes))} != {n} devices")
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def default_mesh(axis: str = "dp") -> Mesh:
    """All devices on a single named axis."""
    return make_mesh({axis: len(jax.devices())})
