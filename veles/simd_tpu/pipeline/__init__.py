"""veles.simd_tpu.pipeline — op chains compiled into one dispatch.

The paper's library is a bag of one-shot SIMD routines, but its real
deployments (matched filters, vibration monitoring, biosignals) run
*chains* of those routines over unbounded streams.  This package makes
the chain the unit of compilation and serving:

* **declare** a chain from stage descriptors
  (:mod:`~veles.simd_tpu.pipeline.stages`):
  ``Pipeline([resample_poly(2, 1), sosfilt(sos), stft(256, 64),
  power()])``;
* **compile** it (:mod:`~veles.simd_tpu.pipeline.compiler`) into ONE
  block-processing ``obs.instrumented_jit`` step — every stage's
  carried state (IIR ``zi``, FIR/overlap-save halo, STFT frame
  overlap, resampler history) threaded explicitly through the step as
  a pytree, stage kernels resolved through the existing
  ``routing.family`` tables at compile time;
* **dispatch** each block under ``faults.breaker_guarded`` at
  ``pipeline.dispatch`` with a per-pipeline-class breaker and
  graceful degradation to the stage-by-stage NumPy oracle twin;
* **serve** it: ``serve.Server.register_pipeline(name, compiled)``
  makes pipeline invocations (block + carried state) first-class
  requests through the deadline batcher, admission control, and
  per-pipeline-class breakers;
* **ship** it: :func:`pipeline_from_spec` rebuilds a compiled chain
  from a pure-JSON spec (``{"name", "block", "stages": [{"stage":
  "sosfilt", "sos": [...]}, ...]}``) — how ``spawn="subprocess"``
  replicas receive their pipelines over the ``_replica_main`` command
  line and register them before serving RPC traffic.
"""

import numpy as _np

from veles.simd_tpu.pipeline.compiler import (PIPELINE_SITE,
                                              CompiledPipeline,
                                              Pipeline)
from veles.simd_tpu.pipeline.stages import (Stage, correlate,
                                            detect_peaks, detrend,
                                            fir, matched_filter,
                                            medfilt, power, power_db,
                                            resample_poly, savgol,
                                            sosfilt, stft, welch)

__all__ = [
    "Pipeline", "CompiledPipeline", "PIPELINE_SITE", "Stage",
    "fir", "correlate", "matched_filter", "sosfilt", "resample_poly",
    "medfilt", "detrend", "stft", "power", "power_db", "welch",
    "savgol", "detect_peaks", "pipeline_from_spec", "SPEC_FACTORIES",
]

# the declarative surface: spec {"stage": <key>} resolves through this
# table, so a spec can only name the public stage factories
SPEC_FACTORIES = {
    "fir": fir, "correlate": correlate,
    "matched_filter": matched_filter, "sosfilt": sosfilt,
    "resample_poly": resample_poly, "medfilt": medfilt,
    "detrend": detrend, "stft": stft, "power": power,
    "power_db": power_db, "welch": welch, "savgol": savgol,
    "detect_peaks": detect_peaks,
}


def pipeline_from_spec(spec: dict) -> CompiledPipeline:
    """Compile a chain from a pure-JSON declarative spec.

    ``spec`` is ``{"name": str, "block": int, "stages": [{"stage":
    factory_key, **kwargs}, ...]}`` where ``factory_key`` names an
    entry of :data:`SPEC_FACTORIES` and the remaining keys are that
    factory's keyword arguments (list-valued kwargs — filter taps,
    SOS rows, windows — become float64 arrays).  This is the form a
    pipeline crosses a process boundary in: the parent serializes the
    spec, the ``serve.cluster._replica_main`` child rebuilds and
    registers the compiled chain before taking traffic.  Malformed
    specs raise ``ValueError`` (typed, never a half-built chain)."""
    if not isinstance(spec, dict):
        raise ValueError(f"pipeline spec must be a dict, got "
                         f"{type(spec).__name__}")
    name = spec.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError("pipeline spec needs a non-empty 'name'")
    try:
        block = int(spec.get("block"))
    except (TypeError, ValueError):
        raise ValueError(f"pipeline spec {name!r} needs an integer "
                         f"'block'") from None
    raw_stages = spec.get("stages")
    if not isinstance(raw_stages, (list, tuple)) or not raw_stages:
        raise ValueError(f"pipeline spec {name!r} needs a non-empty "
                         f"'stages' list")
    stages = []
    for i, entry in enumerate(raw_stages):
        if not isinstance(entry, dict) or "stage" not in entry:
            raise ValueError(f"pipeline spec {name!r} stage #{i} must "
                             f"be a dict with a 'stage' key")
        key = entry["stage"]
        factory = SPEC_FACTORIES.get(key)
        if factory is None:
            raise ValueError(
                f"pipeline spec {name!r} stage #{i}: unknown stage "
                f"{key!r} (known: {sorted(SPEC_FACTORIES)})")
        kwargs = {k: (_np.asarray(v, dtype=_np.float64)
                      if isinstance(v, (list, tuple)) else v)
                  for k, v in entry.items() if k != "stage"}
        try:
            stages.append(factory(**kwargs))
        except (TypeError, ValueError) as e:
            raise ValueError(f"pipeline spec {name!r} stage #{i} "
                             f"({key}): {e}") from e
    return Pipeline(stages, name=name).compile(block)
