#!/usr/bin/env python
"""Load generator + chaos harness for the serving layer.

Drives :class:`veles.simd_tpu.serve.Server` with Poisson (optionally
bursty) arrivals over a mixed op/shape/tenant traffic matrix and
accounts for every request: answered-ok, answered-degraded, shed
(typed Overloaded), errored, LOST (never answered — always a bug), and
double-answered (the ticket layer raises + counts; always a bug).

Three consumers:

* **tests** (``tests/test_serve.py``) import :func:`build_schedule` /
  :func:`run_load` as the overload + device-loss chaos harness — with
  ``VELES_SIMD_FAULT_PLAN`` armed the whole shed/retry/degrade/recover
  story runs deterministically on CPU CI;
* **`make serve-smoke`** — a seconds-long CPU sanity run (rc=1 on any
  lost/double-answered request or parity failure);
* **`make bench-serve`** — the serve bench family: writes
  ``SERVE_DETAILS.json`` rows (throughput + inverse-p99, both
  higher-is-better so the regression gate's floor logic applies
  unchanged) gated via ``python tools/bench_regress.py --details
  SERVE_DETAILS.json``.

Usage::

    python tools/loadgen.py --smoke
    python tools/loadgen.py --requests 400 --rate 800 --burst-every 50 \\
        --burst-size 20 --details SERVE_DETAILS.json
    VELES_SIMD_FAULT_PLAN=serve.dispatch:device_lost:3 \\
        python tools/loadgen.py --smoke   # chaos on
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu import obs  # noqa: E402
from veles.simd_tpu import serve  # noqa: E402

# the traffic matrix: (op, params factory, signal lengths) — short
# mixed signals, the dispatch-bound regime serving exists for.  Length
# spread inside one op lands in 2-3 pow2 buckets, so the run exercises
# bucketing, not just batching.
_SOS = None


def _sos():
    global _SOS
    if _SOS is None:
        from veles.simd_tpu.ops import iir

        _SOS = iir.butterworth(4, 0.25, "lowpass")
    return _SOS


def _mix():
    return [
        ("sosfilt", lambda: {"sos": _sos()}, (384, 500, 777, 1024)),
        ("lfilter", lambda: {"b": [0.2, 0.3, 0.1],
                             "a": [1.0, -0.4, 0.1]}, (256, 640)),
        ("resample_poly", lambda: {"up": 3, "down": 2}, (300, 512)),
        ("stft", lambda: {"frame_length": 128, "hop": 64},
         (512, 1000)),
    ]


DEFAULT_TENANTS = ("alice", "bob", "carol")

# the pipeline-invocation traffic leg: a small compiled chain served
# as a first-class unit (op "pipeline:<name>"), each stream threading
# its carried state through consecutive invocations
PIPELINE_NAME = "loadline"
PIPELINE_BLOCK = 256


def build_pipeline(name: str = PIPELINE_NAME,
                   block: int = PIPELINE_BLOCK):
    """A small compiled pipeline for the serving legs: IIR conditioning
    into a causal FIR — two carried states (zi + halo), cheap enough
    for the CPU smoke."""
    from veles.simd_tpu import pipeline as pl
    from veles.simd_tpu.ops import iir

    sos = iir.butterworth(4, 0.2, "lowpass")
    rng = np.random.RandomState(7)
    h = rng.randn(17).astype(np.float32) / 4.0
    chain = pl.Pipeline([pl.sosfilt(sos, name="condition"),
                         pl.fir(h, name="shape")], name=name)
    return chain.compile(block)


def run_pipeline_streams(server, op: str, compiled, rng, *,
                         streams: int = 2, blocks: int = 4,
                         deadline_ms: float | None = None,
                         result_timeout: float = 120.0,
                         verify: bool = True) -> dict:
    """Drive ``streams`` independent pipeline streams through the
    server, ``blocks`` invocations each, threading every answer's
    carried state into the stream's next invocation (the
    pipeline-serving contract).  Same accounting categories as
    :func:`run_load`; ``verify`` parity-checks each surviving stream's
    concatenated output against the compiled chain's one-shot oracle
    (state threading through the SERVER must be exact — degraded
    blocks included)."""
    nb = compiled.block_len
    report = {"requests": 0, "ok": 0, "degraded": 0, "shed": 0,
              "closed": 0, "errors": 0, "lost": 0, "deadline_miss": 0,
              "parity_failures": 0, "double_answered": 0}
    sigs = {i: rng.randn(blocks * nb).astype(np.float32)
            for i in range(streams)}
    states = {i: None for i in range(streams)}
    outs: dict = {i: [] for i in range(streams)}
    alive = set(range(streams))
    for b in range(blocks):
        tickets = {}
        for i in sorted(alive):
            tickets[i] = server.submit(
                op=op, x=sigs[i][b * nb:(b + 1) * nb],
                params={"state": states[i]}, tenant=f"pstream{i}",
                deadline_ms=deadline_ms)
        report["requests"] += len(tickets)
        for i, t in tickets.items():
            try:
                value = t.result(timeout=result_timeout)
            except TimeoutError:
                report["lost"] += 1
                alive.discard(i)
                continue
            except serve.Overloaded:
                report["shed"] += 1
                alive.discard(i)
                continue
            except serve.DeadlineExceeded:
                report["deadline_miss"] += 1
                alive.discard(i)
                continue
            except serve.ServerClosed:
                report["closed"] += 1
                alive.discard(i)
                continue
            except Exception:  # noqa: BLE001 — typed per-request
                report["errors"] += 1
                alive.discard(i)
                continue
            y, new_state = value
            outs[i].append(y)
            states[i] = new_state
            report["degraded" if t.degraded else "ok"] += 1
    if verify:
        for i in sorted(alive):
            done = len(outs[i])
            if not done:
                continue
            got = compiled.assemble(outs[i])
            want = compiled.oracle(sigs[i][: done * nb])
            scale = float(np.max(np.abs(want))) or 1.0
            if float(np.max(np.abs(got - want)) / scale) > 2e-3:
                report["parity_failures"] += 1
    report["double_answered"] = obs.counter_value(
        "serve_double_answer") if obs.enabled() else 0
    return report


def build_schedule(rng, n_requests: int, rate_hz: float,
                   burst_every: int = 0, burst_size: int = 0,
                   tenants=DEFAULT_TENANTS,
                   deadline_ms: float | None = None) -> list:
    """``[(gap_seconds, Request), ...]`` — exponential inter-arrival
    gaps at ``rate_hz`` (0 = no pacing, submit as fast as possible),
    with a ``burst_size`` zero-gap burst every ``burst_every``-th
    arrival (the overload trigger).  ``deadline_ms`` stamps every
    request with an end-to-end deadline (None = server default)."""
    mix = _mix()
    schedule = []
    for i in range(n_requests):
        op, params, lengths = mix[rng.randint(len(mix))]
        n = int(lengths[rng.randint(len(lengths))])
        x = rng.randn(n).astype(np.float32)
        req = serve.Request(op, x, params(),
                            tenant=tenants[rng.randint(len(tenants))],
                            deadline_ms=deadline_ms)
        gap = float(rng.exponential(1.0 / rate_hz)) if rate_hz > 0 \
            else 0.0
        if burst_every and burst_size and i and i % burst_every == 0:
            gap = 0.0
        schedule.append((gap, req))
        if burst_every and burst_size and i and i % burst_every == 0:
            for _ in range(burst_size):
                op2, params2, lengths2 = mix[rng.randint(len(mix))]
                n2 = int(lengths2[rng.randint(len(lengths2))])
                schedule.append((0.0, serve.Request(
                    op2, rng.randn(n2).astype(np.float32), params2(),
                    tenant=tenants[rng.randint(len(tenants))],
                    deadline_ms=deadline_ms)))
    return schedule


def _oracle_answer(req: serve.Request):
    from veles.simd_tpu.serve.server import _oracle_call

    xs = np.asarray(req.x, np.float32)[None, :]
    return np.asarray(_oracle_call(req.op, xs, _canonical(req)))[0]


def _canonical(req: serve.Request) -> dict:
    from veles.simd_tpu.serve.server import _OPS

    validate, _ = _OPS[req.op]
    params, _ = validate(req.params, int(np.shape(req.x)[0]))
    return params


def run_load(server, schedule, *, block: bool = False,
             block_timeout: float | None = 1.0,
             result_timeout: float = 120.0,
             verify: int = 0, rng=None) -> dict:
    """Submit ``schedule`` against ``server``, wait for every ticket,
    and return the accounting report (see module docstring for the
    categories).  ``verify=k`` parity-checks ``k`` randomly sampled
    answered requests against the NumPy oracle (DEGRADED answers ARE
    the oracle, so they must match exactly-ish too)."""
    t0 = time.perf_counter()
    pairs = []
    for gap, req in schedule:
        if gap > 0:
            time.sleep(gap)
        pairs.append((req, server.submit(req, block=block,
                                         timeout=block_timeout)))
    submitted_s = time.perf_counter() - t0
    report = {"requests": len(pairs), "ok": 0, "degraded": 0,
              "shed": 0, "closed": 0, "errors": 0, "lost": 0,
              "deadline_miss": 0,
              "double_answered": 0, "parity_failures": 0,
              "submit_wall_s": submitted_s}
    answered = []
    waits = []
    tenant_submitted: dict = {}
    tenant_answered: dict = {}
    for req, ticket in pairs:
        tenant_submitted[req.tenant] = \
            tenant_submitted.get(req.tenant, 0) + 1
        try:
            value = ticket.result(timeout=result_timeout)
        except TimeoutError:
            report["lost"] += 1
            continue
        except serve.Overloaded:
            report["shed"] += 1
            continue
        except serve.DeadlineExceeded:
            report["deadline_miss"] += 1
            continue
        except serve.ServerClosed:
            report["closed"] += 1
            continue
        except Exception:  # noqa: BLE001 — typed per-request error
            report["errors"] += 1
            continue
        report["degraded" if ticket.degraded else "ok"] += 1
        tenant_answered[req.tenant] = \
            tenant_answered.get(req.tenant, 0) + 1
        answered.append((req, value))
        if ticket.wait_s is not None:
            waits.append(ticket.wait_s)
    report["wall_s"] = time.perf_counter() - t0
    # per-tenant fairness under overload: the max/min ANSWERED RATIO
    # (answered[t] / submitted[t] — raw counts would read random
    # arrival imbalance as unfairness) across tenants.  max/min is
    # the human form (1.0 = perfectly fair, a starved tenant pushes
    # it toward infinity, reported None when one tenant got nothing);
    # min/max in [0, 1] is the bench-gate form — higher is better,
    # so the regression gate's floor logic applies unchanged.
    report["tenant_submitted"] = dict(sorted(tenant_submitted.items()))
    report["tenant_answered"] = dict(sorted(tenant_answered.items()))
    if len(tenant_submitted) > 1:
        ratios = [tenant_answered.get(t, 0) / n
                  for t, n in tenant_submitted.items() if n]
        lo, hi = min(ratios), max(ratios)
        report["fairness_max_min"] = (hi / lo if lo else None)
        report["fairness_min_max"] = (lo / hi if hi else 0.0)
    report["double_answered"] = obs.counter_value(
        "serve_double_answer") if obs.enabled() else 0
    if waits:
        ws = np.sort(np.asarray(waits))
        report["wait_p50_s"] = float(ws[int(0.50 * (len(ws) - 1))])
        report["wait_p99_s"] = float(ws[int(0.99 * (len(ws) - 1))])
        report["wait_max_s"] = float(ws[-1])
    done = report["ok"] + report["degraded"]
    report["throughput_rps"] = (done / report["wall_s"]
                                if report["wall_s"] > 0 else 0.0)
    if verify and answered:
        rng = rng or np.random.RandomState(0)
        idx = rng.choice(len(answered), min(verify, len(answered)),
                         replace=False)
        for i in idx:
            req, got = answered[int(i)]
            want = _oracle_answer(req)
            scale = float(np.max(np.abs(want))) or 1.0
            err = float(np.max(np.abs(np.asarray(got) - want))
                        / scale)
            if err > 2e-3:
                report["parity_failures"] += 1
    return report


def bench_rows(report: dict) -> list:
    """SERVE_DETAILS.json rows for ``tools/bench_regress.py`` — both
    higher-is-better (the gate's floor logic assumes throughput rows),
    so p99 latency is emitted as its inverse."""
    rows = [{
        "metric": "serve throughput",
        "value": round(report["throughput_rps"], 2),
        "unit": "req/s",
        "vs_baseline": None,
    }]
    if report.get("wait_p99_s"):
        rows.append({
            "metric": "serve p99 inverse latency",
            "value": round(1.0 / report["wait_p99_s"], 2),
            "unit": "1/s",
            "vs_baseline": None,
        })
    if report.get("fairness_min_max") is not None:
        rows.append({
            "metric": "serve tenant fairness",
            "value": round(report["fairness_min_max"], 4),
            "unit": "min/max answered ratio",
            "vs_baseline": None,
        })
    answered = report.get("ok", 0) + report.get("degraded", 0)
    misses = report.get("deadline_miss", 0)
    if answered + misses:
        rows.append({
            "metric": "serve deadline hit rate",
            "value": round(answered / (answered + misses), 4),
            "unit": "fraction",
            "vs_baseline": None,
        })
    if obs.enabled():
        snap = obs.snapshot()
        rows.append({"metric": "serve batches",
                     "value": float(sum(
                         c["value"] for c in snap["counters"]
                         if c["name"] == "serve_batches")),
                     "unit": "batches", "vs_baseline": None,
                     "telemetry": {"counters": {
                         c["name"]: c["value"]
                         for c in snap["counters"]
                         if c["name"].startswith(("serve_", "fault_",
                                                  "breaker_",
                                                  "mesh_"))}}})
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=300)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="Poisson arrival rate, Hz (0 = flat out)")
    ap.add_argument("--burst-every", type=int, default=40)
    ap.add_argument("--burst-size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--tenant-depth", type=int, default=None)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="end-to-end deadline stamped on every "
                         "request (default: server default)")
    ap.add_argument("--block", action="store_true",
                    help="backpressure submits instead of shedding")
    ap.add_argument("--verify", type=int, default=16,
                    help="oracle parity sample size (0 = off)")
    ap.add_argument("--details", default=None,
                    help="write bench rows here (SERVE_DETAILS.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny run, gate on lost/double/parity")
    ap.add_argument("--pipeline-streams", type=int, default=None,
                    help="pipeline-invocation streams to serve "
                         "(default: 2 in --smoke, else 0)")
    ap.add_argument("--pipeline-blocks", type=int, default=4,
                    help="invocations per pipeline stream")
    args = ap.parse_args(argv)

    from veles.simd_tpu.utils.platform import maybe_override_platform

    maybe_override_platform()
    obs.enable()
    obs.reset()
    if args.smoke:
        args.requests = min(args.requests, 80)
        args.rate = 0.0
    rng = np.random.RandomState(args.seed)
    schedule = build_schedule(rng, args.requests, args.rate,
                              args.burst_every, args.burst_size,
                              deadline_ms=args.deadline_ms)
    server = serve.Server(max_batch=args.max_batch,
                          max_wait_ms=args.max_wait_ms,
                          queue_depth=args.queue_depth,
                          tenant_depth=args.tenant_depth,
                          workers=args.workers)
    pipeline_streams = args.pipeline_streams
    if pipeline_streams is None:
        pipeline_streams = 2 if args.smoke else 0
    with server:
        report = run_load(server, schedule, block=args.block,
                          verify=args.verify, rng=rng)
        if pipeline_streams > 0:
            compiled = build_pipeline()
            op = server.register_pipeline(PIPELINE_NAME, compiled)
            prep = run_pipeline_streams(
                server, op, compiled, rng,
                streams=pipeline_streams,
                blocks=args.pipeline_blocks,
                deadline_ms=args.deadline_ms)
            report["pipeline"] = prep
            # the global accounting gates cover the pipeline leg too
            for k in ("lost", "parity_failures"):
                report[k] += prep[k]
            report["double_answered"] = max(report["double_answered"],
                                            prep["double_answered"])
        report["health"] = server.stats()["health"]
    report["dispatch_quantiles"] = obs.quantiles(
        "span.serve.dispatch", phase="steady")
    print(json.dumps(report, indent=2, default=str))
    if args.details:
        with open(args.details, "w") as f:
            json.dump(bench_rows(report), f, indent=2)
        print(f"loadgen: wrote {args.details}", file=sys.stderr)
    bad = (report["lost"] or report["double_answered"]
           or report["parity_failures"])
    if bad:
        print(f"loadgen: FAILED accounting (lost={report['lost']} "
              f"double={report['double_answered']} "
              f"parity={report['parity_failures']})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
