"""Waveform generators vs scipy and the float64 twins.

The reference hand-rolls every test stimulus (``tests/*.cc`` loops);
this family is the standard generator set, new capability.
"""

import numpy as np
import pytest

from scipy import signal as ss

from veles.simd_tpu.ops import waveforms as wf


class TestChirp:
    T = np.linspace(0, 2, 4001)

    @pytest.mark.parametrize("method,f0,f1", [
        ("linear", 10, 100), ("quadratic", 10, 100),
        ("logarithmic", 5, 200), ("hyperbolic", 100, 10),
        ("hyperbolic", 10, 100), ("linear", 100, 100),
    ])
    def test_matches_scipy(self, method, f0, f1):
        got = wf.chirp_na(self.T, f0, 2.0, f1, method, phi=25.0)
        want = ss.chirp(self.T, f0, 2.0, f1, method=method, phi=25.0)
        np.testing.assert_allclose(got, want, atol=1e-10)

    def test_xla_vs_oracle(self):
        """f32 phase accumulation costs ~1e-4 over a 1000-rad sweep —
        the documented device-precision envelope."""
        got = np.asarray(wf.chirp(self.T, 10, 2.0, 100, simd=True))
        want = wf.chirp_na(self.T, 10, 2.0, 100)
        np.testing.assert_allclose(got, want, atol=5e-4)

    def test_instantaneous_frequency(self):
        """The analytic-signal frequency of a linear chirp tracks the
        commanded sweep (cross-family check via ops.spectral)."""
        from veles.simd_tpu.ops import spectral as sp

        fs, dur = 8192.0, 1.0
        t = np.arange(int(fs * dur)) / fs
        x = wf.chirp_na(t, 500, dur, 2000).astype(np.float32)
        z = np.asarray(sp.hilbert(x, simd=True))
        inst = np.diff(np.unwrap(np.angle(z))) * fs / (2 * np.pi)
        mid = slice(1000, 7000)
        want = 500 + (2000 - 500) * t[mid]
        assert np.max(np.abs(inst[mid] - want)) < 30.0

    def test_contracts(self):
        with pytest.raises(ValueError, match="t1"):
            wf.chirp_na(self.T, 10, 0.0, 100)
        with pytest.raises(ValueError, match="logarithmic"):
            wf.chirp_na(self.T, -1, 2.0, 100, "logarithmic")
        with pytest.raises(ValueError, match="method"):
            wf.chirp_na(self.T, 10, 2.0, 100, "cubic")


class TestPeriodic:
    PH = np.linspace(0, 25, 5001)

    @pytest.mark.parametrize("duty", [0.1, 0.3, 0.5, 0.9])
    def test_square_matches_scipy(self, duty):
        np.testing.assert_allclose(wf.square_na(self.PH, duty),
                                   ss.square(self.PH, duty), atol=0)
        got = np.asarray(wf.square(self.PH, duty, simd=True))
        np.testing.assert_allclose(got, ss.square(self.PH, duty),
                                   atol=1e-5)

    @pytest.mark.parametrize("width", [0.0, 0.25, 0.5, 1.0])
    def test_sawtooth_matches_scipy(self, width):
        np.testing.assert_allclose(wf.sawtooth_na(self.PH, width),
                                   ss.sawtooth(self.PH, width),
                                   atol=1e-12)

    def test_sawtooth_xla(self):
        got = np.asarray(wf.sawtooth(self.PH, 0.5, simd=True))
        want = ss.sawtooth(self.PH, 0.5)
        # f32 phase-wrap jitter flips samples right at the apex
        close = np.abs(got - want) < 1e-2
        assert close.mean() > 0.999
        np.testing.assert_allclose(np.sort(got)[50:-50],
                                   np.sort(want)[50:-50], atol=1e-2)

    def test_contracts(self):
        with pytest.raises(ValueError, match="duty"):
            wf.square_na(self.PH, 1.5)
        with pytest.raises(ValueError, match="width"):
            wf.sawtooth_na(self.PH, -0.1)


class TestPulses:
    def test_gausspulse_matches_scipy(self):
        t = np.linspace(-0.01, 0.01, 2001)
        np.testing.assert_allclose(
            wf.gausspulse_na(t, 1000, 0.5),
            ss.gausspulse(t, fc=1000, bw=0.5), atol=1e-12)
        got = np.asarray(wf.gausspulse(t, 1000, 0.5, simd=True))
        np.testing.assert_allclose(got, ss.gausspulse(t, fc=1000, bw=0.5),
                                   atol=1e-5)

    def test_gausspulse_bandwidth(self):
        """The -6 dB spectral width matches the commanded fractional
        bandwidth (cross-check via the PSD family)."""
        from veles.simd_tpu.ops import spectral as sp

        fs = 16000.0
        t = (np.arange(4096) - 2048) / fs
        x = wf.gausspulse_na(t, 2000, 0.5).astype(np.float32)
        f, p = sp.periodogram(x, fs=fs, detrend_type=None)
        p = np.asarray(p)
        half = p >= p.max() * 10 ** (-6.0 / 10.0)
        width = f[half].max() - f[half].min()
        assert abs(width - 0.5 * 2000) < 150.0

    def test_unit_impulse(self):
        np.testing.assert_allclose(
            wf.unit_impulse(11, "mid", simd=False),
            ss.unit_impulse(11, "mid"))
        d = np.asarray(wf.unit_impulse(8, 3, simd=True))
        assert d[3] == 1.0 and d.sum() == 1.0
        with pytest.raises(ValueError, match="idx"):
            wf.unit_impulse(8, 8)

    def test_contracts(self):
        with pytest.raises(ValueError, match="fc"):
            wf.gausspulse_na(np.zeros(4), fc=-1)
        with pytest.raises(ValueError, match="bwr"):
            wf.gausspulse_na(np.zeros(4), bwr=3.0)


class TestMLSAndWindows:
    def test_mls_bit_exact_vs_scipy(self):
        for nb in (2, 3, 5, 8, 12, 15):
            got, st = wf.max_len_seq(nb)
            want, wst = ss.max_len_seq(nb)
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(st, wst)

    def test_mls_resume_and_state(self):
        g1, s1 = wf.max_len_seq(8, length=100)
        g2, _ = wf.max_len_seq(8, state=s1, length=155)
        full, _ = ss.max_len_seq(8)
        np.testing.assert_array_equal(np.r_[g1, g2], full)

    def test_mls_autocorrelation_is_delta(self):
        """The defining property: the ±1-mapped MLS has circular
        autocorrelation N at lag 0 and -1 everywhere else."""
        seq, _ = wf.max_len_seq(10)
        s = 2.0 * seq - 1.0
        ac = np.fft.irfft(np.abs(np.fft.rfft(s)) ** 2, len(s))
        assert abs(ac[0] - len(s)) < 1e-6
        np.testing.assert_allclose(ac[1:], -1.0, atol=1e-6)

    def test_mls_contracts(self):
        with pytest.raises(ValueError, match="nbits"):
            wf.max_len_seq(1)
        with pytest.raises(ValueError, match="all zero"):
            wf.max_len_seq(4, state=np.zeros(4))

    def test_windows_match_scipy(self):
        for name, arg, kw in [("hann", "hann", {}),
                              ("hamming", "hamming", {}),
                              ("blackman", "blackman", {}),
                              ("bartlett", "bartlett", {}),
                              ("kaiser", ("kaiser", 8.6),
                               {"beta": 8.6})]:
            got = wf.get_window(name, 64, **kw)
            want = ss.get_window(arg, 64, fftbins=False)
            np.testing.assert_allclose(got, want, atol=1e-12)

    def test_window_feeds_welch(self):
        """get_window output plugs into the spectral estimators."""
        from veles.simd_tpu.ops import spectral as sp

        x = np.random.RandomState(13).randn(4096).astype(np.float32)
        w = wf.get_window("blackman", 256)
        f1, p1 = sp.welch(x, nperseg=256, window=w, simd=True)
        # (our get_window is symmetric while scipy's default is
        # periodic, so feed scipy the identical array)
        f3, p3 = ss.welch(x.astype(np.float64), nperseg=256, window=w)
        np.testing.assert_allclose(np.asarray(p1), p3,
                                   atol=1e-5 * p3.max())

    def test_window_contracts(self):
        with pytest.raises(ValueError, match="kaiser"):
            wf.get_window("kaiser", 32)
        with pytest.raises(ValueError, match="window"):
            wf.get_window("bohman", 32)
        # tukey gained a default alpha=0.5 in round 4 — it must NOT raise
        w = wf.get_window("tukey", 32)
        assert len(w) == 32

    def test_mls_length_cap(self):
        with pytest.raises(ValueError, match="2\\^22"):
            wf.max_len_seq(32)          # full period would be 4e9 bits
        seq, _ = wf.max_len_seq(32, length=1000)   # explicit length ok
        assert len(seq) == 1000

    def test_window_stray_kwargs(self):
        with pytest.raises(ValueError, match="unexpected"):
            wf.get_window("hann", 32, beta=8.6)
        # the tuple form carries its own parameter — a conflicting
        # keyword must not be silently dropped
        with pytest.raises(ValueError, match="unexpected"):
            wf.get_window(("kaiser", 8.6), 32, beta=2.0)
        np.testing.assert_allclose(wf.get_window(("kaiser", 8.6), 32),
                                   wf.get_window("kaiser", 32, beta=8.6))


class TestMoreWindows:
    """Round-4 window additions vs scipy's symmetric forms."""

    @pytest.mark.parametrize("name,kw,spec", [
        ("blackmanharris", {}, "blackmanharris"),
        ("nuttall", {}, "nuttall"),
        ("flattop", {}, "flattop"),
        ("cosine", {}, "cosine"),
        ("tukey", {"alpha": 0.3}, ("tukey", 0.3)),
        ("tukey", {}, ("tukey", 0.5)),
        ("gaussian", {"std": 7.0}, ("gaussian", 7.0)),
    ])
    def test_matches_scipy_symmetric(self, name, kw, spec):
        from scipy import signal as ss

        for n in (1, 2, 16, 51):
            mine = wf.get_window(name, n, **kw)
            want = ss.get_window(spec, n, fftbins=False)
            np.testing.assert_allclose(mine, want, atol=1e-12)

    def test_contracts(self):
        with pytest.raises(ValueError, match="std"):
            wf.get_window("gaussian", 16)
        with pytest.raises(ValueError, match="alpha"):
            wf.get_window("tukey", 16, alpha=1.5)
        with pytest.raises(ValueError, match="unexpected"):
            wf.get_window("hann", 16, alpha=0.5)
