#!/usr/bin/env python
"""Benchmark harness: reference workloads on the TPU backend.

Measures the five BASELINE.md configs (the reference's benchmark workloads,
``tests/benchmark.inc`` pattern) on the default JAX device and prints ONE
JSON line for the headline metric — the 1M-point convolution in
Msamples/s (BASELINE.json configs[3], the flagship long-signal path) —
with ``vs_baseline`` = speedup over the single-threaded CPU oracle
(NumPy, the reference's ``*_na`` twin) measured in the same process.

Before timing, the per-family XLA-vs-oracle correctness smoke
(``tools/tpu_smoke.py``) runs on the same device and prints one
``TPU-CHECK`` line per family to stderr — the reference's SIMD-vs-``_na``
discipline on real hardware.  Full per-config results go to
BENCH_DETAILS.json.

Usage:  python bench.py           # one JSON line on stdout
        python bench.py --all     # pretty table of every config
        python bench.py --check   # correctness smoke only, no timing
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from veles.simd_tpu.utils.benchmark import (
    device_time_chained, host_time, rms_normalize)


def bench_elementwise(rng):
    """Config 1: f32 add/mul + int16->float, N=4096 (batched to fill the
    chip: 4096 signals of 4096 — per-op timing at N=4096 alone measures
    dispatch, not the VPU)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import arithmetic as ar

    n = 4096
    batch = 4096
    a_np = rng.randn(batch, n).astype(np.float32)
    b_np = rng.randn(batch, n).astype(np.float32)
    i16 = rng.randint(-3000, 3000, (batch, n)).astype(np.int16)
    b = jnp.asarray(b_np)
    i16j = jnp.asarray(i16)

    def step(v):
        # int16 carry: both conversions run every iteration (nothing is
        # loop-invariant or affine — the trunc-saturate cast is nonlinear,
        # so XLA can neither hoist the converts nor reduce the loop).
        # Values stay in the +-3000 range the saturating cast allows.
        f = ar._int16_to_float(v)                  # convert i16 -> f32
        return ar._float_to_int16((f * 1e-4 + b) * 300.0)  # mul, add, back

    t = device_time_chained(step, i16j)
    elems = batch * n
    t_base = host_time(
        lambda: (a_np + b_np) * i16.astype(np.float32))
    return {"metric": "elementwise add*mul*convert", "unit": "Melem/s",
            "value": elems / t / 1e6, "baseline": elems / t_base / 1e6}


def bench_mathfun(rng):
    """Config 2: sin/cos/log/exp on 1M floats."""
    import jax.numpy as jnp

    n = 1 << 20
    x_np = np.abs(rng.randn(n).astype(np.float32)) + 0.1
    x = jnp.asarray(x_np)

    def step(v):  # 4 transcendentals; output stays in [0.1, ~4.7]
        return jnp.abs(jnp.sin(v) + jnp.cos(v) + jnp.log(v)
                       + jnp.exp(-v)) + 0.1

    t = device_time_chained(step, x)
    t_base = host_time(
        lambda: np.sin(x_np) + np.cos(x_np) + np.log(x_np) + np.exp(-x_np))
    # 4 transcendentals per element
    return {"metric": "sin+cos+log+exp 1M floats", "unit": "Msamples/s",
            "value": 4 * n / t / 1e6, "baseline": 4 * n / t_base / 1e6}


def bench_sgemm(rng):
    """Config 3: sgemm 512x512 (+ a gemv) in GFLOP/s."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import matrix as mx

    n = 512
    a_np = rng.randn(n, n).astype(np.float32)
    b_np = rng.randn(n, n).astype(np.float32)
    a, b = jnp.asarray(a_np), jnp.asarray(b_np)

    def step(v):  # rms-normalized so 256 chained GEMMs don't blow up
        return rms_normalize(mx._matmul(v, b))

    t = device_time_chained(step, a)
    flops = 2 * n ** 3
    t_base = host_time(lambda: mx.matrix_multiply_novec(a_np, b_np))
    return {"metric": "sgemm 512", "unit": "GFLOP/s",
            "value": flops / t / 1e9, "baseline": flops / t_base / 1e9}


def bench_convolve_1m(rng):
    """Config 4 (headline): 1M-point convolution, 2047-tap filter,
    overlap-save vs the NumPy-FFT oracle (the strongest CPU formulation
    available — np.convolve direct form would be ~100x slower still)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv

    n, k = 1 << 20, 2047
    x = rng.randn(n).astype(np.float32)
    h = rng.randn(k).astype(np.float32)
    handle = cv.convolve_overlap_save_initialize(n, k)
    xd, hd = jnp.asarray(x), jnp.asarray(h)  # device-resident: measure the
    # chip, not the tunnel

    def step(v):  # 1e-30 * y forces the conv without perturbing v
        y = cv.convolve_overlap_save(handle, v, hd, simd=True)
        return v + 1e-30 * y[..., :n]

    t = device_time_chained(step, xd)
    t_base = host_time(lambda: cv._conv_overlap_save_na(
        x, h, handle.block_length), repeats=2)
    return {"metric": "convolve 1M x 2047 overlap-save",
            "unit": "Msamples/s",
            "value": n / t / 1e6, "baseline": n / t_base / 1e6}


def bench_dwt(rng):
    """Config 5: DWT daub8 + SWT sym8, batch of 512 x 4096 signals."""
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    import jax.numpy as jnp

    batch, n = 512, 4096
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)

    def step(v):  # [B, n] -> hi, lo each [B, n/2] -> concat back to [B, n]
        hi, lo = wv.wavelet_apply(
            WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, v,
            simd=True)
        return jnp.concatenate([hi, lo], axis=-1)

    t = device_time_chained(step, xd)
    t_base = host_time(lambda: wv.wavelet_apply_na(
        WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x),
        repeats=2)
    samples = batch * n
    return {"metric": "DWT daub8 512x4096", "unit": "Msamples/s",
            "value": samples / t / 1e6, "baseline": samples / t_base / 1e6}


def main():
    from veles.simd_tpu.utils.platform import (
        maybe_override_platform, require_reachable_device)

    maybe_override_platform()  # VELES_SIMD_PLATFORM=cpu runs without TPU
    # fail fast on a wedged relay rather than hanging, but give it a
    # 10-min recovery window first (wedges have been observed to clear);
    # $VELES_SIMD_DEVICE_WAIT overrides (0 restores pure fail-fast)
    require_reachable_device(wait=600.0)
    import jax

    from tools.tpu_smoke import run_smoke

    smoke_ok = run_smoke()
    if "--check" in sys.argv:
        sys.exit(0 if smoke_ok else 1)
    if not smoke_ok:
        print("bench.py: correctness smoke FAILED on "
              f"{jax.devices()[0]!r}; timing numbers below are suspect",
              file=sys.stderr)

    rng = np.random.RandomState(0)
    configs = [bench_elementwise, bench_mathfun, bench_sgemm,
               bench_convolve_1m, bench_dwt]
    results = []
    for fn in configs:
        r = fn(rng)
        r["vs_baseline"] = r["value"] / r["baseline"]
        r["device"] = str(jax.devices()[0])
        results.append(r)
        if "--all" in sys.argv:
            print(f"{r['metric']:36s} {r['value']:12.1f} {r['unit']:11s} "
                  f"(cpu-oracle {r['baseline']:10.1f}, "
                  f"x{r['vs_baseline']:.1f})", file=sys.stderr)

    with open("BENCH_DETAILS.json", "w") as f:
        json.dump(results, f, indent=2)

    head = next(r for r in results
                if r["metric"].startswith("convolve 1M"))
    print(json.dumps({
        "metric": head["metric"],
        "value": round(head["value"], 2),
        "unit": head["unit"],
        "vs_baseline": round(head["vs_baseline"], 2),
    }))


if __name__ == "__main__":
    main()
