"""veles.simd_tpu.obs — runtime telemetry: *what was decided*, counted.

The framework's headline feature is automatic best-algorithm selection
(``ops/convolve.py`` re-derives ``src/convolve.c:328-364`` for TPU), but
selection you cannot observe is selection you cannot tune.  This package
is the accounting layer for every dispatch-time decision:

* **counters / gauges / timing histograms** —
  :class:`~veles.simd_tpu.obs.registry.MetricsRegistry`: XLA-vs-oracle
  dispatches per op, compile counts, cache hits;
* **a bounded structured event log** —
  :class:`~veles.simd_tpu.obs.events.EventLog`: one event per algorithm
  decision (convolution algorithm + geometry, STFT framing path, wavelet
  kernel route, shard geometry);
* **compile tracking** — :mod:`~veles.simd_tpu.obs.compile` bridges
  ``jax.monitoring`` into the registry, so backend compiles and
  persistent-cache hit/miss traffic finally show up in numbers;
* **exporters** — :mod:`~veles.simd_tpu.obs.export`: lossless JSON
  snapshot, Prometheus text format, and a human ``report()`` table.

Contract with the compute layer (enforced by ``tools/lint.py``):

* ops modules touch telemetry ONLY through :func:`record_decision` and
  :func:`count`, and ONLY at the Python dispatch layer — never inside
  traced/jitted code.  Telemetry on or off, jaxprs and compiled
  artifacts are byte-identical (``tests/test_obs.py`` pins this).
* Off by default.  Enable with ``VELES_SIMD_TELEMETRY=1`` in the
  environment or :func:`enable` at runtime; when disabled every helper
  is a single attribute check, and when enabled the cost is one locked
  dict increment per public call.

Usage::

    from veles.simd_tpu import obs
    obs.enable()
    convolve(x, h)                      # decisions recorded as they run
    print(obs.report())                 # human table
    obs.save("telemetry.json")          # snapshot for tools/obs_report.py
    text = obs.to_prometheus()          # scrape endpoint body

Scope note: this module answers *what was decided and how often*;
:mod:`veles.simd_tpu.utils.profiler` (XLA traces) answers *where the
time goes* inside a step.  They are deliberately separate layers.
"""

from __future__ import annotations

import os

from veles.simd_tpu.obs import compile as _compile
from veles.simd_tpu.obs import export as _export
from veles.simd_tpu.obs.events import EventLog
from veles.simd_tpu.obs.registry import MetricsRegistry

__all__ = [
    "enable", "disable", "enabled", "configure",
    "count", "gauge", "observe", "record_decision",
    "counter_value", "events", "snapshot", "reset",
    "to_json", "to_prometheus", "report", "save", "load",
    "install_compile_listeners",
    "MetricsRegistry", "EventLog",
]

_TRUTHY = ("1", "true", "yes", "on")

_registry = MetricsRegistry()
_events = EventLog()
_enabled = os.environ.get("VELES_SIMD_TELEMETRY",
                          "0").strip().lower() in _TRUTHY
if _enabled:
    # the env var is documented as equivalent to enable(): compile/cache
    # metrics must flow too.  Tolerate jax-free processes (the rest of
    # the telemetry layer works without an accelerator runtime).
    try:
        _compile.install()
    except ImportError:
        pass


# -- lifecycle ---------------------------------------------------------------

def enabled() -> bool:
    """Is telemetry currently recording?"""
    return _enabled


def enable(compile_listeners: bool = True) -> None:
    """Turn telemetry on (idempotent).

    ``compile_listeners=True`` (default) also bridges ``jax.monitoring``
    compile/cache events into the registry — a one-time, irreversible
    process-level registration (the callbacks themselves stay gated on
    :func:`enabled`, so :func:`disable` still silences them).  Pass
    False in jax-free processes.
    """
    global _enabled
    _enabled = True
    if compile_listeners:
        _compile.install()


def disable() -> None:
    """Stop recording.  Existing metrics/events are kept (snapshot still
    works); use :func:`reset` to clear them."""
    global _enabled
    _enabled = False


def configure(max_events: int | None = None) -> None:
    """Adjust telemetry limits.  ``max_events`` replaces the decision
    log with a fresh bound (history is cleared — resizing a ring buffer
    in place would silently reorder it)."""
    global _events
    if max_events is not None:
        _events = EventLog(max_events)


def install_compile_listeners() -> bool:
    """Explicitly install the ``jax.monitoring`` bridge (normally done
    by :func:`enable`).  Returns True on first installation."""
    return _compile.install()


# -- recording helpers (the ONLY entry points ops modules may call) ----------

def count(name: str, n: int = 1, **labels) -> None:
    """Increment counter ``name`` (no-op while disabled)."""
    if not _enabled:
        return
    _registry.count(name, n, **labels)


def gauge(name: str, value: float, **labels) -> None:
    """Set gauge ``name`` to ``value`` (no-op while disabled)."""
    if not _enabled:
        return
    _registry.gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    """Record one timing-histogram sample (no-op while disabled)."""
    if not _enabled:
        return
    _registry.observe(name, value, **labels)


def record_decision(op: str, decision: str, **fields) -> None:
    """Log one dispatch decision (no-op while disabled).

    ``op`` is the public entry point ("convolve", "stft", ...),
    ``decision`` the chosen algorithm/path, ``fields`` the JSON-native
    geometry that explains it (lengths, block sizes, shard counts).
    Also bumps the ``decisions`` counter labeled by (op, decision) so
    aggregates survive event-log wraparound.
    """
    if not _enabled:
        return
    _events.record(op, decision, **fields)
    _registry.count("decisions", op=op, decision=decision)


# -- reads / exports ---------------------------------------------------------

def counter_value(name: str, **labels) -> int:
    """Current value of one counter (0 if never incremented)."""
    return _registry.counter_value(name, **labels)


def events() -> list:
    """Oldest-first copy of the retained decision events."""
    return _events.events()


def snapshot() -> dict:
    """One JSON-native dict of everything: counters, gauges, histograms,
    events, drop count, and the enabled flag."""
    snap = _registry.snapshot()
    snap["events"] = _events.events()
    snap["events_dropped"] = _events.dropped
    snap["enabled"] = _enabled
    return snap


def reset() -> None:
    """Clear all metrics and events; the enabled flag is untouched."""
    _registry.reset()
    _events.reset()


def to_json(snap: dict | None = None, indent: int | None = 2) -> str:
    return _export.to_json(snapshot() if snap is None else snap, indent)


def to_prometheus(snap: dict | None = None) -> str:
    return _export.to_prometheus(snapshot() if snap is None else snap)


def report(snap: dict | None = None, max_events: int = 20) -> str:
    return _export.report(snapshot() if snap is None else snap,
                          max_events)


def save(path: str, snap: dict | None = None) -> str:
    """Write a JSON snapshot to ``path`` (read back with :func:`load`
    or pretty-printed by ``tools/obs_report.py``); returns ``path``."""
    with open(path, "w") as f:
        f.write(to_json(snap))
    return path


def load(path: str) -> dict:
    """Read a snapshot written by :func:`save`."""
    with open(path) as f:
        return _export.from_json(f.read())
